"""Native C++ data engine vs its pure-Python twins.

The grouping must be byte-identical across implementations — the HF datasets
fingerprint cache and resume determinism depend on it, so these are equality
property tests, not just smoke tests.
"""

import numpy as np
import pytest

from llm_training_tpu import native
from llm_training_tpu.data.pre_training.datamodule import (
    best_fit_bin_packing,
    best_fit_bin_packing_py,
)


def test_native_library_builds_and_loads():
    # g++ is in the image; a silent fallback here would hide a broken build
    assert native.lib() is not None


def test_bfd_groups_identical_to_python():
    rng = np.random.default_rng(0)
    for trial in range(20):
        n = int(rng.integers(1, 2000))
        capacity = int(rng.integers(64, 4096))
        lengths = rng.integers(1, capacity + 1, n).tolist()
        got = native.bfd_pack(capacity, lengths)
        expected = best_fit_bin_packing_py(capacity, lengths)
        assert got == expected, f"trial {trial}: n={n} capacity={capacity}"


def test_bfd_decreasing_order_fills_bins():
    lengths = sorted([700, 300, 300, 500, 200, 900, 100], reverse=True)
    groups = native.bfd_pack(1000, lengths)
    # every bin's total fits
    for group in groups:
        assert sum(lengths[i] for i in group) <= 1000
    # all items placed exactly once
    assert sorted(i for g in groups for i in g) == list(range(len(lengths)))


def test_bfd_oversize_item_raises():
    with pytest.raises(ValueError):
        native.bfd_pack(10, [5, 11])


def test_dispatcher_uses_native_above_threshold():
    lengths = list(np.random.default_rng(1).integers(1, 512, 500))
    assert best_fit_bin_packing(512, [int(x) for x in lengths]) == \
        best_fit_bin_packing_py(512, [int(x) for x in lengths])


def test_pad_batch_matches_collator_semantics():
    rows = [
        np.asarray([5, 6, 7, 8, 9], np.int32),
        np.asarray([1, 2], np.int32),
        np.asarray([3, 3, 3, 3, 3, 3, 3], np.int32),
    ]
    segs = [
        np.asarray([1, 1, 2, 2, 2], np.int32),
        np.asarray([1, 1], np.int32),
        np.asarray([1, 2, 2, 3, 3, 3, 3], np.int32),
    ]
    labels = [r * 10 for r in rows]
    out = native.pad_batch(rows, segs, labels, width=8, pad_id=0, restart_positions=True)
    assert out is not None

    np.testing.assert_array_equal(out["input_ids"][1], [1, 2, 0, 0, 0, 0, 0, 0])
    np.testing.assert_array_equal(out["segment_ids"][0], [1, 1, 2, 2, 2, 0, 0, 0])
    np.testing.assert_array_equal(out["labels"][0][:5], [50, 60, 70, 80, 90])
    np.testing.assert_array_equal(out["labels"][0][5:], [-100, -100, -100])
    # positions restart at each packed document boundary (IT collator rule)
    np.testing.assert_array_equal(out["position_ids"][0], [0, 1, 0, 1, 2, 0, 0, 0])
    np.testing.assert_array_equal(out["position_ids"][2][:7], [0, 0, 1, 0, 1, 2, 3])


def test_pad_batch_shared_positions():
    rows = [np.asarray([4, 4, 4, 4], np.int32)]
    segs = [np.asarray([1, 1, 2, 2], np.int32)]
    out = native.pad_batch(rows, segs, None, width=6, pad_id=9, restart_positions=False)
    # pre-training collator rule: one shared position stream across docs
    np.testing.assert_array_equal(out["position_ids"][0], [0, 1, 2, 3, 0, 0])
    np.testing.assert_array_equal(out["labels"][0], [4, 4, 4, 4, -100, -100])


def test_prefetcher_preserves_order_and_closes():
    import jax

    from llm_training_tpu.data.prefetch import DevicePrefetcher

    batches = ({"x": np.full((2, 2), i, np.int32)} for i in range(10))
    pf = DevicePrefetcher(batches, None, depth=2, host_aux_fn=lambda b: int(b["x"].sum()))
    pairs = list(pf)
    seen = [int(b["x"][0, 0]) for b, _ in pairs]
    assert seen == list(range(10))
    assert [aux for _, aux in pairs] == [i * 4 for i in range(10)]
    # exhausted iterator keeps raising StopIteration instead of blocking
    assert list(pf) == []

    # close() mid-stream stops the worker without hanging
    endless = ({"x": np.zeros((1,), np.int32)} for _ in iter(int, 1))
    pf2 = DevicePrefetcher(endless, None, depth=2)
    next(iter(pf2))
    pf2.close()
    pf2._thread.join(timeout=5)
    assert not pf2._thread.is_alive()


def test_prefetcher_propagates_worker_errors():
    from llm_training_tpu.data.prefetch import DevicePrefetcher

    def bad():
        yield {"x": np.zeros((1,), np.int32)}
        raise RuntimeError("boom")

    pf = DevicePrefetcher(bad(), None, depth=2)
    it = iter(pf)
    next(it)
    with pytest.raises(RuntimeError, match="boom"):
        next(it)
        next(it)


def test_collators_native_equals_python(monkeypatch):
    """The collators' native fast path must be indistinguishable from the
    Python loop."""
    from llm_training_tpu.data.instruction_tuning.collator import (
        InstructionTuningDataCollator,
    )
    from llm_training_tpu.data.pre_training.collator import PreTrainingDataCollator

    class Tok:
        pad_token_id = 0
        bos_token_id = 1

    class Cfg:
        tokenizer = Tok()
        pad_to_multiple_of = 8

    examples = [
        {
            "input_ids": [1, 5, 6, 2, 1, 7, 2],
            "segment_ids": [1, 1, 1, 1, 2, 2, 2],
            "labels": [-100, 5, 6, 2, -100, 7, 2],
        },
        {
            "input_ids": [1, 9, 2],
            "segment_ids": [1, 1, 1],
            "labels": [-100, 9, 2],
        },
    ]

    for collator_cls in (PreTrainingDataCollator, InstructionTuningDataCollator):
        collator = collator_cls(Cfg())
        fast = collator(examples)
        import llm_training_tpu.native as native_mod

        monkeypatch.setattr(native_mod, "pad_batch", lambda *a, **k: None)
        slow = collator(examples)
        monkeypatch.undo()
        assert set(fast) == set(slow)
        for key in fast:
            np.testing.assert_array_equal(fast[key], slow[key], err_msg=f"{collator_cls.__name__}:{key}")
