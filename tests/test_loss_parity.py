"""Loss-curve parity vs the torch/CUDA reference semantics.

BASELINE.md's north star is throughput at "loss-curve parity vs the CUDA
FSDP baseline". This harness proves the training *math* matches end to end:
the same tiny Llama (identical weights via the HF converter), the same token
stream, and the same optimizer hyperparameters are trained for 20 steps in
torch (the reference's stack) and in this framework, and the two loss
trajectories must track within fp32 drift. Covers: forward parity, CE
shift/masking, AdamW semantics (decoupled weight decay), global-norm grad
clipping, and cosine-warmup LR scheduling.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

torch = pytest.importorskip("torch")

from transformers import LlamaConfig as HFLlamaConfig  # noqa: E402
from transformers import LlamaForCausalLM  # noqa: E402

from llm_training_tpu.lms.clm import CLM, CLMConfig  # noqa: E402
from llm_training_tpu.models.llama import Llama  # noqa: E402
from llm_training_tpu.models.llama.hf_conversion import (  # noqa: E402
    config_from_hf,
    params_from_hf,
)

STEPS = 20
LR = 1e-3
WARMUP = 5
WD = 0.1
BETAS = (0.9, 0.95)
EPS = 1e-8
CLIP = 1.0
BATCH, SEQ, VOCAB = 4, 32, 128


def _lr_at(step: int) -> float:
    """linear warmup -> cosine decay to 0 (shared schedule definition)."""
    if step < WARMUP:
        return LR * (step + 1) / WARMUP
    progress = (step - WARMUP) / max(STEPS - WARMUP, 1)
    return LR * 0.5 * (1 + math.cos(math.pi * progress))


def _data():
    rng = np.random.default_rng(0)
    return rng.integers(0, VOCAB, (STEPS, BATCH, SEQ)).astype(np.int64)


def _hf_model():
    torch.manual_seed(0)
    return LlamaForCausalLM(
        HFLlamaConfig(
            vocab_size=VOCAB, hidden_size=64, intermediate_size=112,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=SEQ,
        )
    )


def _train_torch(model, data) -> list[float]:
    model.train()
    opt = torch.optim.AdamW(
        model.parameters(), lr=LR, betas=BETAS, eps=EPS, weight_decay=WD
    )
    losses = []
    for step in range(STEPS):
        for group in opt.param_groups:
            group["lr"] = _lr_at(step)
        ids = torch.tensor(data[step])
        out = model(ids, labels=ids)  # HF shifts internally
        opt.zero_grad()
        out.loss.backward()
        torch.nn.utils.clip_grad_norm_(model.parameters(), CLIP)
        opt.step()
        losses.append(float(out.loss.detach()))
    return losses


def _train_ours(hf_model, data) -> list[float]:
    cfg = config_from_hf(
        hf_model.config, compute_dtype="float32", param_dtype="float32"
    )
    params = jax.tree.map(jnp.asarray, params_from_hf(hf_model.state_dict(), cfg))
    objective = CLM(CLMConfig(), model=Llama(cfg))

    def schedule(count):
        # the exact `_lr_at` math, traceable
        warm = LR * (count + 1) / WARMUP
        progress = (count - WARMUP) / max(STEPS - WARMUP, 1)
        cos = LR * 0.5 * (1 + jnp.cos(jnp.pi * progress))
        return jnp.where(count < WARMUP, warm, cos)
    tx = optax.chain(
        optax.clip_by_global_norm(CLIP),
        optax.adamw(schedule, b1=BETAS[0], b2=BETAS[1], eps=EPS, weight_decay=WD),
    )
    opt_state = tx.init(params)

    @jax.jit
    def step_fn(params, opt_state, ids):
        def loss_fn(p):
            loss, _ = objective.loss_and_metrics(p, {"input_ids": ids}, train=False)
            return loss

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for step in range(STEPS):
        params, opt_state, loss = step_fn(params, opt_state, jnp.asarray(data[step]))
        losses.append(float(loss))
    return losses


@pytest.mark.slow
def test_loss_curves_match_torch_reference():
    data = _data()
    hf_model = _hf_model()
    torch_losses = _train_torch(_hf_model(), data)
    our_losses = _train_ours(hf_model, data)

    # step 0: pure forward parity; later steps accumulate optimizer drift
    assert abs(our_losses[0] - torch_losses[0]) < 1e-4, (our_losses[0], torch_losses[0])
    np.testing.assert_allclose(our_losses, torch_losses, rtol=2e-3, atol=2e-3)
    # and training actually learns (loss drops on a fixed random stream it
    # can memorize a little)
    assert our_losses[-1] < our_losses[0]
