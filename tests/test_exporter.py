"""Live-telemetry tests: the /metrics//statusz//healthz exporter, the SLO
burn-rate monitor, and the BENCH perf-regression ledger
(docs/observability.md#live-telemetry, #slo; docs/performance.md#perf-ledger).

Everything here is jax-free host code (the exporter/SLO/ledger trio carry
graftlint jax-free contracts), so these tests cost milliseconds. HTTP
tests bind ephemeral ports on localhost; clock-driven tests inject fake
clocks — no sleeps.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

import pytest

from llm_training_tpu.resilience.watchdog import HangWatchdog
from llm_training_tpu.telemetry.exporter import (
    MetricsExporter,
    parse_prometheus_text,
    prometheus_name,
    render_prometheus,
    resolve_metrics_port,
    start_exporter,
    watch_main,
)
from llm_training_tpu.telemetry.goodput import GoodputLedger
from llm_training_tpu.telemetry.perf_ledger import (
    check_regression,
    find_comparison,
    load_history,
    normalize_record,
    trend_table,
)
from llm_training_tpu.telemetry.registry import TelemetryRegistry
from llm_training_tpu.telemetry.slo import (
    SLOMonitor,
    build_slo_monitor,
    slo_config_from_env,
    specs_from_config,
)
from llm_training_tpu.telemetry.trace import TraceRecorder, set_tracer

# the shared strict parser IS the validator under test: render->parse must
# round-trip, and every malformed shape must raise ValueError (the loadgen
# cross-check and the precommit exporter smoke rely on exactly that)
parse_prometheus = parse_prometheus_text


def _get(port: int, path: str):
    return urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5.0)


@pytest.fixture
def exporter_factory():
    started = []

    def make(**kwargs) -> MetricsExporter:
        exporter = MetricsExporter(0, **kwargs)
        # bind an OS-assigned ephemeral port directly (requested_port 0)
        assert exporter.start()
        started.append(exporter)
        return exporter

    yield make
    for exporter in started:
        exporter.stop()


# ------------------------------------------------------------ /metrics


def test_metrics_endpoint_is_parse_valid_prometheus(exporter_factory):
    registry = TelemetryRegistry()
    registry.counter("serve/requests_completed").inc(5)
    registry.gauge("hbm/peak_bytes_in_use").set(1.5e9)
    with registry.timer("data/produce").time():
        pass
    ledger = GoodputLedger()
    ledger.start()
    exporter = exporter_factory(registry=registry, ledger=ledger)
    with _get(exporter.port, "/metrics") as resp:
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/plain")
        metrics = parse_prometheus(resp.read().decode())
    assert metrics["llmt_serve_requests_completed"] == 5.0
    assert metrics["llmt_hbm_peak_bytes_in_use"] == 1.5e9
    # timers flatten to the _s/_n accumulator pair
    assert "llmt_data_produce_s" in metrics and metrics["llmt_data_produce_n"] == 1.0
    # the ledger summary rides along
    assert "llmt_goodput_total_s" in metrics
    # the exporter's own counters count THIS scrape
    assert metrics["llmt_exporter_scrapes"] == 1.0
    # and land in the registry so telemetry.jsonl shows whether anyone
    # scraped the run
    assert registry.snapshot()["exporter/scrapes"] == 1.0


def test_metrics_includes_live_extras_and_survives_extra_fn_crash(exporter_factory):
    calls = {"n": 0}

    def extra():
        calls["n"] += 1
        if calls["n"] == 1:
            return {"serve/queue_depth": 3.0}
        raise RuntimeError("live gauge bug")

    exporter = exporter_factory(registry=TelemetryRegistry(), extra_fn=extra)
    with _get(exporter.port, "/metrics") as resp:
        assert parse_prometheus(resp.read().decode())["llmt_serve_queue_depth"] == 3.0
    # a crashing extra_fn costs its gauges, never the scrape
    with _get(exporter.port, "/metrics") as resp:
        metrics = parse_prometheus(resp.read().decode())
    assert "llmt_serve_queue_depth" not in metrics
    assert metrics["llmt_exporter_scrapes"] == 2.0


def test_parse_prometheus_text_rejects_malformed_lines():
    """The strict parser must raise on every drift shape — including the
    3-token sample line (a trailing timestamp) that float()/unpack paths
    can miss."""
    good = render_prometheus({"a/b": 1.0})
    assert parse_prometheus_text(good)["llmt_a_b"] == 1.0
    for bad in (
        "llmt_x 1.0 1699999999\n",     # trailing timestamp (3 tokens)
        "llmt_x\n",                     # no value
        "llmt_x junk\n",                # non-float value
        "9bad_name 1.0\n",              # illegal name
        "# COMMENT not a type line\n llmt_x 1.0\n",  # bad comment
        "",                             # no samples at all
    ):
        with pytest.raises(ValueError):
            parse_prometheus_text(bad)


def test_parse_prometheus_text_labeled_series_are_opt_in():
    """The fleet federation surface re-renders per-replica series with a
    `{replica=...,role=...}` label block: `labels=True` accepts exactly
    that strict shape (keyed by the FULL labeled name); the default
    parser keeps rejecting, so child-exporter scrapes stay label-free."""
    body = (
        '# TYPE llmt_serve_queue_depth gauge\n'
        'llmt_serve_queue_depth{replica="serve-0-42",role="serve"} 3.0\n'
        'llmt_fleet_replicas 1.0\n'
    )
    parsed = parse_prometheus_text(body, labels=True)
    assert parsed[
        'llmt_serve_queue_depth{replica="serve-0-42",role="serve"}'
    ] == 3.0
    assert parsed["llmt_fleet_replicas"] == 1.0
    with pytest.raises(ValueError):
        parse_prometheus_text(body)  # labels stay opt-in
    for bad in (
        'llmt_x{replica=serve-0-42} 1.0\n',      # unquoted value
        'llmt_x{replica="a" role="b"} 1.0\n',    # missing comma
        'llmt_x{replica="a",} 1.0\n',            # trailing comma
        'llmt_x{} 1.0\n',                        # empty block
        'llmt_x{replica="a"\n',                  # unterminated, no value
    ):
        with pytest.raises(ValueError):
            parse_prometheus_text(bad, labels=True)


def test_parse_prometheus_kinds():
    registry = TelemetryRegistry()
    registry.counter("exporter/scrapes").inc()
    registry.gauge("serve/queue_depth").set(1.0)
    from llm_training_tpu.telemetry.exporter import parse_prometheus_kinds

    snapshot, kinds = registry.snapshot_with_kinds()
    body = render_prometheus(snapshot, kinds=kinds)
    parsed_kinds = parse_prometheus_kinds(body)
    assert parsed_kinds["llmt_exporter_scrapes"] == "counter"
    assert parsed_kinds["llmt_serve_queue_depth"] == "gauge"
    # same strictness posture as the sample parser: drift raises
    for bad in ("# TYPE llmt_x histogram\n", "# TYPE too many words here\n"):
        with pytest.raises(ValueError):
            parse_prometheus_kinds(bad)
    assert parse_prometheus_kinds("llmt_x 1.0\n") == {}  # no TYPE lines: fine


def test_render_prometheus_handles_non_finite_and_junk():
    text = render_prometheus(
        {"a/nan": float("nan"), "a/inf": float("inf"), "a/ok": 1.0,
         "a/junk": "not-a-number"},
    )
    assert "llmt_a_nan NaN" in text
    assert "llmt_a_inf +Inf" in text
    assert "llmt_a_ok 1.0" in text
    assert "junk" not in text  # skipped, not crashed


def test_prometheus_name_sanitization():
    assert prometheus_name("goodput/total_s") == "llmt_goodput_total_s"
    assert prometheus_name("slo/serve/ttft_p99_ms/target") == (
        "llmt_slo_serve_ttft_p99_ms_target"
    )


# ----------------------------------------------------------- /healthz


def test_healthz_turns_red_on_stale_heartbeat(exporter_factory):
    t = [0.0]
    watchdog = HangWatchdog(timeout_s=10.0, clock=lambda: t[0])
    watchdog.beat()  # fresh beat at t=0 (never start()ed — no poll thread)
    exporter = exporter_factory(
        registry=TelemetryRegistry(), watchdog=watchdog,
    )
    assert exporter.stale_after_s == 5.0  # half the watchdog window
    with _get(exporter.port, "/healthz") as resp:
        assert resp.status == 200
        assert json.loads(resp.read())["status"] == "ok"
    # wedge: the beat goes stale past timeout/2 but BEFORE the watchdog's
    # own 10s abort — the probe must already be red
    t[0] = 6.0
    with pytest.raises(urllib.error.HTTPError) as err:
        _get(exporter.port, "/healthz")
    assert err.value.code == 503
    detail = json.loads(err.value.read())
    assert detail["status"] == "unhealthy"
    assert "heartbeat" in detail["reason"]
    # progress re-arms the probe
    t[0] = 7.0
    watchdog.beat()
    with _get(exporter.port, "/healthz") as resp:
        assert resp.status == 200


def test_healthz_without_watchdog_is_alive_probe_only(exporter_factory):
    exporter = exporter_factory(registry=TelemetryRegistry())
    with _get(exporter.port, "/healthz") as resp:
        assert resp.status == 200
        assert json.loads(resp.read())["watchdog"] == "none"


def test_healthz_names_the_open_goodput_phase(exporter_factory):
    ledger = GoodputLedger()
    ledger.start()
    exporter = exporter_factory(ledger=ledger)
    with ledger.measure("checkpoint_save"):
        with _get(exporter.port, "/healthz") as resp:
            assert json.loads(resp.read())["phase"] == "checkpoint_save"


# ----------------------------------------------------------- /statusz


def test_statusz_renders_status_fn_and_slo_alert(exporter_factory):
    registry = TelemetryRegistry()
    specs = specs_from_config({"serve": {"ttft_p99_ms": 10.0}})
    t = [0.0]
    monitor = SLOMonitor(
        specs, registry=registry, clock=lambda: t[0],
        fast_window_s=10, slow_window_s=60, fast_burn=2, slow_burn=2,
        min_events=2, cooldown_s=100,
    )
    exporter = exporter_factory(
        registry=registry, slo=monitor,
        status_fn=lambda: {"step": 7, "segment": 1},
    )
    body = _get(exporter.port, "/statusz").read().decode()
    assert "step: 7" in body and "segment: 1" in body
    assert "slo: no breaches" in body
    for _ in range(4):
        t[0] += 1.0
        monitor.observe_request(ttft_ms=100.0)
    body = _get(exporter.port, "/statusz").read().decode()
    assert "last alert: serve/ttft_p99_ms" in body


def test_unknown_path_404s(exporter_factory):
    exporter = exporter_factory()
    with pytest.raises(urllib.error.HTTPError) as err:
        _get(exporter.port, "/nope")
    assert err.value.code == 404


# ----------------------------------------------------- lifecycle / env


def test_port_zero_disables(monkeypatch):
    monkeypatch.delenv("LLMT_METRICS_PORT", raising=False)
    assert resolve_metrics_port() == 0
    assert start_exporter() is None
    monkeypatch.setenv("LLMT_METRICS_PORT", "0")
    assert start_exporter() is None
    monkeypatch.setenv("LLMT_METRICS_PORT", "junk")
    assert resolve_metrics_port() == 0  # warned, not crashed


def test_port_collision_degrades_to_warning(exporter_factory, caplog):
    import logging

    first = exporter_factory(registry=TelemetryRegistry())
    second = MetricsExporter(first.port, registry=TelemetryRegistry())
    with caplog.at_level(logging.WARNING):
        assert second.start() is False
    assert any("cannot bind port" in r.message for r in caplog.records)
    assert start_exporter(port=first.port) is None
    # the first exporter keeps serving
    with _get(first.port, "/metrics") as resp:
        assert resp.status == 200


def test_watch_once_roundtrip_and_unreachable(exporter_factory, capsys):
    exporter = exporter_factory(registry=TelemetryRegistry())
    assert watch_main(port=exporter.port, once=True) == 0
    assert "statusz" in capsys.readouterr().out
    exporter.stop()
    assert watch_main(port=exporter.port, once=True) == 2


# ------------------------------------------------------------------ SLO


@pytest.fixture
def tracer(tmp_path):
    recorder = TraceRecorder(capacity=64, enabled=True)
    previous = set_tracer(recorder)
    yield recorder
    set_tracer(previous)


def _monitor(registry, tmp_path=None, clock=None, **kwargs):
    specs = specs_from_config({
        "serve": {"ttft_p99_ms": 50.0, "error_rate": 0.1},
        "train": {"step_time_p99_s": 1.0, "goodput_pct_min": 40.0},
    })
    defaults = dict(
        fast_window_s=10.0, slow_window_s=60.0, fast_burn=5.0, slow_burn=3.0,
        min_events=4, cooldown_s=30.0,
    )
    defaults.update(kwargs)
    return SLOMonitor(
        specs, registry=registry, run_dir=tmp_path, clock=clock, **defaults
    )


def test_slo_no_breach_on_healthy_traffic(tracer, tmp_path):
    registry = TelemetryRegistry()
    t = [0.0]
    monitor = _monitor(registry, tmp_path, clock=lambda: t[0])
    for _ in range(50):
        t[0] += 0.1
        monitor.observe_request(ttft_ms=10.0, tpot_ms=None, ok=True)
        monitor.observe_step(0.1)
        monitor.observe_goodput(80.0)
    assert monitor.breach_count() == 0
    snap = registry.snapshot()
    assert snap["slo/serve/ttft_p99_ms/target"] == 50.0
    assert snap["slo/serve/ttft_p99_ms/burn_fast"] == 0.0
    assert not list(tmp_path.glob("trace-flight-slo-*.jsonl"))


def test_slo_breach_emits_counter_instant_and_flight_dump(tracer, tmp_path):
    registry = TelemetryRegistry()
    t = [0.0]
    monitor = _monitor(registry, tmp_path, clock=lambda: t[0])
    for _ in range(6):
        t[0] += 0.5
        monitor.observe_request(ttft_ms=500.0, ok=True)
    assert monitor.breach_count() == 1  # cooldown holds repeats
    snap = registry.snapshot()
    assert snap["slo/breaches_total"] == 1.0
    assert snap["slo/serve/ttft_p99_ms/breaches"] == 1.0
    assert snap["slo/serve/ttft_p99_ms/worst"] == 500.0
    assert snap["slo/last_breach_request_n"] >= 4.0
    # trace instant in the ring
    breach_events = [
        e for e in tracer.snapshot() if e.get("name") == "breach"
    ]
    assert breach_events and breach_events[0]["cat"] == "slo"
    assert breach_events[0]["args"]["target"] == "serve/ttft_p99_ms"
    # and the ring flight-dumped next to the run artifacts
    dumps = list(tmp_path.glob("trace-flight-slo-serve-ttft_p99_ms-*.jsonl"))
    assert len(dumps) == 1
    dumped = [json.loads(line) for line in dumps[0].read_text().splitlines()]
    assert any(e.get("name") == "breach" for e in dumped)


def test_slo_multiwindow_gate_needs_both_windows(tracer, tmp_path):
    """A burst that burns the fast window but not the slow one must NOT
    page — the slow window is the straggler guard."""
    registry = TelemetryRegistry()
    t = [0.0]
    monitor = _monitor(
        registry, tmp_path, clock=lambda: t[0],
        fast_window_s=2.0, slow_window_s=60.0, fast_burn=5.0, slow_burn=8.0,
        min_events=4,
    )
    # 40 healthy observations spread over the slow window...
    for _ in range(40):
        t[0] += 1.0
        monitor.observe_request(ttft_ms=1.0, ok=True)
    # ...then a short violation burst: fast-window burn is 100x, but the
    # slow window still holds ~40 good events -> slow burn < 8x
    for _ in range(3):
        t[0] += 0.4
        monitor.observe_request(ttft_ms=500.0, ok=True)
    assert monitor.breach_count() == 0


def test_slo_step_and_goodput_breaches_record_step(tracer, tmp_path):
    registry = TelemetryRegistry()
    t = [0.0]
    monitor = _monitor(registry, tmp_path, clock=lambda: t[0])
    for step in range(1, 6):
        t[0] += 2.0
        monitor.observe_step(3.0, step=step)
    assert monitor.breach_count() == 1
    assert registry.snapshot()["slo/last_breach_step"] == 4.0
    for step in range(6, 12):
        t[0] += 2.0
        monitor.observe_goodput(5.0, step=step)
    assert monitor.breach_count() == 2
    assert registry.snapshot()["slo/train/goodput_pct_min/worst"] == 5.0


def test_slo_error_rate_budget_is_the_target(tracer, tmp_path):
    registry = TelemetryRegistry()
    t = [0.0]
    monitor = _monitor(registry, tmp_path, clock=lambda: t[0])
    # 10% failures == the budget exactly -> burn 1x, no breach
    for i in range(40):
        t[0] += 0.2
        monitor.observe_request(ttft_ms=1.0, ok=i % 10 != 0)
    assert monitor.breach_count() == 0
    # sustained 100% failures: the fast window fills with failures and the
    # slow window's fraction climbs past 3x the 10% budget -> breach
    for _ in range(40):
        t[0] += 0.2
        monitor.observe_request(ttft_ms=None, ok=False)
    assert monitor.breach_count() >= 1
    assert registry.snapshot()["slo/serve/error_rate/breaches"] >= 1.0


def test_slo_specs_are_domain_scoped(tracer, tmp_path):
    """A serve spec must never eat train observations (and vice versa):
    an error-rate SLO armed fleet-wide while a FIT runs would otherwise
    count every healthy step as a healthy request, diluting the real
    request-error fraction and masking a breach."""
    registry = TelemetryRegistry()
    t = [0.0]
    monitor = _monitor(registry, tmp_path, clock=lambda: t[0])
    # a training fit's observations only...
    for step in range(30):
        t[0] += 0.2
        monitor.observe_step(0.01, step=step)
        monitor.observe_goodput(90.0, step=step)
    # ...leave the serve windows EMPTY (no burn gauges published at all)
    snap = registry.snapshot()
    assert "slo/serve/error_rate/burn_fast" not in snap
    assert "slo/serve/ttft_p99_ms/burn_fast" not in snap
    # now 100% request failures breach immediately — undiluted by the 60
    # healthy train events that preceded them
    for _ in range(8):
        t[0] += 0.2
        monitor.observe_request(ttft_ms=None, ok=False)
    assert registry.snapshot()["slo/serve/error_rate/breaches"] >= 1.0


def test_slo_env_knobs_honor_explicit_zero(monkeypatch, tracer, tmp_path):
    """`LLMT_SLO_COOLDOWN_S=0` means count EVERY breach — a falsy-`or`
    fallback would silently revert it to the 30s default."""
    monkeypatch.setenv("LLMT_SLO_COOLDOWN_S", "0")
    monitor = SLOMonitor(
        specs_from_config({"serve": {"ttft_p99_ms": 10.0}}),
        registry=TelemetryRegistry(), clock=lambda: 0.0,
    )
    assert monitor.cooldown_s == 0.0
    monkeypatch.setenv("LLMT_SLO_BURN_FAST", "0")
    monitor = SLOMonitor(
        specs_from_config({"serve": {"ttft_p99_ms": 10.0}}),
        registry=TelemetryRegistry(), clock=lambda: 0.0,
    )
    assert monitor.fast_burn == 0.0


def test_slo_config_from_env(monkeypatch):
    for name in (
        "LLMT_SLO_TTFT_P99_MS", "LLMT_SLO_TPOT_P99_MS", "LLMT_SLO_ERROR_RATE",
        "LLMT_SLO_STEP_TIME_P99_S", "LLMT_SLO_GOODPUT_PCT_MIN",
    ):
        monkeypatch.delenv(name, raising=False)
    assert slo_config_from_env() == {}
    assert build_slo_monitor() is None  # no config -> zero cost
    monkeypatch.setenv("LLMT_SLO_TTFT_P99_MS", "75.5")
    monkeypatch.setenv("LLMT_SLO_GOODPUT_PCT_MIN", "junk")  # warn + ignore
    config = slo_config_from_env({"train": {"step_time_p99_s": 2.0}})
    assert config == {
        "serve": {"ttft_p99_ms": 75.5}, "train": {"step_time_p99_s": 2.0}
    }
    specs = specs_from_config(config)
    assert {s.key for s in specs} == {"serve/ttft_p99_ms", "train/step_time_p99_s"}
    monitor = build_slo_monitor()
    assert monitor is not None and len(monitor.specs) == 1


# ---------------------------------------------------------- perf ledger


def _write_round(tmp_path, n, wrapped=False, **fields):
    record = {
        "metric": "llama_clm_train_mfu", "stage": "summary", "partial": False,
        **fields,
    }
    if wrapped:
        record = {"n": n, "cmd": "python bench.py", "rc": 0, "tail": "",
                  "parsed": record}
    (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps(record))


def test_perf_ledger_parses_both_shapes_and_sorts(tmp_path):
    _write_round(tmp_path, 2, wrapped=True, value=0.5, backend="tpu")
    _write_round(tmp_path, 1, value=0.4, backend="tpu")
    (tmp_path / "BENCH_r03.json").write_text('{"n": 3, "rc": 1, "parsed": null}')
    (tmp_path / "not_a_round.json").write_text("{}")
    history = load_history(tmp_path)
    assert [r["round"] for r in history] == [1, 2, 3]
    assert history[1]["value"] == 0.5  # unwrapped
    assert history[2]["value"] is None and "crashed" in history[2]["error"]
    table = trend_table(history)
    assert "r01" in table and "r03" in table and "crashed" in table


def test_perf_ledger_same_backend_comparison_only(tmp_path):
    _write_round(tmp_path, 1, value=0.5, backend="tpu", model="8b-layer")
    _write_round(tmp_path, 2, value=0.01, backend="cpu", model="8b-layer")
    # newest is cpu; only tpu history before it -> nothing to compare
    verdict = check_regression(load_history(tmp_path))
    assert verdict["status"] == "ok" and "note" in verdict


def test_perf_ledger_flags_seeded_regression(tmp_path):
    _write_round(
        tmp_path, 1, value=0.5, backend="cpu", model="8b-layer",
        decode_tokens_per_sec=2000.0, serve_ttft_p50_ms=10.0,
    )
    _write_round(
        tmp_path, 2, value=0.3, backend="cpu", model="8b-layer",
        decode_tokens_per_sec=1900.0, serve_ttft_p50_ms=20.0,
    )
    verdict = check_regression(load_history(tmp_path), tolerance_pct=25.0)
    assert verdict["status"] == "regression"
    flagged = {c["metric"] for c in verdict["checked"] if c["regressed"]}
    # mfu -40%, ttft +100% regress; decode -5% is inside tolerance
    assert flagged == {"value", "serve_ttft_p50_ms"}
    assert verdict["baseline"] == "BENCH_r01.json"
    # widening the tolerance clears it
    ok = check_regression(load_history(tmp_path), tolerance_pct=200.0)
    assert ok["status"] == "ok"


def test_perf_ledger_crashed_newest_round_fails_the_gate(tmp_path):
    """The round being committed is the newest by number; one that crashed
    before reporting MFU must fail --check-regression itself — not slide
    the comparison back to the two previous healthy rounds."""
    _write_round(tmp_path, 1, value=0.5, backend="cpu", model="m")
    _write_round(tmp_path, 2, value=0.5, backend="cpu", model="m")
    (tmp_path / "BENCH_r03.json").write_text('{"n": 3, "rc": 1, "parsed": null}')
    verdict = check_regression(load_history(tmp_path))
    assert verdict["status"] == "regression"
    assert "no headline value" in verdict["findings"][0]
    assert verdict["candidate"] == "BENCH_r03.json"


def test_perf_ledger_improvements_never_flag(tmp_path):
    _write_round(tmp_path, 1, value=0.3, backend="cpu", model="m",
                 serve_ttft_p50_ms=50.0)
    _write_round(tmp_path, 2, value=0.9, backend="cpu", model="m",
                 serve_ttft_p50_ms=1.0)
    assert check_regression(load_history(tmp_path), 10.0)["status"] == "ok"


def test_bench_check_regression_cli(tmp_path):
    """The real `bench.py --check-regression` entry, exit codes included —
    and the committed r01..rNN history must gate clean (the acceptance
    bar: a regressed round exits nonzero, the real board exits 0)."""
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    bench = str(repo / "bench.py")
    result = subprocess.run(
        [sys.executable, bench, "--check-regression", "--bench-dir", str(repo)],
        capture_output=True, text=True,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    assert "round" in result.stdout  # the trend table rendered
    # seeded regression -> exit 3
    _write_round(tmp_path, 1, value=0.5, backend="cpu", model="m")
    _write_round(tmp_path, 2, value=0.1, backend="cpu", model="m")
    result = subprocess.run(
        [sys.executable, bench, "--check-regression",
         "--bench-dir", str(tmp_path)],
        capture_output=True, text=True,
    )
    assert result.returncode == 3, result.stdout + result.stderr
    verdict = json.loads(result.stdout.strip().splitlines()[-1])
    assert verdict["status"] == "regression"
    # empty history -> exit 2
    empty = tmp_path / "empty"
    empty.mkdir()
    result = subprocess.run(
        [sys.executable, bench, "--check-regression", "--bench-dir", str(empty)],
        capture_output=True, text=True,
    )
    assert result.returncode == 2


def test_normalize_record_passthrough():
    assert normalize_record({"value": 1.0}) == {"value": 1.0}
    assert normalize_record({"parsed": {"value": 2.0}}) == {"value": 2.0}
    assert find_comparison([]) is None


# -------------------------------------------------------- report == SLO ==


def _slo_run_dir(tmp_path, with_slo=True):
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    (run_dir / "metrics.jsonl").write_text(
        json.dumps({"step": 1, "loss": 2.0, "steps_per_sec": 1.0}) + "\n"
    )
    record = {"step": 1, "goodput/total_s": 10.0, "goodput/goodput_pct": 50.0}
    if with_slo:
        record.update({
            "slo/serve/ttft_p99_ms/target": 50.0,
            "slo/serve/ttft_p99_ms/worst": 312.5,
            "slo/serve/ttft_p99_ms/breaches": 3.0,
            "slo/serve/ttft_p99_ms/burn_fast": 16.2,
            "slo/serve/ttft_p99_ms/burn_slow": 7.1,
            "slo/train/step_time_p99_s/target": 1.0,
            "slo/breaches_total": 3.0,
            "slo/last_breach_step": 7.0,
            "slo/last_breach_request_n": 12.0,
        })
    (run_dir / "telemetry.jsonl").write_text(json.dumps(record) + "\n")
    return run_dir


def test_report_slo_section_renders(tmp_path, monkeypatch):
    from llm_training_tpu.telemetry.report import render_report, render_report_data

    monkeypatch.chdir(tmp_path)  # keep the perf cwd fallback out
    run_dir = _slo_run_dir(tmp_path)
    text = render_report(run_dir)
    assert "== SLO ==" in text
    assert "serve/ttft_p99_ms: target 50  worst 312.5  breaches 3" in text
    # a target armed but never violated renders with zero breaches
    assert "train/step_time_p99_s: target 1  breaches 0" in text
    assert "breaches: 3 total  last at step 7  last at request #12" in text
    doc = render_report_data(run_dir)
    assert doc["slo"]["slo/breaches_total"] == 3.0
    assert doc["slo"]["slo/serve/ttft_p99_ms/worst"] == 312.5


def test_report_slo_section_omitted_without_config(tmp_path, monkeypatch):
    from llm_training_tpu.telemetry.report import render_report, render_report_data

    monkeypatch.chdir(tmp_path)
    run_dir = _slo_run_dir(tmp_path, with_slo=False)
    assert "== SLO ==" not in render_report(run_dir)
    assert render_report_data(run_dir)["slo"] is None


# ------------------------------------------- supervisor port passthrough


def test_supervisor_env_carries_metrics_port(monkeypatch):
    """`supervise` relaunches inherit LLMT_METRICS_PORT (plain env
    passthrough), so a scrape target survives drain/replay and elastic
    resume boundaries — the dead child released the port, the relaunch
    re-binds it."""
    from llm_training_tpu.resilience.supervisor import Supervisor, SupervisorConfig

    monkeypatch.setenv("LLMT_METRICS_PORT", "9109")
    supervisor = Supervisor(
        ["true"], SupervisorConfig(log_path=None), run_child=lambda argv: 0
    )
    assert supervisor.env["LLMT_METRICS_PORT"] == "9109"
