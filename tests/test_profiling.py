"""Device-plane observability tests: the ProfileTrigger capture service,
compiled-program compute/comm attribution, the per-device HBM rollup +
timeline, the /profilez endpoint, and the report profiling section
(docs/observability.md#profiling, #device-plane).

The trigger's request surface is jax-free host code; the capture side is
exercised against a monkeypatched `jax.profiler` (no real traces — the
real capture is the profile-smoke gate's job). Attribution parses
synthetic HLO text: on a single-device CPU backend the compiled step
contains no collectives, so the regex walk is pinned directly.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

import pytest

from llm_training_tpu.telemetry.device import (
    HBMTimeline,
    _gauges_from_stats,
    compiled_attribution_gauges,
    hbm_gauges,
    parse_hlo_collectives,
)
from llm_training_tpu.telemetry.exporter import MetricsExporter, profile_main
from llm_training_tpu.telemetry.profiling import (
    ProfileTrigger,
    build_profile_trigger,
    get_profile_trigger,
    sanitize_tag,
    set_profile_trigger,
)
from llm_training_tpu.telemetry.registry import TelemetryRegistry
from llm_training_tpu.telemetry.report import (
    _profiling_section,
    _profiling_summary,
)
from llm_training_tpu.telemetry.trace import TraceRecorder, set_tracer


class _FakeClock:
    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


class _ProfilerRecorder:
    """Patches jax.profiler start/stop so capture transitions are pinned
    without writing real traces (same idiom as tests/test_callbacks.py)."""

    def __init__(self, monkeypatch, fail_start: bool = False):
        import jax

        self.calls: list[tuple] = []

        def start(trace_dir, *a, **k):
            if fail_start:
                raise RuntimeError("profiler backend unavailable")
            self.calls.append(("start", trace_dir))

        monkeypatch.setattr(jax.profiler, "start_trace", start)
        monkeypatch.setattr(
            jax.profiler, "stop_trace", lambda: self.calls.append(("stop",))
        )


# ------------------------------------------------------ request admission


def test_request_budget_cooldown_and_counters(tmp_path):
    clock = _FakeClock()
    registry = TelemetryRegistry()
    trigger = ProfileTrigger(
        run_dir=tmp_path, registry=registry,
        budget=2, cooldown_s=60.0, clock=clock,
    )
    assert trigger.request("first")["accepted"]
    # a second request while the first is still pending: busy (jax forbids
    # nested start_trace — one window at a time is the invariant)
    second = trigger.request("second")
    assert not second["accepted"] and second["reason"] == "busy"
    # consume the pending window so admission state, not the open window,
    # drives the next refusals
    trigger._pending = None
    within = trigger.request("third")
    assert not within["accepted"] and within["reason"] == "cooldown"
    clock.t += 61.0
    assert trigger.request("fourth")["accepted"]
    trigger._pending = None
    trigger._captures = 2  # budget spent
    clock.t += 61.0
    spent = trigger.request("fifth")
    assert not spent["accepted"] and spent["reason"] == "budget"
    snap = registry.snapshot()
    assert snap["profile/requested"] == 5.0
    assert snap["profile/suppressed"] == 3.0
    assert snap["profile/suppressed/busy"] == 1.0
    assert snap["profile/suppressed/cooldown"] == 1.0
    assert snap["profile/suppressed/budget"] == 1.0


def test_budget_zero_refuses_everything():
    trigger = ProfileTrigger(budget=0, cooldown_s=0.0)
    result = trigger.request("never")
    assert not result["accepted"] and result["reason"] == "budget"


def test_concurrent_requests_admit_exactly_one():
    trigger = ProfileTrigger(budget=8, cooldown_s=0.0)
    results: list[dict] = []
    barrier = threading.Barrier(8)

    def fire(i):
        barrier.wait()
        results.append(trigger.request(f"race-{i}"))

    threads = [threading.Thread(target=fire, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    accepted = [r for r in results if r["accepted"]]
    assert len(accepted) == 1
    assert all(r["reason"] == "busy" for r in results if not r["accepted"])


def test_tag_sanitization():
    assert sanitize_tag("slo/train/step_time_p99_s #1") == "slo-train-step_time_p99_s-1"
    assert sanitize_tag("///") == "capture"


# ------------------------------------------------------ capture lifecycle


def test_poll_drives_start_stop_and_manifest(tmp_path, monkeypatch):
    rec = _ProfilerRecorder(monkeypatch)
    registry = TelemetryRegistry()
    trigger = ProfileTrigger(
        run_dir=tmp_path, registry=registry,
        budget=4, cooldown_s=0.0, window_steps=2,
    )
    assert trigger.request("slo-step-1", source="slo")["accepted"]
    trigger.poll(5)  # starts: window [5, 7)
    assert rec.calls == [("start", str(tmp_path / "profile-slo-step-1"))]
    assert trigger.status()["active"] == "slo-step-1"
    trigger.poll(6)  # inside the window: no transition
    assert len(rec.calls) == 1
    trigger.poll(7)  # stop boundary
    assert rec.calls[-1] == ("stop",)
    assert trigger.status()["active"] is None
    manifest = json.loads((tmp_path / "profile-slo-step-1.json").read_text())
    assert manifest["tag"] == "slo-step-1"
    assert manifest["source"] == "slo"
    assert manifest["start_step"] == 5 and manifest["stop_step"] == 7
    assert (tmp_path / "profile-slo-step-1").is_dir()
    snap = registry.snapshot()
    assert snap["profile/captures"] == 1.0
    assert snap["profile/last_capture_step"] == 5.0
    history = trigger.status()["history"]
    assert [h["tag"] for h in history] == ["slo-step-1"]


def test_failed_start_clears_active_and_counts_error(tmp_path, monkeypatch):
    _ProfilerRecorder(monkeypatch, fail_start=True)
    registry = TelemetryRegistry()
    trigger = ProfileTrigger(run_dir=tmp_path, registry=registry, cooldown_s=0.0)
    assert trigger.request("doomed")["accepted"]
    trigger.poll(1)
    assert trigger.status()["active"] is None
    assert registry.snapshot()["profile/errors"] == 1.0
    # the trigger recovers: a later request can still capture
    assert trigger.request("retry")["accepted"]


def test_scheduled_window_clamps_and_drops_past_windows(tmp_path, monkeypatch):
    rec = _ProfilerRecorder(monkeypatch)
    trigger = ProfileTrigger(run_dir=tmp_path, budget=4, cooldown_s=0.0)
    # clamped to max_steps: [3, 5) -> [3, 4)
    assert trigger.schedule(3, 2, max_steps=4)
    # zero after clamping: refused up front, like the old callback
    assert not trigger.schedule(5, 2, max_steps=5)
    trigger.poll(3)
    assert rec.calls == [("start", str(tmp_path / "profile-window-3"))]
    trigger.poll(4)
    assert rec.calls[-1] == ("stop",)
    # a resume landing PAST a scheduled window must drop it silently,
    # never open a trace only teardown would close
    assert trigger.schedule(6, 2)
    trigger.poll(50)
    assert len(rec.calls) == 2
    assert trigger.status()["scheduled"] == []


def test_scheduled_window_honors_explicit_trace_dir(tmp_path, monkeypatch):
    rec = _ProfilerRecorder(monkeypatch)
    trigger = ProfileTrigger(run_dir=tmp_path, cooldown_s=0.0)
    explicit = tmp_path / "bench-trace"
    assert trigger.schedule(2, 1, trace_dir=str(explicit))
    trigger.poll(2)
    assert rec.calls == [("start", str(explicit))]


def test_teardown_closes_dangling_capture_and_refuses(tmp_path, monkeypatch):
    rec = _ProfilerRecorder(monkeypatch)
    trigger = ProfileTrigger(run_dir=tmp_path, cooldown_s=0.0)
    trigger.request("dangling")
    trigger.poll(1)
    trigger.teardown()
    assert rec.calls[-1] == ("stop",)
    trigger.teardown()  # idempotent
    assert rec.calls[-1] == ("stop",)
    refused = trigger.request("late")
    assert not refused["accepted"] and refused["reason"] == "torn-down"
    # the teardown-stopped capture still writes its manifest
    assert (tmp_path / "profile-dangling.json").exists()


def test_process_global_publication():
    set_profile_trigger(None)
    assert get_profile_trigger() is None
    trigger = build_profile_trigger(budget=1)
    try:
        assert get_profile_trigger() is trigger
    finally:
        set_profile_trigger(None)


# ----------------------------------------------------- /profilez endpoint


@pytest.fixture
def exporter_factory():
    started = []

    def make(**kwargs) -> MetricsExporter:
        exporter = MetricsExporter(0, **kwargs)
        assert exporter.start()
        started.append(exporter)
        return exporter

    yield make
    for exporter in started:
        exporter.stop()


def test_profilez_round_trip_and_refusal(exporter_factory):
    registry = TelemetryRegistry()
    trigger = ProfileTrigger(registry=registry, budget=4, cooldown_s=0.0)
    exporter = exporter_factory(registry=registry, profile=trigger)
    url = f"http://127.0.0.1:{exporter.port}/profilez?tag=operator-look"
    with urllib.request.urlopen(url, timeout=5.0) as resp:
        assert resp.status == 200
        body = json.loads(resp.read().decode())
    assert body["accepted"] and body["tag"] == "operator-look"
    assert body["status"]["pending"] == "operator-look"
    # second request while the first is pending: 429, the refusal IS the
    # budget/cooldown/busy machinery answering honestly
    with pytest.raises(urllib.error.HTTPError) as err:
        urllib.request.urlopen(url, timeout=5.0)
    assert err.value.code == 429
    refused = json.loads(err.value.read().decode())
    assert not refused["accepted"] and refused["reason"] == "busy"


def test_profilez_without_trigger_is_404(exporter_factory):
    exporter = exporter_factory(registry=TelemetryRegistry())
    with pytest.raises(urllib.error.HTTPError) as err:
        urllib.request.urlopen(
            f"http://127.0.0.1:{exporter.port}/profilez", timeout=5.0
        )
    assert err.value.code == 404


def test_profile_main_cli(exporter_factory, capsys, monkeypatch):
    monkeypatch.delenv("LLMT_METRICS_PORT", raising=False)
    trigger = ProfileTrigger(budget=4, cooldown_s=0.0)
    exporter = exporter_factory(registry=TelemetryRegistry(), profile=trigger)
    assert profile_main(port=exporter.port, tag="from-cli") == 0
    assert trigger.status()["pending"] == "from-cli"
    # suppressed (busy) maps to exit 3, unreachable to exit 2
    assert profile_main(port=exporter.port, tag="again") == 3
    exporter.stop()
    assert profile_main(port=exporter.port, tag="dead", timeout_s=0.5) == 2
    assert profile_main(port=None) == 2  # no port resolvable
    capsys.readouterr()


# ------------------------------------------------------------ attribution

_SYNTHETIC_HLO = """\
HloModule train_step

fused_computation {
  ROOT mul = f32[128,64] multiply(f32[128,64] a, f32[128,64] b)
}

ENTRY main {
  ar = f32[1024,8] all-reduce(f32[1024,8] g), replica_groups={{0,1,2,3}}, to_apply=add
  ag.s = (bf16[256], bf16[512]) all-gather-start(bf16[256] p), replica_groups=[4,2]<=[8], dimensions={0}
  ag.d = bf16[512] all-gather-done((bf16[256], bf16[512]) ag.s)
  rs = f16[64,32] reduce-scatter(f16[128,32] h), replica_groups={{0,1},{2,3}}, dimensions={0}
  cp = u8[16] collective-permute(u8[16] x), source_target_pairs={{0,1},{1,0}}
  no = f32[4] add(f32[4] y, f32[4] z)
}
"""


def test_parse_hlo_collectives_kinds_groups_and_payloads():
    colls = parse_hlo_collectives(_SYNTHETIC_HLO)
    by_kind = {c["kind"]: c for c in colls}
    assert len(colls) == 4  # the -done half and plain adds never match
    assert by_kind["all_reduce"]["bytes"] == 1024 * 8 * 4
    assert by_kind["all_reduce"]["group_size"] == 4
    # tuple result shape: every element counts; iota replica_groups parse
    assert by_kind["all_gather"]["bytes"] == (256 + 512) * 2
    assert by_kind["all_gather"]["group_size"] == 2
    assert by_kind["reduce_scatter"]["bytes"] == 64 * 32 * 2
    assert by_kind["reduce_scatter"]["group_size"] == 2
    # source_target_pairs form says nothing about group cardinality
    assert by_kind["collective_permute"]["group_size"] is None
    assert by_kind["collective_permute"]["bytes"] == 16


class _FakeCompiled:
    def __init__(self, hlo: str | None, cost: dict | None = None):
        self._hlo = hlo
        self._cost = cost or {}

    def cost_analysis(self):
        return self._cost

    def as_text(self):
        if self._hlo is None:
            raise RuntimeError("no HLO")
        return self._hlo


def test_compiled_attribution_gauges_split_by_axis():
    compiled = _FakeCompiled(
        _SYNTHETIC_HLO, {"flops": 1.0e9, "bytes accessed": 1.0e6}
    )
    gauges = compiled_attribution_gauges(
        compiled, mesh_axes={"data": 2, "fsdp": 4}
    )
    total = (1024 * 8 * 4) + (256 + 512) * 2 + 64 * 32 * 2 + 16
    assert gauges["attr/flops_per_step"] == 1.0e9
    assert gauges["attr/collective_bytes_per_step"] == total
    assert gauges["attr/collective_ops"] == 4.0
    assert gauges["attr/comm_fraction"] == pytest.approx(
        min(1.0, total / 1.0e6)
    )
    # group size 4 -> fsdp, group size 2 -> data; the pair-form permute
    # cannot be matched on a two-axis mesh and stays unattributed
    assert gauges["attr/mesh/fsdp/collective_bytes"] == 1024 * 8 * 4
    assert gauges["attr/mesh/data/collective_bytes"] == (
        (256 + 512) * 2 + 64 * 32 * 2
    )
    assert gauges["attr/mesh/unattributed/collective_bytes"] == 16


def test_attribution_single_axis_mesh_claims_everything():
    gauges = compiled_attribution_gauges(
        _FakeCompiled(_SYNTHETIC_HLO), mesh_axes={"data": 1, "fsdp": 8}
    )
    # one non-trivial axis: even unmatched group sizes belong to it
    assert "attr/mesh/unattributed/collective_bytes" not in gauges
    assert gauges["attr/mesh/fsdp/collective_bytes"] == gauges[
        "attr/collective_bytes_per_step"
    ]


def test_attribution_no_collectives_publishes_stable_zero_record():
    gauges = compiled_attribution_gauges(
        _FakeCompiled("ENTRY main { ROOT a = f32[2] add(f32[2] x, f32[2] y) }",
                      {"flops": 10.0, "bytes accessed": 100.0}),
        mesh_axes={"data": 1, "fsdp": 1},
    )
    assert gauges["attr/comm_fraction"] == 0.0
    assert gauges["attr/collective/all_reduce_bytes"] == 0.0
    assert gauges["attr/collective_ops"] == 0.0


def test_attribution_without_hlo_text_returns_nothing():
    assert compiled_attribution_gauges(_FakeCompiled(None)) == {}


# ------------------------------------------------------- per-device HBM


def _stats(in_use, limit=0, peak=None):
    stats = {"bytes_in_use": in_use, "peak_bytes_in_use": peak or in_use}
    if limit:
        stats["bytes_limit"] = limit
    return stats


def test_hbm_rollup_reports_worst_device_and_per_device_gauges():
    per_device = [
        (0, _stats(4.0e9, limit=16.0e9)),
        (1, _stats(12.0e9, limit=16.0e9)),  # the one that OOMs first
    ]
    gauges = _gauges_from_stats(per_device)
    # legacy flat keys = the WORST device, coherently
    assert gauges["hbm/bytes_in_use"] == 12.0e9
    assert gauges["hbm/bytes_limit"] == 16.0e9
    assert gauges["hbm/worst_device"] == 1.0
    assert gauges["hbm/devices"] == 2.0
    assert gauges["hbm/mean_bytes_in_use"] == 8.0e9
    assert gauges["hbm/device0/bytes_in_use"] == 4.0e9
    assert gauges["hbm/device1/bytes_in_use"] == 12.0e9
    assert "hbm/host_fallback" not in gauges


def test_hbm_gauges_fall_back_to_host_rss_on_cpu():
    gauges = hbm_gauges()  # CPU backend: no allocator stats
    assert gauges.get("hbm/host_fallback") == 1.0
    assert gauges["hbm/bytes_in_use"] > 0


def test_hbm_timeline_records_bound_and_highwater(tmp_path, monkeypatch):
    samples = [
        [(0, _stats(4.0e9, limit=16.0e9)), (1, _stats(5.0e9, limit=16.0e9))],
        [(0, _stats(15.0e9, limit=16.0e9)), (1, _stats(5.0e9, limit=16.0e9))],
        [(0, _stats(15.1e9, limit=16.0e9)), (1, _stats(5.0e9, limit=16.0e9))],
        [(0, _stats(3.0e9, limit=16.0e9)), (1, _stats(5.0e9, limit=16.0e9))],
    ]
    feed = iter(samples)
    monkeypatch.setattr(
        "llm_training_tpu.telemetry.device.local_device_memory_stats",
        lambda: next(feed),
    )
    tracer = TraceRecorder(capacity=64)
    previous = set_tracer(tracer)
    try:
        registry = TelemetryRegistry()
        timeline = HBMTimeline(
            run_dir=tmp_path, registry=registry,
            max_records=3, highwater_frac=0.9, clock=lambda: 1.0,
        )
        gauges = timeline.sample(1)
        assert gauges["hbm/worst_device"] == 1.0
        assert gauges["hbm_timeline/records"] == 1.0
        timeline.sample(2)  # device 0 crosses 90% -> ONE instant
        timeline.sample(3)  # still over: no re-fire
        gauges = timeline.sample(4)  # back below: re-armed, capped file
        assert gauges["hbm_timeline/highwater_events"] == 1.0
        assert gauges["hbm_timeline/truncated"] == 1.0
        assert registry.snapshot()["hbm_timeline/highwater_events"] == 1.0
        instants = [
            e for e in tracer.snapshot() if e.get("name") == "highwater"
        ]
        assert len(instants) == 1
        assert instants[0]["args"]["device"] == 0
        lines = (tmp_path / "hbm.jsonl").read_text().splitlines()
        assert len(lines) == 3  # the bound held
        first = json.loads(lines[0])
        assert first["step"] == 1
        assert {d["id"] for d in first["devices"]} == {0, 1}
    finally:
        set_tracer(previous)


def test_hbm_timeline_host_fallback_record(tmp_path):
    timeline = HBMTimeline(run_dir=tmp_path, clock=lambda: 2.0)
    gauges = timeline.sample(7)  # CPU: host-RSS fallback
    assert gauges["hbm/host_fallback"] == 1.0
    record = json.loads((tmp_path / "hbm.jsonl").read_text())
    assert record["host_fallback"] is True and record["step"] == 7


# -------------------------------------------------------- report section


def test_report_profiling_section_renders(tmp_path):
    (tmp_path / "profile-slo-x-1.json").write_text(json.dumps({
        "tag": "slo-x-1", "source": "slo", "start_step": 5, "stop_step": 7,
        "duration_s": 0.42, "trace_dir": str(tmp_path / "profile-slo-x-1"),
    }))
    telemetry = {
        "profile/requested": 3.0, "profile/captures": 1.0,
        "profile/suppressed": 2.0, "attr/comm_fraction": 0.25,
        "attr/flops_per_step": 1.0e9,
        "attr/collective_bytes_per_step": 4096.0,
        "attr/collective_ops": 2.0,
        "attr/mesh/fsdp/collective_bytes": 4096.0,
        "hbm_timeline/records": 12.0, "hbm_timeline/highwater_events": 1.0,
    }
    summary = _profiling_summary(tmp_path, telemetry)
    assert summary is not None
    assert summary["captures"][0]["tag"] == "slo-x-1"
    text = "\n".join(_profiling_section(summary))
    assert "== Profiling ==" in text
    assert "captures: 1 (requested 3, suppressed 2)" in text
    assert "profile-slo-x-1.json: steps 5..7, 0.42s (slo)" in text
    assert "comm fraction: 25.0% of bytes accessed" in text
    assert "mesh fsdp: 4,096 B" in text
    assert "hbm timeline: 12 record(s), 1 high-water crossing(s)" in text


def test_report_profiling_section_omitted_when_run_never_profiled(tmp_path):
    assert _profiling_summary(tmp_path, {"loss": 1.0}) is None
    assert _profiling_section(None) == []


def test_report_profiling_torn_manifest_degrades_to_error_line(tmp_path):
    (tmp_path / "profile-torn.json").write_text('{"tag": "torn", "sta')
    (tmp_path / "profile-empty.json").write_text("{}")
    summary = _profiling_summary(tmp_path, {})
    text = "\n".join(_profiling_section(summary))
    assert "profile-torn.json: unreadable manifest" in text
    # parsed-but-incomplete manifest: its own honest line, never the section
    assert "profile-empty.json: unreadable manifest — malformed fields" in text


# -------------------------------------------- ProfilerCallback absorption


def test_profiler_callback_exposes_window_and_goes_passive(monkeypatch):
    import jax

    from llm_training_tpu.callbacks import ProfilerCallback, ProfilerCallbackConfig

    calls: list = []
    monkeypatch.setattr(
        jax.profiler, "start_trace", lambda d: calls.append(("start", d))
    )
    monkeypatch.setattr(
        jax.profiler, "stop_trace", lambda: calls.append(("stop",))
    )
    cb = ProfilerCallback(ProfilerCallbackConfig(start_step=2, num_steps=3))
    assert cb.profile_window() == (2, 3, None)
    cb._absorbed = True  # what the trainer sets after trigger.schedule()
    for step in range(1, 7):
        cb.on_train_step(None, step)
    assert calls == []  # the trigger owns the capture now
    cb.teardown()


def test_profiler_callback_standalone_resolves_default_dir(monkeypatch):
    import jax

    from llm_training_tpu.callbacks import ProfilerCallback, ProfilerCallbackConfig
    from llm_training_tpu.callbacks.profiler import DEFAULT_TRACE_DIR

    calls: list = []
    monkeypatch.setattr(
        jax.profiler, "start_trace", lambda d: calls.append(("start", d))
    )
    monkeypatch.setattr(
        jax.profiler, "stop_trace", lambda: calls.append(("stop",))
    )
    cb = ProfilerCallback(ProfilerCallbackConfig(start_step=1, num_steps=1))
    cb.on_train_step(None, 1)
    cb.on_train_step(None, 2)
    # unset trace_dir resolves to the standalone default AND is written
    # back so callers read the actual capture location off the config
    assert cb.config.trace_dir == DEFAULT_TRACE_DIR
    assert calls == [("start", DEFAULT_TRACE_DIR), ("stop",)]
