"""Mixture-of-experts: dense/ragged impl agreement, HF logits parity for
Mixtral / Qwen2-MoE / Qwen3-MoE, export round trip, aux loss, and training.

The reference reaches MoE only through HFCausalLM's torch wrapping
(`hf_causal_lm.py:22`); here the graph is native (models/moe.py) with a
dropless ragged_dot grouped-matmul path, so parity against the HF torch
implementations is the correctness bar.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_training_tpu.models import Llama, LlamaConfig
from llm_training_tpu.models.llama.hf_conversion import (
    config_from_hf,
    params_from_hf,
    params_to_hf,
)

TINY_MOE = dict(
    vocab_size=128,
    hidden_size=64,
    intermediate_size=112,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    max_position_embeddings=64,
    num_experts=4,
    num_experts_per_tok=2,
    moe_intermediate_size=48,
    compute_dtype="float32",
)


@pytest.mark.slow
def test_dense_and_ragged_impls_agree():
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 128, (2, 24)))
    cfg_d = LlamaConfig(**TINY_MOE, moe_impl="dense")
    cfg_r = LlamaConfig(**TINY_MOE, moe_impl="ragged")
    model_d, model_r = Llama(cfg_d), Llama(cfg_r)
    params = model_d.init(jax.random.key(0), ids)
    out_d = model_d.apply(params, ids)
    out_r = model_r.apply(params, ids)
    np.testing.assert_allclose(out_d.logits, out_r.logits, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(out_d.aux_loss, out_r.aux_loss, rtol=1e-6)


def test_router_stats_parity_dense_vs_ragged():
    """The health-layer router stats (load fractions / dropped fraction)
    must be impl-invariant: dense and ragged on the same params/batch agree
    exactly on sel_frac/mean_prob, and both truly-dropless single-rank
    paths report zero drops (guards the EP capacity-buffer accounting —
    an impl that drifted here would corrupt the telemetry the
    ep_capacity_factor tuning reads)."""
    tiny = dict(TINY_MOE, num_hidden_layers=1)
    ids = jnp.asarray(np.random.default_rng(3).integers(0, 128, (2, 16)))
    model_d = Llama(LlamaConfig(**tiny, moe_impl="dense"))
    model_r = Llama(LlamaConfig(**tiny, moe_impl="ragged"))
    params = model_d.init(jax.random.key(3), ids)
    rs_d = model_d.apply(params, ids).router_stats
    rs_r = model_r.apply(params, ids).router_stats
    assert rs_d.layer_ids == rs_r.layer_ids == (0,)
    np.testing.assert_allclose(rs_d.sel_frac, rs_r.sel_frac, rtol=1e-6)
    np.testing.assert_allclose(rs_d.mean_prob, rs_r.mean_prob, rtol=1e-6)
    # each row sums to top_k (each of the K selections per token counts)
    np.testing.assert_allclose(
        np.asarray(rs_d.sel_frac.sum(axis=-1)),
        TINY_MOE["num_experts_per_tok"], rtol=1e-6,
    )
    assert float(rs_d.dropped) == 0.0 and float(rs_r.dropped) == 0.0


def test_bucketed_impl_matches_dense_at_full_capacity():
    """moe_impl='bucketed' with capacity >= every group size is exact: the
    dense-bmm bucket formulation must reproduce the dense path bit-for-tol,
    and report zero drops."""
    ids = jnp.asarray(np.random.default_rng(1).integers(0, 128, (2, 24)))
    cfg_d = LlamaConfig(**TINY_MOE, moe_impl="dense")
    # factor = num_experts -> capacity == all T*K rows: drops impossible
    cfg_b = LlamaConfig(**TINY_MOE, moe_impl="bucketed", moe_capacity_factor=4.0)
    model_d, model_b = Llama(cfg_d), Llama(cfg_b)
    params = model_d.init(jax.random.key(1), ids)
    out_d = model_d.apply(params, ids)
    out_b = model_b.apply(params, ids)
    np.testing.assert_allclose(out_d.logits, out_b.logits, rtol=2e-5, atol=2e-5)
    assert float(out_b.ep_dropped_rows) == 0.0


def test_bucketed_impl_counts_drops():
    """Tiny capacity drops exactly the rows beyond each expert's bucket,
    the counter matches the capacity math, and gradients still flow."""
    from llm_training_tpu.models.moe import dropless_moe_apply

    T, H, E, K = 16, 8, 4, 2
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((T, H)), jnp.float32)
    topk_idx = jnp.zeros((T, K), jnp.int32)  # all 32 rows -> expert 0
    topk_w = jnp.full((T, K), 0.5, jnp.float32)
    w = jnp.asarray(rng.standard_normal((E, H, H)) * 0.1, jnp.float32)

    def bmm_fn(xb):
        return jnp.einsum("ech,ehg->ecg", xb, w)

    def f(x):
        out, dropped = dropless_moe_apply(
            x, topk_idx, topk_w, E, "bucketed", None, None,
            bmm_fn=bmm_fn, moe_capacity_factor=1.0,
        )
        return out.sum(), dropped

    (total, dropped), grads = jax.value_and_grad(f, has_aux=True)(x)
    # capacity = ceil(32/4 * 1.0) = 8 rows/expert; expert 0 gets all 32
    # assignments -> 24 dropped
    assert float(dropped) == 24.0
    assert np.isfinite(float(total)) and np.all(np.isfinite(np.asarray(grads)))


@pytest.mark.slow
def test_aux_loss_near_topk_at_init():
    """Balanced routing at random init: f_e ~ top_k/E, P_e ~ 1/E, so the
    HF-scale aux E * sum(f_pooled * P_pooled) ~ top_k regardless of depth
    (stats pool across layers BEFORE the product, and each of the K
    selections per token is counted, like HF's load_balancing_loss_func
    whose coefficient the conversion imports verbatim)."""
    ids = jnp.asarray(np.random.default_rng(1).integers(0, 128, (4, 32)))
    cfg = LlamaConfig(**TINY_MOE)
    model = Llama(cfg)
    params = model.init(jax.random.key(1), ids)
    aux = float(model.apply(params, ids).aux_loss)
    assert np.isfinite(aux)
    top_k = TINY_MOE["num_experts_per_tok"]
    assert 0.9 * top_k < aux < 1.6 * top_k


@pytest.mark.slow
def test_aux_loss_excludes_padding():
    """Router statistics must ignore padding tokens (segment id 0): the aux
    over a padded batch equals the aux over the unpadded rows."""
    rng = np.random.default_rng(2)
    ids = jnp.asarray(rng.integers(1, 128, (2, 24)))
    seg_full = jnp.ones((2, 24), jnp.int32)
    padded_ids = jnp.concatenate([ids, jnp.zeros((2, 8), jnp.int32)], axis=1)
    seg_padded = jnp.concatenate([seg_full, jnp.zeros((2, 8), jnp.int32)], axis=1)

    cfg = LlamaConfig(**TINY_MOE, moe_impl="dense")
    model = Llama(cfg)
    params = model.init(jax.random.key(2), ids)
    aux_ref = float(model.apply(params, ids, segment_ids=seg_full).aux_loss)
    aux_pad = float(model.apply(params, padded_ids, segment_ids=seg_padded).aux_loss)
    np.testing.assert_allclose(aux_pad, aux_ref, rtol=1e-5)


@pytest.mark.slow
def test_dense_model_has_no_aux():
    cfg = LlamaConfig(**{k: v for k, v in TINY_MOE.items()
                         if not k.startswith(("num_experts", "moe_"))})
    model = Llama(cfg)
    ids = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(0), ids)
    assert model.apply(params, ids).aux_loss is None


# ------------------------------------------------------------ HF parity


def _parity(hf_model, hf_config, seed):
    torch = pytest.importorskip("torch")
    cfg = config_from_hf(hf_config, compute_dtype="float32", moe_impl="dense")
    params = params_from_hf(hf_model.state_dict(), cfg)
    model = Llama(cfg)
    ids = np.random.default_rng(seed).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=3e-4, atol=3e-4)
    return cfg, params, model


@pytest.mark.slow
def test_logits_parity_with_hf_mixtral():
    torch = pytest.importorskip("torch")
    from transformers import MixtralConfig, MixtralForCausalLM

    hf_config = MixtralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, num_local_experts=4, num_experts_per_tok=2,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = MixtralForCausalLM(hf_config).eval()
    assert "model.layers.0.block_sparse_moe.experts.0.w1.weight" in hf_model.state_dict()
    cfg, _, _ = _parity(hf_model, hf_config, seed=20)
    assert cfg.moe_style == "mixtral" and cfg.norm_topk_prob


def test_logits_parity_with_hf_qwen2_moe():
    torch = pytest.importorskip("torch")
    from transformers import Qwen2MoeConfig, Qwen2MoeForCausalLM

    hf_config = Qwen2MoeConfig(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, num_experts=4, num_experts_per_tok=2,
        moe_intermediate_size=48, shared_expert_intermediate_size=80,
        norm_topk_prob=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = Qwen2MoeForCausalLM(hf_config).eval()
    sd = hf_model.state_dict()
    assert "model.layers.0.mlp.shared_expert_gate.weight" in sd
    assert "model.layers.0.self_attn.q_proj.bias" in sd  # qwen2-style biases
    cfg, _, _ = _parity(hf_model, hf_config, seed=21)
    assert cfg.shared_expert_intermediate_size == 80
    assert cfg.attention_bias and not cfg.attention_out_bias


def test_logits_parity_with_hf_qwen3_moe():
    torch = pytest.importorskip("torch")
    from transformers import Qwen3MoeConfig, Qwen3MoeForCausalLM

    hf_config = Qwen3MoeConfig(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64, num_experts=4,
        num_experts_per_tok=2, moe_intermediate_size=48, norm_topk_prob=True,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = Qwen3MoeForCausalLM(hf_config).eval()
    cfg, _, _ = _parity(hf_model, hf_config, seed=22)
    assert cfg.qk_norm and cfg.norm_topk_prob


@pytest.mark.slow
def test_moe_export_round_trip(tmp_path):
    """Export our MoE tree -> transformers reloads it as Qwen3-MoE with
    matching logits (expert stacks unstack correctly in both directions)."""
    torch = pytest.importorskip("torch")
    from transformers import AutoModelForCausalLM

    from llm_training_tpu.models.hf_io import save_hf_checkpoint

    cfg = LlamaConfig(**TINY_MOE, qk_norm=True, head_dim=16, moe_impl="dense")
    model = Llama(cfg)
    ids = jnp.asarray(np.random.default_rng(23).integers(0, 128, (2, 16)))
    params = model.init(jax.random.key(3), ids)
    out_dir = save_hf_checkpoint(params, cfg, tmp_path / "export", dtype="float32")

    hf_model = AutoModelForCausalLM.from_pretrained(
        out_dir, attn_implementation="eager"
    ).eval()
    assert type(hf_model).__name__ == "Qwen3MoeForCausalLM"
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(np.asarray(ids))).logits.numpy()
    ours = model.apply(params, ids).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=3e-4, atol=3e-4)


def test_config_export_reimport_qwen2_moe_style():
    """config_to_hf emits attention_bias=None for the qwen2-style asymmetric
    bias layout; config_from_hf must re-import that as the hardcoded qwen2
    default instead of crashing on the explicit None."""
    from llm_training_tpu.models.llama.hf_conversion import config_to_hf

    cfg = LlamaConfig(
        **TINY_MOE, attention_bias=True, attention_out_bias=False,
        shared_expert_intermediate_size=80,
    )
    hf = config_to_hf(cfg)
    assert hf["model_type"] == "qwen2_moe" and hf["attention_bias"] is None
    back = config_from_hf(hf)
    assert back.attention_bias and not back.attention_out_bias
    assert back.num_experts == cfg.num_experts
    assert back.shared_expert_intermediate_size == 80


def test_hf_round_trip_state_dict():
    pytest.importorskip("torch")
    from transformers import Qwen2MoeConfig, Qwen2MoeForCausalLM

    hf_config = Qwen2MoeConfig(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, num_experts=4, num_experts_per_tok=2,
        moe_intermediate_size=48, shared_expert_intermediate_size=80,
    )
    import torch

    torch.manual_seed(1)
    hf_model = Qwen2MoeForCausalLM(hf_config).eval()
    cfg = config_from_hf(hf_config)
    params = params_from_hf(hf_model.state_dict(), cfg)
    back = params_to_hf(params, cfg)
    sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    assert set(back) == set(sd)
    for key in sd:
        np.testing.assert_array_equal(back[key], sd[key], err_msg=key)


# ------------------------------------------------------------ training


@pytest.mark.slow
def test_moe_trains_and_logs_aux(devices):
    """End-to-end fit on the CPU mesh: loss decreases, aux_loss is finite
    and reported, ragged impl under jit+grad+remat+scan."""
    from llm_training_tpu.data import DummyDataModule, DummyDataModuleConfig
    from llm_training_tpu.lms import CLM, CLMConfig, ModelProvider
    from llm_training_tpu.optim import OptimConfig
    from llm_training_tpu.parallel import MeshConfig
    from llm_training_tpu.trainer import Trainer, TrainerConfig

    seen = {}

    class Capture:
        def on_step_end(self, trainer, step, metrics):
            seen[step] = {k: float(v) for k, v in metrics.items()
                          if k in ("loss", "aux_loss")}

    objective = CLM(
        CLMConfig(
            model=ModelProvider(
                model_class="Llama",
                model_kwargs=dict(
                    **{**TINY_MOE, "compute_dtype": "float32",
                       "param_dtype": "float32"},
                    moe_impl="ragged",
                    enable_gradient_checkpointing=True,
                ),
            ),
            optim=OptimConfig(learning_rate=3e-3, warmup_steps=2),
        )
    )
    # data vocab (16) << model vocab (128): initial loss ~ln(128) has clear
    # headroom above the ~ln(16) floor, so the decrease assertion is stable
    dm = DummyDataModule(DummyDataModuleConfig(
        batch_size=8, max_length=32, num_samples=256, vocab_size=16))
    trainer = Trainer(
        TrainerConfig(max_steps=16, log_every_n_steps=4, mesh=MeshConfig()),
        callbacks=[Capture()],
    )
    trainer.fit(objective, dm)
    steps = sorted(seen)
    assert seen[steps[-1]]["loss"] < seen[steps[0]]["loss"]
    assert all(np.isfinite(m["aux_loss"]) for m in seen.values())


def test_logits_parity_with_hf_olmoe():
    """OLMoE routes to the Llama module: full-width qk-norm (pre-norm
    blocks, unlike OLMo-2), clip_qkv clamp, and qwen-style expert naming
    where HF's intermediate_size is the per-expert width."""
    torch = pytest.importorskip("torch")
    from transformers import OlmoeConfig, OlmoeForCausalLM

    hf_config = OlmoeConfig(
        vocab_size=128, hidden_size=64, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, num_experts=4, num_experts_per_tok=2,
        norm_topk_prob=False, clip_qkv=3.0,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = OlmoeForCausalLM(hf_config).eval()
    sd = hf_model.state_dict()
    assert "model.layers.0.mlp.experts.0.gate_proj.weight" in sd
    assert "model.layers.0.input_layernorm.weight" in sd  # pre-norm, not OLMo-2
    # full-width: the q norm spans all heads
    assert sd["model.layers.0.self_attn.q_norm.weight"].shape == (64,)
    cfg, _, _ = _parity(hf_model, hf_config, seed=22)
    assert cfg.qk_norm_scope == "full" and cfg.norm_scheme == "pre"
    assert cfg.moe_intermediate_size == 48 and cfg.clip_qkv == 3.0


def test_logits_parity_with_hf_flex_olmo():
    """FlexOlmo routes to the Llama module: OLMo-2 post-norm blocks +
    full-width qk-norm composed with the OLMoE-style sparse MoE (softmax
    top-k over qwen-named experts, intermediate_size = per-expert width)."""
    torch = pytest.importorskip("torch")
    from transformers import FlexOlmoConfig, FlexOlmoForCausalLM

    from llm_training_tpu.models.llama.hf_conversion import (
        config_from_hf,
        config_to_hf,
        params_from_hf,
    )

    hf_config = FlexOlmoConfig(
        vocab_size=128, hidden_size=64, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, num_experts=4, num_experts_per_tok=2,
        norm_topk_prob=False, pad_token_id=0, attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = FlexOlmoForCausalLM(hf_config).eval()
    sd = hf_model.state_dict()
    assert "model.layers.0.post_feedforward_layernorm.weight" in sd
    assert "model.layers.0.self_attn.q_norm.weight" in sd
    assert "model.layers.0.mlp.experts.3.gate_proj.weight" in sd

    cfg = config_from_hf(hf_config, compute_dtype="float32", moe_impl="dense")
    assert cfg.norm_scheme == "post" and cfg.qk_norm_scope == "full"
    assert cfg.num_experts == 4 and cfg.moe_intermediate_size == 48
    params = params_from_hf(sd, cfg)
    model = Llama(cfg)

    ids = np.random.default_rng(61).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)

    # export picks flex_olmo (post-norm), not olmoe
    out = config_to_hf(cfg)
    assert out["model_type"] == "flex_olmo"
    cfg2 = config_from_hf(out, compute_dtype="float32")
    assert cfg2.norm_scheme == "post" and cfg2.num_experts == 4


@pytest.mark.parametrize("shared", [False, True])
def test_logits_parity_with_hf_granitemoe(shared):
    """GraniteMoe routes to the Llama module: granite scalar multipliers +
    a PRE-stacked fused-expert MoE (input_linear [E, 2I, H], gate rows
    first; router under router.layer). Its softmax-after-topk routing is
    numerically identical to our softmax->topk->renormalize path. The
    shared variant adds an always-on (gate-free) shared MLP."""
    torch = pytest.importorskip("torch")
    if shared:
        from transformers import GraniteMoeSharedConfig as HFConfig
        from transformers import GraniteMoeSharedForCausalLM as HFModel
        extra = dict(shared_intermediate_size=40)
    else:
        from transformers import GraniteMoeConfig as HFConfig
        from transformers import GraniteMoeForCausalLM as HFModel
        extra = {}

    from llm_training_tpu.models.llama.hf_conversion import config_to_hf

    hf_config = HFConfig(
        vocab_size=128, hidden_size=64, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, num_local_experts=4, num_experts_per_tok=2,
        # non-identity multipliers so the granite scalars are LIVE
        embedding_multiplier=6.0, attention_multiplier=0.2,
        residual_multiplier=0.5, logits_scaling=2.0,
        attn_implementation="eager", **extra,
    )
    torch.manual_seed(0)
    hf_model = HFModel(hf_config).eval()
    sd = hf_model.state_dict()
    assert "model.layers.0.block_sparse_moe.input_linear.weight" in sd
    assert "model.layers.0.block_sparse_moe.router.layer.weight" in sd
    if shared:
        assert "model.layers.0.shared_mlp.input_linear.weight" in sd

    cfg = config_from_hf(hf_config, compute_dtype="float32", moe_impl="dense")
    assert cfg.moe_style == "granite" and cfg.norm_topk_prob
    assert not cfg.shared_expert_gated
    assert cfg.attention_multiplier == 0.2 and cfg.residual_multiplier == 0.5
    params = params_from_hf(sd, cfg)
    model = Llama(cfg)

    ids = np.random.default_rng(62).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=3e-4, atol=3e-4)

    out = config_to_hf(cfg)
    expected = "granitemoeshared" if shared else "granitemoe"
    assert out["model_type"] == expected
    assert out["attention_multiplier"] == 0.2
    cfg2 = config_from_hf(out, compute_dtype="float32", moe_impl="dense")
    # export emits head_dim explicitly (HF GraniteMoe derives it), so the
    # reimport carries the resolved value rather than None
    assert cfg2.resolved_head_dim == cfg.resolved_head_dim
    assert cfg2.model_dump() == {**cfg.model_dump(), "head_dim": cfg2.head_dim}


def test_granitemoe_state_dict_round_trip():
    """params -> HF -> params is exact through the fused-stack layout."""
    torch = pytest.importorskip("torch")
    from transformers import GraniteMoeSharedConfig, GraniteMoeSharedForCausalLM

    hf_config = GraniteMoeSharedConfig(
        vocab_size=128, hidden_size=64, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, num_local_experts=4, num_experts_per_tok=2,
        shared_intermediate_size=40, attn_implementation="eager",
    )
    torch.manual_seed(1)
    hf_model = GraniteMoeSharedForCausalLM(hf_config).eval()
    cfg = config_from_hf(hf_config, compute_dtype="float32")
    params = params_from_hf(hf_model.state_dict(), cfg)
    back = params_to_hf(params, cfg)
    sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    assert set(back) == set(sd)
    for key in sd:
        np.testing.assert_array_equal(back[key], sd[key], err_msg=key)
