"""Example configs as integration fixtures (reference `config/examples/`,
SURVEY.md §4: "Example configs as integration fixtures").

Every example must parse, reference importable classes, and carry valid
trainer/optim nodes. The model/data payloads point at local checkpoint and
corpus paths that don't exist in CI, so full instantiation is exercised once
by swapping in the tiny HF fixture.
"""

from pathlib import Path

import pytest
import yaml

from llm_training_tpu.cli.config import import_class, load_config

EXAMPLES = sorted(
    (Path(__file__).resolve().parent.parent / "config" / "examples").rglob("*.yaml")
)


@pytest.mark.parametrize("path", EXAMPLES, ids=[p.stem for p in EXAMPLES])
def test_example_parses_and_validates_structurally(path):
    config = load_config(path)
    assert set(config) >= {"trainer", "model", "data"}

    from llm_training_tpu.trainer import TrainerConfig

    trainer_node = dict(config["trainer"])
    trainer_node.pop("checkpoint", None)
    callbacks = trainer_node.pop("callbacks", [])
    loggers = trainer_node.pop("loggers", [])
    TrainerConfig(**trainer_node)  # validates mesh sizing etc.

    import importlib

    for node in callbacks + loggers:
        cls = import_class(node["class_path"])
        # constructing the paired pydantic config validates init_args
        module = importlib.import_module(cls.__module__)
        getattr(module, cls.__name__ + "Config")(**node.get("init_args", {}))

    objective_cls = import_class(config["model"]["class_path"])
    assert objective_cls.__name__ in ("CLM", "DPO", "ORPO", "GRPO")
    data_cls = import_class(config["data"]["class_path"])
    assert data_cls is not None

    # optim node validates standalone
    from llm_training_tpu.optim import OptimConfig

    OptimConfig(**config["model"]["init_args"].get("optim", {}))


def test_example_instantiates_with_fixture_checkpoint(tmp_path):
    """Full instantiation of the pt example with the tiny HF fixture swapped
    in for the 8B checkpoint."""
    import torch
    from transformers import LlamaConfig as HFLlamaConfig
    from transformers import LlamaForCausalLM

    torch.manual_seed(0)
    hf_dir = tmp_path / "hf"
    LlamaForCausalLM(
        HFLlamaConfig(
            vocab_size=128, hidden_size=64, intermediate_size=112,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=64,
        )
    ).save_pretrained(hf_dir, safe_serialization=True)

    path = next(p for p in EXAMPLES if p.stem == "llama-3.1-8b_pt")
    config = load_config(path)
    config["model"]["init_args"]["model"]["model_kwargs"]["hf_path"] = str(hf_dir)

    from llm_training_tpu.cli.config import instantiate_from_config
    from llm_training_tpu.models import Llama

    objective = instantiate_from_config(config["model"])
    assert isinstance(objective.model, Llama)
    assert objective.model.config.hidden_size == 64
    assert objective.model.config.pre_trained_weights == str(hf_dir)
