"""Serving-tier resilience tests (docs/serving.md#resilience): deadlines
and load shedding in the jax-free scheduler, the request journal's
replay/dedupe contract, graceful drain + supervised replay token identity
(the tier-1 pin behind the precommit serve-drain gate), hot weight reload
with generation-tagged chunks, chaos serve faults, and the `== Serving ==`
resilience counters."""

import json
import os
import signal
import time

import pytest

from llm_training_tpu.serve.journal import RequestJournal, replay_journal
from llm_training_tpu.serve.paged_cache import BlockAllocator
from llm_training_tpu.serve.scheduler import (
    Scheduler,
    SchedulerConfig,
    ServeRequest,
)

TINY = dict(
    vocab_size=64, hidden_size=32, intermediate_size=64,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    max_position_embeddings=64, attention_impl="xla",
    compute_dtype="float32", param_dtype="float32",
)


def _scheduler(max_batch=2, blocks=8, block_size=8, max_len=32, chunk=4,
               max_queue=None, shed_ttft_ms=None):
    return Scheduler(
        SchedulerConfig(
            max_batch=max_batch, max_model_len=max_len,
            block_size=block_size, prefill_chunk=chunk,
            max_queue=max_queue, shed_ttft_ms=shed_ttft_ms,
        ),
        BlockAllocator(blocks + 1),
    )


def _request(rid, prompt_len=2, n=4, priority=0, arrival=None, deadline_s=None):
    request = ServeRequest(
        id=rid, prompt=[1] * prompt_len, max_new_tokens=n, priority=priority,
        **({"arrival_s": arrival} if arrival is not None else {}),
    )
    request.deadline_s = deadline_s
    return request


# ------------------------------------------------------------- deadlines


def test_deadline_expires_in_queue():
    """A queued request past its deadline terminates with 'deadline'
    before costing a prefill FLOP; an undeadlined neighbor is untouched."""
    scheduler = _scheduler()
    now = time.perf_counter()
    late = _request("late", arrival=now - 10.0, deadline_s=now - 1.0)
    fine = _request("fine", arrival=now - 10.0)
    scheduler.submit(late)
    scheduler.submit(fine)
    scheduler.expire_deadlines(now)
    assert late.stop_reason == "deadline"
    assert late in scheduler.completed
    assert list(scheduler.waiting) == [fine] and fine.stop_reason is None
    assert scheduler.deadline_total == 1


def test_deadline_expires_mid_decode_frees_blocks():
    """A DECODING request past its deadline finishes (slot + blocks
    released) and its streamed-so-far tokens stand as the partial
    result."""
    scheduler = _scheduler()
    now = time.perf_counter()
    request = _request("r", prompt_len=4, n=8, deadline_s=now + 60.0)
    scheduler.submit(request)
    scheduler.admit()
    assert scheduler.allocator.blocks_in_use >= 1
    request.generated = [7, 8]
    scheduler.expire_deadlines(now + 120.0)  # deadline long blown
    assert request.stop_reason == "deadline"
    assert request.slot is None and scheduler.allocator.blocks_in_use == 0
    assert request.generated == [7, 8]
    assert scheduler.deadline_total == 1
    # expiry is idempotent: a second sweep finds nothing
    scheduler.expire_deadlines(now + 200.0)
    assert scheduler.deadline_total == 1


# ---------------------------------------------------------- load shedding


def test_shed_order_is_eviction_priority_order():
    """Over the queue bound, victims fall in eviction-priority order:
    lowest priority first, ties to the YOUNGEST arrival — under overload
    the queue keeps exactly the requests eviction would have kept."""
    scheduler = _scheduler(max_queue=1)
    now = 100.0
    vip = _request("vip", priority=2, arrival=now + 0)
    old = _request("old", priority=0, arrival=now + 1)
    young = _request("young", priority=0, arrival=now + 2)
    for request in (vip, old, young):
        scheduler.waiting.append(request)
    scheduler.shed()
    # two must go to reach max_queue=1: both priority-0s, youngest first
    assert young.stop_reason == "overloaded"
    assert old.stop_reason == "overloaded"
    assert vip.stop_reason is None and list(scheduler.waiting) == [vip]
    assert scheduler.shed_total == 2


def test_bounded_queue_backpressure_at_submit():
    """With every decode slot busy, submit itself sheds over the bound —
    an honest synchronous 'overloaded', never a wedged or unbounded
    intake. With a slot free the bound waits for the next admit pass."""
    scheduler = _scheduler(max_batch=1, max_queue=1)
    running = _request("running", prompt_len=4, n=8)
    scheduler.submit(running)
    scheduler.admit()
    assert not scheduler._free_slots
    first = _request("q1")
    second = _request("q2", arrival=time.perf_counter() + 1)
    scheduler.submit(first)
    assert first.stop_reason is None  # within the bound
    scheduler.submit(second)
    # over the bound while saturated: the lowest-priority/youngest queued
    # request is shed immediately
    assert second.stop_reason == "overloaded"
    assert list(scheduler.waiting) == [first]
    # free-slot case: no shed at submit even over the bound
    relaxed = _scheduler(max_batch=2, max_queue=0)
    queued = _request("q")
    relaxed.submit(queued)
    assert queued.stop_reason is None and list(relaxed.waiting) == [queued]


def test_projected_ttft_shedding():
    """With a service-time estimate, a queue tail projecting past
    shed_ttft_ms is shed; without an estimate TTFT shedding never fires
    (no guess, no drop)."""
    scheduler = _scheduler(max_batch=2, shed_ttft_ms=1500.0)
    for n in range(4):
        scheduler.waiting.append(_request(f"r{n}", arrival=100.0 + n))
    scheduler.shed()  # no EMA yet: nothing sheds
    assert scheduler.shed_total == 0 and len(scheduler.waiting) == 4
    scheduler._service_ema_s = 1.0  # 1s/request, batch 2
    # tail at position 3 -> (3//2 + 1) * 1000ms = 2000ms > 1500ms
    assert scheduler.projected_ttft_ms(3) == pytest.approx(2000.0)
    scheduler.shed()
    # shedding stops once the tail projects inside the bound (position 1
    # -> 1000ms)
    assert len(scheduler.waiting) == 2
    assert scheduler.shed_total == 2
    assert [r.id for r in scheduler.waiting] == ["r0", "r1"]


def test_finish_seeds_service_time_ema():
    scheduler = _scheduler()
    request = _request("r", prompt_len=4, n=2,
                       arrival=time.perf_counter() - 2.0)
    scheduler.submit(request)
    scheduler.admit()
    scheduler.finish(request, "max_tokens")
    assert scheduler._service_ema_s == pytest.approx(2.0, abs=0.5)
    # failures never feed the estimate
    failed = _request("f", arrival=time.perf_counter() - 50.0)
    scheduler.submit(failed)
    scheduler.admit()
    scheduler.finish(failed, "deadline")
    assert scheduler._service_ema_s == pytest.approx(2.0, abs=0.5)


# ---------------------------------------------------------------- journal


def test_journal_roundtrip_dedupe_and_done_exclusion(tmp_path):
    path = tmp_path / "journal.jsonl"
    journal = RequestJournal(path)
    a = _request("a", prompt_len=3, n=8)
    journal.accepted(a)
    a.generated = [5, 6]
    a.emitted = 2
    journal.progress(a)
    b = _request("b", prompt_len=1, n=2)
    journal.accepted(b)
    b.stop_reason = "max_tokens"
    journal.finished(b)
    # id reuse: a NEW 'a' accepted after the first — last acceptance wins
    a2 = _request("a", prompt_len=2, n=4)
    journal.accepted(a2)
    journal.close()
    entries = replay_journal(path)
    assert [e["id"] for e in entries] == ["a"]
    assert entries[0]["prompt"] == [1, 1]  # the reused acceptance
    assert entries[0]["generated"] == [] and entries[0]["emitted"] == 0
    # replay is a pure read: a second fold sees the same remainder
    assert replay_journal(path) == entries


def test_journal_survives_torn_tail_and_junk(tmp_path):
    path = tmp_path / "journal.jsonl"
    journal = RequestJournal(path)
    request = _request("a", prompt_len=2, n=8)
    request.deadline_s = request.arrival_s + 0.25
    journal.accepted(request)
    request.generated = [9]
    request.emitted = 1
    journal.progress(request)
    journal.close()
    with open(path, "a") as f:
        f.write('["not", "a", "record"]\n')
        f.write('{"event": "done", "id": 42}\n')  # non-str id: skipped
        f.write('{"event": "progress", "id": "a", "gen')  # torn tail
    entries = replay_journal(path)
    assert len(entries) == 1
    assert entries[0]["generated"] == [9] and entries[0]["emitted"] == 1
    assert entries[0]["deadline_ms"] == pytest.approx(250.0, abs=1.0)
    assert replay_journal(tmp_path / "absent.jsonl") == []


def test_journal_progress_delta_encoding_folds_back(tmp_path):
    """Progress records are deltas (O(tokens) journal growth, not
    O(tokens^2)); the fold re-concatenates, and a gap from a dropped
    record degrades to the shorter known prefix — re-stream, never
    invent."""
    path = tmp_path / "journal.jsonl"
    journal = RequestJournal(path)
    request = _request("a", prompt_len=2, n=16)
    journal.accepted(request)
    request.generated = [1, 2, 3]
    request.emitted = 3
    journal.progress(request)
    request.generated = [1, 2, 3, 4, 5]
    request.emitted = 5
    journal.progress(request)
    journal.close()
    records = [json.loads(line) for line in path.read_text().splitlines()]
    deltas = [r for r in records if r["event"] == "progress"]
    assert [r["generated_from"] for r in deltas] == [0, 3]
    assert deltas[1]["generated"] == [4, 5]  # only the new tokens
    entries = replay_journal(path)
    assert entries[0]["generated"] == [1, 2, 3, 4, 5]
    assert entries[0]["emitted"] == 5
    # a gap (dropped record): later delta starts past the known prefix
    with open(path, "a") as f:
        f.write(json.dumps({
            "event": "progress", "id": "a", "generated_from": 9,
            "generated": [9], "emitted": 10,
        }) + "\n")
    gapped = replay_journal(path)
    assert gapped[0]["generated"] == [1, 2, 3, 4, 5]
    assert gapped[0]["emitted"] == 5


def test_journal_done_retires_on_next_step(tiny_model, tmp_path):
    """`done` records are deferred one step (the terminal chunk must reach
    the emitter first): right after a completion the journal still
    replays the request; after the next step it is retired."""
    model, variables = tiny_model
    engine = _engine(model, variables, max_batch=1)
    engine.attach_journal(RequestJournal(tmp_path / "j.jsonl"))
    events = list(engine.submit("r", [3, 17], max_new_tokens=2))
    while not any(e["type"] == "done" for e in events):
        events += engine.step()
    # terminal built and returned, not yet retired: a death here would
    # re-deliver (duplicate), never lose
    assert [e["id"] for e in replay_journal(tmp_path / "j.jsonl")] == ["r"]
    engine.step()  # the caller has emitted by now: retire
    assert replay_journal(tmp_path / "j.jsonl") == []


def test_journal_progress_skips_unchanged_state(tmp_path):
    path = tmp_path / "journal.jsonl"
    journal = RequestJournal(path)
    request = _request("a", prompt_len=2, n=8)
    journal.accepted(request)
    request.generated = [3]
    request.emitted = 1
    journal.progress(request)
    journal.progress(request)  # unchanged: no record
    request.generated = [3, 4]
    journal.progress(request)
    journal.close()
    records = [json.loads(line) for line in path.read_text().splitlines()]
    assert [r["event"] for r in records] == ["accepted", "progress", "progress"]


# ------------------------------------------------------------ chaos faults


def test_chaos_serve_env_overlay(monkeypatch):
    from llm_training_tpu.resilience.chaos import ChaosConfig, config_from_env

    monkeypatch.setenv("LLMT_CHAOS_SERVE_STALL_STEP", "4")
    monkeypatch.setenv("LLMT_CHAOS_SERVE_SIGTERM_STEP", "6")
    monkeypatch.setenv("LLMT_CHAOS_SERVE_MALFORMED_FLOOD", "3")
    config = config_from_env(ChaosConfig())
    assert config.serve_stall_step == 4
    assert config.serve_sigterm_step == 6
    assert config.serve_malformed_flood == 3
    assert config.any_active()


def test_chaos_serve_faults_fire_once_first_attempt_only(monkeypatch):
    from llm_training_tpu.resilience.chaos import Chaos, ChaosConfig
    from llm_training_tpu.resilience.elastic import ATTEMPT_ENV

    class _Registry:
        def counter(self, name):
            class _C:
                def inc(self):
                    pass
            return _C()

    slept = []
    chaos = Chaos(
        ChaosConfig(serve_stall_step=3, serve_malformed_flood=2),
        registry=_Registry(),
    )
    monkeypatch.setenv(ATTEMPT_ENV, "1")
    assert not chaos.maybe_serve_stall(2, sleep=slept.append)
    assert chaos.maybe_serve_stall(3, sleep=slept.append)
    assert slept == [3600.0]
    assert not chaos.maybe_serve_stall(3, sleep=slept.append)  # once
    assert len(chaos.serve_malformed_lines()) == 2
    # attempt 2 (the supervised relaunch): every serve fault is inert
    monkeypatch.setenv(ATTEMPT_ENV, "2")
    relaunch = Chaos(
        ChaosConfig(serve_stall_step=3, serve_sigterm_step=3,
                    serve_malformed_flood=2),
        registry=_Registry(),
    )
    assert not relaunch.maybe_serve_stall(3, sleep=slept.append)
    assert not relaunch.maybe_serve_sigterm_mid_stream(3)
    assert relaunch.serve_malformed_lines() == []
    assert slept == [3600.0]


def test_chaos_serve_sigterm_delivers_signal(monkeypatch):
    from llm_training_tpu.resilience.chaos import Chaos, ChaosConfig
    from llm_training_tpu.resilience.elastic import ATTEMPT_ENV

    monkeypatch.setenv(ATTEMPT_ENV, "1")
    received = []
    previous = signal.signal(signal.SIGTERM, lambda s, f: received.append(s))
    try:
        chaos = Chaos(ChaosConfig(serve_sigterm_step=2))
        assert not chaos.maybe_serve_sigterm_mid_stream(1)
        assert chaos.maybe_serve_sigterm_mid_stream(2)
        assert received == [signal.SIGTERM]
        assert not chaos.maybe_serve_sigterm_mid_stream(2)  # once
    finally:
        signal.signal(signal.SIGTERM, previous)


# ------------------------------------------------- report + trace summary


def test_report_serving_resilience_counters():
    from llm_training_tpu.telemetry.report import _serving_section

    text = "\n".join(_serving_section({
        "serve/requests_completed": 3, "serve/tokens_per_sec": 10.0,
        "serve/shed_total": 2, "serve/deadline_total": 1,
        "serve/weights_generation": 4, "serve/replayed_requests": 5,
    }))
    assert "resilience: 2 shed (overloaded), 1 deadline-expired, " \
        "weights generation 4, 5 replayed from journal" in text
    # absent -> the whole resilience line is omitted (older telemetry)
    legacy = "\n".join(_serving_section({
        "serve/requests_completed": 3, "serve/tokens_per_sec": 10.0,
    }))
    assert "resilience:" not in legacy
    # zero-valued counters are as good as absent
    zeros = "\n".join(_serving_section({
        "serve/requests_completed": 3, "serve/shed_total": 0,
        "serve/deadline_total": 0, "serve/weights_generation": 0,
        "serve/replayed_requests": 0,
    }))
    assert "resilience:" not in zeros


def test_summarize_trace_counts_terminal_reasons():
    from llm_training_tpu.telemetry.trace import summarize_trace

    events = [
        {"ts": 1.0, "ph": "i", "cat": "serve", "name": "done",
         "args": {"request_id": "a", "stop_reason": "max_tokens",
                  "n_tokens": 4}},
        {"ts": 2.0, "ph": "i", "cat": "serve", "name": "done",
         "args": {"request_id": "b", "stop_reason": "deadline"}},
        {"ts": 3.0, "ph": "i", "cat": "serve", "name": "done",
         "args": {"request_id": "c", "stop_reason": "overloaded"}},
    ]
    summary = summarize_trace(events)
    assert summary["terminal_reasons"] == {
        "max_tokens": 1, "deadline": 1, "overloaded": 1,
    }
    assert summary["requests_completed"] == 1


# --------------------------------------------------- engine (jax) tests


@pytest.fixture(scope="module")
def tiny_model():
    import jax
    import numpy as np

    from llm_training_tpu.models import Llama, LlamaConfig

    model = Llama(LlamaConfig(**TINY))
    variables = model.init(jax.random.key(0), np.zeros((1, 4), np.int32))
    return model, variables


def _engine(model, variables, **overrides):
    from llm_training_tpu.serve import ServeConfig, ServingEngine

    config = ServeConfig(**{
        "max_batch": 2, "max_model_len": 48, "block_size": 8,
        "prefill_chunk": 4, "eos_token_id": None, **overrides,
    })
    return ServingEngine(model, variables, config)


def test_drain_then_replay_is_token_identical_exactly_once(tiny_model, tmp_path):
    """THE tier-1 drain pin (mirrored end-to-end by the precommit
    serve-drain gate): mid-stream drain journals the remainder without
    emitting terminals and frees every pool block; a second engine's
    replay continues token-identically to an uninterrupted run, streams no
    token twice, and emits exactly one terminal per request."""
    model, variables = tiny_model
    prompts = {"a": [3, 17, 42], "b": [5, 9]}
    n = 10
    baseline = _engine(model, variables)
    base_done = {
        e["id"]: e for e in baseline.run([
            {"id": rid, "prompt": p, "max_new_tokens": n}
            for rid, p in prompts.items()
        ]) if e["type"] == "done"
    }

    journal_path = tmp_path / "serve-journal.jsonl"
    first = _engine(model, variables)
    first.attach_journal(RequestJournal(journal_path))
    events = []
    for rid, prompt in prompts.items():
        events += first.submit(rid, prompt, max_new_tokens=n)
    while sum(e["type"] == "token" for e in events) < 6:
        events += first.step()
    first.drain()
    first.journal.close()
    assert first.allocator.blocks_in_use == 0, "drain leaked pool blocks"
    assert not [e for e in events if e["type"] == "done"], \
        "drain emitted a terminal it does not own"

    streamed = {
        rid: [e["token"] for e in events
              if e["type"] == "token" and e["id"] == rid]
        for rid in prompts
    }
    entries = replay_journal(journal_path)
    assert {e["id"] for e in entries} == set(prompts)
    second = _engine(model, variables)
    replay_events = []
    for entry in entries:
        replay_events += second.submit_resumed(entry)
    while not second.scheduler.idle:
        replay_events += second.step()
    done = {e["id"]: e for e in replay_events if e["type"] == "done"}
    assert second.replayed_requests == 2
    for rid in prompts:
        total = streamed[rid] + [
            e["token"] for e in replay_events
            if e["type"] == "token" and e["id"] == rid
        ]
        assert total == base_done[rid]["tokens"], f"{rid} diverged across drain"
        assert done[rid]["tokens"] == base_done[rid]["tokens"]
        assert sum(
            e["type"] == "done" and e["id"] == rid for e in replay_events
        ) == 1
    assert second.allocator.blocks_in_use == 0
    stats = second.stats()
    assert stats["serve/replayed_requests"] == 2


def test_reload_weights_mid_stream_token_identity_and_tags(tiny_model):
    """Acceptance: reload_weights on a live engine neither drops nor
    corrupts the in-flight stream — post-reload tokens equal a FRESH
    engine on the new weights fed prompt + tokens-so-far (the fold-in
    point), and every chunk carries the generation it was decoded
    under."""
    import jax
    import numpy as np

    from llm_training_tpu.models import Llama, LlamaConfig

    model, v1 = tiny_model
    v2 = Llama(LlamaConfig(**TINY)).init(
        jax.random.key(1), np.zeros((1, 4), np.int32)
    )
    prompt, n = [3, 17, 42], 10
    engine = _engine(model, v1)
    events = list(engine.submit("r", prompt, max_new_tokens=n))
    while sum(e["type"] == "token" for e in events) < 4:
        events += engine.step()
    pre_reload = [e["token"] for e in events if e["type"] == "token"]
    assert engine.reload_weights(v2) == 1
    while not engine.scheduler.idle:
        events += engine.step()
    token_events = [e for e in events if e["type"] == "token"]
    done = [e for e in events if e["type"] == "done"][0]

    fresh = _engine(model, v2)
    fresh_done = [
        e for e in fresh.run([{
            "id": "f", "prompt": prompt + pre_reload,
            "max_new_tokens": n - len(pre_reload),
        }]) if e["type"] == "done"
    ][0]
    post_reload = [e["token"] for e in token_events[len(pre_reload):]]
    assert post_reload == fresh_done["tokens"], "reload corrupted the stream"
    generations = [e["generation"] for e in token_events]
    assert generations == [0] * len(pre_reload) + [1] * len(post_reload)
    assert done["generation"] == 1
    assert done["tokens"] == pre_reload + post_reload  # nothing dropped
    stats = engine.stats()
    assert stats["serve/weights_generation"] == 1


def test_reload_weights_rejects_mismatched_variables(tiny_model):
    import jax
    import numpy as np

    from llm_training_tpu.models import Llama, LlamaConfig

    model, variables = tiny_model
    engine = _engine(model, variables)
    other = Llama(LlamaConfig(**{**TINY, "num_hidden_layers": 1})).init(
        jax.random.key(2), np.zeros((1, 4), np.int32)
    )
    with pytest.raises(ValueError, match="reload_weights"):
        engine.reload_weights(other)
    assert engine.weights_generation == 0


def test_engine_deadline_mid_decode_emits_done(tiny_model):
    """A deadline blowing mid-decode surfaces as a 'deadline' done chunk
    on the next step, with the partial tokens and the generation tag."""
    model, variables = tiny_model
    engine = _engine(model, variables)
    events = list(engine.submit(
        "r", [3, 17, 42], max_new_tokens=10, deadline_ms=60_000.0
    ))
    while sum(e["type"] == "token" for e in events) < 2:
        events += engine.step()
    request = next(iter(engine.scheduler.running.values()))
    request.deadline_s = time.perf_counter() - 1.0  # blow it mid-decode
    events += engine.step()
    done = [e for e in events if e["type"] == "done"]
    assert len(done) == 1 and done[0]["stop_reason"] == "deadline"
    assert done[0]["n_tokens"] >= 2 and "generation" in done[0]
    assert engine.scheduler.idle and engine.allocator.blocks_in_use == 0
    assert engine.stats()["serve/deadline_total"] == 1


def test_engine_sheds_over_bounded_queue(tiny_model):
    """max_queue=0 with one decode slot: the queued second request is shed
    with an honest 'overloaded' terminal while the first streams to
    completion."""
    model, variables = tiny_model
    engine = _engine(model, variables, max_batch=1, max_queue=0)
    events = list(engine.submit("first", [3, 17], max_new_tokens=4))
    events += list(engine.submit("second", [5, 9], max_new_tokens=4))
    while not engine.scheduler.idle:
        events += engine.step()
    done = {e["id"]: e for e in events if e["type"] == "done"}
    assert done["second"]["stop_reason"] == "overloaded"
    assert done["first"]["stop_reason"] == "max_tokens"
    assert len(done["first"]["tokens"]) == 4
    assert engine.stats()["serve/shed_total"] == 1
