"""GPipe pipeline parallelism (models/pipeline.py, 'pipe' mesh axis).

No reference analogue — cchou0519/LLM-Training has no PP (SURVEY.md §2.8);
these tests hold the feature to the same standard as the other axes: exact
math parity against the scanned stack (microbatching must not change any
token's computation), gradient parity through the full tick loop, and a
real sharded train step composing pipe x fsdp x tensor on the virtual mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_training_tpu.data import DummyDataModule, DummyDataModuleConfig
from llm_training_tpu.lms import CLM, CLMConfig, ModelProvider
from llm_training_tpu.optim import OptimConfig
from llm_training_tpu.parallel import MeshConfig
from llm_training_tpu.trainer import Trainer, TrainerConfig

KW = dict(
    vocab_size=128,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=4,
    num_attention_heads=4,
    num_key_value_heads=2,
    max_position_embeddings=64,
    compute_dtype="float32",
    param_dtype="float32",
)


def _models():
    from llm_training_tpu.models.llama.config import LlamaConfig
    from llm_training_tpu.models.llama.model import Llama

    return (
        Llama(LlamaConfig(**KW)),
        Llama(LlamaConfig(**KW, pipeline_stages=2, pipeline_microbatches=4)),
    )


def _inputs(batch=8, seq=16):
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(1, KW["vocab_size"], (batch, seq)), jnp.int32)
    # two packed documents per row: the segment ids must travel with their
    # microbatch through the shift buffers
    seg = jnp.asarray(np.repeat([[1, 2]], batch, 0).repeat(seq // 2, 1), jnp.int32)
    pos = jnp.asarray(np.tile(np.r_[np.arange(seq // 2), np.arange(seq // 2)], (batch, 1)), jnp.int32)
    return ids, seg, pos


def _scan_params_from_pipeline(p_p, num_layers):
    """[S, L/S, ...] pipeline stacks -> the scan path's [L, ...] layout."""
    stack = jax.tree.map(
        lambda v: v.reshape((num_layers,) + v.shape[2:]),
        p_p["pipeline"]["ticks"]["layers"],
    )
    p_s = {k: v for k, v in p_p.items() if k != "pipeline"}
    p_s["layers"] = stack
    return p_s


def test_pipeline_matches_scan_forward_and_grad(devices):
    import flax.linen as nn

    m_s, m_p = _models()
    ids, seg, pos = _inputs()
    p_p = nn.meta.unbox(m_p.init(jax.random.key(0), ids, seg, pos))["params"]
    p_s = _scan_params_from_pipeline(p_p, KW["num_hidden_layers"])

    out_s = m_s.apply({"params": p_s}, ids, seg, pos)
    out_p = m_p.apply({"params": p_p}, ids, seg, pos)
    np.testing.assert_allclose(
        np.asarray(out_p.logits), np.asarray(out_s.logits), atol=1e-5
    )

    def loss_fn(params, model):
        out = model.apply({"params": params}, ids, seg, pos)
        logp = jax.nn.log_softmax(out.logits.astype(jnp.float32))
        return jnp.mean(logp[..., 0] ** 2)

    g_s = jax.grad(loss_fn)(p_s, m_s)
    g_p = jax.grad(loss_fn)(p_p, m_p)
    g_p_as_scan = _scan_params_from_pipeline(g_p, KW["num_hidden_layers"])
    for a, b in zip(jax.tree.leaves(g_s), jax.tree.leaves(g_p_as_scan)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=1e-5)


def test_pipeline_microbatch_counts_agree(devices):
    """M = S, M = 2S and a non-divisor M (gcd fallback) must all produce
    identical logits — the schedule never changes the math."""
    import flax.linen as nn

    from llm_training_tpu.models.llama.config import LlamaConfig
    from llm_training_tpu.models.llama.model import Llama

    ids, seg, pos = _inputs()
    ref = None
    for micro in (2, 4, 3):  # 3 does not divide batch 8 -> gcd degrades to 1
        m = Llama(LlamaConfig(**KW, pipeline_stages=2, pipeline_microbatches=micro))
        p = nn.meta.unbox(m.init(jax.random.key(0), ids, seg, pos))["params"]
        out = np.asarray(m.apply({"params": p}, ids, seg, pos).logits)
        if ref is None:
            ref = out
        else:
            np.testing.assert_allclose(out, ref, atol=1e-5)


@pytest.mark.parametrize(
    "mesh_kw",
    [
        dict(pipeline_parallel_size=2, fsdp_size=2, tensor_parallel_size=2),
        # sequence-parallel activations inside each stage (plain SP, not
        # ring): the shift buffers carry an act_seq axis sharded over
        # 'sequence' and GSPMD composes it with the stage shift
        dict(pipeline_parallel_size=2, fsdp_size=2, sequence_parallel_size=2),
    ],
    ids=["pipe-fsdp-tp", "pipe-fsdp-sp"],
)
def test_pipeline_sharded_train_step(devices, mesh_kw):
    """One real train step on a pipe-composed mesh: executes, loss finite,
    parameters actually move."""
    objective = CLM(
        CLMConfig(
            model=ModelProvider(
                model_class="llm_training_tpu.models.Llama",
                model_kwargs=dict(
                    KW, pipeline_stages=2, pipeline_microbatches=4,
                    enable_gradient_checkpointing=True,
                ),
            ),
            optim=OptimConfig(learning_rate=3e-3, warmup_steps=1),
        )
    )
    dm = DummyDataModule(
        DummyDataModuleConfig(batch_size=8, max_length=32, num_samples=16, vocab_size=128)
    )
    metrics = {}

    class Rec:
        def on_step_end(self, trainer, step, m):
            metrics.update(m)

    trainer = Trainer(
        TrainerConfig(
            max_steps=2, log_every_n_steps=1,
            mesh=MeshConfig(**mesh_kw),
        ),
        callbacks=[Rec()],
    )
    state = trainer.fit(objective, dm)
    assert int(jax.device_get(state.step)) == 2
    assert np.isfinite(metrics["loss"]) and metrics["loss"] > 3.0
    assert np.isfinite(metrics["grad_norm"]) and metrics["grad_norm"] > 0.0
    # the layer stacks really shard their stage axis over 'pipe'
    stack_leaf = jax.tree.leaves(state.params["params"]["pipeline"])[0]
    spec = stack_leaf.sharding.spec
    assert spec[0] == "pipe", spec


@pytest.mark.slow
def test_pipeline_loss_decreases(devices):
    objective = CLM(
        CLMConfig(
            model=ModelProvider(
                model_class="llm_training_tpu.models.Llama",
                model_kwargs=dict(
                    KW, pipeline_stages=2, pipeline_microbatches=4
                ),
            ),
            optim=OptimConfig(learning_rate=1e-2, warmup_steps=5),
        )
    )
    dm = DummyDataModule(
        DummyDataModuleConfig(batch_size=8, max_length=64, num_samples=64, vocab_size=128)
    )
    losses = []

    class Rec:
        def on_step_end(self, trainer, step, m):
            losses.append(float(m["loss"]))

    trainer = Trainer(
        TrainerConfig(
            max_steps=40, log_every_n_steps=1,
            mesh=MeshConfig(
                pipeline_parallel_size=2, fsdp_size=2, tensor_parallel_size=2
            ),
        ),
        callbacks=[Rec()],
    )
    trainer.fit(objective, dm)
    assert losses[0] > 4.0  # ~ln(128)
    assert min(losses[-5:]) < losses[0] - 0.3


MOE_KW = dict(
    KW, num_experts=4, num_experts_per_tok=2, moe_intermediate_size=32,
)


def test_pipeline_moe_matches_scan(devices):
    """MoE under PP: logits AND the pooled router-stat aux loss must match
    the scanned stack exactly — the pipeline pools sel_frac/mean_prob over
    the real (tick, stage) cells only (each equal-sized microbatch's mean
    averages to the full-batch mean) and masks bubble-tick junk stats."""
    import flax.linen as nn

    from llm_training_tpu.models.llama.config import LlamaConfig
    from llm_training_tpu.models.llama.model import Llama

    m_s = Llama(LlamaConfig(**MOE_KW))
    m_p = Llama(LlamaConfig(**MOE_KW, pipeline_stages=2, pipeline_microbatches=4))
    ids, seg, pos = _inputs()
    # concentrate padding in the FIRST microbatch (rows 0-1): the router
    # stats normalize per dispatch by valid-token count, so equal-weight
    # pooling would diverge here — the token-share weighting must not
    seg = seg.at[:2, 10:].set(0)
    p_p = nn.meta.unbox(m_p.init(jax.random.key(0), ids, seg, pos))["params"]
    p_s = _scan_params_from_pipeline(p_p, KW["num_hidden_layers"])

    out_s = m_s.apply({"params": p_s}, ids, seg, pos)
    out_p = m_p.apply({"params": p_p}, ids, seg, pos)
    np.testing.assert_allclose(
        np.asarray(out_p.logits), np.asarray(out_s.logits), atol=1e-5
    )
    np.testing.assert_allclose(
        float(out_p.aux_loss), float(out_s.aux_loss), rtol=1e-6
    )

    def loss_fn(params, model):
        out = model.apply({"params": params}, ids, seg, pos)
        logp = jax.nn.log_softmax(out.logits.astype(jnp.float32))
        return jnp.mean(logp[..., 0] ** 2) + 0.01 * out.aux_loss

    g_s = jax.grad(loss_fn)(p_s, m_s)
    g_p = _scan_params_from_pipeline(
        jax.grad(loss_fn)(p_p, m_p), KW["num_hidden_layers"]
    )
    for a, b in zip(jax.tree.leaves(g_s), jax.tree.leaves(g_p)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=2e-5)


def test_pipeline_moe_rejects_expert_parallel(devices):
    objective = CLM(
        CLMConfig(
            model=ModelProvider(
                model_class="llm_training_tpu.models.Llama",
                model_kwargs=dict(
                    MOE_KW, pipeline_stages=2, pipeline_microbatches=2
                ),
            ),
            optim=OptimConfig(learning_rate=1e-3),
        )
    )
    dm = DummyDataModule(
        DummyDataModuleConfig(batch_size=8, max_length=32, num_samples=16, vocab_size=128)
    )
    trainer = Trainer(
        TrainerConfig(
            max_steps=1,
            mesh=MeshConfig(
                pipeline_parallel_size=2, expert_parallel_size=2,
                tensor_parallel_size=2,
            ),
        )
    )
    with pytest.raises(ValueError, match="expert_parallel"):
        trainer.fit(objective, dm)


def test_pipeline_hf_round_trip(devices):
    """HF checkpoint -> pipeline layout -> HF: loading a converted HF state
    dict into the [S, L/S] layout must give logits parity with the scan
    model loaded from the same dict, and exporting back must reproduce the
    HF tensors bitwise (the PP layout is a pure reshape)."""
    import flax.linen as nn

    from llm_training_tpu.models.hf_io import _pp_as_scan, load_pretrained_params
    from llm_training_tpu.models.llama.hf_conversion import params_to_hf

    m_s, m_p = _models()
    ids, seg, pos = _inputs()
    p_p = nn.meta.unbox(m_p.init(jax.random.key(0), ids, seg, pos))["params"]

    # export the pipelined params to an HF state dict (exercises _pp_as_scan)
    sd = params_to_hf(_pp_as_scan({"params": p_p}, m_p.config), m_p.config)
    # load it back into BOTH layouts
    p_s2 = load_pretrained_params(m_s.config, sd)["params"]
    p_p2 = load_pretrained_params(m_p.config, sd)["params"]

    out_s = m_s.apply({"params": p_s2}, ids, seg, pos)
    out_p = m_p.apply({"params": p_p2}, ids, seg, pos)
    np.testing.assert_allclose(
        np.asarray(out_p.logits), np.asarray(out_s.logits), atol=1e-5
    )
    # pipeline leaves really are the stage layout
    leaf = jax.tree.leaves(p_p2["pipeline"]["ticks"]["layers"])[0]
    assert leaf.shape[:2] == (2, 2)
    # and exporting the re-loaded pipeline params reproduces the dict bitwise
    sd2 = params_to_hf(_pp_as_scan({"params": p_p2}, m_p.config), m_p.config)
    assert set(sd2) == set(sd)
    for k in sd:
        np.testing.assert_array_equal(np.asarray(sd2[k]), np.asarray(sd[k]))


def test_mesh_model_stage_mismatch_raises(devices):
    """pipe mesh axis without matching model stages would silently
    replicate all work across the axis — must fail loudly at fit."""
    objective = CLM(
        CLMConfig(
            model=ModelProvider(
                model_class="llm_training_tpu.models.Llama",
                model_kwargs=dict(KW),  # pipeline_stages defaults to 1
            ),
            optim=OptimConfig(learning_rate=1e-3),
        )
    )
    dm = DummyDataModule(
        DummyDataModuleConfig(batch_size=8, max_length=32, num_samples=16, vocab_size=128)
    )
    trainer = Trainer(
        TrainerConfig(
            max_steps=1,
            mesh=MeshConfig(pipeline_parallel_size=2, fsdp_size=2, tensor_parallel_size=2),
        )
    )
    with pytest.raises(ValueError, match="pipeline_stages"):
        trainer.fit(objective, dm)


@pytest.mark.slow
def test_pipeline_save_resume_matches_uninterrupted(devices, tmp_path):
    """Checkpoint/resume determinism holds for the [S, L/S] layout on the
    pipe mesh: a run interrupted at step 3 and resumed matches the
    uninterrupted run's losses exactly (orbax restores the stage-sharded
    stacks + the data stream position)."""
    from llm_training_tpu.trainer.checkpoint import CheckpointConfig, Checkpointer

    def objective():
        return CLM(
            CLMConfig(
                model=ModelProvider(
                    model_class="llm_training_tpu.models.Llama",
                    model_kwargs=dict(
                        KW, pipeline_stages=2, pipeline_microbatches=4
                    ),
                ),
                optim=OptimConfig(
                    learning_rate=1e-3, warmup_steps=2, lr_scheduler="constant"
                ),
            )
        )

    def data():
        return DummyDataModule(
            DummyDataModuleConfig(
                batch_size=8, max_length=32, num_samples=48, vocab_size=128
            )
        )

    mesh = MeshConfig(pipeline_parallel_size=2, fsdp_size=2, tensor_parallel_size=2)

    class Rec:
        def __init__(self):
            self.losses = {}

        def on_step_end(self, trainer, step, metrics):
            self.losses[step] = float(metrics["loss"])

    rec_full = Rec()
    Trainer(
        TrainerConfig(max_steps=6, log_every_n_steps=1, mesh=mesh),
        callbacks=[rec_full],
        checkpointer=Checkpointer(
            CheckpointConfig(dirpath=str(tmp_path / "full"), async_save=False)
        ),
    ).fit(objective(), data())

    ckpt_dir = str(tmp_path / "resume")
    rec_a, rec_b = Rec(), Rec()
    Trainer(
        TrainerConfig(
            max_steps=3, log_every_n_steps=1, checkpoint_every_n_steps=3, mesh=mesh
        ),
        callbacks=[rec_a],
        checkpointer=Checkpointer(CheckpointConfig(dirpath=ckpt_dir, async_save=False)),
    ).fit(objective(), data())
    Trainer(
        TrainerConfig(max_steps=6, log_every_n_steps=1, mesh=mesh),
        callbacks=[rec_b],
        checkpointer=Checkpointer(CheckpointConfig(dirpath=ckpt_dir, async_save=False)),
    ).fit(objective(), data())

    # the resumed run must actually RESUME at step 4 (a silent restore
    # miss would rerun 1-6 deterministically and pass the loss checks)
    assert set(rec_b.losses) == {4, 5, 6}
    for step in range(1, 4):  # checkpointing must not perturb the live run
        np.testing.assert_allclose(
            rec_a.losses[step], rec_full.losses[step], rtol=1e-6,
            err_msg=f"interrupted step {step}",
        )
    for step in range(4, 7):
        np.testing.assert_allclose(
            rec_b.losses[step], rec_full.losses[step], rtol=1e-6,
            err_msg=f"step {step}",
        )


def test_pipeline_config_validation():
    from llm_training_tpu.models.llama.config import LlamaConfig

    with pytest.raises(ValueError, match="split evenly"):
        LlamaConfig(**{**KW, "num_hidden_layers": 5}, pipeline_stages=2)
    with pytest.raises(ValueError, match="scan_layers"):
        LlamaConfig(**KW, pipeline_stages=2, scan_layers=False)
    with pytest.raises(ValueError, match="rotary"):
        LlamaConfig(**KW, pipeline_stages=2, position_embedding_type="learned")
    with pytest.raises(ValueError, match="ring_attention"):
        LlamaConfig(**KW, pipeline_stages=2, ring_attention=True)
