"""Block-quantized offloaded optimizer state (offload_state_dtype).

The r5 chip measurement showed the fp32 offload round trip is host-link
bandwidth-bound (overlap buys nothing: 0.3035 vs 0.313 MFU), so the int8
codec exists to shrink the bytes 4x. These tests pin the codec's numerics
(including the safety property that quantized nu never underestimates),
the field-name -> codec routing, the trained-step behaviour vs exact fp32
state, and the checkpoint round trip of the compressed layout. Memory-kind
placement itself needs the chip; everything here runs with device kinds
(same discipline as test_blocked_offload_update_matches_whole_tree).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from llm_training_tpu.optim.quantized_state import (
    QuantArray,
    decode_state,
    dequantize_array,
    encode_state,
    quantize_array,
)


def test_sym_codec_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((4, 1024)) * rng.uniform(1e-4, 10), jnp.float32)
    qa = quantize_array(x, "sym", 256)
    assert qa.q.dtype == jnp.int8 and qa.q.shape == x.shape
    assert qa.scale.shape == (4, 4)
    err = np.abs(np.asarray(dequantize_array(qa) - x))
    # error bound: half a quantization step per block
    bound = np.repeat(np.asarray(qa.scale), 256, axis=-1) * 0.5 + 1e-12
    assert (err <= bound).all()


def test_sqrt_codec_never_underestimates():
    """Ceil rounding: dequantized nu >= true nu (minus the 5e-4-step
    idempotency slack — negligible) — a real underestimate would blow up
    Adam's per-coordinate step by sqrt(nu)/eps."""
    rng = np.random.default_rng(1)
    # high dynamic range within a block: the dangerous case
    x = jnp.asarray(
        10.0 ** rng.uniform(-12, 0, (8, 512)), jnp.float32
    )
    qa = quantize_array(x, "sqrt", 256)
    assert qa.q.dtype == jnp.uint8
    deq = np.asarray(dequantize_array(qa))
    # bound: sqrt may be under by <= 5e-4 grid steps -> nu under by
    # <= ~2*sqrt(nu)*5e-4*scale; assert in sqrt space where it is linear
    r, dr = np.sqrt(np.asarray(x)), np.sqrt(deq)
    step = np.repeat(np.asarray(qa.scale), 256, axis=-1)
    assert (dr >= r - 1e-3 * step).all()
    # and NEVER to zero for nonzero input — dequantized nu = 0 would blow
    # up the Adam step by sqrt(nu_true)/eps
    assert (deq[np.asarray(x) > 0] > 0).all()
    # and it is still a useful approximation for values near the block max
    big = np.asarray(x) > np.asarray(x).max(-1, keepdims=True) * 0.1
    rel = np.abs(deq - np.asarray(x)) / np.asarray(x)
    assert rel[big].max() < 0.05


def test_codecs_are_grid_idempotent():
    """decode -> re-encode must be a FIXED POINT for both codecs: the
    serialized offload path re-encodes the (unchanged) state every
    accumulation micro-step, so any per-cycle drift would ratchet nu
    upward across training."""
    rng = np.random.default_rng(3)
    for kind, data in (
        ("sym", rng.standard_normal((4, 1024)) * 3.0),
        ("sqrt", 10.0 ** rng.uniform(-10, 2, (4, 1024))),
    ):
        x = jnp.asarray(data, jnp.float32)
        qa = quantize_array(x, kind, 256)
        for cycle in range(10):
            qa2 = quantize_array(dequantize_array(qa), kind, 256)
            np.testing.assert_array_equal(
                np.asarray(qa2.q), np.asarray(qa.q),
                err_msg=f"{kind} codes drifted at cycle {cycle}",
            )
            np.testing.assert_array_equal(
                np.asarray(qa2.scale), np.asarray(qa.scale),
                err_msg=f"{kind} scales drifted at cycle {cycle}",
            )
            qa = qa2


def test_encode_state_routes_fields_and_skips_ineligible():
    params = {
        "w": jnp.zeros((4, 512)),
        "v": jnp.zeros((2, 512)),  # param NAMED v — must not get sqrt codec
        "tiny": jnp.zeros((7,)),  # last axis % block != 0 — stays fp32
    }
    tx = optax.adamw(1e-3)
    state = tx.init(params)
    # make mu signed and nu non-negative, as in real training
    state = jax.tree.map(lambda x: x, state)
    enc = encode_state(state, block=256)
    leaves = jax.tree_util.tree_flatten_with_path(
        enc, is_leaf=lambda x: isinstance(x, QuantArray)
    )[0]
    kinds = {}
    for path, leaf in leaves:
        names = [
            str(
                getattr(p, "name", None)
                or getattr(p, "key", None)
                or getattr(p, "idx", None)
            )
            for p in path
        ]
        if isinstance(leaf, QuantArray):
            kinds["/".join(names)] = leaf.kind
    assert kinds["0/mu/w"] == "sym"
    assert kinds["0/mu/v"] == "sym"  # param name must not flip the codec
    assert kinds["0/nu/w"] == "sqrt"
    assert kinds["0/nu/v"] == "sqrt"
    assert not any(k.endswith("/tiny") for k in kinds)  # ineligible skipped
    # decode restores the exact original structure and dtypes
    dec = decode_state(enc)
    assert jax.tree.structure(dec) == jax.tree.structure(state)
    assert all(leaf.dtype == jnp.float32 for leaf in jax.tree.leaves(dec)
               if hasattr(leaf, "ndim") and leaf.ndim >= 1)


def test_adam_with_quantized_state_tracks_exact(devices):
    """Run Adam 20 steps on a quadratic with the state quantized between
    every step (the offload storage pattern); trajectory must track the
    exact-state run closely and reach a comparably low loss."""
    tx = optax.adam(5e-2)
    target = jnp.asarray(np.random.default_rng(2).standard_normal((4, 512)), jnp.float32)

    def loss_fn(p):
        return jnp.mean((p - target) ** 2)

    p_a = p_b = jnp.zeros_like(target)
    st_a = st_b = tx.init(p_a)
    for _ in range(20):
        g_a = jax.grad(loss_fn)(p_a)
        upd, st_a = tx.update(g_a, st_a, p_a)
        p_a = optax.apply_updates(p_a, upd)

        g_b = jax.grad(loss_fn)(p_b)
        upd, st_fp = tx.update(g_b, decode_state(encode_state(st_b, 256)), p_b)
        st_b = st_fp
        p_b = optax.apply_updates(p_b, upd)

    la, lb = float(loss_fn(p_a)), float(loss_fn(p_b))
    assert lb < float(loss_fn(jnp.zeros_like(target))) * 0.2  # actually optimizes
    assert lb < la * 1.5 + 1e-4  # and not much worse than exact Adam
    # per-coordinate trajectories may drift (ceil-rounded nu shrinks steps
    # on small-nu coordinates by design); the aggregate path must track
    diff = np.abs(np.asarray(p_b) - np.asarray(p_a))
    travel = np.abs(np.asarray(p_a)).mean()  # ~1.0: distance optimized so far
    assert diff.mean() < 0.05 * travel + 1e-3
    cos = float(
        (p_a.ravel() @ p_b.ravel())
        / (jnp.linalg.norm(p_a.ravel()) * jnp.linalg.norm(p_b.ravel()))
    )
    assert cos > 0.995


def _offloadable_trainer(offload_dtype, block=16, max_steps=6):
    from tests.test_trainer import _make

    trainer, objective, dm = _make(max_steps=max_steps)
    trainer.config = trainer.config.model_copy(
        update={
            "offload_optimizer_state": True,
            "offload_state_dtype": offload_dtype,
            "offload_quant_block": block,
        }
    )
    return trainer, objective, dm


@pytest.mark.parametrize("offload_dtype", ["bfloat16", "int8"])
def test_blocked_compressed_step_matches_fp32(devices, offload_dtype):
    """One blocked-offload step with compressed state storage vs the fp32
    blocked step: params must agree tightly (fresh state: mu/nu leave the
    first step nearly unquantized), opt state must hold the compressed
    dtypes. Device memory kinds — the codec math is placement-agnostic."""
    import flax.linen as nn

    from llm_training_tpu.optim.builder import build_optimizer
    from llm_training_tpu.parallel.mesh import MeshConfig, build_mesh
    from llm_training_tpu.trainer.state import TrainState
    from llm_training_tpu.trainer.trainer import LOGICAL_AXIS_RULES

    results = {}
    for dtype in ("float32", offload_dtype):
        trainer, objective, dm = _offloadable_trainer(dtype)
        trainer.mesh = build_mesh(MeshConfig(fsdp_size=4, tensor_parallel_size=2))
        dm.setup()
        batch = next(dm.train_batches(start_step=0))
        clip_free = objective.config.optim.model_copy(update={"grad_clip_norm": None})
        tx, _ = build_optimizer(clip_free, num_total_steps=4)
        with trainer.mesh, nn.logical_axis_rules(LOGICAL_AXIS_RULES):
            trainer._blocked_offload = True
            trainer._clip_norm = objective.config.optim.grad_clip_norm
            params = nn.meta.unbox(objective.init_params(jax.random.key(0), batch))
            blocks = trainer._opt_init(tx, params)
            state = TrainState.create(params, blocks, jax.random.key(7))
            dev = jax.sharding.NamedSharding(trainer.mesh, jax.sharding.PartitionSpec())
            opt_sh = tuple(jax.tree.map(lambda _: dev, blk) for blk in blocks)
            step = trainer._build_blocked_offload_step(objective, tx, opt_sh, opt_sh)
            new_state, metrics = jax.jit(step)(state, batch)
        results[dtype] = (new_state, metrics)

    new_fp, m_fp = results["float32"]
    new_q, m_q = results[offload_dtype]
    np.testing.assert_allclose(
        float(m_fp["grad_norm"]), float(m_q["grad_norm"]), rtol=1e-6
    )
    for a, b in zip(jax.tree.leaves(new_fp.params), jax.tree.leaves(new_q.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4, rtol=1e-3)
    # storage really is compressed
    q_dtypes = {
        leaf.q.dtype
        for blk in new_q.opt_state
        for leaf in jax.tree.leaves(
            blk, is_leaf=lambda x: isinstance(x, QuantArray)
        )
        if isinstance(leaf, QuantArray)
    }
    if offload_dtype == "int8":
        assert q_dtypes == {jnp.dtype(jnp.int8), jnp.dtype(jnp.uint8)}
    else:
        bf_leaves = [
            leaf for blk in new_q.opt_state for leaf in jax.tree.leaves(blk)
            if hasattr(leaf, "dtype") and leaf.ndim >= 1
        ]
        assert all(leaf.dtype == jnp.bfloat16 for leaf in bf_leaves)


def test_compressed_dtype_requires_offload(devices):
    trainer, objective, dm = _offloadable_trainer("int8")
    trainer.config = trainer.config.model_copy(
        update={"offload_optimizer_state": False}
    )
    with pytest.raises(ValueError, match="offload_optimizer_state"):
        trainer._build_tx(objective)


def _acc_grad_leaves(opt_state):
    return [
        leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(
            opt_state, is_leaf=lambda x: isinstance(x, QuantArray)
        )[0]
        if any(getattr(p, "name", None) == "acc_grads" for p in path)
    ]


def test_serialized_int8_with_accumulation_matches_fp32(devices):
    """Grad accumulation forces the serialized (whole-tree) layout; the
    codec's field whitelist must leave MultiSteps' acc_grads exact while
    still compressing mu/nu, and the accumulated update must track the
    fp32-state run. Runs the REAL serialized train_step (device memory
    kinds) for two micro-steps = one optimizer step."""
    import flax.linen as nn

    from llm_training_tpu.parallel.mesh import MeshConfig, build_mesh
    from llm_training_tpu.trainer.state import TrainState
    from llm_training_tpu.trainer.trainer import LOGICAL_AXIS_RULES

    runs = {}
    for dtype in ("float32", "int8"):
        trainer, objective, dm = _offloadable_trainer(dtype)
        trainer.config = trainer.config.model_copy(
            update={"accumulate_grad_batches": 2}
        )
        trainer.mesh = build_mesh(MeshConfig(fsdp_size=4, tensor_parallel_size=2))
        dm.setup()
        it = dm.train_batches(start_step=0)
        b1, b2 = next(it), next(it)
        with trainer.mesh, nn.logical_axis_rules(LOGICAL_AXIS_RULES):
            tx, _ = trainer._build_tx(objective)
            assert not trainer._blocked_offload  # accumulation -> serialized
            params = nn.meta.unbox(objective.init_params(jax.random.key(0), b1))
            opt_state = trainer._opt_init(tx, params)
            state = TrainState.create(params, opt_state, jax.random.key(7))
            dev = jax.sharding.NamedSharding(
                trainer.mesh, jax.sharding.PartitionSpec()
            )
            trainer.state_shardings = jax.tree.map(
                lambda _: dev, jax.eval_shape(lambda: state)
            )
            step = jax.jit(trainer._build_step(objective, tx))
            s1, _ = step(state, b1)
            s2, _ = step(s1, b2)
        runs[dtype] = (opt_state, s1, s2)

    init_q, s1_q, s2_q = runs["int8"]
    init_f, s1_f, s2_f = runs["float32"]
    # mu/nu compressed, accumulators exact fp32 arrays
    flat_q = jax.tree_util.tree_flatten_with_path(
        init_q, is_leaf=lambda x: isinstance(x, QuantArray)
    )[0]
    assert any(isinstance(leaf, QuantArray) for _, leaf in flat_q)
    accs = _acc_grad_leaves(init_q)
    assert accs and all(
        not isinstance(a, QuantArray) and a.dtype == jnp.float32 for a in accs
    )
    # after micro-step 1 (accumulate only) the accumulators match BITWISE
    for a, b in zip(_acc_grad_leaves(s1_q.opt_state), _acc_grad_leaves(s1_f.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # after micro-step 2 the optimizer fired: params track the fp32 run
    for a, b in zip(jax.tree.leaves(s2_q.params), jax.tree.leaves(s2_f.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4, rtol=1e-3)


def test_checkpoint_roundtrip_int8_state(tmp_path, devices):
    """Orbax save/restore of the compressed per-leaf state layout: the
    QuantArray pytree (int8 q + fp32 scale, static kind/block) must survive
    a round trip against the abstract target."""
    import flax.linen as nn

    from llm_training_tpu.optim.builder import build_optimizer
    from llm_training_tpu.parallel.mesh import MeshConfig, build_mesh
    from llm_training_tpu.trainer.checkpoint import CheckpointConfig, Checkpointer
    from llm_training_tpu.trainer.state import TrainState
    from llm_training_tpu.trainer.trainer import LOGICAL_AXIS_RULES

    trainer, objective, dm = _offloadable_trainer("int8")
    trainer.mesh = build_mesh(MeshConfig(fsdp_size=4, tensor_parallel_size=2))
    dm.setup()
    batch = next(dm.train_batches(start_step=0))
    tx, _ = build_optimizer(objective.config.optim, num_total_steps=4)
    trainer._blocked_offload = True
    with trainer.mesh, nn.logical_axis_rules(LOGICAL_AXIS_RULES):
        params = nn.meta.unbox(objective.init_params(jax.random.key(0), batch))
        state = TrainState.create(
            params, trainer._opt_init(tx, params), jax.random.key(7)
        )
        abstract = jax.eval_shape(lambda: state)
        shardings = jax.tree.map(
            lambda _: jax.sharding.NamedSharding(
                trainer.mesh, jax.sharding.PartitionSpec()
            ),
            abstract,
        )

    ckpt = Checkpointer(CheckpointConfig(dirpath=str(tmp_path), max_to_keep=1))
    ckpt.save(0, state, {})
    ckpt.wait()
    restored, _ = ckpt.maybe_restore(abstract, shardings, 0)
    ckpt.close()

    for a, b in zip(
        jax.tree.leaves(state.opt_state, is_leaf=lambda x: isinstance(x, QuantArray)),
        jax.tree.leaves(restored.opt_state, is_leaf=lambda x: isinstance(x, QuantArray)),
    ):
        if isinstance(a, QuantArray):
            assert a.kind == b.kind and a.block == b.block
            np.testing.assert_array_equal(np.asarray(a.q), np.asarray(b.q))
            np.testing.assert_array_equal(np.asarray(a.scale), np.asarray(b.scale))
        else:
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
