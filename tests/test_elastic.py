"""Elastic training: topology planner, DP-resize data continuity, chaos
device shrink, supervisor capacity renegotiation, goodput-per-dollar, and
the == Elastic == report section (docs/resilience.md#elastic).

Everything here is host-side and fast (tier-1); the end-to-end
kill→shrink→resume proof is `scripts/crash_resume_smoke.py` leg 8 and the
slow fit test at the bottom.
"""

import json

import numpy as np
import pytest

from llm_training_tpu.resilience.elastic import (
    ATTEMPT_ENV,
    CHAOS_DEVICES_ENV,
    CHIP_PRICE_ENV,
    SUPERVISOR_LOG_ENV,
    ElasticConfig,
    ElasticTopologyError,
    chaos_device_limit,
    check_data_continuity,
    log_segment_topology,
    plan_topology,
    resolve_chip_price,
    segment_attempt,
)

SIZES_1 = {"pipe": 1, "fsdp": 1, "expert": 1, "tensor": 1, "sequence": 1}


# ---------------------------------------------------------------- planner


def test_planner_scales_data_down_on_shrink():
    plan = plan_topology(
        4, {"data": -1, **SIZES_1}, checkpoint_mesh={"data": 8, **SIZES_1}
    )
    assert plan.axis_sizes["data"] == 4
    assert plan.device_count == 4 and plan.spare_devices == 0
    assert "scaled data 8->4" in plan.decision
    assert plan.source == "checkpoint"


def test_planner_scales_data_up_on_growth():
    plan = plan_topology(
        8, {"data": 2, **SIZES_1}, checkpoint_mesh={"data": 2, **SIZES_1}
    )
    assert plan.axis_sizes["data"] == 8
    assert "scaled data 2->8" in plan.decision


def test_planner_keeps_model_axes_fixed():
    ckpt = {"data": 4, "pipe": 1, "fsdp": 2, "expert": 1, "tensor": 2, "sequence": 1}
    plan = plan_topology(8, {"data": -1, **{k: v for k, v in ckpt.items() if k != "data"}},
                         checkpoint_mesh=ckpt)
    assert plan.axis_sizes["fsdp"] == 2 and plan.axis_sizes["tensor"] == 2
    assert plan.axis_sizes["data"] == 2  # 8 // (2*2)


def test_planner_refuses_when_model_axes_cannot_fit():
    with pytest.raises(ElasticTopologyError, match="scales only"):
        plan_topology(
            4, {"data": 1, "fsdp": -1}, checkpoint_mesh={"data": 1, "fsdp": 8}
        )


def test_planner_refuses_config_model_axis_conflict():
    # the user explicitly changed a model axis mid-run: elastic never
    # reshards model axes behind their back
    with pytest.raises(ElasticTopologyError, match="keeps the model axes"):
        plan_topology(8, {"data": 1, "fsdp": 4}, checkpoint_mesh={"fsdp": 8})


def test_planner_spare_devices_are_dropped_not_fatal():
    plan = plan_topology(
        7, {"data": -1, "fsdp": 2}, checkpoint_mesh={"data": 4, "fsdp": 2}
    )
    assert plan.axis_sizes == {"data": 3, "pipe": 1, "fsdp": 2, "expert": 1,
                               "tensor": 1, "sequence": 1}
    assert plan.device_count == 6 and plan.spare_devices == 1
    assert "spare" in plan.decision


def test_planner_fresh_start_fills_auto_model_axis():
    # the default MeshConfig posture (fsdp=-1) on a fresh start resolves
    # classically; later resumes pin the filled degree via the checkpoint
    plan = plan_topology(8, {"data": 1, "fsdp": -1})
    assert plan.axis_sizes["fsdp"] == 8 and plan.axis_sizes["data"] == 1
    assert plan.source == "config"


def test_planner_fresh_start_scales_explicit_data_to_fit():
    plan = plan_topology(8, {"data": 2, **SIZES_1})
    assert plan.axis_sizes["data"] == 8
    assert "scaled data 2->8" in plan.decision


def test_planner_no_devices_refuses():
    with pytest.raises(ElasticTopologyError):
        plan_topology(0, {"data": -1, **SIZES_1})


def test_planner_refuses_two_auto_axes():
    # the classic resolver rejects this config; elastic must not widen the
    # set of accepted-but-misinterpreted meshes
    with pytest.raises(ElasticTopologyError, match="at most one"):
        plan_topology(8, {"data": -1, "fsdp": -1})


def test_planner_clamps_data_to_divide_the_global_batch():
    # 6 chips come back for a batch of 8: data=6 would die in fit's
    # divisibility check every relaunch — plan data=4 (spare 2) instead
    plan = plan_topology(
        6, {"data": -1, **SIZES_1},
        checkpoint_mesh={"data": 8, **SIZES_1}, global_batch_size=8,
    )
    assert plan.axis_sizes["data"] == 4
    assert plan.device_count == 4 and plan.spare_devices == 2
    assert "divide the global batch" in plan.decision


def test_planner_leaves_data_alone_when_no_degree_divides():
    # batch % fsdp != 0: no data degree can fix it — fit's own check must
    # report the real problem, so the planner doesn't mask it
    plan = plan_topology(
        4, {"data": -1, "fsdp": 3},
        checkpoint_mesh={"data": 1, "fsdp": 3}, global_batch_size=8,
    )
    assert plan.axis_sizes["data"] == 1  # 4 // 3, unclamped


def test_verify_restored_topology_guards_model_axes():
    from llm_training_tpu.resilience.elastic import verify_restored_topology

    plan = plan_topology(
        4, {"data": -1, **SIZES_1}, checkpoint_mesh={"data": 8, **SIZES_1}
    )
    # data-axis change is THE elastic change; pre-elastic meta passes
    verify_restored_topology(plan, {"mesh": {"data": 8, **SIZES_1}})
    verify_restored_topology(plan, None)
    verify_restored_topology(plan, {})
    # a model-axis difference (planner fell back to config, restore then
    # succeeded) must refuse instead of resharding silently
    with pytest.raises(ElasticTopologyError, match="model axes differ"):
        verify_restored_topology(
            plan, {"mesh": {"data": 8, **{**SIZES_1, "fsdp": 2}}}
        )


# ------------------------------------------------------------ chaos shrink


def test_chaos_device_limit_single_value(monkeypatch):
    monkeypatch.setenv(CHAOS_DEVICES_ENV, "5")
    assert chaos_device_limit(1) == 5
    assert chaos_device_limit(7) == 5  # single value clamps every launch


def test_chaos_device_limit_schedule_indexed_by_attempt(monkeypatch):
    monkeypatch.setenv(CHAOS_DEVICES_ENV, "8,4")
    assert chaos_device_limit(1) == 8
    assert chaos_device_limit(2) == 4
    assert chaos_device_limit(9) == 4  # clamps to the last entry
    monkeypatch.setenv(ATTEMPT_ENV, "2")
    assert chaos_device_limit() == 4  # attempt defaults to the env


def test_chaos_device_limit_absent_and_malformed(monkeypatch):
    monkeypatch.delenv(CHAOS_DEVICES_ENV, raising=False)
    assert chaos_device_limit() is None
    monkeypatch.setenv(CHAOS_DEVICES_ENV, "lots")
    assert chaos_device_limit() is None  # typo must not kill a run
    monkeypatch.setenv(CHAOS_DEVICES_ENV, "0")
    assert chaos_device_limit() is None


def test_segment_attempt_defaults_and_parses(monkeypatch):
    monkeypatch.delenv(ATTEMPT_ENV, raising=False)
    assert segment_attempt() == 1
    monkeypatch.setenv(ATTEMPT_ENV, "3")
    assert segment_attempt() == 3
    monkeypatch.setenv(ATTEMPT_ENV, "junk")
    assert segment_attempt() == 1


# ------------------------------------------------------------ chip price


def test_chip_price_env_overrides_config(monkeypatch):
    monkeypatch.setenv(CHIP_PRICE_ENV, "4.2")
    assert resolve_chip_price(ElasticConfig(price_per_chip_hour=1.0)) == 4.2
    monkeypatch.delenv(CHIP_PRICE_ENV)
    assert resolve_chip_price(ElasticConfig(price_per_chip_hour=1.0)) == 1.0
    assert resolve_chip_price(ElasticConfig()) is None
    assert resolve_chip_price(None) is None
    monkeypatch.setenv(CHIP_PRICE_ENV, "not-a-price")
    assert resolve_chip_price(None) is None


# ------------------------------------------------------ data continuity


def test_check_data_continuity_accepts_dp_resize():
    # same global batch, different replica stride: the stream is identical
    check_data_continuity(
        {"global_batch_size": 8, "replica_stride": 1}, 8, elastic=True
    )
    check_data_continuity(None, 8, elastic=True)
    check_data_continuity({}, 8, elastic=True)


def test_check_data_continuity_refuses_global_batch_change():
    with pytest.raises(ValueError, match="GLOBAL batch size 16 -> 8"):
        check_data_continuity({"global_batch_size": 16}, 8, elastic=True)
    # legacy (elastic off): warn, don't raise — historical behavior
    check_data_continuity({"global_batch_size": 16}, 8, elastic=False)


def _datamodule(batch_size=8):
    from llm_training_tpu.data import DummyDataModule, DummyDataModuleConfig

    dm = DummyDataModule(DummyDataModuleConfig(
        batch_size=batch_size, max_length=8, num_samples=48, vocab_size=64,
    ))
    dm.setup()
    return dm


@pytest.mark.parametrize("dp_size", [1, 2, 4])
def test_replica_streams_concatenate_to_the_global_stream(dp_size):
    """The elastic data contract (ISSUE 8 satellite): the concatenated
    global sample stream is IDENTICAL for dp=1/2/4 given the same seed,
    cursor, and skip windows — the (seed, step) → sample mapping never
    depends on the replica count."""
    from llm_training_tpu.resilience import DataSkipList

    steps, start = 10, 3  # cursor: resume mid-epoch
    windows = DataSkipList(windows=[(4, 2)], reserve=2)

    def take(stream, n):
        return [next(stream) for _ in range(n)]

    reference = take(_datamodule().train_batches(
        start_step=start, skip_list=DataSkipList(windows=[(4, 2)], reserve=2)
    ), steps)
    replicas = [
        take(_datamodule().replica_batches(
            rank, dp_size, start_step=start,
            skip_list=DataSkipList(windows=[(4, 2)], reserve=2),
        ), steps)
        for rank in range(dp_size)
    ]
    for step in range(steps):
        for key in reference[step]:
            rebuilt = np.concatenate(
                [replicas[rank][step][key] for rank in range(dp_size)], axis=0
            )
            np.testing.assert_array_equal(
                rebuilt, reference[step][key],
                err_msg=f"step {step} key {key} dp={dp_size}",
            )


def test_replica_batches_validates_rank_and_divisibility():
    dm = _datamodule(batch_size=8)
    with pytest.raises(ValueError, match="not divisible"):
        next(dm.replica_batches(0, 3))
    with pytest.raises(ValueError, match="outside"):
        next(dm.replica_batches(4, 4))
    with pytest.raises(ValueError, match="dp_size"):
        next(dm.replica_batches(0, 0))


# ---------------------------------------------------------- ledger cost


def test_ledger_cost_basis_gauges():
    from llm_training_tpu.telemetry import GoodputLedger

    t = [0.0]
    ledger = GoodputLedger(clock=lambda: t[0])
    ledger.start()
    with ledger.measure("step_compute"):
        t[0] += 30.0
    t[0] += 30.0  # other
    base = ledger.summary()
    assert "goodput/chip_count" not in base  # schema unchanged w/o basis

    ledger.set_cost_basis(4, price_per_chip_hour=3.0)
    summary = ledger.summary()
    # 60s total on 4 chips at $3/chip-hour
    assert summary["goodput/chip_count"] == 4.0
    assert summary["goodput/chip_hours"] == pytest.approx(60 * 4 / 3600)
    assert summary["goodput/productive_chip_hours"] == pytest.approx(30 * 4 / 3600)
    assert summary["goodput/cost_dollars"] == pytest.approx(0.2)
    # productive chip-hours per dollar = goodput_pct/100/price = 0.5/3
    assert summary["goodput/goodput_per_dollar"] == pytest.approx(0.5 / 3.0)

    ledger.set_cost_basis(4, price_per_chip_hour=None)
    summary = ledger.summary()
    assert "goodput/chip_hours" in summary
    assert "goodput/cost_dollars" not in summary  # no invented prices


# -------------------------------------------------------- audit trail


def test_log_segment_topology_appends_to_env_path(tmp_path, monkeypatch):
    log = tmp_path / "supervisor.jsonl"
    monkeypatch.setenv(SUPERVISOR_LOG_ENV, str(log))
    monkeypatch.setenv(ATTEMPT_ENV, "2")
    record = log_segment_topology(
        {"data": 4, "fsdp": 1}, 4, decision="scaled data 8->4",
        price_per_chip_hour=3.0,
    )
    assert record["attempt"] == 2 and record["device_count"] == 4
    events = [json.loads(line) for line in log.read_text().splitlines()]
    assert events[-1]["event"] == "segment_topology"
    assert events[-1]["mesh"] == {"data": 4, "fsdp": 1}
    assert events[-1]["decision"] == "scaled data 8->4"


def test_log_segment_topology_noop_without_target(monkeypatch):
    monkeypatch.delenv(SUPERVISOR_LOG_ENV, raising=False)
    assert log_segment_topology({"data": 1}, 1) is None


# ---------------------------------------------------------- supervisor


def _supervisor(probe_values, min_devices=2, max_wait=100.0, rcs=(75, 0),
                log=None):
    from llm_training_tpu.resilience import Supervisor, SupervisorConfig

    codes = list(rcs)
    probes = list(probe_values)
    launches = []
    clock = [0.0]

    def run_child(argv):
        launches.append(dict(sup.env))
        return codes.pop(0)

    sup = Supervisor(
        ["fit"],
        SupervisorConfig(
            max_restarts=5, backoff_base_s=0.0, min_devices=min_devices,
            probe_backoff_s=1.0, probe_max_wait_s=max_wait,
            log_path=str(log) if log else None,
        ),
        run_child=run_child,
        probe=lambda: probes.pop(0) if probes else None,
        sleep=lambda s: clock.__setitem__(0, clock[0] + s),
        clock=lambda: clock[0],
    )
    return sup, launches


def test_supervisor_waits_for_capacity_then_relaunches(tmp_path):
    log = tmp_path / "sup.jsonl"
    sup, launches = _supervisor([1, 1, 4], log=log)
    assert sup.run() == 0
    assert len(launches) == 2
    events = [json.loads(line) for line in log.read_text().splitlines()]
    kinds = [e["event"] for e in events]
    assert kinds.count("probe") == 3
    assert kinds.count("capacity_wait") == 2
    # children (and probes) see the attempt they are
    assert launches[0][ATTEMPT_ENV] == "1"
    assert launches[1][ATTEMPT_ENV] == "2"
    # children learn where the churn log lives
    assert launches[0][SUPERVISOR_LOG_ENV].endswith("sup.jsonl")


def test_supervisor_log_env_overrides_stale_parent_value(tmp_path, monkeypatch):
    # children belong to THIS supervisor: an inherited LLMT_SUPERVISOR_LOG
    # from an outer wrapper or previous run must not win over --log
    monkeypatch.setenv(SUPERVISOR_LOG_ENV, "/stale/other-run.jsonl")
    log = tmp_path / "mine.jsonl"
    sup, launches = _supervisor([4], log=log)
    assert sup.run() == 0
    assert launches[0][SUPERVISOR_LOG_ENV] == str(log.absolute())


def test_supervisor_gives_up_below_min_devices():
    sup, launches = _supervisor([1, 1, 1, 1], max_wait=2.5, rcs=(75, 0))
    assert sup.run() == 75  # the child's code propagates
    assert len(launches) == 1
    giveups = [e for e in sup.events if e["event"] == "giveup"]
    assert giveups and "insufficient devices" in giveups[0]["reason"]


def test_supervisor_unknowable_probe_proceeds():
    # a broken probe must not park the relaunch forever
    sup, launches = _supervisor([None], rcs=(75, 0))
    assert sup.run() == 0
    assert len(launches) == 2


def test_supervisor_no_min_devices_skips_probing():
    probes = []
    sup, launches = _supervisor(probes, min_devices=None, rcs=(75, 0))
    assert sup.run() == 0
    assert len(launches) == 2  # never consumed a probe


# -------------------------------------------------------------- report


def _write_run(tmp_path, telemetry_records, supervisor_events=None,
               supervisor_text=None):
    run_dir = tmp_path / "run"
    run_dir.mkdir(exist_ok=True)
    (run_dir / "metrics.jsonl").write_text(
        "\n".join(json.dumps({"step": i + 1, "loss": 1.0,
                              "steps_per_sec": 1.0})
                  for i in range(len(telemetry_records))) + "\n"
    )
    (run_dir / "telemetry.jsonl").write_text(
        "\n".join(json.dumps(r) for r in telemetry_records) + "\n"
    )
    if supervisor_text is not None:
        (run_dir / "supervisor.jsonl").write_text(supervisor_text)
    elif supervisor_events is not None:
        (run_dir / "supervisor.jsonl").write_text(
            "\n".join(json.dumps(e) for e in supervisor_events) + "\n"
        )
    return run_dir


def _segment_record(step, segment, chips, cost=None, productive=None):
    record = {
        "step": step,
        "elastic/segment": segment,
        "goodput/total_s": 10.0 * step,
        "goodput/chip_count": float(chips),
        "goodput/chip_hours": 10.0 * step * chips / 3600,
    }
    if cost is not None:
        record["goodput/cost_dollars"] = cost
        record["goodput/productive_chip_hours"] = productive
    return record


def test_report_elastic_section_renders_segments_and_gpd(tmp_path):
    from llm_training_tpu.telemetry.report import render_report

    events = [
        {"event": "launch", "attempt": 1},
        {"event": "segment_topology", "attempt": 1, "device_count": 8,
         "mesh": {"data": 8, "fsdp": 1}, "decision": "fresh start: data=8"},
        {"event": "exit", "attempt": 1, "rc": -9, "signal": "SIGKILL",
         "runtime_s": 12.0},
        {"event": "segment_topology", "attempt": 2, "device_count": 4,
         "mesh": {"data": 4, "fsdp": 1}, "decision": "scaled data 8->4"},
        {"event": "exit", "attempt": 2, "rc": 0, "runtime_s": 20.0},
    ]
    records = [
        _segment_record(2, 1, 8, cost=0.1, productive=0.02),
        _segment_record(6, 2, 4, cost=0.2, productive=0.08),
    ]
    out = render_report(_write_run(tmp_path, records, events))
    assert "== Elastic ==" in out
    assert "segment #1:" in out and "8 device(s)" in out
    assert "segment #2:" in out and "scaled data 8->4" in out
    assert "exit SIGKILL" in out
    assert "cost: $0.3" in out
    # (0.02 + 0.08) / (0.1 + 0.2)
    assert "goodput-per-dollar: 0.333" in out


def test_report_elastic_degrades_without_price(tmp_path):
    from llm_training_tpu.telemetry.report import render_report

    events = [
        {"event": "segment_topology", "attempt": 1, "device_count": 8,
         "mesh": {"data": 8}},
        {"event": "segment_topology", "attempt": 2, "device_count": 4,
         "mesh": {"data": 4}},
    ]
    records = [_segment_record(2, 1, 8), _segment_record(6, 2, 4)]
    out = render_report(_write_run(tmp_path, records, events))
    assert "== Elastic ==" in out
    assert "cost: unavailable" in out and "LLMT_CHIP_PRICE_PER_HOUR" in out


def test_report_elastic_degrades_on_malformed_supervisor_log(tmp_path):
    from llm_training_tpu.telemetry.report import render_report

    records = [_segment_record(2, 1, 8), _segment_record(6, 2, 4)]
    out = render_report(_write_run(
        tmp_path, records, supervisor_text="{torn json\nnot a record\n"
    ))
    assert "== Elastic ==" in out
    assert "unreadable" in out  # one honest line, no crash


def test_report_elastic_omitted_for_plain_runs(tmp_path):
    from llm_training_tpu.telemetry.report import render_report

    # one segment, no price, no supervisor log: nothing elastic to say
    records = [_segment_record(4, 1, 8)]
    out = render_report(_write_run(tmp_path, records))
    assert "== Elastic ==" not in out


def test_report_elastic_aggregates_without_supervisor_log(tmp_path):
    from llm_training_tpu.telemetry.report import render_report

    records = [
        _segment_record(2, 1, 8, cost=0.1, productive=0.02),
        _segment_record(6, 2, 4, cost=0.2, productive=0.08),
    ]
    out = render_report(_write_run(tmp_path, records))
    assert "== Elastic ==" in out
    assert "goodput-per-dollar" in out


def test_report_elastic_ignores_empty_supervisor_log(tmp_path):
    from llm_training_tpu.telemetry.report import render_report

    # a zero-byte log (supervisor killed before its first event) says
    # nothing: a plain run must stay section-free, not claim corruption
    records = [_segment_record(4, 1, 8)]
    out = render_report(_write_run(tmp_path, records, supervisor_text=""))
    assert "== Elastic ==" not in out


def test_report_elastic_survives_non_numeric_event_fields(tmp_path):
    from llm_training_tpu.telemetry.report import render_report

    # valid-JSON but foreign/corrupt fields must degrade per field, not
    # crash the whole report
    events = [
        {"event": "segment_topology", "attempt": 1, "device_count": "junk",
         "mesh": {"data": "x", "fsdp": None}},
        {"event": "segment_topology", "attempt": 2, "device_count": 4,
         "mesh": {"data": 4, "fsdp": 1}},
    ]
    records = [_segment_record(2, 1, 8), _segment_record(6, 2, 4)]
    out = render_report(_write_run(tmp_path, records, events))
    assert "== Elastic ==" in out
    assert "segment #2:" in out and "4 device(s)" in out


# ------------------------------------------------------------- config


def test_elastic_config_parses_in_trainer_config():
    from llm_training_tpu.trainer import TrainerConfig

    config = TrainerConfig(
        resilience={"elastic": {"price_per_chip_hour": 4.2}}
    )
    assert config.resilience.elastic.price_per_chip_hour == 4.2
    assert TrainerConfig().resilience.elastic is None


def test_mesh_config_axis_sizes_roundtrip():
    from llm_training_tpu.parallel import MeshConfig

    sizes = {"data": 4, "pipe": 1, "fsdp": 2, "expert": 1, "tensor": 1,
             "sequence": 1}
    assert MeshConfig.from_axis_sizes(sizes).axis_sizes() == sizes


# ----------------------------------------------------------- slow e2e


@pytest.mark.slow
def test_elastic_resume_onto_fewer_devices(devices, tmp_path):
    """A fit checkpointed on 8 devices resumes under elastic onto 4: the
    planner scales data 8->4, the restored stream continues, and the
    post-resume losses match a clean same-seed run on the 4-device
    topology (rtol mirrors test_cross_topology_resume: steps 1-3 ran on
    different meshes, so fp32 reduction-order noise compounds into the
    resumed state — 5e-5 is ~50x that floor, far below any planner bug)."""
    from llm_training_tpu.data import DummyDataModule, DummyDataModuleConfig
    from llm_training_tpu.lms import CLM, CLMConfig, ModelProvider
    from llm_training_tpu.optim import OptimConfig
    from llm_training_tpu.parallel import MeshConfig
    from llm_training_tpu.trainer import Trainer, TrainerConfig
    from llm_training_tpu.trainer.checkpoint import CheckpointConfig, Checkpointer

    def objective():
        return CLM(CLMConfig(
            model=ModelProvider(
                model_class="llm_training_tpu.models.Llama",
                model_kwargs=dict(
                    vocab_size=128, hidden_size=32, intermediate_size=64,
                    num_hidden_layers=1, num_attention_heads=2,
                    num_key_value_heads=2, max_position_embeddings=64,
                    compute_dtype="float32",
                ),
            ),
            optim=OptimConfig(learning_rate=1e-3, warmup_steps=2,
                              lr_scheduler="constant"),
        ))

    def data():
        return DummyDataModule(DummyDataModuleConfig(
            batch_size=8, max_length=32, num_samples=64, vocab_size=128,
        ))

    class Rec:
        def __init__(self):
            self.losses = {}

        def on_step_end(self, trainer, step, metrics):
            self.losses[step] = float(metrics["loss"])

    mesh = MeshConfig(data_parallel_size=-1, fsdp_size=1)
    resilience = {"elastic": {"price_per_chip_hour": 3.0}}
    ckpt = str(tmp_path / "ck")

    t1 = Trainer(
        TrainerConfig(max_steps=3, log_every_n_steps=1,
                      checkpoint_every_n_steps=3, mesh=mesh,
                      resilience=resilience),
        checkpointer=Checkpointer(
            CheckpointConfig(dirpath=ckpt, async_save=False)),
    )
    t1.fit(objective(), data())
    assert t1.topology_plan.axis_sizes["data"] == 8

    rec_resumed = Rec()
    t2 = Trainer(
        TrainerConfig(max_steps=6, log_every_n_steps=1, mesh=mesh,
                      resilience=resilience),
        callbacks=[rec_resumed], devices=devices[:4],
        checkpointer=Checkpointer(
            CheckpointConfig(dirpath=ckpt, async_save=False)),
    )
    import jax

    state = t2.fit(objective(), data())
    assert t2.topology_plan.axis_sizes["data"] == 4
    assert "scaled data 8->4" in t2.topology_plan.decision
    assert jax.tree.leaves(state.params)[0].sharding.mesh.shape["data"] == 4
    # cost accounting rode the segment's telemetry
    assert t2.ledger.summary()["goodput/chip_count"] == 4.0
    assert t2.ledger.summary()["goodput/cost_dollars"] > 0

    rec_clean = Rec()
    t3 = Trainer(
        TrainerConfig(max_steps=6, log_every_n_steps=1, mesh=mesh,
                      resilience=resilience),
        callbacks=[rec_clean], devices=devices[:4],
    )
    t3.fit(objective(), data())
    for step in range(4, 7):
        np.testing.assert_allclose(
            rec_resumed.losses[step], rec_clean.losses[step], rtol=5e-5,
            err_msg=f"step {step}",
        )
