"""Model-health layer: param grouping, layer norms, MoE router health, the
EMA spike detector, NaN provenance, and the anomaly-dump path.

Unit tests run host-side math on synthetic trees; the slow tests drive a
real tiny MoE fit with `health.every_n_steps` set and assert the metrics
flow registry -> telemetry.jsonl -> `report` (the ISSUE 2 acceptance
criteria).
"""

import json
import math

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_training_tpu.models.base import RouterStats
from llm_training_tpu.telemetry import (
    EmaZScore,
    TelemetryRegistry,
    build_param_groups,
    layer_health_metrics,
    moe_router_health,
    offending_layers,
    top_layers,
)

# ------------------------------------------------------------ param groups


def _boxed_tree():
    """A miniature boxed abstract tree: one scanned stack (3 layers), one
    unscanned block, embeddings, and a final norm."""
    f32 = jnp.float32
    return {
        "params": {
            "embed_tokens": {"embedding": jax.ShapeDtypeStruct((16, 4), f32)},
            "layers": {
                "layer": {
                    "mlp": {
                        "kernel": nn.Partitioned(
                            jax.ShapeDtypeStruct((3, 4, 8), f32),
                            names=("layers", "embed", "mlp"),
                        )
                    }
                }
            },
            "layers_0": {
                "attn": {"kernel": jax.ShapeDtypeStruct((4, 4), f32)}
            },
            "norm": {"weight": jax.ShapeDtypeStruct((4,), f32)},
        }
    }


def _value_tree(scale=1.0):
    f32 = jnp.float32
    return {
        "params": {
            "embed_tokens": {"embedding": jnp.full((16, 4), scale, f32)},
            "layers": {
                "layer": {
                    "mlp": {
                        # layer i of the stack filled with (i+1)*scale so the
                        # per-index norms are distinguishable
                        "kernel": jnp.stack(
                            [jnp.full((4, 8), (i + 1) * scale, f32) for i in range(3)]
                        )
                    }
                }
            },
            "layers_0": {"attn": {"kernel": jnp.full((4, 4), scale, f32)}},
            "norm": {"weight": jnp.full((4,), scale, f32)},
        }
    }


def test_param_groups_classify_stacked_block_and_toplevel():
    groups = build_param_groups(_boxed_tree())
    by_leaf = {g[0]: g for g in groups.leaves}
    assert ("embed_tokens", None, None) in groups.leaves
    assert ("norm", None, None) in groups.leaves
    # unscanned layers_0 normalizes to a zero-padded block key
    assert ("layers_00", None, None) in groups.leaves
    # the scanned stack records its stacking axis + length
    assert by_leaf["layers"][1] == (0,) and by_leaf["layers"][2] == 3


def test_param_groups_pipeline_stages_enumerate_global_layers():
    """Under PP the stack carries ('stages', 'layers', ...): per-index keys
    must span stage-major global layer numbers, not conflate the same
    within-stage index across stages."""
    f32 = jnp.float32
    boxed = {
        "params": {
            "pipeline": {
                "ticks": {
                    "kernel": nn.Partitioned(
                        jax.ShapeDtypeStruct((2, 3, 4), f32),
                        names=("stages", "layers", "embed"),
                    )
                }
            }
        }
    }
    groups = build_param_groups(boxed)
    assert groups.leaves == [("pipeline", (0, 1), 6)]
    # layer (stage 1, idx 2) — global layer 5 — must land in _05 only
    value = jnp.zeros((2, 3, 4), f32).at[1, 2].set(2.0)
    tree = {"params": {"pipeline": {"ticks": {"kernel": value}}}}
    out = layer_health_metrics(groups, tree, tree, tree)
    assert float(out["health/grad_norm/pipeline_05"]) == pytest.approx(4.0)
    assert float(out["health/grad_norm/pipeline_04"]) == 0.0


def test_layer_health_metrics_values_and_keys():
    groups = build_param_groups(_boxed_tree())
    params = _value_tree(1.0)
    grads = _value_tree(2.0)
    updates = _value_tree(0.5)
    out = layer_health_metrics(groups, params, grads, updates)
    # scanned stack emits one key per layer index
    for i in range(3):
        assert f"health/grad_norm/layers_{i:02d}" in out
    # per-index norms: layer i kernel filled with 2(i+1) over 32 elements
    got = float(out["health/grad_norm/layers_01"])
    assert math.isclose(got, math.sqrt(32 * (2 * 2) ** 2), rel_tol=1e-5)
    # plain group: embedding grad = 2.0 over 64 elements
    got = float(out["health/grad_norm/embed_tokens"])
    assert math.isclose(got, math.sqrt(64 * 4.0), rel_tol=1e-5)
    # update ratio = update_norm / param_norm = 0.5 everywhere
    for key in out:
        if key.startswith("health/update_ratio/"):
            assert math.isclose(float(out[key]), 0.5, rel_tol=1e-4)


def test_layer_health_metrics_rejects_mismatched_plan():
    groups = build_param_groups(_boxed_tree())
    with pytest.raises(ValueError, match="param-group plan"):
        layer_health_metrics(groups, {"a": jnp.ones(3)}, {"a": jnp.ones(3)}, {"a": jnp.ones(3)})


def test_param_groups_from_real_model_match_unboxed_flatten():
    """The plan must index straight into the step's (unboxed) leaf order —
    build it from a real model's boxed eval_shape tree and check coverage."""
    from llm_training_tpu.models import Llama, LlamaConfig

    cfg = LlamaConfig(
        vocab_size=64, hidden_size=16, intermediate_size=32,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=32, compute_dtype="float32", scan_layers=True,
    )
    model = Llama(cfg)
    ids = jnp.ones((1, 8), jnp.int32)
    boxed = jax.eval_shape(lambda r: model.init(r, ids), jax.random.key(0))
    groups = build_param_groups(boxed)
    unboxed = nn.meta.unbox(boxed)
    assert len(groups) == len(jax.tree.leaves(unboxed))
    scanned = [g for g in groups.leaves if g[1] is not None]
    assert scanned and all(g[2] == 2 for g in scanned)


def test_param_groups_multi_model_trees_stay_disjoint():
    """DPO-style objectives nest two model trees (policy/ref): their groups
    must carry the subtree prefix — a shared 'layers' group mixing a
    stacked policy leaf with a plain ref leaf would broadcast garbage."""
    inner = _boxed_tree()["params"]
    boxed = {"policy": {"params": inner}, "ref": {"params": inner}}
    groups = build_param_groups(boxed)
    names = {g[0] for g in groups.leaves}
    assert "policy/layers" in names and "ref/layers" in names
    assert "policy/embed_tokens" in names and "ref/norm" in names
    assert "policy/layers_00" in names
    # every group is either all-stacked or all-plain (the metrics fn
    # enforces it; exercise with real values)
    params = {"policy": {"params": _value_tree(1.0)["params"]},
              "ref": {"params": _value_tree(1.0)["params"]}}
    out = layer_health_metrics(groups, params, params, params)
    assert "health/grad_norm/policy/layers_01" in out
    assert all(np.ndim(v) == 0 for v in jax.tree.leaves(out))


# ------------------------------------------------------------ moe health


def test_moe_router_health_balanced_vs_collapsed():
    sel = jnp.asarray([[0.5, 0.5, 0.5, 0.5], [2.0, 0.0, 0.0, 0.0]], jnp.float32)
    prob = jnp.asarray([[0.25] * 4, [1.0, 0.0, 0.0, 0.0]], jnp.float32)
    stats = RouterStats(
        sel_frac=sel, mean_prob=prob, dropped=jnp.float32(8.0), layer_ids=(0, 3)
    )
    out = moe_router_health(stats, n_tokens=16)
    # layer ids (not row indices) name the keys
    assert "health/moe/router_entropy/layer_03" in out
    assert math.isclose(float(out["health/moe/router_entropy/layer_00"]), 1.0, rel_tol=1e-5)
    assert float(out["health/moe/router_entropy/layer_03"]) < 0.01
    assert math.isclose(float(out["health/moe/max_expert_share/layer_03"]), 1.0, rel_tol=1e-5)
    # per-layer aux: balanced layer = E * sum(0.5 * 0.25) = 2.0 (= top_k)
    assert math.isclose(float(out["health/moe/aux_loss/layer_00"]), 2.0, rel_tol=1e-5)
    # dropped fraction: 8 dropped of sel.sum()*n_tokens = 4*16 = 64 rows
    assert math.isclose(float(out["health/moe/dropped_frac"]), 8.0 / 64.0, rel_tol=1e-5)
    # 4 experts <= cap: per-expert load keys present
    assert "health/moe/load_frac/expert_00" in out


def test_moe_router_health_caps_expert_cardinality():
    n_experts = 64
    sel = jnp.full((1, n_experts), 1.0 / n_experts, jnp.float32)
    stats = RouterStats(sel_frac=sel, mean_prob=sel, dropped=jnp.float32(0.0))
    out = moe_router_health(stats, n_tokens=4)
    assert not any(k.startswith("health/moe/load_frac/") for k in out)
    assert "health/moe/router_entropy/layer_00" in out


# ------------------------------------------------------------ spike detector


def test_ema_zscore_warmup_then_spike():
    det = EmaZScore(beta=0.9, warmup=5)
    for value in (1.0, 1.1, 0.9, 1.0, 1.05):
        assert det.score(value) is None
        det.update(value)
    assert abs(det.score(1.0)) < 1.0
    assert det.score(10.0) > 6.0
    # signed: a sharp IMPROVEMENT scores negative, never above a threshold
    assert det.score(0.1) < 0.0


def test_ema_zscore_ignores_non_finite_updates():
    det = EmaZScore(beta=0.9, warmup=2)
    det.update(1.0)
    det.update(float("nan"))
    assert det.count == 1
    det.update(1.0)
    assert det.score(float("inf")) == math.inf


# ------------------------------------------------------------ provenance


def test_offending_layers_picks_non_finite_grad_groups():
    health = {
        "health/grad_norm/layers_00": 1.0,
        "health/grad_norm/layers_01": float("nan"),
        "health/grad_norm/embed_tokens": float("inf"),
        "health/update_ratio/layers_01": float("nan"),  # not a grad key
    }
    assert offending_layers(health) == ["layers_01", "embed_tokens"]
    assert offending_layers(None) == []


def test_top_layers_ranks_update_ratio():
    health = {
        "health/update_ratio/layers_00": 0.1,
        "health/update_ratio/layers_01": 0.5,
        "health/update_ratio/norm": 0.3,
    }
    assert top_layers(health, k=2) == ["layers_01", "norm"]


# ------------------------------------------------------------ NanGuard


class _FakeTrainer:
    def __init__(self, tmp_path=None, last_health=None):
        self.should_stop = False
        self.abort_final_save = False
        self.telemetry = TelemetryRegistry()
        self.last_health = last_health
        self.callbacks = []
        self.checkpointer = None
        if tmp_path is not None:
            class _Logger:
                run_dir = tmp_path

            self.callbacks = [_Logger()]


def test_nan_guard_patience_window_resets_on_recovery():
    from llm_training_tpu.callbacks import NanGuard, NanGuardConfig, NonFiniteLossError

    guard = NanGuard(NanGuardConfig(patience=1))
    trainer = _FakeTrainer()
    guard.on_step_end(trainer, 1, {"loss": float("nan"), "grad_norm": 1.0})
    guard.on_step_end(trainer, 2, {"loss": 1.0, "grad_norm": 1.0})  # recovery
    guard.on_step_end(trainer, 3, {"loss": float("nan"), "grad_norm": 1.0})
    # streak restarted at 1 — still within patience; one more trips it
    with pytest.raises(NonFiniteLossError):
        guard.on_step_end(trainer, 4, {"loss": float("nan"), "grad_norm": 1.0})
    assert guard.non_finite_steps == 3
    # the registry counter mirrors the host counter (telemetry.jsonl parity)
    assert trainer.telemetry.snapshot()["nan_guard/non_finite_steps"] == 3.0


def test_nan_guard_stop_sets_abort_final_save():
    from llm_training_tpu.callbacks import NanGuard, NanGuardConfig

    guard = NanGuard(NanGuardConfig(patience=0, action="stop"))
    trainer = _FakeTrainer()
    guard.on_step_end(trainer, 1, {"loss": float("nan"), "grad_norm": 1.0})
    assert trainer.should_stop is True
    assert trainer.abort_final_save is True


def test_nan_guard_names_layers_and_dumps(tmp_path):
    from llm_training_tpu.callbacks import NanGuard, NanGuardConfig, NonFiniteLossError

    trainer = _FakeTrainer(
        tmp_path=tmp_path,
        last_health={
            "health/grad_norm/layers_02": float("nan"),
            "health/grad_norm/embed_tokens": 0.5,
        },
    )
    guard = NanGuard(NanGuardConfig(patience=0))
    with pytest.raises(NonFiniteLossError, match="layers_02"):
        guard.on_step_end(trainer, 7, {"loss": float("nan"), "grad_norm": 2.0})
    dump = json.loads((tmp_path / "anomaly-7.json").read_text())
    assert dump["reason"] == "non_finite"
    assert dump["offending_layers"] == ["layers_02"]
    assert dump["metrics"]["loss"] == "nan"
    assert dump["health"]["health/grad_norm/layers_02"] == "nan"


def test_nan_guard_skips_dump_without_run_dir():
    from llm_training_tpu.callbacks import NanGuard, NanGuardConfig, NonFiniteLossError

    guard = NanGuard(NanGuardConfig(patience=0))
    with pytest.raises(NonFiniteLossError) as err:
        guard.on_step_end(_FakeTrainer(), 1, {"loss": float("nan"), "grad_norm": 1.0})
    assert "anomaly dump" not in str(err.value)


def test_spike_guard_warmup_no_false_positives():
    from llm_training_tpu.callbacks import NanGuard, NanGuardConfig

    guard = NanGuard(NanGuardConfig(spike_zscore=4.0, spike_warmup_steps=30))
    trainer = _FakeTrainer()
    rng = np.random.default_rng(0)
    # wildly varying pre-warmup losses must never trip the un-armed guard
    for step in range(1, 30):
        guard.on_step_end(
            trainer, step, {"loss": float(rng.uniform(0.1, 50.0)), "grad_norm": 1.0}
        )
    assert guard.spike_steps == 0 and not trainer.should_stop


def test_spike_guard_steady_descent_is_not_a_spike():
    from llm_training_tpu.callbacks import NanGuard, NanGuardConfig

    guard = NanGuard(NanGuardConfig(spike_zscore=6.0, spike_warmup_steps=20))
    trainer = _FakeTrainer()
    loss = 5.0
    for step in range(1, 60):
        guard.on_step_end(trainer, step, {"loss": loss, "grad_norm": 1.0})
        loss *= 0.99  # a healthy training curve
    assert guard.spike_steps == 0


def test_spike_guard_ignores_sharp_improvement():
    """An LR-drop/curriculum loss CLIFF is a negative z — a converging run
    must never be aborted as a 'spike'."""
    from llm_training_tpu.callbacks import NanGuard, NanGuardConfig

    guard = NanGuard(NanGuardConfig(spike_zscore=6.0, spike_warmup_steps=10))
    trainer = _FakeTrainer()
    for step in range(1, 21):
        guard.on_step_end(trainer, step, {"loss": 2.0, "grad_norm": 1.0})
    guard.on_step_end(trainer, 21, {"loss": 0.5, "grad_norm": 1.0})
    assert guard.spike_steps == 0 and not trainer.should_stop


def test_spike_guard_raises_on_spike_with_suspects():
    from llm_training_tpu.callbacks import LossSpikeError, NanGuard, NanGuardConfig

    guard = NanGuard(NanGuardConfig(spike_zscore=6.0, spike_warmup_steps=10))
    trainer = _FakeTrainer(
        last_health={"health/update_ratio/layers_01": 0.9,
                     "health/update_ratio/norm": 0.1},
    )
    for step in range(1, 21):
        guard.on_step_end(trainer, step, {"loss": 2.0, "grad_norm": 1.0})
    with pytest.raises(LossSpikeError, match="layers_01"):
        guard.on_step_end(trainer, 21, {"loss": 40.0, "grad_norm": 1.0})
    assert guard.spike_steps == 1
    assert trainer.telemetry.snapshot()["nan_guard/spike_steps"] == 1.0


def test_spike_guard_stop_keeps_final_save():
    from llm_training_tpu.callbacks import NanGuard, NanGuardConfig

    guard = NanGuard(NanGuardConfig(
        spike_zscore=6.0, spike_warmup_steps=5, action="stop"
    ))
    trainer = _FakeTrainer()
    for step in range(1, 11):
        guard.on_step_end(trainer, step, {"loss": 2.0, "grad_norm": 1.0})
    guard.on_step_end(trainer, 11, {"loss": 50.0, "grad_norm": 1.0})
    assert trainer.should_stop is True
    # spiked weights are finite — the final checkpoint stays useful
    assert trainer.abort_final_save is False


# ------------------------------------------------------------ integration


def _moe_objective():
    from llm_training_tpu.lms import CLM, CLMConfig, ModelProvider

    return CLM(CLMConfig(model=ModelProvider(
        model_class="Llama",
        model_kwargs=dict(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
            max_position_embeddings=64, attention_impl="xla",
            param_dtype="float32", compute_dtype="float32",
            num_experts=4, num_experts_per_tok=2, moe_intermediate_size=32,
        ),
    )))


@pytest.mark.slow
def test_fit_with_health_flows_to_telemetry_and_report(tmp_path):
    from llm_training_tpu.callbacks import JsonlLogger, JsonlLoggerConfig
    from llm_training_tpu.data import DummyDataModule, DummyDataModuleConfig
    from llm_training_tpu.parallel import MeshConfig
    from llm_training_tpu.telemetry.report import render_report
    from llm_training_tpu.trainer import Trainer, TrainerConfig

    logger = JsonlLogger(JsonlLoggerConfig(save_dir=str(tmp_path), name="health"))
    trainer = Trainer(
        TrainerConfig(max_steps=4, log_every_n_steps=2, mesh=MeshConfig(),
                      health={"every_n_steps": 2}),
        callbacks=[logger],
    )
    dm = DummyDataModule(DummyDataModuleConfig(
        batch_size=8, max_length=32, num_samples=128, vocab_size=128))
    trainer.fit(_moe_objective(), dm)

    assert trainer.last_health is not None
    records = [
        json.loads(line)
        for line in (logger.run_dir / "telemetry.jsonl").read_text().splitlines()
    ]
    last = records[-1]
    # per-layer grad/update norms grouped per block
    assert "health/grad_norm/layers_00" in last
    assert "health/update_ratio/layers_01" in last
    # MoE router health keyed by layer
    assert "health/moe/router_entropy/layer_00" in last
    assert 0.0 <= last["health/moe/max_expert_share/layer_01"] <= 1.0
    assert last["health/moe/dropped_rows"] == 0.0
    report = render_report(logger.run_dir)
    assert "== Health ==" in report
    assert "router_entropy" in report


@pytest.mark.slow
def test_fit_without_health_emits_no_health_metrics():
    from llm_training_tpu.data import DummyDataModule, DummyDataModuleConfig
    from llm_training_tpu.parallel import MeshConfig
    from llm_training_tpu.trainer import Trainer, TrainerConfig

    seen = {}

    class Capture:
        def on_step_end(self, trainer, step, metrics):
            seen.update(metrics)

    trainer = Trainer(
        TrainerConfig(max_steps=2, log_every_n_steps=1, mesh=MeshConfig()),
        callbacks=[Capture()],
    )
    dm = DummyDataModule(DummyDataModuleConfig(
        batch_size=8, max_length=32, num_samples=64, vocab_size=128))
    trainer.fit(_moe_objective(), dm)
    assert trainer.last_health is None
    assert not any(k.startswith("health/") for k in seen)
