"""Serving subsystem tests (docs/serving.md): paged-decode parity against
the dense `DecodeState` path and the full-forward oracle, continuous-
batching behaviours (mid-stream admission, eviction-then-resume, slot
recycling), the block allocator / scheduler policy units, the ragged
paged-decode kernel vs the XLA gather fallback, and the `== Serving ==`
report section."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_training_tpu.infer import GenerateConfig, InferenceEngine
from llm_training_tpu.models import Gemma, GemmaConfig, Llama, LlamaConfig
from llm_training_tpu.serve import (
    BlockAllocator,
    Scheduler,
    SchedulerConfig,
    ServeConfig,
    ServeRequest,
    ServingEngine,
)
from llm_training_tpu.serve.paged_cache import TRASH_BLOCK, resolve_block_size
from llm_training_tpu.telemetry import get_registry

TINY = dict(
    vocab_size=64, hidden_size=32, intermediate_size=64,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    max_position_embeddings=64, attention_impl="xla",
    compute_dtype="float32", param_dtype="float32",
)


def _init(model, seed=0):
    return model.init(jax.random.key(seed), np.zeros((1, 4), np.int32))


_ORACLE_WIDTH = 32  # static pad width: covers every prompt + n in this file
_oracle_cache: dict[int, tuple] = {}  # id(model) -> (model, jitted forward)


def _full_forward_greedy(model, variables, prompt, n):
    """The oracle (test_infer.py): n argmax tokens from n full forwards —
    jitted ONCE per model at a padded static width (length traced, pads
    masked via segment ids) so each step is a cheap cached call, not an
    eager CPU forward."""
    entry = _oracle_cache.get(id(model))
    if entry is None or entry[0] is not model:

        @jax.jit
        def fwd(variables, ids, length):
            seg = (jnp.arange(ids.shape[1]) < length).astype(jnp.int32)[None]
            out = model.apply(variables, input_ids=ids, segment_ids=seg)
            logits = jax.lax.dynamic_index_in_dim(
                out.logits[0], length - 1, axis=0, keepdims=False
            )
            return jnp.argmax(logits)

        entry = (model, fwd)  # strong model ref: id() can't be recycled
        _oracle_cache[id(model)] = entry
    fwd = entry[1]
    seq = list(prompt)
    for _ in range(n):
        ids = np.zeros((1, _ORACLE_WIDTH), np.int32)
        ids[0, : len(seq)] = seq
        seq.append(int(fwd(variables, jnp.asarray(ids), jnp.int32(len(seq)))))
    return seq[len(prompt):]


def _serve_all(model, variables, prompts, n, **overrides):
    """Drain `prompts` through a ServingEngine; -> ({id: tokens}, engine)."""
    config = ServeConfig(**{
        "max_batch": 2, "max_model_len": 48, "block_size": 8,
        "prefill_chunk": 4, "eos_token_id": None, **overrides,
    })
    engine = ServingEngine(model, variables, config)
    events = engine.run([
        {"id": str(row), "prompt": list(p), "max_new_tokens": n}
        for row, p in enumerate(prompts)
    ])
    done = {e["id"]: e for e in events if e["type"] == "done"}
    assert engine.allocator.blocks_in_use == 0, "pool leak after drain"
    return done, engine


# ------------------------------------------------------- allocator unit


def test_allocator_alloc_free_roundtrip():
    allocator = BlockAllocator(num_blocks=5)  # 4 usable + trash
    assert allocator.free_blocks == 4
    blocks = allocator.alloc(3)
    assert len(blocks) == 3 and TRASH_BLOCK not in blocks
    # all-or-nothing: asking past the remaining 1 allocates NOTHING
    assert allocator.alloc(2) is None
    assert allocator.free_blocks == 1
    allocator.free(blocks)
    assert allocator.free_blocks == 4 and allocator.blocks_in_use == 0
    assert allocator.peak_in_use == 3
    with pytest.raises(ValueError):
        allocator.free([blocks[0]])  # double free is a bug, not a no-op
    with pytest.raises(ValueError):
        BlockAllocator(num_blocks=1)  # trash block only — unusable


def test_allocator_occupancy_gauges():
    allocator = BlockAllocator(num_blocks=4)
    blocks = allocator.alloc(2)
    registry = get_registry()
    assert registry.gauge("decode/cache_blocks_in_use").value == 2
    allocator.free(blocks)
    assert registry.gauge("decode/cache_blocks_in_use").value == 0
    assert registry.gauge("decode/cache_peak_blocks_in_use").value == 2


# ------------------------------------------------------- scheduler unit


def _scheduler(max_batch=2, blocks=8, block_size=8, max_len=32, chunk=4):
    return Scheduler(
        SchedulerConfig(
            max_batch=max_batch, max_model_len=max_len,
            block_size=block_size, prefill_chunk=chunk,
        ),
        BlockAllocator(blocks + 1),
    )


def test_scheduler_rejects_impossible_requests():
    scheduler = _scheduler(max_len=16)
    over = ServeRequest(id="over", prompt=[1] * 10, max_new_tokens=10)
    assert scheduler.submit(over) is over and over.stop_reason == "rejected"
    empty = ServeRequest(id="empty", prompt=[], max_new_tokens=4)
    assert scheduler.submit(empty) is empty
    ok = ServeRequest(id="ok", prompt=[1, 2], max_new_tokens=4)
    assert scheduler.submit(ok) is None and scheduler.waiting[0] is ok


def test_scheduler_admission_is_all_or_nothing():
    scheduler = _scheduler(blocks=2, max_len=32)
    long = ServeRequest(id="long", prompt=[1] * 20, max_new_tokens=4)
    short = ServeRequest(id="short", prompt=[1, 2], max_new_tokens=4)
    scheduler.submit(long)
    scheduler.submit(short)
    # head of queue needs ceil(21/8)=3 blocks, pool holds 2, nothing is
    # running to drain -> head fails with 'capacity' instead of starving
    # the queue; the short request behind it admits normally
    admitted = scheduler.admit()
    assert long.stop_reason == "capacity"
    assert admitted == [short] and short.slot is not None
    assert scheduler.allocator.blocks_in_use == 1


def test_scheduler_chunked_prefill_is_oldest_first():
    scheduler = _scheduler(chunk=4)
    first = ServeRequest(id="first", prompt=[1] * 6, max_new_tokens=2, arrival_s=1.0)
    second = ServeRequest(id="second", prompt=[2] * 3, max_new_tokens=2, arrival_s=2.0)
    scheduler.submit(first)
    scheduler.submit(second)
    scheduler.admit()
    request, chunk, start = scheduler.next_prefill()
    assert request is first and chunk == [1, 1, 1, 1] and start == 0
    request.prefilled += len(chunk)
    request, chunk, start = scheduler.next_prefill()
    assert request is first and chunk == [1, 1] and start == 4
    request.prefilled += len(chunk)
    request.cache_len = 6
    assert first.decoding
    request, chunk, start = scheduler.next_prefill()
    assert request is second


def test_scheduler_evicts_lowest_priority_then_youngest():
    scheduler = _scheduler(blocks=2, max_batch=3, chunk=8)
    vip = ServeRequest(id="vip", prompt=[1] * 4, max_new_tokens=8,
                       priority=1, arrival_s=1.0)
    old = ServeRequest(id="old", prompt=[2] * 4, max_new_tokens=8, arrival_s=2.0)
    young = ServeRequest(id="young", prompt=[3] * 4, max_new_tokens=8, arrival_s=3.0)
    for request in (vip, old, young):
        scheduler.submit(request)
    # blocks=2 admits exactly two 1-block residencies; 'young' waits
    assert scheduler.admit() == [vip, old]
    vip.cache_len = old.cache_len = 8  # both pages now full
    # vip's next token needs a second block: pool dry -> the LOWEST
    # priority running request is the victim ('old', not the vip)
    assert scheduler.ensure_decode_blocks(vip)
    assert old.slot is None and old.evictions == 1
    assert scheduler.waiting[0] is old  # requeued at the FRONT
    assert len(vip.blocks) == 2


def test_scheduler_eviction_folds_progress_into_prompt():
    scheduler = _scheduler(blocks=1, max_batch=2)
    request = ServeRequest(id="r", prompt=[1, 2, 3], max_new_tokens=8)
    scheduler.submit(request)
    scheduler.admit()
    request.generated = [7, 8]
    request.cache_len = 5
    scheduler.evict(request)
    assert scheduler.allocator.blocks_in_use == 0
    readmitted = scheduler.admit()
    assert readmitted == [request]
    # the re-prefill replays prompt + generated, so greedy continuation
    # is token-identical to the uninterrupted run
    assert request.prefill_tokens == [1, 2, 3, 7, 8]
    assert request.prefilled == 0 and request.cache_len == 0


# -------------------------------------------------- paged tuning / pool


def test_resolve_block_size_paged_kind(monkeypatch):
    config = LlamaConfig(**TINY)
    monkeypatch.delenv("PAGED_BLOCK_K", raising=False)
    assert resolve_block_size(config, max_model_len=64) == 16  # paged default
    monkeypatch.setenv("PAGED_BLOCK_K", "32")
    assert resolve_block_size(config, max_model_len=64) == 32
    # explicit config wins over env; sublane (8) alignment enforced
    assert resolve_block_size(config, 64, block_size=8) == 8
    with pytest.raises(ValueError):
        resolve_block_size(config, 64, block_size=12)


def test_paged_append_pads_go_to_trash():
    from llm_training_tpu.ops.paged_attention import paged_append

    pool = jnp.zeros((4, 8, 1, 4))  # [blocks, page, h, d]
    k = jnp.ones((1, 4, 1, 4))
    seg = jnp.asarray([[1, 1, 0, 0]])  # 2 real tokens, 2 pads
    tables = jnp.asarray([[2, 3]])
    new_k, _ = paged_append(
        pool, pool, k, k, jnp.asarray([7]), tables, seg
    )
    # row length 7: real tokens land at block 2 slot 7 then block 3 slot 0
    assert float(new_k[2, 7, 0, 0]) == 1.0
    assert float(new_k[3, 0, 0, 0]) == 1.0
    # pads went to the trash block, nowhere else
    assert float(jnp.sum(new_k[1:])) == 2 * 4  # two real tokens x head_dim
    assert float(jnp.sum(new_k[TRASH_BLOCK])) > 0


@pytest.mark.parametrize("window,cap,group", [
    (None, None, 2), (5, None, 2), (None, 4.0, 1), (5, 4.0, 4),
])
def test_paged_kernel_matches_gather_fallback(window, cap, group):
    """The interpreted Pallas kernel and the XLA gather path must agree on
    ragged single-token decode — GQA groups, sliding windows, soft cap."""
    from llm_training_tpu.ops.paged_attention import paged_cached_attention

    batch, kv_heads, head_dim, page, pages = 3, 2, 8, 8, 3
    keys = jax.random.split(jax.random.key(0), 4)
    pool_shape = (1 + batch * pages, page, kv_heads, head_dim)
    pool_k = jax.random.normal(keys[0], pool_shape)
    pool_v = jax.random.normal(keys[1], pool_shape)
    q = jax.random.normal(keys[2], (batch, 1, kv_heads * group, head_dim))
    k = jax.random.normal(keys[3], (batch, 1, kv_heads, head_dim))
    v = jax.random.normal(keys[3], (batch, 1, kv_heads, head_dim)) + 1.0
    tables = jnp.arange(1, 1 + batch * pages, dtype=jnp.int32).reshape(batch, pages)
    lengths = jnp.asarray([0, 7, 20], jnp.int32)  # ragged: page starts/middles
    outs = {}
    for impl in ("pallas", "xla"):
        outs[impl], _ = paged_cached_attention(
            q, k, v, (pool_k, pool_v), lengths, tables,
            sliding_window=window, logits_soft_cap=cap, impl=impl,
        )
    np.testing.assert_allclose(
        np.asarray(outs["pallas"]), np.asarray(outs["xla"]), rtol=2e-5, atol=2e-5
    )


# -------------------------------------------- paged == dense greedy parity


@pytest.mark.parametrize("scan_layers", [True, False], ids=["scan", "looped"])
def test_paged_greedy_matches_dense_and_oracle(scan_layers):
    """Continuous-batching greedy decode through the paged pool must be
    token-identical to BOTH the dense `DecodeState` engine and the full-
    forward oracle, with ragged prompts spanning page boundaries."""
    model = Llama(LlamaConfig(**TINY, scan_layers=scan_layers))
    variables = _init(model)
    prompts = [[3, 17, 42, 7, 11], [5, 9], [1, 2, 3]]
    n = 8
    done, _ = _serve_all(model, variables, prompts, n)
    dense = InferenceEngine(model, variables).generate(
        prompts, GenerateConfig(max_new_tokens=n, eos_token_id=None)
    )
    for row, prompt in enumerate(prompts):
        expected = _full_forward_greedy(model, variables, prompt, n)
        assert done[str(row)]["tokens"] == expected, f"row {row} vs oracle"
        assert dense["tokens"][row] == expected, f"row {row} dense vs oracle"


def test_paged_greedy_moe_and_sliding_window():
    model = Llama(LlamaConfig(
        **TINY, num_experts=4, num_experts_per_tok=2, moe_intermediate_size=32,
        sliding_window=4,
    ))
    variables = _init(model)
    prompts = [[3, 17, 42, 7, 11, 2], [9, 4, 6]]
    done, _ = _serve_all(model, variables, prompts, 6)
    for row, prompt in enumerate(prompts):
        assert done[str(row)]["tokens"] == _full_forward_greedy(
            model, variables, prompt, 6
        ), f"row {row}"


def test_paged_greedy_gemma():
    model = Gemma(GemmaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, max_position_embeddings=64, attention_impl="xla",
        compute_dtype="float32",
    ))
    variables = _init(model)
    prompts = [[3, 17, 42], [5, 9, 11, 13]]
    done, _ = _serve_all(model, variables, prompts, 5)
    for row, prompt in enumerate(prompts):
        assert done[str(row)]["tokens"] == _full_forward_greedy(
            model, variables, prompt, 5
        ), f"row {row}"


def test_eos_recycles_slot_and_reports_stop_reason():
    """A row hitting eos frees its slot/blocks immediately; the engine
    reports 'eos' and the dense engine satellite reports the same per-row
    lengths/stop_reasons split."""
    model = Llama(LlamaConfig(**TINY))
    variables = _init(model)
    prompt = [3, 17, 42, 7]
    oracle = _full_forward_greedy(model, variables, prompt, 6)
    eos = oracle[2]  # force an early deterministic stop
    config = ServeConfig(max_batch=1, max_model_len=32, block_size=8,
                         prefill_chunk=4, eos_token_id=eos)
    engine = ServingEngine(model, variables, config)
    events = engine.run([{"id": "r", "prompt": prompt, "max_new_tokens": 6}])
    done = [e for e in events if e["type"] == "done"]
    assert done[0]["stop_reason"] == "eos"
    assert done[0]["tokens"] == oracle[:3]  # up to and including eos
    assert engine.allocator.blocks_in_use == 0

    dense_engine = InferenceEngine(model, variables)  # one compile set
    dense = dense_engine.generate(
        [prompt], GenerateConfig(max_new_tokens=6, eos_token_id=eos)
    )
    assert dense["stop_reasons"] == ["eos"] and dense["lengths"] == [3]
    full = dense_engine.generate(
        [prompt], GenerateConfig(max_new_tokens=6, eos_token_id=None)
    )
    assert full["stop_reasons"] == ["max_tokens"] and full["lengths"] == [6]


# ------------------------------------------------- continuous batching


def test_mid_stream_admission_is_token_identical():
    """A request submitted while another is mid-decode joins the SAME
    batch (continuous batching) and both finish token-identical to the
    oracle — the dense engine's closed-batch limitation, lifted."""
    model = Llama(LlamaConfig(**TINY))
    variables = _init(model)
    first, second = [3, 17, 42, 7], [5, 9, 11]
    n = 8
    config = ServeConfig(max_batch=2, max_model_len=48, block_size=8,
                         prefill_chunk=4, eos_token_id=None)
    engine = ServingEngine(model, variables, config)
    events = list(engine.submit("first", first, max_new_tokens=n))
    while sum(e["type"] == "token" for e in events) < 2:
        events.extend(engine.step())  # 'first' is now mid-decode
    events.extend(engine.submit("second", second, max_new_tokens=n))
    events.extend(engine.step())
    assert len(engine.scheduler.running) == 2, "second not admitted mid-flight"
    while not engine.scheduler.idle:
        events.extend(engine.step())
    done = {e["id"]: e for e in events if e["type"] == "done"}
    assert done["first"]["tokens"] == _full_forward_greedy(model, variables, first, n)
    assert done["second"]["tokens"] == _full_forward_greedy(model, variables, second, n)
    assert engine.peak_running == 2
    assert engine.allocator.blocks_in_use == 0


def test_eviction_then_resume_is_token_identical():
    """Under pool pressure the lowest-priority request is evicted, its
    blocks freed, and after re-admission its greedy continuation matches
    the uninterrupted oracle exactly (progress re-prefilled, already-
    streamed tokens never re-emitted)."""
    model = Llama(LlamaConfig(**TINY))
    variables = _init(model)
    prompts = [[3, 17, 42, 7], [5, 9, 11]]
    n = 12
    # 3 usable blocks of 8 for two requests reaching 15-16 tokens: growth
    # past each page boundary forces an eviction instead of a clean alloc
    done, engine = _serve_all(
        model, variables, prompts, n,
        max_batch=2, max_model_len=32, num_blocks=3, prefill_chunk=4,
    )
    assert engine.scheduler.evictions >= 1, "pool pressure never evicted"
    assert sum(d["evictions"] for d in done.values()) >= 1
    for row, prompt in enumerate(prompts):
        assert done[str(row)]["tokens"] == _full_forward_greedy(
            model, variables, prompt, n
        ), f"row {row} diverged across eviction"
    # token chunks stream exactly once per generated token despite the
    # evict/resume round trip
    assert engine.allocator.blocks_in_use == 0


def test_cross_survivor_eviction_mid_decode_step():
    """A LATER decode row's block growth can evict an EARLIER row that
    already passed its own ensure_decode_blocks this step (lower priority,
    mid-page). The evicted row must be dropped from the step's batch — its
    blocks may already belong to the evictor — and still finish
    token-identically after re-admission."""
    model = Llama(LlamaConfig(**TINY))
    variables = _init(model)
    # pool of 2: A (priority 0, prompt 4) and B (priority 1, prompt 6)
    # admit with one block each. B hits its page boundary (cache 8) while
    # A sits mid-page — B's growth needs a block, the pool is dry, and the
    # victim is A, processed EARLIER in the same decode step.
    config = ServeConfig(max_batch=2, max_model_len=16, block_size=8,
                         num_blocks=2, prefill_chunk=8, eos_token_id=None)
    engine = ServingEngine(model, variables, config)
    events = engine.run([
        {"id": "a", "prompt": [3, 17, 42, 7], "max_new_tokens": 8, "priority": 0},
        {"id": "b", "prompt": [5, 9, 11, 13, 2, 6], "max_new_tokens": 8, "priority": 1},
    ])
    done = {e["id"]: e for e in events if e["type"] == "done"}
    assert done["a"]["evictions"] >= 1, "priority eviction never fired"
    assert done["b"]["evictions"] == 0
    assert done["a"]["tokens"] == _full_forward_greedy(model, variables, [3, 17, 42, 7], 8)
    assert done["b"]["tokens"] == _full_forward_greedy(
        model, variables, [5, 9, 11, 13, 2, 6], 8
    )
    assert engine.allocator.blocks_in_use == 0


def test_capacity_failure_emits_done_event():
    """A request that fits max_model_len but can NEVER fit the pool ends
    with stop_reason='capacity' — and the protocol owes the client that
    done chunk (an interactive client would otherwise block forever)."""
    model = Llama(LlamaConfig(**TINY))
    variables = _init(model)
    engine = ServingEngine(model, variables, ServeConfig(
        max_batch=2, max_model_len=32, block_size=8, num_blocks=1,
        prefill_chunk=4, eos_token_id=None,
    ))
    events = engine.run([
        # needs ceil(13/8) = 2 blocks against a 1-block pool
        {"id": "big", "prompt": [1] * 12, "max_new_tokens": 4},
        {"id": "ok", "prompt": [3, 5], "max_new_tokens": 2},
    ])
    done = {e["id"]: e for e in events if e["type"] == "done"}
    assert done["big"]["stop_reason"] == "capacity"
    assert done["ok"]["stop_reason"] == "max_tokens"
    assert engine.allocator.blocks_in_use == 0


def test_submit_rejects_non_int_prompt():
    """A syntactically valid request with a junk prompt must fail AT
    SUBMIT (where the CLI's error contract lives), never inside a later
    engine.step() taking the whole batch down."""
    model = Llama(LlamaConfig(**TINY))
    variables = _init(model)
    engine = ServingEngine(model, variables, ServeConfig(
        max_batch=1, max_model_len=32, block_size=8, eos_token_id=None,
    ))
    with pytest.raises((TypeError, ValueError)):
        engine.submit("junk", "abc", max_new_tokens=4)
    # numeric strings coerce; the queue stays serviceable
    events = engine.run([{"id": "ok", "prompt": ["3", 17], "max_new_tokens": 2}])
    assert [e["id"] for e in events if e["type"] == "done"] == ["ok"]


def test_serve_config_validators():
    with pytest.raises(ValueError):
        ServeConfig(max_batch=0)
    with pytest.raises(ValueError):
        ServeConfig(max_model_len=1)
    with pytest.raises(ValueError):
        ServeConfig(prefill_chunk=0)
    with pytest.raises(ValueError):
        ServeConfig(block_size=0)
    with pytest.raises(ValueError):
        ServeConfig(unknown_knob=1)


def test_engine_stats_and_pool_gauges():
    model = Llama(LlamaConfig(**TINY))
    variables = _init(model)
    done, engine = _serve_all(model, variables, [[3, 5, 7]], 4, max_batch=1)
    stats = engine.stats()
    assert stats["serve/requests_completed"] == 1
    assert stats["serve/tokens_generated"] == 4
    assert stats["serve/tokens_per_sec"] > 0
    assert stats["decode/cache_blocks_in_use"] == 0
    assert stats["decode/cache_peak_blocks_in_use"] >= 1
    assert stats["serve/ttft_p50_ms"] > 0 and stats["serve/tpot_p50_ms"] >= 0
    registry = get_registry()
    assert registry.gauge("serve/tokens_per_sec").value == stats["serve/tokens_per_sec"]
    # pool construction published its footprint (the cache_bytes satellite)
    assert registry.gauge("decode/cache_bytes").value is not None


def test_init_decode_state_publishes_cache_bytes():
    """Satellite: EVERY dense cache construction lands decode/cache_bytes
    in the registry — not just engine.generate's."""
    from llm_training_tpu.infer import cache_bytes, init_decode_state

    state = init_decode_state(LlamaConfig(**TINY), batch_size=2, max_length=16)
    assert get_registry().gauge("decode/cache_bytes").value == cache_bytes(state)


# ----------------------------------------------------------- reporting


def test_report_serving_section():
    from llm_training_tpu.telemetry.report import _serving_section

    lines = _serving_section({
        "serve/requests_completed": 3, "serve/requests_evicted": 1,
        "serve/peak_running": 2, "serve/tokens_per_sec": 123.4,
        "serve/tokens_per_sec_per_chip": 30.85, "serve/tokens_generated": 96,
        "serve/ttft_p50_ms": 12.5, "serve/ttft_p99_ms": 80.0,
        "serve/tpot_p50_ms": 3.1, "decode/cache_blocks_total": 16,
        "decode/cache_peak_blocks_in_use": 9, "decode/cache_blocks_in_use": 0,
    })
    text = "\n".join(lines)
    assert "== Serving ==" in text
    assert "3 completed" in text and "1 evictions" in text
    assert "123.4 tokens/s" in text and "(30.9/chip)" in text
    assert "ttft: p50 12.5 ms  p99 80.0 ms" in text
    assert "16 blocks, peak 9 in use (56%)" in text
    assert "leak" not in text
    leaky = "\n".join(_serving_section({
        "serve/requests_completed": 1, "decode/cache_blocks_total": 8,
        "decode/cache_blocks_in_use": 2,
    }))
    assert "2 still held at exit (leak?)" in leaky
    assert _serving_section({"goodput/total_s": 1.0}) == []


# ------------------------------------------------- stats edges + tracing


@pytest.fixture()
def fresh_tracer():
    """A fresh process tracer so span/ttft assertions see only this test's
    events (engine + scheduler emit through the module-global tracer)."""
    from llm_training_tpu.telemetry.trace import TraceRecorder, set_tracer

    recorder = TraceRecorder(capacity=4096, sample_every=1, enabled=True)
    previous = set_tracer(recorder)
    try:
        yield recorder
    finally:
        set_tracer(previous)


def test_stats_zero_completed_requests():
    """Percentile edge: a fresh engine (and one holding only failed
    requests) must not crash on empty ttft/tpot lists — the keys are
    simply absent."""
    model = Llama(LlamaConfig(**TINY))
    variables = _init(model)
    engine = ServingEngine(model, variables, ServeConfig(
        max_batch=1, max_model_len=16, block_size=8, eos_token_id=None,
    ))
    stats = engine.stats()
    assert stats["serve/requests_completed"] == 0
    assert stats["serve/tokens_per_sec"] == 0.0
    assert "serve/ttft_p50_ms" not in stats and "serve/tpot_p50_ms" not in stats
    # a rejected request is a failure, never a latency sample
    engine.run([{"id": "big", "prompt": [1] * 20, "max_new_tokens": 4}])
    stats = engine.stats()
    assert stats["serve/requests_completed"] == 0
    assert stats["serve/requests_failed"] == 1
    assert "serve/ttft_p50_ms" not in stats


def test_stats_single_request_percentiles():
    """Percentile edge: with one completed request p50 == p99 == its own
    latency, and both match the done event's ttft_ms."""
    model = Llama(LlamaConfig(**TINY))
    variables = _init(model)
    done, engine = _serve_all(model, variables, [[3, 5, 7]], 6, max_batch=1)
    stats = engine.stats()
    assert stats["serve/requests_completed"] == 1
    assert stats["serve/ttft_p50_ms"] == pytest.approx(stats["serve/ttft_p99_ms"])
    assert stats["serve/ttft_p50_ms"] == pytest.approx(done["0"]["ttft_ms"], rel=1e-3)
    assert stats["serve/tpot_p50_ms"] == pytest.approx(stats["serve/tpot_p99_ms"])
    assert stats["serve/tpot_p50_ms"] == pytest.approx(done["0"]["tpot_ms"], rel=1e-3)


def test_stats_evicted_request_ttft_from_original_arrival(fresh_tracer):
    """Percentile edge (the subtle one): an evicted-then-resumed request's
    TTFT is measured from its ORIGINAL arrival — never from the requeue —
    and is never double-counted (exactly one first_token per request)."""
    model = Llama(LlamaConfig(**TINY))
    variables = _init(model)
    prompts = [[3, 17, 42, 7], [5, 9, 11]]
    done, engine = _serve_all(
        model, variables, prompts, 12,
        max_batch=2, max_model_len=32, num_blocks=3, prefill_chunk=4,
    )
    assert engine.scheduler.evictions >= 1
    ring = fresh_tracer.snapshot()
    by_request = {}
    for event in ring:
        args = event.get("args") or {}
        if "request_id" in args:
            by_request.setdefault(args["request_id"], []).append(event)
    evicted = [r for r in engine.scheduler.completed if r.evictions]
    assert evicted, "pool pressure never evicted"
    for request in engine.scheduler.completed:
        events = by_request[request.id]
        firsts = [e for e in events if e["name"] == "first_token"]
        assert len(firsts) == 1, "first_token double-counted across residencies"
        submit = next(e for e in events if e["name"] == "submit")
        # arrival-anchored: the instant's ttft equals first_token - submit
        measured = 1000.0 * (firsts[0]["ts"] - submit["ts"])
        assert firsts[0]["args"]["ttft_ms"] == pytest.approx(measured, abs=1.0)
        assert done[request.id]["ttft_ms"] == pytest.approx(measured, abs=1.0)
    for request in evicted:
        events = by_request[request.id]
        evict_ts = [e["ts"] for e in events if e["name"] == "evicted"]
        first_ts = next(e for e in events if e["name"] == "first_token")["ts"]
        if any(t < first_ts for t in evict_ts):
            # evicted before its first token: a requeue-anchored TTFT would
            # be smaller than first_token - requeue; the reported one spans
            # the whole wait from original arrival
            requeue_anchored = 1000.0 * (first_ts - min(evict_ts))
            assert done[request.id]["ttft_ms"] > requeue_anchored - 1.0
    # stats percentiles are computed over those same arrival-anchored values
    stats = engine.stats()
    ttfts = sorted(d["ttft_ms"] for d in done.values())
    assert min(ttfts) - 1e-3 <= stats["serve/ttft_p50_ms"] <= max(ttfts) + 1e-3


def test_request_lifecycle_spans_tile_wall_clock(fresh_tracer):
    """Acceptance: every completed request's queue -> prefill -> decode
    spans sum to its wall time (arrival -> completion), across evictions,
    and the sink receives only sampled requests."""
    import time as _time

    model = Llama(LlamaConfig(**TINY))
    variables = _init(model)
    prompts = [[3, 17, 42, 7], [5, 9, 11]]
    done, engine = _serve_all(
        model, variables, prompts, 12,
        max_batch=2, max_model_len=32, num_blocks=3, prefill_chunk=4,
    )
    t_end = _time.perf_counter()
    ring = fresh_tracer.snapshot()
    for request in engine.scheduler.completed:
        phase_sum = sum(
            e["dur"] for e in ring
            if e.get("ph") == "X"
            and (e.get("args") or {}).get("request_id") == request.id
            and e["name"] in ("queue", "prefill", "decode")
        )
        wall = t_end - request.arrival_s
        # phases tile arrival -> finish exactly; only the post-finish slice
        # of `wall` (bookkeeping after the last done event) is uncovered
        assert 0 < phase_sum <= wall + 1e-6
        last = request.last_token_s - request.arrival_s
        assert phase_sum == pytest.approx(last, abs=0.05)
    engine_steps = [e for e in ring if e["name"] == "engine_step"]
    assert engine_steps and all(e["ph"] == "X" for e in engine_steps)


def test_request_sampling_gates_sink_not_ring(tmp_path):
    """LLMT_TRACE_SAMPLE=N: only every Nth request reaches trace.jsonl;
    the ring (flight recorder) still sees all of them."""
    from llm_training_tpu.telemetry.trace import (
        TraceRecorder,
        read_trace_events,
        set_tracer,
    )

    recorder = TraceRecorder(capacity=4096, sample_every=2, enabled=True)
    previous = set_tracer(recorder)
    try:
        recorder.attach_sink(tmp_path / "trace.jsonl")
        model = Llama(LlamaConfig(**TINY))
        variables = _init(model)
        done, _ = _serve_all(
            model, variables, [[3, 5, 7], [9, 11], [4, 8]], 2, max_batch=2
        )
        assert len(done) == 3
        recorder.detach_sink()
        written = {
            (e.get("args") or {}).get("request_id")
            for e in read_trace_events(tmp_path / "trace.jsonl")
            if (e.get("args") or {}).get("request_id")
        }
        assert written == {"0", "2"}  # every 2nd submit, starting at the first
        ring_ids = {
            (e.get("args") or {}).get("request_id")
            for e in recorder.snapshot()
            if (e.get("args") or {}).get("request_id")
        }
        assert ring_ids == {"0", "1", "2"}
    finally:
        recorder.detach_sink()
        set_tracer(previous)
