"""Gemma 1/2: shapes, config validation, HF logits parity, state-dict round
trip (incl. the gemma-2 (sliding, full) scan pairing), and HFCausalLM routing.

Gemma-2's numerics are exactly the ones that silently break: (1+w) RMSNorm
with fp32 pre-downcast multiply, sqrt(hidden) embedding scaling, sandwich
norms, attention/final logit soft-capping, query_pre_attn_scalar scale, and
sliding window on even layer indices.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_training_tpu.models import Gemma, GemmaConfig
from llm_training_tpu.models.gemma.hf_conversion import (
    config_from_hf,
    params_from_hf,
    params_to_hf,
)
from llm_training_tpu.models.hf_io import model_class_for_hf

TINY_V1 = dict(
    vocab_size=128,
    hidden_size=64,
    intermediate_size=112,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=1,
    head_dim=16,
    max_position_embeddings=64,
    compute_dtype="float32",
)

TINY_V2 = dict(
    version=2,
    vocab_size=128,
    hidden_size=64,
    intermediate_size=112,
    num_hidden_layers=4,
    num_attention_heads=4,
    num_key_value_heads=2,
    head_dim=16,
    max_position_embeddings=64,
    query_pre_attn_scalar=24,
    attn_logit_softcapping=50.0,
    final_logit_softcapping=30.0,
    sliding_window=8,
    compute_dtype="float32",
)


@pytest.mark.slow
def test_forward_shapes():
    cfg = GemmaConfig(**TINY_V1)
    model = Gemma(cfg)
    ids = jnp.ones((2, 10), jnp.int32)
    params = model.init(jax.random.key(0), ids)
    out = model.apply(params, ids, return_last_hidden_states=True)
    assert out.logits.shape == (2, 10, 128)
    assert out.last_hidden_states.shape == (2, 10, 64)


def test_v1_rejects_v2_features():
    with pytest.raises(ValueError, match="version=2"):
        GemmaConfig(**{**TINY_V1, "attn_logit_softcapping": 50.0})


def test_v2_scan_needs_even_layers():
    with pytest.raises(ValueError, match="even"):
        GemmaConfig(**{**TINY_V2, "num_hidden_layers": 3})


def test_routing():
    assert model_class_for_hf({"model_type": "gemma"}).endswith("Gemma")
    assert model_class_for_hf({"model_type": "gemma2"}).endswith("Gemma")


# ------------------------------------------------------------ HF parity


def _hf_tiny_gemma1():
    torch = pytest.importorskip("torch")
    from transformers import GemmaConfig as HFGemmaConfig
    from transformers import GemmaForCausalLM

    hf_config = HFGemmaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=1,
        head_dim=16, max_position_embeddings=64,
        hidden_act="gelu_pytorch_tanh", hidden_activation="gelu_pytorch_tanh",
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    return GemmaForCausalLM(hf_config).eval(), hf_config


def _hf_tiny_gemma2():
    torch = pytest.importorskip("torch")
    from transformers import Gemma2Config as HFGemma2Config
    from transformers import Gemma2ForCausalLM

    hf_config = HFGemma2Config(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64,
        query_pre_attn_scalar=24,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        sliding_window=8,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    return Gemma2ForCausalLM(hf_config).eval(), hf_config


def test_logits_parity_with_hf_gemma1():
    torch = pytest.importorskip("torch")
    hf_model, hf_config = _hf_tiny_gemma1()
    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.version == 1
    params = params_from_hf(hf_model.state_dict(), cfg)
    model = Gemma(cfg)

    ids = np.random.default_rng(7).integers(0, 128, (2, 16))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


def test_logits_parity_with_hf_gemma2():
    torch = pytest.importorskip("torch")
    hf_model, hf_config = _hf_tiny_gemma2()
    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.version == 2
    assert cfg.attn_logit_softcapping == 50.0
    assert cfg.sliding_window == 8
    params = params_from_hf(hf_model.state_dict(), cfg)
    model = Gemma(cfg)

    # 24 > sliding_window so local attention actually truncates
    ids = np.random.default_rng(8).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


def test_gemma2_sliding_layers_are_even_indices():
    """HF gemma-2 applies the window on even layer indices; the scanned
    (sliding, full) pairing must agree with the HF per-layer layout."""
    torch = pytest.importorskip("torch")
    hf_model, hf_config = _hf_tiny_gemma2()
    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert [cfg.layer_sliding_window(i) for i in range(4)] == [8, None, 8, None]


# ------------------------------------------------------------ round trips


@pytest.mark.parametrize("tiny", [TINY_V1, TINY_V2], ids=["v1", "v2"])
def test_hf_round_trip(tiny):
    pytest.importorskip("torch")
    hf_model, hf_config = _hf_tiny_gemma1() if tiny is TINY_V1 else _hf_tiny_gemma2()
    cfg = config_from_hf(hf_config)
    params = params_from_hf(hf_model.state_dict(), cfg)
    back = params_to_hf(params, cfg)
    sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()
          if k != "lm_head.weight"}  # tied: HF materializes it, we never store it
    assert set(back) == set(sd)
    for key in sd:
        np.testing.assert_array_equal(back[key], sd[key], err_msg=key)


@pytest.mark.slow
def test_hf_causal_lm_loads_gemma2_checkpoint(tmp_path):
    """End-to-end: HF checkpoint dir -> HFCausalLM router -> Gemma module ->
    streamed weights -> logits parity (the reference's `HFCausalLM` wrapping
    of a Gemma checkpoint, `hf_causal_lm.py:22`)."""
    torch = pytest.importorskip("torch")
    from llm_training_tpu.models import HFCausalLM, HFCausalLMConfig
    from llm_training_tpu.models.hf_io import load_pretrained_params

    hf_model, _ = _hf_tiny_gemma2()
    hf_model.save_pretrained(tmp_path / "gemma2", safe_serialization=True)

    model = HFCausalLM(
        HFCausalLMConfig(hf_path=str(tmp_path / "gemma2"), compute_dtype="float32")
    )
    assert isinstance(model, Gemma)
    assert model.config.pre_trained_weights == str(tmp_path / "gemma2")
    params = load_pretrained_params(model.config, tmp_path / "gemma2")

    ids = np.random.default_rng(10).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(jax.tree.map(jnp.asarray, params), jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


def test_scan_and_loop_layers_agree_v2():
    """The paired scan layout must compute the same function as the plain
    per-layer loop (which follows HF layer order directly)."""
    pytest.importorskip("torch")
    hf_model, hf_config = _hf_tiny_gemma2()
    ids = jnp.asarray(np.random.default_rng(9).integers(0, 128, (2, 24)))

    cfg_scan = config_from_hf(hf_config, compute_dtype="float32", scan_layers=True)
    cfg_loop = config_from_hf(hf_config, compute_dtype="float32", scan_layers=False)
    out_scan = Gemma(cfg_scan).apply(params_from_hf(hf_model.state_dict(), cfg_scan), ids)
    out_loop = Gemma(cfg_loop).apply(params_from_hf(hf_model.state_dict(), cfg_loop), ids)
    np.testing.assert_allclose(out_scan.logits, out_loop.logits, rtol=2e-5, atol=1e-5)


def test_logits_parity_with_hf_gemma3():
    """Gemma3 text: per-head zero-centered qk-norm, the 5:1 layer_types
    sliding/full pattern, and DUAL rotary tables (local theta for sliding
    layers, scaled global theta for full layers)."""
    torch = pytest.importorskip("torch")
    from transformers import Gemma3TextConfig, Gemma3ForCausalLM

    hf_config = Gemma3TextConfig(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64,
        query_pre_attn_scalar=24, sliding_window=8,
        sliding_window_pattern=3,  # layers 0,1 sliding; 2 full; 3 sliding
        rope_theta=1000000.0, rope_local_base_freq=10000.0,
        rope_scaling={"rope_type": "linear", "factor": 8.0},
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = Gemma3ForCausalLM(hf_config).eval()
    sd = hf_model.state_dict()
    assert "model.layers.0.self_attn.q_norm.weight" in sd
    assert sd["model.layers.0.self_attn.q_norm.weight"].shape == (16,)  # per-head
    assert "model.layers.0.pre_feedforward_layernorm.weight" in sd

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.version == 3 and cfg.use_qk_norm
    assert cfg.layer_types == list(hf_config.layer_types)
    assert not cfg.scan_layers  # aperiodic pattern -> looped layers
    # the pattern must mix both kinds or the dual-rope path goes untested
    assert {"sliding_attention", "full_attention"} <= set(cfg.layer_types)
    params = params_from_hf(sd, cfg)
    model = Gemma(cfg)

    # 24 > sliding_window so local attention actually truncates
    ids = np.random.default_rng(9).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


def test_gemma3_export_guards():
    """gemma3_text exports must carry an explicit layer_types list (HF
    re-derives a 5:1 sliding pattern from null) and refuse qk-norm-off
    configs (HF builds the norms unconditionally)."""
    import pytest as _pytest

    from llm_training_tpu.models.gemma.hf_conversion import config_to_hf

    hf = config_to_hf(GemmaConfig(
        version=3, vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, query_pre_attn_scalar=24,
    ))
    assert hf["layer_types"] == ["full_attention"] * 2
    with _pytest.raises(ValueError, match="use_qk_norm"):
        config_to_hf(GemmaConfig(
            version=3, vocab_size=128, hidden_size=64, intermediate_size=112,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            head_dim=16, use_qk_norm=False,
        ))


def test_clm_fused_loss_applies_final_softcap():
    """The CLM fused-CE path must apply Gemma-2's final_logit_softcapping —
    the loss computed without logits must equal CE over the (capped)
    compute_logits output."""
    from llm_training_tpu.lms import CLM, CLMConfig

    cfg = GemmaConfig(
        version=2, vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64, query_pre_attn_scalar=24,
        final_logit_softcapping=5.0, compute_dtype="float32",
    )
    model = Gemma(cfg)
    ids = jnp.asarray(np.random.default_rng(21).integers(1, 128, (2, 16)))
    params = model.init(jax.random.key(6), ids)

    objective = CLM(CLMConfig(), model=model)
    loss, _ = objective.loss_and_metrics(params, {"input_ids": ids}, train=False)

    logits = model.apply(params, ids).logits  # capped by compute_logits
    shifted = np.full(ids.shape, -100)
    shifted[:, :-1] = np.asarray(ids)[:, 1:]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    rows = []
    for b in range(ids.shape[0]):
        for t in range(ids.shape[1] - 1):
            rows.append(-logp[b, t, shifted[b, t]])
    np.testing.assert_allclose(float(loss), np.mean(rows), rtol=1e-5)
