"""Checkpoint save/restore/resume determinism + CLI fit/validate."""

import jax
import numpy as np
import pytest
import yaml

from llm_training_tpu.data import DummyDataModule, DummyDataModuleConfig
from llm_training_tpu.lms import CLM, CLMConfig, ModelProvider
from llm_training_tpu.optim import OptimConfig
from llm_training_tpu.trainer import Trainer, TrainerConfig
from llm_training_tpu.trainer.checkpoint import CheckpointConfig, Checkpointer

TINY_MODEL = dict(
    model_class="llm_training_tpu.models.Llama",
    model_kwargs=dict(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, compute_dtype="float32",
    ),
)


def _objective():
    # constant schedule: the cosine schedule depends on num_total_steps, so a
    # 5-step and a 10-step run would legitimately differ at steps 1-5
    return CLM(
        CLMConfig(
            model=ModelProvider(**TINY_MODEL),
            optim=OptimConfig(learning_rate=1e-3, warmup_steps=2, lr_scheduler="constant"),
        )
    )


def _data():
    return DummyDataModule(
        DummyDataModuleConfig(batch_size=8, max_length=64, num_samples=64,
                              vocab_size=256, validation_split=8)
    )


class _Rec:
    def __init__(self):
        self.losses = {}

    def on_step_end(self, trainer, step, metrics):
        self.losses[step] = float(metrics["loss"])


@pytest.mark.slow
def test_save_resume_matches_uninterrupted(devices, tmp_path):
    # straight 10-step run
    rec_full = _Rec()
    trainer = Trainer(
        TrainerConfig(max_steps=10, log_every_n_steps=1),
        callbacks=[rec_full],
        checkpointer=Checkpointer(
            CheckpointConfig(dirpath=str(tmp_path / "full"), async_save=False)
        ),
    )
    trainer.fit(_objective(), _data())
    full_counters = dict(trainer.counters)

    # interrupted at 5 + resumed
    rec_a = _Rec()
    ckpt_dir = str(tmp_path / "resume")
    t1 = Trainer(
        TrainerConfig(max_steps=5, log_every_n_steps=1, checkpoint_every_n_steps=5),
        callbacks=[rec_a],
        checkpointer=Checkpointer(CheckpointConfig(dirpath=ckpt_dir, async_save=False)),
    )
    t1.fit(_objective(), _data())

    rec_b = _Rec()
    t2 = Trainer(
        TrainerConfig(max_steps=10, log_every_n_steps=1),
        callbacks=[rec_b],
        checkpointer=Checkpointer(CheckpointConfig(dirpath=ckpt_dir, async_save=False)),
    )
    t2.fit(_objective(), _data())

    # steps 6..10 of the resumed run match the uninterrupted run exactly
    for step in range(6, 11):
        np.testing.assert_allclose(
            rec_b.losses[step], rec_full.losses[step], rtol=1e-6,
            err_msg=f"step {step}",
        )
    assert t2.counters == full_counters


@pytest.mark.slow
def test_cross_topology_resume(devices, tmp_path):
    """VERDICT r3 #8 (reference DCP restore, fsdp2_strategy.py:395-409):
    a checkpoint written on a {fsdp:4, tensor:2} mesh must restore onto a
    pure {fsdp:8} mesh — orbax reshards against the new target shardings —
    and continue EXACTLY like a same-topology resume.

    This is the EXPLICIT model-axis reshard path (the user changed the
    mesh config on purpose). The elastic planner (resilience/elastic.py,
    `trainer.resilience.elastic`) deliberately refuses to do this
    implicitly — it pins model axes to the checkpoint's degrees and scales
    only `data`; tests/test_elastic.py covers that path. De-flake history:
    the original rtol=1e-6 straddled the cross-mesh fp32 reduction-order
    noise floor (missed by ~1.1e-6); PR 4 widened it to the justified
    5e-5 bound below."""
    from llm_training_tpu.parallel import MeshConfig

    ckpt_dir = str(tmp_path / "xtopo")
    mesh_a = MeshConfig(fsdp_size=4, tensor_parallel_size=2)
    t1 = Trainer(
        TrainerConfig(max_steps=5, log_every_n_steps=1,
                      checkpoint_every_n_steps=5, mesh=mesh_a),
        checkpointer=Checkpointer(
            CheckpointConfig(dirpath=ckpt_dir, async_save=False)
        ),
    )
    t1.fit(_objective(), _data())

    # each resume gets its own COPY of the step-5 checkpoint so neither
    # run's later saves can shadow the restore point of the other
    import shutil

    dir_same, dir_x = str(tmp_path / "same"), str(tmp_path / "cross")
    shutil.copytree(ckpt_dir, dir_same)
    shutil.copytree(ckpt_dir, dir_x)

    # reference run: same topology throughout
    rec_same = _Rec()
    t_same = Trainer(
        TrainerConfig(max_steps=10, log_every_n_steps=1, mesh=mesh_a),
        callbacks=[rec_same],
        checkpointer=Checkpointer(
            CheckpointConfig(dirpath=dir_same, async_save=False)
        ),
    )
    t_same.fit(_objective(), _data())

    # cross-topology resume: restore the same step-5 checkpoint on fsdp:8
    rec_x = _Rec()
    t2 = Trainer(
        TrainerConfig(max_steps=10, log_every_n_steps=1,
                      mesh=MeshConfig(fsdp_size=8)),
        callbacks=[rec_x],
        checkpointer=Checkpointer(
            CheckpointConfig(dirpath=dir_x, async_save=False)
        ),
    )
    state = t2.fit(_objective(), _data())

    assert int(jax.device_get(state.step)) == 10
    for step in range(6, 11):
        # rtol bound: the two resumes run on DIFFERENT meshes ({fsdp:4,
        # tensor:2} vs {fsdp:8}), so GSPMD legitimately reorders the
        # gradient/loss reductions — fp32 sum-order noise of ~1e-7/step
        # compounds through 5 optimizer steps to the low 1e-6s, which
        # straddled the old rtol=1e-6 and flaked. 5e-5 is ~50x that noise
        # floor yet far below any real restore bug (a resharding error
        # shows up as O(1) divergence within a step or two).
        np.testing.assert_allclose(
            rec_x.losses[step], rec_same.losses[step], rtol=5e-5,
            err_msg=f"step {step}",
        )
    # and the restored params really live on the new mesh
    leaf = jax.tree.leaves(state.params)[0]
    assert leaf.sharding.mesh.shape["fsdp"] == 8


@pytest.mark.slow
def test_validate_from_checkpoint(devices, tmp_path):
    ckpt_dir = str(tmp_path / "v")
    trainer = Trainer(
        TrainerConfig(max_steps=3, log_every_n_steps=1),
        checkpointer=Checkpointer(CheckpointConfig(dirpath=ckpt_dir, async_save=False)),
    )
    trainer.fit(_objective(), _data())

    t2 = Trainer(
        TrainerConfig(max_steps=3),
        checkpointer=Checkpointer(CheckpointConfig(dirpath=ckpt_dir, async_save=False)),
    )
    result = t2.validate_from_checkpoint(_objective(), _data())
    assert np.isfinite(result["val_loss"])


@pytest.mark.slow
def test_checkpoint_embeds_config(devices, tmp_path):
    ckpt_dir = str(tmp_path / "c")
    run_config = {"model": {"class_path": "llm_training_tpu.lms.CLM"}, "note": "hi"}
    trainer = Trainer(
        TrainerConfig(max_steps=2, checkpoint_every_n_steps=2),
        checkpointer=Checkpointer(
            CheckpointConfig(dirpath=ckpt_dir, async_save=False), run_config=run_config
        ),
    )
    trainer.fit(_objective(), _data())
    import orbax.checkpoint as ocp

    with ocp.CheckpointManager(ckpt_dir, item_names=("state", "meta")) as m:
        meta = m.restore(m.latest_step(), args=ocp.args.Composite(meta=ocp.args.JsonRestore()))
    assert meta["meta"]["config"] == run_config
    assert meta["meta"]["counters"]["consumed_samples"] == 2 * 8


# ---------------------------------------------------------------- CLI


def _write_config(tmp_path, **extra):
    config = {
        "seed_everything": 7,
        "trainer": {
            "max_steps": 3,
            "log_every_n_steps": 1,
            "checkpoint": {"dirpath": str(tmp_path / "ckpt"), "async_save": False},
        },
        "model": {
            "class_path": "llm_training_tpu.lms.CLM",
            "init_args": {
                "model": TINY_MODEL,
                "optim": {"learning_rate": 1e-3},
            },
        },
        "data": {
            "class_path": "llm_training_tpu.data.DummyDataModule",
            "init_args": {
                "batch_size": 8, "max_length": 64, "num_samples": 32,
                "vocab_size": 256, "validation_split": 8,
            },
        },
        **extra,
    }
    path = tmp_path / "config.yaml"
    path.write_text(yaml.safe_dump(config))
    return path


@pytest.mark.slow
def test_cli_fit_and_validate(devices, tmp_path, capsys):
    from llm_training_tpu.cli.main import main

    config_path = _write_config(tmp_path)
    assert main(["fit", "--config", str(config_path)]) == 0
    assert (tmp_path / "ckpt").exists()
    assert main(["validate", "--config", str(config_path)]) == 0


def test_cli_overrides(tmp_path):
    from llm_training_tpu.cli.config import load_config

    config_path = _write_config(tmp_path)
    config = load_config(str(config_path), ["trainer.max_steps=7", "seed_everything=1"])
    assert config["trainer"]["max_steps"] == 7
    assert config["seed_everything"] == 1


def test_config_interpolation(tmp_path):
    from llm_training_tpu.cli.config import load_config

    path = tmp_path / "i.yaml"
    path.write_text(yaml.safe_dump({
        "base": {"vocab": 256},
        "model": {"vocab_size": "${base.vocab}", "name": "v${base.vocab}-model"},
    }))
    config = load_config(str(path))
    assert config["model"]["vocab_size"] == 256      # type-preserving
    assert config["model"]["name"] == "v256-model"   # string substitution
