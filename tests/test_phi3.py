"""Phi-3: HF logits parity (incl. fused qkv/gate_up split), sliding window,
longrope factor defaulting."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_training_tpu.models import Phi3, Phi3Config
from llm_training_tpu.models.phi3.hf_conversion import (
    config_from_hf,
    params_from_hf,
    params_to_hf,
)

TINY = dict(
    vocab_size=160,
    hidden_size=64,
    intermediate_size=96,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    max_position_embeddings=64,
)


def _hf_tiny_phi3(**kwargs):
    torch = pytest.importorskip("torch")
    from transformers import Phi3Config as HFPhi3Config, Phi3ForCausalLM

    hf_config = HFPhi3Config(
        **TINY,
        attn_implementation="eager",
        bos_token_id=1, eos_token_id=2, pad_token_id=0,
        **kwargs,
    )
    torch.manual_seed(0)
    return Phi3ForCausalLM(hf_config).eval(), hf_config


def test_logits_parity_with_hf():
    torch = pytest.importorskip("torch")
    hf_model, hf_config = _hf_tiny_phi3()
    cfg = config_from_hf(hf_config, compute_dtype="float32")
    params = params_from_hf(hf_model.state_dict(), cfg)
    model = Phi3(cfg)

    ids = np.random.default_rng(0).integers(0, TINY["vocab_size"], (2, 12))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


def test_hf_round_trip_fused():
    hf_model, hf_config = _hf_tiny_phi3()
    cfg = config_from_hf(hf_config, compute_dtype="float32")
    params = params_from_hf(hf_model.state_dict(), cfg)
    back = params_to_hf(params, cfg)
    sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    assert set(back) == set(sd)
    for key in sd:
        np.testing.assert_array_equal(back[key], sd[key], err_msg=key)


@pytest.mark.slow
def test_sliding_window_changes_output():
    cfg_full = Phi3Config(**TINY, compute_dtype="float32")
    cfg_win = Phi3Config(**TINY, compute_dtype="float32", sliding_window=4)
    ids = jnp.asarray(np.random.default_rng(1).integers(0, 160, (1, 16)))
    model = Phi3(cfg_full)
    params = model.init(jax.random.key(0), ids)
    out_full = model.apply(params, ids)
    out_win = Phi3(cfg_win).apply(params, ids)
    # early positions (< window) identical, late positions differ
    np.testing.assert_allclose(out_full.logits[:, :4], out_win.logits[:, :4], rtol=1e-5)
    assert np.abs(np.asarray(out_full.logits[:, -1]) - np.asarray(out_win.logits[:, -1])).max() > 1e-3


def test_longrope_factor_defaulting():
    dim = (TINY["hidden_size"] // TINY["num_attention_heads"]) // 2
    cfg = Phi3Config(
        **{**TINY, "max_position_embeddings": 8192},
        original_max_position_embeddings=64,
        rope_scaling={
            "rope_type": "longrope",
            "short_factor": [1.0] * dim,
            "long_factor": [4.0] * dim,
        },
    )
    rope = cfg.rope_config
    assert rope.type == "longrope"
    assert rope.scaling["factor"] == 8192 / 64
    assert rope.max_position_embeddings == 64  # frequencies against original window

    with pytest.raises(ValueError, match="original_max_position_embeddings"):
        Phi3Config(
            **TINY,
            rope_scaling={
                "rope_type": "longrope",
                "short_factor": [1.0] * dim,
                "long_factor": [4.0] * dim,
            },
        )


def test_longrope_short_long_parity_with_hf():
    """HF selects short_factor for seq <= original_max and long_factor above;
    our seq_len-aware frequency computation must match both regimes."""
    torch = pytest.importorskip("torch")
    dim = (TINY["hidden_size"] // TINY["num_attention_heads"]) // 2
    rope_scaling = {  # HF Phi3Config validator wants the legacy 'type' key
        "type": "longrope",
        "short_factor": [1.0 + 0.05 * i for i in range(dim)],
        "long_factor": [2.0 + 0.1 * i for i in range(dim)],
    }
    hf_model, hf_config = _hf_tiny_phi3(  # TINY already has max_position=64
        original_max_position_embeddings=16,
        rope_scaling=rope_scaling,
    )
    cfg = config_from_hf(hf_config, compute_dtype="float32")
    params = params_from_hf(hf_model.state_dict(), cfg)
    model = Phi3(cfg)

    for seq in (12, 32):  # short regime (<=16) and long regime (>16)
        ids = np.random.default_rng(seq).integers(0, TINY["vocab_size"], (1, seq))
        with torch.no_grad():
            hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
        ours = model.apply(params, jnp.asarray(ids)).logits
        np.testing.assert_allclose(
            np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4,
            err_msg=f"seq={seq}",
        )


@pytest.mark.slow
def test_attention_compute_dtype():
    cfg = Phi3Config(**TINY, compute_dtype="bfloat16", attention_compute_dtype="float32")
    ids = jnp.ones((1, 8), jnp.int32)
    model = Phi3(cfg)
    params = model.init(jax.random.key(0), ids)
    out = model.apply(params, ids)
    assert out.logits.dtype == jnp.bfloat16  # cast back after attention
