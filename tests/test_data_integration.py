"""Data pipeline -> trainer integration + offline pre-processing round trip."""

import sys
from pathlib import Path

import numpy as np
import pytest

from data_fixtures import text_dataset, tiny_tokenizer
from llm_training_tpu.data.pre_training import (
    PreTrainingDataModule,
    PreTrainingDataModuleConfig,
)
from llm_training_tpu.lms import CLM, CLMConfig, ModelProvider
from llm_training_tpu.optim import OptimConfig
from llm_training_tpu.trainer import Trainer, TrainerConfig


def _module(**kwargs):
    module = PreTrainingDataModule(
        PreTrainingDataModuleConfig(
            tokenizer=tiny_tokenizer(),
            max_length=32,
            batch_size=8,
            enable_cache=False,
            pad_to_multiple_of=32,
            **kwargs,
        )
    )
    module.load_data = lambda: text_dataset(n_per_source=40)
    return module


@pytest.mark.slow
def test_packed_pretraining_trains(devices):
    datamodule = _module()
    objective = CLM(
        CLMConfig(
            model=ModelProvider(
                model_class="llm_training_tpu.models.Llama",
                model_kwargs=dict(
                    vocab_size=512, hidden_size=32, intermediate_size=64,
                    num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
                    max_position_embeddings=64, compute_dtype="float32",
                ),
            ),
            optim=OptimConfig(learning_rate=1e-3, lr_scheduler="constant"),
        )
    )
    trainer = Trainer(TrainerConfig(max_steps=3, log_every_n_steps=1))
    state = trainer.fit(objective, datamodule)
    assert int(np.asarray(state.step)) == 3
    # consumed_tokens counted only non-padding positions
    assert 0 < trainer.counters["consumed_tokens"] <= 3 * 8 * 32


def test_save_and_reload_preprocessed(tmp_path):
    module = _module()
    module.setup()
    module.save_pre_processed_data(str(tmp_path / "prep"))

    module2 = _module(pre_processed_data_path=str(tmp_path / "prep"))
    module2.load_data = lambda: (_ for _ in ()).throw(AssertionError("must not re-load"))
    module2.setup()
    assert len(module2.train_dataset) == len(module.train_dataset)
    np.testing.assert_array_equal(
        module2.train_dataset[0]["input_ids"], module.train_dataset[0]["input_ids"]
    )


def test_pre_process_script(tmp_path):
    """Run scripts/pre_process_data.py main() end-to-end with real files."""
    import yaml

    tiny_tokenizer().save_pretrained(str(tmp_path / "tokenizer"))
    text_dataset(n_per_source=20)["train"].save_to_disk(str(tmp_path / "raw"))
    arrow = next((tmp_path / "raw").glob("*.arrow"))

    out = tmp_path / "prep2"
    config = {
        "data": {
            "class_path": "llm_training_tpu.data.PreTrainingDataModule",
            "init_args": {
                "tokenizer": str(tmp_path / "tokenizer"),
                "dataset_kwargs": {"path": "arrow", "data_files": str(arrow)},
                "max_length": 32,
                "batch_size": 4,
                "enable_cache": False,
                "pre_processed_data_path": str(out),
            },
        }
    }
    config_path = tmp_path / "run.yaml"
    config_path.write_text(yaml.safe_dump(config))

    sys.path.insert(0, str(Path(__file__).parent.parent / "scripts"))
    import pre_process_data

    assert pre_process_data.main(["--config", str(config_path), "--num-proc", "1"]) == 0
    assert (out / "info.txt").exists()
    assert "wiki" in (out / "info.txt").read_text()

    # and the saved data round-trips into a fresh module
    module = _module(pre_processed_data_path=str(out))
    module.load_data = lambda: (_ for _ in ()).throw(AssertionError("must not re-load"))
    module.setup()
    assert len(module.train_dataset) > 0
