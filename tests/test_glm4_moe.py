"""GLM-4.5 (glm4_moe): GQA + DeepSeek-V3-style noaux MoE, HF parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_training_tpu.models.glm4_moe import Glm4Moe, Glm4MoeConfig
from llm_training_tpu.models.glm4_moe.hf_conversion import (
    config_from_hf,
    config_to_hf,
    params_from_hf,
    params_to_hf,
)

TINY = dict(
    vocab_size=128,
    hidden_size=64,
    intermediate_size=112,
    moe_intermediate_size=32,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    head_dim=16,
    max_position_embeddings=64,
    n_routed_experts=8,
    n_shared_experts=1,
    num_experts_per_tok=2,
    first_k_dense_replace=1,
    n_group=4,
    topk_group=2,
    routed_scaling_factor=1.5,
    compute_dtype="float32",
)


def _hf_tiny(**extra):
    torch = pytest.importorskip("torch")
    from transformers import Glm4MoeConfig as HFConfig
    from transformers import Glm4MoeForCausalLM

    kwargs = dict(TINY)
    kwargs.pop("compute_dtype")
    kwargs.update(attn_implementation="eager", **extra)
    hf_config = HFConfig(**kwargs)
    torch.manual_seed(0)
    return Glm4MoeForCausalLM(hf_config).eval(), hf_config


@pytest.mark.parametrize("use_qk_norm,attention_bias",
                         [(False, False), (True, True)])
def test_logits_parity_with_hf(use_qk_norm, attention_bias):
    """GQA with partial (half-rotation) rotary + the V3-style sigmoid
    router with a LIVE noaux bias; layer 0 dense, layer 1 MoE with shared
    expert."""
    torch = pytest.importorskip("torch")
    # attention_bias=True mirrors the released GLM-4.5 checkpoints:
    # q/k/v biased, o_proj bias-free
    hf_model, hf_config = _hf_tiny(
        use_qk_norm=use_qk_norm, attention_bias=attention_bias
    )
    sd = hf_model.state_dict()
    assert "model.layers.1.mlp.gate.e_score_correction_bias" in sd
    assert "model.layers.0.mlp.gate_proj.weight" in sd  # dense prefix
    if attention_bias:
        assert "model.layers.0.self_attn.q_proj.bias" in sd
        assert "model.layers.0.self_attn.o_proj.bias" not in sd
    with torch.no_grad():
        sd["model.layers.1.mlp.gate.e_score_correction_bias"].copy_(
            torch.linspace(-0.2, 0.2, 8)
        )

    cfg = config_from_hf(hf_config, compute_dtype="float32", moe_impl="dense")
    assert cfg.use_qk_norm == use_qk_norm and cfg.routed_scaling_factor == 1.5
    params = params_from_hf(sd, cfg)
    model = Glm4Moe(cfg)

    ids = np.random.default_rng(95).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=4e-4, atol=4e-4)


def test_hf_round_trip():
    hf_model, hf_config = _hf_tiny(use_qk_norm=True)
    cfg = config_from_hf(hf_config)
    params = params_from_hf(hf_model.state_dict(), cfg)
    back = params_to_hf(params, cfg)
    sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    assert set(back) == set(sd)
    for key in sd:
        np.testing.assert_array_equal(back[key], sd[key], err_msg=key)


def test_config_round_trip():
    cfg = Glm4MoeConfig(**TINY)
    hf = config_to_hf(cfg)
    assert hf["model_type"] == "glm4_moe"
    cfg2 = config_from_hf(hf, compute_dtype="float32")
    assert cfg2.model_dump() == cfg.model_dump()


@pytest.mark.slow
def test_e2e_fit_decreases_loss():
    from conftest import fit_losses

    losses = fit_losses(
        "llm_training_tpu.models.Glm4Moe",
        dict(TINY, enable_gradient_checkpointing=True, moe_impl="dense"),
        max_steps=20, lr=3e-3,
    )
    assert np.mean(losses[-3:]) < np.mean(losses[:3])


def test_logits_parity_with_hf_dots1():
    """dots1 routes to the Glm4Moe module: the same V3-style noaux MoE with
    full-rotary attention, ALWAYS-ON per-head qk-norm, one bias flag
    covering o_proj too, and a qwen2-style per-layer sliding pattern."""
    torch = pytest.importorskip("torch")
    from transformers import Dots1Config, Dots1ForCausalLM

    kwargs = dict(TINY)
    kwargs.pop("compute_dtype")
    hf_config = Dots1Config(
        **kwargs, attention_bias=True, sliding_window=8,
        max_window_layers=1,  # layer 0 full, layer 1 sliding
        attn_implementation="eager",
    )
    assert hf_config.layer_types == ["full_attention", "sliding_attention"]
    torch.manual_seed(0)
    hf_model = Dots1ForCausalLM(hf_config).eval()
    sd = hf_model.state_dict()
    assert "model.layers.0.self_attn.o_proj.bias" in sd
    assert "model.layers.0.self_attn.q_norm.weight" in sd
    # salt zero-init biases + the noaux bias so both are LIVE
    with torch.no_grad():
        for k, v in sd.items():
            if k.endswith(".bias"):
                v.copy_(torch.linspace(-0.2, 0.2, v.numel()))

    cfg = config_from_hf(hf_config, compute_dtype="float32", moe_impl="dense")
    assert cfg.hf_flavor == "dots1" and cfg.use_qk_norm
    assert cfg.partial_rotary_factor == 1.0 and cfg.attention_out_bias
    assert cfg.layer_types == ["full_attention", "sliding_attention"]
    # the MoE suffix (layer 1) is uniformly sliding, so it still scans;
    # only a MIXED suffix forces the loop
    assert cfg.num_scanned_layers == 1
    params = params_from_hf(sd, cfg)
    model = Glm4Moe(cfg)

    ids = np.random.default_rng(60).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=3e-4, atol=3e-4)


def test_dots1_config_round_trip():
    cfg = Glm4MoeConfig(
        **{**TINY, "partial_rotary_factor": 1.0}, use_qk_norm=True,
        attention_bias=True, attention_out_bias=True,
        sliding_window=8, layer_types=["full_attention", "sliding_attention"],
        hf_flavor="dots1",
    )
    hf = config_to_hf(cfg)
    assert hf["model_type"] == "dots1"
    cfg2 = config_from_hf(hf, compute_dtype="float32")
    assert cfg2.model_dump() == cfg.model_dump()


def test_glm4_moe_export_refuses_dots_features():
    cfg = Glm4MoeConfig(**TINY, sliding_window=8,
                        layer_types=["sliding_attention", "sliding_attention"])
    with pytest.raises(ValueError, match="dots1"):
        config_to_hf(cfg)
