"""Llama model: shapes, scan/remat invariance, tied embeddings, and logits
parity against HF transformers' torch implementation on a tiny config."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_training_tpu.models import Llama, LlamaConfig
from llm_training_tpu.models.llama.hf_conversion import (
    config_from_hf,
    config_to_hf,
    params_from_hf,
    params_to_hf,
)

TINY = dict(
    vocab_size=128,
    hidden_size=64,
    intermediate_size=112,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    max_position_embeddings=64,
    compute_dtype="float32",
)


def _init_and_run(cfg, ids, **kwargs):
    model = Llama(cfg)
    params = model.init(jax.random.key(0), ids)
    return model.apply(params, ids, **kwargs), params


@pytest.mark.slow
def test_forward_shapes_and_dtypes():
    cfg = LlamaConfig(**TINY)
    ids = jnp.ones((2, 10), jnp.int32)
    out, _ = _init_and_run(cfg, ids, return_last_hidden_states=True)
    assert out.logits.shape == (2, 10, 128)
    assert out.last_hidden_states.shape == (2, 10, 64)


@pytest.mark.slow
def test_hidden_only_forward():
    cfg = LlamaConfig(**TINY)
    ids = jnp.ones((2, 10), jnp.int32)
    out, _ = _init_and_run(cfg, ids, compute_logits=False, return_last_hidden_states=True)
    assert out.logits is None
    assert out.last_hidden_states is not None


@pytest.mark.slow
def test_scan_and_loop_layers_agree():
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 128, (2, 12)))
    cfg_scan = LlamaConfig(**TINY, scan_layers=True)
    model_scan = Llama(cfg_scan)
    params_scan = model_scan.init(jax.random.key(0), ids)

    # restack scanned params into per-layer trees for the loop model
    hf_sd = params_to_hf(jax.tree.map(lambda x: x, params_scan["params"]), cfg_scan)
    cfg_loop = LlamaConfig(**TINY, scan_layers=False)
    params_loop = params_from_hf(hf_sd, cfg_loop)

    out_scan = model_scan.apply(params_scan, ids)
    out_loop = Llama(cfg_loop).apply(params_loop, ids)
    np.testing.assert_allclose(out_scan.logits, out_loop.logits, rtol=2e-5, atol=1e-5)


@pytest.mark.parametrize("granularity", ["full", "selective"])
@pytest.mark.slow
def test_remat_matches_no_remat(granularity):
    ids = jnp.asarray(np.random.default_rng(1).integers(0, 128, (1, 8)))
    cfg = LlamaConfig(**TINY)
    model = Llama(cfg)
    params = model.init(jax.random.key(0), ids)

    cfg_remat = LlamaConfig(
        **TINY, enable_gradient_checkpointing=True, recompute_granularity=granularity
    )
    model_remat = Llama(cfg_remat)

    def loss(m, p):
        return m.apply(p, ids).logits.astype(jnp.float32).sum()

    np.testing.assert_allclose(loss(model, params), loss(model_remat, params), rtol=1e-6)
    g1 = jax.grad(lambda p: loss(model, p))(params)
    g2 = jax.grad(lambda p: loss(model_remat, p))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5), g1, g2
    )


@pytest.mark.slow
def test_tied_embeddings():
    cfg = LlamaConfig(**{**TINY, "tie_word_embeddings": True})
    ids = jnp.ones((1, 4), jnp.int32)
    model = Llama(cfg)
    params = model.init(jax.random.key(0), ids)
    flat = jax.tree_util.tree_leaves_with_path(params)
    names = [jax.tree_util.keystr(p) for p, _ in flat]
    assert not any("lm_head" in n for n in names)
    out = model.apply(params, ids)
    assert out.logits.shape == (1, 4, 128)


@pytest.mark.slow
def test_packed_forward_matches_separate_docs():
    """End-to-end (full model) packing parity: one packed row with segment ids
    == two separate unpadded forwards."""
    rng = np.random.default_rng(2)
    cfg = LlamaConfig(**TINY)
    model = Llama(cfg)
    doc_a = rng.integers(1, 128, 5)
    doc_b = rng.integers(1, 128, 7)
    packed = jnp.asarray(np.concatenate([doc_a, doc_b])[None])
    segment_ids = jnp.asarray([[1] * 5 + [2] * 7])
    position_ids = jnp.asarray([list(range(5)) + list(range(7))])
    params = model.init(jax.random.key(0), packed)

    out = model.apply(params, packed, segment_ids=segment_ids, position_ids=position_ids)
    out_a = model.apply(params, jnp.asarray(doc_a[None]))
    out_b = model.apply(params, jnp.asarray(doc_b[None]))
    np.testing.assert_allclose(out.logits[0, :5], out_a.logits[0], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(out.logits[0, 5:], out_b.logits[0], rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------- HF parity


def _hf_tiny_llama(rope_scaling=None, tie=False):
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig as HFLlamaConfig, LlamaForCausalLM

    hf_config = HFLlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=112,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64 if rope_scaling is None else 131072,
        rope_scaling=rope_scaling,
        tie_word_embeddings=tie,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    return LlamaForCausalLM(hf_config).eval(), hf_config


@pytest.mark.parametrize(
    "rope_scaling",
    [
        None,
        {"rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
         "high_freq_factor": 4.0, "original_max_position_embeddings": 8192},
    ],
)
def test_logits_parity_with_hf(rope_scaling):
    torch = pytest.importorskip("torch")
    hf_model, hf_config = _hf_tiny_llama(rope_scaling)
    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.rope_config.type == ("default" if rope_scaling is None else "llama3")

    params = params_from_hf(hf_model.state_dict(), cfg)
    model = Llama(cfg)

    ids = np.random.default_rng(3).integers(0, 128, (2, 16))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


def test_hf_round_trip():
    hf_model, hf_config = _hf_tiny_llama()
    cfg = config_from_hf(hf_config)
    params = params_from_hf(hf_model.state_dict(), cfg)
    back = params_to_hf(params, cfg)
    sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    assert set(back) == set(sd)
    for key in sd:
        np.testing.assert_array_equal(back[key], sd[key], err_msg=key)


# -------------------------------------------- sibling architectures (routing)


def test_logits_parity_with_hf_mistral():
    """Mistral routes to the Llama module (sliding window + GQA + SwiGLU)."""
    torch = pytest.importorskip("torch")
    from transformers import MistralConfig, MistralForCausalLM

    hf_config = MistralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, sliding_window=8,
    )
    torch.manual_seed(0)
    hf_model = MistralForCausalLM(hf_config).eval()
    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.sliding_window == 8
    params = params_from_hf(hf_model.state_dict(), cfg)
    model = Llama(cfg)

    ids = np.random.default_rng(4).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


def test_logits_parity_with_hf_qwen2():
    """Qwen2 routes to the Llama module; its q/k/v projections carry biases
    while o_proj does not — the asymmetry must survive conversion."""
    torch = pytest.importorskip("torch")
    from transformers import Qwen2Config, Qwen2ForCausalLM

    hf_config = Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64,
    )
    torch.manual_seed(0)
    hf_model = Qwen2ForCausalLM(hf_config).eval()
    # qwen2 really has the asymmetric bias layout
    sd = hf_model.state_dict()
    assert "model.layers.0.self_attn.q_proj.bias" in sd
    assert "model.layers.0.self_attn.o_proj.bias" not in sd

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    params = params_from_hf(sd, cfg)
    model = Llama(cfg)

    ids = np.random.default_rng(5).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


def test_logits_parity_with_hf_qwen3():
    """Qwen3 routes to the Llama module; its per-head q/k RMSNorm (over
    head_dim, before RoPE) must be applied and its weights converted."""
    torch = pytest.importorskip("torch")
    from transformers import Qwen3Config, Qwen3ForCausalLM

    hf_config = Qwen3Config(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = Qwen3ForCausalLM(hf_config).eval()
    sd = hf_model.state_dict()
    assert "model.layers.0.self_attn.q_norm.weight" in sd
    assert "model.layers.0.self_attn.q_proj.bias" not in sd

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.qk_norm and not cfg.attention_bias
    params = params_from_hf(sd, cfg)
    model = Llama(cfg)

    ids = np.random.default_rng(6).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


def test_logits_parity_with_hf_olmo2():
    """OLMo-2 routes to the Llama module with post-norm blocks (no input
    norms; block outputs normed into the residual) and a FULL-width qk-norm
    applied before the head reshape."""
    torch = pytest.importorskip("torch")
    from transformers import Olmo2Config, Olmo2ForCausalLM

    hf_config = Olmo2Config(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = Olmo2ForCausalLM(hf_config).eval()
    sd = hf_model.state_dict()
    assert "model.layers.0.self_attn.q_norm.weight" in sd
    assert "model.layers.0.post_feedforward_layernorm.weight" in sd
    assert "model.layers.0.input_layernorm.weight" not in sd
    # full-width: the norm spans all heads, not one head_dim
    assert sd["model.layers.0.self_attn.q_norm.weight"].shape == (64,)

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.norm_scheme == "post" and cfg.qk_norm_scope == "full"
    params = params_from_hf(sd, cfg)
    model = Llama(cfg)

    ids = np.random.default_rng(12).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_qwen3_export_round_trip(tmp_path):
    """Export a qk_norm model -> HF reloads it as Qwen3 with matching
    logits (the norm weights must survive both directions)."""
    torch = pytest.importorskip("torch")
    from transformers import AutoModelForCausalLM

    from llm_training_tpu.models.hf_io import save_hf_checkpoint

    cfg = LlamaConfig(**TINY, qk_norm=True, head_dim=16)
    model = Llama(cfg)
    ids = jnp.asarray(np.random.default_rng(11).integers(0, 128, (2, 16)))
    params = model.init(jax.random.key(2), ids)
    out_dir = save_hf_checkpoint(params, cfg, tmp_path / "export", dtype="float32")

    hf_model = AutoModelForCausalLM.from_pretrained(out_dir, attn_implementation="eager").eval()
    assert type(hf_model).__name__ == "Qwen3ForCausalLM"
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(np.asarray(ids))).logits.numpy()
    ours = model.apply(params, ids).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


def test_qwen2_export_round_trip(tmp_path):
    """Exporting a Qwen2-derived config must produce a checkpoint that
    transformers loads with NO missing keys (asymmetric bias preserved)."""
    torch = pytest.importorskip("torch")
    from transformers import AutoModelForCausalLM, Qwen2Config, Qwen2ForCausalLM

    from llm_training_tpu.models.hf_io import save_hf_checkpoint

    hf_config = Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64,
    )
    torch.manual_seed(0)
    hf_model = Qwen2ForCausalLM(hf_config).eval()
    cfg = config_from_hf(hf_config, compute_dtype="float32")
    params = params_from_hf(hf_model.state_dict(), cfg)

    out = save_hf_checkpoint(params, cfg, tmp_path / "export", dtype="float32")
    reloaded = AutoModelForCausalLM.from_pretrained(out).eval()
    assert reloaded.config.model_type == "qwen2"

    ids = np.random.default_rng(6).integers(0, 128, (1, 16))
    with torch.no_grad():
        a = hf_model(torch.tensor(ids)).logits.numpy()
        b = reloaded(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(b, a, rtol=1e-5, atol=1e-5)


def test_logits_parity_with_hf_granite():
    """Granite routes to the Llama module with four scalar multipliers:
    embeddings scaled into the residual stream, a config attention scale
    replacing 1/sqrt(head_dim), block outputs scaled before the residual
    add, and logits divided by logits_scaling."""
    torch = pytest.importorskip("torch")
    from transformers import GraniteConfig, GraniteForCausalLM

    hf_config = GraniteConfig(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64,
        embedding_multiplier=12.0, attention_multiplier=0.12,
        residual_multiplier=0.22, logits_scaling=6.0,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = GraniteForCausalLM(hf_config).eval()

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.embedding_multiplier == 12.0
    assert cfg.attention_multiplier == 0.12
    assert cfg.residual_multiplier == 0.22
    assert cfg.logits_scaling == 6.0
    params = params_from_hf(hf_model.state_dict(), cfg)
    model = Llama(cfg)

    ids = np.random.default_rng(13).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_granite_export_round_trip(tmp_path):
    """A config with non-identity multipliers must export as Granite and
    reload in transformers with matching logits (multipliers live only in
    config.json — the weights are plain Llama)."""
    torch = pytest.importorskip("torch")
    from transformers import AutoModelForCausalLM

    from llm_training_tpu.models.hf_io import save_hf_checkpoint

    cfg = LlamaConfig(
        **TINY, embedding_multiplier=12.0, attention_multiplier=0.12,
        residual_multiplier=0.22, logits_scaling=6.0,
    )
    model = Llama(cfg)
    ids = jnp.asarray(np.random.default_rng(14).integers(0, 128, (2, 16)))
    params = model.init(jax.random.key(3), ids)
    out_dir = save_hf_checkpoint(params, cfg, tmp_path / "export", dtype="float32")

    hf_model = AutoModelForCausalLM.from_pretrained(
        out_dir, attn_implementation="eager"
    ).eval()
    assert type(hf_model).__name__ == "GraniteForCausalLM"
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(np.asarray(ids))).logits.numpy()
    ours = model.apply(params, ids).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


def test_logits_parity_with_hf_starcoder2():
    """Starcoder2 routes to the Llama module with biased LayerNorm blocks,
    biased q/k/v/o projections, and a non-gated c_fc -> gelu_tanh -> c_proj
    MLP; HF's use_bias covers attention and MLP together and norm_epsilon is
    the LayerNorm eps."""
    torch = pytest.importorskip("torch")
    from transformers import Starcoder2Config, Starcoder2ForCausalLM

    hf_config = Starcoder2Config(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, use_bias=True, norm_epsilon=1e-5,
        sliding_window=8, tie_word_embeddings=True,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = Starcoder2ForCausalLM(hf_config).eval()
    sd = hf_model.state_dict()
    assert "model.layers.0.mlp.c_fc.bias" in sd
    assert "model.layers.0.input_layernorm.bias" in sd
    assert "model.norm.bias" in sd

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.norm_type == "layernorm" and cfg.mlp_type == "gelu"
    assert cfg.attention_bias and cfg.attention_out_bias and cfg.mlp_bias
    assert cfg.sliding_window == 8
    params = params_from_hf(sd, cfg)
    model = Llama(cfg)

    # 24 > sliding_window so local attention actually truncates
    ids = np.random.default_rng(15).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_starcoder2_export_round_trip(tmp_path):
    """A layernorm+gelu config must export as Starcoder2 and reload in
    transformers with NO missing keys and matching logits."""
    torch = pytest.importorskip("torch")
    from transformers import AutoModelForCausalLM

    from llm_training_tpu.models.hf_io import save_hf_checkpoint

    cfg = LlamaConfig(
        **TINY, norm_type="layernorm", mlp_type="gelu",
        attention_bias=True, mlp_bias=True, tie_word_embeddings=True,
    )
    model = Llama(cfg)
    ids = jnp.asarray(np.random.default_rng(16).integers(0, 128, (2, 16)))
    params = model.init(jax.random.key(4), ids)
    out_dir = save_hf_checkpoint(params, cfg, tmp_path / "export", dtype="float32")

    hf_model = AutoModelForCausalLM.from_pretrained(
        out_dir, attn_implementation="eager"
    ).eval()
    assert type(hf_model).__name__ == "Starcoder2ForCausalLM"
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(np.asarray(ids))).logits.numpy()
    ours = model.apply(params, ids).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("use_qk_norm", [False, True])
def test_logits_parity_with_hf_cohere(use_qk_norm):
    """Cohere (Command R) routes to the Llama module: a single mean-centered
    weight-only input norm feeding attention AND mlp in parallel, interleaved
    (GPT-J) rope pairing, always-tied embeddings, a multiplicative
    logit_scale, and (Command R+) a per-head-weighted qk-norm."""
    torch = pytest.importorskip("torch")
    from transformers import CohereConfig, CohereForCausalLM

    hf_config = CohereConfig(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, logit_scale=0.125,
        layer_norm_eps=1e-5, use_qk_norm=use_qk_norm,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = CohereForCausalLM(hf_config).eval()
    sd = hf_model.state_dict()
    assert "model.layers.0.post_attention_layernorm.weight" not in sd
    if use_qk_norm:
        assert sd["model.layers.0.self_attn.q_norm.weight"].shape == (4, 16)

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.norm_scheme == "parallel" and cfg.norm_type == "layernorm_nobias"
    assert cfg.rope_interleaved and cfg.logit_scale == 0.125
    assert cfg.qk_norm == use_qk_norm
    params = params_from_hf(sd, cfg)
    model = Llama(cfg)

    ids = np.random.default_rng(17).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


def test_unexportable_combos_raise():
    """Feature combinations no HF architecture represents must fail at
    export instead of silently falling through to a plain-llama config that
    reloads with random-initialized modules."""
    import pytest as _pytest

    from llm_training_tpu.models.llama.hf_conversion import config_to_hf

    with _pytest.raises(ValueError, match="Starcoder2"):
        config_to_hf(LlamaConfig(**TINY, mlp_type="gelu"))  # gelu w/o layernorm
    with _pytest.raises(ValueError, match="use_bias"):
        config_to_hf(LlamaConfig(
            **TINY, norm_type="layernorm", mlp_type="gelu",
            attention_bias=True, mlp_bias=False,
        ))
    with _pytest.raises(ValueError, match="clip_qkv"):
        config_to_hf(LlamaConfig(**TINY, clip_qkv=3.0))  # dense, no OLMoE home
    # a cohere-graph config with layer_types but rope on EVERY layer must
    # refuse the cohere2 export (the HF module derives NoPE on full layers)
    with _pytest.raises(ValueError, match="layer_types"):
        config_to_hf(LlamaConfig(
            **{**TINY, "num_hidden_layers": 2, "scan_layers": False},
            norm_scheme="parallel", norm_type="layernorm_nobias",
            rope_interleaved=True, sliding_window=8,
            layer_types=["sliding_attention", "full_attention"],
        ))


def test_logits_parity_with_hf_phi():
    """Phi-1/1.5/2 routes to the Llama module: parallel blocks under one
    biased LayerNorm, partial rotary (tables span factor*head_dim), biased
    everything including the untied lm_head, and HF's dense/fc1/fc2/
    final_layernorm key naming."""
    torch = pytest.importorskip("torch")
    from transformers import PhiConfig, PhiForCausalLM

    hf_config = PhiConfig(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, partial_rotary_factor=0.5,
        resid_pdrop=0.0, embd_pdrop=0.0, attention_dropout=0.0,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = PhiForCausalLM(hf_config).eval()
    sd = hf_model.state_dict()
    assert "model.layers.0.self_attn.dense.bias" in sd
    assert "model.layers.0.mlp.fc1.weight" in sd
    assert "model.final_layernorm.bias" in sd
    assert "lm_head.bias" in sd

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.norm_scheme == "parallel" and cfg.norm_type == "layernorm"
    assert cfg.partial_rotary_factor == 0.5 and cfg.lm_head_bias
    params = params_from_hf(sd, cfg)
    model = Llama(cfg)

    ids = np.random.default_rng(18).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_phi_export_round_trip(tmp_path):
    """Export a phi-graph config -> transformers reloads it as Phi with NO
    missing keys (renamed dense/fc1/fc2/final_layernorm + lm_head.bias all
    present) and matching logits."""
    torch = pytest.importorskip("torch")
    from transformers import AutoModelForCausalLM

    from llm_training_tpu.models.hf_io import save_hf_checkpoint

    cfg = LlamaConfig(
        **TINY, norm_scheme="parallel", norm_type="layernorm", mlp_type="gelu",
        attention_bias=True, mlp_bias=True, lm_head_bias=True,
        partial_rotary_factor=0.5,
    )
    model = Llama(cfg)
    ids = jnp.asarray(np.random.default_rng(19).integers(0, 128, (2, 16)))
    params = model.init(jax.random.key(5), ids)
    out_dir = save_hf_checkpoint(params, cfg, tmp_path / "export", dtype="float32")

    hf_model = AutoModelForCausalLM.from_pretrained(
        out_dir, attn_implementation="eager"
    ).eval()
    assert type(hf_model).__name__ == "PhiForCausalLM"
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(np.asarray(ids))).logits.numpy()
    ours = model.apply(params, ids).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("cls_name", ["Glm", "Glm4"])
def test_logits_parity_with_hf_glm(cls_name):
    """GLM / GLM-4 route to the Llama module: interleaved partial rotary
    (factor 0.5), q/k/v biases with no o_proj bias, a fused gate_up_proj
    split at the conversion boundary, and (GLM-4) sandwich norms — input
    AND output norms around both blocks."""
    torch = pytest.importorskip("torch")
    import transformers

    config_cls = getattr(transformers, cls_name + "Config")
    model_cls = getattr(transformers, cls_name + "ForCausalLM")
    hf_config = config_cls(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, partial_rotary_factor=0.5, max_position_embeddings=64,
        attention_bias=True, pad_token_id=0,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = model_cls(hf_config).eval()
    sd = hf_model.state_dict()
    assert "model.layers.0.mlp.gate_up_proj.weight" in sd
    assert "model.layers.0.self_attn.q_proj.bias" in sd
    assert "model.layers.0.self_attn.o_proj.bias" not in sd
    if cls_name == "Glm4":
        assert "model.layers.0.post_self_attn_layernorm.weight" in sd

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.rope_interleaved and cfg.partial_rotary_factor == 0.5
    assert cfg.norm_scheme == ("sandwich" if cls_name == "Glm4" else "pre")
    params = params_from_hf(sd, cfg)
    model = Llama(cfg)

    ids = np.random.default_rng(40).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_glm4_export_round_trip(tmp_path):
    """A sandwich + interleaved config exports as GLM-4 and reloads in
    transformers with NO missing keys (re-fused gate_up) and matching
    logits."""
    torch = pytest.importorskip("torch")
    from transformers import AutoModelForCausalLM

    from llm_training_tpu.models.hf_io import save_hf_checkpoint

    cfg = LlamaConfig(
        **TINY, norm_scheme="sandwich", rope_interleaved=True, head_dim=16,
        fused_gate_up=True, partial_rotary_factor=0.5, attention_bias=True,
        attention_out_bias=False, pad_token_id=0,
    )
    model = Llama(cfg)
    ids = jnp.asarray(np.random.default_rng(41).integers(0, 128, (2, 16)))
    params = model.init(jax.random.key(9), ids)
    # zero-init biases would mask a bias-dropping export: randomize them
    import flax.linen as fnn

    def salt_biases(path, leaf):
        if path[-1].key == "bias":
            value = leaf.value if isinstance(leaf, fnn.Partitioned) else leaf
            noise = jnp.asarray(
                np.random.default_rng(len(str(path))).normal(0, 0.1, value.shape),
                value.dtype,
            )
            return leaf.replace_boxed(noise) if isinstance(leaf, fnn.Partitioned) else noise
        return leaf
    params = jax.tree_util.tree_map_with_path(
        salt_biases, params, is_leaf=lambda x: isinstance(x, fnn.Partitioned)
    )
    out_dir = save_hf_checkpoint(params, cfg, tmp_path / "export", dtype="float32")

    hf_model = AutoModelForCausalLM.from_pretrained(
        out_dir, attn_implementation="eager"
    ).eval()
    assert type(hf_model).__name__ == "Glm4ForCausalLM"
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(np.asarray(ids))).logits.numpy()
    ours = model.apply(params, ids).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


def test_logits_parity_with_hf_nemotron():
    """Nemotron routes to the Llama module: zero-centered (1+w) biased
    LayerNorm blocks, a non-gated up -> relu^2 -> down MLP, and partial
    rotary."""
    torch = pytest.importorskip("torch")
    from transformers import NemotronConfig, NemotronForCausalLM

    hf_config = NemotronConfig(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, partial_rotary_factor=0.5, norm_eps=1e-5,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = NemotronForCausalLM(hf_config).eval()
    sd = hf_model.state_dict()
    assert "model.layers.0.mlp.up_proj.weight" in sd
    assert "model.layers.0.mlp.gate_proj.weight" not in sd
    assert "model.layers.0.input_layernorm.bias" in sd
    # salt the zero-init norm weights so the (1 + w) convention is LIVE:
    # a plain-LayerNorm misread would pass with w == 0
    with torch.no_grad():
        for k, v in sd.items():
            if "layernorm.weight" in k or k == "model.norm.weight":
                v.copy_(torch.linspace(-0.2, 0.2, v.numel()))

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.norm_type == "layernorm1p" and cfg.mlp_type == "relu2"
    assert cfg.partial_rotary_factor == 0.5
    params = params_from_hf(sd, cfg)
    model = Llama(cfg)

    ids = np.random.default_rng(42).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_nemotron_export_round_trip(tmp_path):
    """A layernorm1p + relu2 config exports as Nemotron and reloads in
    transformers with NO missing keys and matching logits."""
    torch = pytest.importorskip("torch")
    from transformers import AutoModelForCausalLM

    from llm_training_tpu.models.hf_io import save_hf_checkpoint

    cfg = LlamaConfig(
        **TINY, norm_type="layernorm1p", mlp_type="relu2", head_dim=16,
        partial_rotary_factor=0.5,
    )
    model = Llama(cfg)
    ids = jnp.asarray(np.random.default_rng(43).integers(0, 128, (2, 16)))
    params = model.init(jax.random.key(12), ids)
    out_dir = save_hf_checkpoint(params, cfg, tmp_path / "export", dtype="float32")

    hf_model = AutoModelForCausalLM.from_pretrained(
        out_dir, attn_implementation="eager"
    ).eval()
    assert type(hf_model).__name__ == "NemotronForCausalLM"
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(np.asarray(ids))).logits.numpy()
    ours = model.apply(params, ids).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


def test_logits_parity_with_hf_ernie45():
    """Ernie 4.5 routes to the Llama module: plain llama weights with
    GLM-style interleaved full-dim rope."""
    torch = pytest.importorskip("torch")
    from transformers import Ernie4_5Config, Ernie4_5ForCausalLM

    hf_config = Ernie4_5Config(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64, use_bias=True,
        tie_word_embeddings=True,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = Ernie4_5ForCausalLM(hf_config).eval()
    sd = hf_model.state_dict()
    assert "model.layers.0.mlp.gate_proj.weight" in sd  # NOT fused
    assert "model.layers.0.self_attn.o_proj.bias" in sd  # use_bias covers o

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.rope_interleaved and not cfg.fused_gate_up
    assert cfg.attention_bias and cfg.attention_out_bias
    params = params_from_hf(sd, cfg)
    model = Llama(cfg)

    ids = np.random.default_rng(44).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


def test_logits_parity_with_hf_hunyuan():
    """HunYuan dense routes to the Llama module: per-head qk-norm applied
    AFTER rotary (query_layernorm/key_layernorm HF names)."""
    torch = pytest.importorskip("torch")
    from transformers import HunYuanDenseV1Config, HunYuanDenseV1ForCausalLM

    hf_config = HunYuanDenseV1Config(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = HunYuanDenseV1ForCausalLM(hf_config).eval()
    sd = hf_model.state_dict()
    assert "model.layers.0.self_attn.query_layernorm.weight" in sd
    # salt the norm weights: pre- vs post-rope ordering only shows when the
    # norm is NOT a no-op... (ones-init RMS weights still rescale rows, but
    # make them asymmetric to be safe)
    with torch.no_grad():
        for k, v in sd.items():
            if "layernorm.weight" in k and "self_attn" in k:
                v.copy_(torch.linspace(0.5, 1.5, v.numel()))

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.qk_norm and cfg.qk_norm_position == "post_rope"
    params = params_from_hf(sd, cfg)
    model = Llama(cfg)

    ids = np.random.default_rng(45).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


def test_logits_parity_with_hf_gpt2():
    """GPT-2 routes to the Llama module: learned wpe positions (no rope),
    biased LayerNorm + gelu MLP, fused Conv1D c_attn split into q/k/v at
    the conversion boundary (Conv1D stores [in, out] — no transposes)."""
    torch = pytest.importorskip("torch")
    from transformers import GPT2Config, GPT2LMHeadModel

    hf_config = GPT2Config(
        vocab_size=128, n_embd=64, n_inner=112, n_layer=2, n_head=4,
        n_positions=64, embd_pdrop=0.0, attn_pdrop=0.0, resid_pdrop=0.0,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = GPT2LMHeadModel(hf_config).eval()
    sd = hf_model.state_dict()
    assert "transformer.wpe.weight" in sd
    assert sd["transformer.h.0.attn.c_attn.weight"].shape == (64, 192)

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.position_embedding_type == "learned" and cfg.tie_word_embeddings
    assert cfg.intermediate_size == 112 and cfg.num_key_value_heads == 4
    params = params_from_hf(sd, cfg)
    model = Llama(cfg)

    ids = np.random.default_rng(46).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_gpt2_export_round_trip(tmp_path):
    """A learned-positions config exports as GPT-2 and reloads in
    transformers with NO missing keys (re-fused c_attn) and matching
    logits."""
    torch = pytest.importorskip("torch")
    from transformers import AutoModelForCausalLM

    from llm_training_tpu.models.hf_io import save_hf_checkpoint

    cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=64, compute_dtype="float32",
        position_embedding_type="learned", norm_type="layernorm",
        mlp_type="gelu", attention_bias=True, mlp_bias=True,
        tie_word_embeddings=True, scan_layers=False,
    )
    model = Llama(cfg)
    ids = jnp.asarray(np.random.default_rng(47).integers(0, 128, (2, 16)))
    params = model.init(jax.random.key(13), ids)
    out_dir = save_hf_checkpoint(params, cfg, tmp_path / "export", dtype="float32")

    hf_model = AutoModelForCausalLM.from_pretrained(
        out_dir, attn_implementation="eager"
    ).eval()
    assert type(hf_model).__name__ == "GPT2LMHeadModel"
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(np.asarray(ids))).logits.numpy()
    ours = model.apply(params, ids).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


def test_logits_parity_with_hf_smollm3():
    """SmolLM3 routes to the Llama module: a plain llama graph with
    per-layer NoPE (every 4th layer skips rotary; NoPE layers rotate with
    identity tables so the layer body stays uniform)."""
    torch = pytest.importorskip("torch")
    from transformers import SmolLM3Config, SmolLM3ForCausalLM

    hf_config = SmolLM3Config(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, pad_token_id=0,
        attn_implementation="eager",
    )
    assert hf_config.no_rope_layers == [1, 1, 1, 0]
    torch.manual_seed(0)
    hf_model = SmolLM3ForCausalLM(hf_config).eval()

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.no_rope_layers == [1, 1, 1, 0] and not cfg.scan_layers
    params = params_from_hf(hf_model.state_dict(), cfg)
    model = Llama(cfg)

    ids = np.random.default_rng(48).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


def test_logits_parity_with_hf_olmo3():
    """OLMo-3 routes to the Llama module: OLMo-2's post-norm + full qk-norm
    plus a per-layer sliding/full pattern with DUAL rope tables — sliding
    layers rotate unscaled, full layers with the configured rope_scaling."""
    torch = pytest.importorskip("torch")
    from transformers import Olmo3Config, Olmo3ForCausalLM

    hf_config = Olmo3Config(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, sliding_window=8,
        rope_scaling={"rope_type": "yarn", "factor": 4.0},
        attn_implementation="eager",
    )
    assert hf_config.layer_types == [
        "sliding_attention", "sliding_attention", "sliding_attention",
        "full_attention",
    ]
    torch.manual_seed(0)
    hf_model = Olmo3ForCausalLM(hf_config).eval()

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.norm_scheme == "post" and cfg.qk_norm_scope == "full"
    assert cfg.layer_sliding_window(0) == 8 and cfg.layer_sliding_window(3) is None
    params = params_from_hf(hf_model.state_dict(), cfg)
    model = Llama(cfg)

    # 24 > sliding_window so local attention truncates, and yarn is live on
    # the full layer only
    ids = np.random.default_rng(49).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


def test_logits_parity_with_hf_ministral():
    """Ministral routes to the Llama module: mistral weights with an
    explicit per-layer sliding/full `layer_types` pattern, rotated by ONE
    rope table (unlike OLMo-3's dual-table variant)."""
    torch = pytest.importorskip("torch")
    from transformers import MinistralConfig, MinistralForCausalLM

    hf_config = MinistralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, sliding_window=8, head_dim=16,
        layer_types=["sliding_attention", "full_attention"] * 2,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = MinistralForCausalLM(hf_config).eval()

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.layer_types == ["sliding_attention", "full_attention"] * 2
    assert not cfg.dual_local_rope
    params = params_from_hf(hf_model.state_dict(), cfg)
    model = Llama(cfg)

    ids = np.random.default_rng(50).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


def test_logits_parity_with_hf_helium():
    """Helium routes to the Llama module: plain llama graph (o_proj bias
    hardcoded off even when attention_bias is on)."""
    torch = pytest.importorskip("torch")
    from transformers import HeliumConfig, HeliumForCausalLM

    hf_config = HeliumConfig(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, attention_bias=True, head_dim=16,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = HeliumForCausalLM(hf_config).eval()
    sd = hf_model.state_dict()
    assert "model.layers.0.self_attn.q_proj.bias" in sd
    assert "model.layers.0.self_attn.o_proj.bias" not in sd
    # salt the zero-init biases: a bias-dropping conversion would pass
    # with fresh zeros
    with torch.no_grad():
        for k, v in sd.items():
            if k.endswith(".bias"):
                v.copy_(torch.linspace(-0.2, 0.2, v.numel()))

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.attention_bias and not cfg.attention_out_bias
    params = params_from_hf(sd, cfg)
    model = Llama(cfg)

    ids = np.random.default_rng(51).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


def test_logits_parity_with_hf_arcee():
    """Arcee routes to the Llama module: the Nemotron-style non-gated
    up -> relu^2 -> down MLP under standard RMSNorm pre-norm blocks."""
    torch = pytest.importorskip("torch")
    from transformers import ArceeConfig, ArceeForCausalLM

    hf_config = ArceeConfig(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, head_dim=16,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = ArceeForCausalLM(hf_config).eval()
    sd = hf_model.state_dict()
    assert "model.layers.0.mlp.up_proj.weight" in sd
    assert "model.layers.0.mlp.gate_proj.weight" not in sd

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.mlp_type == "relu2" and cfg.norm_type == "rmsnorm"
    params = params_from_hf(sd, cfg)
    model = Llama(cfg)

    ids = np.random.default_rng(52).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


def test_logits_parity_with_hf_seed_oss():
    """Seed-OSS routes to the Llama module: qkv biases with a SEPARATE
    o_proj bias flag; nonzero residual_dropout is refused at import."""
    torch = pytest.importorskip("torch")
    from transformers import SeedOssConfig, SeedOssForCausalLM

    hf_config = SeedOssConfig(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, head_dim=16,
        attention_bias=True, attention_out_bias=False, residual_dropout=0.0,
        attention_dropout=0.0, attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = SeedOssForCausalLM(hf_config).eval()
    sd = hf_model.state_dict()
    assert "model.layers.0.self_attn.q_proj.bias" in sd
    assert "model.layers.0.self_attn.o_proj.bias" not in sd
    with torch.no_grad():
        for k, v in sd.items():
            if k.endswith(".bias"):
                v.copy_(torch.linspace(-0.2, 0.2, v.numel()))

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.attention_bias and not cfg.attention_out_bias
    params = params_from_hf(sd, cfg)
    model = Llama(cfg)

    ids = np.random.default_rng(53).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)

    with pytest.raises(ValueError, match="residual_dropout"):
        config_from_hf({**hf_config.to_dict(), "residual_dropout": 0.1})


def test_logits_parity_with_hf_stablelm():
    """StableLM routes to the Llama module: biased LayerNorm pre-norm
    blocks with a SWIGLU MLP, partial rotary 0.25, optional qkv biases
    (o_proj hardcoded bias-free)."""
    torch = pytest.importorskip("torch")
    from transformers import StableLmConfig, StableLmForCausalLM

    hf_config = StableLmConfig(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, use_qkv_bias=True,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = StableLmForCausalLM(hf_config).eval()
    sd = hf_model.state_dict()
    assert "model.layers.0.input_layernorm.bias" in sd
    assert "model.layers.0.self_attn.q_proj.bias" in sd
    assert "model.layers.0.self_attn.o_proj.bias" not in sd
    assert "model.layers.0.mlp.gate_proj.weight" in sd
    # salt zero-init biases so a bias-dropping conversion cannot pass
    with torch.no_grad():
        for k, v in sd.items():
            if k.endswith(".bias"):
                v.copy_(torch.linspace(-0.2, 0.2, v.numel()))

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.norm_type == "layernorm" and cfg.mlp_type == "swiglu"
    assert cfg.partial_rotary_factor == 0.25
    assert cfg.attention_bias and not cfg.attention_out_bias
    params = params_from_hf(sd, cfg)
    model = Llama(cfg)

    ids = np.random.default_rng(54).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)

    # export picks stablelm; round trip preserves the graph knobs
    out = config_to_hf(cfg)
    assert out["model_type"] == "stablelm" and out["use_qkv_bias"]
    cfg2 = config_from_hf(out, compute_dtype="float32")
    assert cfg2.norm_type == "layernorm" and cfg2.partial_rotary_factor == 0.25

    with pytest.raises(ValueError, match="parallel_residual"):
        config_from_hf({**hf_config.to_dict(), "use_parallel_residual": True})


def test_logits_parity_with_hf_exaone4():
    """EXAONE-4 routes to the Llama module: OLMo-2-style post-norm blocks,
    per-head (qwen3-style) qk-norm, a 3:1 sliding/full hybrid pattern where
    FULL-attention layers are NoPE (sliding layers rotate) — composed from
    norm_scheme='post' + qk_norm head + layer_types + derived
    no_rope_layers."""
    torch = pytest.importorskip("torch")
    from transformers import Exaone4Config, Exaone4ForCausalLM

    hf_config = Exaone4Config(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, sliding_window=8,
        sliding_window_pattern=4,  # every 4th layer is global attention
        attn_implementation="eager",
    )
    assert hf_config.layer_types == [
        "sliding_attention", "sliding_attention", "sliding_attention",
        "full_attention",
    ]
    torch.manual_seed(0)
    hf_model = Exaone4ForCausalLM(hf_config).eval()
    sd = hf_model.state_dict()
    assert "model.layers.0.post_feedforward_layernorm.weight" in sd
    assert "model.layers.0.self_attn.q_norm.weight" in sd
    assert "model.layers.0.input_layernorm.weight" not in sd

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.norm_scheme == "post" and cfg.qk_norm_scope == "head"
    assert cfg.no_rope_layers == [1, 1, 1, 0]  # full layer is NoPE
    params = params_from_hf(sd, cfg)
    model = Llama(cfg)

    ids = np.random.default_rng(55).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)

    out = config_to_hf(cfg)
    assert out["model_type"] == "exaone4"
    cfg2 = config_from_hf(out, compute_dtype="float32")
    assert cfg2.layer_types == cfg.layer_types
    assert cfg2.no_rope_layers == cfg.no_rope_layers


def test_logits_parity_with_hf_apertus():
    """Apertus routes to the Llama module: non-gated up -> xIELU -> down MLP
    whose activation carries two LEARNABLE scalars per layer (stored as
    softplus pre-images under mlp.act_fn), plus qwen3-style per-head
    qk-norm. The scalars are salted so a conversion that dropped or
    misread them cannot pass."""
    torch = pytest.importorskip("torch")
    from transformers import ApertusConfig, ApertusForCausalLM

    hf_config = ApertusConfig(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = ApertusForCausalLM(hf_config).eval().float()
    sd = hf_model.state_dict()
    assert "model.layers.0.mlp.act_fn.alpha_p" in sd
    assert "model.layers.0.mlp.gate_proj.weight" not in sd
    assert "model.layers.0.self_attn.q_norm.weight" in sd
    with torch.no_grad():  # make the learnable activation scalars LIVE
        sd["model.layers.0.mlp.act_fn.alpha_p"].copy_(torch.tensor([1.3]))
        sd["model.layers.1.mlp.act_fn.alpha_n"].copy_(torch.tensor([-0.4]))

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.mlp_type == "xielu" and cfg.qk_norm_scope == "head"
    params = params_from_hf(sd, cfg)
    model = Llama(cfg)

    ids = np.random.default_rng(56).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)

    out = config_to_hf(cfg)
    assert out["model_type"] == "apertus" and out["hidden_act"] == "xielu"
    cfg2 = config_from_hf(out, compute_dtype="float32")
    assert cfg2.mlp_type == "xielu"


@pytest.mark.slow
def test_logits_parity_with_hf_cohere2():
    """Cohere2 (Command R7B) = the Cohere graph + a sliding/full layer
    pattern where full-attention layers skip rope entirely (derived NoPE,
    like EXAONE-4) — routed to the looped Llama path via layer_types +
    no_rope_layers."""
    torch = pytest.importorskip("torch")
    from transformers import Cohere2Config, Cohere2ForCausalLM

    hf_config = Cohere2Config(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, logit_scale=0.125,
        layer_norm_eps=1e-5, sliding_window=8, sliding_window_pattern=2,
        layer_types=["sliding_attention", "full_attention"] * 2,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = Cohere2ForCausalLM(hf_config).eval()
    sd = hf_model.state_dict()

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.norm_scheme == "parallel" and cfg.norm_type == "layernorm_nobias"
    assert cfg.rope_interleaved and cfg.logit_scale == 0.125
    assert cfg.layer_types == [
        "sliding_attention", "full_attention",
        "sliding_attention", "full_attention",
    ]
    assert cfg.no_rope_layers == [1, 0, 1, 0]  # full layers are NoPE
    assert not cfg.scan_layers  # per-layer patterns loop
    params = params_from_hf(sd, cfg)
    model = Llama(cfg)

    # 24 > sliding_window so local attention actually truncates
    ids = np.random.default_rng(18).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_cohere2_export_round_trip(tmp_path):
    """A parallel-block weight-only-LayerNorm config WITH a sliding/full
    pattern must export as Cohere2 and reload in transformers with matching
    logits."""
    torch = pytest.importorskip("torch")
    from transformers import AutoModelForCausalLM

    from llm_training_tpu.models.hf_io import save_hf_checkpoint

    cfg = LlamaConfig(
        **{**TINY, "num_hidden_layers": 2, "scan_layers": False},
        norm_scheme="parallel", norm_type="layernorm_nobias",
        rope_interleaved=True, logit_scale=0.125,
        tie_word_embeddings=True, sliding_window=8,
        layer_types=["sliding_attention", "full_attention"],
        no_rope_layers=[1, 0],
    )
    model = Llama(cfg)
    ids = jnp.asarray(np.random.default_rng(19).integers(0, 128, (2, 24)))
    params = model.init(jax.random.key(5), ids)
    out_dir = save_hf_checkpoint(params, cfg, tmp_path / "export", dtype="float32")

    hf_model = AutoModelForCausalLM.from_pretrained(
        out_dir, attn_implementation="eager"
    ).eval()
    assert type(hf_model).__name__ == "Cohere2ForCausalLM"
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(np.asarray(ids))).logits.numpy()
    ours = model.apply(params, ids).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_logits_parity_with_hf_phimoe():
    """Phi-3.5-MoE routes to the Llama module + MoEMLP: mixtral expert
    naming, biased LayerNorms, attention/lm_head biases, and SparseMixer
    routing — sequential argmax picks weighted by a band-masked softmax,
    weights NOT renormalized across the two picks (models/moe.py:
    sparsemixer_topk matches HF's eval-mode sparsemixer exactly)."""
    torch = pytest.importorskip("torch")
    from transformers import PhimoeConfig, PhimoeForCausalLM

    hf_config = PhimoeConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, num_local_experts=4,
        num_experts_per_tok=2, rms_norm_eps=1e-5,
        attention_bias=True, lm_head_bias=True,
        router_jitter_noise=0.01, input_jitter_noise=0.0,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = PhimoeForCausalLM(hf_config).eval()
    sd = hf_model.state_dict()
    assert "model.layers.0.block_sparse_moe.experts.0.w1.weight" in sd
    assert "model.layers.0.input_layernorm.bias" in sd
    assert "lm_head.bias" in sd

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.norm_type == "layernorm" and cfg.moe_style == "mixtral"
    assert cfg.moe_router_impl == "sparsemixer" and not cfg.norm_topk_prob
    assert cfg.attention_bias and cfg.lm_head_bias
    params = params_from_hf(sd, cfg)
    model = Llama(cfg)

    ids = np.random.default_rng(20).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


def test_cohere2_imports_r7b_style_raw_config():
    """The published Command R7B config.json predates layer_types (it
    carries sliding_window_pattern=4 only) and arrives as a raw dict —
    the pattern must resolve to the derived sliding/full list + NoPE."""
    raw = dict(
        model_type="cohere2", vocab_size=128, hidden_size=64,
        intermediate_size=112, num_hidden_layers=8, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        logit_scale=0.125, layer_norm_eps=1e-5, sliding_window=8,
        sliding_window_pattern=4, rope_theta=50000.0,
    )
    cfg = config_from_hf(raw, compute_dtype="float32")
    assert cfg.layer_types == (
        ["sliding_attention"] * 3 + ["full_attention"]
    ) * 2
    assert cfg.no_rope_layers == [1, 1, 1, 0] * 2
    assert cfg.sliding_window == 8 and not cfg.scan_layers


@pytest.mark.slow
def test_phimoe_export_round_trip(tmp_path):
    """A SparseMixer MoE config must export as Phimoe and reload in
    transformers with matching logits (routing weights un-renormalized)."""
    torch = pytest.importorskip("torch")
    from transformers import AutoModelForCausalLM

    from llm_training_tpu.models.hf_io import save_hf_checkpoint

    cfg = LlamaConfig(
        **{**TINY, "num_hidden_layers": 2},
        norm_type="layernorm", attention_bias=True, lm_head_bias=True,
        num_experts=4, num_experts_per_tok=2, moe_intermediate_size=96,
        norm_topk_prob=False, moe_style="mixtral",
        moe_router_impl="sparsemixer",
    )
    model = Llama(cfg)
    ids = jnp.asarray(np.random.default_rng(21).integers(0, 128, (2, 16)))
    params = model.init(jax.random.key(6), ids)
    out_dir = save_hf_checkpoint(params, cfg, tmp_path / "export", dtype="float32")

    hf_model = AutoModelForCausalLM.from_pretrained(
        out_dir, attn_implementation="eager"
    ).eval()
    assert type(hf_model).__name__ == "PhimoeForCausalLM"
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(np.asarray(ids))).logits.numpy()
    ours = model.apply(params, ids).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


def test_sparsemixer_and_cohere_window_exports_guarded():
    """Silent-fallthrough refusals: sparsemixer outside the Phimoe shape,
    and a cohere graph with a uniform window but no layer pattern."""
    import pytest as _pytest

    from llm_training_tpu.models.llama.hf_conversion import config_to_hf

    with _pytest.raises(ValueError, match="sparsemixer"):
        config_to_hf(LlamaConfig(
            **TINY, num_experts=4, num_experts_per_tok=2,
            moe_intermediate_size=32, moe_router_impl="sparsemixer",
        ))  # qwen-style naming + rmsnorm: would reload with softmax routing
    with _pytest.raises(ValueError, match="cohere"):
        config_to_hf(LlamaConfig(
            **TINY, norm_scheme="parallel", norm_type="layernorm_nobias",
            rope_interleaved=True, sliding_window=8,
        ))  # uniform window: HF Cohere would silently run full attention


@pytest.mark.slow
@pytest.mark.parametrize("parallel", [True, False])
def test_logits_parity_with_hf_gpt_neox(parallel):
    """GPT-NeoX (Pythia) routes to the Llama module: two biased LayerNorms
    feeding attention and mlp in parallel over the same block input
    (norm_scheme='parallel2'; use_parallel_residual=False is plain
    pre-norm), a per-head INTERLEAVED fused query_key_value split at
    conversion, biased gelu MLP with EXACT (erf) gelu, partial rotary
    0.25, untied embed_out."""
    torch = pytest.importorskip("torch")
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    hf_config = GPTNeoXConfig(
        vocab_size=128, hidden_size=64, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, rotary_pct=0.25,
        use_parallel_residual=parallel, layer_norm_eps=1e-5,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = GPTNeoXForCausalLM(hf_config).eval()
    sd = hf_model.state_dict()
    assert "gpt_neox.layers.0.attention.query_key_value.weight" in sd
    assert "embed_out.weight" in sd

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.norm_scheme == ("parallel2" if parallel else "pre")
    assert cfg.norm_type == "layernorm" and cfg.mlp_type == "gelu"
    assert cfg.mlp_bias and cfg.attention_bias and not cfg.gelu_approximate
    assert cfg.partial_rotary_factor == 0.25
    params = params_from_hf(sd, cfg)
    model = Llama(cfg)

    ids = np.random.default_rng(22).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_gpt_neox_export_round_trip(tmp_path):
    torch = pytest.importorskip("torch")
    from transformers import AutoModelForCausalLM

    from llm_training_tpu.models.hf_io import save_hf_checkpoint

    cfg = LlamaConfig(
        **{**TINY, "num_hidden_layers": 2, "num_key_value_heads": TINY["num_attention_heads"]},
        norm_scheme="parallel2", norm_type="layernorm", mlp_type="gelu",
        gelu_approximate=False, attention_bias=True, mlp_bias=True,
        lm_head_bias=False, partial_rotary_factor=0.25,
    )
    model = Llama(cfg)
    ids = jnp.asarray(np.random.default_rng(23).integers(0, 128, (2, 16)))
    params = model.init(jax.random.key(7), ids)
    out_dir = save_hf_checkpoint(params, cfg, tmp_path / "export", dtype="float32")

    hf_model = AutoModelForCausalLM.from_pretrained(
        out_dir, attn_implementation="eager"
    ).eval()
    assert type(hf_model).__name__ == "GPTNeoXForCausalLM"
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(np.asarray(ids))).logits.numpy()
    ours = model.apply(params, ids).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_logits_parity_with_hf_olmo1():
    """OLMo-1 routes to the Llama module: a plain bias-free llama graph
    whose norms are FULLY non-parametric (F.layer_norm with no weight or
    bias — zero norm keys in the checkpoint) plus the clip_qkv clamp."""
    torch = pytest.importorskip("torch")
    from transformers import OlmoConfig, OlmoForCausalLM

    hf_config = OlmoConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, clip_qkv=1.5,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = OlmoForCausalLM(hf_config).eval()
    sd = hf_model.state_dict()
    assert not any("norm" in k for k in sd)  # truly parameter-free norms

    cfg = config_from_hf(hf_config, compute_dtype="float32")
    assert cfg.norm_type == "layernorm_nonparam" and cfg.clip_qkv == 1.5
    params = params_from_hf(sd, cfg)
    assert "input_layernorm" not in str(jax.tree_util.tree_structure(params))
    model = Llama(cfg)

    ids = np.random.default_rng(24).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_olmo1_export_round_trip(tmp_path):
    torch = pytest.importorskip("torch")
    from transformers import AutoModelForCausalLM

    from llm_training_tpu.models.hf_io import save_hf_checkpoint

    cfg = LlamaConfig(
        **{**TINY, "num_hidden_layers": 2, "rms_norm_eps": 1e-5},
        norm_type="layernorm_nonparam", clip_qkv=2.0,
    )
    model = Llama(cfg)
    ids = jnp.asarray(np.random.default_rng(25).integers(0, 128, (2, 16)))
    params = model.init(jax.random.key(8), ids)
    out_dir = save_hf_checkpoint(params, cfg, tmp_path / "export", dtype="float32")

    hf_model = AutoModelForCausalLM.from_pretrained(
        out_dir, attn_implementation="eager"
    ).eval()
    assert type(hf_model).__name__ == "OlmoForCausalLM"
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(np.asarray(ids))).logits.numpy()
    ours = model.apply(params, ids).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)
