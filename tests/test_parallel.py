"""Mesh construction + logical sharding rules on the virtual 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec

from llm_training_tpu.parallel import (
    MeshConfig,
    build_mesh,
    logical_to_sharding,
    shard_pytree,
)
from llm_training_tpu.parallel.mesh import resolve_axis_sizes
from llm_training_tpu.parallel.sharding import logical_to_spec


def test_auto_factoring(devices):
    sizes = resolve_axis_sizes(MeshConfig(tensor_parallel_size=2), 8)
    assert sizes == {"data": 1, "pipe": 1, "fsdp": 4, "expert": 1, "tensor": 2, "sequence": 1}


def test_auto_factoring_default_is_pure_fsdp(devices):
    sizes = resolve_axis_sizes(MeshConfig(), 8)
    assert sizes == {"data": 1, "pipe": 1, "fsdp": 8, "expert": 1, "tensor": 1, "sequence": 1}


def test_factoring_errors():
    with pytest.raises(ValueError, match="cannot factor"):
        resolve_axis_sizes(MeshConfig(tensor_parallel_size=3), 8)
    with pytest.raises(ValueError, match="at most one"):
        resolve_axis_sizes(MeshConfig(data_parallel_size=-1, fsdp_size=-1), 8)
    with pytest.raises(ValueError, match="uses 4 devices"):
        resolve_axis_sizes(
            MeshConfig(data_parallel_size=2, fsdp_size=2, tensor_parallel_size=1), 8
        )


def test_build_mesh(devices):
    mesh = build_mesh(MeshConfig(fsdp_size=2, tensor_parallel_size=2, sequence_parallel_size=2))
    assert mesh.shape == {"data": 1, "pipe": 1, "fsdp": 2, "expert": 1, "tensor": 2, "sequence": 2}


def test_logical_to_spec_rules():
    assert logical_to_spec(("embed", "mlp")) == PartitionSpec("fsdp", "tensor")
    assert logical_to_spec(("vocab", "embed")) == PartitionSpec("tensor", "fsdp")
    assert logical_to_spec(("norm",)) == PartitionSpec(None)
    assert logical_to_spec(("batch", "act_seq", "act_embed")) == PartitionSpec(
        ("data", "fsdp", "expert"), "sequence", None
    )
    # an already-used mesh axis is not assigned twice
    assert logical_to_spec(("heads", "mlp")) == PartitionSpec("tensor", None)


def test_shard_pytree_places_shards(devices):
    mesh = build_mesh(MeshConfig(fsdp_size=4, tensor_parallel_size=2))
    params = {
        "w_up": jnp.ones((16, 8)),    # (embed, mlp) -> ('fsdp', 'tensor')
        "norm": jnp.ones((16,)),      # replicated
    }
    axes = {"w_up": ("embed", "mlp"), "norm": ("norm",)}
    shardings = logical_to_sharding(axes, mesh)
    sharded = shard_pytree(params, shardings)
    shard_shapes = {k: v.addressable_shards[0].data.shape for k, v in sharded.items()}
    assert shard_shapes["w_up"] == (4, 4)   # 16/4 fsdp, 8/2 tensor
    assert shard_shapes["norm"] == (16,)

    @jax.jit
    def f(p):
        return p["w_up"].sum() + p["norm"].sum()

    np.testing.assert_allclose(f(sharded), 16 * 8 + 16)
