"""Flash-attention block-size tuning layer + wedge-proof bench plumbing.

Covers `ops/pallas/tuning.py` (resolution order: call > env > table >
default, all read at CALL time — the old import-time FLASH_BLOCK_* read
made overrides require a re-import), the telemetry gauges recording what
each compiled step ran with, and the pure parts of `bench.py`'s
stage/partial-JSON orchestration (summary assembly, stage schema)."""

import json
import os
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_training_tpu.ops.pallas import tuning
from llm_training_tpu.ops.pallas.flash_attention import flash_attention
from llm_training_tpu.telemetry import TelemetryRegistry, set_registry

sys.path.insert(0, str(Path(__file__).parent.parent))  # repo root: bench.py
import bench


@pytest.fixture(autouse=True)
def _isolate_tuning(monkeypatch, tmp_path):
    """Each test sees an empty table (not the committed one) unless it
    installs its own, and a clean cache before AND after."""
    monkeypatch.setenv(tuning.ENV_TABLE, str(tmp_path / "absent.json"))
    monkeypatch.delenv("FLASH_BLOCK_Q", raising=False)
    monkeypatch.delenv("FLASH_BLOCK_K", raising=False)
    monkeypatch.delenv("FLASH_BLOCK_Q_BWD", raising=False)
    monkeypatch.delenv("FLASH_BLOCK_K_BWD", raising=False)
    tuning.clear_table_cache()
    yield
    tuning.clear_table_cache()


def _write_table(path: Path, entries: dict) -> None:
    path.write_text(json.dumps({"version": 1, "entries": entries}))


SHAPE = dict(seq_len=2048, head_dim=128, dtype=jnp.bfloat16, causal=True)


# ------------------------------------------------------------ resolution


def test_default_resolution():
    choice = tuning.resolve_block_sizes("fwd", **SHAPE)
    assert (choice.block_q, choice.block_k) == (tuning.DEFAULT_BLOCK,) * 2
    assert choice.source == "default"


def test_call_args_win_over_env_and_table(monkeypatch, tmp_path):
    table = tmp_path / "t.json"
    _write_table(table, {tuning.table_key("fwd", 2048, 128, jnp.bfloat16, True, None):
                         {"block_q": 512, "block_k": 512}})
    monkeypatch.setenv(tuning.ENV_TABLE, str(table))
    monkeypatch.setenv("FLASH_BLOCK_Q", "256")
    monkeypatch.setenv("FLASH_BLOCK_K", "256")
    choice = tuning.resolve_block_sizes("fwd", block_q=128, block_k=128, **SHAPE)
    assert (choice.block_q, choice.block_k, choice.source) == (128, 128, "call")


def test_env_wins_over_table_and_is_read_at_call_time(monkeypatch, tmp_path):
    table = tmp_path / "t.json"
    _write_table(table, {tuning.table_key("fwd", 2048, 128, jnp.bfloat16, True, None):
                         {"block_q": 512, "block_k": 512}})
    monkeypatch.setenv(tuning.ENV_TABLE, str(table))
    tuning.clear_table_cache()
    assert tuning.resolve_block_sizes("fwd", **SHAPE).source == "table"
    # env set AFTER import/first resolution still takes effect: no
    # module-level constant involved anywhere
    monkeypatch.setenv("FLASH_BLOCK_Q", "256")
    monkeypatch.setenv("FLASH_BLOCK_K", "384")
    choice = tuning.resolve_block_sizes("fwd", **SHAPE)
    assert (choice.block_q, choice.block_k, choice.source) == (256, 384, "env")


def test_bwd_env_knobs_fall_back_to_shared(monkeypatch):
    monkeypatch.setenv("FLASH_BLOCK_Q", "256")
    monkeypatch.setenv("FLASH_BLOCK_K", "256")
    assert tuning.resolve_block_sizes("bwd", **SHAPE).block_q == 256
    monkeypatch.setenv("FLASH_BLOCK_Q_BWD", "512")
    choice = tuning.resolve_block_sizes("bwd", **SHAPE)
    assert (choice.block_q, choice.block_k) == (512, 256)  # bwd-specific > shared


def test_fwd_and_bwd_table_entries_are_independent(monkeypatch, tmp_path):
    table = tmp_path / "t.json"
    _write_table(table, {
        tuning.table_key("fwd", 2048, 128, jnp.bfloat16, True, None):
            {"block_q": 1024, "block_k": 512},
        tuning.table_key("bwd", 2048, 128, jnp.bfloat16, True, None):
            {"block_q": 256, "block_k": 1024},
    })
    monkeypatch.setenv(tuning.ENV_TABLE, str(table))
    fwd = tuning.resolve_block_sizes("fwd", **SHAPE)
    bwd = tuning.resolve_block_sizes("bwd", **SHAPE)
    assert (fwd.block_q, fwd.block_k) == (1024, 512)
    assert (bwd.block_q, bwd.block_k) == (256, 1024)
    assert fwd.source == bwd.source == "table"


def test_nearest_seq_fallback(monkeypatch, tmp_path):
    table = tmp_path / "t.json"
    _write_table(table, {
        tuning.table_key("fwd", 1024, 128, jnp.bfloat16, True, None):
            {"block_q": 256, "block_k": 256},
        tuning.table_key("fwd", 8192, 128, jnp.bfloat16, True, None):
            {"block_q": 2048, "block_k": 1024},
    })
    monkeypatch.setenv(tuning.ENV_TABLE, str(table))
    near_small = tuning.resolve_block_sizes("fwd", **{**SHAPE, "seq_len": 1536})
    assert (near_small.block_q, near_small.source) == (256, "table")
    near_big = tuning.resolve_block_sizes("fwd", **{**SHAPE, "seq_len": 7000})
    assert (near_big.block_q, near_big.block_k) == (2048, 1024)
    # a different head_dim/dtype/window must NOT borrow these entries
    assert tuning.resolve_block_sizes("fwd", **{**SHAPE, "head_dim": 64}).source == "default"
    assert tuning.resolve_block_sizes(
        "fwd", **{**SHAPE, "sliding_window": 4096}).source == "default"


def test_missing_or_corrupt_table_degrades_to_default(monkeypatch, tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    monkeypatch.setenv(tuning.ENV_TABLE, str(bad))
    assert tuning.resolve_block_sizes("fwd", **SHAPE).source == "default"


def test_malformed_table_entries_degrade_not_crash(monkeypatch, tmp_path):
    """A structurally-valid table whose ENTRY is bad (missing knob, non-int,
    non-lane-multiple, wrong type) must degrade like a corrupt table —
    skipped at lookup, never a trace-time ValueError in a training run."""
    table = tmp_path / "t.json"
    _write_table(table, {
        tuning.table_key("fwd", 2048, 128, jnp.bfloat16, True, None):
            {"block_q": 100, "block_k": 512},        # not lane-aligned
        tuning.table_key("fwd", 1024, 128, jnp.bfloat16, True, None):
            {"block_q": 256},                        # missing block_k
        tuning.table_key("fwd", 4096, 128, jnp.bfloat16, True, None):
            ["not", "a", "dict"],
        tuning.table_key("bwd", 2048, 128, jnp.bfloat16, True, None):
            {"block_q": "huge", "block_k": 512},     # non-int
    })
    monkeypatch.setenv(tuning.ENV_TABLE, str(table))
    tuning.clear_table_cache()
    assert tuning.resolve_block_sizes("fwd", **SHAPE).source == "default"
    assert tuning.resolve_block_sizes("bwd", **SHAPE).source == "default"
    # a valid entry at another seq still wins via nearest-seq over the
    # malformed exact hit
    _write_table(table, {
        tuning.table_key("fwd", 2048, 128, jnp.bfloat16, True, None):
            {"block_q": 100, "block_k": 512},
        tuning.table_key("fwd", 1024, 128, jnp.bfloat16, True, None):
            {"block_q": 256, "block_k": 256},
    })
    tuning.clear_table_cache()
    choice = tuning.resolve_block_sizes("fwd", **SHAPE)
    assert (choice.block_q, choice.source) == (256, "table")


def test_non_lane_multiple_rejected(monkeypatch):
    with pytest.raises(ValueError, match="multiple of 128"):
        tuning.resolve_block_sizes("fwd", block_q=100, block_k=128, **SHAPE)
    monkeypatch.setenv("FLASH_BLOCK_Q", "77")
    with pytest.raises(ValueError, match="multiple of 128"):
        tuning.resolve_block_sizes("fwd", **SHAPE)


def test_fit_block():
    assert tuning.fit_block(1024, 512) == 512
    assert tuning.fit_block(1024, 1536) == 768   # largest <=1024 dividing 1536
    assert tuning.fit_block(256, 384) == 128     # 256 doesn't divide 384
    assert tuning.fit_block(128, 2048) == 128
    with pytest.raises(ValueError, match="multiple of 128"):
        tuning.fit_block(128, 200)


def test_divisibility_error_for_explicit_blocks():
    """Explicit (call-site) blocks stay strict: the existing
    `_check_block_divisibility` message, not a silent degrade."""
    from llm_training_tpu.ops.pallas.flash_attention import flash_fwd_flat

    q = jnp.zeros((2, 384, 64), jnp.float32)
    seg = jnp.ones((1, 384), jnp.int32)
    with pytest.raises(ValueError, match="must be multiples of the blocks"):
        flash_fwd_flat(q, q, q, seg, seg, num_q_heads=2, num_kv_heads=2,
                       scale=1.0, causal=True, block_q=256, block_k=256,
                       interpret=True)


# ------------------------------------------------------------ end-to-end


def test_table_blocks_reach_kernel_and_telemetry(monkeypatch, tmp_path):
    """A table entry changes the compiled tiles AND is visible in telemetry
    (flash/* gauges + tuning_table_hit counters), numerics unchanged."""
    registry = TelemetryRegistry()
    previous = set_registry(registry)
    try:
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.standard_normal((1, 512, 4, 64)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((1, 512, 2, 64)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((1, 512, 2, 64)), jnp.float32)
        cot = jnp.asarray(rng.standard_normal((1, 512, 4, 64)), jnp.float32)

        def grad_norm():
            g = jax.grad(
                lambda q, k, v: (flash_attention(q, k, v, causal=True) * cot).sum(),
                argnums=(0, 1, 2),
            )(q, k, v)
            return [np.asarray(x) for x in g]

        base = grad_norm()
        snap = registry.snapshot()
        # gauges record the POST-clamp tiles (what actually compiled): the
        # 1024 default clamps to the 512-long sequence
        assert snap["flash/fwd/block_q"] == 512
        assert snap["flash/bwd/block_q"] == 512
        assert snap["flash/tuning_table_hit/default"] >= 2.0  # fwd + bwd

        table = tmp_path / "t.json"
        _write_table(table, {
            tuning.table_key("fwd", 512, 64, jnp.float32, True, None):
                {"block_q": 128, "block_k": 256},
            tuning.table_key("bwd", 512, 64, jnp.float32, True, None):
                {"block_q": 256, "block_k": 128},
        })
        monkeypatch.setenv(tuning.ENV_TABLE, str(table))
        tuning.clear_table_cache()
        tuned = grad_norm()
        snap = registry.snapshot()
        assert (snap["flash/fwd/block_q"], snap["flash/fwd/block_k"]) == (128, 256)
        assert (snap["flash/bwd/block_q"], snap["flash/bwd/block_k"]) == (256, 128)
        assert snap["flash/tuning_table_hit/table"] >= 2.0
        for a, b in zip(base, tuned):
            np.testing.assert_allclose(b, a, rtol=1e-5, atol=1e-5)
    finally:
        set_registry(previous)


def test_explicit_fwd_blocks_tile_both_passes():
    """The pre-tuning-layer contract: explicit block_q/block_k with no bwd
    override tile the backward too (scripts/microbench_flash.py's sweep
    depends on this); independent bwd tiles are an explicit opt-in."""
    registry = TelemetryRegistry()
    previous = set_registry(registry)
    try:
        q = jnp.ones((1, 512, 2, 64), jnp.float32)
        jax.grad(
            lambda q: flash_attention(
                q, q, q, causal=True, block_q=256, block_k=128, interpret=True
            ).sum()
        )(q)
        snap = registry.snapshot()
        assert (snap["flash/fwd/block_q"], snap["flash/fwd/block_k"]) == (256, 128)
        assert (snap["flash/bwd/block_q"], snap["flash/bwd/block_k"]) == (256, 128)
        assert snap["flash/tuning_table_hit/call"] >= 2.0
    finally:
        set_registry(previous)


def test_single_explicit_bwd_knob_keeps_env_for_other(monkeypatch):
    """Pinning ONE bwd knob in the call must not discard the env/table
    resolution of the OTHER: bwd_block_q=256 + FLASH_BLOCK_K_BWD=128 has to
    compile the backward at 256x128, not 256x<fwd tile>."""
    monkeypatch.setenv("FLASH_BLOCK_K_BWD", "128")
    registry = TelemetryRegistry()
    previous = set_registry(registry)
    try:
        q = jnp.ones((1, 512, 2, 64), jnp.float32)
        jax.grad(
            lambda q: flash_attention(
                q, q, q, causal=True, bwd_block_q=256, interpret=True
            ).sum()
        )(q)
        snap = registry.snapshot()
        assert (snap["flash/bwd/block_q"], snap["flash/bwd/block_k"]) == (256, 128)
    finally:
        set_registry(previous)


def test_explicit_fwd_blocks_respect_bwd_env(monkeypatch):
    """Explicit fwd tiles inherit to the backward ONLY when no bwd-specific
    source claims a knob: the documented FLASH_BLOCK_{Q,K}_BWD env override
    must still retile the backward of a pinned-fwd call (a bwd sweep that
    pins fwd tiles per call would otherwise measure the fwd tiles twice)."""
    monkeypatch.setenv("FLASH_BLOCK_Q_BWD", "128")
    monkeypatch.setenv("FLASH_BLOCK_K_BWD", "128")
    registry = TelemetryRegistry()
    previous = set_registry(registry)
    try:
        q = jnp.ones((1, 512, 2, 64), jnp.float32)
        jax.grad(
            lambda q: flash_attention(
                q, q, q, causal=True, block_q=256, block_k=256, interpret=True
            ).sum()
        )(q)
        snap = registry.snapshot()
        assert (snap["flash/fwd/block_q"], snap["flash/fwd/block_k"]) == (256, 256)
        assert (snap["flash/bwd/block_q"], snap["flash/bwd/block_k"]) == (128, 128)
        assert snap["flash/tuning_table_hit/env"] >= 1.0  # the bwd resolution
    finally:
        set_registry(previous)

    monkeypatch.setenv("FLASH_BLOCK_Q_BWD", "100")
    with pytest.raises(ValueError, match="multiple of 128"):
        flash_attention(q, q, q, causal=True, block_q=256, block_k=256,
                        interpret=True)


def test_explicit_bwd_blocks_validated_with_explicit_fwd():
    """The lane-multiple check must hold on EVERY path: explicit bwd tiles
    are rejected whether or not the fwd tiles are also explicit (a 192
    tile would otherwise slip past divisibility on a 384-long seq and die
    in Mosaic instead of a clean ValueError)."""
    q = jnp.ones((1, 384, 2, 64), jnp.float32)
    for extra in ({}, {"block_q": 128, "block_k": 128}):
        with pytest.raises(ValueError, match="multiple of 128"):
            flash_attention(q, q, q, causal=True, bwd_block_q=192,
                            bwd_block_k=192, interpret=True, **extra)


def test_single_explicit_fwd_knob_inherits_per_knob(monkeypatch, tmp_path):
    """Pinning ONLY block_q still pins the backward's q tile (per-knob
    inheritance); the unpinned k knob resolves through the shared chain —
    here a bwd table entry."""
    table = tmp_path / "t.json"
    _write_table(table, {
        tuning.table_key("bwd", 512, 64, jnp.float32, True, None):
            {"block_q": 128, "block_k": 128},
    })
    monkeypatch.setenv(tuning.ENV_TABLE, str(table))
    tuning.clear_table_cache()
    registry = TelemetryRegistry()
    previous = set_registry(registry)
    try:
        q = jnp.ones((1, 512, 2, 64), jnp.float32)
        jax.grad(
            lambda q: flash_attention(
                q, q, q, causal=True, block_q=256, interpret=True
            ).sum()
        )(q)
        snap = registry.snapshot()
        assert (snap["flash/bwd/block_q"], snap["flash/bwd/block_k"]) == (256, 128)
    finally:
        set_registry(previous)


def test_explicit_fwd_blocks_ignore_bwd_table(monkeypatch, tmp_path):
    """...but a TABLE entry is not an override under explicit fwd tiles: a
    pinned microbench must measure the tiles it pinned, never a stale
    table's (env is deliberate per-run intent; the table is ambient)."""
    table = tmp_path / "t.json"
    _write_table(table, {
        tuning.table_key("bwd", 512, 64, jnp.float32, True, None):
            {"block_q": 128, "block_k": 128},
    })
    monkeypatch.setenv(tuning.ENV_TABLE, str(table))
    tuning.clear_table_cache()
    registry = TelemetryRegistry()
    previous = set_registry(registry)
    try:
        q = jnp.ones((1, 512, 2, 64), jnp.float32)
        jax.grad(
            lambda q: flash_attention(
                q, q, q, causal=True, block_q=256, block_k=256, interpret=True
            ).sum()
        )(q)
        snap = registry.snapshot()
        assert (snap["flash/bwd/block_q"], snap["flash/bwd/block_k"]) == (256, 256)
    finally:
        set_registry(previous)


def test_hardware_table_entries_skipped_off_tpu(monkeypatch, tmp_path):
    """backend-tagged entries only apply to the runtime they were measured
    on: a v5e entry must not drive interpret-mode runs (and cpu-interpret
    placeholders must never drive a compiled TPU step)."""
    table = tmp_path / "t.json"
    _write_table(table, {
        tuning.table_key("fwd", 2048, 128, jnp.bfloat16, True, None):
            {"block_q": 512, "block_k": 512, "backend": "v5e"},
        tuning.table_key("bwd", 2048, 128, jnp.bfloat16, True, None):
            {"block_q": 256, "block_k": 256, "backend": "cpu-interpret"},
    })
    monkeypatch.setenv(tuning.ENV_TABLE, str(table))
    # this suite runs off-TPU: the v5e fwd entry is ignored, the
    # cpu-interpret bwd entry applies
    assert tuning.resolve_block_sizes("fwd", **SHAPE).source == "default"
    bwd = tuning.resolve_block_sizes("bwd", **SHAPE)
    assert (bwd.block_q, bwd.source) == (256, "table")


def test_forward_only_trace_records_no_bwd_gauges():
    """The bwd gauges say what the compiled step ACTUALLY ran with — a
    forward-only trace (eval/validation) compiles no backward kernel, so
    it must not report bwd tiles or count a bwd resolution."""
    registry = TelemetryRegistry()
    previous = set_registry(registry)
    try:
        q = jnp.ones((1, 256, 2, 64), jnp.float32)
        flash_attention(q, q, q, causal=True, interpret=True)
        snap = registry.snapshot()
        assert "flash/fwd/block_q" in snap
        assert not any(k.startswith("flash/bwd/") for k in snap), snap
        assert snap.get("flash/tuning_table_hit/default", 0) == 1.0  # fwd only
        # ...and the backward records exactly once a grad trace exists
        jax.grad(lambda q: flash_attention(
            q, q, q, causal=True, interpret=True).sum())(q)
        snap = registry.snapshot()
        assert snap["flash/bwd/block_q"] == 256
    finally:
        set_registry(previous)


def test_resolved_blocks_fit_sequence():
    """Default 1024 tiles on a 256-long input must degrade to runnable
    tiles (no divisibility crash) — the wrapper clamps fwd, fits bwd."""
    q = jnp.zeros((1, 256, 2, 64), jnp.float32)
    out = flash_attention(q, q, q, causal=True, interpret=True)
    assert out.shape == q.shape


# ------------------------------------------------------------ bench schema


def _ok(stage, **payload):
    return {"stage": stage, "partial": True, "status": "ok", **payload}


def test_bench_summary_all_ok():
    results = {
        "backend_init": _ok("backend_init", backend="cpu"),
        "train": _ok("train", value=0.61, vs_baseline=1.109, sec_per_step=1.5,
                     blocks={"fwd": [1024, 1024], "bwd": [512, 1024]},
                     goodput_pct=93.0),
        "health": _ok("health", sec_per_step_health=1.65),
        "trace": _ok("trace", sec_per_step_trace=1.515, trace_events_written=60),
        "decode": _ok("decode", prefill_time_s=0.1, decode_tokens_per_sec=900.0),
    }
    summary = bench.summarize(results)
    assert summary["metric"] == "llama_clm_train_mfu"
    assert summary["stage"] == "summary" and summary["partial"] is False
    assert summary["value"] == 0.61 and summary["vs_baseline"] == 1.109
    assert summary["health_overhead_pct"] == pytest.approx(10.0)
    assert summary["trace_overhead_pct"] == pytest.approx(1.0)
    assert summary["blocks"] == {"fwd": [1024, 1024], "bwd": [512, 1024]}
    assert all(summary["stages"][s]["status"] == "ok" for s in results)


def test_bench_summary_degrades_single_stage_to_error():
    """A wedged stage becomes one error entry; the headline MFU and the
    other stages' metrics survive."""
    results = {
        "backend_init": _ok("backend_init"),
        "train": _ok("train", value=0.6, vs_baseline=1.09, sec_per_step=1.5),
        "health": {"stage": "health", "partial": True, "status": "error",
                   "error": "stage wedged: no completion within 15s (child killed)",
                   "rc": -9},
        "decode": _ok("decode", decode_tokens_per_sec=800.0),
    }
    summary = bench.summarize(results)
    assert summary["value"] == 0.6
    assert summary["health_overhead_pct"] is None
    assert summary["trace_overhead_pct"] is None
    assert summary["decode_tokens_per_sec"] == 800.0
    assert summary["stages"]["health"]["status"] == "error"
    assert "wedged" in summary["stages"]["health"]["error"]


def test_bench_summary_train_failure_keeps_record_valid():
    results = {
        "backend_init": _ok("backend_init"),
        "train": {"stage": "train", "partial": True, "status": "error",
                  "error": "stage failed (exit 1)", "rc": 1},
        "decode": _ok("decode", decode_tokens_per_sec=800.0),
    }
    summary = bench.summarize(results)
    assert summary["value"] is None and summary["vs_baseline"] is None
    assert "error" in summary
    assert summary["decode_tokens_per_sec"] == 800.0
    json.dumps(summary)  # the record must stay serializable for the driver


def test_report_perf_section_degrades_on_malformed_record():
    """The broad bench*.json glob (with a cwd fallback) can pick up a
    foreign or hand-mangled file — the report must render one honest line,
    not crash with a traceback."""
    from llm_training_tpu.telemetry.report import _perf_section

    for bad in (
        {"value": "n/a"},                                  # non-numeric mfu
        {"value": 0.6, "blocks": {"fwd": [1, 2, 3]}},      # unpackable blocks
        {"value": 0.6, "stages": {"train": "ok"}},         # stage not a dict
        {"value": 0.6, "health_overhead_pct": "high"},
    ):
        lines = _perf_section((bad, "bench_bad.json"))
        assert lines[1] == "== Perf ==" and "bench_bad.json" in lines[2]
        assert any("unreadable bench record" in l for l in lines), (bad, lines)
    # a well-formed record still renders fully
    ok = _perf_section(({"value": 0.6, "vs_baseline": 1.09,
                         "blocks": {"fwd": [1024, 1024]},
                         "stages": {"train": {"status": "ok"}}}, "b.json"))
    assert any(l.startswith("mfu: 0.6") for l in ok)
    assert any("fwd 1024x1024" in l for l in ok)


def test_bench_chaos_crash_degrades_stage_not_run():
    """Real subprocess leg: a chaos-crashed backend_init child yields an
    error record + a summary line, not a dead bench (fast: the child dies
    before any jax work)."""
    proc = subprocess.run(
        [sys.executable, str(Path(bench.__file__).resolve()), "--dry"],
        capture_output=True, text=True, timeout=240,
        env={**os.environ,
             "BENCH_CHAOS_CRASH": "backend_init", "BENCH_STAGE_RETRIES": "0"},
    )
    lines = [json.loads(l) for l in proc.stdout.splitlines() if l.strip().startswith("{")]
    assert lines, proc.stderr
    summary = lines[-1]
    assert summary["stage"] == "summary" and summary["value"] is None
    assert summary["stages"]["backend_init"]["status"] == "error"
    # dependent stages skipped, not hung
    assert summary["stages"]["train"]["status"] == "skipped"
    assert proc.returncode == 1
