"""Pallas flash attention kernel vs the XLA reference path.

The kernel runs in interpreter mode on CPU (the wrapper auto-selects), so
these tests exercise the real kernel logic — tiling, online softmax, block
skipping, GQA grid folding, the custom VJP — without TPU hardware. The
reference validated its attention only implicitly through flash-attn's own
tests (SURVEY.md §4); here packed/causal/windowed parity is asserted
directly against the einsum path.
"""

import zlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_training_tpu.ops.attention import dot_product_attention
from llm_training_tpu.ops.pallas.flash_attention import flash_attention


def _rand(rng, shape):
    return rng.standard_normal(shape).astype(np.float32)


def _make_qkv(rng, batch, sq, skv, hq, hkv, d):
    return (
        jnp.asarray(_rand(rng, (batch, sq, hq, d))),
        jnp.asarray(_rand(rng, (batch, skv, hkv, d))),
        jnp.asarray(_rand(rng, (batch, skv, hkv, d))),
    )


def _packed_segments(rng, batch, seq, max_docs=4):
    """Random packed segment ids: 1..N runs then 0-padding."""
    rows = []
    for _ in range(batch):
        cuts = np.sort(rng.choice(np.arange(1, seq), size=max_docs - 1, replace=False))
        row, seg = [], 1
        prev = 0
        for c in list(cuts) + [seq - 2]:
            if c <= prev:
                continue
            row += [seg] * (c - prev)
            seg += 1
            prev = c
        row += [0] * (seq - len(row))
        rows.append(row)
    return jnp.asarray(rows, jnp.int32)


CASES = [
    # (name, hq, hkv, sliding_window, soft_cap, packed)
    ("causal", 4, 4, None, None, False),
    ("gqa", 4, 2, None, None, False),
    ("packed_gqa", 4, 2, None, None, True),
    ("window", 2, 2, 37, None, False),
    ("softcap", 2, 2, None, 20.0, False),
    ("everything", 4, 2, 50, 30.0, True),
]


@pytest.mark.parametrize("name,hq,hkv,window,cap,packed", CASES, ids=[c[0] for c in CASES])
def test_forward_matches_xla(name, hq, hkv, window, cap, packed):
    rng = np.random.default_rng(zlib.crc32(name.encode()))
    batch, seq, d = 2, 256, 32
    q, k, v = _make_qkv(rng, batch, seq, seq, hq, hkv, d)
    seg = _packed_segments(rng, batch, seq) if packed else None

    kwargs = dict(segment_ids=seg, causal=True, sliding_window=window, logits_soft_cap=cap)
    expected = dot_product_attention(q, k, v, impl="xla", **kwargs)
    got = flash_attention(q, k, v, block_q=128, block_k=128, **kwargs)
    np.testing.assert_allclose(got, expected, rtol=2e-3, atol=2e-3)


def test_packed_block_aligned_docs():
    """Document boundaries aligned to kv blocks: the DMA-elision index maps
    redirect segment-skipped tiles onto already-resident kv blocks, so the
    kernel's skip decision must come from the grid index, not the streamed
    segment ids. Two 256-token docs at block 128 put kv blocks wholly inside
    an earlier document — the exact layout the random cuts in
    `_packed_segments` never produce (r4 advisor repro: max abs error 2.5)."""
    rng = np.random.default_rng(7)
    batch, seq, h, d = 2, 512, 2, 32
    q, k, v = _make_qkv(rng, batch, seq, seq, h, h, d)
    seg = jnp.asarray(np.tile(np.repeat([1, 2], 256)[None], (batch, 1)), jnp.int32)
    expected = dot_product_attention(q, k, v, segment_ids=seg, causal=True, impl="xla")
    got = flash_attention(q, k, v, segment_ids=seg, causal=True, block_q=128, block_k=128)
    np.testing.assert_allclose(got, expected, rtol=2e-3, atol=2e-3)


def test_gradients_smoke_packed_aligned():
    """Fast (non-slow) gradient check so the default suite always traces the
    backward kernels — the r4 regression shipped because every gradient test
    was slow-marked. Block-aligned packing exercises the dq/dkv segment-skip
    gates too."""
    rng = np.random.default_rng(8)
    batch, seq, h, d = 1, 256, 2, 32
    q, k, v = _make_qkv(rng, batch, seq, seq, h, h, d)
    seg = jnp.asarray(np.repeat([1, 2], 128)[None], jnp.int32)
    cot = jnp.asarray(_rand(rng, (batch, seq, h, d)))

    gx = jax.grad(
        lambda q, k, v: (dot_product_attention(q, k, v, segment_ids=seg, impl="xla") * cot).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    gp = jax.grad(
        lambda q, k, v: (flash_attention(q, k, v, segment_ids=seg, block_q=128, block_k=128) * cot).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(gx, gp, "qkv"):
        np.testing.assert_allclose(b, a, rtol=2e-3, atol=2e-3, err_msg=f"d{name}")


@pytest.mark.slow
def test_gradients_match_xla():
    rng = np.random.default_rng(0)
    batch, seq, hq, hkv, d = 1, 256, 4, 2, 32
    q, k, v = _make_qkv(rng, batch, seq, seq, hq, hkv, d)
    seg = _packed_segments(rng, batch, seq)
    cot = jnp.asarray(_rand(rng, (batch, seq, hq, d)))

    def loss(fn, q, k, v):
        return (fn(q, k, v) * cot).sum()

    def xla(q, k, v):
        return dot_product_attention(q, k, v, segment_ids=seg, impl="xla")

    def pallas(q, k, v):
        return flash_attention(q, k, v, segment_ids=seg, block_q=128, block_k=128)

    gx = jax.grad(lambda *a: loss(xla, *a), argnums=(0, 1, 2))(q, k, v)
    gp = jax.grad(lambda *a: loss(pallas, *a), argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gx, gp, "qkv"):
        np.testing.assert_allclose(b, a, rtol=2e-3, atol=2e-3, err_msg=f"d{name}")


@pytest.mark.slow
def test_gradients_match_xla_softcap_window():
    rng = np.random.default_rng(1)
    batch, seq, h, d = 1, 128, 2, 32
    q, k, v = _make_qkv(rng, batch, seq, seq, h, h, d)
    cot = jnp.asarray(_rand(rng, (batch, seq, h, d)))
    kw = dict(sliding_window=33, logits_soft_cap=25.0)

    gx = jax.grad(
        lambda q, k, v: (dot_product_attention(q, k, v, impl="xla", **kw) * cot).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    gp = jax.grad(
        lambda q, k, v: (flash_attention(q, k, v, block_q=128, block_k=128, **kw) * cot).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(gx, gp, "qkv"):
        np.testing.assert_allclose(b, a, rtol=2e-3, atol=2e-3, err_msg=f"d{name}")


def test_unaligned_shapes_are_padded():
    """seq/head_dim not multiples of the lane width go through the padding
    path; result must still match the XLA path on the unpadded region."""
    rng = np.random.default_rng(2)
    batch, seq, h, d = 2, 200, 2, 24
    q, k, v = _make_qkv(rng, batch, seq, seq, h, h, d)
    expected = dot_product_attention(q, k, v, impl="xla")
    got = flash_attention(q, k, v, block_q=128, block_k=128)
    np.testing.assert_allclose(got, expected, rtol=2e-3, atol=2e-3)


def test_cross_length_chunk_matches_slice():
    """Ring-attention chunk shape: q shorter than kv with q_offset."""
    rng = np.random.default_rng(3)
    seq, d = 256, 32
    q, k, v = _make_qkv(rng, 1, seq, seq, 2, 2, d)
    seg = _packed_segments(rng, 1, seq)

    full = flash_attention(q, k, v, segment_ids=seg, block_q=128, block_k=128)
    chunk = slice(128, 256)
    part = flash_attention(
        q[:, chunk], k, v,
        segment_ids=seg, q_segment_ids=seg[:, chunk], q_offset=128,
        block_q=128, block_k=128,
    )
    np.testing.assert_allclose(part, full[:, chunk], rtol=2e-3, atol=2e-3)


def test_fully_masked_rows_emit_zero():
    """Padding rows (segment 0) must produce exactly 0 output, not NaN —
    the invariant ring attention's combiner relies on."""
    rng = np.random.default_rng(4)
    q, k, v = _make_qkv(rng, 1, 128, 128, 2, 2, 32)
    seg = jnp.asarray([[1] * 64 + [0] * 64], jnp.int32)
    out = flash_attention(q, k, v, segment_ids=seg, block_q=128, block_k=128)
    assert not np.any(np.isnan(np.asarray(out)))
    np.testing.assert_array_equal(np.asarray(out[:, 64:]), 0.0)


def test_dispatch_uses_pallas_off_tpu():
    """`impl='pallas'` now runs the kernel (interpreted off-TPU) instead of
    raising, and agrees with the XLA path through the dispatcher."""
    rng = np.random.default_rng(5)
    q, k, v = _make_qkv(rng, 1, 128, 128, 2, 2, 32)
    got = dot_product_attention(q, k, v, impl="pallas")
    expected = dot_product_attention(q, k, v, impl="xla")
    np.testing.assert_allclose(got, expected, rtol=2e-3, atol=2e-3)


def test_bf16_inputs():
    rng = np.random.default_rng(6)
    q, k, v = _make_qkv(rng, 1, 128, 128, 2, 2, 32)
    q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
    got = flash_attention(q, k, v, block_q=128, block_k=128)
    assert got.dtype == jnp.bfloat16
    expected = dot_product_attention(q, k, v, impl="xla")
    np.testing.assert_allclose(
        got.astype(np.float32), expected.astype(np.float32), rtol=5e-2, atol=5e-2
    )


@pytest.mark.parametrize("sliding_window", [None, 8])
def test_sinks_match_xla(sliding_window):
    """gpt-oss sink softmax in the kernel (denominator seeded with the sink
    mass) must match the einsum reference on outputs AND on every gradient
    including d_sinks."""
    from llm_training_tpu.ops.attention import dot_product_attention

    rng = np.random.default_rng(60)
    b, s, hq, hkv, d = 2, 32, 4, 2, 64
    q = jnp.asarray(rng.standard_normal((b, s, hq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)
    sinks = jnp.asarray(rng.standard_normal(hq), jnp.float32)
    seg = jnp.asarray(
        np.concatenate([np.ones((b, s - 6)), np.full((b, 4), 2), np.zeros((b, 2))], 1),
        jnp.int32,
    )

    def loss(fn_impl):
        def f(q, k, v, sinks):
            out = dot_product_attention(
                q, k, v, segment_ids=seg, causal=True,
                sliding_window=sliding_window, sinks=sinks, impl=fn_impl,
            )
            return (out * jnp.arange(d)).sum(), out

        return jax.value_and_grad(lambda *a: f(*a)[0], argnums=(0, 1, 2, 3)), f

    (gx, fx), (gp, fp) = loss("xla"), loss("pallas")
    out_x, out_p = fx(q, k, v, sinks)[1], fp(q, k, v, sinks)[1]
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_x), rtol=2e-5, atol=2e-5)

    (_, grads_x), (_, grads_p) = gx(q, k, v, sinks), gp(q, k, v, sinks)
    for name, a, b_ in zip(("dq", "dk", "dv", "d_sinks"), grads_x, grads_p):
        # d_sinks sums hundreds-magnitude row contributions that can cancel
        # to near zero — tolerate the accumulation-order noise
        np.testing.assert_allclose(
            np.asarray(b_), np.asarray(a), rtol=1e-4, atol=1e-3, err_msg=name
        )


@pytest.mark.parametrize("name,hq,hkv,window,cap,packed", CASES, ids=[c[0] for c in CASES])
def test_backward_matches_xla(name, hq, hkv, window, cap, packed):
    """Fast-tier grad parity vs the einsum path across the full config
    grid (causal / GQA / packed / sliding-window / softcap / everything) —
    the BENCH_r04 crash class (`_dq_kernel` arity at trace time) can never
    again reach hardware untraced, and dq/dk/dv stay numerically pinned.
    GQA cases (group 2) drive the dkv kernel's 4-D (bh_kv, nk, group, nq)
    grid."""
    rng = np.random.default_rng(zlib.crc32(("bwd" + name).encode()))
    batch, seq, d = 2, 256, 32
    q, k, v = _make_qkv(rng, batch, seq, seq, hq, hkv, d)
    seg = _packed_segments(rng, batch, seq) if packed else None
    cot = jnp.asarray(_rand(rng, (batch, seq, hq, d)))
    kwargs = dict(segment_ids=seg, causal=True, sliding_window=window, logits_soft_cap=cap)

    gx = jax.grad(
        lambda q, k, v: (dot_product_attention(q, k, v, impl="xla", **kwargs) * cot).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    gp = jax.grad(
        lambda q, k, v: (flash_attention(q, k, v, block_q=128, block_k=128, **kwargs) * cot).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b, grad_name in zip(gx, gp, "qkv"):
        np.testing.assert_allclose(b, a, rtol=3e-3, atol=3e-3, err_msg=f"d{grad_name}")


def test_backward_traces_with_resolved_blocks():
    """The exact r04 call path: NO explicit blocks, so the backward traces
    with tuning-layer-resolved tiles (table/default). A fwd/bwd kernel-arity
    or resolution regression fails here before any hardware round."""
    rng = np.random.default_rng(41)
    q, k, v = _make_qkv(rng, 1, 256, 256, 4, 2, 32)
    cot = jnp.asarray(_rand(rng, (1, 256, 4, 32)))

    gx = jax.grad(
        lambda q, k, v: (dot_product_attention(q, k, v, impl="xla", causal=True) * cot).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    gp = jax.grad(
        lambda q, k, v: (flash_attention(q, k, v, causal=True) * cot).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b, grad_name in zip(gx, gp, "qkv"):
        np.testing.assert_allclose(b, a, rtol=3e-3, atol=3e-3, err_msg=f"d{grad_name}")


def test_backward_independent_fwd_bwd_blocks():
    """fwd and bwd tiles are independent knobs; mixing them must be
    numerically invisible (same grads as uniform tiles)."""
    rng = np.random.default_rng(42)
    q, k, v = _make_qkv(rng, 1, 512, 512, 4, 2, 32)
    seg = jnp.asarray(np.repeat([1, 2], 256)[None], jnp.int32)
    cot = jnp.asarray(_rand(rng, (1, 512, 4, 32)))

    def grads(**blocks):
        return jax.grad(
            lambda q, k, v: (flash_attention(
                q, k, v, segment_ids=seg, causal=True, sliding_window=100, **blocks
            ) * cot).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)

    base = grads(block_q=128, block_k=128, bwd_block_q=128, bwd_block_k=128)
    mixed = grads(block_q=256, block_k=128, bwd_block_q=128, bwd_block_k=256)
    for a, b, grad_name in zip(base, mixed, "qkv"):
        np.testing.assert_allclose(b, a, rtol=1e-5, atol=1e-5, err_msg=f"d{grad_name}")


def test_flash_bwd_flat_kernel_arity():
    """Direct flat-kernel call (the layer ring attention uses): the dq
    pallas_call hands the kernel 2 scalar-prefetch + 8 input refs + 1
    output + 1 scratch, the dkv call 2+8+2+2 on its 4-D grid — a parameter
    drift in either kernel body TypeErrors at trace time right here."""
    from llm_training_tpu.ops.pallas.flash_attention import (
        flash_bwd_flat, flash_fwd_flat,
    )

    rng = np.random.default_rng(43)
    batch, seq, hq, hkv, d = 2, 256, 4, 2, 64
    q = jnp.asarray(_rand(rng, (batch * hq, seq, d)))
    k = jnp.asarray(_rand(rng, (batch * hkv, seq, d)))
    v = jnp.asarray(_rand(rng, (batch * hkv, seq, d)))
    seg = jnp.asarray(np.tile(np.repeat([1, 2], seq // 2)[None], (batch, 1)), jnp.int32)
    kw = dict(num_q_heads=hq, num_kv_heads=hkv, scale=d**-0.5, causal=True,
              block_q=128, block_k=128, interpret=True)

    o, lse = flash_fwd_flat(q, k, v, seg, seg, **kw)
    do = jnp.asarray(_rand(rng, (batch * hq, seq, d)))
    delta = jnp.sum(do * o.astype(jnp.float32), axis=-1)
    dq, dk, dv = flash_bwd_flat(q, k, v, seg, seg, do, lse, delta, **kw)
    assert dq.shape == q.shape and dk.shape == k.shape and dv.shape == v.shape
    for name, g in (("dq", dq), ("dk", dk), ("dv", dv)):
        assert np.isfinite(np.asarray(g)).all(), f"{name} has non-finite entries"


def test_backward_gqa_group4_dkv_grid():
    """Group-4 GQA: the dkv kernel's group axis is length 4, so its
    (g == ng-1) flush gate and q-head indexing get a non-trivial workout."""
    rng = np.random.default_rng(44)
    q, k, v = _make_qkv(rng, 1, 256, 256, 8, 2, 32)
    cot = jnp.asarray(_rand(rng, (1, 256, 8, 32)))
    kwargs = dict(causal=True, sliding_window=70)

    gx = jax.grad(
        lambda q, k, v: (dot_product_attention(q, k, v, impl="xla", **kwargs) * cot).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    gp = jax.grad(
        lambda q, k, v: (flash_attention(q, k, v, block_q=128, block_k=128, **kwargs) * cot).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b, grad_name in zip(gx, gp, "qkv"):
        np.testing.assert_allclose(b, a, rtol=3e-3, atol=3e-3, err_msg=f"d{grad_name}")


@pytest.mark.parametrize("case", [
    # (seq, docs spec, window, gqa, block)  — layouts chosen to stress the
    # DMA-elision index maps: block-aligned boundaries, a doc spanning
    # blocks, windows cutting through doc boundaries, uneven GQA
    dict(seq=384, docs=[128, 128, 128], window=None, hq=4, hkv=1, blk=128),
    dict(seq=384, docs=[256, 128], window=64, hq=4, hkv=2, blk=128),
    dict(seq=512, docs=[128, 256, 128], window=96, hq=8, hkv=2, blk=128),
    dict(seq=512, docs=[384, 128], window=None, hq=2, hkv=2, blk=256),
    dict(seq=512, docs=[64, 192, 256], window=160, hq=4, hkv=4, blk=128),
])
def test_packed_layout_fuzz_fwd_and_grad(case):
    """Structured fuzz over packed layouts x windows x GQA x blocks for
    BOTH passes — the r4 regression (segment-skip on redirected tiles)
    shipped because only random unaligned cuts were tested."""
    rng = np.random.default_rng(zlib.crc32(str(sorted(case.items())).encode()))
    seq, hq, hkv, blk = case["seq"], case["hq"], case["hkv"], case["blk"]
    q, k, v = _make_qkv(rng, 1, seq, seq, hq, hkv, 32)
    seg_row = np.concatenate([
        np.full(n, i + 1) for i, n in enumerate(case["docs"])
    ])
    seg = jnp.asarray(seg_row[None], jnp.int32)
    cot = jnp.asarray(_rand(rng, (1, seq, hq, 32)))
    kw = dict(segment_ids=seg, causal=True, sliding_window=case["window"])

    def loss(fn):
        return jax.value_and_grad(
            lambda q, k, v: (fn(q, k, v).astype(jnp.float32)
                             * cot.astype(jnp.float32)).sum(),
            argnums=(0, 1, 2),
        )

    vx, gx = loss(lambda q, k, v: dot_product_attention(q, k, v, impl="xla", **kw))(q, k, v)
    vp, gp = loss(lambda q, k, v: flash_attention(q, k, v, block_q=blk, block_k=blk, **kw))(q, k, v)
    np.testing.assert_allclose(float(vp), float(vx), rtol=2e-3, atol=1e-2)
    for a, b, name in zip(gx, gp, "qkv"):
        np.testing.assert_allclose(b, a, rtol=3e-3, atol=3e-3, err_msg=f"d{name}")
