"""DPO and ORPO objectives: numerics vs hand-computed formulas, e2e training
on preference pairs, reference-model freezing."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from data_fixtures import preference_dataset, tiny_tokenizer
from llm_training_tpu.data.preference_tuning import (
    PreferenceTuningDataModule,
    PreferenceTuningDataModuleConfig,
)
from llm_training_tpu.lms import DPO, DPOConfig, ORPO, ORPOConfig, ModelProvider
from llm_training_tpu.ops.cross_entropy import fused_linear_log_probs
from llm_training_tpu.optim import OptimConfig
from llm_training_tpu.trainer import Trainer, TrainerConfig

TINY_MODEL = dict(
    model_class="llm_training_tpu.models.Llama",
    model_kwargs=dict(
        vocab_size=512, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=128, compute_dtype="float32",
    ),
)


def test_fused_linear_log_probs_matches_naive():
    rng = np.random.default_rng(0)
    hidden = jnp.asarray(rng.standard_normal((2, 10, 8)).astype(np.float32))
    weight = jnp.asarray(rng.standard_normal((8, 32)).astype(np.float32))
    labels = rng.integers(0, 32, (2, 10))
    labels[0, :3] = -100
    labels = jnp.asarray(labels)

    logps, counts = fused_linear_log_probs(hidden, weight, labels, chunk_size=4)

    logits = hidden @ weight
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    valid = labels != -100
    safe = jnp.where(valid, labels, 0)
    naive = jnp.where(valid, jnp.take_along_axis(log_probs, safe[..., None], -1)[..., 0], 0.0)
    np.testing.assert_allclose(logps, naive.sum(-1), rtol=1e-5)
    np.testing.assert_array_equal(counts, valid.sum(-1))


def _datamodule(batch_size=8):
    module = PreferenceTuningDataModule(
        PreferenceTuningDataModuleConfig(
            tokenizer=tiny_tokenizer(),
            chat_template="chatml",
            batch_size=batch_size,
            max_length=64,
            pad_to_multiple_of=64,
            enable_cache=False,
        )
    )
    module.load_data = lambda: preference_dataset(n=16)
    return module


def _assert_ref_frozen_policy_moved(objective, trainer, state):
    """The frozen ref copy never moved; the policy did."""
    import flax.linen as nn

    params = jax.device_get(nn.meta.unbox(state.params))
    init = jax.device_get(
        nn.meta.unbox(
            objective.init_params(
                jax.random.key(trainer.config.seed),
                {"chosen_input_ids": np.ones((1, 64), np.int32)},
            )
        )
    )
    ref_diff = jax.tree.map(
        lambda a, b: float(np.abs(a - b).max()), params["ref"], init["ref"]
    )
    assert max(jax.tree.leaves(ref_diff)) < 1e-6
    policy_diff = jax.tree.map(
        lambda a, b: float(np.abs(a - b).max()), params["policy"], init["policy"]
    )
    assert max(jax.tree.leaves(policy_diff)) > 1e-4


class _Rec:
    def __init__(self):
        self.metrics = []

    def on_step_end(self, trainer, step, metrics):
        self.metrics.append({k: float(v) for k, v in metrics.items() if np.ndim(v) == 0})


@pytest.mark.slow
def test_dpo_initial_loss_is_log2_and_improves(devices):
    objective = DPO(
        DPOConfig(
            model=ModelProvider(**TINY_MODEL),
            optim=OptimConfig(learning_rate=1e-3, lr_scheduler="constant"),
            beta=0.1,
        )
    )
    rec = _Rec()
    trainer = Trainer(
        TrainerConfig(max_steps=15, log_every_n_steps=1), callbacks=[rec]
    )
    state = trainer.fit(objective, _datamodule())
    # policy == ref at init -> logits 0 -> loss = -log sigmoid(0) = ln 2
    assert rec.metrics[0]["loss"] == pytest.approx(float(np.log(2)), abs=1e-3)
    assert rec.metrics[-1]["loss"] < rec.metrics[0]["loss"]
    assert rec.metrics[-1]["reward_margin"] > 0

    _assert_ref_frozen_policy_moved(objective, trainer, state)


@pytest.mark.slow
def test_dpo_with_pipeline_parallelism(devices):
    """DPO's policy + frozen-ref pair both run the GPipe stage layout on a
    pipe mesh (the trainer's stage cross-check demands they match): initial
    loss is exactly ln 2 (policy == ref through the pipeline), training
    moves it, the ref stays frozen."""
    from llm_training_tpu.parallel import MeshConfig

    pp_model = dict(
        TINY_MODEL,
        model_kwargs=dict(
            TINY_MODEL["model_kwargs"],
            pipeline_stages=2, pipeline_microbatches=4,
        ),
    )
    objective = DPO(
        DPOConfig(
            model=ModelProvider(**pp_model),
            optim=OptimConfig(learning_rate=1e-3, lr_scheduler="constant"),
            beta=0.1,
        )
    )
    rec = _Rec()
    trainer = Trainer(
        TrainerConfig(
            max_steps=8, log_every_n_steps=1,
            mesh=MeshConfig(
                pipeline_parallel_size=2, fsdp_size=2, tensor_parallel_size=2
            ),
        ),
        callbacks=[rec],
    )
    state = trainer.fit(objective, _datamodule())
    assert rec.metrics[0]["loss"] == pytest.approx(float(np.log(2)), abs=1e-3)
    assert rec.metrics[-1]["loss"] < rec.metrics[0]["loss"]
    _assert_ref_frozen_policy_moved(objective, trainer, state)


def test_dpo_label_smoothing_changes_loss():
    cfg = DPOConfig(model=ModelProvider(**TINY_MODEL), label_smoothing=0.2)
    # closed-form check of the smoothed sigmoid loss at a known logit gap
    beta, ls, gap = cfg.beta, cfg.label_smoothing, 2.0
    expected = -np.log(1 / (1 + np.exp(-beta * gap))) * (1 - ls) - np.log(
        1 / (1 + np.exp(beta * gap))
    ) * ls
    got = (
        -jax.nn.log_sigmoid(beta * gap) * (1 - ls)
        - jax.nn.log_sigmoid(-beta * gap) * ls
    )
    np.testing.assert_allclose(float(got), expected, rtol=1e-6)


@pytest.mark.slow
def test_orpo_trains_and_metrics(devices):
    objective = ORPO(
        ORPOConfig(
            model=ModelProvider(**TINY_MODEL),
            optim=OptimConfig(learning_rate=1e-3, lr_scheduler="constant"),
            beta=0.1,
        )
    )
    rec = _Rec()
    trainer = Trainer(
        TrainerConfig(max_steps=15, log_every_n_steps=1), callbacks=[rec]
    )
    trainer.fit(objective, _datamodule())
    first, last = rec.metrics[0], rec.metrics[-1]
    assert last["loss"] < first["loss"]
    assert last["ce_loss"] < first["ce_loss"]
    for m in rec.metrics:
        assert np.isfinite(m["or_loss"]) and np.isfinite(m["log_odds_ratio"])
    # CE dominates at init: loss ~ ce + or
    assert first["loss"] == pytest.approx(first["ce_loss"] + first["or_loss"], rel=1e-5)


@pytest.mark.slow
def test_dpo_on_hybrid_recurrent_family(devices):
    """DPO's policy + frozen-ref two-model setup must also work on a hybrid
    recurrent family (Qwen3-Next: scanned DeltaNet/full-attention period +
    MoE) — the ys-channel scan bodies and the doubled param tree compose."""
    from test_qwen3_next import TINY as TINY_QWEN3NEXT

    objective = DPO(
        DPOConfig(
            model=ModelProvider(
                model_class="llm_training_tpu.models.Qwen3Next",
                # the fixture tokenizer's ids exceed the family test's tiny
                # vocab; size the embedding for it
                model_kwargs={**TINY_QWEN3NEXT, "moe_impl": "dense",
                              "vocab_size": 512},
            ),
            optim=OptimConfig(learning_rate=1e-3, lr_scheduler="constant"),
            beta=0.1,
        )
    )
    rec = _Rec()
    trainer = Trainer(
        TrainerConfig(max_steps=8, log_every_n_steps=1), callbacks=[rec]
    )
    state = trainer.fit(objective, _datamodule())
    # policy == ref at init -> loss = ln 2; training moves it down
    assert rec.metrics[0]["loss"] == pytest.approx(float(np.log(2)), abs=1e-3)
    assert rec.metrics[-1]["loss"] < rec.metrics[0]["loss"]

    _assert_ref_frozen_policy_moved(objective, trainer, state)
