"""Qwen3-Next: hybrid gated DeltaNet + gated attention, HF parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_training_tpu.models.qwen3_next import Qwen3Next, Qwen3NextConfig
from llm_training_tpu.models.qwen3_next.hf_conversion import (
    config_from_hf,
    config_to_hf,
    params_from_hf,
    params_to_hf,
)

TINY = dict(
    vocab_size=128,
    hidden_size=64,
    intermediate_size=112,
    num_hidden_layers=4,  # 3 linear + 1 full
    num_attention_heads=4,
    num_key_value_heads=2,
    head_dim=16,
    max_position_embeddings=128,
    linear_num_key_heads=2,
    linear_num_value_heads=4,
    linear_key_head_dim=16,
    linear_value_head_dim=16,
    num_experts=4,
    num_experts_per_tok=2,
    moe_intermediate_size=32,
    shared_expert_intermediate_size=48,
    compute_dtype="float32",
)


def _hf_tiny(**extra):
    torch = pytest.importorskip("torch")
    from transformers import Qwen3NextConfig as HFConfig
    from transformers import Qwen3NextForCausalLM

    kwargs = dict(TINY)
    kwargs.pop("compute_dtype")
    kwargs.update(attn_implementation="eager", **extra)
    hf_config = HFConfig(**kwargs)
    torch.manual_seed(0)
    return Qwen3NextForCausalLM(hf_config).eval(), hf_config


@pytest.mark.parametrize("seq", [24, 80])
def test_logits_parity_with_hf(seq):
    """Hybrid stack vs HF eager: seq 24 fits one delta chunk; seq 80 spans
    two, exercising the cross-chunk recurrent state."""
    torch = pytest.importorskip("torch")
    hf_model, hf_config = _hf_tiny()
    sd = hf_model.state_dict()
    assert "model.layers.0.linear_attn.in_proj_qkvz.weight" in sd
    assert "model.layers.3.self_attn.q_proj.weight" in sd
    assert "model.layers.0.mlp.shared_expert_gate.weight" in sd
    # make the decay/write dynamics non-trivial
    with torch.no_grad():
        for i in (0, 1, 2):
            sd[f"model.layers.{i}.linear_attn.A_log"].copy_(
                torch.linspace(-1.0, 1.0, 4)
            )
            sd[f"model.layers.{i}.linear_attn.dt_bias"].copy_(
                torch.linspace(-0.5, 0.5, 4)
            )

    cfg = config_from_hf(hf_config, compute_dtype="float32", moe_impl="dense")
    assert cfg.layer_is_linear(0) and not cfg.layer_is_linear(3)
    params = params_from_hf(sd, cfg)
    model = Qwen3Next(cfg)

    ids = np.random.default_rng(70).integers(0, 128, (2, seq))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=4e-4, atol=4e-4)


def test_hf_round_trip():
    hf_model, hf_config = _hf_tiny()
    cfg = config_from_hf(hf_config)
    params = params_from_hf(hf_model.state_dict(), cfg)
    back = params_to_hf(params, cfg)
    sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    assert set(back) == set(sd)
    for key in sd:
        np.testing.assert_array_equal(back[key], sd[key], err_msg=key)


def test_config_round_trip():
    cfg = Qwen3NextConfig(**TINY)
    hf = config_to_hf(cfg)
    assert hf["model_type"] == "qwen3_next"
    cfg2 = config_from_hf(hf, compute_dtype="float32")
    a, b = cfg.model_dump(), cfg2.model_dump()
    a.pop("layer_types"), b.pop("layer_types")
    assert a == b


@pytest.mark.slow
def test_e2e_fit_decreases_loss():
    from conftest import fit_losses

    losses = fit_losses(
        "llm_training_tpu.models.Qwen3Next",
        dict(TINY, enable_gradient_checkpointing=True, moe_impl="dense",
             delta_chunk_size=16),
        max_steps=20, lr=3e-3,
    )
    assert np.mean(losses[-3:]) < np.mean(losses[:3])


@pytest.mark.slow
def test_hf_causal_lm_loads_qwen3_next_checkpoint(tmp_path):
    """End-to-end: HF checkpoint dir -> HFCausalLM router -> Qwen3Next
    (hybrid) -> streamed weights -> logits parity."""
    torch = pytest.importorskip("torch")
    from llm_training_tpu.models import HFCausalLM, HFCausalLMConfig
    from llm_training_tpu.models.hf_io import load_pretrained_params

    hf_model, _ = _hf_tiny()
    hf_model.save_pretrained(tmp_path / "q3n", safe_serialization=True)

    model = HFCausalLM(HFCausalLMConfig(
        hf_path=str(tmp_path / "q3n"), compute_dtype="float32",
        moe_impl="dense",
    ))
    assert isinstance(model, Qwen3Next)
    params = load_pretrained_params(model.config, tmp_path / "q3n")

    ids = np.random.default_rng(71).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(jax.tree.map(jnp.asarray, params), jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=4e-4, atol=4e-4)
