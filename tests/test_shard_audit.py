"""shardcheck audit + strict sharding resolution (docs/static-analysis.md#audit).

Three layers, cheapest first: pure-math hbm_budget units, the strict-mode /
structured-drop regression pins on `parallel/sharding.py`, then the real
family × mesh audit matrix — `jax.eval_shape` only, zero FLOPs, so the full
13-family × 6-mesh sweep costs single-digit seconds on CPU. The capstone is
the copied-tree acceptance test: a seeded one-character typo in a family's
logical-axis metadata must fail `--audit` with a finding naming the leaf
path, the bad axis, and the affected mesh configs.
"""

from __future__ import annotations

import json
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from llm_training_tpu.analysis import hbm_budget
from llm_training_tpu.analysis.shard_audit import (
    AuditConfig,
    FAMILY_REGISTRY,
    FamilySpec,
    MESH_MATRIX,
    run_audit,
    worst_estimate,
)
from llm_training_tpu.parallel.sharding import (
    DEFAULT_LOGICAL_AXIS_RULES,
    KNOWN_LOGICAL_AXES,
    UnknownLogicalAxisError,
    logical_to_spec,
    resolve_spec,
)

REPO_ROOT = Path(__file__).resolve().parents[1]


# ------------------------------------------------------------ hbm_budget


def test_entry_ways_and_shard_ways():
    sizes = {"fsdp": 4, "tensor": 2}
    assert hbm_budget.entry_ways(None, sizes) == 1
    assert hbm_budget.entry_ways("fsdp", sizes) == 4
    assert hbm_budget.entry_ways(("fsdp", "tensor"), sizes) == 8
    assert hbm_budget.entry_ways("sequence", sizes) == 1  # unlisted axis = 1
    # spec shorter than rank pads with unsharded dims
    assert hbm_budget.shard_ways(("fsdp",), (8, 6, 4), sizes) == (4, 1, 1)


def test_per_chip_bytes_ceils_ragged_shards():
    # 10 rows over 4 ways -> ceil(10/4)=3 rows per chip, like GSPMD padding
    assert hbm_budget.per_chip_bytes((10, 2), 4, (4, 1)) == 3 * 2 * 4
    assert hbm_budget.global_bytes((10, 2), 4) == 80


def test_hbm_estimate_totals_and_fits():
    est = hbm_budget.HbmEstimate(
        params_bytes=100, opt_state_bytes=200, kv_cache_bytes=50,
        activation_bytes=25,
    )
    assert est.total_bytes == 375
    assert est.fits(375) and not est.fits(374)
    assert est.to_json()["total_gib"] == pytest.approx(
        375 / hbm_budget.GIB, abs=1e-9
    )


def test_activation_proxy_shards_by_batch_and_seq():
    dense = hbm_budget.activation_proxy_bytes(8, 64, 32, 2, 2, 1, 1)
    sharded = hbm_budget.activation_proxy_bytes(8, 64, 32, 2, 2, 4, 2)
    assert dense == 8 * sharded


# ------------------------------------------- strict resolution regressions


def test_known_axes_registry_matches_rule_table():
    """The registry and the rule table must not drift (the lint rule and
    the audit both treat KNOWN_LOGICAL_AXES as the source of truth)."""
    rule_names = {name for name, _ in DEFAULT_LOGICAL_AXIS_RULES}
    assert set(KNOWN_LOGICAL_AXES) == rule_names | {"layers"}


def test_strict_mode_raises_on_unknown_axis_with_leaf_path():
    with pytest.raises(UnknownLogicalAxisError) as err:
        logical_to_spec(("embd", "mlp"), strict=True, path="mlp/up_proj/kernel")
    message = str(err.value)
    assert "'embd'" in message
    assert "mlp/up_proj/kernel" in message
    assert "replicates" in message.lower()
    assert err.value.axis == "embd"


def test_legacy_mode_still_replicates_unknown_axes():
    """Pinned on purpose: non-strict callers (serving paths resolving with
    partial rule sets) keep the permissive behavior."""
    spec = logical_to_spec(("embd", "mlp"))
    assert tuple(spec) == (None, "tensor")


def test_duplicate_axis_drop_is_structured_not_silent():
    # 'batch' consumes data+fsdp+expert; a later 'embed' dim loses fsdp
    spec, drops = resolve_spec(("batch", "embed"), path="x")
    assert tuple(spec) == (("data", "fsdp", "expert"), None)
    assert len(drops) == 1
    drop = drops[0]
    assert drop.axis == "embed"
    assert drop.mesh_axes == ("fsdp",)
    assert drop.position == 1
    assert drop.path == "x"


def test_clean_resolution_reports_no_drops():
    spec, drops = resolve_spec(("embed", "mlp"))
    assert tuple(spec) == ("fsdp", "tensor") and drops == ()


def test_trainer_state_shardings_are_strict(devices):
    """The Trainer's resolution path must raise (naming the leaf) on an
    unknown axis instead of silently replicating, and surface duplicate
    drops as warnings instead of swallowing them."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from llm_training_tpu.parallel.mesh import MeshConfig, build_mesh
    from llm_training_tpu.trainer import Trainer, TrainerConfig

    trainer = Trainer(TrainerConfig())
    trainer.mesh = build_mesh(MeshConfig(), devices)

    bad = {
        "params": {
            "up_proj": {
                "kernel": nn.Partitioned(
                    jax.ShapeDtypeStruct((8, 8), jnp.float32),
                    names=("embd", "mlp"),
                )
            }
        }
    }
    with pytest.raises(UnknownLogicalAxisError) as err:
        trainer._state_shardings(bad)
    assert "up_proj" in str(err.value)

    good = {
        "params": {
            "kernel": nn.Partitioned(
                jax.ShapeDtypeStruct((8, 8), jnp.float32),
                names=("embed", "mlp"),
            )
        }
    }
    shardings = trainer._state_shardings(good)
    assert tuple(shardings["params"]["kernel"].spec) == ("fsdp", "tensor")


# ------------------------------------------------------- the audit matrix


def test_audit_matrix_all_families_all_meshes_clean():
    """THE regression gate for the ROADMAP-5 rule-table refactor: every
    registered family × every matrix mesh resolves with zero findings at
    HEAD, well inside the acceptance budget."""
    result = run_audit(REPO_ROOT)
    assert result.findings == [], [f.render() for f in result.findings]
    assert len(result.families_run) == 13
    assert set(result.meshes_run) == set(MESH_MATRIX)
    assert result.elapsed_s < 60.0
    # every cell produced an estimate and fits the default budget
    for family in result.families_run:
        cells = result.estimates[family]["meshes"]
        assert set(cells) == set(MESH_MATRIX)
        for cell in cells.values():
            assert cell["fits"] and cell["total_gib"] > 0
    worst = worst_estimate(result.estimates)
    assert worst is not None and worst[2] < 1.0  # tiny registry families


def test_audit_unknown_family_or_mesh_raises():
    with pytest.raises(ValueError, match="unknown family"):
        run_audit(REPO_ROOT, AuditConfig(families=("nope",)))
    with pytest.raises(ValueError, match="unknown mesh"):
        run_audit(REPO_ROOT, AuditConfig(meshes=("nope",)))


def test_audit_hbm_budget_finding_fires():
    """An absurdly small chip budget must flag every (family, mesh) cell
    with the budget + mesh named in the message."""
    result = run_audit(
        REPO_ROOT,
        AuditConfig(families=("llama",), hbm_budget_gib=1e-9),
    )
    rules = {f.rule for f in result.findings}
    assert rules == {"shard-hbm-budget"}
    assert len(result.findings) == len(MESH_MATRIX)
    message = result.findings[0].message
    assert "exceeds" in message and "budget" in message
    assert any(mesh in message for mesh in MESH_MATRIX)
    # the baseline key is mesh- and estimate-independent: all six per-mesh
    # findings for the family collapse to ONE grandfatherable key
    from llm_training_tpu.analysis.shard_audit import _baseline_key

    assert len({_baseline_key(f) for f in result.findings}) == 1


def test_audit_replicated_threshold_finding_fires():
    """With a ~zero size threshold, intentionally-replicated tensors (norm
    weights) trip the large-replicated check on param-capable meshes — and
    the pure-DP mesh (data8) must NOT appear in the mesh list."""
    result = run_audit(
        REPO_ROOT,
        AuditConfig(families=("llama",), replicated_threshold_mib=0.0),
    )
    replicated = [f for f in result.findings if f.rule == "shard-replicated"]
    assert replicated, [f.render() for f in result.findings]
    for finding in replicated:
        assert "data8" not in finding.message.split("mesh(es)")[-1]


def test_audit_indivisible_finding_fires(monkeypatch):
    """A family whose embed dim cannot divide the 8-way fsdp axis is
    flagged with the offending mesh named."""
    import llm_training_tpu.analysis.shard_audit as shard_audit

    ragged = FamilySpec(
        "ragged_llama", "llm_training_tpu.models.llama", "Llama",
        "llm_training_tpu/models/llama/model.py",
        dict(vocab_size=128, hidden_size=36, intermediate_size=64,
             num_hidden_layers=2, num_attention_heads=2,
             num_key_value_heads=2, max_position_embeddings=64),
    )
    monkeypatch.setattr(shard_audit, "FAMILY_REGISTRY", (ragged,))
    result = run_audit(REPO_ROOT, AuditConfig(meshes=("fsdp8", "data8")))
    indivisible = [f for f in result.findings if f.rule == "shard-indivisible"]
    assert indivisible, [f.render() for f in result.findings]
    assert any(
        "36" in f.message and "fsdp8" in f.message for f in indivisible
    )
    # the pure-DP mesh shards nothing, so it can never be the offender
    assert all("data8" not in f.message for f in indivisible)


@pytest.mark.slow
def test_audit_seeded_typo_acceptance(tmp_path):
    """ISSUE 10 acceptance: on a copied tree with a one-character typo in
    llama's q_proj logical axes, `--audit` exits nonzero and the finding
    names the leaf path, the bad axis, and the affected mesh configs.

    Slow-marked: it respawns a full jax interpreter over a copied tree
    (~5s), and the tier-1 suite sits within noise of its 870s timeout
    (1132s measured on a loaded container, 2026-08-04); the in-process
    matrix + strict-mode tests carry the tier-1 signal, and the same
    seeded-typo path is what `test_logical_axis_literal_flags_typos_in_models`
    pins at AST level in every tier-1 run."""
    tree = tmp_path / "tree"
    tree.mkdir()
    shutil.copytree(
        REPO_ROOT / "llm_training_tpu", tree / "llm_training_tpu",
        ignore=shutil.ignore_patterns("__pycache__"),
    )
    shutil.copytree(REPO_ROOT / "config", tree / "config")
    target = tree / "llm_training_tpu/models/llama/model.py"
    source = target.read_text()
    assert '("embed", "heads")' in source
    target.write_text(source.replace('("embed", "heads")', '("embd", "heads")', 1))

    proc = subprocess.run(
        [
            sys.executable, "-m", "llm_training_tpu.analysis", "--audit",
            "--families", "llama", "--meshes", "fsdp8,dryrun_fsdp2_tp2_sp2",
            "--json",
        ],
        cwd=tree,
        capture_output=True,
        text=True,
        env={
            **__import__("os").environ,
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": str(tree),
        },
        timeout=120,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    record = json.loads(proc.stdout)
    findings = record["findings"]
    assert findings and all(f["rule"] == "shard-unknown-axis" for f in findings)
    message = findings[0]["message"]
    assert "q_proj" in message  # the leaf path
    assert "'embd'" in message  # the bad axis
    assert "fsdp8" in message and "dryrun_fsdp2_tp2_sp2" in message  # meshes


# ------------------------------------------------------ report rendering


def test_report_audit_section_renders_and_degrades(tmp_path):
    from llm_training_tpu.telemetry.report import (
        _audit_section,
        _newest_audit_record,
        render_report,
    )

    good = {
        "version": 1, "mode": "audit", "findings": [], "baselined": 0,
        "families": ["llama"], "meshes": ["fsdp8"], "hbm_budget_gib": 32.0,
        "estimates": {"llama": {"meshes": {"fsdp8": {
            "params_gib": 0.001, "opt_state_gib": 0.002,
            "kv_cache_gib": 0.0005, "activation_gib": 0.0005,
            "total_gib": 0.004, "fits": True,
        }}}},
    }
    lines = _audit_section(
        (good, "audit.json"), None, {"hbm/peak_bytes_in_use": 2 * 1024**3}
    )
    text = "\n".join(lines)
    assert "== Audit ==" in text
    assert "shardcheck: OK" in text
    assert "0.004 GiB (llama @ fsdp8" in text
    assert "measured hbm/peak_bytes_in_use: 2.000" in text

    failing = dict(good, findings=[{"rule": "shard-unknown-axis"}] * 2)
    text = "\n".join(_audit_section((failing, "a.json"), None, {}))
    assert "shardcheck: FAIL — 2 finding(s)" in text
    assert "shard-unknown-axis x2" in text

    # malformed record: one honest line, never a crash
    text = "\n".join(_audit_section(({"findings": "what"}, "a.json"), None, {}))
    assert "unreadable audit record" in text

    # absent: the section is omitted entirely
    assert _audit_section(None, None, {}) == []

    # end-to-end: render_report picks audit.json out of the run dir
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    (run_dir / "metrics.jsonl").write_text(
        json.dumps({"step": 1, "loss": 2.0, "steps_per_sec": 1.0}) + "\n"
    )
    (run_dir / "audit.json").write_text(json.dumps(good))
    report = render_report(run_dir)
    assert "== Audit ==" in report and "shardcheck: OK" in report
    # a run dir with no audit json renders no section
    (run_dir / "audit.json").unlink()
    assert "== Audit ==" not in render_report(run_dir)


def test_baseline_keys_are_mesh_selection_stable():
    """A `--meshes`-narrowed `--update-baseline` run and the full precommit
    run must agree on baseline keys: the mesh-list suffix is stripped, and
    unknown-axis messages always name the full matrix."""
    from llm_training_tpu.analysis.engine import Finding
    from llm_training_tpu.analysis.shard_audit import _baseline_key

    # the per-mesh shard counts differ (8-way vs 8-way + 4-way) — the
    # stable prefix must not mention them, only the suffix does
    narrow = Finding(
        rule="shard-indivisible", path="p", line=1,
        message="fam: leaf x dim of size 36 does not divide its sharding "
                "(spec entry 'fsdp') on mesh(es) fsdp8 (8-way); the shard "
                "goes ragged and pads on every chip",
    )
    full = Finding(
        rule="shard-indivisible", path="p", line=1,
        message="fam: leaf x dim of size 36 does not divide its sharding "
                "(spec entry 'fsdp') on mesh(es) fsdp8 (8-way), "
                "data2_fsdp4 (4-way); the shard goes ragged and pads on "
                "every chip",
    )
    assert _baseline_key(narrow) == _baseline_key(full)
    # unknown-axis findings name every matrix mesh regardless of --meshes
    result = run_audit(
        REPO_ROOT, AuditConfig(families=("llama",), meshes=("fsdp8",))
    )
    assert result.meshes_run == ("fsdp8",)


def test_registry_covers_thirteen_families():
    names = [f.name for f in FAMILY_REGISTRY]
    assert len(names) == len(set(names)) == 13
    # the registry must exercise scan stacks, MoE, and pipeline layouts
    assert {"llama", "llama_moe", "llama_pp"} <= set(names)
