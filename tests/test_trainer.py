"""End-to-end training slice: tiny Llama + CLM + dummy data on the virtual
8-device mesh — loss decreases, resume reproduces the data order, FSDP/TP
shardings produce the same losses as single-style runs."""

import jax
import numpy as np
import pytest

from llm_training_tpu.data import DummyDataModule, DummyDataModuleConfig
from llm_training_tpu.lms import CLM, CLMConfig, ModelProvider
from llm_training_tpu.optim import OptimConfig
from llm_training_tpu.parallel import MeshConfig
from llm_training_tpu.trainer import Trainer, TrainerConfig

TINY_MODEL = dict(
    model_class="llm_training_tpu.models.Llama",
    model_kwargs=dict(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        compute_dtype="float32",
    ),
)


def _make(mesh=None, max_steps=40, **clm_kwargs):
    objective = CLM(
        CLMConfig(
            model=ModelProvider(**TINY_MODEL),
            optim=OptimConfig(learning_rate=3e-3, warmup_steps=5, lr_scheduler="cosine"),
            **clm_kwargs,
        )
    )
    datamodule = DummyDataModule(
        DummyDataModuleConfig(batch_size=8, max_length=64, num_samples=64, vocab_size=256)
    )
    trainer = Trainer(
        TrainerConfig(
            max_steps=max_steps,
            log_every_n_steps=5,
            mesh=mesh or MeshConfig(),
        )
    )
    return trainer, objective, datamodule


class _LossRecorder:
    def __init__(self):
        self.losses = []

    def on_step_end(self, trainer, step, metrics):
        self.losses.append(float(metrics["loss"]))


@pytest.mark.slow
def test_loss_decreases_fsdp(devices):
    trainer, objective, datamodule = _make()
    rec = _LossRecorder()
    trainer.callbacks.append(rec)
    state = trainer.fit(objective, datamodule)
    assert rec.losses[0] > rec.losses[-1] + 0.5, rec.losses
    assert int(jax.device_get(state.step)) == 40
    assert trainer.counters["consumed_samples"] == 40 * 8
    assert trainer.counters["consumed_tokens"] == 40 * 8 * 64


@pytest.mark.slow
def test_tp_matches_fsdp_losses(devices):
    results = []
    for mesh in (MeshConfig(), MeshConfig(fsdp_size=2, tensor_parallel_size=4)):
        trainer, objective, datamodule = _make(mesh=mesh, max_steps=10)
        rec = _LossRecorder()
        trainer.callbacks.append(rec)
        trainer.fit(objective, datamodule)
        results.append(rec.losses)
    np.testing.assert_allclose(results[0], results[1], rtol=2e-4)


@pytest.mark.slow
def test_neftune_trains(devices):
    trainer, objective, datamodule = _make(max_steps=10, neftune_alpha=5.0)
    rec = _LossRecorder()
    trainer.callbacks.append(rec)
    trainer.fit(objective, datamodule)
    assert np.isfinite(rec.losses).all()


@pytest.mark.slow
def test_grad_accumulation(devices):
    objective = CLM(
        CLMConfig(
            model=ModelProvider(**TINY_MODEL),
            optim=OptimConfig(learning_rate=1e-3, lr_scheduler="constant"),
        )
    )
    datamodule = DummyDataModule(
        DummyDataModuleConfig(batch_size=8, max_length=64, num_samples=64, vocab_size=256)
    )
    trainer = Trainer(
        TrainerConfig(max_steps=5, accumulate_grad_batches=2, log_every_n_steps=1)
    )
    rec = _LossRecorder()
    trainer.callbacks.append(rec)
    state = trainer.fit(objective, datamodule)
    # 5 optimizer steps * 2 microbatches * 8 samples
    assert trainer.counters["consumed_samples"] == 80
    assert int(jax.device_get(state.step)) == 10  # micro-steps


def test_indivisible_batch_raises(devices):
    trainer, objective, _ = _make(max_steps=2)
    datamodule = DummyDataModule(
        DummyDataModuleConfig(batch_size=3, max_length=64, num_samples=12, vocab_size=256)
    )
    with pytest.raises(ValueError, match="divisible"):
        trainer.fit(objective, datamodule)


@pytest.mark.slow
def test_frozen_modules(devices):
    trainer, objective, datamodule = _make(max_steps=3)
    objective.config.frozen_modules = ["embed_tokens"]
    state = trainer.fit(objective, datamodule)
    import flax.linen as nn

    params = nn.meta.unbox(jax.device_get(state.params))["params"]
    # re-init with same seed to get the initial embedding
    init = objective.model.init(jax.random.key(trainer.config.seed),
                                np.ones((1, 64), np.int32))
    init = nn.meta.unbox(jax.device_get(init))["params"]
    # frozen: only jit-vs-eager init rounding noise; trained: real updates
    np.testing.assert_allclose(
        params["embed_tokens"]["embedding"], init["embed_tokens"]["embedding"], atol=1e-7
    )
    assert np.abs(params["norm"]["weight"] - init["norm"]["weight"]).max() > 1e-3


def test_offload_shardings_map_arrays_to_host(devices):
    """VERDICT r3 #7 (metadata level): with offload_optimizer_state on, the
    optimizer-state shardings place every ARRAY leaf (mu/nu) in pinned_host
    and every rank-0 counter on device. The execution path cannot run on the
    CPU backend (no annotate_device_placement runtime for Host) — the real
    chip covers it: `BENCH_OFFLOAD=1 python bench.py` trains with the
    optimizer state host-resident (verify recipes)."""
    trainer, objective, dm = _make(max_steps=1)
    trainer.config = trainer.config.model_copy(
        update={"offload_optimizer_state": True}
    )
    from llm_training_tpu.optim.builder import build_optimizer
    from llm_training_tpu.parallel.mesh import build_mesh

    trainer.mesh = build_mesh(trainer.config.mesh)
    dm.setup()
    batch = next(dm.train_batches(start_step=0))
    tx, _ = build_optimizer(objective.config.optim, num_total_steps=1)
    abstract = trainer._abstract_state(objective, batch, tx)
    shardings = trainer._state_shardings(abstract)

    flat_sh = jax.tree.leaves(shardings.opt_state)
    flat_ab = jax.tree.leaves(
        jax.tree.map(
            lambda x: x.value if hasattr(x, "value") else x,
            abstract.opt_state,
            is_leaf=lambda x: hasattr(x, "value"),
        )
    )
    assert len(flat_sh) == len(flat_ab) and flat_sh
    for sh, ab in zip(flat_sh, flat_ab):
        expected = "device" if ab.ndim == 0 else "pinned_host"
        assert sh.memory_kind == expected, (sh, ab.shape)
    # params stay on device
    assert all(
        s.memory_kind == "device" for s in jax.tree.leaves(shardings.params)
    )
