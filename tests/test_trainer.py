"""End-to-end training slice: tiny Llama + CLM + dummy data on the virtual
8-device mesh — loss decreases, resume reproduces the data order, FSDP/TP
shardings produce the same losses as single-style runs."""

import jax
import numpy as np
import pytest

from llm_training_tpu.data import DummyDataModule, DummyDataModuleConfig
from llm_training_tpu.lms import CLM, CLMConfig, ModelProvider
from llm_training_tpu.optim import OptimConfig
from llm_training_tpu.parallel import MeshConfig
from llm_training_tpu.trainer import Trainer, TrainerConfig

TINY_MODEL = dict(
    model_class="llm_training_tpu.models.Llama",
    model_kwargs=dict(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        compute_dtype="float32",
    ),
)


def _make(mesh=None, max_steps=40, **clm_kwargs):
    objective = CLM(
        CLMConfig(
            model=ModelProvider(**TINY_MODEL),
            optim=OptimConfig(learning_rate=3e-3, warmup_steps=5, lr_scheduler="cosine"),
            **clm_kwargs,
        )
    )
    datamodule = DummyDataModule(
        DummyDataModuleConfig(batch_size=8, max_length=64, num_samples=64, vocab_size=256)
    )
    trainer = Trainer(
        TrainerConfig(
            max_steps=max_steps,
            log_every_n_steps=5,
            mesh=mesh or MeshConfig(),
        )
    )
    return trainer, objective, datamodule


class _LossRecorder:
    def __init__(self):
        self.losses = []

    def on_step_end(self, trainer, step, metrics):
        self.losses.append(float(metrics["loss"]))


@pytest.mark.slow
def test_loss_decreases_fsdp(devices):
    trainer, objective, datamodule = _make()
    rec = _LossRecorder()
    trainer.callbacks.append(rec)
    state = trainer.fit(objective, datamodule)
    assert rec.losses[0] > rec.losses[-1] + 0.5, rec.losses
    assert int(jax.device_get(state.step)) == 40
    assert trainer.counters["consumed_samples"] == 40 * 8
    assert trainer.counters["consumed_tokens"] == 40 * 8 * 64


@pytest.mark.slow
def test_tp_matches_fsdp_losses(devices):
    results = []
    for mesh in (MeshConfig(), MeshConfig(fsdp_size=2, tensor_parallel_size=4)):
        trainer, objective, datamodule = _make(mesh=mesh, max_steps=10)
        rec = _LossRecorder()
        trainer.callbacks.append(rec)
        trainer.fit(objective, datamodule)
        results.append(rec.losses)
    np.testing.assert_allclose(results[0], results[1], rtol=2e-4)


@pytest.mark.slow
def test_neftune_trains(devices):
    trainer, objective, datamodule = _make(max_steps=10, neftune_alpha=5.0)
    rec = _LossRecorder()
    trainer.callbacks.append(rec)
    trainer.fit(objective, datamodule)
    assert np.isfinite(rec.losses).all()


@pytest.mark.slow
def test_grad_accumulation(devices):
    objective = CLM(
        CLMConfig(
            model=ModelProvider(**TINY_MODEL),
            optim=OptimConfig(learning_rate=1e-3, lr_scheduler="constant"),
        )
    )
    datamodule = DummyDataModule(
        DummyDataModuleConfig(batch_size=8, max_length=64, num_samples=64, vocab_size=256)
    )
    trainer = Trainer(
        TrainerConfig(max_steps=5, accumulate_grad_batches=2, log_every_n_steps=1)
    )
    rec = _LossRecorder()
    trainer.callbacks.append(rec)
    state = trainer.fit(objective, datamodule)
    # 5 optimizer steps * 2 microbatches * 8 samples
    assert trainer.counters["consumed_samples"] == 80
    assert int(jax.device_get(state.step)) == 10  # micro-steps


def test_indivisible_batch_raises(devices):
    trainer, objective, _ = _make(max_steps=2)
    datamodule = DummyDataModule(
        DummyDataModuleConfig(batch_size=3, max_length=64, num_samples=12, vocab_size=256)
    )
    with pytest.raises(ValueError, match="divisible"):
        trainer.fit(objective, datamodule)


@pytest.mark.slow
def test_frozen_modules(devices):
    trainer, objective, datamodule = _make(max_steps=3)
    objective.config.frozen_modules = ["embed_tokens"]
    state = trainer.fit(objective, datamodule)
    import flax.linen as nn

    params = nn.meta.unbox(jax.device_get(state.params))["params"]
    # re-init with same seed to get the initial embedding
    init = objective.model.init(jax.random.key(trainer.config.seed),
                                np.ones((1, 64), np.int32))
    init = nn.meta.unbox(jax.device_get(init))["params"]
    # frozen: only jit-vs-eager init rounding noise; trained: real updates
    np.testing.assert_allclose(
        params["embed_tokens"]["embedding"], init["embed_tokens"]["embedding"], atol=1e-7
    )
    assert np.abs(params["norm"]["weight"] - init["norm"]["weight"]).max() > 1e-3


def test_blocked_offload_update_matches_whole_tree(devices):
    """Numeric parity of the per-leaf blocked update (global clip factored
    out + per-leaf tx.update over zipped leaves) against the whole-tree
    chain(clip, adamw) step. Runs on CPU with device memory kinds — the
    blocked step's MATH is memory-kind agnostic, only the pinned_host
    placement needs the chip."""
    import flax.linen as nn

    from llm_training_tpu.optim.builder import build_optimizer
    from llm_training_tpu.parallel.mesh import MeshConfig, build_mesh
    from llm_training_tpu.trainer.state import TrainState
    from llm_training_tpu.trainer.trainer import LOGICAL_AXIS_RULES

    trainer, objective, dm = _make(max_steps=1)
    trainer.mesh = build_mesh(MeshConfig(fsdp_size=4, tensor_parallel_size=2))
    dm.setup()
    batch = next(dm.train_batches(start_step=0))

    tx_full, _ = build_optimizer(objective.config.optim, num_total_steps=4)
    clip_free = objective.config.optim.model_copy(update={"grad_clip_norm": None})
    tx_core, _ = build_optimizer(clip_free, num_total_steps=4)

    with trainer.mesh, nn.logical_axis_rules(LOGICAL_AXIS_RULES):
        params = nn.meta.unbox(
            objective.init_params(jax.random.key(0), batch)
        )
        # whole-tree reference step
        trainer._blocked_offload = False
        state_a = TrainState.create(params, tx_full.init(params), jax.random.key(7))
        step_a = trainer._build_step(objective, tx_full)
        new_a, metrics_a = jax.jit(step_a)(state_a, batch)

        # blocked step, device memory kinds (no offload placement)
        trainer._blocked_offload = True
        trainer._clip_norm = objective.config.optim.grad_clip_norm
        opt_blocks = trainer._opt_init(tx_core, params)
        state_b = TrainState.create(params, opt_blocks, jax.random.key(7))
        dev_sharding = jax.sharding.NamedSharding(
            trainer.mesh, jax.sharding.PartitionSpec()
        )
        opt_dev = tuple(
            jax.tree.map(lambda _: dev_sharding, blk) for blk in opt_blocks
        )
        step_b = trainer._build_blocked_offload_step(
            objective, tx_core, opt_dev, opt_dev
        )
        new_b, metrics_b = jax.jit(step_b)(state_b, batch)

    np.testing.assert_allclose(
        float(metrics_a["grad_norm"]), float(metrics_b["grad_norm"]), rtol=1e-6
    )
    flat_a = jax.tree.leaves(new_a.params)
    flat_b = jax.tree.leaves(new_b.params)
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-6, atol=2e-6
        )


def test_blocked_offload_state_structure(devices):
    """Overlapped offload (VERDICT r4 #5): with the blocked path active the
    optimizer state is one block per param leaf (independent copy/update
    chains for transfer/compute overlap), every mu/nu maps to the
    backend's HOST memory kind with the PARAM's sharding (not replicated),
    and counters stay in compute memory. On TPU/GPU that is
    pinned_host/device; a CPU backend addresses only unpinned_host, so
    both kinds collapse and offload degrades to a same-memory placement —
    the metadata path is identical either way (real-chip execution:
    `BENCH_OFFLOAD=1 python bench.py`)."""
    import flax.linen as nn
    from jax.sharding import PartitionSpec

    from llm_training_tpu.optim.builder import build_optimizer
    from llm_training_tpu.parallel.mesh import MeshConfig, build_mesh
    from llm_training_tpu.trainer.trainer import (
        LOGICAL_AXIS_RULES,
        offload_memory_kinds,
    )

    trainer, objective, dm = _make(max_steps=1)
    trainer.config = trainer.config.model_copy(
        update={"offload_optimizer_state": True}
    )
    trainer.mesh = build_mesh(MeshConfig(fsdp_size=4, tensor_parallel_size=2))
    trainer._blocked_offload = True
    trainer._clip_norm = objective.config.optim.grad_clip_norm
    clip_free = objective.config.optim.model_copy(update={"grad_clip_norm": None})
    tx, _ = build_optimizer(clip_free, num_total_steps=1)
    dm.setup()
    batch = next(dm.train_batches(start_step=0))
    with trainer.mesh, nn.logical_axis_rules(LOGICAL_AXIS_RULES):
        abstract = trainer._abstract_state(objective, batch, tx)
        shardings = trainer._state_shardings(abstract)

    n_param_leaves = len(
        jax.tree.leaves(
            jax.tree.map(
                lambda x: 0, abstract.params,
                is_leaf=lambda x: hasattr(x, "value"),
            )
        )
    )
    assert isinstance(abstract.opt_state, tuple)
    assert len(abstract.opt_state) == n_param_leaves
    compute_kind, host_kind = offload_memory_kinds()
    host_specs = []
    for blk_sh, blk_ab in zip(shardings.opt_state, abstract.opt_state):
        unboxed = jax.tree.map(
            lambda x: x.value if hasattr(x, "value") else x,
            blk_ab, is_leaf=lambda x: hasattr(x, "value"),
        )
        for s, a in zip(jax.tree.leaves(blk_sh), jax.tree.leaves(unboxed)):
            expected = compute_kind if a.ndim == 0 else host_kind
            assert s.memory_kind == expected, (s, a.shape)
            if a.ndim > 0:
                host_specs.append(s.spec)
    # mu/nu inherit the param shardings — offloaded state still shards
    assert any(spec != PartitionSpec() for spec in host_specs)


def test_offload_shardings_map_arrays_to_host(devices):
    """VERDICT r3 #7 (metadata level): with offload_optimizer_state on, the
    optimizer-state shardings place every ARRAY leaf (mu/nu) in the
    backend's host memory kind and every rank-0 counter in compute memory.
    Kinds resolve per backend (offload_memory_kinds): pinned_host/device
    on TPU/GPU; a CPU device addresses only unpinned_host, so the kinds
    collapse and the placement is a same-memory no-op — the resolution
    path is what this pins (the real chip covers execution:
    `BENCH_OFFLOAD=1 python bench.py`, verify recipes)."""
    trainer, objective, dm = _make(max_steps=1)
    trainer.config = trainer.config.model_copy(
        update={"offload_optimizer_state": True}
    )
    from llm_training_tpu.optim.builder import build_optimizer
    from llm_training_tpu.parallel.mesh import build_mesh
    from llm_training_tpu.trainer.trainer import offload_memory_kinds

    trainer.mesh = build_mesh(trainer.config.mesh)
    dm.setup()
    batch = next(dm.train_batches(start_step=0))
    tx, _ = build_optimizer(objective.config.optim, num_total_steps=1)
    abstract = trainer._abstract_state(objective, batch, tx)
    shardings = trainer._state_shardings(abstract)
    compute_kind, host_kind = offload_memory_kinds()

    flat_sh = jax.tree.leaves(shardings.opt_state)
    flat_ab = jax.tree.leaves(
        jax.tree.map(
            lambda x: x.value if hasattr(x, "value") else x,
            abstract.opt_state,
            is_leaf=lambda x: hasattr(x, "value"),
        )
    )
    assert len(flat_sh) == len(flat_ab) and flat_sh
    for sh, ab in zip(flat_sh, flat_ab):
        expected = compute_kind if ab.ndim == 0 else host_kind
        assert sh.memory_kind == expected, (sh, ab.shape)
    # params keep the default (compute) placement — on a backend with a
    # distinct host kind they must NOT have been dragged along
    if host_kind == "pinned_host":
        assert all(
            s.memory_kind != host_kind
            for s in jax.tree.leaves(shardings.params)
        )
    else:
        assert all(
            s.memory_kind == compute_kind
            for s in jax.tree.leaves(shardings.params)
        )
