"""Golden tests for the ops layer: numerics vs. pure-numpy references and,
for RoPE variants, vs. HF transformers' implementations (torch CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_training_tpu.ops import (
    apply_rope,
    compute_rope_cos_sin,
    compute_rope_frequencies,
    cross_entropy,
    dot_product_attention,
    fused_linear_cross_entropy,
    make_attention_mask,
    rms_norm,
    RoPEConfig,
    shift_labels,
    silu_mul,
    swiglu,
)


def test_rms_norm_matches_numpy():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((4, 64)).astype(np.float32)
    w = rng.standard_normal(64).astype(np.float32)
    eps = 1e-6
    expected = x / np.sqrt((x**2).mean(-1, keepdims=True) + eps) * w
    np.testing.assert_allclose(rms_norm(jnp.asarray(x), jnp.asarray(w), eps), expected, rtol=1e-5)


def test_rms_norm_bf16_upcasts():
    x = jnp.full((2, 128), 3.0, dtype=jnp.bfloat16)
    w = jnp.ones(128, dtype=jnp.bfloat16)
    out = rms_norm(x, w)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32), 1.0, rtol=1e-2)


def test_shift_labels():
    labels = jnp.array([[1, 2, 3, 4]])
    out = shift_labels(labels)
    np.testing.assert_array_equal(out, [[2, 3, 4, -100]])


def test_cross_entropy_matches_numpy():
    rng = np.random.default_rng(1)
    logits = rng.standard_normal((8, 32)).astype(np.float32)
    labels = rng.integers(0, 32, size=8)
    labels[2] = -100
    log_probs = logits - np.log(np.exp(logits - logits.max(-1, keepdims=True)).sum(-1, keepdims=True)) - logits.max(-1, keepdims=True)
    valid = labels != -100
    expected = -log_probs[np.arange(8)[valid], labels[valid]].mean()
    got = cross_entropy(jnp.asarray(logits), jnp.asarray(labels))
    np.testing.assert_allclose(got, expected, rtol=1e-5)


def test_fused_linear_cross_entropy_matches_unfused():
    rng = np.random.default_rng(2)
    hidden = rng.standard_normal((10, 16)).astype(np.float32)
    weight = rng.standard_normal((16, 50)).astype(np.float32)
    labels = rng.integers(0, 50, size=10)
    labels[0] = -100

    logits = jnp.asarray(hidden) @ jnp.asarray(weight)
    expected = cross_entropy(logits, jnp.asarray(labels))

    total, count = fused_linear_cross_entropy(
        jnp.asarray(hidden), jnp.asarray(weight), jnp.asarray(labels), chunk_size=3
    )
    np.testing.assert_allclose(total / count, expected, rtol=1e-5)


def test_fused_linear_cross_entropy_grads_match():
    rng = np.random.default_rng(3)
    hidden = jnp.asarray(rng.standard_normal((12, 8)).astype(np.float32))
    weight = jnp.asarray(rng.standard_normal((8, 20)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 20, size=12))

    def unfused(h, w):
        return cross_entropy(h @ w, labels)

    def fused(h, w):
        total, count = fused_linear_cross_entropy(h, w, labels, chunk_size=5)
        return total / count

    g1 = jax.grad(unfused, argnums=(0, 1))(hidden, weight)
    g2 = jax.grad(fused, argnums=(0, 1))(hidden, weight)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def test_swiglu_variants_agree():
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((3, 8)).astype(np.float32))
    w_gate = jnp.asarray(rng.standard_normal((8, 16)).astype(np.float32))
    w_up = jnp.asarray(rng.standard_normal((8, 16)).astype(np.float32))
    separate = silu_mul(x @ w_gate, x @ w_up)
    fused = swiglu(x, jnp.concatenate([w_gate, w_up], axis=-1))
    np.testing.assert_allclose(separate, fused, rtol=1e-5)


# ---------------------------------------------------------------- RoPE


def _hf_rope(rope_type, head_dim, base, max_pos, scaling, seq_len=None):
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig, Phi3Config
    from transformers.modeling_rope_utils import ROPE_INIT_FUNCTIONS

    rope_scaling = dict(scaling or {}, rope_type=rope_type) if rope_type != "default" else None
    if rope_type == "longrope":
        # Phi3Config validates rope_scaling to exactly {type, short_factor,
        # long_factor}; transformers derives factor from the ratio of
        # max_position_embeddings to original_max_position_embeddings.
        config = Phi3Config(
            hidden_size=head_dim * 4, num_attention_heads=4,
            rope_theta=base, max_position_embeddings=max_pos,
            original_max_position_embeddings=max_pos,
            rope_scaling={
                "type": "longrope",
                "short_factor": scaling["short_factor"],
                "long_factor": scaling["long_factor"],
            },
        )
        config.max_position_embeddings = int(max_pos * scaling["factor"])
    else:
        config = LlamaConfig(
            hidden_size=head_dim * 4, num_attention_heads=4,
            rope_theta=base, max_position_embeddings=max_pos,
            rope_scaling=rope_scaling,
        )
    inv_freq, attention_factor = ROPE_INIT_FUNCTIONS[rope_type](config, "cpu", seq_len=seq_len)
    return inv_freq.numpy(), attention_factor


@pytest.mark.parametrize(
    "rope_type,scaling",
    [
        ("default", None),
        ("linear", {"factor": 4.0}),
        ("dynamic", {"factor": 4.0}),
        ("yarn", {"factor": 4.0}),
        ("llama3", {"factor": 8.0, "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                    "original_max_position_embeddings": 8192}),
    ],
)
def test_rope_variants_match_transformers(rope_type, scaling):
    head_dim, base, max_pos = 64, 10000.0, 4096 if rope_type != "llama3" else 131072
    config = RoPEConfig(
        type=rope_type, base=base, dim=head_dim,
        max_position_embeddings=max_pos, scaling=scaling,
    )
    inv_freq, attention_factor = compute_rope_frequencies(config)
    hf_inv_freq, hf_attention_factor = _hf_rope(rope_type, head_dim, base, max_pos, scaling)
    np.testing.assert_allclose(inv_freq, hf_inv_freq, rtol=1e-5)
    assert attention_factor == pytest.approx(hf_attention_factor)


def test_rope_longrope_matches_transformers():
    head_dim, base, max_pos = 32, 10000.0, 4096
    rng = np.random.default_rng(5)
    scaling = {
        "factor": 32.0,
        "short_factor": rng.uniform(1.0, 2.0, head_dim // 2).tolist(),
        "long_factor": rng.uniform(2.0, 8.0, head_dim // 2).tolist(),
    }
    config = RoPEConfig(
        type="longrope", base=base, dim=head_dim,
        max_position_embeddings=max_pos, scaling=scaling,
    )
    # seq_len passed explicitly to both sides: the reference defaults to the
    # long branch when seq_len is None (rope_utils.py longrope), current
    # transformers defaults to the short branch, so only explicit seq_len is
    # comparable across both.
    for seq_len in (max_pos // 2, max_pos * 8):
        inv_freq, attention_factor = compute_rope_frequencies(config, seq_len=seq_len)
        hf_inv_freq, hf_attention_factor = _hf_rope(
            "longrope", head_dim, base, max_pos, scaling, seq_len=seq_len
        )
        np.testing.assert_allclose(inv_freq, hf_inv_freq, rtol=1e-5)
        assert attention_factor == pytest.approx(hf_attention_factor)
    # default (seq_len=None) follows the reference: long branch
    inv_freq, _ = compute_rope_frequencies(config)
    long_expected = 1.0 / (
        np.asarray(scaling["long_factor"], np.float32)
        * base ** (np.arange(0, head_dim, 2, dtype=np.float32) / head_dim)
    )
    np.testing.assert_allclose(inv_freq, long_expected, rtol=1e-5)


def test_rope_dynamic_grows_with_seq_len():
    config = RoPEConfig(type="dynamic", base=10000.0, dim=32,
                        max_position_embeddings=2048, scaling={"factor": 2.0})
    short, _ = compute_rope_frequencies(config, seq_len=1024)
    long, _ = compute_rope_frequencies(config, seq_len=8192)
    assert (long[1:] < short[1:]).all()


def test_rope_validators():
    with pytest.raises(ValueError):
        RoPEConfig(type="linear", base=1e4, dim=32, max_position_embeddings=128)
    with pytest.raises(ValueError):
        RoPEConfig(type="nope", base=1e4, dim=32, max_position_embeddings=128)
    with pytest.raises(ValueError):
        RoPEConfig(type="longrope", base=1e4, dim=32, max_position_embeddings=128,
                   scaling={"factor": 2.0, "short_factor": [1.0], "long_factor": [1.0]})


def test_apply_rope_matches_manual():
    rng = np.random.default_rng(6)
    batch, seq, heads, dim = 2, 5, 3, 8
    q = jnp.asarray(rng.standard_normal((batch, seq, heads, dim)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((batch, seq, 1, dim)).astype(np.float32))
    config = RoPEConfig(type="default", base=10000.0, dim=dim, max_position_embeddings=seq)
    inv_freq, factor = compute_rope_frequencies(config)
    positions = jnp.arange(seq)
    cos, sin = compute_rope_cos_sin(inv_freq, positions, factor)
    q_rot, k_rot = apply_rope(q, k, cos, sin)

    # manual complex-number rotation on the (i, i + dim/2) pairs
    theta = np.asarray(positions)[:, None] * np.asarray(inv_freq)[None, :]
    q_np = np.asarray(q)
    q1, q2 = q_np[..., : dim // 2], q_np[..., dim // 2:]
    rot1 = q1 * np.cos(theta)[None, :, None] - q2 * np.sin(theta)[None, :, None]
    rot2 = q2 * np.cos(theta)[None, :, None] + q1 * np.sin(theta)[None, :, None]
    expected = np.concatenate([rot1, rot2], -1)
    np.testing.assert_allclose(q_rot, expected, rtol=1e-5, atol=1e-6)
    # norms preserved
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(k_rot), axis=-1), np.linalg.norm(np.asarray(k), axis=-1), rtol=1e-5
    )


# ---------------------------------------------------------------- attention


def _naive_attention(q, k, v, mask):
    """Per-head numpy attention with an explicit boolean mask."""
    b, sq, hq, d = q.shape
    hkv = k.shape[2]
    group = hq // hkv
    out = np.zeros_like(q)
    for bi in range(b):
        for h in range(hq):
            kh = h // group
            scores = (q[bi, :, h] @ k[bi, :, kh].T) / np.sqrt(d)
            scores = np.where(mask[bi, 0], scores, -1e30)
            probs = np.exp(scores - scores.max(-1, keepdims=True))
            probs /= probs.sum(-1, keepdims=True)
            out[bi, :, h] = probs @ v[bi, :, kh]
    return out


def test_attention_causal_matches_naive():
    rng = np.random.default_rng(7)
    q = rng.standard_normal((2, 6, 4, 8)).astype(np.float32)
    k = rng.standard_normal((2, 6, 2, 8)).astype(np.float32)
    v = rng.standard_normal((2, 6, 2, 8)).astype(np.float32)
    mask = np.asarray(make_attention_mask(None, None, 6, 6, causal=True))
    mask = np.broadcast_to(mask, (2, 1, 6, 6))
    expected = _naive_attention(q, k, v, mask)
    got = dot_product_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), impl="xla")
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_packed_attention_equals_separate_forwards():
    """The reference's no-cross-contamination claim (README.md:107-115):
    a packed row with segment ids must equal running each document alone."""
    rng = np.random.default_rng(8)
    d, h = 8, 2
    lens = [3, 4, 2]
    seq = sum(lens) + 1  # one padding token
    q = rng.standard_normal((1, seq, h, d)).astype(np.float32)
    k = rng.standard_normal((1, seq, h, d)).astype(np.float32)
    v = rng.standard_normal((1, seq, h, d)).astype(np.float32)
    segment_ids = jnp.asarray([[1] * 3 + [2] * 4 + [3] * 2 + [0]])

    packed = dot_product_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        segment_ids=segment_ids, impl="xla",
    )

    start = 0
    for length in lens:
        sl = slice(start, start + length)
        alone = dot_product_attention(
            jnp.asarray(q[:, sl]), jnp.asarray(k[:, sl]), jnp.asarray(v[:, sl]), impl="xla"
        )
        np.testing.assert_allclose(packed[:, sl], alone, rtol=1e-4, atol=1e-5)
        start += length


def test_sliding_window_attention():
    rng = np.random.default_rng(9)
    q = rng.standard_normal((1, 8, 1, 4)).astype(np.float32)
    k = rng.standard_normal((1, 8, 1, 4)).astype(np.float32)
    v = rng.standard_normal((1, 8, 1, 4)).astype(np.float32)
    window = 3
    mask = np.asarray(make_attention_mask(None, None, 8, 8, causal=True, sliding_window=window))
    # row i attends to keys (i-window, i]
    for i in range(8):
        for j in range(8):
            assert mask[0, 0, i, j] == (j <= i and i - j < window)
    expected = _naive_attention(q, k, v, np.broadcast_to(mask, (1, 1, 8, 8)))
    got = dot_product_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), sliding_window=window, impl="xla"
    )
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_make_attention_mask_q_offset_decode_rows():
    """The KV-cache decode invariant (infer/, docs/inference.md): a 1-row
    mask built with q_offset=i (+ sliding_window + packed/left-pad segment
    ids) must equal ROW i of the full dense q_len==kv_len mask — this is
    the exact path the decode step's cache attention rides."""
    S, window = 10, 3
    # row 0: left-padded single document; row 1: packed docs + trailing pad
    seg = jnp.asarray([
        [0, 0, 1, 1, 1, 1, 1, 1, 1, 1],
        [1, 1, 1, 2, 2, 2, 2, 3, 3, 0],
    ])
    for sliding in (None, window):
        dense = np.asarray(make_attention_mask(
            seg, seg, S, S, causal=True, sliding_window=sliding
        ))
        for i in range(S):
            row = np.asarray(make_attention_mask(
                seg[:, i:i + 1], seg, 1, S,
                causal=True, sliding_window=sliding, q_offset=i,
            ))
            np.testing.assert_array_equal(
                row[:, :, 0], dense[:, :, i],
                err_msg=f"q_offset={i} sliding_window={sliding}",
            )
    # the decode step traces q_offset as a dynamic scalar — same rows must
    # come out when the offset is a traced value inside jit
    row_fn = jax.jit(
        lambda off: make_attention_mask(
            seg[:, 4:5], seg, 1, S, causal=True, sliding_window=window,
            q_offset=off,
        )
    )
    dense = np.asarray(make_attention_mask(
        seg, seg, S, S, causal=True, sliding_window=window
    ))
    np.testing.assert_array_equal(
        np.asarray(row_fn(jnp.int32(4)))[:, :, 0], dense[:, :, 4]
    )


def test_soft_cap_matches_naive_tanh():
    rng = np.random.default_rng(10)
    q = rng.standard_normal((1, 4, 1, 4)).astype(np.float32) * 10
    k = rng.standard_normal((1, 4, 1, 4)).astype(np.float32) * 10
    v = rng.standard_normal((1, 4, 1, 4)).astype(np.float32)
    cap = 20.0
    # naive with tanh capping
    scores = (q[0, :, 0] @ k[0, :, 0].T) / np.sqrt(4)
    scores = cap * np.tanh(scores / cap)
    scores = np.where(np.tril(np.ones((4, 4), bool)), scores, -1e30)
    probs = np.exp(scores - scores.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    expected = probs @ v[0, :, 0]
    got = dot_product_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), logits_soft_cap=cap, impl="xla"
    )
    np.testing.assert_allclose(got[0, :, 0], expected, rtol=1e-4, atol=1e-5)
    # and the cap actually changes the result
    uncapped = dot_product_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), impl="xla")
    assert np.abs(np.asarray(uncapped) - np.asarray(got)).max() > 1e-3


def test_cross_length_attention_chunks():
    """q shorter than kv (ring-attention chunk shape): q_offset causal mask +
    per-side segment ids must match slicing the full square attention."""
    rng = np.random.default_rng(11)
    seq, d = 8, 4
    q = rng.standard_normal((1, seq, 1, d)).astype(np.float32)
    k = rng.standard_normal((1, seq, 1, d)).astype(np.float32)
    v = rng.standard_normal((1, seq, 1, d)).astype(np.float32)
    seg = jnp.asarray([[1, 1, 1, 1, 2, 2, 2, 2]])

    full = dot_product_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), segment_ids=seg, impl="xla"
    )
    chunk = slice(4, 8)
    part = dot_product_attention(
        jnp.asarray(q[:, chunk]), jnp.asarray(k), jnp.asarray(v),
        segment_ids=seg, q_segment_ids=seg[:, chunk], q_offset=4, impl="xla",
    )
    np.testing.assert_allclose(part, full[:, chunk], rtol=1e-4, atol=1e-5)

    with pytest.raises(ValueError, match="q_segment_ids"):
        dot_product_attention(
            jnp.asarray(q[:, chunk]), jnp.asarray(k), jnp.asarray(v),
            segment_ids=seg, impl="xla",
        )


def test_fully_masked_rows_emit_zero_xla():
    """Padding rows (segment 0) produce exactly 0 in the XLA path — the same
    invariant the pallas kernel and ring combiner provide."""
    q = jnp.ones((1, 4, 1, 8), jnp.float32)
    seg = jnp.asarray([[1, 1, 0, 0]])
    out = dot_product_attention(q, q, q, segment_ids=seg, impl="xla")
    np.testing.assert_array_equal(np.asarray(out[:, 2:]), 0.0)


def test_yarn_matches_hf_deepseek_style():
    """DeepSeek-style yarn dicts (mscale, mscale_all_dim,
    original_max_position_embeddings, truncate) must produce the exact
    inv_freq + attention factor transformers computes."""
    pytest.importorskip("torch")
    from transformers import PretrainedConfig
    from transformers.modeling_rope_utils import ROPE_INIT_FUNCTIONS

    from llm_training_tpu.ops.rope_utils import RoPEConfig, compute_rope_frequencies

    for scaling in [
        {"factor": 40.0, "mscale": 1.0, "mscale_all_dim": 1.0,
         "original_max_position_embeddings": 512, "beta_fast": 32,
         "beta_slow": 1},
        {"factor": 8.0, "original_max_position_embeddings": 1024,
         "truncate": False},
        {"factor": 4.0},
    ]:
        hf_config = PretrainedConfig()
        hf_config.rope_theta = 10000.0
        hf_config.hidden_size = 64
        hf_config.num_attention_heads = 1
        hf_config.head_dim = 64
        hf_config.max_position_embeddings = 4096
        hf_config.rope_scaling = dict(scaling, rope_type="yarn")
        hf_inv, hf_factor = ROPE_INIT_FUNCTIONS["yarn"](hf_config, device="cpu")

        ours_inv, ours_factor = compute_rope_frequencies(
            RoPEConfig(type="yarn", base=10000.0, dim=64,
                       max_position_embeddings=4096, scaling=scaling),
            seq_len=4096,
        )
        np.testing.assert_allclose(
            np.asarray(ours_inv), hf_inv.numpy(), rtol=1e-6, err_msg=str(scaling)
        )
        assert abs(ours_factor - hf_factor) < 1e-6, scaling
