"""Callbacks: time/MFU estimator, JSONL logger, output redirection, profiler.

The reference exercised its callbacks only inside live Lightning runs
(SURVEY.md §4 — no tests existed); here each one runs against a real tiny
fit on the CPU mesh.
"""

import json

import jax
import numpy as np
import pytest

from llm_training_tpu.callbacks import (
    JsonlLogger,
    JsonlLoggerConfig,
    OutputRedirection,
    OutputRedirectionConfig,
    TrainingTimeEstimator,
    TrainingTimeEstimatorConfig,
)
from llm_training_tpu.data import DummyDataModule, DummyDataModuleConfig
from llm_training_tpu.lms import CLM, CLMConfig, ModelProvider
from llm_training_tpu.parallel import MeshConfig
from llm_training_tpu.trainer import Trainer, TrainerConfig


def _tiny_objective():
    return CLM(
        CLMConfig(
            model=ModelProvider(
                model_class="Llama",
                model_kwargs=dict(
                    vocab_size=128, hidden_size=32, intermediate_size=64,
                    num_hidden_layers=1, num_attention_heads=2,
                    num_key_value_heads=2, max_position_embeddings=64,
                    attention_impl="xla", param_dtype="float32",
                    compute_dtype="float32",
                ),
            )
        )
    )


def _tiny_dm(batch=8):
    return DummyDataModule(
        DummyDataModuleConfig(batch_size=batch, max_length=32, num_samples=256, vocab_size=128)
    )


def _fit(callbacks, max_steps=12, log_every=2):
    trainer = Trainer(
        TrainerConfig(
            max_steps=max_steps, log_every_n_steps=log_every,
            mesh=MeshConfig(),
        ),
        callbacks=callbacks,
    )
    trainer.fit(_tiny_objective(), _tiny_dm())
    return trainer


@pytest.mark.slow
def test_time_estimator_reports_throughput_and_extrapolation():
    est = TrainingTimeEstimator(
        TrainingTimeEstimatorConfig(num_steps=4, skip_first_n_steps=2)
    )
    _fit([est])
    assert est.result is not None
    assert est.result["steps_per_sec"] > 0
    assert est.result["tokens_per_sec"] > 0
    assert est.result["estimated_total_hours"] > 0
    # CPU has no peak-FLOPs entry, so MFU is absent there; on TPU it appears
    if jax.default_backend() == "tpu":
        assert 0 < est.result["mfu"] < 1


@pytest.mark.slow
def test_time_estimator_dry_run_stops_training():
    est = TrainingTimeEstimator(
        TrainingTimeEstimatorConfig(num_steps=2, skip_first_n_steps=0, stop_after_steps=4)
    )
    trainer = _fit([est], max_steps=100, log_every=2)
    assert trainer.last_step < 100
    assert est.result is not None


@pytest.mark.slow
def test_early_stop_checkpoint_labeled_with_actual_step(tmp_path):
    """Regression: a dry-run stop must not write its checkpoint under
    max_steps — that would block the real final save on resume."""
    from llm_training_tpu.trainer.checkpoint import CheckpointConfig, Checkpointer

    est = TrainingTimeEstimator(
        TrainingTimeEstimatorConfig(num_steps=2, skip_first_n_steps=0, stop_after_steps=3)
    )
    ckpt = Checkpointer(CheckpointConfig(dirpath=str(tmp_path / "ckpt")))
    trainer = Trainer(
        TrainerConfig(max_steps=50, log_every_n_steps=1, mesh=MeshConfig()),
        callbacks=[est],
        checkpointer=ckpt,
    )
    trainer.fit(_tiny_objective(), _tiny_dm())
    steps = ckpt.manager.all_steps()
    assert trainer.last_step < 50
    assert max(steps) == trainer.last_step


@pytest.mark.slow
def test_jsonl_logger_writes_metrics_and_config(tmp_path):
    logger = JsonlLogger(JsonlLoggerConfig(save_dir=str(tmp_path), name="run1"))
    _fit([logger], max_steps=6, log_every=2)
    lines = (tmp_path / "llm-training-tpu" / "run1" / "metrics.jsonl").read_text().splitlines()
    records = [json.loads(line) for line in lines]
    assert [r["step"] for r in records] == [2, 4, 6]
    assert all("loss" in r and "grad_norm" in r for r in records)


@pytest.mark.slow
def test_output_redirection_tees_to_log_file(tmp_path):
    import logging

    # the CLI sets INFO via basicConfig (cli/main.py); do the equivalent here
    # so the trainer's log records pass the level check
    logging.getLogger("llm_training_tpu").setLevel(logging.INFO)
    cb = OutputRedirection(OutputRedirectionConfig(log_dir=str(tmp_path)))
    _fit([cb], max_steps=4, log_every=2)
    assert cb.log_path is not None and cb.log_path.exists()
    content = cb.log_path.read_text()
    assert "step 4" in content  # trainer log line captured
    # numbered files: a second run gets 1.log
    cb2 = OutputRedirection(OutputRedirectionConfig(log_dir=str(tmp_path)))
    _fit([cb2], max_steps=2, log_every=2)
    assert cb2.log_path.name == "1.log"


def test_wandb_logger_requires_wandb():
    from llm_training_tpu.callbacks import WandbLogger

    try:
        import wandb  # noqa: F401

        pytest.skip("wandb installed; gating not testable")
    except ImportError:
        with pytest.raises(ImportError):
            WandbLogger()


def test_mfu_model():
    from llm_training_tpu.callbacks.time_estimator import transformer_step_flops

    # 6·N·T exactly when no shape hints
    assert transformer_step_flops(1000, 10) == 60000
    # attention term adds 12·L·H·S·T
    flops = transformer_step_flops(1000, 10, num_layers=2, hidden_size=8, seq_len=4)
    assert flops == 60000 + 12 * 2 * 8 * 4 * 10


def test_nan_guard_raises_on_divergence():
    from llm_training_tpu.callbacks import NanGuard, NanGuardConfig, NonFiniteLossError

    guard = NanGuard(NanGuardConfig(patience=1))

    class T:
        should_stop = False

    guard.on_step_end(T(), 1, {"loss": 1.0, "grad_norm": 2.0})
    guard.on_step_end(T(), 2, {"loss": float("nan"), "grad_norm": 1.0})  # within patience
    guard.on_step_end(T(), 3, {"loss": 1.0, "grad_norm": 1.0})  # streak resets
    guard.on_step_end(T(), 4, {"loss": float("inf"), "grad_norm": 1.0})
    with pytest.raises(NonFiniteLossError):
        guard.on_step_end(T(), 5, {"loss": float("nan"), "grad_norm": 1.0})
    assert guard.non_finite_steps == 3


def test_nan_guard_stop_mode():
    from llm_training_tpu.callbacks import NanGuard, NanGuardConfig

    guard = NanGuard(NanGuardConfig(patience=0, action="stop"))

    class T:
        should_stop = False

    trainer = T()
    guard.on_step_end(trainer, 1, {"loss": float("nan"), "grad_norm": 1.0})
    assert trainer.should_stop is True


class _TraceRecorder:
    """Monkeypatch target for jax.profiler start/stop — records transitions."""

    def __init__(self, monkeypatch):
        self.calls = []
        monkeypatch.setattr(
            jax.profiler, "start_trace", lambda d: self.calls.append(("start", d))
        )
        monkeypatch.setattr(
            jax.profiler, "stop_trace", lambda: self.calls.append(("stop",))
        )


def _profiler_trainer(max_steps):
    class T:
        config = TrainerConfig(max_steps=max_steps, mesh=MeshConfig())

    return T()


def test_profiler_start_stop_window(monkeypatch):
    from llm_training_tpu.callbacks import ProfilerCallback, ProfilerCallbackConfig

    rec = _TraceRecorder(monkeypatch)
    cb = ProfilerCallback(ProfilerCallbackConfig(start_step=3, num_steps=2))
    trainer = _profiler_trainer(10)
    for step in range(1, 8):
        cb.on_train_step(trainer, step)
    cb.teardown()
    # starts at 3, stops at 5 (boundary explicit), teardown adds nothing
    assert rec.calls == [("start", cb.config.trace_dir), ("stop",)]


def test_profiler_window_overrunning_max_steps_stops_in_loop(monkeypatch):
    """Regression: start_step + num_steps > max_steps used to leave the
    trace open until teardown; the boundary is now clamped to max_steps."""
    from llm_training_tpu.callbacks import ProfilerCallback, ProfilerCallbackConfig

    rec = _TraceRecorder(monkeypatch)
    cb = ProfilerCallback(ProfilerCallbackConfig(start_step=4, num_steps=10))
    trainer = _profiler_trainer(5)
    for step in range(1, 6):
        cb.on_train_step(trainer, step)
    # stopped AT step 5 (the final step), not via teardown
    assert rec.calls == [("start", cb.config.trace_dir), ("stop",)]
    assert not cb._active
    cb.teardown()
    assert rec.calls.count(("stop",)) == 1


def test_profiler_teardown_stops_dangling_trace(monkeypatch):
    from llm_training_tpu.callbacks import ProfilerCallback, ProfilerCallbackConfig

    rec = _TraceRecorder(monkeypatch)
    cb = ProfilerCallback(ProfilerCallbackConfig(start_step=2, num_steps=5))
    trainer = _profiler_trainer(10)
    cb.on_train_step(trainer, 2)  # started; fit dies before the window ends
    assert cb._active
    cb.teardown()
    assert rec.calls == [("start", cb.config.trace_dir), ("stop",)]
    cb.teardown()  # idempotent
    assert rec.calls.count(("stop",)) == 1


def test_profiler_zero_length_window_never_starts(monkeypatch):
    """A window that clamps to nothing (start_step == max_steps) must not
    open a trace that only teardown would close — it would capture the fit
    epilogue, not steps."""
    from llm_training_tpu.callbacks import ProfilerCallback, ProfilerCallbackConfig

    rec = _TraceRecorder(monkeypatch)
    cb = ProfilerCallback(ProfilerCallbackConfig(start_step=5, num_steps=10))
    trainer = _profiler_trainer(5)
    for step in range(1, 6):
        cb.on_train_step(trainer, step)
    assert not cb._active
    cb.teardown()
    assert rec.calls == []


def test_profiler_never_starts_past_window(monkeypatch):
    from llm_training_tpu.callbacks import ProfilerCallback, ProfilerCallbackConfig

    rec = _TraceRecorder(monkeypatch)
    cb = ProfilerCallback(ProfilerCallbackConfig(start_step=3, num_steps=2))
    trainer = _profiler_trainer(10)
    for step in (6, 7, 8):  # resume landed past the window
        cb.on_train_step(trainer, step)
    cb.teardown()
    assert rec.calls == []


def test_extra_config_flags(monkeypatch):
    import jax

    from llm_training_tpu.cli.main import _apply_extra_config

    before = jax.config.jax_default_matmul_precision
    try:
        _apply_extra_config({"matmul_precision": "highest"})
        assert jax.config.jax_default_matmul_precision == "float32"
        _apply_extra_config({"float32_matmul_precision": "bfloat16"})
        assert jax.config.jax_default_matmul_precision == "bfloat16"
    finally:
        jax.config.update("jax_default_matmul_precision", before)


@pytest.mark.slow
def test_non_log_step_divergence_never_checkpointed(tmp_path):
    """The save gate must check the CURRENT step's loss, independent of log
    cadence: with checkpoint_every_n_steps not a multiple of
    log_every_n_steps, a divergence between log steps must not be persisted
    as the newest checkpoint (VERDICT r2 weak #4)."""
    import jax.numpy as jnp

    from llm_training_tpu.trainer.checkpoint import CheckpointConfig, Checkpointer

    class PoisonedTrainer(Trainer):
        """Loss turns NaN from step 4 on — inside the jitted step, so the
        host only ever sees it through the save gate / log-step pulls."""

        def _build_step(self, objective, tx):
            base = super()._build_step(objective, tx)

            def step(state, batch):
                new_state, metrics = base(state, batch)
                metrics["loss"] = jnp.where(
                    new_state.step >= 4, jnp.float32(jnp.nan), metrics["loss"]
                )
                return new_state, metrics

            return step

    ckpt = Checkpointer(CheckpointConfig(dirpath=str(tmp_path / "ckpt"), async_save=False))
    trainer = PoisonedTrainer(
        # log every 5, checkpoint every 3: steps 6/9 and the final save all
        # fall between log steps — only the pre-divergence step 3 may persist
        TrainerConfig(max_steps=7, log_every_n_steps=5, checkpoint_every_n_steps=3,
                      mesh=MeshConfig()),
        checkpointer=ckpt,
    )
    trainer.fit(_tiny_objective(), _tiny_dm())
    assert ckpt.manager.all_steps() == [3]


@pytest.mark.slow
def test_nan_guard_stop_skips_final_checkpoint(tmp_path):
    """Regression: a divergence stop must not persist the NaN state as the
    newest checkpoint."""
    from llm_training_tpu.callbacks import NanGuard, NanGuardConfig
    from llm_training_tpu.trainer.checkpoint import CheckpointConfig, Checkpointer

    class Poison:
        """Forces should_stop + abort via the guard on a fabricated metric."""

        def __init__(self):
            self.guard = NanGuard(NanGuardConfig(patience=0, action="stop"))

        def on_step_end(self, trainer, step, metrics):
            if step >= 2:
                self.guard.on_step_end(trainer, step, {"loss": float("nan"), "grad_norm": 1.0})

    ckpt = Checkpointer(CheckpointConfig(dirpath=str(tmp_path / "ckpt"), async_save=False))
    trainer = Trainer(
        TrainerConfig(max_steps=50, log_every_n_steps=1, mesh=MeshConfig()),
        callbacks=[Poison()],
        checkpointer=ckpt,
    )
    trainer.fit(_tiny_objective(), _tiny_dm())
    assert trainer.last_step < 50
    assert ckpt.manager.all_steps() == []  # nothing persisted
