"""Callbacks: time/MFU estimator, JSONL logger, output redirection, profiler.

The reference exercised its callbacks only inside live Lightning runs
(SURVEY.md §4 — no tests existed); here each one runs against a real tiny
fit on the CPU mesh.
"""

import json

import jax
import numpy as np
import pytest

from llm_training_tpu.callbacks import (
    JsonlLogger,
    JsonlLoggerConfig,
    OutputRedirection,
    OutputRedirectionConfig,
    TrainingTimeEstimator,
    TrainingTimeEstimatorConfig,
)
from llm_training_tpu.data import DummyDataModule, DummyDataModuleConfig
from llm_training_tpu.lms import CLM, CLMConfig, ModelProvider
from llm_training_tpu.parallel import MeshConfig
from llm_training_tpu.trainer import Trainer, TrainerConfig


def _tiny_objective():
    return CLM(
        CLMConfig(
            model=ModelProvider(
                model_class="Llama",
                model_kwargs=dict(
                    vocab_size=128, hidden_size=32, intermediate_size=64,
                    num_hidden_layers=1, num_attention_heads=2,
                    num_key_value_heads=2, max_position_embeddings=64,
                    attention_impl="xla", param_dtype="float32",
                    compute_dtype="float32",
                ),
            )
        )
    )


def _tiny_dm(batch=8):
    return DummyDataModule(
        DummyDataModuleConfig(batch_size=batch, max_length=32, num_samples=256, vocab_size=128)
    )


def _fit(callbacks, max_steps=12, log_every=2):
    trainer = Trainer(
        TrainerConfig(
            max_steps=max_steps, log_every_n_steps=log_every,
            mesh=MeshConfig(),
        ),
        callbacks=callbacks,
    )
    trainer.fit(_tiny_objective(), _tiny_dm())
    return trainer


def test_time_estimator_reports_throughput_and_extrapolation():
    est = TrainingTimeEstimator(
        TrainingTimeEstimatorConfig(num_steps=4, skip_first_n_steps=2)
    )
    _fit([est])
    assert est.result is not None
    assert est.result["steps_per_sec"] > 0
    assert est.result["tokens_per_sec"] > 0
    assert est.result["estimated_total_hours"] > 0
    # CPU has no peak-FLOPs entry, so MFU is absent there; on TPU it appears
    if jax.default_backend() == "tpu":
        assert 0 < est.result["mfu"] < 1


def test_time_estimator_dry_run_stops_training():
    est = TrainingTimeEstimator(
        TrainingTimeEstimatorConfig(num_steps=2, skip_first_n_steps=0, stop_after_steps=4)
    )
    trainer = _fit([est], max_steps=100, log_every=2)
    assert trainer.last_step < 100
    assert est.result is not None


def test_early_stop_checkpoint_labeled_with_actual_step(tmp_path):
    """Regression: a dry-run stop must not write its checkpoint under
    max_steps — that would block the real final save on resume."""
    from llm_training_tpu.trainer.checkpoint import CheckpointConfig, Checkpointer

    est = TrainingTimeEstimator(
        TrainingTimeEstimatorConfig(num_steps=2, skip_first_n_steps=0, stop_after_steps=3)
    )
    ckpt = Checkpointer(CheckpointConfig(dirpath=str(tmp_path / "ckpt")))
    trainer = Trainer(
        TrainerConfig(max_steps=50, log_every_n_steps=1, mesh=MeshConfig()),
        callbacks=[est],
        checkpointer=ckpt,
    )
    trainer.fit(_tiny_objective(), _tiny_dm())
    steps = ckpt.manager.all_steps()
    assert trainer.last_step < 50
    assert max(steps) == trainer.last_step


def test_jsonl_logger_writes_metrics_and_config(tmp_path):
    logger = JsonlLogger(JsonlLoggerConfig(save_dir=str(tmp_path), name="run1"))
    _fit([logger], max_steps=6, log_every=2)
    lines = (tmp_path / "llm-training-tpu" / "run1" / "metrics.jsonl").read_text().splitlines()
    records = [json.loads(line) for line in lines]
    assert [r["step"] for r in records] == [2, 4, 6]
    assert all("loss" in r and "grad_norm" in r for r in records)


def test_output_redirection_tees_to_log_file(tmp_path):
    import logging

    # the CLI sets INFO via basicConfig (cli/main.py); do the equivalent here
    # so the trainer's log records pass the level check
    logging.getLogger("llm_training_tpu").setLevel(logging.INFO)
    cb = OutputRedirection(OutputRedirectionConfig(log_dir=str(tmp_path)))
    _fit([cb], max_steps=4, log_every=2)
    assert cb.log_path is not None and cb.log_path.exists()
    content = cb.log_path.read_text()
    assert "step 4" in content  # trainer log line captured
    # numbered files: a second run gets 1.log
    cb2 = OutputRedirection(OutputRedirectionConfig(log_dir=str(tmp_path)))
    _fit([cb2], max_steps=2, log_every=2)
    assert cb2.log_path.name == "1.log"


def test_wandb_logger_requires_wandb():
    from llm_training_tpu.callbacks import WandbLogger

    try:
        import wandb  # noqa: F401

        pytest.skip("wandb installed; gating not testable")
    except ImportError:
        with pytest.raises(ImportError):
            WandbLogger()


def test_mfu_model():
    from llm_training_tpu.callbacks.time_estimator import transformer_step_flops

    # 6·N·T exactly when no shape hints
    assert transformer_step_flops(1000, 10) == 60000
    # attention term adds 12·L·H·S·T
    flops = transformer_step_flops(1000, 10, num_layers=2, hidden_size=8, seq_len=4)
    assert flops == 60000 + 12 * 2 * 8 * 4 * 10
