"""Checkpoint durability plane: hashed manifests, verify-before-restore,
mirror healing, retention GC, byte-level corruption chaos, and the `ckpt`
CLI (docs/resilience.md#durability)."""

import json
import os
import signal
import subprocess
import sys
import textwrap
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_training_tpu.resilience import (
    ChaosConfig,
    MirrorDaemon,
    config_from_env,
    install_chaos,
    uninstall_chaos,
)
from llm_training_tpu.resilience import durability
from llm_training_tpu.telemetry import TelemetryRegistry, set_registry
from llm_training_tpu.trainer.state import TrainState


@pytest.fixture(autouse=True)
def _no_leaked_chaos():
    yield
    uninstall_chaos()


@pytest.fixture()
def registry():
    registry = TelemetryRegistry()
    previous = set_registry(registry)
    yield registry
    set_registry(previous)


def _fake_step(root: Path, step: int, payload: bytes = b"x" * 256,
               manifest: bool = True) -> Path:
    """A committed orbax-shaped step dir with two payload files."""
    sdir = root / str(step)
    (sdir / "state").mkdir(parents=True)
    (sdir / "state" / "array.bin").write_bytes(payload)
    (sdir / "meta.json").write_text(json.dumps({"step": step}))
    (sdir / "_CHECKPOINT_METADATA").write_text("{}")
    if manifest:
        durability.write_manifest(
            root, step, durability.build_manifest(sdir, step)
        )
    return sdir


# ------------------------------------------------------------- manifests


def test_manifest_round_trip_and_atomic_write(tmp_path):
    _fake_step(tmp_path, 3)
    manifest = durability.load_manifest(tmp_path, 3)
    assert manifest["step"] == 3
    assert set(manifest["files"]) == {
        "_CHECKPOINT_METADATA", "meta.json", "state/array.bin"
    }
    assert manifest["total_bytes"] == sum(
        entry["bytes"] for entry in manifest["files"].values()
    )
    # tmp-then-rename left no torn intermediate behind
    assert not list(tmp_path.glob("*.tmp"))
    assert durability.verify_step(tmp_path, 3, mode="full").ok


def test_load_manifest_absent_vs_torn(tmp_path):
    assert durability.load_manifest(tmp_path, 9) is None
    durability.manifest_path(tmp_path, 9).write_text("{not json")
    with pytest.raises(ValueError):
        durability.load_manifest(tmp_path, 9)


# -------------------------------------------------- corruption matrix


@pytest.mark.parametrize("corrupt_mode", ["flip", "truncate", "delete"])
@pytest.mark.parametrize("target", ["state/array.bin", "meta.json"])
def test_verify_full_names_step_and_file(tmp_path, corrupt_mode, target):
    _fake_step(tmp_path, 5)
    victim = durability.corrupt_step(tmp_path, 5, corrupt_mode, target=target)
    assert victim == target
    result = durability.verify_step(tmp_path, 5, mode="full")
    assert result.verifiable and result.findings
    # every finding names the step and the damaged file
    assert all(f.startswith("step 5: ") for f in result.findings)
    assert any(target in f for f in result.findings)


@pytest.mark.parametrize("corrupt_mode,fast_catches", [
    ("flip", False),      # same size, same file set — needs the hash pass
    ("truncate", True),   # size mismatch
    ("delete", True),     # file-set mismatch
])
def test_verify_fast_catches_shape_not_content(tmp_path, corrupt_mode,
                                               fast_catches):
    _fake_step(tmp_path, 1)
    durability.corrupt_step(tmp_path, 1, corrupt_mode)
    fast = durability.verify_step(tmp_path, 1, mode="fast")
    assert bool(fast.findings) == fast_catches
    assert not durability.verify_step(tmp_path, 1, mode="full").ok


def test_verify_catches_manifest_corruption_itself(tmp_path):
    """The manifest is part of the verified surface: a torn manifest is a
    named finding, not a crash and not a silent pass."""
    _fake_step(tmp_path, 2)
    mpath = durability.manifest_path(tmp_path, 2)
    mpath.write_text(mpath.read_text()[: len(mpath.read_text()) // 2])
    result = durability.verify_step(tmp_path, 2, mode="fast")
    assert result.verifiable and result.findings
    assert any("manifest-2.json" in f for f in result.findings)


def test_verify_catches_unexpected_file(tmp_path):
    _fake_step(tmp_path, 4)
    (tmp_path / "4" / "state" / "stray.bin").write_bytes(b"stray")
    result = durability.verify_step(tmp_path, 4, mode="fast")
    assert any("state/stray.bin" in f and "not in manifest" in f
               for f in result.findings)


def test_verify_legacy_step_is_unverifiable_not_a_finding(tmp_path):
    _fake_step(tmp_path, 7, manifest=False)
    result = durability.verify_step(tmp_path, 7, mode="full")
    assert not result.verifiable and not result.findings and not result.ok


def test_corrupt_step_picks_largest_payload(tmp_path):
    sdir = _fake_step(tmp_path, 1, payload=b"y" * 4096)
    victim = durability.corrupt_step(tmp_path, 1, "flip")
    assert victim == "state/array.bin"  # the largest file, not a marker
    assert (sdir / victim).stat().st_size == 4096  # flip preserves size


# ------------------------------------------------------------ retention


def test_retention_victims_policy():
    steps = [10, 20, 30, 40, 50, 60]
    # keep-last-2 → newest two survive
    assert durability.retention_victims(steps, 2) == [10, 20, 30, 40]
    # keep_every pins the long-tail archive
    assert durability.retention_victims(steps, 1, keep_every=30) == [10, 20, 40, 50]
    # protected (mirror-only intact copies) are never victims
    assert durability.retention_victims(steps, 1, protected={20}) == [10, 30, 40, 50]
    with pytest.raises(ValueError):
        durability.retention_victims(steps, 0)


def test_retention_never_deletes_newest():
    """Property: for any step set and policy, the newest step survives."""
    for steps in ([1], [1, 2], [3, 7, 9, 12], list(range(1, 30, 3))):
        for keep_last in (1, 2, 5):
            for keep_every in (None, 2, 10):
                victims = durability.retention_victims(
                    steps, keep_last, keep_every
                )
                assert max(steps) not in victims
                assert len(set(steps) - set(victims)) >= min(len(steps), keep_last)


def test_apply_retention_and_orphan_manifests(tmp_path):
    for step in (1, 2, 3, 4):
        _fake_step(tmp_path, step)
    victims = durability.apply_retention(tmp_path, keep_last=2)
    assert victims == [1, 2]
    assert durability.committed_steps(tmp_path) == [3, 4]
    assert not durability.manifest_path(tmp_path, 1).exists()
    # an orbax-side delete leaves a manifest orphan; the sweep drops it
    import shutil

    shutil.rmtree(tmp_path / "3")
    assert durability.gc_orphan_manifests(tmp_path) == [3]
    assert not durability.manifest_path(tmp_path, 3).exists()


# ------------------------------------------------------------ mirroring


def test_mirror_step_publishes_verified_copy(tmp_path):
    primary, mirror = tmp_path / "p", tmp_path / "m"
    _fake_step(primary, 1)
    assert durability.mirror_step(primary, mirror, 1) == []
    assert durability.verify_step(mirror, 1, mode="full").ok
    # idempotent: an intact mirror copy is not re-copied or disturbed
    assert durability.mirror_step(primary, mirror, 1) == []
    # a mirror copy is real bytes, not a hardlink back to the primary —
    # otherwise in-place corruption would damage both copies at once
    src = primary / "1" / "state" / "array.bin"
    dst = mirror / "1" / "state" / "array.bin"
    assert os.stat(src).st_ino != os.stat(dst).st_ino


def test_mirror_step_rejects_post_manifest_rot(tmp_path):
    """A source that decayed after its manifest landed must never publish:
    the mirror-side re-hash rejects the copy and tears it down."""
    primary, mirror = tmp_path / "p", tmp_path / "m"
    _fake_step(primary, 2)
    durability.corrupt_step(primary, 2, "flip")
    findings = durability.mirror_step(primary, mirror, 2)
    assert findings and any("sha256 mismatch" in f for f in findings)
    assert not (mirror / "2").exists()
    assert not list(mirror.glob(".tmp-*"))


def test_last_intact_on_mirror_protects_broken_primaries(tmp_path):
    primary, mirror = tmp_path / "p", tmp_path / "m"
    for step in (1, 2):
        _fake_step(primary, step)
        assert durability.mirror_step(primary, mirror, step) == []
    durability.corrupt_step(primary, 2, "truncate")
    assert durability.last_intact_on_mirror(primary, mirror) == {2}
    # and retention on the mirror honors the protection
    victims = durability.apply_retention(
        mirror, keep_last=1,
        protected=durability.last_intact_on_mirror(primary, mirror),
    )
    assert victims == [1]
    assert durability.committed_steps(mirror) == [2]


def test_mirror_daemon_mirrors_gcs_and_scrubs(tmp_path, registry):
    primary, mirror = tmp_path / "p", tmp_path / "m"
    primary.mkdir()
    for step in (1, 2, 3):
        _fake_step(primary, step)
    daemon = MirrorDaemon(
        primary, mirror, interval_s=0.05, keep_last=2,
        scrub_interval_s=0.0,  # exercised separately below
        registry=registry,
    ).start()
    try:
        assert daemon.drain(timeout_s=30.0)
        stats = daemon.stats()
        assert stats["mirrored"] and not stats["failed"]
        # retention keeps the newest keep_last on the mirror side; drain()
        # only barriers the mirroring attempts, so wait out the GC pass
        import time

        deadline = time.monotonic() + 30.0
        while (durability.committed_steps(mirror) != [2, 3]
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert durability.committed_steps(mirror) == [2, 3]
    finally:
        daemon.stop()
    snap, _ = registry.snapshot_with_kinds()
    assert snap["ckpt/mirrored_steps"] == 2
    assert snap["ckpt/mirror_lag_steps"] == 1  # step 1 GC'd mirror-side
    assert snap["ckpt/gc_deleted"] >= 1
    # scrubber: drive _maybe_scrub directly with a fake clock
    clock = iter([100.0, 200.0]).__next__
    scrubber = MirrorDaemon(
        primary, mirror, scrub_interval_s=1.0, registry=registry, clock=clock
    )
    scrubber._maybe_scrub(registry)
    snap, _ = registry.snapshot_with_kinds()
    assert snap["ckpt/scrub_ok"] == 1 and snap["ckpt/scrub_last_ok"] == 1.0
    durability.corrupt_step(primary, 1, "flip")
    scrubber._scrub_cursor = 0
    scrubber._maybe_scrub(registry)
    snap, _ = registry.snapshot_with_kinds()
    assert snap["ckpt/scrub_failures"] == 1 and snap["ckpt/scrub_last_ok"] == 0.0


# -------------------------------------------------------- staged swaps


def test_stale_stage_promote_round_trip(tmp_path):
    _fake_step(tmp_path, 1)
    staged = durability.stage_stale_step(tmp_path, 1)
    assert staged is not None and staged.is_dir()
    # the SIGKILL-mid-swap signature: old step deleted, replacement absent
    import shutil

    shutil.rmtree(tmp_path / "1")
    durability.manifest_path(tmp_path, 1).unlink()
    assert durability.promote_stale_steps(tmp_path) == [1]
    assert durability.verify_step(tmp_path, 1, mode="full").ok
    assert not (tmp_path / durability.STALE_DIR).exists()


def test_promote_skips_committed_replacement(tmp_path):
    _fake_step(tmp_path, 1)
    durability.stage_stale_step(tmp_path, 1)
    # replacement committed fine — the staged copy is just swap trash
    assert durability.promote_stale_steps(tmp_path) == []
    assert not (tmp_path / durability.STALE_DIR).exists()


# ----------------------------------------------------------- chaos env


def test_chaos_ckpt_env_parsing(monkeypatch):
    monkeypatch.setenv("LLMT_CHAOS_CKPT_CORRUPT", "flip:3")
    monkeypatch.setenv("LLMT_CHAOS_CKPT_KILL_IN_SWAP", "2")
    config = config_from_env(ChaosConfig())
    assert config.ckpt_corrupt == "flip:3"
    assert config.ckpt_kill_in_swap == 2
    assert config.any_active()


def test_chaos_corrupts_targeted_step_once(tmp_path, registry):
    _fake_step(tmp_path, 3)
    _fake_step(tmp_path, 4)
    chaos = install_chaos(ChaosConfig(ckpt_corrupt="truncate:3"),
                          registry=registry)
    assert chaos.maybe_corrupt_checkpoint(tmp_path, 4) is None  # wrong step
    victim = chaos.maybe_corrupt_checkpoint(tmp_path, 3)
    assert victim is not None
    assert not durability.verify_step(tmp_path, 3, mode="fast").ok
    # fire-once: the second matching call is a no-op
    assert chaos.maybe_corrupt_checkpoint(tmp_path, 3) is None


def test_chaos_untargeted_waits_for_final_barrier(tmp_path, registry):
    _fake_step(tmp_path, 1)
    chaos = install_chaos(ChaosConfig(ckpt_corrupt="flip"), registry=registry)
    assert chaos.maybe_corrupt_checkpoint(tmp_path, 1) is None  # mid-run: no
    assert chaos.maybe_corrupt_checkpoint(
        tmp_path, 1, at_final_barrier=True
    ) is not None


# ------------------------------------------------------------ ckpt CLI


def _run_ckpt(*argv):
    from llm_training_tpu.cli.main import main

    return main(["ckpt", *[str(a) for a in argv]])


def test_ckpt_cli_exit_codes(tmp_path, capsys):
    primary = tmp_path / "p"
    # 2 = unusable: nothing to examine, every searched path named
    assert _run_ckpt("verify", primary) == 2
    assert str(primary) in capsys.readouterr().out
    _fake_step(primary, 1)
    _fake_step(primary, 2)
    assert _run_ckpt("verify", primary, "--mode", "full") == 0
    assert _run_ckpt("ls", primary) == 0
    assert "step 1" in capsys.readouterr().out
    # 1 = findings, naming step and file
    durability.corrupt_step(primary, 2, "flip", target="state/array.bin")
    assert _run_ckpt("verify", primary, "--mode", "full") == 1
    out = capsys.readouterr().out
    assert "FINDING" in out and "step 2" in out and "state/array.bin" in out
    # fast mode cannot see a same-size flip — that asymmetry is the point
    assert _run_ckpt("verify", primary, "--mode", "fast") == 0


def test_ckpt_cli_mirror_and_gc(tmp_path, capsys):
    primary, mirror = tmp_path / "p", tmp_path / "m"
    for step in (1, 2, 3):
        _fake_step(primary, step)
    assert _run_ckpt("mirror", primary, "--mirror-dir", mirror) == 0
    assert durability.committed_steps(mirror) == [1, 2, 3]
    # dry-run reports victims without deleting
    assert _run_ckpt("gc", primary, "--mirror-dir", mirror,
                     "--keep-last", "1", "--dry-run") == 0
    assert durability.committed_steps(mirror) == [1, 2, 3]
    assert _run_ckpt("gc", primary, "--mirror-dir", mirror,
                     "--keep-last", "1") == 0
    assert durability.committed_steps(mirror) == [3]
    # mirror with no mirror dir configured = unusable
    capsys.readouterr()
    assert _run_ckpt("mirror", primary) == 2


# ----------------------------------------- Checkpointer integration


def _tiny_state(value: float) -> TrainState:
    return TrainState.create(
        params={"w": jnp.full((4,), value, jnp.float32)},
        opt_state={"m": jnp.zeros((4,), jnp.float32)},
        rng=jax.random.key(0),
    )


def _restore_args(state: TrainState):
    abstract = jax.eval_shape(lambda: state)
    shardings = jax.tree.map(lambda leaf: None, abstract)
    return abstract, shardings


def _checkpointer(dirpath, **overrides):
    from llm_training_tpu.trainer.checkpoint import CheckpointConfig, Checkpointer

    kwargs = dict(dirpath=str(dirpath), async_save=False, retry_backoff_s=0.0,
                  mirror_interval_s=0.05)
    kwargs.update(overrides)
    return Checkpointer(CheckpointConfig(**kwargs))


def test_save_writes_manifest_at_commit(tmp_path, registry):
    ckpt = _checkpointer(tmp_path / "p")
    ckpt.save(1, _tiny_state(1.0))
    assert durability.verify_step(tmp_path / "p", 1, mode="full").ok
    snap, _ = registry.snapshot_with_kinds()
    assert snap.get("checkpoint/manifest_n", 0) >= 1  # timer fired
    ckpt.close()


def test_restore_heals_corrupt_primary_from_mirror(tmp_path, registry):
    """The heal leg: flip a byte in the newest primary step; verify-before-
    restore detects it, the restore lands on the mirror's copy in place,
    and no fallback to an older step happens."""
    primary, mirror = tmp_path / "p", tmp_path / "m"
    ckpt = _checkpointer(primary, mirror_dir=str(mirror), verify="full")
    ckpt.save(1, _tiny_state(1.0))
    ckpt.save(2, _tiny_state(2.0))
    ckpt.wait()  # manifest flush + mirror drain
    assert durability.committed_steps(mirror) == [1, 2]
    durability.corrupt_step(primary, 2, "flip")
    state, shardings = _restore_args(_tiny_state(0.0))
    restored, meta = ckpt.maybe_restore(state, shardings)
    assert meta["step"] == 2  # healed in place, NOT a fallback
    np.testing.assert_array_equal(np.asarray(restored.params["w"]), 2.0)
    snap, _ = registry.snapshot_with_kinds()
    assert snap["checkpoint/verify_failures"] == 1
    assert snap["checkpoint/mirror_restores"] == 1
    assert snap.get("resilience/restore_fallbacks", 0) == 0
    # the primary copy is whole again
    assert durability.verify_step(primary, 2, mode="full").ok
    ckpt.close()


def test_restore_falls_back_when_mirror_also_rotten(tmp_path, registry):
    """Both copies of the newest step are bad → exactly one fallback leg to
    the older step, and the verified-corrupt step is repaired away."""
    primary, mirror = tmp_path / "p", tmp_path / "m"
    ckpt = _checkpointer(primary, mirror_dir=str(mirror), verify="full")
    ckpt.save(1, _tiny_state(1.0))
    ckpt.save(2, _tiny_state(2.0))
    ckpt.wait()
    durability.corrupt_step(primary, 2, "flip")
    durability.corrupt_step(mirror, 2, "flip")
    state, shardings = _restore_args(_tiny_state(0.0))
    restored, meta = ckpt.maybe_restore(state, shardings)
    assert meta["step"] == 1
    np.testing.assert_array_equal(np.asarray(restored.params["w"]), 1.0)
    snap, _ = registry.snapshot_with_kinds()
    assert snap["resilience/restore_fallbacks"] == 1  # exactly one leg
    assert snap["checkpoint/verify_failures"] >= 1
    assert 2 not in ckpt.manager.all_steps()  # verified corrupt → repaired
    assert not durability.manifest_path(primary, 2).exists()
    ckpt.close()


def test_environmental_error_preserves_checkpoint(tmp_path, registry,
                                                  monkeypatch):
    """A restore failure whose bytes verify clean is environmental (perms,
    mounts): fall back, but do NOT delete the good checkpoint."""
    ckpt = _checkpointer(tmp_path / "p", save_retries=0)
    ckpt.save(1, _tiny_state(1.0))
    ckpt.save(2, _tiny_state(2.0))
    ckpt.wait()
    real_restore = ckpt.manager.restore

    def broken_env(step, *args, **kwargs):
        if step == 2:
            raise PermissionError("mount went read-only")
        return real_restore(step, *args, **kwargs)

    monkeypatch.setattr(ckpt.manager, "restore", broken_env)
    state, shardings = _restore_args(_tiny_state(0.0))
    restored, meta = ckpt.maybe_restore(state, shardings)
    assert meta["step"] == 1
    assert 2 in ckpt.manager.all_steps()  # NOT deleted
    assert durability.manifest_path(tmp_path / "p", 2).exists()
    snap, _ = registry.snapshot_with_kinds()
    assert snap["resilience/restore_fallbacks"] >= 1
    assert snap.get("checkpoint/verify_failures", 0) == 0
    ckpt.close()


def test_legacy_step_without_manifest_keeps_repair_delete(tmp_path, registry):
    """Pre-manifest checkpoints keep today's behavior: an unrestorable
    legacy step is dropped so the resumed run can re-save it."""
    import shutil

    ckpt = _checkpointer(tmp_path / "p")
    ckpt.save(1, _tiny_state(1.0))
    ckpt.save(2, _tiny_state(2.0))
    durability.manifest_path(tmp_path / "p", 2).unlink()  # make it legacy
    shutil.rmtree(next((tmp_path / "p" / "2").glob("state*")))
    state, shardings = _restore_args(_tiny_state(0.0))
    restored, meta = ckpt.maybe_restore(state, shardings)
    assert meta["step"] == 1
    assert 2 not in ckpt.manager.all_steps()  # legacy path still repairs
    ckpt.close()


def test_force_save_leaves_no_stale_residue_on_success(tmp_path):
    ckpt = _checkpointer(tmp_path / "p")
    ckpt.save(1, _tiny_state(1.0))
    ckpt.save(1, _tiny_state(3.0), force=True)
    assert not (tmp_path / "p" / durability.STALE_DIR).exists()
    assert durability.verify_step(tmp_path / "p", 1, mode="full").ok
    state, shardings = _restore_args(_tiny_state(0.0))
    restored, meta = ckpt.maybe_restore(state, shardings)
    np.testing.assert_array_equal(np.asarray(restored.params["w"]), 3.0)
    ckpt.close()


def test_startup_promotes_interrupted_force_save(tmp_path):
    """Simulated SIGKILL inside the swap window: the staged copy is
    promoted by the next Checkpointer before orbax scans the dir."""
    import shutil

    ckpt = _checkpointer(tmp_path / "p")
    ckpt.save(1, _tiny_state(1.0))
    ckpt.close()
    durability.stage_stale_step(tmp_path / "p", 1)
    shutil.rmtree(tmp_path / "p" / "1")  # the delete the kill interrupts
    ckpt = _checkpointer(tmp_path / "p")
    assert ckpt.manager.all_steps() == [1]
    state, shardings = _restore_args(_tiny_state(0.0))
    restored, meta = ckpt.maybe_restore(state, shardings)
    assert meta["step"] == 1
    np.testing.assert_array_equal(np.asarray(restored.params["w"]), 1.0)
    ckpt.close()


@pytest.mark.slow
def test_force_save_survives_sigkill_in_swap(tmp_path):
    """The chaos-kill pin for the force-save data-loss window: a SIGKILL
    between the old step's delete and the replacement's commit must leave
    at least one restorable durable copy."""
    child = textwrap.dedent(
        """
        import jax, jax.numpy as jnp
        from llm_training_tpu.trainer.state import TrainState
        from llm_training_tpu.trainer.checkpoint import CheckpointConfig, Checkpointer
        from llm_training_tpu.resilience import ChaosConfig, config_from_env, install_chaos

        install_chaos(config_from_env(ChaosConfig()))

        def tiny(v):
            return TrainState.create(
                params={"w": jnp.full((4,), v, jnp.float32)},
                opt_state={"m": jnp.zeros((4,), jnp.float32)},
                rng=jax.random.key(0),
            )

        ckpt = Checkpointer(CheckpointConfig(
            dirpath=%r, async_save=False, retry_backoff_s=0.0))
        ckpt.save(1, tiny(1.0))
        ckpt.save(1, tiny(9.0), force=True)  # chaos SIGKILLs mid-swap
        raise SystemExit("survived the kill window")
        """ % str(tmp_path / "p")
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               LLMT_CHAOS_CKPT_KILL_IN_SWAP="1")
    proc = subprocess.run([sys.executable, "-c", child], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == -signal.SIGKILL, proc.stdout + proc.stderr
    # relaunch: promotion restores the pre-force copy
    ckpt = _checkpointer(tmp_path / "p")
    assert ckpt.manager.all_steps() == [1]
    state, shardings = _restore_args(_tiny_state(0.0))
    restored, meta = ckpt.maybe_restore(state, shardings)
    assert meta["step"] == 1
    np.testing.assert_array_equal(np.asarray(restored.params["w"]), 1.0)
    ckpt.close()


def test_targeted_chaos_exercises_mirror_reject_then_fallback(tmp_path,
                                                              registry):
    """`LLMT_CHAOS_CKPT_CORRUPT=truncate:2` fires post-manifest, pre-
    mirror: the mirror must reject the rotten copy, and the restore must
    fall back primary(2 corrupt) -> mirror(2 absent) -> older step 1."""
    primary, mirror = tmp_path / "p", tmp_path / "m"
    install_chaos(ChaosConfig(ckpt_corrupt="truncate:2"), registry=registry)
    ckpt = _checkpointer(primary, mirror_dir=str(mirror), verify="fast")
    ckpt.save(1, _tiny_state(1.0))
    ckpt.save(2, _tiny_state(2.0))  # corrupted right after its manifest
    ckpt.wait()
    assert durability.committed_steps(mirror) == [1]  # 2 was rejected
    state, shardings = _restore_args(_tiny_state(0.0))
    restored, meta = ckpt.maybe_restore(state, shardings)
    assert meta["step"] == 1
    snap, _ = registry.snapshot_with_kinds()
    assert snap["ckpt/mirror_verify_rejects"] >= 1
    assert snap["resilience/restore_fallbacks"] == 1
    assert snap["checkpoint/verify_failures"] >= 1
    ckpt.close()


def test_untargeted_chaos_flip_heals_at_restore(tmp_path, registry):
    """`LLMT_CHAOS_CKPT_CORRUPT=flip` (no step) fires at the final barrier
    AFTER the mirror drained — the restore must land on the mirror copy."""
    primary, mirror = tmp_path / "p", tmp_path / "m"
    install_chaos(ChaosConfig(ckpt_corrupt="flip"), registry=registry)
    ckpt = _checkpointer(primary, mirror_dir=str(mirror), verify="full")
    ckpt.save(2, _tiny_state(7.0))
    ckpt.wait()  # drain, then the flip lands on the newest primary step
    assert not durability.verify_step(primary, 2, mode="full").ok
    state, shardings = _restore_args(_tiny_state(0.0))
    restored, meta = ckpt.maybe_restore(state, shardings)
    assert meta["step"] == 2
    np.testing.assert_array_equal(np.asarray(restored.params["w"]), 7.0)
    snap, _ = registry.snapshot_with_kinds()
    assert snap["checkpoint/mirror_restores"] == 1
    ckpt.close()


# ------------------------------------------------------- report surface


def test_report_renders_durability_section(tmp_path):
    from llm_training_tpu.telemetry.report import render_report, render_report_data

    run_dir = tmp_path / "run"
    run_dir.mkdir()
    (run_dir / "telemetry.jsonl").write_text(json.dumps({
        "step": 10,
        "checkpoint/verify_failures": 1.0,
        "checkpoint/mirror_restores": 1.0,
        "ckpt/mirror_verify_rejects": 0.0,
        "ckpt/mirrored_steps": 3.0,
        "ckpt/mirror_lag_steps": 0.0,
        "ckpt/scrub_ok": 5.0,
    }) + "\n")
    text = render_report(run_dir)
    assert "== Durability ==" in text
    assert "restores healed from the mirror: 1" in text
    assert "mirrored steps: 3" in text
    data = render_report_data(run_dir)
    assert data["durability"]["checkpoint/verify_failures"] == 1.0
    assert data["durability"]["ckpt/mirrored_steps"] == 3.0


def test_statusz_health_line_flags_durability(tmp_path, registry):
    from llm_training_tpu.telemetry.exporter import MetricsExporter

    registry.counter("checkpoint/verify_failures").inc()
    registry.gauge("ckpt/mirror_lag_steps").set(2)
    registry.gauge("ckpt/mirrored_steps").set(1)
    text = MetricsExporter(0, registry=registry).render_statusz()
    assert "durability:" in text
    assert "verify failures 1" in text
    # the problem surfaces on the health line itself, not just the detail
    health_line = next(l for l in text.splitlines() if l.startswith("health:"))
    assert "durability" in health_line
