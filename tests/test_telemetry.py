"""Telemetry subsystem: registry, goodput ledger, device gauges, report CLI.

The ledger tests use an injected fake clock, so phase classification is
asserted deterministically — no sleeps. The integration test runs a real
tiny fit and checks the acceptance contract: telemetry.jsonl carries
goodput%, per-phase seconds, HBM gauges, and compile_time_s; phases sum to
the ledger total; and `report` renders it with exit code 0.
"""

import json
import threading

import pytest

from llm_training_tpu.telemetry import (
    GoodputLedger,
    TelemetryRegistry,
    get_registry,
    hbm_gauges,
    set_registry,
)
from llm_training_tpu.telemetry.goodput import PHASES
from llm_training_tpu.telemetry.report import render_report, report_main


class FakeClock:
    def __init__(self):
        self.now = 100.0

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += seconds


# ------------------------------------------------------------ registry


def test_registry_counters_gauges_timers():
    reg = TelemetryRegistry()
    reg.counter("events").inc()
    reg.counter("events").inc(2)
    reg.gauge("hbm/peak").set(42.0)
    timer = reg.timer("io")
    timer.add(0.5)
    timer.add(1.5)
    snap = reg.snapshot()
    assert snap["events"] == 3.0
    assert snap["hbm/peak"] == 42.0
    assert snap["io_s"] == 2.0
    assert snap["io_n"] == 2.0
    # unset gauges are omitted, not emitted as None
    reg.gauge("unset")
    assert "unset" not in reg.snapshot()


def test_registry_timer_context_manager_counts_on_exception():
    reg = TelemetryRegistry(clock=FakeClock())
    timer = reg.timer("t")
    with pytest.raises(RuntimeError):
        with timer.time():
            raise RuntimeError("boom")
    assert timer.count == 1


def test_registry_thread_safety():
    reg = TelemetryRegistry()
    counter = reg.counter("n")

    def hammer():
        for _ in range(1000):
            counter.inc()
            reg.timer("t").add(0.001)

    threads = [threading.Thread(target=hammer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = reg.snapshot()
    assert snap["n"] == 8000.0
    assert snap["t_n"] == 8000.0


def test_current_registry_install_and_restore():
    mine = TelemetryRegistry()
    previous = set_registry(mine)
    try:
        assert get_registry() is mine
    finally:
        set_registry(previous)
    assert get_registry() is previous


# ------------------------------------------------------------ goodput ledger


def test_ledger_phase_classification_sums_to_total():
    """Satellite: fake-clock phase classification — injected checkpoint,
    validation, and data-stall phases must land in their buckets, sum (with
    `other`) to total wall time, and yield the right goodput%."""
    clock = FakeClock()
    ledger = GoodputLedger(clock=clock)
    ledger.start()

    with ledger.measure("compile"):
        clock.advance(10.0)
    for _ in range(4):
        with ledger.measure("data_wait"):
            clock.advance(2.0)  # injected data stall
        with ledger.measure("step_compute"):
            clock.advance(15.0)
    with ledger.measure("checkpoint_save"):
        clock.advance(5.0)
    with ledger.measure("validation"):
        clock.advance(7.0)
    clock.advance(10.0)  # unattributed host time -> other

    s = ledger.summary()
    assert s["goodput/compile_s"] == 10.0
    assert s["goodput/data_wait_s"] == 8.0
    assert s["goodput/step_compute_s"] == 60.0
    assert s["goodput/checkpoint_save_s"] == 5.0
    assert s["goodput/validation_s"] == 7.0
    assert s["goodput/other_s"] == 10.0
    assert s["goodput/total_s"] == 100.0
    phase_sum = sum(s[f"goodput/{p}_s"] for p in PHASES + ("other",))
    assert phase_sum == pytest.approx(s["goodput/total_s"])
    assert s["goodput/goodput_pct"] == pytest.approx(60.0)


def test_ledger_restart_zeroes_and_unknown_phase_rejected():
    clock = FakeClock()
    ledger = GoodputLedger(clock=clock)
    # summary before start: all zeros, no division by zero
    s = ledger.summary()
    assert s["goodput/total_s"] == 0.0 and s["goodput/goodput_pct"] == 0.0
    ledger.start()
    with ledger.measure("step_compute"):
        clock.advance(3.0)
    ledger.start()  # restart zeroes phases
    assert ledger.summary()["goodput/step_compute_s"] == 0.0
    with pytest.raises(KeyError):
        ledger.note("not_a_phase", 1.0)


def test_ledger_note_accumulates_externally_measured_time():
    clock = FakeClock()
    ledger = GoodputLedger(clock=clock)
    ledger.start()
    ledger.note("checkpoint_save", 2.5)
    ledger.note("checkpoint_save", 1.5)
    clock.advance(8.0)
    s = ledger.summary()
    assert s["goodput/checkpoint_save_s"] == 4.0
    assert s["goodput/other_s"] == pytest.approx(4.0)


# ------------------------------------------------------------ device gauges


def test_hbm_gauges_present_on_cpu():
    """CPU backends expose no memory_stats; the host-RSS fallback must still
    produce the gauges the acceptance contract asserts on."""
    gauges = hbm_gauges()
    assert "hbm/bytes_in_use" in gauges
    assert "hbm/peak_bytes_in_use" in gauges
    assert gauges["hbm/peak_bytes_in_use"] > 0


def test_compiled_cost_gauges_from_aot_step():
    import jax
    import numpy as np

    from llm_training_tpu.telemetry import compiled_cost_gauges

    compiled = jax.jit(lambda x: (x @ x).sum()).lower(
        np.ones((16, 16), np.float32)
    ).compile()
    gauges = compiled_cost_gauges(compiled)
    assert gauges.get("xla/flops_per_step", 0) > 0


# ------------------------------------------------------------ report


def _write_run_dir(tmp_path, with_telemetry=True):
    run_dir = tmp_path / "run1"
    run_dir.mkdir()
    metrics = [
        {"step": 2, "loss": 5.0, "grad_norm": 1.0, "steps_per_sec": 2.0,
         "consumed_tokens": 512, "consumed_samples": 16},
        {"step": 4, "loss": 4.0, "grad_norm": 0.9, "steps_per_sec": 2.5,
         "consumed_tokens": 1024, "consumed_samples": 32},
        {"step": 4, "val_loss": 4.2},
    ]
    (run_dir / "metrics.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in metrics)
    )
    if with_telemetry:
        telemetry = {
            "step": 4,
            "goodput/compile_s": 3.0,
            "goodput/data_wait_s": 1.0,
            "goodput/step_compute_s": 14.0,
            "goodput/checkpoint_save_s": 1.0,
            "goodput/validation_s": 0.5,
            "goodput/other_s": 0.5,
            "goodput/total_s": 20.0,
            "goodput/goodput_pct": 70.0,
            "hbm/peak_bytes_in_use": 2.0 * 1024**3,
            "hbm/bytes_limit": 16.0 * 1024**3,
            "compile_time_s": 3.0,
            "perf/mfu": 0.55,
        }
        (run_dir / "telemetry.jsonl").write_text(json.dumps(telemetry) + "\n")
    return run_dir


def test_report_renders_goodput_table(tmp_path):
    text = render_report(_write_run_dir(tmp_path))
    assert "goodput: 70.0%" in text
    for phase in PHASES + ("other", "total"):
        assert phase in text
    assert "loss: first 5.0000 -> last 4.0000" in text
    assert "MFU (analytic 6N+attention): 0.5500" in text
    assert "peak: 2.00 GiB (HBM) of 16.00 GiB limit (12%)" in text
    assert "val_loss: 4.2000" in text


def test_report_falls_back_to_metrics_embedded_telemetry(tmp_path):
    run_dir = _write_run_dir(tmp_path, with_telemetry=False)
    # goodput keys embedded in metrics.jsonl (W&B-style single stream)
    with open(run_dir / "metrics.jsonl", "a") as f:
        f.write(json.dumps({"step": 6, "loss": 3.5,
                            "goodput/step_compute_s": 9.0,
                            "goodput/total_s": 10.0,
                            "goodput/goodput_pct": 90.0}) + "\n")
    assert "goodput: 90.0%" in render_report(run_dir)


def test_report_uses_only_the_last_run_segment(tmp_path):
    """Re-running a fixed-name config appends a second run to the same
    files; a step-number reset marks the new run and the summary must not
    pool the two."""
    run_dir = _write_run_dir(tmp_path)
    with open(run_dir / "metrics.jsonl", "a") as f:  # second run, steps reset
        f.write(json.dumps({"step": 2, "loss": 9.0, "steps_per_sec": 1.0}) + "\n")
        f.write(json.dumps({"step": 4, "loss": 8.0, "steps_per_sec": 1.0}) + "\n")
    text = render_report(run_dir)
    assert "loss: first 9.0000 -> last 8.0000" in text
    assert "(2 records)" in text


def test_report_main_exit_codes(tmp_path, capsys):
    run_dir = _write_run_dir(tmp_path)
    assert report_main(str(run_dir)) == 0
    assert "Run report" in capsys.readouterr().out
    assert report_main(str(tmp_path / "nope")) == 2


def test_report_cli_subcommand(tmp_path, capsys):
    from llm_training_tpu.cli.main import main

    run_dir = _write_run_dir(tmp_path)
    assert main(["report", str(run_dir)]) == 0
    assert "== Goodput ==" in capsys.readouterr().out


# ------------------------------------------------------------ multihost guard


def test_jsonl_logger_silent_on_secondary_hosts(tmp_path, monkeypatch):
    """Satellite: only process 0 writes run-dir artifacts — N hosts
    appending to one metrics.jsonl corrupts multi-host runs."""
    import jax

    from llm_training_tpu.callbacks import JsonlLogger, JsonlLoggerConfig

    monkeypatch.setattr(jax, "process_index", lambda: 1)
    logger = JsonlLogger(JsonlLoggerConfig(save_dir=str(tmp_path), name="r"))
    logger.on_fit_start(None, None, None, 0)
    logger.on_step_end(None, 2, {"loss": 1.0, "goodput/total_s": 1.0})
    logger.on_fit_end(None, None)
    assert not logger.run_dir.exists()  # nothing written, not even the dir

    monkeypatch.setattr(jax, "process_index", lambda: 0)
    logger.on_step_end(None, 4, {"loss": 1.0, "goodput/total_s": 1.0})
    assert (logger.run_dir / "metrics.jsonl").exists()
    assert (logger.run_dir / "telemetry.jsonl").exists()


# ------------------------------------------------------------ integration


@pytest.mark.slow
def test_fit_writes_telemetry_and_report_renders(tmp_path):
    """Acceptance: a real tiny fit (with validation + checkpointing) must
    persist goodput%, per-phase seconds, HBM gauges, and compile_time_s to
    both JSONL streams; phase seconds must sum to the ledger total (within
    5%); and `report` must render the run dir with exit code 0."""
    from llm_training_tpu.callbacks import JsonlLogger, JsonlLoggerConfig
    from llm_training_tpu.data import DummyDataModule, DummyDataModuleConfig
    from llm_training_tpu.lms import CLM, CLMConfig, ModelProvider
    from llm_training_tpu.parallel import MeshConfig
    from llm_training_tpu.trainer import Trainer, TrainerConfig
    from llm_training_tpu.trainer.checkpoint import CheckpointConfig, Checkpointer

    objective = CLM(CLMConfig(model=ModelProvider(
        model_class="Llama",
        model_kwargs=dict(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
            max_position_embeddings=64, attention_impl="xla",
            param_dtype="float32", compute_dtype="float32",
        ),
    )))
    datamodule = DummyDataModule(DummyDataModuleConfig(
        batch_size=8, max_length=32, num_samples=128, vocab_size=128,
        validation_split=16,
    ))
    jsonl = JsonlLogger(JsonlLoggerConfig(save_dir=str(tmp_path), name="telem"))
    trainer = Trainer(
        TrainerConfig(
            max_steps=6, log_every_n_steps=2, val_check_interval=3,
            limit_val_batches=2, checkpoint_every_n_steps=5, mesh=MeshConfig(),
        ),
        callbacks=[jsonl],
        checkpointer=Checkpointer(CheckpointConfig(
            dirpath=str(tmp_path / "ckpt"), async_save=False,
        )),
    )
    trainer.fit(objective, datamodule)

    run_dir = jsonl.run_dir
    telemetry_lines = (run_dir / "telemetry.jsonl").read_text().splitlines()
    last = json.loads(telemetry_lines[-1])
    for key in (
        ["goodput/goodput_pct", "goodput/total_s", "goodput/other_s",
         "compile_time_s", "hbm/peak_bytes_in_use"]
        + [f"goodput/{p}_s" for p in PHASES]
    ):
        assert key in last, f"missing {key}"
    phase_sum = sum(last[f"goodput/{p}_s"] for p in PHASES + ("other",))
    assert phase_sum == pytest.approx(last["goodput/total_s"], rel=0.05)
    assert last["goodput/step_compute_s"] > 0
    assert last["goodput/compile_s"] > 0
    assert last["compile_time_s"] > 0
    assert 0 < last["goodput/goodput_pct"] <= 100
    # checkpoint (step 5) and validation (step 3) ran before the final log
    assert last["goodput/checkpoint_save_s"] > 0
    assert last["goodput/validation_s"] > 0
    # metrics.jsonl carries the same telemetry keys alongside loss/grad_norm
    records = [json.loads(l) for l in (run_dir / "metrics.jsonl").read_text().splitlines()]
    train_records = [r for r in records if "loss" in r]
    assert all("goodput/goodput_pct" in r for r in train_records)
    # the report CLI renders it
    from llm_training_tpu.cli.main import main

    assert main(["report", str(run_dir)]) == 0


@pytest.mark.slow
def test_variable_length_batches_fall_back_from_aot_step():
    """Pad-to-longest collators emit per-batch sequence lengths; the AOT
    executable is pinned to sample_batch's shapes, so the trainer must fall
    back to the jitted step (which recompiles) instead of aborting."""
    from llm_training_tpu.data import DummyDataModule, DummyDataModuleConfig
    from llm_training_tpu.lms import CLM, CLMConfig, ModelProvider
    from llm_training_tpu.parallel import MeshConfig
    from llm_training_tpu.trainer import Trainer, TrainerConfig

    class VarLenDataModule(DummyDataModule):
        def train_batches(self, start_step=0):
            for i, batch in enumerate(super().train_batches(start_step)):
                if i % 2 == 1:  # every other batch pads shorter
                    batch = {k: v[:, :24] for k, v in batch.items()}
                yield batch

    objective = CLM(CLMConfig(model=ModelProvider(
        model_class="Llama",
        model_kwargs=dict(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
            max_position_embeddings=64, attention_impl="xla",
            param_dtype="float32", compute_dtype="float32",
        ),
    )))
    datamodule = VarLenDataModule(DummyDataModuleConfig(
        batch_size=8, max_length=32, num_samples=128, vocab_size=128,
    ))
    trainer = Trainer(
        TrainerConfig(max_steps=4, log_every_n_steps=2, mesh=MeshConfig()),
    )
    trainer.fit(objective, datamodule)
    assert trainer.last_step == 4
    assert float(trainer.last_metrics["loss"]) > 0


@pytest.mark.slow
def test_first_log_window_excludes_compile_time(tmp_path):
    """Satellite: steps_per_sec must not be dragged down by JIT compile —
    the window resets after step 1, and compile lands in compile_time_s."""
    from llm_training_tpu.callbacks import JsonlLogger, JsonlLoggerConfig
    from llm_training_tpu.data import DummyDataModule, DummyDataModuleConfig
    from llm_training_tpu.lms import CLM, CLMConfig, ModelProvider
    from llm_training_tpu.parallel import MeshConfig
    from llm_training_tpu.trainer import Trainer, TrainerConfig

    objective = CLM(CLMConfig(model=ModelProvider(
        model_class="Llama",
        model_kwargs=dict(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
            max_position_embeddings=64, attention_impl="xla",
            param_dtype="float32", compute_dtype="float32",
        ),
    )))
    datamodule = DummyDataModule(DummyDataModuleConfig(
        batch_size=8, max_length=32, num_samples=128, vocab_size=128,
    ))
    jsonl = JsonlLogger(JsonlLoggerConfig(save_dir=str(tmp_path), name="sps"))
    Trainer(
        TrainerConfig(max_steps=4, log_every_n_steps=2, mesh=MeshConfig()),
        callbacks=[jsonl],
    ).fit(objective, datamodule)
    records = [json.loads(l) for l in
               (jsonl.run_dir / "metrics.jsonl").read_text().splitlines()]
    first = records[0]
    assert first["compile_time_s"] > 0
    # window [1 -> 2] covers one compiled step; if compile leaked in, the
    # implied per-step time would exceed compile_time_s
    assert 1.0 / first["steps_per_sec"] < first["compile_time_s"]
