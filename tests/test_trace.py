"""Request/step tracing + flight recorder (docs/observability.md#tracing):
`TraceRecorder` units (ring bound, sampling, sink gating), the Chrome-trace
export and its Perfetto track mapping, `summarize_trace` aggregates, the
`trace` CLI, `report`'s `== Trace ==` section + `--format json` schema, and
the flight-dump hooks (watchdog hang dumps, anomaly dumps)."""

import json
import threading

import pytest

from llm_training_tpu.telemetry.trace import (
    TraceRecorder,
    clock_anchor,
    get_tracer,
    merge_traces,
    read_trace_events,
    resolve_trace_file,
    set_tracer,
    summarize_trace,
    to_chrome_trace,
    trace_main,
    wall_align,
)


@pytest.fixture()
def tracer():
    """A fresh recorder installed as process-current, restored afterwards
    (engine/scheduler/trainer code paths all emit through get_tracer())."""
    recorder = TraceRecorder(capacity=256, sample_every=1, train_steps=False,
                             enabled=True)
    previous = set_tracer(recorder)
    try:
        yield recorder
    finally:
        recorder.detach_sink()
        set_tracer(previous)


# ------------------------------------------------------------- recorder


def test_ring_is_bounded_and_keeps_newest():
    recorder = TraceRecorder(capacity=4, enabled=True)
    for n in range(10):
        recorder.instant("train", f"e{n}")
    names = [e["name"] for e in recorder.snapshot()]
    assert names == ["e6", "e7", "e8", "e9"]
    assert recorder.counts()["recorded"] == 10


def test_span_and_measure_record_duration(tracer):
    tracer.span("serve", "queue", 1.0, 1.5, request_id="r0")
    with tracer.measure("train", "compile"):
        pass
    spans = tracer.snapshot()
    assert spans[0]["ph"] == "X" and spans[0]["dur"] == pytest.approx(0.5)
    assert spans[0]["args"]["request_id"] == "r0"
    assert spans[1]["name"] == "compile" and spans[1]["dur"] >= 0.0


def test_sink_writes_only_sampled_events(tmp_path, tracer):
    path = tmp_path / "trace.jsonl"
    assert tracer.attach_sink(path)
    # the first owner keeps the sink; a second attach is refused
    assert not tracer.attach_sink(tmp_path / "other.jsonl")
    tracer.instant("serve", "submit", write=True, request_id="a")
    tracer.instant("serve", "submit", write=False, request_id="b")
    tracer.detach_sink()
    written = read_trace_events(path)
    # attaching always writes the clock anchor first — the wall/monotonic
    # pair `trace --merge` aligns replicas on — then sampled events only
    assert written[0]["cat"] == "meta" and written[0]["name"] == "clock_anchor"
    assert [e["args"]["request_id"] for e in written[1:]] == ["a"]
    counts = tracer.counts()
    assert counts["recorded"] == 3 and counts["written"] == 2


def test_request_sampling_every_nth():
    recorder = TraceRecorder(sample_every=3, enabled=True)
    decisions = [recorder.sample_request() for _ in range(7)]
    assert decisions == [True, False, False, True, False, False, True]
    assert recorder.counts()["requests_sampled"] == 3


def test_env_knobs_override_defaults(monkeypatch):
    monkeypatch.setenv("LLMT_TRACE_RING", "7")
    monkeypatch.setenv("LLMT_TRACE_SAMPLE", "4")
    monkeypatch.setenv("LLMT_TRACE_TRAIN", "1")
    recorder = TraceRecorder()
    assert recorder.capacity == 7
    assert recorder.sample_every == 4
    assert recorder.train_steps is True
    monkeypatch.setenv("LLMT_TRACE", "0")
    disabled = TraceRecorder()
    assert disabled.enabled is False
    disabled.instant("train", "e")
    assert disabled.snapshot() == []
    assert not disabled.attach_sink("/dev/null")


def test_malformed_env_degrades_to_default(monkeypatch):
    monkeypatch.setenv("LLMT_TRACE_RING", "banana")
    assert TraceRecorder().capacity == 2048


def test_recorder_is_thread_safe(tracer):
    def emit(tag):
        for n in range(200):
            tracer.instant("serve", f"{tag}-{n}")

    threads = [threading.Thread(target=emit, args=(t,)) for t in range(4)]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join()
    assert tracer.counts()["recorded"] == 800
    assert len(tracer.snapshot()) == 256  # capacity


def test_flight_dump_writes_ring(tmp_path, tracer):
    for n in range(5):
        tracer.instant("train", "train_step", step=n)
    path = tracer.flight_dump(tmp_path, "hang-test")
    assert path is not None and path.name == "trace-flight-hang-test.jsonl"
    events = read_trace_events(path)
    # a flight dump is mergeable too: its head line is a fresh clock anchor
    assert events[0]["cat"] == "meta" and events[0]["name"] == "clock_anchor"
    assert [e["args"]["step"] for e in events[1:]] == list(range(5))
    assert tracer.counts()["flight_dumps"] == 1


# --------------------------------------------------------------- export


def _sample_events():
    return [
        {"ts": 1.0, "dur": 0.5, "ph": "X", "cat": "serve", "name": "queue",
         "args": {"request_id": "r0", "residency": 0}},
        {"ts": 1.5, "dur": 1.0, "ph": "X", "cat": "serve", "name": "prefill",
         "args": {"request_id": "r0", "residency": 0}},
        {"ts": 2.5, "ph": "i", "cat": "serve", "name": "first_token",
         "args": {"request_id": "r0", "ttft_ms": 1500.0}},
        {"ts": 2.5, "dur": 0.7, "ph": "X", "cat": "serve", "name": "decode",
         "args": {"request_id": "r0", "residency": 0}},
        {"ts": 3.2, "ph": "i", "cat": "serve", "name": "done",
         "args": {"request_id": "r0", "stop_reason": "max_tokens",
                  "n_tokens": 8, "evictions": 0, "queue_wait_ms": 500.0}},
        {"ts": 0.9, "dur": 2.4, "ph": "X", "cat": "serve", "name": "engine_step",
         "args": {"step": 1}},
        {"ts": 0.0, "dur": 0.8, "ph": "X", "cat": "train", "name": "compile"},
        {"ts": 0.8, "dur": 0.1, "ph": "X", "cat": "train", "name": "train_step",
         "args": {"step": 0}},
        {"ts": 4.0, "ph": "i", "cat": "resilience", "name": "rollback",
         "args": {"failed_step": 3}},
    ]


def test_chrome_export_tracks_and_units():
    doc = to_chrome_trace(_sample_events())
    events = doc["traceEvents"]
    meta = [e for e in events if e["ph"] == "M"]
    names = {(e["pid"], e["tid"]): e["args"]["name"] for e in meta
             if e["name"] == "thread_name"}
    # the request got its own named track, distinct from the engine's
    request_tids = {e["tid"] for e in events
                    if e.get("args", {}).get("request_id") == "r0"}
    assert len(request_tids) == 1
    request_tid = request_tids.pop()
    assert names[(1, request_tid)] == "req r0"
    engine = next(e for e in events if e["name"] == "engine_step")
    assert engine["tid"] != request_tid
    # train and resilience land on their own pids
    assert next(e for e in events if e["name"] == "compile")["pid"] == 2
    assert next(e for e in events if e["name"] == "rollback")["pid"] == 3
    # µs conversion + instant scoping
    queue = next(e for e in events if e["name"] == "queue")
    assert queue["ts"] == pytest.approx(1.0e6) and queue["dur"] == pytest.approx(0.5e6)
    first = next(e for e in events if e["name"] == "first_token")
    assert first["ph"] == "i" and first["s"] == "t"


def test_chrome_export_skips_malformed_records():
    events = _sample_events() + [{"ts": "junk", "ph": "X", "name": "bad"}]
    doc = to_chrome_trace(events)
    assert all(e["name"] != "bad" for e in doc["traceEvents"])


def test_summarize_trace_aggregates_and_slowest():
    summary = summarize_trace(_sample_events())
    assert summary["events"] == 9
    assert summary["spans"]["serve/queue"]["count"] == 1
    assert summary["spans"]["train/train_step"]["total_s"] == pytest.approx(0.1)
    assert summary["requests_traced"] == 1
    assert summary["requests_completed"] == 1
    (slowest,) = summary["slowest_requests"]
    assert slowest["id"] == "r0"
    assert slowest["wall_ms"] == pytest.approx(2200.0)
    assert slowest["queue_ms"] == pytest.approx(500.0)
    assert slowest["prefill_ms"] == pytest.approx(1000.0)
    assert slowest["decode_ms"] == pytest.approx(700.0)
    assert slowest["ttft_ms"] == pytest.approx(1500.0)
    assert slowest["n_tokens"] == 8


def test_summarize_splits_reused_ids_across_appended_runs():
    """trace.jsonl appends across runs and the loadgen reuses req-0 per
    run: a second submit for an already-completed id must open a NEW
    logical request, not merge phases across runs (review finding)."""
    run1 = _sample_events()
    run2 = [
        {"ts": 10.0, "ph": "i", "cat": "serve", "name": "submit",
         "args": {"request_id": "r0", "prompt_len": 4}},
        {"ts": 10.0, "dur": 0.2, "ph": "X", "cat": "serve", "name": "queue",
         "args": {"request_id": "r0", "residency": 0}},
        {"ts": 10.2, "dur": 0.3, "ph": "X", "cat": "serve", "name": "prefill",
         "args": {"request_id": "r0", "residency": 0}},
        {"ts": 10.5, "ph": "i", "cat": "serve", "name": "first_token",
         "args": {"request_id": "r0", "ttft_ms": 500.0}},
        {"ts": 10.5, "dur": 0.1, "ph": "X", "cat": "serve", "name": "decode",
         "args": {"request_id": "r0", "residency": 0}},
        {"ts": 10.6, "ph": "i", "cat": "serve", "name": "done",
         "args": {"request_id": "r0", "stop_reason": "eos", "n_tokens": 2,
                  "evictions": 0, "queue_wait_ms": 200.0}},
    ]
    summary = summarize_trace(run1 + run2, top_k=5)
    assert summary["requests_traced"] == 2
    assert summary["requests_completed"] == 2
    by_id = {r["id"]: r for r in summary["slowest_requests"]}
    assert by_id["r0"]["wall_ms"] == pytest.approx(2200.0)  # run 1 alone
    assert by_id["r0#2"]["wall_ms"] == pytest.approx(600.0)  # run 2 alone
    assert by_id["r0#2"]["ttft_ms"] == pytest.approx(500.0)


def test_read_trace_events_tolerates_torn_tail(tmp_path):
    path = tmp_path / "trace.jsonl"
    path.write_text(
        json.dumps({"ts": 1.0, "ph": "i", "cat": "train", "name": "a"})
        + "\n[not json\n" + '{"no_ts": true}\n'
        + json.dumps({"ts": 2.0, "ph": "i", "cat": "train", "name": "b"})[:-4]
        + "\n"
    )
    events = read_trace_events(path)
    assert [e["name"] for e in events] == ["a"]


# ------------------------------------------------------------------ CLI


def test_trace_cli_exports_run_dir(tmp_path, capsys):
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    with open(run_dir / "trace.jsonl", "w") as f:
        for event in _sample_events():
            f.write(json.dumps(event) + "\n")
    assert resolve_trace_file(run_dir) == run_dir / "trace.jsonl"
    assert trace_main(str(run_dir)) == 0
    out = capsys.readouterr().out
    assert "perfetto" in out.lower()
    doc = json.loads((run_dir / "trace-export.json").read_text())
    assert doc["traceEvents"] and doc["displayTimeUnit"] == "ms"


def test_trace_cli_exit_2_on_missing_or_empty(tmp_path, capsys):
    assert trace_main(str(tmp_path)) == 2
    empty = tmp_path / "trace.jsonl"
    empty.write_text("not json\n")
    assert trace_main(str(tmp_path)) == 2
    capsys.readouterr()


# --------------------------------------------------------------- report


def _write_run_dir(tmp_path, with_trace=True):
    run_dir = tmp_path / "run"
    run_dir.mkdir(exist_ok=True)
    with open(run_dir / "metrics.jsonl", "w") as f:
        for step in (1, 2):
            f.write(json.dumps({"step": step, "loss": 2.0 - step * 0.1,
                                "steps_per_sec": 1.5}) + "\n")
    with open(run_dir / "telemetry.jsonl", "w") as f:
        f.write(json.dumps({
            "step": 2, "goodput/total_s": 10.0, "goodput/step_compute_s": 8.0,
            "goodput/goodput_pct": 80.0, "serve/requests_completed": 1.0,
            "trace/events_recorded": 9.0,
        }) + "\n")
    if with_trace:
        with open(run_dir / "trace.jsonl", "w") as f:
            for event in _sample_events():
                f.write(json.dumps(event) + "\n")
    return run_dir


def test_report_trace_section_renders_and_omits(tmp_path):
    from llm_training_tpu.telemetry.report import render_report

    run_dir = _write_run_dir(tmp_path)
    text = render_report(run_dir)
    assert "== Trace ==" in text
    assert "serve/queue" in text
    assert "slowest requests:" in text
    assert "r0:" in text
    # no trace.jsonl -> section omitted entirely
    (run_dir / "trace.jsonl").unlink()
    assert "== Trace ==" not in render_report(run_dir)


def test_report_trace_section_degrades_on_garbage(tmp_path):
    from llm_training_tpu.telemetry.report import render_report

    run_dir = _write_run_dir(tmp_path, with_trace=False)
    (run_dir / "trace.jsonl").write_text("not json at all\n{{{\n")
    text = render_report(run_dir)
    assert "== Trace ==" in text
    assert "no parseable events" in text


def test_report_json_schema(tmp_path, monkeypatch):
    """`report --format json` (CI trend tracking): pin the top-level
    schema — every section key present, absent sections null, numbers
    where CI expects them."""
    from llm_training_tpu.telemetry.report import (
        REPORT_SCHEMA_VERSION,
        render_report_data,
    )

    # the perf section's cwd fallback would otherwise find the repo's
    # committed BENCH_r*.json rounds
    monkeypatch.chdir(tmp_path)
    run_dir = _write_run_dir(tmp_path)
    doc = render_report_data(run_dir)
    assert doc["schema_version"] == REPORT_SCHEMA_VERSION == 1
    for key in (
        "run_dir", "world", "training", "goodput", "device_memory",
        "health", "perf", "audit", "inference", "serving", "slo",
        "elastic", "trace", "recovery", "flash", "telemetry", "fleet",
    ):
        assert key in doc, key
    # no fleet.json snapshot in the fixture -> null block
    # (tests/test_fleet.py pins the populated shape)
    assert doc["fleet"] is None
    # no SLO config armed in the fixture -> null block, like the omitted
    # text section (tests/test_exporter.py pins the armed shape)
    assert doc["slo"] is None
    assert doc["training"]["records"] == 2
    assert doc["training"]["loss_last"] == pytest.approx(1.8)
    assert doc["goodput"]["goodput/goodput_pct"] == 80.0
    assert doc["serving"] == {"serve/requests_completed": 1.0}
    assert doc["trace"]["events"] == 9
    assert doc["health"] is None and doc["perf"] is None
    # the raw record rides along so no numeric key is lost to shaping
    assert doc["telemetry"]["trace/events_recorded"] == 9.0
    json.dumps(doc)  # the whole document must be JSON-serializable


def test_report_json_carries_supervisor_segments(tmp_path, monkeypatch):
    """`--format json` must not drop the per-segment elastic data text
    mode renders from supervisor.jsonl (review finding)."""
    from llm_training_tpu.telemetry.report import render_report_data

    monkeypatch.chdir(tmp_path)
    run_dir = _write_run_dir(tmp_path, with_trace=False)
    with open(run_dir / "supervisor.jsonl", "w") as f:
        f.write(json.dumps({
            "event": "segment_topology", "attempt": 0, "device_count": 8,
            "mesh": {"data": 8}, "decision": "fresh",
        }) + "\n")
        f.write(json.dumps({
            "event": "exit", "attempt": 0, "rc": -9, "signal": "SIGKILL",
            "runtime_s": 12.5,
        }) + "\n")
        f.write(json.dumps({
            "event": "segment_topology", "attempt": 1, "device_count": 4,
            "mesh": {"data": 4}, "decision": "scaled data 8->4",
        }) + "\n")
    doc = render_report_data(run_dir)
    segments = doc["elastic"]["segments"]
    assert [s["attempt"] for s in segments] == [0, 1]
    assert segments[0]["device_count"] == 8 and segments[0]["exit"] == "SIGKILL"
    assert segments[0]["runtime_s"] == 12.5
    assert segments[1]["decision"] == "scaled data 8->4"
    json.dumps(doc)


def test_report_json_requires_run_dir(tmp_path):
    from llm_training_tpu.telemetry.report import render_report_data

    with pytest.raises(FileNotFoundError):
        render_report_data(tmp_path)


# ------------------------------------------------------- flight recorder


def test_watchdog_dump_flushes_flight_recorder(tmp_path, tracer):
    from llm_training_tpu.resilience.watchdog import HangWatchdog

    tracer.instant("train", "train_step", step=41)
    tracer.instant("train", "train_step", step=42)
    watchdog = HangWatchdog(timeout_s=60.0, run_dir=tmp_path)
    watchdog.beat("train_loop", step=42)
    assert watchdog.dump(123.0) is not None
    flights = list(tmp_path.glob("trace-flight-hang-*.jsonl"))
    assert len(flights) == 1
    events = read_trace_events(flights[0])
    assert events[0]["name"] == "clock_anchor"
    assert [e["args"]["step"] for e in events[1:3]] == [41, 42]


def test_anomaly_dump_flushes_flight_recorder(tmp_path, tracer):
    from llm_training_tpu.telemetry.anomaly import dump_anomaly

    tracer.instant("train", "train_step", step=7)
    path = dump_anomaly(tmp_path, 7, "non_finite", {"loss": float("nan")})
    assert path is not None
    flight = tmp_path / "trace-flight-anomaly-7.jsonl"
    assert flight.is_file()
    events = read_trace_events(flight)
    assert events[0]["name"] == "clock_anchor"
    assert events[1]["args"]["step"] == 7


def test_flight_dumps_export_to_chrome(tmp_path, tracer):
    """A flight dump is itself a valid `trace` CLI source — post-mortems
    open straight in Perfetto."""
    tracer.instant("serve", "submit", request_id="r9")
    dump = tracer.flight_dump(tmp_path, "rollback-3")
    assert trace_main(str(dump), out=str(tmp_path / "out.json")) == 0
    doc = json.loads((tmp_path / "out.json").read_text())
    assert any(
        e.get("args", {}).get("request_id") == "r9" for e in doc["traceEvents"]
    )


# ----------------------------------------- cross-replica merge (#fleet)


def _anchor_line(mono_s, wall_s, err_s=0.0, attempt=0, pid=1):
    return {"ts": mono_s, "ph": "i", "cat": "meta", "name": "clock_anchor",
            "args": {"mono_s": mono_s, "wall_s": wall_s, "err_s": err_s,
                     "pid": pid, "attempt": attempt}}


def _serve_span(ts, rid, dur=0.5, name="decode"):
    return {"ts": ts, "dur": dur, "ph": "X", "cat": "serve", "name": name,
            "args": {"request_id": rid}}


def _write_trace(run_dir, lines):
    run_dir.mkdir(parents=True, exist_ok=True)
    path = run_dir / "trace.jsonl"
    path.write_text("".join(json.dumps(line) + "\n" for line in lines))
    return path


def test_clock_anchor_pairs_wall_and_monotonic(monkeypatch):
    anchor = clock_anchor(clock=lambda: 5.0)
    assert anchor["mono_s"] == 5.0 and anchor["err_s"] == 0.0
    assert anchor["pid"] > 0 and anchor["attempt"] == 0
    import time as _time
    live = clock_anchor()
    assert abs(live["wall_s"] - _time.time()) < 5.0
    assert live["err_s"] >= 0.0
    monkeypatch.setenv("LLMT_SUPERVISOR_ATTEMPT", "3")
    assert clock_anchor()["attempt"] == 3
    monkeypatch.setenv("LLMT_SUPERVISOR_ATTEMPT", "banana")
    assert clock_anchor()["attempt"] == 0  # malformed degrades, never raises


def test_attach_sink_leads_with_anchor_and_round_trips(tmp_path, tracer):
    """The satellite round-trip: the anchor the sink writes is the anchor
    wall_align reads back, so |aligned - wall| <= err_s by construction."""
    path = tmp_path / "trace.jsonl"
    assert tracer.attach_sink(path)
    tracer.instant("serve", "submit", write=True, request_id="r0")
    tracer.detach_sink()
    events = read_trace_events(path)
    anchor = events[0]["args"]
    aligned, max_err = wall_align(events)
    assert len(aligned) == 1  # the meta event steers, never renders
    want_wall = events[1]["ts"] + (anchor["wall_s"] - anchor["mono_s"])
    assert aligned[0]["ts"] == pytest.approx(want_wall, abs=1e-9)
    assert max_err == anchor["err_s"] >= 0.0


def test_wall_align_is_segment_wise():
    """A supervised relaunch appends a fresh anchor mid-file: events after
    it must align by the NEW pair, events before it by the old one."""
    events = [
        _anchor_line(10.0, 1000.0, attempt=0),
        _serve_span(11.0, "a"),        # old segment: wall 1001
        _anchor_line(3.0, 2000.0, attempt=1),  # relaunch: clock restarted
        _serve_span(4.0, "b"),         # new segment: wall 2001
    ]
    # the relaunch anchor has the SMALLER mono — nearest-preceding must
    # key on mono order, not file order
    aligned, _ = wall_align(events)
    by_rid = {e["args"]["request_id"]: e["ts"] for e in aligned}
    assert by_rid["a"] == pytest.approx(1001.0)
    assert by_rid["b"] == pytest.approx(2001.0)


def test_wall_align_returns_none_without_anchor():
    assert wall_align([_serve_span(1.0, "a")]) is None


def test_to_chrome_trace_merge_hooks():
    events = [_anchor_line(0.0, 50.0), _serve_span(1.0, "r1")]
    doc = to_chrome_trace(events, pid_base=300, label="replica-3")
    names = [e for e in doc["traceEvents"] if e.get("name") == "process_name"]
    assert all(e["args"]["name"].startswith("replica-3/") for e in names)
    assert all(e["pid"] >= 300 for e in doc["traceEvents"])
    assert not any(e.get("cat") == "meta" for e in doc["traceEvents"])


def test_merge_traces_aligns_and_namespaces(tmp_path):
    """Two replicas with wildly different monotonic bases but overlapping
    wall time merge into one timeline: same-wall-instant events land at
    the same merged ts, each under its own pid namespace and label."""
    a = _write_trace(tmp_path / "replica-0", [
        _anchor_line(100.0, 5000.0, err_s=0.002),
        _serve_span(101.0, "req-0"),   # wall 5001 -> merged t=0
        _serve_span(103.0, "req-1"),   # wall 5003
    ])
    _write_trace(tmp_path / "replica-1", [
        _anchor_line(7.0, 4994.0, err_s=0.003),
        _serve_span(14.0, "req-2"),    # wall 5001 too — same instant
    ])
    document, info = merge_traces(
        [tmp_path / "replica-0", tmp_path / "replica-1"]
    )
    assert info["labels"] == ["replica-0", "replica-1"]
    assert info["events"] == 3 and info["t0_wall_s"] == pytest.approx(5001.0)
    # the skew bound is the SUM of the two worst per-file anchor errors
    assert info["skew_bound_s"] == pytest.approx(0.005)
    spans = {e["args"]["request_id"]: e for e in document["traceEvents"]
             if e.get("ph") == "X"}
    assert spans["req-0"]["ts"] == pytest.approx(0.0)
    assert spans["req-2"]["ts"] == pytest.approx(0.0)       # wall-aligned
    assert spans["req-1"]["ts"] == pytest.approx(2e6)       # +2s in µs
    assert spans["req-0"]["pid"] != spans["req-2"]["pid"]   # pid namespaces
    assert str(a) in info["sources"][0]


def test_merge_traces_dedupes_labels_and_rejects_bad_sources(tmp_path):
    _write_trace(tmp_path / "a" / "run", [
        _anchor_line(0.0, 100.0), _serve_span(1.0, "x")])
    _write_trace(tmp_path / "b" / "run", [
        _anchor_line(0.0, 100.0), _serve_span(1.0, "y")])
    _, info = merge_traces([tmp_path / "a" / "run", tmp_path / "b" / "run"])
    assert info["labels"] == ["run", "run#1"]

    missing = tmp_path / "nope"
    with pytest.raises(ValueError) as excinfo:
        merge_traces([missing])
    # exit-2 contract: the error names EVERY searched path
    assert str(missing) in str(excinfo.value)
    assert str(missing / "trace.jsonl") in str(excinfo.value)

    anchorless = tmp_path / "old"
    _write_trace(anchorless, [_serve_span(1.0, "z")])
    with pytest.raises(ValueError, match="clock_anchor"):
        merge_traces([anchorless])


def test_trace_cli_merge_and_exit_2_paths(tmp_path, capsys):
    _write_trace(tmp_path / "r0", [
        _anchor_line(0.0, 100.0, err_s=0.001), _serve_span(1.0, "req-0")])
    _write_trace(tmp_path / "r1", [
        _anchor_line(50.0, 100.5, err_s=0.001), _serve_span(51.0, "req-1")])
    assert trace_main(merge=[str(tmp_path / "r0"), str(tmp_path / "r1")]) == 0
    out = capsys.readouterr().out
    assert "merged" in out and "|skew| <=" in out
    # default out lands in the FIRST source dir
    merged = json.loads((tmp_path / "r0" / "trace-merged.json").read_text())
    rids = {e.get("args", {}).get("request_id") for e in merged["traceEvents"]}
    assert {"req-0", "req-1"} <= rids

    assert trace_main(merge=[str(tmp_path / "gone")]) == 2
    err = capsys.readouterr().err
    assert str(tmp_path / "gone") in err
    assert str(tmp_path / "gone" / "trace.jsonl") in err

    assert trace_main() == 2  # no source, no --merge
    assert "--merge" in capsys.readouterr().err

    assert trace_main(str(tmp_path / "void")) == 2
    err = capsys.readouterr().err
    assert str(tmp_path / "void") in err
    assert str(tmp_path / "void" / "trace.jsonl") in err
