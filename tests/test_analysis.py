"""graftlint (llm_training_tpu.analysis) tests — docs/static-analysis.md.

Pure-AST fixtures: each rule gets a minimal positive (a violation the rule
must flag — including a reconstruction of the exact BENCH_r04 `_dq_kernel`
two-missing-refs arity bug) and a negative (the sanctioned pattern passes).
The capstone is the whole-repo run: the real tree must produce ZERO
unbaselined findings, in under 10 seconds, without the analysis package
ever importing jax. None of these tests build a jax program, so the whole
module adds ~nothing to the tier-1 time budget.
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap
import time
from pathlib import Path

import pytest

from llm_training_tpu.analysis import contracts
from llm_training_tpu.analysis.engine import (
    DEFAULT_BASELINE,
    all_rules,
    load_baseline,
    main,
    run_analysis,
    write_baseline,
)

REPO_ROOT = Path(__file__).resolve().parents[1]

_DEFAULT_LOGGERS = """
TELEMETRY_PREFIXES = ("goodput/", "decode/", "flash/")
TELEMETRY_KEYS = ("compile_time_s",)
"""

# the logical-axis registry the `logical-axis-literal` rule parses out of
# the sharding file's AST (fixture trees get a tiny stand-in)
_DEFAULT_SHARDING = """
KNOWN_LOGICAL_AXES: tuple[str, ...] = (
    "batch", "embed", "mlp", "norm", "layers", "stages",
)
"""


def make_repo(tmp_path: Path, files: dict[str, str]) -> Path:
    """A minimal tree the engine accepts as a repo root: package inits, the
    telemetry routing file, and empty stubs for every declared jax-free
    contract file (so fixture trees don't trip the missing-contract check),
    overlaid with the test's own files."""
    base = {
        "llm_training_tpu/__init__.py": "",
        "llm_training_tpu/callbacks/__init__.py": "",
        "llm_training_tpu/callbacks/loggers.py": _DEFAULT_LOGGERS,
        "llm_training_tpu/parallel/__init__.py": "",
        "llm_training_tpu/parallel/sharding.py": _DEFAULT_SHARDING,
        "docs/performance.md": "env table: BENCH_DOCUMENTED, FLASH_DOCUMENTED\n",
    }
    for contract_rel in contracts.JAX_FREE_CONTRACTS:
        base.setdefault(contract_rel, "")
        init = Path(contract_rel).parent / "__init__.py"
        if str(init) != ".":
            base.setdefault(init.as_posix(), "")
    base.update(files)
    for rel, content in base.items():
        target = tmp_path / rel
        target.parent.mkdir(parents=True, exist_ok=True)
        target.write_text(textwrap.dedent(content))
    return tmp_path


def findings_for(root: Path, rule: str | None = None, **kwargs):
    rules = [rule] if rule else None
    return run_analysis(root, rules=rules, **kwargs).findings


# --------------------------------------------------------------- engine


def test_rule_table_has_the_seven_rules():
    names = [rule.name for rule in all_rules()]
    assert names == [
        "pallas-kernel-arity",
        "jax-free-import",
        "host-sync",
        "telemetry-prefix",
        "env-doc-drift",
        "logical-axis-literal",
        "thread-jax-free",
    ]


def test_whole_repo_is_clean_and_fast():
    """The committed tree lints clean against the committed baseline (which
    must stay empty — debt goes through inline suppressions with reasons)."""
    t0 = time.monotonic()
    baseline = load_baseline(REPO_ROOT / DEFAULT_BASELINE)
    result = run_analysis(REPO_ROOT, baseline_keys=baseline)
    elapsed = time.monotonic() - t0
    assert result.findings == [], [f.render() for f in result.findings]
    assert baseline == set(), "baseline must stay empty; fix or suppress inline"
    assert elapsed < 10.0, f"lint gate took {elapsed:.1f}s (budget 10s)"


def test_analysis_package_never_imports_jax():
    """The acceptance bar: the gate runs before any backend exists."""
    code = (
        "import sys\n"
        "from llm_training_tpu.analysis.engine import main\n"
        "rc = main(['--list-rules'])\n"
        "leaked = [m for m in sys.modules if m == 'jax' or m.startswith(('jax.', 'jaxlib'))]\n"
        "assert rc == 0 and not leaked, (rc, leaked)\n"
        "print('JAXFREE-OK')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "JAXFREE-OK" in proc.stdout


# ------------------------------------------------- rule: pallas-kernel-arity

# the exact BENCH_r04 shape: `_dq_kernel() missing 2 required positional
# arguments: 'dq_ref' and 'dq_scr'` — the kernel binds 12 refs, the call's
# specs imply 10 (2 prefetch + 6 in_specs + 1 out + 1 scratch)
_R04_FIXTURE = """
    import functools
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu


    def _dq_kernel(seg_lo_ref, seg_hi_ref, q_seg_ref, kv_seg_ref, q_ref,
                   k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr,
                   *, scale, causal):
        pass


    def flash_bwd(q, k, v, do, lse, delta, seg_lo, seg_hi, seg_q, seg_kv):
        return pl.pallas_call(
            functools.partial(_dq_kernel, scale=1.0, causal=True),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(8, 4, 4),
                in_specs=[
                    pl.BlockSpec((1, 1, 128), lambda b, i, j, lo, hi: (b, 0, i)),
                    pl.BlockSpec((1, 1, 128), lambda b, i, j, lo, hi: (b, 0, j)),
                    pl.BlockSpec((1, 128, 64), lambda b, i, j, lo, hi: (b, i, 0)),
                    pl.BlockSpec((1, 128, 64), lambda b, i, j, lo, hi: (b, j, 0)),
                    pl.BlockSpec((1, 128, 64), lambda b, i, j, lo, hi: (b, j, 0)),
                    pl.BlockSpec((1, 128, 64), lambda b, i, j, lo, hi: (b, i, 0)),
                ],
                out_specs=pl.BlockSpec((1, 128, 64), lambda b, i, j, lo, hi: (b, i, 0)),
                scratch_shapes=[pltpu.VMEM((128, 64), jax.numpy.float32)],
            ),
            out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        )(seg_lo, seg_hi, seg_q, seg_kv, q, k, v, do)
"""


def test_arity_flags_the_r04_two_missing_refs_bug(tmp_path):
    root = make_repo(tmp_path, {"llm_training_tpu/kern.py": _R04_FIXTURE})
    found = findings_for(root, "pallas-kernel-arity")
    assert len(found) == 1, [f.render() for f in found]
    message = found[0].message
    assert "_dq_kernel" in message
    assert "2 ref(s) missing" in message
    assert "BENCH_r04" in message


def test_arity_passes_once_the_two_refs_are_restored(tmp_path):
    # the shipped fix: two more in_specs (lse/delta rows) make 12 == 12
    fixed = _R04_FIXTURE.replace(
        "                ],\n                out_specs=",
        "                    pl.BlockSpec((1, 1, 128), lambda b, i, j, lo, hi: (b, 0, i)),\n"
        "                    pl.BlockSpec((1, 1, 128), lambda b, i, j, lo, hi: (b, 0, i)),\n"
        "                ],\n                out_specs=",
        1,
    )
    assert fixed != _R04_FIXTURE
    root = make_repo(tmp_path, {"llm_training_tpu/kern.py": fixed})
    assert findings_for(root, "pallas-kernel-arity") == []


def test_arity_flags_extra_refs(tmp_path):
    src = """
    from jax.experimental import pallas as pl
    import jax


    def k(a_ref, o_ref):
        pass


    def call(x):
        return pl.pallas_call(
            k,
            in_specs=[pl.BlockSpec((8,), lambda i: (i,)),
                      pl.BlockSpec((8,), lambda i: (i,))],
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        )(x, x)
    """
    root = make_repo(tmp_path, {"llm_training_tpu/kern.py": src})
    found = findings_for(root, "pallas-kernel-arity")
    assert len(found) == 1 and "extra ref(s)" in found[0].message


def test_arity_tolerates_vararg_kernels_and_conditional_appends(tmp_path):
    # the flash forward pattern: specs built as a local with a conditional
    # append, kernel absorbing the tail in *rest — provably consistent
    src = """
    from jax.experimental import pallas as pl
    import jax


    def k(a_ref, b_ref, *rest, flag=False):
        pass


    def call(x, extra):
        in_specs = [pl.BlockSpec((8,), lambda i: (i,)),
                    pl.BlockSpec((8,), lambda i: (i,))]
        args = [x, x]
        if extra is not None:
            in_specs.append(pl.BlockSpec((8,), lambda i: (i,)))
            args.append(extra)
        return pl.pallas_call(
            k,
            in_specs=in_specs,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        )(*args)
    """
    root = make_repo(tmp_path, {"llm_training_tpu/kern.py": src})
    assert findings_for(root, "pallas-kernel-arity") == []


def test_arity_degrades_to_silence_on_extend_and_augassign(tmp_path):
    # only single-element .append widens the count; .extend/+= make it
    # unknowable and must NEVER produce a false "refs missing" alarm
    src = """
    from jax.experimental import pallas as pl
    import jax


    def k(a_ref, b_ref, c_ref, o_ref):
        pass


    def call(x):
        in_specs = [pl.BlockSpec((8,), lambda i: (i,))]
        in_specs.extend([pl.BlockSpec((8,), lambda i: (i,)),
                         pl.BlockSpec((8,), lambda i: (i,))])
        return pl.pallas_call(
            k,
            in_specs=in_specs,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        )(x, x, x)


    def call2(x):
        in_specs = [pl.BlockSpec((8,), lambda i: (i,))]
        in_specs += [pl.BlockSpec((8,), lambda i: (i,)),
                     pl.BlockSpec((8,), lambda i: (i,))]
        return pl.pallas_call(
            k,
            in_specs=in_specs,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        )(x, x, x)
    """
    root = make_repo(tmp_path, {"llm_training_tpu/kern.py": src})
    assert findings_for(root, "pallas-kernel-arity") == []


def test_arity_negative_on_the_real_kernels():
    """The current (fixed) flash + paged kernels pass the rule."""
    found = run_analysis(
        REPO_ROOT, paths=["llm_training_tpu/ops/pallas"], rules=["pallas-kernel-arity"]
    ).findings
    assert found == [], [f.render() for f in found]


# ------------------------------------------------- rule: jax-free-import


def test_contract_flags_module_level_jax_import(tmp_path):
    root = make_repo(
        tmp_path,
        {"llm_training_tpu/resilience/supervisor.py": "import jax\n"},
    )
    found = findings_for(root, "jax-free-import")
    assert any(
        f.path == "llm_training_tpu/resilience/supervisor.py"
        and "module-level import of 'jax'" in f.message
        for f in found
    ), [f.render() for f in found]


def test_contract_allows_lazy_and_type_checking_imports(tmp_path):
    root = make_repo(
        tmp_path,
        {
            "llm_training_tpu/resilience/supervisor.py": """
            from typing import TYPE_CHECKING

            if TYPE_CHECKING:
                import jax

            def probe():
                import jax  # lazy: the sanctioned pattern

                return jax.devices()
            """
        },
    )
    assert findings_for(root, "jax-free-import") == []


def test_contract_walks_transitive_chains_through_package_inits(tmp_path):
    # supervisor -> (package __init__ of .helpers executes) -> helper pulls jax
    root = make_repo(
        tmp_path,
        {
            "llm_training_tpu/resilience/supervisor.py": (
                "from llm_training_tpu.helpers.util import f\n"
            ),
            "llm_training_tpu/helpers/__init__.py": (
                "from llm_training_tpu.helpers.heavy import g\n"
            ),
            "llm_training_tpu/helpers/util.py": "def f():\n    return 1\n",
            "llm_training_tpu/helpers/heavy.py": "import jax\n\ndef g():\n    pass\n",
        },
    )
    found = [
        f
        for f in findings_for(root, "jax-free-import")
        if f.path == "llm_training_tpu/resilience/supervisor.py"
    ]
    assert len(found) == 1
    assert "llm_training_tpu/helpers/heavy.py" in found[0].message
    assert found[0].line == 1  # the import in the contract module that starts the chain


def test_contract_checks_the_modules_own_package_init_chain(tmp_path):
    # importing the contract module executes its ancestor __init__s first;
    # a jax import there breaks the contract even when the contract file
    # itself imports nothing from the repo
    root = make_repo(
        tmp_path,
        {
            "llm_training_tpu/resilience/supervisor.py": (
                "def probe():\n    import jax\n    return jax.devices()\n"
            ),
            "llm_training_tpu/resilience/__init__.py": "import jax\n",
        },
    )
    found = [
        f
        for f in findings_for(root, "jax-free-import")
        if f.path == "llm_training_tpu/resilience/supervisor.py"
    ]
    assert len(found) == 1, [f.render() for f in found]
    assert "llm_training_tpu/resilience/__init__.py" in found[0].message


def test_arity_handles_module_scope_spec_lists(tmp_path):
    # specs assigned AND mutated at module scope, used inside a function:
    # the append is in the owning scope, so the count stays provable (3)
    src = """
    from jax.experimental import pallas as pl
    import jax

    IN_SPECS = [pl.BlockSpec((8,), lambda i: (i,)),
                pl.BlockSpec((8,), lambda i: (i,))]
    IN_SPECS.append(pl.BlockSpec((8,), lambda i: (i,)))


    def k(a_ref, b_ref, c_ref, o_ref):
        pass


    def call(x):
        return pl.pallas_call(
            k,
            in_specs=IN_SPECS,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        )(x, x, x)
    """
    root = make_repo(tmp_path, {"llm_training_tpu/kern.py": src})
    assert findings_for(root, "pallas-kernel-arity") == []


def test_update_baseline_with_narrow_paths_keeps_outside_entries(tmp_path, capsys):
    root = make_repo(
        tmp_path,
        {
            "bench.py": "import jax\n",
            "llm_training_tpu/other/__init__.py": "",
        },
    )
    baseline = root / "config/lint_baseline.json"
    assert main(["--root", str(root), "--update-baseline"]) == 0  # full scan
    assert main(["--root", str(root)]) == 0  # grandfathered
    # a narrow-path update must not drop the bench.py entry it cannot see.
    # (scanning a path with no contract files would still WALK bench.py via
    # the contract table, so also restrict to a rule that never leaves the
    # scan set — the hostile case for entry preservation)
    assert main(
        [
            "--root",
            str(root),
            "--update-baseline",
            "--rules",
            "telemetry-prefix",
            "llm_training_tpu/other",
        ]
    ) == 0
    assert load_baseline(baseline), "narrow update dropped the outside entry"
    assert main(["--root", str(root)]) == 0  # still grandfathered
    capsys.readouterr()


def test_contract_sees_imports_inside_match_statements(tmp_path):
    root = make_repo(
        tmp_path,
        {
            "llm_training_tpu/resilience/supervisor.py": (
                "import os\n"
                "match os.environ.get('X'):\n"
                "    case '1':\n"
                "        import jax\n"
                "    case _:\n"
                "        pass\n"
            )
        },
    )
    found = findings_for(root, "jax-free-import")
    assert any("module-level import of 'jax'" in f.message for f in found), [
        f.render() for f in found
    ]


def test_update_baseline_with_narrow_rules_keeps_other_rules_entries(tmp_path, capsys):
    root = make_repo(
        tmp_path,
        {
            "bench.py": "import jax\n",
        },
    )
    baseline = root / "config/lint_baseline.json"
    assert main(["--root", str(root), "--update-baseline"]) == 0  # full
    assert main(["--root", str(root)]) == 0
    # updating under a single rule must not drop the other rules' entries
    assert main(
        ["--root", str(root), "--update-baseline", "--rules", "telemetry-prefix"]
    ) == 0
    assert load_baseline(baseline), "rule-narrowed update dropped entries"
    assert main(["--root", str(root)]) == 0
    capsys.readouterr()


def test_real_supervisor_contract_holds_and_breaks_when_jax_is_added(tmp_path):
    """Acceptance: adding `import jax` to resilience/supervisor.py makes the
    gate exit nonzero naming the rule and location. Run on a copied tree so
    the real one stays untouched."""
    import shutil

    root = tmp_path / "copy"
    for rel in ("llm_training_tpu", "scripts", "bench.py", "docs", "README.md"):
        src = REPO_ROOT / rel
        if src.is_dir():
            shutil.copytree(src, root / rel, ignore=shutil.ignore_patterns("__pycache__"))
        else:
            root.mkdir(parents=True, exist_ok=True)
            shutil.copy(src, root / rel)
    sup = root / "llm_training_tpu/resilience/supervisor.py"
    sup.write_text("import jax\n" + sup.read_text())
    # narrow scan paths keep the test fast; the contract walk parses the
    # rest of the tree on demand regardless
    rc = main(
        [
            "--root",
            str(root),
            "--no-baseline",
            "--rules",
            "jax-free-import",
            "llm_training_tpu/resilience",
        ]
    )
    assert rc == 1


# ------------------------------------------------- rule: host-sync

_HOST_SYNC_FIXTURE = """
    import jax
    import jax.numpy as jnp


    def helper(x):
        return x.item()


    def step(params, batch):
        loss = helper(params)
        denom = float(jnp.sum(batch))
        static = float(1e-6)  # plain python float() stays legal
        return loss, denom, static


    stepped = jax.jit(step)


    def unreached(x):
        return x.item()  # not reachable from any jitted entry: not flagged
"""


def test_host_sync_flags_item_and_jax_float_in_reachable_code(tmp_path):
    root = make_repo(tmp_path, {"llm_training_tpu/step.py": _HOST_SYNC_FIXTURE})
    found = findings_for(root, "host-sync")
    rendered = [f.render() for f in found]
    assert len(found) == 2, rendered
    assert any(".item()" in f.message and "`helper`" in f.message for f in found)
    assert any("float(<jax expression>)" in f.message for f in found)
    # the unreached function's .item() stays silent
    assert not any("`unreached`" in f.message for f in found), rendered


def test_host_sync_suppression_requires_a_reason(tmp_path):
    suppressed = _HOST_SYNC_FIXTURE.replace(
        "return x.item()\n",
        "return x.item()  # lint: allow(host-sync): eval-only helper, never jitted hot\n",
        1,
    ).replace(
        "denom = float(jnp.sum(batch))",
        "denom = float(jnp.sum(batch))  # lint: allow(host-sync)",
    )
    root = make_repo(tmp_path, {"llm_training_tpu/step.py": suppressed})
    result = run_analysis(root, rules=["host-sync"])
    # the reasoned suppression silences its finding; the reasonless one
    # converts into a suppression-reason finding
    assert len(result.suppressed) == 1
    assert [f.rule for f in result.findings] == ["suppression-reason"]
    assert "no reason" in result.findings[0].message


def test_host_sync_bare_names_skip_class_scope(tmp_path):
    # Python scoping: a method's bare `helper(x)` resolves to the module
    # function, never to an unrelated sibling method of the same name
    src = """
    import jax


    def helper(x):
        return x + 1


    class T:
        def helper(self):
            print("never reached via bare-name call")

        def step(self, x):
            return helper(x)

        def compile(self):
            return jax.jit(self.step)
    """
    root = make_repo(tmp_path, {"llm_training_tpu/cls.py": src})
    assert findings_for(root, "host-sync") == []


def test_host_sync_follows_factory_built_steps(tmp_path):
    # the trainer pattern: jax.jit(self._build_step(...)) where the builder
    # returns a closure
    src = """
    import jax


    class Trainer:
        def _build_step(self):
            def train_step(state, batch):
                print("step!", state)
                return state

            return train_step

        def compile(self):
            return jax.jit(self._build_step(), donate_argnums=0)
    """
    root = make_repo(tmp_path, {"llm_training_tpu/tr.py": src})
    found = findings_for(root, "host-sync")
    assert len(found) == 1 and "print(...)" in found[0].message


# ------------------------------------------------- rule: telemetry-prefix


def test_telemetry_prefix_flags_unregistered_names(tmp_path):
    src = """
    def publish(registry, kind):
        registry.gauge("mystery/thing").set(1.0)          # unregistered
        registry.counter(f"mystery/{kind}/hits").inc()    # unregistered f-string
        registry.gauge("decode/ok").set(1.0)              # registered prefix
        registry.gauge("compile_time_s").set(1.0)         # registered key
        registry.gauge(f"flash/{kind}/block_q").set(1.0)  # registered f-head
        registry.timer(kind)                              # dynamic: skipped
    """
    root = make_repo(tmp_path, {"llm_training_tpu/pub.py": src})
    found = findings_for(root, "telemetry-prefix")
    assert sorted(f.line for f in found) == [3, 4], [f.render() for f in found]
    assert all("telemetry.jsonl" in f.message for f in found)


def test_telemetry_prefix_ignores_non_registry_receivers(tmp_path):
    src = """
    def other(widget):
        widget.gauge("whatever/name")  # not a telemetry receiver
    """
    root = make_repo(tmp_path, {"llm_training_tpu/pub.py": src})
    assert findings_for(root, "telemetry-prefix") == []


# ------------------------------------------------- rule: env-doc-drift


def test_env_doc_drift_flags_undocumented_reads(tmp_path):
    src = '''
    import os

    """BENCH_DOCSTRING_ONLY is prose, not a read."""

    KNOB = os.environ.get("BENCH_SECRET_KNOB")
    OK = os.environ.get("BENCH_DOCUMENTED")
    TABLE = {"block_q": "FLASH_SECRET_TILE"}  # dict values count as reads
    '''
    root = make_repo(tmp_path, {"llm_training_tpu/env.py": src})
    found = findings_for(root, "env-doc-drift")
    names = sorted(f.message.split("`")[1] for f in found)
    assert names == ["BENCH_SECRET_KNOB", "FLASH_SECRET_TILE"], [
        f.render() for f in found
    ]


def test_env_doc_drift_ignores_docstring_mentions(tmp_path):
    src = '''
    def f():
        """Reads BENCH_PROSE_ONLY from the environment (doc prose)."""
        return None
    '''
    root = make_repo(tmp_path, {"llm_training_tpu/env.py": src})
    assert findings_for(root, "env-doc-drift") == []


# ------------------------------------------------- logical-axis-literal


_AXIS_FIXTURE = """
    import flax.linen as nn


    def _dense(features, logical_axes, name):
        return nn.Dense(
            features,
            kernel_init=nn.with_logical_partitioning(init, logical_axes),
            name=name,
        )


    class Block(nn.Module):
        def __call__(self, x):
            w = self.param(
                "w",
                nn.with_logical_partitioning(init, ("embd", "mlp")),  # typo
                (4, 4),
            )
            x = nn.with_logical_constraint(x, ("batch", None, "norm"))
            up = _dense(8, ("embed", "mpl"), "up")  # typo via the helper
            scanned = nn.scan(
                Block, metadata_params={nn.PARTITION_NAME: "layrs"},  # typo
            )
            shaped = (None,) * 2 + ("norm",)  # concatenated tuple: known
            return x
"""


def test_logical_axis_literal_flags_typos_in_models(tmp_path):
    root = make_repo(
        tmp_path, {"llm_training_tpu/models/fake/model.py": _AXIS_FIXTURE}
    )
    found = findings_for(root, "logical-axis-literal")
    bad = sorted(f.message.split("'")[1] for f in found)
    assert bad == ["embd", "layrs", "mpl"], [f.render() for f in found]
    for finding in found:
        assert "KNOWN_LOGICAL_AXES" in finding.message


def test_logical_axis_literal_only_scans_models(tmp_path):
    # the same typo outside models/ (e.g. an infer helper building specs
    # dynamically) is the audit's job, not this rule's
    root = make_repo(
        tmp_path, {"llm_training_tpu/infer/helper.py": _AXIS_FIXTURE}
    )
    assert findings_for(root, "logical-axis-literal") == []


def test_logical_axis_literal_unparseable_registry_is_loud(tmp_path):
    root = make_repo(
        tmp_path,
        {
            "llm_training_tpu/parallel/sharding.py": "KNOWN_LOGICAL_AXES = build()\n",
            "llm_training_tpu/models/fake/model.py": _AXIS_FIXTURE,
        },
    )
    found = findings_for(root, "logical-axis-literal")
    assert len(found) == 1 and "unverifiable" in found[0].message


def test_logical_axis_literal_real_models_clean():
    """Every axis literal in the real models/ tree is registered (the
    whole-repo capstone also proves this; this narrow run localizes a
    failure to the rule)."""
    found = findings_for(REPO_ROOT, "logical-axis-literal")
    assert found == [], [f.render() for f in found]


# --------------------------------------------------------------- CLI


def test_cli_json_output_and_exit_codes(tmp_path, capsys):
    root = make_repo(
        tmp_path,
        {"llm_training_tpu/resilience/supervisor.py": "import jax\n"},
    )
    rc = main(["--root", str(root), "--no-baseline", "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert payload["findings"][0]["rule"] == "jax-free-import"
    assert "key" in payload["findings"][0]

    rc = main(["--root", str(root), "--no-baseline", "--rules", "telemetry-prefix"])
    capsys.readouterr()
    assert rc == 0  # the jax import is invisible to the selected rule

    rc = main(["--root", str(root), "--rules", "no-such-rule"])
    assert rc == 2


def test_cli_baseline_workflow(tmp_path, capsys):
    root = make_repo(
        tmp_path,
        {"llm_training_tpu/resilience/supervisor.py": "import jax\n"},
    )
    baseline = root / "config/lint_baseline.json"
    assert main(["--root", str(root)]) == 1  # missing baseline == empty
    assert main(["--root", str(root), "--update-baseline"]) == 0
    assert load_baseline(baseline)  # the finding was recorded
    assert main(["--root", str(root)]) == 0  # grandfathered
    assert main(["--root", str(root), "--no-baseline"]) == 1
    capsys.readouterr()


def test_cli_audit_rejects_lint_scoping(tmp_path, capsys):
    # `--audit` must not silently ignore lint-only scoping — a user who
    # typed `--audit --rules x path/` believes the run was scoped. Returns
    # 2 BEFORE the audit module (and jax) would load.
    root = make_repo(tmp_path, {})
    assert main(["--root", str(root), "--audit", "--rules", "host-sync"]) == 2
    assert main(["--root", str(root), "--audit", "llm_training_tpu"]) == 2
    assert "--families/--meshes" in capsys.readouterr().err


def test_cli_list_rules(capsys):
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in all_rules():
        assert rule.name in out


def test_write_baseline_roundtrip(tmp_path):
    from llm_training_tpu.analysis.engine import Finding

    target = tmp_path / "b.json"
    finding = Finding(rule="r", path="p.py", line=3, message="m")
    write_baseline(target, [finding])
    assert load_baseline(target) == {finding.key}


def test_update_baseline_carries_over_still_firing_entries(tmp_path, capsys):
    """--update-baseline must never un-grandfather debt it didn't fix."""
    root = make_repo(
        tmp_path,
        {"llm_training_tpu/resilience/supervisor.py": "import jax\n"},
    )
    baseline = root / "config/lint_baseline.json"
    assert main(["--root", str(root), "--update-baseline"]) == 0
    old_keys = load_baseline(baseline)
    # add a SECOND violation, then update again: both must be recorded
    (root / "llm_training_tpu/resilience/elastic.py").write_text("import jax\n")
    assert main(["--root", str(root), "--update-baseline"]) == 0
    assert load_baseline(baseline) > old_keys  # superset: old entry kept
    assert main(["--root", str(root)]) == 0
    capsys.readouterr()


def test_parse_errors_from_contract_walk_surface_on_narrow_scans(tmp_path):
    """A syntax-broken jax-free contract file must fail the gate even when
    the scan paths don't include it (the import walk parses on demand)."""
    root = make_repo(
        tmp_path,
        {
            "bench.py": "import jax\ndef broken(:\n",
            "llm_training_tpu/other/__init__.py": "",
        },
    )
    result = run_analysis(root, paths=["llm_training_tpu/other"])
    assert any(f.rule == "parse-error" and f.path == "bench.py" for f in result.findings), [
        f.render() for f in result.findings
    ]


def test_baseline_never_grandfathers_reasonless_suppressions(tmp_path, capsys):
    root = make_repo(
        tmp_path,
        {
            "llm_training_tpu/resilience/supervisor.py": (
                "# lint: allow(jax-free-import)\nimport jax\n"
            )
        },
    )
    assert main(["--root", str(root), "--update-baseline"]) == 0
    # the suppression-reason finding was NOT recorded: the gate still fails
    assert main(["--root", str(root)]) == 1
    capsys.readouterr()


def test_contract_suppressions_work_outside_narrow_scan_paths(tmp_path):
    root = make_repo(
        tmp_path,
        {
            "bench.py": (
                "# lint: allow(jax-free-import): proving suppressions reach "
                "walked-not-scanned files\nimport jax\n"
            ),
            "llm_training_tpu/other/__init__.py": "",
        },
    )
    result = run_analysis(root, paths=["llm_training_tpu/other"], rules=["jax-free-import"])
    assert result.findings == [], [f.render() for f in result.findings]
    assert len(result.suppressed) == 1


def test_suppression_syntax_in_docstrings_is_inert(tmp_path):
    """Only real comments register suppressions — prose quoting the syntax
    (like the rule modules' own docstrings) must not suppress findings."""
    src = '''
    """Suppress with `# lint: allow(jax-free-import): reason` if needed."""
    import jax
    '''
    root = make_repo(tmp_path, {"llm_training_tpu/resilience/supervisor.py": src})
    found = findings_for(root, "jax-free-import")
    assert len(found) == 1, [f.render() for f in found]


def test_suppression_star_and_multi_rule(tmp_path):
    root = make_repo(
        tmp_path,
        {
            "llm_training_tpu/resilience/supervisor.py": (
                "# lint: allow(*): fixture keeps jax on purpose\n"
                "import jax\n"
            )
        },
    )
    result = run_analysis(root, rules=["jax-free-import"])
    assert result.findings == []
    assert len(result.suppressed) == 1
