"""Offline test fixtures: a tiny trained BPE tokenizer + toy datasets."""

from __future__ import annotations

import functools

from datasets import Dataset, DatasetDict

_CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "pack my box with five dozen liquor jugs",
    "how vexingly quick daft zebras jump",
    "sphinx of black quartz judge my vow",
    "the five boxing wizards jump quickly",
    "hello world how are you today my friend",
    "training language models on tensor processing units",
    "sequence packing avoids cross contamination between documents",
]


@functools.lru_cache(maxsize=1)
def tiny_tokenizer():
    from tokenizers import Tokenizer, models, pre_tokenizers, trainers
    from transformers import PreTrainedTokenizerFast

    tok = Tokenizer(models.BPE(unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    trainer = trainers.BpeTrainer(
        special_tokens=[
            "<unk>", "<s>", "</s>", "<pad>",
            "<|im_start|>", "<|im_end|>",
            "<|user|>", "<|assistant|>", "<|system|>", "<|end|>",
        ],
        vocab_size=400,
    )
    tok.train_from_iterator(_CORPUS, trainer)
    return PreTrainedTokenizerFast(
        tokenizer_object=tok,
        bos_token="<s>",
        eos_token="</s>",
        pad_token="<pad>",
        unk_token="<unk>",
    )


def text_dataset(n_per_source: int = 12) -> DatasetDict:
    rows = {"text": [], "source": []}
    for source in ("wiki", "code"):
        for i in range(n_per_source):
            rows["text"].append(_CORPUS[i % len(_CORPUS)] + f" sample {i}")
            rows["source"].append(source)
    rows["text"].append("")  # empty doc must be dropped
    rows["source"].append("wiki")
    return DatasetDict(train=Dataset.from_dict(rows))


def chat_dataset(n: int = 12) -> DatasetDict:
    rows = {"messages": []}
    for i in range(n):
        rows["messages"].append(
            [
                {"role": "user", "content": _CORPUS[i % len(_CORPUS)]},
                {"role": "assistant", "content": _CORPUS[(i + 1) % len(_CORPUS)]},
            ]
        )
    return DatasetDict(train=Dataset.from_dict(rows))


def preference_dataset(n: int = 10) -> DatasetDict:
    rows = {"prompt": [], "chosen": [], "rejected": []}
    for i in range(n):
        rows["prompt"].append(_CORPUS[i % len(_CORPUS)])
        rows["chosen"].append(_CORPUS[(i + 1) % len(_CORPUS)])
        rows["rejected"].append(_CORPUS[(i + 2) % len(_CORPUS)])
    return DatasetDict(train=Dataset.from_dict(rows))
