"""Segment-boundary state reset for the recurrent families (VERDICT r3 #6).

With `segment_state_reset=True`, a document packed after another must see
EXACTLY the hidden states it would see alone: the DeltaNet fast-weight /
Mamba-2 SSD state resets at the boundary (attention already segment-masks).
Default (False) keeps HF parity, where state leaks across packed documents.

The boundary is placed INSIDE a recurrence chunk, so the in-chunk masking
paths (triangular corrections, decay matrices) are exercised, not just the
cross-chunk carry.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def _models(family, reset):
    if family == "qwen3_next":
        from llm_training_tpu.models.qwen3_next import Qwen3Next, Qwen3NextConfig
        from tests.test_qwen3_next import TINY

        cfg = Qwen3NextConfig(
            **TINY, moe_impl="dense", delta_chunk_size=16,
            segment_state_reset=reset,
        )
        return Qwen3Next(cfg), cfg
    from llm_training_tpu.models.bamba import Bamba, BambaConfig
    from tests.test_bamba import TINY

    cfg = BambaConfig(**TINY, segment_state_reset=reset)
    return Bamba(cfg), cfg


def _run(model, params, ids, seg, pos):
    out = model.apply(
        params, jnp.asarray(ids), segment_ids=jnp.asarray(seg),
        position_ids=jnp.asarray(pos),
    )
    return np.asarray(out.logits, np.float32)


@pytest.mark.parametrize("family", ["qwen3_next", "bamba"])
def test_packed_matches_separate_docs(family):
    # 27 + 37 tokens: the boundary falls mid-chunk (chunk 16/8), and doc 2
    # spans multiple chunks
    l1, l2 = 27, 37
    rng = np.random.default_rng(0)
    doc1 = rng.integers(1, 128, (1, l1))
    doc2 = rng.integers(1, 128, (1, l2))
    packed_ids = np.concatenate([doc1, doc2], axis=1)
    packed_seg = np.concatenate(
        [np.ones((1, l1), np.int32), np.full((1, l2), 2, np.int32)], axis=1
    )
    packed_pos = np.concatenate(
        [np.arange(l1)[None], np.arange(l2)[None]], axis=1
    )

    model, cfg = _models(family, reset=True)
    params = model.init(jax.random.key(0), jnp.asarray(packed_ids))

    packed = _run(model, params, packed_ids, packed_seg, packed_pos)
    solo = _run(
        model, params, doc2, np.ones((1, l2), np.int32), np.arange(l2)[None]
    )
    np.testing.assert_allclose(
        packed[:, l1:], solo, rtol=2e-5, atol=2e-5,
        err_msg="doc 2 logits differ between packed and standalone runs",
    )

    # and doc 1 must be unaffected by what follows it (causality sanity)
    solo1 = _run(
        model, params, doc1, np.ones((1, l1), np.int32), np.arange(l1)[None]
    )
    np.testing.assert_allclose(packed[:, :l1], solo1, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("family", ["qwen3_next", "bamba"])
def test_default_keeps_hf_leak_parity(family):
    """Without the flag, the recurrent state leaks across documents (HF
    parity) — the packed doc-2 logits must NOT match the standalone run."""
    l1, l2 = 27, 37
    rng = np.random.default_rng(1)
    doc1 = rng.integers(1, 128, (1, l1))
    doc2 = rng.integers(1, 128, (1, l2))
    packed_ids = np.concatenate([doc1, doc2], axis=1)
    packed_seg = np.concatenate(
        [np.ones((1, l1), np.int32), np.full((1, l2), 2, np.int32)], axis=1
    )
    packed_pos = np.concatenate(
        [np.arange(l1)[None], np.arange(l2)[None]], axis=1
    )

    model, cfg = _models(family, reset=False)
    params = model.init(jax.random.key(0), jnp.asarray(packed_ids))
    packed = _run(model, params, packed_ids, packed_seg, packed_pos)
    solo = _run(
        model, params, doc2, np.ones((1, l2), np.int32), np.arange(l2)[None]
    )
    assert np.max(np.abs(packed[:, l1:] - solo)) > 1e-4
