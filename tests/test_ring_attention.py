"""Ring attention (context parallelism) vs single-device attention.

Runs on the virtual 8-device CPU mesh (conftest). The invariant: attention
over the full sequence must be bit-for-bit reproduced (up to fp tolerance)
when the sequence is sharded over the ring — forward AND gradients, with
packed segment ids crossing chunk boundaries. The reference has no context
parallelism (SURVEY.md §2.8), so this subsystem is validated purely against
our own single-device path.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # every case spins the 8-way CPU mesh
from jax.sharding import Mesh, PartitionSpec as P

from llm_training_tpu.ops.attention import dot_product_attention
from llm_training_tpu.parallel.ring_attention import ring_attention


def _ring_mesh(n=4):
    return Mesh(np.asarray(jax.devices()[:n]).reshape(1, 1, 1, 1, n),
                ("data", "fsdp", "expert", "tensor", "sequence"))


def _shard_mapped_ring(mesh, **kw):
    spec = P(None, "sequence", None, None)
    seg_spec = P(None, "sequence")
    return jax.shard_map(
        functools.partial(ring_attention, axis_name="sequence", **kw),
        mesh=mesh,
        in_specs=(spec, spec, spec, seg_spec),
        out_specs=spec,
        check_vma=False,
    )


def _data(rng, batch=2, seq=64, hq=4, hkv=2, d=16):
    q = jnp.asarray(rng.standard_normal((batch, seq, hq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((batch, seq, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((batch, seq, hkv, d)), jnp.float32)
    # segments crossing chunk boundaries + trailing padding
    seg = np.ones((batch, seq), np.int32)
    seg[:, seq // 3:] = 2
    seg[:, 3 * seq // 4:] = 3
    seg[:, -5:] = 0
    return q, k, v, jnp.asarray(seg)


def test_ring_forward_matches_single_device():
    rng = np.random.default_rng(0)
    q, k, v, seg = _data(rng)
    mesh = _ring_mesh(4)
    expected = dot_product_attention(q, k, v, segment_ids=seg, impl="xla")
    got = _shard_mapped_ring(mesh)(q, k, v, seg)
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_ring_forward_eight_way():
    rng = np.random.default_rng(1)
    q, k, v, seg = _data(rng, seq=80)
    mesh = _ring_mesh(8)
    expected = dot_product_attention(q, k, v, segment_ids=seg, impl="xla")
    got = _shard_mapped_ring(mesh)(q, k, v, seg)
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_ring_gradients_match_single_device():
    rng = np.random.default_rng(2)
    q, k, v, seg = _data(rng)
    mesh = _ring_mesh(4)
    cot = jnp.asarray(rng.standard_normal(q.shape), jnp.float32)

    ring = _shard_mapped_ring(mesh)
    g_ring = jax.grad(lambda q, k, v: (ring(q, k, v, seg) * cot).sum(), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: (dot_product_attention(q, k, v, segment_ids=seg, impl="xla") * cot).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5, err_msg=f"d{name}")


def test_ring_soft_cap():
    rng = np.random.default_rng(3)
    q, k, v, seg = _data(rng, hq=2, hkv=2)
    mesh = _ring_mesh(4)
    expected = dot_product_attention(
        q, k, v, segment_ids=seg, logits_soft_cap=15.0, impl="xla"
    )
    got = _shard_mapped_ring(mesh, logits_soft_cap=15.0)(q, k, v, seg)
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_ring_pallas_chunks_match():
    """Ring with the pallas per-chunk kernels (interpret mode): chunk sizes
    lane-aligned so the kernel path is exercised end to end."""
    rng = np.random.default_rng(4)
    q, k, v, _ = _data(rng, batch=1, seq=512, hq=2, hkv=1, d=128)
    seg = np.ones((1, 512), np.int32)
    seg[:, 300:] = 2
    seg = jnp.asarray(seg)
    mesh = _ring_mesh(4)
    expected = dot_product_attention(q, k, v, segment_ids=seg, impl="xla")
    got = _shard_mapped_ring(mesh, impl="pallas")(q, k, v, seg)
    np.testing.assert_allclose(got, expected, rtol=2e-3, atol=2e-3)


def test_ring_pallas_non_block_multiple_chunks():
    """Regression: chunk 640 (a 128-multiple but not a 512-block multiple)
    must pick a dividing block, not silently truncate the kernel grid."""
    rng = np.random.default_rng(7)
    q, k, v, _ = _data(rng, batch=1, seq=2560, hq=2, hkv=2, d=128)
    seg = jnp.ones((1, 2560), jnp.int32)
    mesh = _ring_mesh(4)
    expected = dot_product_attention(q, k, v, segment_ids=seg, impl="xla")
    got = _shard_mapped_ring(mesh, impl="pallas")(q, k, v, seg)
    assert not np.any(np.isnan(np.asarray(got)))
    np.testing.assert_allclose(got, expected, rtol=2e-3, atol=2e-3)


def test_ring_sliding_window():
    """Window smaller than a chunk AND window spanning several chunks, both
    with packed segments: the ring must cut compute/steps yet match the
    global windowed attention exactly."""
    rng = np.random.default_rng(8)
    q, k, v, seg = _data(rng)
    mesh = _ring_mesh(4)
    for window in (7, 16, 37):
        expected = dot_product_attention(
            q, k, v, segment_ids=seg, sliding_window=window, impl="xla"
        )
        got = _shard_mapped_ring(mesh, sliding_window=window)(q, k, v, seg)
        np.testing.assert_allclose(
            got, expected, rtol=1e-4, atol=1e-5, err_msg=f"window={window}"
        )


def test_ring_sliding_window_gradients():
    rng = np.random.default_rng(9)
    q, k, v, seg = _data(rng)
    mesh = _ring_mesh(4)
    cot = jnp.asarray(rng.standard_normal(q.shape), jnp.float32)
    ring = _shard_mapped_ring(mesh, sliding_window=20)
    g_ring = jax.grad(
        lambda q, k, v: (ring(q, k, v, seg) * cot).sum(), argnums=(0, 1, 2)
    )(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: (
            dot_product_attention(
                q, k, v, segment_ids=seg, sliding_window=20, impl="xla"
            ) * cot
        ).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5, err_msg=f"d{name}")


def _shard_mapped_ring_sinks(mesh, **kw):
    spec = P(None, "sequence", None, None)
    seg_spec = P(None, "sequence")
    def run(q, k, v, seg, sinks):
        return ring_attention(
            q, k, v, seg, axis_name="sequence", sinks=sinks, **kw
        )

    return jax.shard_map(
        run,
        mesh=mesh,
        in_specs=(spec, spec, spec, seg_spec, P(None)),
        out_specs=spec,
        check_vma=False,
    )


def test_ring_sinks():
    """gpt-oss attention sinks: the owner chunk seeds the combine, so the
    sink mass joins every row's denominator exactly once across the ring."""
    rng = np.random.default_rng(10)
    q, k, v, seg = _data(rng)
    sinks = jnp.asarray(rng.standard_normal(q.shape[2]), jnp.float32)
    mesh = _ring_mesh(4)
    expected = dot_product_attention(
        q, k, v, segment_ids=seg, sinks=sinks, impl="xla"
    )
    got = _shard_mapped_ring_sinks(mesh)(q, k, v, seg, sinks)
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_ring_sinks_gradients():
    """d_sinks flows through the seeded combine; the shard_map transpose
    sums the per-device contributions over the sequence axis."""
    rng = np.random.default_rng(11)
    q, k, v, seg = _data(rng)
    sinks = jnp.asarray(rng.standard_normal(q.shape[2]), jnp.float32)
    mesh = _ring_mesh(4)
    cot = jnp.asarray(rng.standard_normal(q.shape), jnp.float32)
    ring = _shard_mapped_ring_sinks(mesh)
    g_ring = jax.grad(
        lambda q, k, v, s: (ring(q, k, v, seg, s) * cot).sum(),
        argnums=(0, 1, 2, 3),
    )(q, k, v, sinks)
    g_ref = jax.grad(
        lambda q, k, v, s: (
            dot_product_attention(q, k, v, segment_ids=seg, sinks=s, impl="xla")
            * cot
        ).sum(),
        argnums=(0, 1, 2, 3),
    )(q, k, v, sinks)
    for a, b, name in zip(g_ring, g_ref, ("dq", "dk", "dv", "dsinks")):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5, err_msg=name)


def test_ring_window_and_sinks_compose():
    rng = np.random.default_rng(12)
    q, k, v, seg = _data(rng)
    sinks = jnp.asarray(rng.standard_normal(q.shape[2]), jnp.float32)
    mesh = _ring_mesh(4)
    expected = dot_product_attention(
        q, k, v, segment_ids=seg, sliding_window=20, sinks=sinks, impl="xla"
    )
    got = _shard_mapped_ring_sinks(mesh, sliding_window=20)(q, k, v, seg, sinks)
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_ring_inside_jit_under_mesh():
    """The model-integration shape: ring inside jit with sharded inputs."""
    rng = np.random.default_rng(5)
    q, k, v, seg = _data(rng)
    mesh = _ring_mesh(4)
    ring = _shard_mapped_ring(mesh)
    with mesh:
        got = jax.jit(ring)(q, k, v, seg)
    expected = dot_product_attention(q, k, v, segment_ids=seg, impl="xla")
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_model_level_ring_matches_non_ring():
    """Llama forward/backward with ring_attention=True on a sequence-sharded
    mesh equals the plain GSPMD run."""
    import flax.linen as nn

    from llm_training_tpu.models.llama import Llama, LlamaConfig
    from llm_training_tpu.trainer.trainer import LOGICAL_AXIS_RULES

    base = dict(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, attention_impl="xla", param_dtype="float32",
        compute_dtype="float32",
    )
    rng = np.random.default_rng(6)
    ids = jnp.asarray(rng.integers(0, 128, (2, 64)), jnp.int32)
    seg = jnp.ones((2, 64), jnp.int32)

    model_ref = Llama(LlamaConfig(**base))
    params = model_ref.init(jax.random.key(0), ids)

    def loss(model, params):
        out = model.apply(params, ids, segment_ids=seg)
        return (out.logits.astype(jnp.float32) ** 2).mean()

    l_ref, g_ref = jax.value_and_grad(lambda p: loss(model_ref, p))(params)

    model_ring = Llama(LlamaConfig(**base, ring_attention=True))
    mesh = _ring_mesh(4)
    with mesh, nn.logical_axis_rules(LOGICAL_AXIS_RULES):
        l_ring, g_ring = jax.jit(
            jax.value_and_grad(lambda p: loss(model_ring, p))
        )(params)
    np.testing.assert_allclose(l_ring, l_ref, rtol=1e-5)
    flat_ref = jax.tree.leaves(g_ref)
    flat_ring = jax.tree.leaves(g_ring)
    for a, b in zip(flat_ring, flat_ref):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-5)
