"""Fleet observability plane (docs/observability.md#fleet): replica
discovery cards + their arm/stop/SIGKILL lifecycle, the multi-target
aggregator (rollups, verdict, stale-card handling, SLO feed), the
federation/`/fleetz` surfaces, the `fleet` CLI exit-2 contracts, and
`report`'s `fleet` block.

Everything here is jax-free host code (fleet.py carries a graftlint
jax-free contract — the aggregator is a scrape *parent* like the
loadgen), so these tests cost milliseconds. Real-replica scrapes run
against in-process `MetricsExporter`s on ephemeral localhost ports.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from llm_training_tpu.telemetry.exporter import (
    MetricsExporter,
    parse_prometheus_text,
)
from llm_training_tpu.telemetry.fleet import (
    FleetAggregator,
    discover_replicas,
    fleet_main,
    parse_targets,
    remove_replica_card,
    resolve_fleet_dir,
    resolve_scrape_interval,
    write_replica_card,
)
from llm_training_tpu.telemetry.registry import TelemetryRegistry


def _dead_pid() -> int:
    """A pid that WAS a real process and is now gone — the SIGKILL/OOM
    card signature (`os.kill(pid, 0)` raises ProcessLookupError)."""
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait()
    return proc.pid


@pytest.fixture
def serve_exporter():
    """An armed serve-shaped exporter on an ephemeral port, stopped after."""
    registry = TelemetryRegistry()
    registry.counter("exporter/scrapes")  # a counter for sum rollups
    registry.gauge("serve/queue_depth").set(3.0)
    registry.gauge("serve/running").set(2.0)
    registry.gauge("serve/requests_completed").set(5.0)
    registry.gauge("serve/ttft_p99_ms").set(40.0)
    exporter = MetricsExporter(0, registry=registry, role="serve")
    assert exporter.start()
    try:
        yield exporter
    finally:
        exporter.stop()


# ------------------------------------------------------- discovery cards


def test_card_lifecycle_arm_and_clean_stop(tmp_path):
    card = write_replica_card(tmp_path / "fleet", 9100, role="serve")
    assert card is not None and card.name == f"replica-{os.getpid()}.json"
    loaded = json.loads(card.read_text())
    assert loaded["schema"] == 1
    assert loaded["replica_id"] == f"serve-0-{os.getpid()}"
    assert loaded["pid"] == os.getpid() and loaded["port"] == 9100
    # the wall+mono anchor pair rides the card like the trace anchor
    assert loaded["start_wall_s"] > 0 and loaded["start_mono_s"] >= 0
    replicas = discover_replicas(tmp_path / "fleet")
    assert len(replicas) == 1 and replicas[0]["stale"] is False
    remove_replica_card(card)  # clean stop
    assert not card.exists()
    assert discover_replicas(tmp_path / "fleet") == []
    remove_replica_card(card)  # idempotent
    remove_replica_card(None)  # never armed


def test_card_tags_supervisor_attempt(tmp_path, monkeypatch):
    """A supervised relaunch re-registers under a fresh attempt-tagged id
    (the dead predecessor's id must not be reused)."""
    monkeypatch.setenv("LLMT_SUPERVISOR_ATTEMPT", "2")
    card = write_replica_card(tmp_path, 9100, role="train")
    loaded = json.loads(card.read_text())
    assert loaded["replica_id"] == f"train-2-{os.getpid()}"
    assert loaded["attempt"] == 2
    monkeypatch.setenv("LLMT_SUPERVISOR_ATTEMPT", "banana")
    assert json.loads(write_replica_card(tmp_path, 9100).read_text())[
        "attempt"
    ] == 0  # malformed degrades, never raises


def test_card_write_failure_degrades(tmp_path):
    blocker = tmp_path / "not-a-dir"
    blocker.write_text("")
    assert write_replica_card(blocker / "fleet", 9100) is None


def test_discover_flags_dead_pid_stale(tmp_path):
    """A SIGKILLed replica never removed its card: flagged stale."""
    card = write_replica_card(tmp_path, 9100, role="serve")
    doctored = json.loads(card.read_text())
    doctored["pid"] = _dead_pid()
    card.write_text(json.dumps(doctored))
    replicas = discover_replicas(tmp_path)
    assert len(replicas) == 1 and replicas[0]["stale"] is True


def test_discover_tolerates_torn_and_junk_cards(tmp_path):
    (tmp_path / "replica-1.json").write_text("{torn mid-wri")
    (tmp_path / "replica-2.json").write_text(json.dumps({"no": "port"}))
    (tmp_path / "replica-3.json").write_text(json.dumps([1, 2]))
    assert discover_replicas(tmp_path) == []
    assert discover_replicas(tmp_path / "absent") == []


def test_exporter_start_stop_drops_and_removes_card(tmp_path, monkeypatch):
    """The integration the whole plane hangs on: arming ANY exporter with
    LLMT_FLEET_DIR set registers the replica; a clean stop deregisters."""
    fleet_dir = tmp_path / "fleet"
    monkeypatch.setenv("LLMT_FLEET_DIR", str(fleet_dir))
    assert resolve_fleet_dir() == fleet_dir
    exporter = MetricsExporter(0, registry=TelemetryRegistry(), role="bench")
    assert exporter.start()
    try:
        replicas = discover_replicas(fleet_dir)
        assert len(replicas) == 1
        assert replicas[0]["port"] == exporter.port
        assert replicas[0]["role"] == "bench"
    finally:
        exporter.stop()
    assert discover_replicas(fleet_dir) == []
    monkeypatch.delenv("LLMT_FLEET_DIR")
    assert resolve_fleet_dir() is None


def test_parse_targets():
    targets = parse_targets("127.0.0.1:9100, :9101,junk,host:nan,")
    assert [(t["host"], t["port"]) for t in targets] == [
        ("127.0.0.1", 9100), ("127.0.0.1", 9101),
    ]
    assert targets[0]["replica_id"] == "target-127.0.0.1:9100"
    assert all(t["static"] and not t["stale"] for t in targets)
    assert parse_targets("") == []


def test_resolve_scrape_interval(monkeypatch):
    assert resolve_scrape_interval() == 2.0
    monkeypatch.setenv("LLMT_FLEET_SCRAPE_S", "0.5")
    assert resolve_scrape_interval() == 0.5
    monkeypatch.setenv("LLMT_FLEET_SCRAPE_S", "banana")
    assert resolve_scrape_interval() == 2.0
    monkeypatch.setenv("LLMT_FLEET_SCRAPE_S", "-1")
    assert resolve_scrape_interval() == 2.0


# ------------------------------------------------------------ aggregator


def test_sweep_green_fleet_and_rollups(serve_exporter, tmp_path, monkeypatch):
    monkeypatch.setenv("LLMT_FLEET_DIR", str(tmp_path))
    card = write_replica_card(tmp_path, serve_exporter.port, role="serve")
    try:
        aggregator = FleetAggregator(fleet_dir=tmp_path)
        snapshot = aggregator.sweep()
        assert snapshot["verdict"] == "green"
        (rid, entry), = snapshot["replicas"].items()
        assert entry["healthy"] and entry["error"] is None
        assert entry["metrics"]["llmt_serve_queue_depth"] == 3.0
        rollup = snapshot["rollup"]
        # serve load gauges sum unsuffixed; every gauge spreads min/mean/max
        assert rollup["llmt_fleet_serve_queue_depth"] == 3.0
        assert rollup["llmt_fleet_serve_queue_depth_max"] == 3.0
        assert rollup["llmt_fleet_replicas"] == 1.0
        assert rollup["llmt_fleet_replicas_healthy"] == 1.0
        assert rollup["llmt_fleet_stale_cards"] == 0.0
        healthy, _ = aggregator.health()
        assert healthy
    finally:
        remove_replica_card(card)


def test_sweep_two_replicas_sums_counters_spreads_gauges(tmp_path):
    """Two serve replicas via static targets (two exporters in ONE process
    share a card path, so the 2-replica discovery leg lives in the fleet
    smoke): counters sum, gauges min/mean/max, serve load keys ALSO sum."""
    exporters = []
    try:
        for completed in (5.0, 7.0):
            registry = TelemetryRegistry()
            registry.gauge("serve/queue_depth").set(completed - 4.0)
            registry.gauge("serve/requests_completed").set(completed)
            exporter = MetricsExporter(0, registry=registry, role="serve")
            assert exporter.start()
            exporters.append(exporter)
        targets = ",".join(f"127.0.0.1:{e.port}" for e in exporters)
        aggregator = FleetAggregator(targets=targets)
        # prime each exporter's scrape counter, then sweep again so the
        # counter-sum rollup sees nonzero values
        snapshot = aggregator.sweep()
        assert snapshot["verdict"] == "green"
        snapshot = aggregator.sweep()
        rollup = snapshot["rollup"]
        assert rollup["llmt_fleet_replicas"] == 2.0
        assert rollup["llmt_fleet_serve_requests_completed"] == 12.0
        assert rollup["llmt_fleet_serve_queue_depth"] == 4.0  # 1 + 3
        assert rollup["llmt_fleet_serve_queue_depth_min"] == 1.0
        assert rollup["llmt_fleet_serve_queue_depth_max"] == 3.0
        assert rollup["llmt_fleet_serve_queue_depth_mean"] == 2.0
        # exporter/scrapes is a `# TYPE ... counter`: sums, no spread
        assert rollup["llmt_fleet_exporter_scrapes"] >= 2.0
        assert "llmt_fleet_exporter_scrapes_mean" not in rollup

        # federation render round-trips the shared strict parser
        body = aggregator.render_metrics()
        federated = parse_prometheus_text(body, labels=True)
        labeled = {k for k in federated if "{replica=" in k}
        assert len(labeled) >= 4  # both replicas' series, labeled
        assert federated["llmt_fleet_serve_requests_completed"] == 12.0
        assert federated["llmt_fleet_sweeps"] == 2.0
        with pytest.raises(ValueError):
            parse_prometheus_text(body)  # labels are opt-in, still strict
    finally:
        for exporter in exporters:
            exporter.stop()


def test_sweep_red_on_unscrapeable_and_unhealthy(serve_exporter):
    dead_port = serve_exporter.port  # live now; dead after stop below
    aggregator = FleetAggregator(
        targets=f"127.0.0.1:{dead_port}", timeout_s=0.5
    )
    assert aggregator.sweep()["verdict"] == "green"
    serve_exporter.stop()
    snapshot = aggregator.sweep()
    assert snapshot["verdict"] == "red"
    assert snapshot["red"] == [f"target-127.0.0.1:{dead_port}"]
    entry = snapshot["replicas"][f"target-127.0.0.1:{dead_port}"]
    assert entry["error"] and not entry["healthy"]
    healthy, _ = aggregator.health()
    assert not healthy
    assert "RED" in aggregator.render_fleetz()


def test_sweep_flags_stale_card_and_never_scrapes_it(tmp_path):
    """The SIGKILL signature: dead pid's card -> red verdict naming the
    stale replica, no scrape attempted (the port may be anyone's now)."""
    card = write_replica_card(tmp_path, 1, role="serve")  # port 1: nobody's
    doctored = json.loads(card.read_text())
    doctored["pid"] = _dead_pid()
    card.write_text(json.dumps(doctored))
    aggregator = FleetAggregator(fleet_dir=tmp_path, timeout_s=0.5)
    snapshot = aggregator.sweep()
    assert snapshot["verdict"] == "red"
    (rid,) = snapshot["stale_cards"]
    assert rid == doctored["replica_id"]
    entry = snapshot["replicas"][rid]
    assert "stale card" in entry["error"]
    assert entry["metrics"] == {}  # never scraped
    assert snapshot["rollup"]["llmt_fleet_stale_cards"] == 1.0
    fleetz = aggregator.render_fleetz()
    assert "STALE CARD" in fleetz and rid in fleetz


def test_sweep_empty_fleet(tmp_path):
    snapshot = FleetAggregator(fleet_dir=tmp_path / "nobody").sweep()
    assert snapshot["verdict"] == "empty" and snapshot["replicas"] == {}
    healthy, _ = FleetAggregator(fleet_dir=tmp_path / "nobody").health()
    assert not healthy  # an empty fleet is not a healthy fleet


def test_sweep_feeds_fleet_slo(serve_exporter):
    class _SpySLO:
        observed = []

        def observe_request(self, ttft_ms=None, tpot_ms=None, ok=True):
            self.observed.append((ttft_ms, tpot_ms, ok))

        def breach_count(self):
            return 0

    slo = _SpySLO()
    aggregator = FleetAggregator(
        targets=f"127.0.0.1:{serve_exporter.port}", slo=slo
    )
    snapshot = aggregator.sweep()
    # one observation per serve replica per sweep: the rolling p99 as the
    # latency sample, the health verdict as ok
    assert slo.observed == [(40.0, None, True)]
    assert snapshot["slo_breaches"] == 0


def test_aggregator_serves_federation_endpoints(serve_exporter, tmp_path):
    aggregator = FleetAggregator(
        targets=f"127.0.0.1:{serve_exporter.port}", interval_s=0.05
    )
    assert aggregator.start(port=0)
    try:
        deadline_sweeps = 50
        while aggregator.sweep_count() < 2 and deadline_sweeps:
            deadline_sweeps -= 1
            time.sleep(0.05)
        base = f"http://127.0.0.1:{aggregator.port}"
        body = urllib.request.urlopen(f"{base}/metrics", timeout=5.0).read()
        federated = parse_prometheus_text(body.decode(), labels=True)
        assert federated["llmt_fleet_replicas"] == 1.0
        fleetz = urllib.request.urlopen(f"{base}/fleetz", timeout=5.0).read()
        assert b"GREEN" in fleetz or b"green" in fleetz
        health = urllib.request.urlopen(f"{base}/healthz", timeout=5.0)
        assert health.status == 200
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            urllib.request.urlopen(f"{base}/nope", timeout=5.0)
        assert excinfo.value.code == 404
    finally:
        aggregator.stop()


# --------------------------------------------------------------- fleet CLI


def test_fleet_main_once_json_and_out(serve_exporter, tmp_path, capsys):
    card = write_replica_card(tmp_path, serve_exporter.port, role="serve")
    out = tmp_path / "fleet.json"
    try:
        rc = fleet_main(
            fleet_dir=str(tmp_path), once=True, as_json=True, out=str(out)
        )
    finally:
        remove_replica_card(card)
    assert rc == 0
    printed = json.loads(capsys.readouterr().out)
    assert printed["verdict"] == "green"
    assert json.loads(out.read_text()) == printed


def test_fleet_main_once_exit_2_names_searched_paths(tmp_path, capsys):
    empty = tmp_path / "empty"
    empty.mkdir()
    assert fleet_main(fleet_dir=str(empty), once=True) == 2
    err = capsys.readouterr().err
    assert f"{empty}/replica-*.json" in err

    absent = tmp_path / "absent"
    assert fleet_main(fleet_dir=str(absent), once=True) == 2
    err = capsys.readouterr().err
    assert f"{absent}/replica-*.json" in err and "(dir absent)" in err


def test_fleet_main_nowhere_to_look_exit_2(monkeypatch, capsys):
    monkeypatch.delenv("LLMT_FLEET_DIR", raising=False)
    assert fleet_main() == 2
    assert "LLMT_FLEET_DIR" in capsys.readouterr().err


# ---------------------------------------------------- report fleet block


def test_report_fleet_block_and_section(tmp_path):
    """`fleet --out <run_dir>/fleet.json` surfaces in report; the shape
    CI reads (tests/test_trace.py pins the null-when-absent case)."""
    from llm_training_tpu.telemetry.report import (
        REPORT_SCHEMA_VERSION,
        render_report,
        render_report_data,
    )

    run_dir = tmp_path / "run"
    run_dir.mkdir()
    (run_dir / "metrics.jsonl").write_text(
        json.dumps({"step": 1, "loss": 1.0}) + "\n"
    )
    (run_dir / "fleet.json").write_text(json.dumps({
        "verdict": "red",
        "sweeps": 9,
        "replicas": {
            "serve-0-11": {"role": "serve", "healthy": True, "stale": False,
                           "error": None, "attempt": 0},
            "serve-1-22": {"role": "serve", "healthy": False, "stale": True,
                           "error": "stale card", "attempt": 1},
        },
        "red": [],
        "stale_cards": ["serve-1-22"],
        "rollup": {"llmt_fleet_serve_requests_completed": 4.0,
                   "llmt_fleet_replicas": 2.0},
    }))
    doc = render_report_data(run_dir)
    assert doc["schema_version"] == REPORT_SCHEMA_VERSION == 1
    fleet = doc["fleet"]
    assert fleet["verdict"] == "red" and fleet["sweeps"] == 9
    assert fleet["stale_cards"] == ["serve-1-22"]
    assert fleet["replicas"]["serve-1-22"]["stale"] is True
    text = render_report(run_dir)
    assert "== Fleet ==" in text and "serve-1-22" in text

    (run_dir / "fleet.json").write_text("{torn")
    assert "error" in render_report_data(run_dir)["fleet"]
