"""Resilience subsystem: retry policy, chaos harness, graceful shutdown,
hang watchdog, durable checkpointing, and the kill-and-resume contract
(docs/resilience.md)."""

import os
import signal
import threading
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import yaml

from llm_training_tpu.resilience import (
    RESUMABLE_EXIT_CODE,
    ChaosConfig,
    ChaosError,
    GracefulShutdown,
    HangWatchdog,
    PreemptionInterrupt,
    ResilienceConfig,
    RetryPolicy,
    config_from_env,
    install_chaos,
    is_transient,
    retry_call,
    uninstall_chaos,
)
from llm_training_tpu.telemetry import GoodputLedger, TelemetryRegistry
from llm_training_tpu.trainer.state import TrainState


@pytest.fixture(autouse=True)
def _no_leaked_chaos():
    yield
    uninstall_chaos()


# ---------------------------------------------------------------- retry


def test_retry_call_backoff_counter_and_success():
    registry = TelemetryRegistry()
    sleeps = []
    calls = []

    def flaky(attempt):
        calls.append(attempt)
        if attempt < 2:
            raise OSError("transient")
        return "ok"

    result = retry_call(
        flaky,
        RetryPolicy(max_retries=3, backoff_base_s=0.5, backoff_factor=2.0),
        counter=registry.counter("data/retries"),
        sleep=sleeps.append,
    )
    assert result == "ok"
    assert calls == [0, 1, 2]
    assert sleeps == [0.5, 1.0]  # exponential
    assert registry.counter("data/retries").value == 2


def test_retry_call_exhausts_and_reraises():
    with pytest.raises(OSError):
        retry_call(
            lambda attempt: (_ for _ in ()).throw(OSError("always")),
            RetryPolicy(max_retries=2, backoff_base_s=0),
            sleep=lambda s: None,
        )


def test_retry_call_non_transient_fails_fast():
    calls = []

    def broken(attempt):
        calls.append(attempt)
        raise ValueError("bug, not weather")

    with pytest.raises(ValueError):
        retry_call(broken, RetryPolicy(max_retries=5, backoff_base_s=0))
    assert calls == [0]  # no retries for programming errors


def test_backoff_is_capped():
    policy = RetryPolicy(max_retries=10, backoff_base_s=1.0, backoff_max_s=4.0)
    assert policy.delay_s(0) == 1.0
    assert policy.delay_s(5) == 4.0


def test_transient_classification():
    assert is_transient(OSError())
    assert is_transient(ConnectionError())
    assert is_transient(TimeoutError())
    assert is_transient(ChaosError("injected"))
    assert not is_transient(ValueError())


# ---------------------------------------------------------------- chaos


def test_chaos_deterministic_trigger_fires_exactly_once():
    registry = TelemetryRegistry()
    chaos = install_chaos(ChaosConfig(data_error_steps=(2,)), registry=registry)
    chaos.maybe_raise("data", step=1)  # no trigger
    with pytest.raises(ChaosError):
        chaos.maybe_raise("data", step=2)
    chaos.maybe_raise("data", step=2)  # the retry path succeeds
    assert registry.counter("resilience/chaos_injections").value == 1


def test_chaos_checkpoint_site_and_unknown_site():
    chaos = install_chaos(ChaosConfig(checkpoint_error_steps=(5,)))
    with pytest.raises(ChaosError):
        chaos.maybe_raise("checkpoint_save", step=5)
    chaos.maybe_raise("data", step=5)  # other site untouched
    with pytest.raises(ValueError):
        chaos.maybe_raise("nope", step=1)


def test_chaos_install_requires_active_trigger():
    assert install_chaos(ChaosConfig()) is None  # all-default = off
    assert install_chaos(ChaosConfig(sigterm_step=3)) is not None
    uninstall_chaos()
    from llm_training_tpu.resilience import get_chaos

    assert get_chaos() is None


def test_chaos_config_from_env(monkeypatch):
    monkeypatch.setenv("LLMT_CHAOS_DATA_ERROR_STEPS", "3,5")
    monkeypatch.setenv("LLMT_CHAOS_SIGTERM_STEP", "7")
    monkeypatch.setenv("LLMT_CHAOS_CHECKPOINT_ERROR_PROB", "0.25")
    config = config_from_env(ChaosConfig(seed=9))
    assert config.data_error_steps == (3, 5)
    assert config.sigterm_step == 7
    assert config.checkpoint_error_prob == 0.25
    assert config.seed == 9  # untouched fields keep the base values


# ---------------------------------------------------------------- shutdown


def test_graceful_shutdown_real_sigterm_sets_flag():
    shutdown = GracefulShutdown().install()
    try:
        assert shutdown.installed
        assert not shutdown.requested
        os.kill(os.getpid(), signal.SIGTERM)
        # CPython runs the Python-level handler at the next bytecode boundary
        deadline = time.monotonic() + 5.0
        while not shutdown.requested and time.monotonic() < deadline:
            time.sleep(0.01)
        assert shutdown.requested
        assert shutdown.reason == "SIGTERM"
        assert shutdown.should_stop(step=1)
    finally:
        shutdown.uninstall()


def test_graceful_shutdown_restores_previous_handlers():
    before = signal.getsignal(signal.SIGTERM)
    shutdown = GracefulShutdown().install()
    assert signal.getsignal(signal.SIGTERM) is not before
    shutdown.uninstall()
    assert signal.getsignal(signal.SIGTERM) is before


def test_graceful_shutdown_programmatic_request():
    shutdown = GracefulShutdown()  # no handlers installed
    assert not shutdown.should_stop(step=0)
    shutdown.request()
    assert shutdown.should_stop(step=0)


# ---------------------------------------------------------------- watchdog


def test_watchdog_dumps_stacks_on_stall(tmp_path):
    registry = TelemetryRegistry()
    ledger = GoodputLedger()
    ledger.start()
    parked = threading.Event()

    def park():
        parked.wait(timeout=30)

    thread = threading.Thread(target=park, name="parked-worker", daemon=True)
    thread.start()
    watchdog = HangWatchdog(
        timeout_s=0.2, run_dir=tmp_path, ledger=ledger, registry=registry
    ).start()
    try:
        with ledger.measure("data_wait"):  # the phase the "hang" is inside
            deadline = time.monotonic() + 10.0
            while not watchdog.dump_paths and time.monotonic() < deadline:
                time.sleep(0.05)
    finally:
        watchdog.stop()
        parked.set()
        thread.join()
    assert watchdog.dump_paths, "no hang dump produced under a forced stall"
    content = watchdog.dump_paths[0].read_text()
    # the header names the PRIMARY beat source (train_loop for a fit,
    # engine_step for the serving tier)
    assert "no train_loop heartbeat" in content
    assert "goodput phase open at stall: data_wait" in content
    assert "parked-worker" in content  # every thread's stack is in the dump
    assert "MainThread" in content
    assert registry.counter("resilience/watchdog_dumps").value == 1


def test_watchdog_beat_rearms_and_prevents_dump(tmp_path):
    watchdog = HangWatchdog(timeout_s=0.5, run_dir=tmp_path).start()
    try:
        for _ in range(6):  # keep beating well past the timeout window
            watchdog.beat("train_loop", step=1)
            time.sleep(0.1)
        assert not watchdog.dump_paths
    finally:
        watchdog.stop()


def test_watchdog_dumps_once_per_stall(tmp_path):
    watchdog = HangWatchdog(timeout_s=0.1, run_dir=tmp_path).start()
    try:
        deadline = time.monotonic() + 10.0
        while not watchdog.dump_paths and time.monotonic() < deadline:
            time.sleep(0.05)
        time.sleep(0.4)  # several timeout windows later: still one dump
        assert len(watchdog.dump_paths) == 1
        watchdog.beat("train_loop")  # progress re-arms
        deadline = time.monotonic() + 10.0
        while len(watchdog.dump_paths) < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert len(watchdog.dump_paths) == 2
    finally:
        watchdog.stop()


def test_watchdog_validates_config():
    with pytest.raises(ValueError):
        HangWatchdog(timeout_s=0)
    with pytest.raises(ValueError):
        HangWatchdog(timeout_s=1, action="explode")


def test_ledger_current_phase_tracks_nesting():
    ledger = GoodputLedger()
    ledger.start()
    assert ledger.current_phase is None
    with ledger.measure("data_wait"):
        assert ledger.current_phase == "data_wait"
        with ledger.measure("step_compute"):
            assert ledger.current_phase == "step_compute"
        assert ledger.current_phase == "data_wait"
    assert ledger.current_phase is None


# ---------------------------------------------------------------- checkpointer


def _tiny_state(value: float) -> TrainState:
    return TrainState.create(
        params={"w": jnp.full((4,), value, jnp.float32)},
        opt_state={"m": jnp.zeros((4,), jnp.float32)},
        rng=jax.random.key(0),
    )


def _restore_args(state: TrainState):
    abstract = jax.eval_shape(lambda: state)
    shardings = jax.tree.map(lambda leaf: None, abstract)
    return abstract, shardings


def _checkpointer(tmp_path, **overrides):
    from llm_training_tpu.trainer.checkpoint import CheckpointConfig, Checkpointer

    kwargs = dict(dirpath=str(tmp_path), async_save=False, retry_backoff_s=0.0)
    kwargs.update(overrides)
    return Checkpointer(CheckpointConfig(**kwargs))


def test_checkpointer_save_honors_force(tmp_path):
    ckpt = _checkpointer(tmp_path)
    ckpt.save(1, _tiny_state(1.0))
    ckpt.save(1, _tiny_state(2.0))  # duplicate step without force: skipped
    state, shardings = _restore_args(_tiny_state(0.0))
    restored, _ = ckpt.maybe_restore(state, shardings)
    np.testing.assert_array_equal(np.asarray(restored.params["w"]), 1.0)
    # force=True overwrites the stale entry (the emergency-save contract)
    ckpt.save(1, _tiny_state(3.0), force=True)
    restored, meta = ckpt.maybe_restore(state, shardings)
    np.testing.assert_array_equal(np.asarray(restored.params["w"]), 3.0)
    assert meta["step"] == 1
    ckpt.close()


def test_checkpointer_retries_transient_save_error(tmp_path):
    registry = TelemetryRegistry()
    from llm_training_tpu.telemetry import set_registry

    previous = set_registry(registry)
    try:
        install_chaos(ChaosConfig(checkpoint_error_steps=(1,)), registry=registry)
        ckpt = _checkpointer(tmp_path, save_retries=2)
        ckpt.save(1, _tiny_state(1.0))  # first attempt injected, retry lands
        assert registry.counter("checkpoint/retries").value == 1
        assert registry.counter("resilience/chaos_injections").value == 1
        assert ckpt.latest_step() == 1
        ckpt.close()
    finally:
        set_registry(previous)


def test_checkpointer_save_fails_after_retries_exhausted(tmp_path):
    install_chaos(ChaosConfig(checkpoint_error_prob=1.0))  # every attempt fails
    ckpt = _checkpointer(tmp_path, save_retries=2)
    with pytest.raises(ChaosError):
        ckpt.save(1, _tiny_state(1.0))


def test_restore_falls_back_to_previous_step_on_corrupt_latest(tmp_path):
    registry = TelemetryRegistry()
    from llm_training_tpu.telemetry import set_registry

    previous = set_registry(registry)
    try:
        ckpt = _checkpointer(tmp_path)
        ckpt.save(1, _tiny_state(1.0))
        ckpt.save(2, _tiny_state(2.0))
        # simulate a preemption mid-commit: the newest step dir loses its
        # state payload
        import shutil

        state_dir = next((tmp_path / "2").glob("state*"))
        shutil.rmtree(state_dir)
        state, shardings = _restore_args(_tiny_state(0.0))
        # an EXPLICIT step request must not silently fall back (checked
        # first: the implicit restore below deletes the corrupt step)
        with pytest.raises(Exception):
            ckpt.maybe_restore(state, shardings, step=2)
        restored, meta = ckpt.maybe_restore(state, shardings)
        np.testing.assert_array_equal(np.asarray(restored.params["w"]), 1.0)
        assert meta["step"] == 1
        assert registry.counter("resilience/restore_fallbacks").value >= 1
        # the unrestorable step is dropped, so the resumed run's next save
        # at step 2 is not skipped by the already-exists early return —
        # the corruption gets repaired instead of poisoning the dir forever
        assert 2 not in ckpt.manager.all_steps()
        ckpt.save(2, _tiny_state(5.0))
        restored, meta = ckpt.maybe_restore(state, shardings)
        np.testing.assert_array_equal(np.asarray(restored.params["w"]), 5.0)
        assert meta["step"] == 2
        ckpt.close()
    finally:
        set_registry(previous)


def test_restore_repair_false_keeps_corrupt_step(tmp_path):
    """Read-only callers (the validate CLI) must not mutate the checkpoint
    dir: fallback still works, but the corrupt step stays in place."""
    ckpt = _checkpointer(tmp_path)
    ckpt.save(1, _tiny_state(1.0))
    ckpt.save(2, _tiny_state(2.0))
    import shutil

    shutil.rmtree(next((tmp_path / "2").glob("state*")))
    state, shardings = _restore_args(_tiny_state(0.0))
    restored, meta = ckpt.maybe_restore(state, shardings, repair=False)
    assert meta["step"] == 1
    assert 2 in ckpt.manager.all_steps()  # NOT deleted
    ckpt.close()


def test_restore_retries_transient_error_without_fallback(tmp_path, monkeypatch):
    """A one-off I/O blip during restore must be retried, NOT misclassified
    as corruption (which would fall back AND delete the good newest step)."""
    registry = TelemetryRegistry()
    from llm_training_tpu.telemetry import set_registry

    previous = set_registry(registry)
    try:
        ckpt = _checkpointer(tmp_path, save_retries=2)
        ckpt.save(1, _tiny_state(1.0))
        ckpt.save(2, _tiny_state(2.0))
        real_restore = ckpt.manager.restore
        calls = {"n": 0}

        def flaky(step, *args, **kwargs):
            calls["n"] += 1
            if calls["n"] == 1:
                raise ConnectionError("transient storage blip")
            return real_restore(step, *args, **kwargs)

        monkeypatch.setattr(ckpt.manager, "restore", flaky)
        state, shardings = _restore_args(_tiny_state(0.0))
        restored, meta = ckpt.maybe_restore(state, shardings)
        assert meta["step"] == 2  # the newest step, not a fallback
        np.testing.assert_array_equal(np.asarray(restored.params["w"]), 2.0)
        assert 2 in ckpt.manager.all_steps()  # and it was NOT deleted
        assert registry.counter("checkpoint/retries").value == 1
        assert registry.counter("resilience/restore_fallbacks").value == 0
        ckpt.close()
    finally:
        set_registry(previous)


def test_restore_raises_when_every_step_is_corrupt(tmp_path):
    ckpt = _checkpointer(tmp_path, max_to_keep=1)
    ckpt.save(1, _tiny_state(1.0))
    import shutil

    shutil.rmtree(next((tmp_path / "1").glob("state*")))
    state, shardings = _restore_args(_tiny_state(0.0))
    with pytest.raises(RuntimeError, match="failed to restore"):
        ckpt.maybe_restore(state, shardings)


def test_async_error_surfaces_at_next_save_point(tmp_path, monkeypatch):
    ckpt = _checkpointer(tmp_path, async_save=True)
    ckpt.save(1, _tiny_state(1.0))
    ckpt.wait()

    def boom():
        raise RuntimeError("background save failed")

    monkeypatch.setattr(ckpt.manager, "check_for_errors", boom)
    with pytest.raises(RuntimeError, match="background save failed"):
        ckpt.save(2, _tiny_state(2.0))


def test_close_waits_for_inflight_async_save(tmp_path):
    ckpt = _checkpointer(tmp_path, async_save=True)
    ckpt.save(3, _tiny_state(3.0))
    ckpt.close()  # must barrier first — the save below must be durable
    ckpt2 = _checkpointer(tmp_path)
    assert ckpt2.latest_step() == 3
    ckpt2.close()


# ---------------------------------------------------------------- prefetcher


def _batch_stream(n):
    for i in range(n):
        yield {"x": np.full((2,), i, np.float32)}


def test_prefetcher_retries_transient_data_errors():
    from llm_training_tpu.data.prefetch import DevicePrefetcher

    registry = TelemetryRegistry()
    install_chaos(ChaosConfig(data_error_steps=(1,)), registry=registry)
    beats = []
    pf = DevicePrefetcher(
        _batch_stream(3), None, depth=2, registry=registry,
        retries=2, retry_backoff_s=0.0, heartbeat=lambda: beats.append(1),
    )
    got = [np.asarray(batch["x"])[0] for batch, _ in pf]
    assert got == [0.0, 1.0, 2.0]  # nothing lost across the injected fault
    assert registry.counter("data/retries").value == 1
    assert len(beats) == 3


def test_prefetcher_surfaces_real_generator_error_despite_retries():
    """A transient error raised INSIDE a generator closes it; the retries'
    re-pulls then see StopIteration. That must surface the ORIGINAL error,
    not truncate the stream into a silent clean-looking end."""
    from llm_training_tpu.data.prefetch import DevicePrefetcher

    def stream():
        yield {"x": np.zeros((2,), np.float32)}
        raise OSError("mid-epoch storage failure")

    pf = DevicePrefetcher(
        stream(), None, depth=2, registry=TelemetryRegistry(),
        retries=2, retry_backoff_s=0.0,
    )
    got = 0
    with pytest.raises(OSError, match="mid-epoch storage failure"):
        for _ in pf:
            got += 1
    assert got == 1  # the good batch still arrived


def test_prefetcher_default_zero_retries_surfaces_error():
    from llm_training_tpu.data.prefetch import DevicePrefetcher

    install_chaos(ChaosConfig(data_error_steps=(1,)))
    pf = DevicePrefetcher(_batch_stream(3), None, depth=2, registry=TelemetryRegistry())
    with pytest.raises(ChaosError):
        for _ in pf:
            pass
    pf.close()


# ---------------------------------------------------------------- config/report


def test_trainer_config_carries_resilience():
    from llm_training_tpu.trainer import TrainerConfig

    config = TrainerConfig(
        resilience={"watchdog_timeout_s": 120, "data_retries": 2,
                    "chaos": {"sigterm_step": 4}}
    )
    assert config.resilience.watchdog_timeout_s == 120
    assert config.resilience.chaos.sigterm_step == 4
    with pytest.raises(Exception):
        TrainerConfig(resilience={"watchdong_timeout_s": 1})  # typo rejected
    with pytest.raises(Exception):
        ResilienceConfig(watchdog_action="panic")


def test_report_renders_resilience_section(tmp_path):
    import json

    from llm_training_tpu.telemetry.report import render_report

    (tmp_path / "metrics.jsonl").write_text(
        json.dumps({"step": 1, "loss": 2.0, "steps_per_sec": 1.0}) + "\n"
    )
    (tmp_path / "telemetry.jsonl").write_text(
        json.dumps({
            "step": 1, "goodput/total_s": 10.0, "goodput/step_compute_s": 8.0,
            "resilience/preemptions": 1.0, "resilience/emergency_saves": 1.0,
            "data/retries": 3.0, "checkpoint/retries": 2.0,
        }) + "\n"
    )
    report = render_report(tmp_path)
    assert "== Resilience ==" in report
    assert "preemptions (graceful shutdowns): 1" in report
    assert "data-source retries: 3" in report
    assert "checkpoint I/O retries: 2" in report


def test_report_omits_resilience_section_for_clean_runs(tmp_path):
    import json

    from llm_training_tpu.telemetry.report import render_report

    (tmp_path / "metrics.jsonl").write_text(
        json.dumps({"step": 1, "loss": 2.0}) + "\n"
    )
    (tmp_path / "telemetry.jsonl").write_text(
        json.dumps({"step": 1, "goodput/total_s": 10.0,
                    "resilience/preemptions": 0.0, "data/retries": 0.0}) + "\n"
    )
    assert "== Resilience ==" not in render_report(tmp_path)


# ---------------------------------------------------------------- CLI


def test_cli_maps_preemption_to_resumable_exit_code(tmp_path, monkeypatch):
    from llm_training_tpu.cli.main import main
    from llm_training_tpu.trainer import Trainer

    config = {
        "trainer": {"max_steps": 2},
        "model": {
            "class_path": "llm_training_tpu.lms.CLM",
            "init_args": {
                "model": {
                    "model_class": "llm_training_tpu.models.Llama",
                    "model_kwargs": {
                        "vocab_size": 64, "hidden_size": 16,
                        "intermediate_size": 32, "num_hidden_layers": 1,
                        "num_attention_heads": 2, "num_key_value_heads": 2,
                        "max_position_embeddings": 32,
                    },
                },
                "optim": {"learning_rate": 1e-3},
            },
        },
        "data": {
            "class_path": "llm_training_tpu.data.DummyDataModule",
            "init_args": {"batch_size": 8, "max_length": 16, "num_samples": 16,
                          "vocab_size": 64},
        },
    }
    path = tmp_path / "config.yaml"
    path.write_text(yaml.safe_dump(config))

    def fake_fit(self, objective, datamodule, resume_step=None, state=None):
        raise PreemptionInterrupt(3, "preempted at step 3")

    monkeypatch.setattr(Trainer, "fit", fake_fit)
    assert main(["fit", "--config", str(path)]) == RESUMABLE_EXIT_CODE
    assert RESUMABLE_EXIT_CODE == 75  # BSD EX_TEMPFAIL, the supervisor contract


# ---------------------------------------------------------------- kill & resume


TINY_MODEL = dict(
    model_class="llm_training_tpu.models.Llama",
    model_kwargs=dict(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, compute_dtype="float32",
    ),
)


def _objective():
    from llm_training_tpu.lms import CLM, CLMConfig, ModelProvider
    from llm_training_tpu.optim import OptimConfig

    return CLM(
        CLMConfig(
            model=ModelProvider(**TINY_MODEL),
            optim=OptimConfig(learning_rate=1e-3, warmup_steps=2,
                              lr_scheduler="constant"),
        )
    )


def _data():
    from llm_training_tpu.data import DummyDataModule, DummyDataModuleConfig

    return DummyDataModule(
        DummyDataModuleConfig(batch_size=8, max_length=64, num_samples=64,
                              vocab_size=256, validation_split=8)
    )


class _Rec:
    def __init__(self):
        self.losses = {}

    def on_step_end(self, trainer, step, metrics):
        self.losses[step] = float(metrics["loss"])


@pytest.mark.slow
def test_chaos_sigterm_kill_and_resume_is_exact(devices, tmp_path):
    """The acceptance path: a chaos-injected SIGTERM mid-fit produces a
    committed emergency checkpoint and PreemptionInterrupt; a fresh fit
    resumes at the right micro-step with matching consumed counters and
    losses identical to an uninterrupted run."""
    from llm_training_tpu.trainer import Trainer, TrainerConfig
    from llm_training_tpu.trainer.checkpoint import CheckpointConfig, Checkpointer

    rec_full = _Rec()
    trainer = Trainer(
        TrainerConfig(max_steps=6, log_every_n_steps=1),
        callbacks=[rec_full],
        checkpointer=Checkpointer(
            CheckpointConfig(dirpath=str(tmp_path / "full"), async_save=False)
        ),
    )
    trainer.fit(_objective(), _data())
    full_counters = dict(trainer.counters)

    # preempted at step 3 — async save proves the emergency path waits the
    # barrier out before exiting
    rec_a = _Rec()
    ckpt_dir = str(tmp_path / "resume")
    t1 = Trainer(
        TrainerConfig(
            max_steps=6, log_every_n_steps=1,
            resilience=ResilienceConfig(chaos=ChaosConfig(sigterm_step=3)),
        ),
        callbacks=[rec_a],
        checkpointer=Checkpointer(CheckpointConfig(dirpath=ckpt_dir)),
    )
    with pytest.raises(PreemptionInterrupt) as excinfo:
        t1.fit(_objective(), _data())
    assert excinfo.value.step == 3
    assert max(rec_a.losses) == 3  # stopped AT the boundary, not later
    assert t1.telemetry.snapshot()["resilience/preemptions"] == 1
    # the emergency checkpoint is committed and restorable
    ckpt = Checkpointer(CheckpointConfig(dirpath=ckpt_dir))
    assert ckpt.latest_step() == 3
    ckpt.close()

    # relaunch: resumes micro-step 3 and matches the uninterrupted run
    rec_b = _Rec()
    t2 = Trainer(
        TrainerConfig(max_steps=6, log_every_n_steps=1),
        callbacks=[rec_b],
        checkpointer=Checkpointer(CheckpointConfig(dirpath=ckpt_dir, async_save=False)),
    )
    t2.fit(_objective(), _data())
    assert sorted(rec_b.losses) == [4, 5, 6]
    for step in range(4, 7):
        np.testing.assert_allclose(
            rec_b.losses[step], rec_full.losses[step], rtol=1e-6,
            err_msg=f"step {step}",
        )
    assert t2.counters == full_counters


@pytest.mark.slow
def test_fit_retries_chaos_checkpoint_error_and_completes(devices, tmp_path):
    """A transient checkpoint I/O fault mid-fit is retried and the run
    completes normally, with the retry visible in telemetry."""
    from llm_training_tpu.trainer import Trainer, TrainerConfig
    from llm_training_tpu.trainer.checkpoint import CheckpointConfig, Checkpointer

    trainer = Trainer(
        TrainerConfig(
            max_steps=4, log_every_n_steps=1, checkpoint_every_n_steps=2,
            resilience=ResilienceConfig(chaos=ChaosConfig(checkpoint_error_steps=(2,))),
        ),
        checkpointer=Checkpointer(
            CheckpointConfig(dirpath=str(tmp_path), async_save=False,
                             retry_backoff_s=0.0)
        ),
    )
    state = trainer.fit(_objective(), _data())
    assert int(jax.device_get(state.step)) == 4
    snapshot = trainer.telemetry.snapshot()
    assert snapshot["checkpoint/retries"] == 1
    assert snapshot["resilience/chaos_injections"] == 1
    ckpt = Checkpointer(CheckpointConfig(dirpath=str(tmp_path)))
    assert ckpt.latest_step() == 4
    ckpt.close()


@pytest.mark.slow
def test_fit_with_data_retries_survives_chaos_data_fault(devices, tmp_path):
    from llm_training_tpu.trainer import Trainer, TrainerConfig

    trainer = Trainer(
        TrainerConfig(
            max_steps=3, log_every_n_steps=1,
            resilience=ResilienceConfig(
                data_retries=2, data_retry_backoff_s=0.0,
                chaos=ChaosConfig(data_error_steps=(2,)),
            ),
        ),
    )
    state = trainer.fit(_objective(), _data())
    assert int(jax.device_get(state.step)) == 3
    snapshot = trainer.telemetry.snapshot()
    assert snapshot["data/retries"] == 1
