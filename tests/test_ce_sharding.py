"""HLO-level proof of the vocab-sharded fused CE (VERDICT r3 #4).

`lms/clm.py` claims the chunked `fused_linear_cross_entropy` lowers to a
vocab-sharded lm-head matmul + psum under tensor parallelism — i.e. the
reference's `loss_parallel` semantics without a dedicated code path. These
tests compile the op on a tensor-sharded mesh and inspect the partitioned
HLO: no full-vocab logits buffer may materialize per device, and the head
must never be all-gathered. They FAIL if the sharding regresses (e.g. a
future change constrains the logits to replicated).
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from llm_training_tpu.ops.cross_entropy import fused_linear_cross_entropy
from llm_training_tpu.parallel.mesh import MeshConfig, build_mesh

TOKENS, HIDDEN, VOCAB, CHUNK = 4096, 256, 32000, 1024
TP = 8


@pytest.fixture()
def tp_mesh(devices):
    return build_mesh(MeshConfig(fsdp_size=1, tensor_parallel_size=TP))


def _compile(tp_mesh, grad: bool):
    hidden_sh = NamedSharding(tp_mesh, P(None, None))
    head_sh = NamedSharding(tp_mesh, P(None, "tensor"))  # vocab-sharded
    labels_sh = NamedSharding(tp_mesh, P(None))

    def loss(hidden, head, labels):
        total, count = fused_linear_cross_entropy(
            hidden, head, labels, chunk_size=CHUNK
        )
        return total / jnp.maximum(count, 1).astype(jnp.float32)

    fn = jax.grad(loss, argnums=(0, 1)) if grad else loss
    return (
        jax.jit(fn)
        .lower(
            jax.ShapeDtypeStruct((TOKENS, HIDDEN), jnp.bfloat16, sharding=hidden_sh),
            jax.ShapeDtypeStruct((HIDDEN, VOCAB), jnp.bfloat16, sharding=head_sh),
            jax.ShapeDtypeStruct((TOKENS,), jnp.int32, sharding=labels_sh),
        )
        .compile()
    )


def _shapes_in(txt: str) -> set[tuple[int, ...]]:
    return {
        tuple(int(d) for d in m.group(1).split(",") if d)
        for m in re.finditer(r"\w+\[([\d,]+)\]", txt)
    }


@pytest.mark.parametrize("grad", [False, True], ids=["fwd", "fwd+bwd"])
def test_ce_stays_vocab_sharded(tp_mesh, grad):
    compiled = _compile(tp_mesh, grad)
    txt = compiled.as_text()
    shapes = _shapes_in(txt)

    # 1. no full-vocab logits chunk on any device: [CHUNK, VOCAB] must not
    #    appear (the per-device chunk is [CHUNK, VOCAB/TP])
    assert (CHUNK, VOCAB) not in shapes, "full logits chunk materialized"
    assert (CHUNK, VOCAB // TP) in shapes, "expected vocab-sharded chunk missing"

    # 2. the lm_head is never all-gathered: no instruction produces a
    #    full [HIDDEN, VOCAB] tensor (each device keeps [HIDDEN, VOCAB/TP])
    assert (HIDDEN, VOCAB) not in shapes, "lm_head all-gathered"

    # 3. the cross-shard softmax reduction exists (psum over tensor ranks)
    assert "all-reduce" in txt

    # 4. nothing full-vocab anywhere: the largest vocab-dim buffer is the
    #    sharded one
    assert not any(s and s[-1] == VOCAB for s in shapes), (
        "some buffer materialized the full vocab axis"
    )


def test_ce_sharded_numerics_match_replicated(tp_mesh):
    """The vocab-sharded compile must produce the same loss as a plain
    single-device evaluation."""
    rng = np.random.default_rng(0)
    hidden = jnp.asarray(rng.standard_normal((TOKENS, HIDDEN)) * 0.02, jnp.bfloat16)
    head = jnp.asarray(rng.standard_normal((HIDDEN, VOCAB)) * 0.02, jnp.bfloat16)
    labels = jnp.asarray(rng.integers(0, VOCAB, (TOKENS,)), jnp.int32)

    compiled = _compile(tp_mesh, grad=False)
    sharded = compiled(
        jax.device_put(hidden, NamedSharding(tp_mesh, P(None, None))),
        jax.device_put(head, NamedSharding(tp_mesh, P(None, "tensor"))),
        jax.device_put(labels, NamedSharding(tp_mesh, P(None))),
    )
    total, count = fused_linear_cross_entropy(hidden, head, labels, chunk_size=CHUNK)
    expected = total / jnp.maximum(count, 1).astype(jnp.float32)
    np.testing.assert_allclose(float(sharded), float(expected), rtol=1e-5)
