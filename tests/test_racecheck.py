"""racecheck (--races) + thread-jax-free tests — docs/static-analysis.md#racecheck.

Same shape as test_analysis.py: minimal positive/negative AST fixtures per
rule, the whole-repo capstone (clean against an EMPTY committed baseline),
and a copied-tree acceptance test proving that seeding an unguarded
shared-mutation AND a lock-order inversion makes the gate exit 1 naming
the attribute, both entry threads, and the missing/violated lock. Nothing
here builds a jax program.
"""

from __future__ import annotations

import json
import shutil
import subprocess
import textwrap
import time
from pathlib import Path

import pytest

from llm_training_tpu.analysis.engine import (
    DEFAULT_RACE_BASELINE,
    load_baseline,
    main,
    run_analysis,
)
from llm_training_tpu.analysis.racecheck import race_rules

REPO_ROOT = Path(__file__).resolve().parents[1]


def make_repo(tmp_path: Path, files: dict[str, str]) -> Path:
    base = {"llm_training_tpu/__init__.py": ""}
    base.update(files)
    for rel, content in base.items():
        target = tmp_path / rel
        target.parent.mkdir(parents=True, exist_ok=True)
        target.write_text(textwrap.dedent(content))
    return tmp_path


def race_findings(root: Path, rule: str | None = None):
    return run_analysis(
        root,
        rules=[rule] if rule else None,
        rule_specs=race_rules(),
    ).findings


# ---------------------------------------------------------------- rule table


def test_race_rule_table():
    names = [rule.name for rule in race_rules()]
    assert names == [
        "race-unguarded-shared",
        "race-lock-order",
        "race-signal-unsafe",
    ]


def test_whole_repo_races_clean_and_baseline_empty():
    """The acceptance bar: `--races` exits 0 at HEAD with an EMPTY
    committed baseline, in seconds."""
    t0 = time.monotonic()
    baseline = load_baseline(REPO_ROOT / DEFAULT_RACE_BASELINE)
    result = run_analysis(
        REPO_ROOT, baseline_keys=baseline, rule_specs=race_rules()
    )
    elapsed = time.monotonic() - t0
    assert result.findings == [], [f.render() for f in result.findings]
    assert baseline == set(), "race baseline must stay empty"
    assert elapsed < 15.0, f"race gate took {elapsed:.1f}s (budget 15s)"


def test_races_mode_never_imports_jax():
    code = (
        "import sys\n"
        "from llm_training_tpu.analysis.engine import main\n"
        "rc = main(['--races', '--list-rules'])\n"
        "leaked = [m for m in sys.modules if m == 'jax' or m.startswith(('jax.', 'jaxlib'))]\n"
        "assert rc == 0 and not leaked, (rc, leaked)\n"
        "print('RACES-JAXFREE-OK')\n"
    )
    proc = subprocess.run(
        ["python", "-c", code], cwd=REPO_ROOT,
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "RACES-JAXFREE-OK" in proc.stdout


# ------------------------------------------------- race-unguarded-shared


_UNGUARDED = """
    import threading


    class Pump:
        def __init__(self):
            self._lock = threading.Lock()
            self._boxes = []

        def start(self):
            threading.Thread(target=self._run, daemon=True).start()

        def _run(self):
            self._boxes.append(1)

        def add(self, item):
            self._boxes.append(item)
"""


def test_unguarded_shared_names_attr_and_both_entries(tmp_path):
    root = make_repo(tmp_path, {"llm_training_tpu/pump.py": _UNGUARDED})
    found = race_findings(root, "race-unguarded-shared")
    assert len(found) == 1, [f.render() for f in found]
    message = found[0].message
    assert "Pump._boxes" in message
    assert "thread:_run" in message and "main" in message
    assert "guarded by" in message


def test_declared_and_held_guard_passes(tmp_path):
    guarded = _UNGUARDED.replace(
        "            self._boxes = []",
        "            self._boxes = []  # guarded by: _lock",
    ).replace(
        "            self._boxes.append(1)",
        "            with self._lock:\n                self._boxes.append(1)",
    ).replace(
        "            self._boxes.append(item)",
        "            with self._lock:\n                self._boxes.append(item)",
    )
    root = make_repo(tmp_path, {"llm_training_tpu/pump.py": guarded})
    assert race_findings(root, "race-unguarded-shared") == []


def test_declared_guard_violated_names_the_lock_and_method(tmp_path):
    # declared, held in _run, but add() mutates outside the lock
    partially = _UNGUARDED.replace(
        "            self._boxes = []",
        "            self._boxes = []  # guarded by: _lock",
    ).replace(
        "            self._boxes.append(1)",
        "            with self._lock:\n                self._boxes.append(1)",
    )
    root = make_repo(tmp_path, {"llm_training_tpu/pump.py": partially})
    found = race_findings(root, "race-unguarded-shared")
    assert len(found) == 1, [f.render() for f in found]
    message = found[0].message
    assert "`Pump._boxes`" in message and "`add`" in message
    assert "`_lock`" in message


def test_declared_guard_must_be_a_real_lock(tmp_path):
    bogus = _UNGUARDED.replace(
        "            self._boxes = []",
        "            self._boxes = []  # guarded by: _no_such_lock",
    )
    root = make_repo(tmp_path, {"llm_training_tpu/pump.py": bogus})
    found = race_findings(root, "race-unguarded-shared")
    assert len(found) == 1
    assert "_no_such_lock" in found[0].message
    assert "not a Lock/RLock" in found[0].message


def test_caller_holds_contract_on_def_line(tmp_path):
    # the RequestJournal._append pattern: a private helper documented as
    # "caller holds the lock" — the def-line declaration grants it
    src = """
    import threading


    class Sink:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []  # guarded by: _lock

        def start(self):
            threading.Thread(target=self._run, daemon=True).start()

        def _run(self):
            with self._lock:
                self._push(1)

        def push(self, item):
            with self._lock:
                self._push(item)

        def _push(self, item):  # guarded by: _lock
            self._items.append(item)
    """
    root = make_repo(tmp_path, {"llm_training_tpu/sink.py": src})
    assert race_findings(root, "race-unguarded-shared") == []


def test_lock_name_heuristic_is_word_boundary_only(tmp_path):
    # `_blocks`/`_clock` must never classify as locks via substring match
    # — that would silently drop BlockAllocator-style state from the
    # shared-mutation analysis (found by review, pinned here)
    src = """
    import threading


    class Pool:
        def __init__(self, blocks, clock):
            self._blocks = blocks
            self._clock = clock

        def start(self):
            threading.Thread(target=self._run, daemon=True).start()

        def _run(self):
            self._blocks.append(1)

        def put(self, item):
            self._blocks.append(item)
    """
    root = make_repo(tmp_path, {"llm_training_tpu/pool.py": src})
    found = race_findings(root, "race-unguarded-shared")
    assert len(found) == 1, [f.render() for f in found]
    assert "Pool._blocks" in found[0].message
    # the sanctioned injected-lock pattern (`self._lock = lock`) still
    # counts as a lock and guards its attrs
    injected = """
    import threading


    class Shared:
        def __init__(self, lock):
            self._lock = lock
            self._items = []  # guarded by: _lock

        def start(self):
            threading.Thread(target=self._run, daemon=True).start()

        def _run(self):
            with self._lock:
                self._items.append(1)

        def put(self, item):
            with self._lock:
                self._items.append(item)
    """
    root2 = make_repo(tmp_path / "ok", {"llm_training_tpu/shared.py": injected})
    assert race_findings(root2, "race-unguarded-shared") == []


def test_threadsafe_containers_are_exempt(tmp_path):
    src = """
    import queue
    import threading


    class Feeder:
        def __init__(self):
            self._queue = queue.Queue()
            self._stop = threading.Event()

        def start(self):
            threading.Thread(target=self._run, daemon=True).start()

        def _run(self):
            self._queue.put(1)

        def stop(self):
            self._stop.set()
            self._queue.put(None)
    """
    root = make_repo(tmp_path, {"llm_training_tpu/feeder.py": src})
    assert race_findings(root, "race-unguarded-shared") == []


def test_module_global_shared_requires_declaration(tmp_path):
    src = """
    import threading

    _active = None
    _active_lock = threading.Lock()


    def install(value):
        global _active
        with _active_lock:
            _active = value


    def reader_loop():
        return _active


    def start():
        threading.Thread(target=reader_loop, daemon=True).start()
    """
    root = make_repo(tmp_path, {"llm_training_tpu/hooks.py": src})
    found = race_findings(root, "race-unguarded-shared")
    assert len(found) == 1, [f.render() for f in found]
    assert "module global `_active`" in found[0].message
    declared = src.replace(
        "    _active = None",
        "    _active = None  # guarded by: _active_lock",
    )
    root2 = make_repo(tmp_path / "ok", {"llm_training_tpu/hooks.py": declared})
    assert race_findings(root2, "race-unguarded-shared") == []


def test_closure_shared_with_nested_thread_target(tmp_path):
    # the PR 12 shape: a nested reader thread mutating a plain list the
    # enclosing serve loop also drains
    src = """
    import threading


    def serve_loop(stream):
        pending = []

        def reader():
            for line in stream:
                pending.append(line)

        threading.Thread(target=reader, daemon=True).start()
        while pending:
            pending.pop()
    """
    root = make_repo(tmp_path, {"llm_training_tpu/loop.py": src})
    found = race_findings(root, "race-unguarded-shared")
    assert len(found) == 1, [f.render() for f in found]
    assert "closure variable `pending`" in found[0].message
    assert "thread:reader" in found[0].message
    # the sanctioned queue handoff is silent
    fixed = src.replace("pending = []", "import queue\n        pending = queue.Queue()").replace(
        "pending.append(line)", "pending.put(line)"
    ).replace("while pending:\n            pending.pop()", "pending.get()")
    root2 = make_repo(tmp_path / "ok", {"llm_training_tpu/loop.py": fixed})
    assert race_findings(root2, "race-unguarded-shared") == []


def test_signal_entries_do_not_demand_locks(tmp_path):
    # a handler setting a flag the main loop polls is THE sanctioned
    # pattern — locks are the wrong tool in a handler (reentrancy)
    src = """
    import os
    import signal


    class Shutdown:
        def __init__(self):
            self._requested = False

        def install(self):
            signal.signal(signal.SIGTERM, self._handler)

        def _handler(self, signum, frame):
            self._requested = True
            os.write(2, b"shutting down\\n")

        @property
        def requested(self):
            return self._requested
    """
    root = make_repo(tmp_path, {"llm_training_tpu/sd.py": src})
    assert race_findings(root) == []


# ----------------------------------------------------- race-lock-order


_INVERSION = """
    import threading


    class Twisty:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def start(self):
            threading.Thread(target=self._run, daemon=True).start()

        def _run(self):
            with self._a:
                with self._b:
                    pass

        def poke(self):
            with self._b:
                with self._a:
                    pass
"""


def test_lock_order_inversion_is_flagged(tmp_path):
    root = make_repo(tmp_path, {"llm_training_tpu/twisty.py": _INVERSION})
    found = race_findings(root, "race-lock-order")
    assert len(found) == 1, [f.render() for f in found]
    message = found[0].message
    assert "Twisty._a" in message and "Twisty._b" in message
    assert "deadlock" in message


def test_consistent_lock_order_passes(tmp_path):
    consistent = _INVERSION.replace(
        "            with self._b:\n                with self._a:",
        "            with self._a:\n                with self._b:",
    )
    root = make_repo(tmp_path, {"llm_training_tpu/twisty.py": consistent})
    assert race_findings(root, "race-lock-order") == []


def test_lock_order_through_method_calls(tmp_path):
    # one hop of call propagation: _run holds _a and calls helper() which
    # acquires _b; poke nests them the other way
    src = _INVERSION.replace(
        "            with self._a:\n                with self._b:\n                    pass",
        "            with self._a:\n                self.helper()\n\n"
        "    def helper(self):\n            with self._b:\n                pass",
    )
    root = make_repo(tmp_path, {"llm_training_tpu/twisty.py": src})
    found = race_findings(root, "race-lock-order")
    assert len(found) == 1, [f.render() for f in found]


def test_single_threaded_modules_never_report_lock_order(tmp_path):
    solo = _INVERSION.replace(
        "        def start(self):\n"
        "            threading.Thread(target=self._run, daemon=True).start()\n\n",
        "",
    )
    root = make_repo(tmp_path, {"llm_training_tpu/twisty.py": solo})
    assert race_findings(root, "race-lock-order") == []


# --------------------------------------------------- race-signal-unsafe


def test_signal_handler_unsafe_work_is_flagged(tmp_path):
    src = """
    import logging
    import signal
    import threading

    logger = logging.getLogger(__name__)


    class Bad:
        def __init__(self):
            self._lock = threading.Lock()

        def install(self):
            signal.signal(signal.SIGTERM, self._handler)

        def _handler(self, signum, frame):
            print("dying")
            with self._lock:
                pass
            self._log_it()

        def _log_it(self):
            logger.warning("handled")
    """
    root = make_repo(tmp_path, {"llm_training_tpu/bad.py": src})
    found = race_findings(root, "race-signal-unsafe")
    whats = "\n".join(f.message for f in found)
    assert "print()" in whats
    assert "lock `_lock`" in whats
    assert "logging" in whats
    assert all("Bad._handler" in f.message for f in found)


def test_signal_handler_os_write_pattern_is_clean(tmp_path):
    src = """
    import os
    import signal


    def _handler(signum, frame):
        os.write(2, b"caught\\n")
        signal.raise_signal(signum)


    def install():
        signal.signal(signal.SIGTERM, _handler)
    """
    root = make_repo(tmp_path, {"llm_training_tpu/ok.py": src})
    assert race_findings(root, "race-signal-unsafe") == []


# ---------------------------------------------------------------- CLI modes


def test_races_cli_json_baseline_and_exit_codes(tmp_path, capsys):
    root = make_repo(tmp_path, {"llm_training_tpu/pump.py": _UNGUARDED})
    rc = main(["--root", str(root), "--races", "--no-baseline", "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert payload["mode"] == "races"
    assert payload["findings"][0]["rule"] == "race-unguarded-shared"
    # baseline workflow (config/race_baseline.json, kept separate from lint)
    assert main(["--root", str(root), "--races", "--update-baseline"]) == 0
    assert load_baseline(root / DEFAULT_RACE_BASELINE)
    assert not (root / "config/lint_baseline.json").exists()
    assert main(["--root", str(root), "--races"]) == 0  # grandfathered
    assert main(["--root", str(root), "--races", "--no-baseline"]) == 1
    # the two audits stay separate gates
    assert main(["--root", str(root), "--races", "--audit"]) == 2
    capsys.readouterr()


def test_races_suppression_with_reason(tmp_path):
    suppressed = _UNGUARDED.replace(
        "            self._boxes = []",
        "            # lint: allow(race-unguarded-shared): fixture proves the suppression path\n"
        "            self._boxes = []",
    )
    root = make_repo(tmp_path, {"llm_training_tpu/pump.py": suppressed})
    result = run_analysis(root, rule_specs=race_rules())
    assert result.findings == [], [f.render() for f in result.findings]
    assert len(result.suppressed) == 1


def test_copied_tree_acceptance_seeded_races_exit_1(tmp_path, capsys):
    """Acceptance: seeding an unguarded shared mutation AND a lock-order
    inversion into a copy of the real tree makes `--races` exit 1, naming
    the attribute, both entry threads, and the lock."""
    root = tmp_path / "copy"
    for rel in ("llm_training_tpu", "scripts", "bench.py", "config"):
        src = REPO_ROOT / rel
        if src.is_dir():
            shutil.copytree(
                src, root / rel, ignore=shutil.ignore_patterns("__pycache__")
            )
        else:
            root.mkdir(parents=True, exist_ok=True)
            shutil.copy(src, root / rel)
    target = root / "llm_training_tpu/resilience/watchdog.py"
    target.write_text(target.read_text() + textwrap.dedent(_UNGUARDED) + textwrap.dedent(_INVERSION))
    rc = main([
        "--root", str(root), "--races",
        "llm_training_tpu/resilience",  # narrowed scan keeps the test fast
    ])
    out = capsys.readouterr().out
    assert rc == 1, out
    assert "Pump._boxes" in out  # the attribute
    assert "thread:_run" in out and "main" in out  # both entry threads
    assert "guarded by" in out  # the missing lock
    assert "Twisty._a" in out and "Twisty._b" in out  # the inversion


# ------------------------------------------------------------ --changed-only


def _git(root: Path, *argv: str) -> None:
    subprocess.run(
        ["git", "-C", str(root), "-c", "user.email=t@t", "-c", "user.name=t",
         *argv],
        check=True, capture_output=True, timeout=30,
    )


def test_changed_only_scopes_the_scan_to_the_diff(tmp_path, capsys):
    root = make_repo(tmp_path, {
        "llm_training_tpu/pump.py": _UNGUARDED,
        "llm_training_tpu/other.py": "X = 1\n",
    })
    _git(root, "init", "-q")
    _git(root, "add", "-A")
    _git(root, "commit", "-qm", "seed")
    # the committed violation is invisible when only other.py changed
    (root / "llm_training_tpu/other.py").write_text("X = 2\n")
    assert main(["--root", str(root), "--races", "--changed-only"]) == 0
    # ...and visible again once pump.py itself is in the diff
    (root / "llm_training_tpu/pump.py").write_text(
        (root / "llm_training_tpu/pump.py").read_text() + "\n"
    )
    assert main(["--root", str(root), "--races", "--changed-only"]) == 1
    # a clean tree short-circuits with exit 0
    _git(root, "add", "-A")
    _git(root, "commit", "-qm", "wip")
    rc = main(["--root", str(root), "--changed-only"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "no changed .py files" in out


def test_changed_only_rejects_explicit_paths(tmp_path, capsys):
    root = make_repo(tmp_path, {})
    rc = main(["--root", str(root), "--changed-only", "llm_training_tpu"])
    assert rc == 2
    assert "mutually exclusive" in capsys.readouterr().err


def test_changed_only_usage_errors_beat_the_empty_diff_short_circuit(
    tmp_path, capsys
):
    """Invalid flag combinations must exit 2 regardless of git diff state
    — a clean worktree must never turn a usage error into a silent 0
    (review finding, pinned)."""
    root = make_repo(tmp_path, {"llm_training_tpu/clean.py": "X = 1\n"})
    _git(root, "init", "-q")
    _git(root, "add", "-A")
    _git(root, "commit", "-qm", "seed")
    rc = main(["--root", str(root), "--changed-only", "--families", "llama"])
    assert rc == 2
    assert "require --audit" in capsys.readouterr().err


def test_changed_only_keeps_cross_module_reachability(tmp_path):
    """A changed file spawning a thread whose target lives in an UNCHANGED
    jax-importing module must still fail under the narrowed scan — the
    call graph resolves out-of-scan modules on demand (review finding,
    pinned)."""
    root = make_repo(tmp_path, {
        "llm_training_tpu/worker.py": (
            "import jax\n\n\ndef worker():\n    jax.device_put(1)\n"
        ),
        "llm_training_tpu/spawner.py": (
            "import threading\n\n"
            "from llm_training_tpu.worker import worker\n\n\n"
            "def start():\n"
            "    threading.Thread(target=worker, daemon=True).start()\n"
        ),
    })
    _git(root, "init", "-q")
    _git(root, "add", "-A")
    _git(root, "commit", "-qm", "seed")
    # only the spawner is in the diff; the violation is in worker.py
    (root / "llm_training_tpu/spawner.py").write_text(
        (root / "llm_training_tpu/spawner.py").read_text() + "\n"
    )
    assert main([
        "--root", str(root), "--changed-only", "--no-baseline",
        "--rules", "thread-jax-free",
    ]) == 1


def test_changed_only_untracked_files_are_scanned(tmp_path):
    root = make_repo(tmp_path, {"llm_training_tpu/clean.py": "X = 1\n"})
    _git(root, "init", "-q")
    _git(root, "add", "-A")
    _git(root, "commit", "-qm", "seed")
    (root / "llm_training_tpu/pump.py").write_text(textwrap.dedent(_UNGUARDED))
    assert main(["--root", str(root), "--races", "--changed-only"]) == 1


# ------------------------------------------------------- thread-jax-free


def test_thread_jax_free_flags_thread_targets(tmp_path):
    src = """
    import threading

    import jax


    def worker():
        jax.device_put(1)


    def start():
        threading.Thread(target=worker, daemon=True).start()
    """
    root = make_repo(tmp_path, {"llm_training_tpu/w.py": src})
    found = run_analysis(root, rules=["thread-jax-free"]).findings
    assert len(found) == 1, [f.render() for f in found]
    assert "thread:worker" in found[0].message
    assert "jax" in found[0].message


def test_thread_jax_free_flags_lazy_imports_and_transitive_calls(tmp_path):
    src = """
    import threading


    def helper():
        import jax

        return jax.devices()


    def worker():
        helper()


    def start():
        threading.Thread(target=worker, daemon=True).start()
    """
    root = make_repo(tmp_path, {"llm_training_tpu/w.py": src})
    found = run_analysis(root, rules=["thread-jax-free"]).findings
    # both the lazy `import jax` and the call through its alias land in
    # the transitively-reached helper
    assert found, [f.render() for f in found]
    assert all("`helper`" in f.message for f in found)
    assert any("import jax" in f.message for f in found)


def test_thread_jax_free_ignores_main_thread_jax(tmp_path):
    src = """
    import threading

    import jax


    def step():
        return jax.jit(lambda x: x)(1)


    def worker():
        pass


    def start():
        threading.Thread(target=worker, daemon=True).start()
    """
    root = make_repo(tmp_path, {"llm_training_tpu/w.py": src})
    assert run_analysis(root, rules=["thread-jax-free"]).findings == []


def test_thread_jax_free_real_tree_only_sanctioned_suppression():
    """The whole-tree rule run: the only jax-on-a-thread site is the
    prefetcher's suppressed device_put."""
    result = run_analysis(REPO_ROOT, rules=["thread-jax-free"])
    assert result.findings == [], [f.render() for f in result.findings]
    assert any(
        "prefetch" in f.path for f in result.suppressed
    ), "expected the sanctioned prefetcher suppression to be exercised"


# ------------------------------------------------------- report race line


def test_report_audit_section_renders_race_gate(tmp_path):
    from llm_training_tpu.telemetry.report import _audit_section

    races = ({
        "version": 1, "mode": "races", "findings": [], "suppressed": 1,
        "baselined": 0, "elapsed_s": 1.0,
    }, "race.json")
    lines = _audit_section(None, races, {})
    text = "\n".join(lines)
    assert "== Audit ==" in text
    assert "racecheck: OK — 0 finding(s)" in text

    failing = ({
        "version": 1, "mode": "races",
        "findings": [{"rule": "race-unguarded-shared", "path": "x.py",
                      "line": 1, "message": "m", "key": "k"}],
        "suppressed": 0, "baselined": 2, "elapsed_s": 1.0,
    }, "race.json")
    text = "\n".join(_audit_section(None, failing, {}))
    assert "racecheck: FAIL — 1 finding(s)" in text
    assert "race-unguarded-shared x1" in text

    # honest degrade on malformed record
    text = "\n".join(_audit_section(None, ({"findings": "junk"}, "race.json"), {}))
    assert "racecheck" in text and "unreadable" in text

    # absent record: no racecheck line, and no crash
    assert _audit_section(None, None, {}) == []


def test_report_run_dir_race_json_end_to_end(tmp_path):
    from llm_training_tpu.telemetry.report import render_report

    run_dir = tmp_path / "run"
    run_dir.mkdir()
    (run_dir / "metrics.jsonl").write_text(
        json.dumps({"step": 1, "loss": 2.0}) + "\n"
    )
    (run_dir / "race.json").write_text(json.dumps({
        "version": 1, "mode": "races", "findings": [], "suppressed": 0,
        "baselined": 0, "elapsed_s": 0.5,
    }))
    rendered = render_report(run_dir)
    assert "== Audit ==" in rendered
    assert "racecheck: OK" in rendered
