"""HunYuan V1 MoE: post-rope qk-norm + softmax top-k MoE, HF parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_training_tpu.models.hunyuan_moe import HunYuanMoe, HunYuanMoeConfig
from llm_training_tpu.models.hunyuan_moe.hf_conversion import (
    config_from_hf,
    config_to_hf,
    params_from_hf,
    params_to_hf,
)

TINY = dict(
    vocab_size=128,
    hidden_size=64,
    intermediate_size=48,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    head_dim=16,
    max_position_embeddings=64,
    num_experts=4,
    moe_topk=2,
    compute_dtype="float32",
)


def _hf_tiny(**extra):
    torch = pytest.importorskip("torch")
    from transformers import HunYuanMoEV1Config as HFConfig
    from transformers import HunYuanMoEV1ForCausalLM

    kwargs = dict(TINY)
    kwargs.pop("compute_dtype")
    kwargs.update(attn_implementation="eager", **extra)
    hf_config = HFConfig(**kwargs)
    torch.manual_seed(0)
    return HunYuanMoEV1ForCausalLM(hf_config).eval(), hf_config


def test_logits_parity_with_hf():
    """Post-rope per-head qk-norm + softmax top-k router + gate-free shared
    MLP (HF keys: gate.wg, shared_mlp)."""
    torch = pytest.importorskip("torch")
    hf_model, hf_config = _hf_tiny()
    sd = hf_model.state_dict()
    assert "model.layers.0.mlp.gate.wg.weight" in sd
    assert "model.layers.0.mlp.shared_mlp.gate_proj.weight" in sd
    assert "model.layers.0.self_attn.query_layernorm.weight" in sd
    with torch.no_grad():  # post-rope ordering live
        for k, v in sd.items():
            if "layernorm.weight" in k and "self_attn" in k:
                v.copy_(torch.linspace(0.5, 1.5, v.numel()))

    cfg = config_from_hf(hf_config, compute_dtype="float32", moe_impl="dense")
    params = params_from_hf(sd, cfg)
    model = HunYuanMoe(cfg)

    ids = np.random.default_rng(99).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=4e-4, atol=4e-4)


def test_scan_and_loop_layers_agree():
    cfg_s = HunYuanMoeConfig(**TINY, scan_layers=True, moe_impl="dense")
    cfg_l = HunYuanMoeConfig(**TINY, scan_layers=False, moe_impl="dense")
    hf_model, hf_config = _hf_tiny()
    sd = hf_model.state_dict()
    ps = params_from_hf(sd, cfg_s)
    pl = params_from_hf(sd, cfg_l)
    ids = jnp.asarray(np.random.default_rng(100).integers(0, 128, (1, 16)))
    out_s = HunYuanMoe(cfg_s).apply(ps, ids).logits
    out_l = HunYuanMoe(cfg_l).apply(pl, ids).logits
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_l), rtol=2e-5, atol=2e-5)


def test_hf_round_trip():
    hf_model, hf_config = _hf_tiny()
    cfg = config_from_hf(hf_config)
    params = params_from_hf(hf_model.state_dict(), cfg)
    back = params_to_hf(params, cfg)
    sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    assert set(back) == set(sd)
    for key in sd:
        np.testing.assert_array_equal(back[key], sd[key], err_msg=key)


def test_config_round_trip():
    cfg = HunYuanMoeConfig(**TINY)
    hf = config_to_hf(cfg)
    assert hf["model_type"] == "hunyuan_v1_moe"
    cfg2 = config_from_hf(hf, compute_dtype="float32")
    assert cfg2.model_dump() == cfg.model_dump()


@pytest.mark.slow
def test_e2e_fit_decreases_loss():
    from conftest import fit_losses

    losses = fit_losses(
        "llm_training_tpu.models.HunYuanMoe",
        dict(TINY, enable_gradient_checkpointing=True, moe_impl="dense"),
        max_steps=20, lr=3e-3,
    )
    assert np.mean(losses[-3:]) < np.mean(losses[:3])
