"""RL post-training tests (docs/post-training.md): sampled-logprob
correctness against a numpy reference under top-k/top-p, decode-logprob
fidelity (incremental paged decode == teacher-forced full forward),
group-relative advantages, verifiable rewards, generation-staleness
rejection, the fused-vs-host weight-sync stream-equivalence contract,
SLO-breach rollout yielding, and the frozen-modules restore-tree fix the
GRPO policy/reference layout depends on. The end-to-end learning +
crash-resume legs live in scripts/rl_smoke.py (precommit)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_training_tpu.infer import SamplingConfig
from llm_training_tpu.infer.sampling import (
    filtered_logits,
    sample_tokens_with_logprob,
)
from llm_training_tpu.lms.grpo import group_relative_advantages
from llm_training_tpu.models import Gemma, GemmaConfig, Llama, LlamaConfig
from llm_training_tpu.rl import RolloutCollector, resolve_reward, sync_weights
from llm_training_tpu.rl import reward as reward_mod
from llm_training_tpu.rl.rollout import parse_rollout_id, rollout_id
from llm_training_tpu.serve import ServeConfig, ServingEngine
from llm_training_tpu.telemetry.registry import TelemetryRegistry
from llm_training_tpu.telemetry.slo import SLOMonitor, specs_from_config

TINY = dict(
    vocab_size=64, hidden_size=32, intermediate_size=64,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    max_position_embeddings=64, attention_impl="xla",
    compute_dtype="float32", param_dtype="float32",
)


def _init(model, seed=0):
    return model.init(jax.random.key(seed), np.zeros((1, 4), np.int32))


def _engine(model, variables, **overrides):
    config = ServeConfig(**{
        "max_batch": 2, "max_model_len": 48, "block_size": 8,
        "prefill_chunk": 4, "eos_token_id": None, **overrides,
    })
    return ServingEngine(model, variables, config)


# ------------------------------------------------- sampled-logprob unit


def _numpy_filtered_logprobs(logits, temperature, top_k, top_p):
    """Independent reference for the behavior distribution: temperature
    scale, then top-k, then top-p over the survivors (HF order), then
    log-softmax. Mirrors docs/inference.md semantics, not the jax code."""
    x = np.asarray(logits, np.float64) / temperature
    if top_k is not None and top_k < x.shape[-1]:
        threshold = np.sort(x, axis=-1)[..., -top_k][..., None]
        x = np.where(x >= threshold, x, -1e10)
    if top_p is not None:
        order = np.argsort(-x, axis=-1)
        sorted_x = np.take_along_axis(x, order, axis=-1)
        probs = np.exp(sorted_x - sorted_x.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        mass_before = np.cumsum(probs, axis=-1) - probs
        keep = mass_before < top_p
        threshold = np.min(
            np.where(keep, sorted_x, np.inf), axis=-1, keepdims=True
        )
        x = np.where(x >= threshold, x, -1e10)
    x -= x.max(-1, keepdims=True)
    return x - np.log(np.exp(x).sum(-1, keepdims=True))


@pytest.mark.parametrize(
    "temperature,top_k,top_p",
    [(1.0, None, None), (0.7, 8, None), (1.3, None, 0.9), (0.9, 12, 0.8)],
    ids=["plain", "top_k", "top_p", "both"],
)
def test_sampled_logprob_matches_numpy_reference(temperature, top_k, top_p):
    """The logprob the sampler returns must be the chosen token's mass
    under the FILTERED distribution it actually drew from — pinned
    against an independent numpy implementation of the filter chain."""
    logits = jax.random.normal(jax.random.key(3), (5, 32)) * 3.0
    config = SamplingConfig(temperature=temperature, top_k=top_k, top_p=top_p)
    tokens, logprobs = sample_tokens_with_logprob(
        logits, jax.random.key(7), config
    )
    reference = _numpy_filtered_logprobs(logits, temperature, top_k, top_p)
    for row in range(5):
        np.testing.assert_allclose(
            float(logprobs[row]), reference[row, int(tokens[row])],
            rtol=1e-4, atol=1e-5,
        )
    # a filtered-out token carries ~no mass in the behavior distribution
    if top_k is not None:
        worst = int(jnp.argmin(logits[0]))
        filtered = jax.nn.log_softmax(filtered_logits(logits, config))
        assert float(filtered[0, worst]) < -1e8


def test_greedy_logprob_is_raw_log_softmax():
    """temperature=0 scores under the RAW distribution, so incremental
    greedy-decode logprobs are comparable to a teacher-forced forward."""
    logits = jax.random.normal(jax.random.key(0), (3, 16))
    tokens, logprobs = sample_tokens_with_logprob(
        logits, None, SamplingConfig(temperature=0.0)
    )
    raw = jax.nn.log_softmax(logits, axis=-1)
    assert list(tokens) == list(jnp.argmax(logits, axis=-1))
    np.testing.assert_allclose(
        np.asarray(logprobs),
        np.asarray(raw)[np.arange(3), np.asarray(tokens)],
        rtol=1e-6,
    )


# ---------------------------------------------------------- GRPO math


def test_group_relative_advantages_standardize_within_group():
    rewards = jnp.asarray([1.0, 0.0, 1.0, 1.0, 5.0, 0.0])
    groups = jnp.asarray([0, 0, 0, 0, 1, 1])
    adv = np.asarray(group_relative_advantages(rewards, groups))
    g0 = np.asarray([1.0, 0.0, 1.0, 1.0])
    expected0 = (g0 - g0.mean()) / (g0.std() + 1e-6)
    np.testing.assert_allclose(adv[:4], expected0, rtol=1e-5)
    # group mean is removed exactly — a constant reward shift is invisible
    shifted = np.asarray(
        group_relative_advantages(rewards + 10.0, groups)
    )
    np.testing.assert_allclose(adv, shifted, rtol=1e-5)


def test_group_relative_advantages_degenerate_groups():
    # singleton group and zero-variance group: advantage ~0, never inf/nan
    adv = np.asarray(group_relative_advantages(
        jnp.asarray([3.0, 1.0, 1.0]), jnp.asarray([0, 1, 1])
    ))
    assert np.all(np.isfinite(adv))
    np.testing.assert_allclose(adv, 0.0, atol=1e-5)


# ------------------------------------------------------------- rewards


def test_reward_builtins(monkeypatch):
    copy_digit = resolve_reward("copy_digit")
    assert copy_digit([1, 2, 7], [7, 7, 3, 7]) == pytest.approx(0.75)
    assert copy_digit([1, 2, 7], []) == 0.0

    monkeypatch.setenv(reward_mod.TARGET_LEN_ENV, "4")
    length = resolve_reward("length")
    assert length([1], [5, 5, 5, 5]) == pytest.approx(1.0)
    assert length([1], [5, 5]) < 1.0

    monkeypatch.setenv(reward_mod.ANSWER_ENV, "42")
    numeric = resolve_reward("numeric_answer")
    # tokens render as space-separated decimal ids: "42" is token 42,
    # not the pair (4, 2)
    assert numeric([1], [3, 42, 5]) == pytest.approx(1.0)
    assert numeric([1], [4, 2]) == 0.0


def test_reward_env_selection(monkeypatch):
    monkeypatch.setenv(reward_mod.REWARD_ENV, "regex")
    monkeypatch.setenv(reward_mod.PATTERN_ENV, r"7 7")
    reward = resolve_reward(None)
    assert reward([0], [3, 7, 7, 1]) == pytest.approx(1.0)
    assert reward([0], [3, 1]) == 0.0
    monkeypatch.delenv(reward_mod.REWARD_ENV)
    # unset env -> copy_digit default (behavioral check: fraction of
    # completion tokens equal to the prompt's last token)
    assert resolve_reward(None)([1, 7], [7, 7, 3]) == pytest.approx(2 / 3)
    with pytest.raises(ValueError):
        resolve_reward("no_such_reward")


def test_rollout_id_roundtrip():
    assert parse_rollout_id(rollout_id(3, 1, 2)) == (3, 1, 2)
    assert parse_rollout_id("user:42") is None
    assert parse_rollout_id("rl:banana") is None


# ----------------------------------------- decode-logprob fidelity


def _teacher_forced_logprobs(model, variables, prompt, tokens):
    """One full forward over prompt+tokens; logprob of tokens[j] read at
    predictor position len(prompt)+j-1 of the raw log-softmax."""
    seq = list(prompt) + list(tokens)
    ids = jnp.asarray([seq], jnp.int32)
    out = model.apply(variables, input_ids=ids)
    logps = jax.nn.log_softmax(out.logits[0].astype(jnp.float32), axis=-1)
    return [
        float(logps[len(prompt) + j - 1, token])
        for j, token in enumerate(tokens)
    ]


def _fidelity_model(name):
    if name == "gemma":
        return Gemma(GemmaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, head_dim=8, max_position_embeddings=64,
            attention_impl="xla", compute_dtype="float32",
        ))
    extra = {
        "scan": dict(scan_layers=True),
        "looped": dict(scan_layers=False),
        "moe": dict(
            num_experts=4, num_experts_per_tok=2, moe_intermediate_size=32
        ),
    }[name]
    return Llama(LlamaConfig(**TINY, **extra))


@pytest.mark.parametrize("name", ["scan", "looped", "moe", "gemma"])
def test_decode_logprobs_match_teacher_forced_forward(name):
    """The behavior logprobs the engine collects token-by-token through
    the paged cache must equal a teacher-forced full forward over the
    finished sequence at the same weights — the property that makes them
    usable as GRPO's importance-ratio denominator."""
    model = _fidelity_model(name)
    variables = _init(model)
    engine = _engine(
        model, variables,
        sampling=SamplingConfig(temperature=1.0), seed=11,
    )
    collector = RolloutCollector(engine, group_size=2, max_new_tokens=8)
    rollouts = collector.collect(0, [[3, 17, 42, 7], [5, 9]])
    assert len(rollouts) == 4
    assert collector.stats()["rl/rollouts_stale_dropped"] == 0
    for rollout in rollouts:
        assert len(rollout.logprobs) == len(rollout.tokens) == 8
        reference = _teacher_forced_logprobs(
            model, variables, rollout.prompt, rollout.tokens
        )
        np.testing.assert_allclose(
            rollout.logprobs, reference, rtol=1e-4, atol=1e-5,
            err_msg=f"{name}: incremental decode logprobs diverge from "
            "teacher-forced forward",
        )


# ------------------------------------------------ generation staleness


def test_stale_generation_rollouts_dropped():
    """A weight reload mid-collection makes every in-flight rollout span
    two generations — ALL of them must be dropped at harvest, none may
    reach a training batch."""
    model = Llama(LlamaConfig(**TINY))
    variables = _init(model)
    engine = _engine(model, variables)
    collector = RolloutCollector(engine, group_size=2, max_new_tokens=8)

    steps = [0]

    def reload_mid_collection():
        steps[0] += 1
        if steps[0] == 3:
            engine.reload_weights(variables)  # same values, new generation
        return False

    rollouts = collector.collect(
        0, [[3, 17, 42], [5, 9]], should_stop=reload_mid_collection
    )
    stats = collector.stats()
    assert stats["rl/rollouts_stale_dropped"] >= 1
    # whatever survived was decoded entirely under the new generation
    assert all(r.generation == engine.weights_generation for r in rollouts)
    assert (
        stats["rl/rollouts_collected"] + stats["rl/rollouts_stale_dropped"]
        == 4.0
    )


# ---------------------------------------- weight-sync stream equivalence


def test_weight_sync_stream_equivalence_fused_vs_host_vs_fresh():
    """The acceptance contract (docs/post-training.md#weight-sync):
    continuing a mid-flight greedy request after a fused sync produces
    tokens identical to (a) the same scenario under the host-oracle sync
    and (b) a FRESH engine built from the synced weights and fed
    prompt + tokens-so-far."""
    model = Llama(LlamaConfig(**TINY))
    w0, w1 = _init(model, seed=0), _init(model, seed=1)
    prompt = [3, 17, 42, 7]
    total = 10

    def run_with_sync(mode):
        engine = _engine(model, w0, max_batch=1)
        events = list(engine.submit(id="r", prompt=prompt, max_new_tokens=total))
        before = [e["token"] for e in events if e.get("type") == "token"]
        while len(before) < 4:  # some tokens decoded under w0
            before += [
                e["token"] for e in engine.step() if e.get("type") == "token"
            ]
        summary = sync_weights(engine, w1, mode=mode)
        assert summary["generation"] == engine.weights_generation
        done = None
        while done is None:
            for event in engine.step():
                if event.get("type") == "done":
                    done = event
        return len(before), done["tokens"]

    k_fused, fused_tokens = run_with_sync("fused")
    k_host, host_tokens = run_with_sync("host")
    assert (k_fused, fused_tokens) == (k_host, host_tokens), (
        "fused on-device sync diverged from the host round-trip oracle"
    )
    # fresh engine restored from the synced weights, fed prompt + prefix
    fresh = _engine(model, w1, max_batch=1)
    events = list(fresh.submit(
        id="f", prompt=prompt + fused_tokens[:k_fused],
        max_new_tokens=total - k_fused,
    ))
    done = next((e for e in events if e.get("type") == "done"), None)
    while done is None:
        done = next(
            (e for e in fresh.step() if e.get("type") == "done"), None
        )
    assert fused_tokens[k_fused:] == done["tokens"], (
        "post-sync continuation diverged from a fresh engine on the "
        "synced weights"
    )


# --------------------------------------------------- SLO arbitration


def test_slo_breach_yields_rollout_submission():
    """The headline scenario: user traffic and rollouts share the engine;
    a burn-rate breach on serve TTFT (fed by user terminals) must open
    the collector's yield window — and every class still completes."""
    model = Llama(LlamaConfig(**TINY))
    variables = _init(model)
    engine = _engine(model, variables)
    monitor = SLOMonitor(
        specs_from_config({"serve": {"ttft_p99_ms": 10.0}}),
        registry=TelemetryRegistry(),
        min_events=1, cooldown_s=0.0, fast_burn=1.0, slow_burn=1.0,
    )
    user_done = []

    def on_foreign(event):
        if event.get("type") == "done":
            user_done.append(event["id"])
            # a user terminal far over the 10ms TTFT target
            monitor.observe_request(ttft_ms=100.0, ok=True)

    collector = RolloutCollector(
        engine, group_size=2, max_new_tokens=6,
        slo=monitor, yield_steps=2, on_foreign_event=on_foreign,
    )
    for i in range(2):
        collector.ingest(engine.submit(
            id=f"user:{i}", prompt=[9, 4, 6], max_new_tokens=2, priority=0
        ))
    # serve traffic alongside (the rl-fit loop's serve-first posture):
    # user terminals feed the monitor and breach the 10ms TTFT target
    for _ in range(50):
        if len(user_done) == 2:
            break
        collector.ingest(engine.step())
    assert len(user_done) == 2, "user traffic never completed"
    assert monitor.breach_count() >= 1, "TTFT breach never fired"
    # the NEXT rollout wave must open a yield window before submitting
    rollouts = collector.collect(
        0, [[3, 17], [5, 9], [1, 2], [7, 4], [8, 3], [2, 6]]
    )
    stats = collector.stats()
    assert stats["rl/rollout_yields"] >= 1, (
        "collector never yielded to the serve SLO breach"
    )
    assert len(rollouts) == 12, "yield window must defer, not drop, groups"


# ------------------------------------- frozen-modules restore structure


def test_frozen_modules_shardings_match_state_tree(tmp_path):
    """optax.masked puts empty MaskedNode slots in a frozen module's
    opt_state; the shardings tree must preserve them as empties (not
    invent leaves) or every GRPO/DPO restore dies on a pytree mismatch."""
    import flax.linen as nn

    from llm_training_tpu.cli.config import load_config
    from llm_training_tpu.cli.main import _build
    from llm_training_tpu.parallel.mesh import build_mesh
    from llm_training_tpu.trainer.trainer import LOGICAL_AXIS_RULES

    config = load_config(
        "config/examples/smoke/rl-smoke.yaml", [f"run_root={tmp_path}"]
    )
    trainer, objective, _ = _build(config)
    assert objective.config.frozen_modules, "GRPO must freeze its reference"
    trainer.mesh = build_mesh(trainer.config.mesh, trainer.devices)
    with trainer.mesh, nn.logical_axis_rules(LOGICAL_AXIS_RULES):
        sample_batch = {"input_ids": np.zeros((1, 8), np.int32)}
        tx, _ = trainer._build_tx(objective)
        abstract_boxed = trainer._abstract_state(objective, sample_batch, tx)
        shardings = trainer._state_shardings(abstract_boxed)
        abstract = nn.meta.unbox(abstract_boxed)
    assert jax.tree.structure(abstract) == jax.tree.structure(shardings)
