"""Bamba: Mamba-2 SSD + attention hybrid, HF parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_training_tpu.models.bamba import Bamba, BambaConfig
from llm_training_tpu.models.bamba.hf_conversion import (
    config_from_hf,
    config_to_hf,
    params_from_hf,
    params_to_hf,
)

TINY = dict(
    vocab_size=128,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    max_position_embeddings=128,
    attn_layer_indices=[1],
    mamba_n_heads=8,
    mamba_d_head=8,
    mamba_n_groups=2,
    mamba_d_state=16,
    mamba_expand=2,
    mamba_d_conv=4,
    mamba_chunk_size=8,
    compute_dtype="float32",
)


def _hf_tiny(**extra):
    torch = pytest.importorskip("torch")
    from transformers import BambaConfig as HFConfig
    from transformers import BambaForCausalLM

    kwargs = dict(TINY)
    kwargs.pop("compute_dtype")
    kwargs.pop("mamba_chunk_size")
    kwargs.update(attn_implementation="eager", **extra)
    hf_config = HFConfig(**kwargs)
    torch.manual_seed(0)
    return BambaForCausalLM(hf_config).eval(), hf_config


@pytest.mark.parametrize("seq", [6, 24])
def test_logits_parity_with_hf(seq):
    """SSD + attention hybrid vs HF eager ('ssd naive' torch path). seq 6
    fits one chunk; seq 24 spans three (HF chunk 8 via our override),
    exercising the cross-chunk state recurrence."""
    torch = pytest.importorskip("torch")
    hf_model, hf_config = _hf_tiny()
    # HF's mamba_chunk_size default is 256; shrink it so multi-chunk paths
    # run at test sizes (the chunking must not change the math)
    hf_model.model.layers[0].mamba.chunk_size = 8
    sd = hf_model.state_dict()
    assert "model.layers.0.mamba.in_proj.weight" in sd
    assert "model.layers.1.self_attn.q_proj.weight" in sd
    # make the decay dynamics non-trivial
    with torch.no_grad():
        sd["model.layers.0.mamba.A_log"].copy_(torch.linspace(-1.0, 1.0, 8))
        sd["model.layers.0.mamba.dt_bias"].copy_(torch.linspace(-0.5, 0.5, 8))

    cfg = config_from_hf(hf_config, compute_dtype="float32", mamba_chunk_size=8)
    assert not cfg.layer_is_attention(0) and cfg.layer_is_attention(1)
    params = params_from_hf(sd, cfg)
    model = Bamba(cfg)

    ids = np.random.default_rng(90).integers(0, 128, (2, seq))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=4e-4, atol=4e-4)


def test_hf_round_trip():
    hf_model, hf_config = _hf_tiny()
    cfg = config_from_hf(hf_config)
    params = params_from_hf(hf_model.state_dict(), cfg)
    back = params_to_hf(params, cfg)
    sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    assert set(back) == set(sd)
    for key in sd:
        np.testing.assert_array_equal(back[key], sd[key], err_msg=key)


def test_config_round_trip():
    cfg = BambaConfig(**TINY)
    hf = config_to_hf(cfg)
    assert hf["model_type"] == "bamba"
    cfg2 = config_from_hf(hf, compute_dtype="float32")
    assert cfg2.model_dump() == cfg.model_dump()


@pytest.mark.slow
def test_e2e_fit_decreases_loss():
    from conftest import fit_losses

    losses = fit_losses(
        "llm_training_tpu.models.Bamba",
        dict(TINY, enable_gradient_checkpointing=True),
        max_steps=20, lr=3e-3,
    )
    assert np.mean(losses[-3:]) < np.mean(losses[:3])
