"""Deterministic-interleaving tests (analysis/interleave.py) — the dynamic
half of racecheck (docs/static-analysis.md#racecheck).

Everything here is jax-free host code: the harness drives real threads one
baton at a time, so these tests add seconds, not minutes, to tier-1. The
two "known hairy windows" from the ISSUE are pinned here:

- RequestJournal: the stdin reader's `delivered()` racing the drain path's
  `progress()` flush (the PR 12 lost-delivery race class) — no delivered
  record may be lost under ANY schedule;
- TraceRecorder: `flight_dump()` racing the sampled sink writer — every
  dump must be a consistent ring snapshot and the sink must stay parseable.

Plus the watchdog regression: the stale-check/dump-commit window that used
to span two lock acquisitions is now one critical section, pinned by a
schedule assertion that fails against the old shape.
"""

from __future__ import annotations

import json
import threading
from types import SimpleNamespace

import pytest

from llm_training_tpu.analysis import contracts
from llm_training_tpu.analysis.interleave import (
    DeadlockError,
    Interleaver,
    LockOrderError,
    find_failing_seed,
    instrumented_locks,
    sched_point,
    shrink,
)
from llm_training_tpu.serve.journal import RequestJournal, replay_journal
from llm_training_tpu.telemetry.trace import TraceRecorder


# ------------------------------------------------------------- the harness


def test_schedules_are_seed_deterministic():
    """The acceptance bar: a schedule replays byte-identically from its
    seed — same decisions, same lock interleavings, same trace."""

    def build(run: Interleaver) -> Interleaver:
        lock = run.lock("shared")
        log = []

        def worker(tag):
            def body():
                for i in range(3):
                    sched_point(f"{tag}:{i}")
                    with lock:
                        log.append((tag, i))
            return body

        run.thread(worker("a"), name="a")
        run.thread(worker("b"), name="b")
        run.run()
        return run

    first = build(Interleaver(seed=1234))
    second = build(Interleaver(seed=1234))
    assert first.run_fingerprint() == second.run_fingerprint()
    assert first.choices == second.choices
    # a different seed really schedules differently (sanity, not strictly
    # guaranteed per-seed — 4321 vs 1234 differ for this workload)
    other = build(Interleaver(seed=4321))
    assert other.run_fingerprint() != first.run_fingerprint()


def test_explicit_schedule_replays_choices():
    order = []

    def make(tag):
        def body():
            sched_point("mid")
            order.append(tag)
        return body

    run = Interleaver(seed=0, schedule=["b", "b", "a", "a"])
    run.thread(make("a"), name="a")
    run.thread(make("b"), name="b")
    run.run()
    assert order == ["b", "a"]


def test_assertion_failures_carry_seed_and_replay_schedule():
    def build(run: Interleaver) -> None:
        counter = SimpleNamespace(value=0)

        def bump():
            seen = counter.value
            sched_point("between-read-and-write")  # the classic lost update
            counter.value = seen + 1

        run.thread(bump, name="a")
        run.thread(bump, name="b")
        run.run()
        assert counter.value == 2, counter.value

    seed = find_failing_seed(build, seeds=range(64))
    assert seed is not None, "no seed interleaved the lost update?"
    with pytest.raises(AssertionError) as info:
        build(Interleaver(seed=seed))
    assert f"seed {seed}" in str(info.value) or "counter" not in str(info.value)
    # shrinking keeps the failure and never grows the schedule
    minimal = shrink(build, seed)
    with pytest.raises(AssertionError):
        build(Interleaver(seed=seed, schedule=list(minimal)))


def test_deadlock_detection_names_the_locks_and_lock_order_asserts():
    """A classic AB/BA inversion: some schedule deadlocks (named, not
    hung), and the recorded edges violate any declared order."""

    def build(run: Interleaver) -> Interleaver:
        a, b = run.lock("A"), run.lock("B")

        def ab():
            with a:
                sched_point("holding-A")
                with b:
                    pass

        def ba():
            with b:
                sched_point("holding-B")
                with a:
                    pass

        run.thread(ab, name="ab")
        run.thread(ba, name="ba")
        run.run()
        return run

    seed = find_failing_seed(build, seeds=range(64))
    assert seed is not None, "no schedule produced the AB/BA deadlock?"
    with pytest.raises(DeadlockError) as info:
        build(Interleaver(seed=seed))
    assert "A" in str(info.value) and "B" in str(info.value)
    # a non-deadlocking seed still records the inverted edges
    clean = None
    for candidate in range(64):
        try:
            clean = build(Interleaver(seed=candidate))
            break
        except DeadlockError:
            continue
    if clean is not None and {("A", "B"), ("B", "A")} <= clean.lock_edges:
        with pytest.raises(LockOrderError):
            clean.assert_lock_order(("A", "B"))


def test_declared_repo_lock_order_is_self_consistent():
    # the contract table itself: no duplicates, all labels named
    assert len(set(contracts.LOCK_ORDER)) == len(contracts.LOCK_ORDER)
    assert "registry" in contracts.LOCK_ORDER  # the leaf every subsystem uses
    assert contracts.LOCK_ORDER.index("registry") == len(contracts.LOCK_ORDER) - 1


# --------------------------------------------------- journal: the PR 12 class


def _journal_under(run: Interleaver, tmp_path):
    with instrumented_locks(run):
        journal = RequestJournal(tmp_path / "journal.jsonl")
    if journal._lock in run.locks or hasattr(journal._lock, "rename"):
        journal._lock.rename("journal")
    return journal


def _fake_request(rid: str, generated: list[int], emitted: int = 0):
    return SimpleNamespace(
        id=rid, generated=list(generated), emitted=emitted,
        stop_reason=None,
    )


@pytest.mark.parametrize("seed", range(12))
def test_journal_reader_delivery_vs_drain_flush_never_loses_a_record(
    tmp_path, seed
):
    """The PR 12 race class, replayed on purpose: the stdin reader thread
    journals deliveries while the drain path flushes progress for every
    in-flight request. Under EVERY schedule, all delivered ids must
    survive into the replay fold, the drained request's progress must be
    exact, and the file must stay line-parseable (no torn interleaving)."""
    run = Interleaver(seed=seed)
    journal = _journal_under(run, tmp_path)
    in_flight = _fake_request("running-0", [5, 6, 7], emitted=2)

    def reader():
        for n in range(3):
            sched_point(f"deliver:{n}")
            journal.delivered(f"req-{n}", [1, 2, n], max_new_tokens=8)

    def drain():
        sched_point("drain:progress")
        journal.progress(in_flight)
        sched_point("drain:done")

    run.thread(reader, name="reader")
    run.thread(drain, name="drain")
    run.run()
    run.assert_lock_order()

    lines = (tmp_path / "journal.jsonl").read_text().splitlines()
    assert all(json.loads(line) for line in lines)  # no torn lines
    remainder = {entry["id"]: entry for entry in replay_journal(tmp_path / "journal.jsonl")}
    # every delivered request replays; none vanished in the interleaving
    assert {"req-0", "req-1", "req-2"} <= set(remainder)
    for n in range(3):
        assert remainder[f"req-{n}"]["prompt"] == [1, 2, n]
    # delivered() is acceptance-only: replayed deliveries carry no tokens
    assert remainder["req-0"]["generated"] == []


def test_journal_failing_schedule_replays_byte_identically(tmp_path):
    """One fixed seed: two runs produce identical journal bytes AND
    identical harness traces — the replay contract the shrinker rests on."""

    def one(run_dir):
        run = Interleaver(seed=7)
        journal = _journal_under(run, run_dir)
        request = _fake_request("r", [9], emitted=0)
        run.thread(lambda: journal.delivered("a", [1], 4), name="reader")
        run.thread(lambda: journal.progress(request), name="drain")
        run.run()
        return run.run_fingerprint(), (run_dir / "journal.jsonl").read_bytes()

    first_dir, second_dir = tmp_path / "one", tmp_path / "two"
    first_dir.mkdir(), second_dir.mkdir()
    trace1, bytes1 = one(first_dir)
    trace2, bytes2 = one(second_dir)
    assert trace1 == trace2
    assert bytes1 == bytes2


def test_journal_close_during_delivery_never_corrupts(tmp_path):
    """close() racing a late delivery (the drain-tail window the serve CLI
    documents): the delivery either lands before the close or is dropped
    with a log — never an exception, never a torn file."""
    for seed in range(12):
        run_dir = tmp_path / f"seed{seed}"
        run_dir.mkdir()
        run = Interleaver(seed=seed)
        journal = _journal_under(run, run_dir)

        def late_delivery():
            sched_point("pre-delivery")
            journal.delivered("late", [3], 4)

        def closer():
            sched_point("pre-close")
            journal.close()

        run.thread(late_delivery, name="reader")
        run.thread(closer, name="closer")
        run.run()  # raises InterleaveFailure if any schedule throws
        for line in (run_dir / "journal.jsonl").read_text().splitlines():
            json.loads(line)


# --------------------------------------- trace ring: flight_dump vs sink


@pytest.mark.parametrize("seed", range(10))
def test_flight_dump_racing_sink_writer_is_consistent(tmp_path, seed):
    """The watchdog flight-dumps the ring from its poll thread while the
    engine step records sampled events into the sink. Every dump must be a
    prefix-consistent snapshot of the recorded sequence, counts must add
    up, and the sink must contain exactly the written events afterwards."""
    run = Interleaver(seed=seed)
    ticker = iter(range(10_000))
    with instrumented_locks(run):
        recorder = TraceRecorder(
            capacity=64, sample_every=1, enabled=True,
            clock=lambda: float(next(ticker)),
        )
    recorder._lock.rename("trace")
    sink_dir = tmp_path / "run"
    assert recorder.attach_sink(sink_dir / "trace.jsonl")

    def writer():
        for n in range(8):
            sched_point(f"record:{n}")
            recorder.instant("serve", f"event-{n}", write=True, n=n)

    def dumper():
        for round_ in range(2):
            sched_point(f"dump:{round_}")
            assert recorder.flight_dump(sink_dir, f"seed{seed}-{round_}") is not None

    run.thread(writer, name="writer")
    run.thread(dumper, name="dumper")
    run.run()
    run.assert_lock_order()

    recorder.detach_sink()
    counts = recorder.counts()
    # attach_sink emits one clock_anchor meta event on top of the 8 payloads
    assert counts["recorded"] == 9
    assert counts["written"] == 9
    assert counts["flight_dumps"] == 2
    sink_events = [
        json.loads(line)
        for line in (sink_dir / "trace.jsonl").read_text().splitlines()
    ]
    assert sink_events[0]["cat"] == "meta"
    assert sink_events[0]["name"] == "clock_anchor"
    assert [e["name"] for e in sink_events[1:]] == [
        f"event-{n}" for n in range(8)
    ]
    for round_ in range(2):
        dump = sink_dir / f"trace-flight-seed{seed}-{round_}.jsonl"
        lines = [json.loads(line) for line in dump.read_text().splitlines()]
        # every dump leads with a fresh alignment anchor
        assert lines[0]["name"] == "clock_anchor"
        names = [e["name"] for e in lines if e["name"] != "clock_anchor"]
        # a dump is a consistent prefix of the recorded sequence — never a
        # torn view with holes
        assert names == [f"event-{n}" for n in range(len(names))]


# ------------------------------------------------- watchdog: the fixed window


def _watchdog_under(run: Interleaver, clock):
    from llm_training_tpu.resilience.watchdog import HangWatchdog

    with instrumented_locks(run):
        watchdog = HangWatchdog(timeout_s=10.0, clock=clock)
    watchdog._lock.rename("watchdog")
    return watchdog


def test_watchdog_beat_vs_poll_decision_never_loses_the_rearm():
    """Regression for the check-then-commit window: in the old shape the
    staleness read and the `_dumped = True` commit were two separate lock
    acquisitions, so a beat() landing between them had its re-arm
    (`_dumped = False`) clobbered — the dump fired AND the next stall was
    silently ignored (one lost hang per race). With decision+commit in
    ONE critical section, the beat either wins the lock first (no dump, a
    later stall still dumps) or re-arms after the dump — under every
    schedule `_dumped` ends False and a second stall always dumps."""
    for seed in range(24):
        run = Interleaver(seed=seed)
        now = {"t": 100.0}
        watchdog = _watchdog_under(run, clock=lambda: now["t"])
        # the primary beat is stale: recorded at t=100, checked at t=200
        watchdog.beat()
        now["t"] = 200.0
        fired = {}

        def poll():
            fired["dump"] = watchdog._poll_once()

        def beat():
            watchdog.beat()  # fresh beat at t=200

        run.thread(poll, name="poll")
        run.thread(beat, name="beat")
        run.run()

        acquires = [
            (event[1], event[2]) for event in run.trace
            if event[0] == "acquire" and event[2] == "watchdog"
        ]
        poll_first = next(i for i, (who, _) in enumerate(acquires) if who == "poll")
        beat_first = next(i for i, (who, _) in enumerate(acquires) if who == "beat")
        # the decision is atomic: fired iff the poll's decision section
        # won the lock before the fresh beat
        assert fired["dump"] == (poll_first < beat_first), (seed, acquires)
        # the re-arm is NEVER lost (the old shape's failure): _dumped ends
        # False under every schedule, so...
        assert watchdog._dumped is False, (seed, fired)
        # ...a second stall after the fresh beat still dumps
        now["t"] = 400.0
        assert watchdog._poll_once() is True, seed


def test_watchdog_dump_paths_guarded_against_poll_thread(tmp_path):
    """dump() appends dump_paths from the poll thread while the main
    thread reads it (the crash smokes poll it in a loop) — pinned by
    asserting the append happens under the watchdog lock in every
    schedule."""
    for seed in range(8):
        run = Interleaver(seed=seed)
        now = {"t": 100.0}
        watchdog = _watchdog_under(run, clock=lambda: now["t"])
        watchdog.run_dir = tmp_path / f"seed{seed}"
        watchdog.beat()
        now["t"] = 300.0
        seen = {}

        def poll():
            watchdog._poll_once()

        def main_reader():
            sched_point("reading-dump-paths")
            with watchdog._lock:
                seen["paths"] = list(watchdog.dump_paths)

        run.thread(poll, name="poll")
        run.thread(main_reader, name="reader")
        run.run()
        # the reader saw either nothing (scheduled first) or the full path
        assert len(seen["paths"]) in (0, 1)
        assert len(watchdog.dump_paths) == 1


# ------------------------------------ exporter: snapshot-under-scrape window


@pytest.mark.parametrize("seed", range(12))
def test_registry_snapshot_never_observes_a_torn_metric_under_scrape(seed):
    """The /metrics scrape path (telemetry/exporter.py) takes ONE
    registry snapshot while writer threads publish — the ISSUE's 'scrape
    mid-write must never observe a torn counter' window. A Timer's
    add() mutates its `total_s`/`count` pair inside one critical section
    and snapshot_with_kinds() flattens inside the same one, so under
    EVERY schedule each snapshot sees the pair move together: probe_s ==
    probe_n always (each add() contributes exactly 1.0s and 1 count). A
    snapshot taken between the two field writes would break the
    equality — this test fails against that shape."""
    from llm_training_tpu.telemetry.registry import TelemetryRegistry

    run = Interleaver(seed=seed)
    with instrumented_locks(run):
        registry = TelemetryRegistry()
    registry._lock.rename("registry")
    # metric objects created OUTSIDE the scheduled threads (plain-lock
    # semantics for setup), mutated inside them
    timer = registry.timer("exporter/probe")
    counter = registry.counter("exporter/events")
    snapshots = []

    def writer():
        for n in range(4):
            sched_point(f"write:{n}")
            timer.add(1.0)
            counter.inc()

    def scraper():
        for n in range(5):
            sched_point(f"scrape:{n}")
            values, kinds = registry.snapshot_with_kinds()
            snapshots.append(values)
            assert kinds.get("exporter/events") == "counter"

    run.thread(writer, name="writer")
    run.thread(scraper, name="scrape")
    run.run()
    assert snapshots
    for values in snapshots:
        assert values.get("exporter/probe_s", 0.0) == values.get(
            "exporter/probe_n", 0.0
        ), values
        # counters are monotone floats committed whole
        assert values.get("exporter/events", 0.0) in (0.0, 1.0, 2.0, 3.0, 4.0)
    final, _ = registry.snapshot_with_kinds()
    assert final["exporter/probe_n"] == 4.0 and final["exporter/events"] == 4.0


@pytest.mark.parametrize("seed", range(8))
def test_slo_observe_vs_scrape_read_obeys_lock_order(seed, tmp_path):
    """The serve loop observing requests (slo lock -> breach emission into
    registry/trace AFTER release) racing the exporter's statusz read
    (last_alert) — no deadlock under any schedule, and every recorded
    acquisition edge is consistent with contracts.LOCK_ORDER."""
    from llm_training_tpu.telemetry.registry import TelemetryRegistry
    from llm_training_tpu.telemetry.slo import SLOMonitor, specs_from_config

    run = Interleaver(seed=seed)
    t = {"now": 0.0}
    with instrumented_locks(run):
        registry = TelemetryRegistry()
        monitor = SLOMonitor(
            specs_from_config({"serve": {"ttft_p99_ms": 10.0}}),
            registry=registry, clock=lambda: t["now"],
            fast_window_s=10.0, slow_window_s=60.0, fast_burn=2.0,
            slow_burn=2.0, min_events=2, cooldown_s=100.0,
        )
    registry._lock.rename("registry")
    monitor._lock.rename("slo")
    alerts = []

    def serve_loop():
        for n in range(4):
            sched_point(f"observe:{n}")
            t["now"] += 1.0
            monitor.observe_request(ttft_ms=100.0, ok=True)

    def scrape():
        for n in range(4):
            sched_point(f"statusz:{n}")
            alerts.append(monitor.last_alert())
            registry.snapshot_with_kinds()

    run.thread(serve_loop, name="serve")
    run.thread(scrape, name="scrape")
    run.run()
    run.assert_lock_order()
    # the breach fired and a later statusz read could see it whole
    assert monitor.breach_count() == 1
    seen = [a for a in alerts if a is not None]
    for alert in seen:
        assert alert["key"] == "serve/ttft_p99_ms" and "burn_fast" in alert


# ------------------------------------------------- router: assignment vs death


class _RouterStubHandle:
    def __init__(self, rid: str, port: int):
        self.rid = rid
        self.port = port


def _router_under(run: Interleaver, tmp_path):
    from llm_training_tpu.serve.router import Router

    with instrumented_locks(run):
        journal = RequestJournal(tmp_path / "router-journal.jsonl")
        router = Router()
    router.journal = journal
    journal._lock.rename("journal")
    router._lock.rename("router")
    return router, journal


def test_lock_order_declares_router_before_journal():
    """The router appends journal records (assignment notes, progress)
    while holding its own lock, so the contract table must sort `router`
    before `journal` — and keep it a distinct label."""
    assert "router" in contracts.LOCK_ORDER
    assert contracts.LOCK_ORDER.index("router") < contracts.LOCK_ORDER.index(
        "journal"
    )


@pytest.mark.parametrize("seed", range(12))
def test_router_assignment_vs_replica_death_window(tmp_path, seed):
    """The ISSUE's hairy window: the main loop assigning + folding chunks
    from replica r0 while the EOF path declares r0 dead and folds its
    journal. Under EVERY schedule: at most one terminal ever reaches the
    client, the request is never lost (finished, re-assignable, or
    orphaned — never vanished), and every recorded lock edge obeys
    contracts.LOCK_ORDER (router -> journal, never inverted)."""
    router, journal = _router_under(run := Interleaver(seed=seed), tmp_path)
    router.register_replica(_RouterStubHandle("r0", 9001))
    router.register_replica(_RouterStubHandle("r1", 9002))
    req = router.intake({"id": "req-0", "prompt": [1, 2], "max_new_tokens": 8})
    events = []
    failover = {}

    def main_loop():
        sched_point("assign")
        router.assign(req)
        sched_point("token")
        events.extend(router.record_token("r0", {"id": "r0::req-0", "token": 5}))
        sched_point("done")
        events.extend(
            router.record_done("r0", {"id": "r0::req-0", "stop_reason": "eos"})
        )

    def death():
        sched_point("death")
        folded = [
            {
                "id": "r0::req-0",
                "client_id": "req-0",
                "source_replica": "r0",
                "prompt": [1, 2],
                "generated": [5],
                "emitted": 1,
                "max_new_tokens": 8,
                "priority": 0,
            }
        ]
        failover.update(router.fail_replica("r0", folded))

    run.thread(main_loop, name="main")
    run.thread(death, name="death")
    run.run()
    run.assert_lock_order()

    terminals = [e for e in events + failover.get("events", []) if e.get("type") == "done"]
    assert len(terminals) <= 1, terminals
    stats = router.stats()
    assert stats["duplicate_terminals_suppressed"] + stats["suppressed_chunks"] >= 0
    if terminals:
        # finished exactly once: tombstoned, dedupes forever, nothing orphaned
        assert stats["requests_completed"] == 1
        assert failover.get("orphans", []) == []
        assert router.inflight() == 0
        assert router.intake({"id": "req-0", "prompt": [1, 2]}) is None
    else:
        # replica died before the terminal: the request survives as an
        # orphan (resubmittable) or as a still-registered in-flight entry
        orphans = failover.get("orphans", [])
        assert [o.id for o in orphans] == ["req-0"] or router.inflight() == 1
        assert stats["requests_completed"] == 0
    # the journal stayed line-parseable under the interleaving and its fold
    # agrees with the terminal outcome
    journal.close()
    lines = (tmp_path / "router-journal.jsonl").read_text().splitlines()
    assert all(json.loads(line) for line in lines)
    remainder = replay_journal(tmp_path / "router-journal.jsonl")
    if terminals:
        assert remainder == []
    else:
        assert [e["id"] for e in remainder] == ["req-0"]


@pytest.mark.parametrize("seed", range(8))
def test_router_live_stats_scrape_never_observes_torn_counters(tmp_path, seed):
    """The exporter's extra_fn (HTTP thread) scraping live_stats() while
    the main loop registers and finishes requests: every snapshot must be
    internally consistent — a request is in-flight XOR terminal, so
    total == completed + failed + inflight at every observation point."""
    router, journal = _router_under(run := Interleaver(seed=seed), tmp_path)
    router.register_replica(_RouterStubHandle("r0", 9001))
    reqs = [
        router.intake({"id": f"req-{n}", "prompt": [n], "max_new_tokens": 4})
        for n in range(3)
    ]
    snapshots = []

    def main_loop():
        for n, req in enumerate(reqs):
            sched_point(f"assign:{n}")
            router.assign(req)
            sched_point(f"done:{n}")
            router.record_done(
                "r0", {"id": f"r0::req-{n}", "stop_reason": "eos"}
            )

    def scrape():
        for n in range(5):
            sched_point(f"scrape:{n}")
            snapshots.append(router.live_stats())

    run.thread(main_loop, name="main")
    run.thread(scrape, name="scrape")
    run.run()
    run.assert_lock_order()
    assert snapshots
    for snap in snapshots:
        total = snap["router/requests_total"]
        settled = snap["router/requests_completed"] + snap["router/requests_failed"]
        assert total == settled + snap["router/inflight"], snap
    assert router.stats()["requests_completed"] == 3
    journal.close()
