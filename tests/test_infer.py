"""Inference subsystem tests (docs/inference.md): KV-cache decode parity
against full forwards (the canonical cache-correctness oracle), sampling
transforms, the eval harness, and the generate/evaluate CLI wiring."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_training_tpu.infer import (
    GenerateConfig,
    InferenceEngine,
    SamplingConfig,
    cache_bytes,
    init_decode_state,
    sample_tokens,
)
from llm_training_tpu.infer.sampling import top_k_filter, top_p_filter
from llm_training_tpu.models import (
    Gemma,
    GemmaConfig,
    Llama,
    LlamaConfig,
)

TINY = dict(
    vocab_size=64, hidden_size=32, intermediate_size=64,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    max_position_embeddings=64, attention_impl="xla",
    compute_dtype="float32", param_dtype="float32",
)


def _init(model, seed=0):
    return model.init(jax.random.key(seed), np.zeros((1, 4), np.int32))


def _full_forward_greedy(model, variables, prompt, n):
    """The oracle: n argmax tokens from n FULL forward passes (no cache)."""
    seq = list(prompt)
    for _ in range(n):
        out = model.apply(variables, input_ids=jnp.asarray([seq]))
        seq.append(int(jnp.argmax(out.logits[0, -1])))
    return seq[len(prompt):]


# ------------------------------------------------------------ greedy parity


@pytest.mark.parametrize("scan_layers", [True, False], ids=["scan", "looped"])
def test_greedy_decode_matches_full_forward(scan_layers):
    """N-token greedy generation through the KV cache must be token-
    identical to argmax over N full forward passes — with RAGGED prompt
    lengths, so the left-pad bookkeeping (per-row positions, pad segment
    ids) is part of what parity proves."""
    model = Llama(LlamaConfig(**TINY, scan_layers=scan_layers))
    variables = _init(model)
    engine = InferenceEngine(model, variables)
    prompts = [[3, 17, 42, 7, 11], [5, 9], [1, 2, 3]]
    n = 8
    result = engine.generate(prompts, GenerateConfig(max_new_tokens=n))
    for row, prompt in enumerate(prompts):
        expected = _full_forward_greedy(model, variables, prompt, n)
        assert result["tokens"][row] == expected, f"row {row}"
        assert result["sequences"][row] == list(prompt) + expected


def test_greedy_decode_moe_and_sliding_window():
    """The smoke-config shape: a tiny MoE Llama (router + experts run in
    the decode programs too) with a sliding window small enough to actually
    truncate attention mid-generation."""
    model = Llama(LlamaConfig(
        **TINY, num_experts=4, num_experts_per_tok=2, moe_intermediate_size=32,
        sliding_window=4,
    ))
    variables = _init(model)
    engine = InferenceEngine(model, variables)
    prompts = [[3, 17, 42, 7, 11, 2]]
    result = engine.generate(prompts, GenerateConfig(max_new_tokens=6))
    assert result["tokens"][0] == _full_forward_greedy(model, variables, prompts[0], 6)


def test_greedy_decode_gemma():
    model = Gemma(GemmaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, max_position_embeddings=64, attention_impl="xla",
        compute_dtype="float32",
    ))
    variables = _init(model)
    engine = InferenceEngine(model, variables)
    prompts = [[3, 17, 42], [5, 9, 11, 13]]
    result = engine.generate(prompts, GenerateConfig(max_new_tokens=5))
    for row, prompt in enumerate(prompts):
        assert result["tokens"][row] == _full_forward_greedy(model, variables, prompt, 5)


def test_prefill_logits_match_full_forward():
    """Prefill writes the cache AND must reproduce the training forward's
    logits on the prompt (same stack, same mask) — checked directly on the
    model so a future engine change can't mask a stack regression."""
    from llm_training_tpu.models.base import DecodeState  # noqa: F401

    model = Llama(LlamaConfig(**TINY))
    variables = _init(model)
    ids = jax.random.randint(jax.random.key(3), (2, 6), 0, 64)
    state = init_decode_state(model.config, batch_size=2, max_length=10)
    out = model.apply(
        variables, input_ids=ids,
        segment_ids=jnp.ones_like(ids),
        position_ids=jnp.broadcast_to(jnp.arange(6), (2, 6)),
        decode_state=state,
    )
    full = model.apply(variables, input_ids=ids)
    np.testing.assert_allclose(
        np.asarray(out.logits), np.asarray(full.logits), rtol=2e-5, atol=2e-5
    )
    assert int(out.decode_state.index) == 6


# ------------------------------------------------------------ sampling


def test_sample_tokens_greedy_is_argmax():
    logits = jnp.asarray([[0.1, 3.0, -1.0], [2.0, 0.0, 2.5]])
    tokens = sample_tokens(logits, None, SamplingConfig(temperature=0.0))
    assert tokens.tolist() == [1, 2]


def test_top_k_filter_keeps_k_largest():
    logits = jnp.asarray([[1.0, 4.0, 2.0, 3.0]])
    filtered = np.asarray(top_k_filter(logits, 2))
    assert (filtered[0] > -1e9).tolist() == [False, True, False, True]
    # k >= vocab is the identity
    np.testing.assert_array_equal(np.asarray(top_k_filter(logits, 4)), np.asarray(logits))


def test_top_p_filter_nucleus():
    # probs ~ [0.643, 0.236, 0.087, 0.032]: p=0.7 keeps the boundary-
    # crossing 2nd token (HF semantics), p=0.5 keeps only the 1st
    logits = jnp.asarray([[4.0, 3.0, 2.0, 1.0]])
    keep_07 = np.asarray(top_p_filter(logits, 0.7))[0] > -1e9
    assert keep_07.tolist() == [True, True, False, False]
    keep_05 = np.asarray(top_p_filter(logits, 0.5))[0] > -1e9
    assert keep_05.tolist() == [True, False, False, False]
    # p=1.0 keeps everything
    assert (np.asarray(top_p_filter(logits, 1.0))[0] > -1e9).all()


def test_sampled_tokens_respect_filters_and_seed():
    logits = jax.random.normal(jax.random.key(0), (4, 32))
    config = SamplingConfig(temperature=0.7, top_k=5)
    a = sample_tokens(logits, jax.random.key(1), config)
    b = sample_tokens(logits, jax.random.key(1), config)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # every sampled token must be inside each row's top-5
    top5 = jax.lax.top_k(logits, 5)[1]
    for row in range(4):
        assert int(a[row]) in np.asarray(top5[row]).tolist()


def test_sampling_config_validators():
    with pytest.raises(ValueError):
        SamplingConfig(temperature=-0.1)
    with pytest.raises(ValueError):
        SamplingConfig(top_k=0)
    with pytest.raises(ValueError):
        SamplingConfig(top_p=0.0)
    with pytest.raises(ValueError):
        sample_tokens(jnp.zeros((1, 4)), None, SamplingConfig(temperature=1.0))


# ------------------------------------------------------------ engine


def test_engine_rejects_unthreaded_families():
    class NoCacheModel:
        config = None

        def __call__(self, input_ids=None, segment_ids=None, position_ids=None,
                     inputs_embeds=None, compute_logits=True,
                     return_last_hidden_states=False):
            raise AssertionError("never applied")

    with pytest.raises(NotImplementedError, match="decode_state"):
        InferenceEngine(NoCacheModel(), {})


def test_engine_eos_truncation():
    model = Llama(LlamaConfig(**TINY))
    variables = _init(model)
    engine = InferenceEngine(model, variables)
    base = engine.generate([[3, 17, 42]], GenerateConfig(max_new_tokens=6))
    eos = base["tokens"][0][2]  # force a stop at the 3rd greedy token
    result = engine.generate(
        [[3, 17, 42]], GenerateConfig(max_new_tokens=6, eos_token_id=eos)
    )
    assert result["tokens"][0] == base["tokens"][0][:3]
    assert result["tokens"][0][-1] == eos


def test_generate_config_validators():
    with pytest.raises(ValueError, match="max_new_tokens"):
        GenerateConfig(max_new_tokens=0)
    with pytest.raises(ValueError, match="max_new_tokens"):
        GenerateConfig(max_new_tokens=-5)
    with pytest.raises(ValueError, match="max_length"):
        GenerateConfig(max_length=0)


def test_engine_cache_sizing_and_stats():
    model = Llama(LlamaConfig(**TINY))
    variables = _init(model)
    engine = InferenceEngine(model, variables)
    with pytest.raises(ValueError, match="max_length"):
        engine.generate([[1, 2, 3]], GenerateConfig(max_new_tokens=8, max_length=4))
    result = engine.generate(
        [[1, 2, 3]], GenerateConfig(max_new_tokens=4, cache_dtype="bfloat16")
    )
    stats = result["stats"]
    # [L=2, B=1, S=7, H=2, D=8] bf16 k+v
    assert stats["decode/cache_bytes"] == 2 * (2 * 1 * 7 * 2 * 8) * 2
    assert stats["decode/new_tokens"] == 4
    assert stats["decode/prefill_time_s"] > 0


def test_init_decode_state_dtypes():
    config = LlamaConfig(**TINY)
    state = init_decode_state(config, 2, 8)
    assert state.k.dtype == jnp.float32  # param dtype default
    assert int(state.index) == 0
    assert state.segment_ids.shape == (2, 8)
    bf16 = init_decode_state(config, 2, 8, cache_dtype="bfloat16")
    assert bf16.k.dtype == jnp.bfloat16
    assert cache_bytes(bf16) == cache_bytes(state) // 2


def test_engine_on_mesh(devices):
    """Sharded decode: the default 8-device mesh, batch divisible by the
    data ways — greedy tokens must match the meshless run exactly."""
    import flax.linen as nn

    from llm_training_tpu.parallel import MeshConfig
    from llm_training_tpu.parallel.mesh import build_mesh
    from llm_training_tpu.trainer.trainer import LOGICAL_AXIS_RULES

    model = Llama(LlamaConfig(**TINY))
    variables = _init(model)
    prompts = [[i + 1, i + 2, i + 3] for i in range(8)]
    reference = InferenceEngine(model, variables).generate(
        prompts, GenerateConfig(max_new_tokens=4)
    )
    mesh = build_mesh(MeshConfig(), devices)
    with mesh, nn.logical_axis_rules(LOGICAL_AXIS_RULES):
        sharded_vars = jax.device_put(variables)
    engine = InferenceEngine(model, sharded_vars, mesh=mesh, rules=LOGICAL_AXIS_RULES)
    result = engine.generate(prompts, GenerateConfig(max_new_tokens=4))
    assert result["tokens"] == reference["tokens"]


# ------------------------------------------------------------ evaluate


def _dummy_data(**kwargs):
    from llm_training_tpu.data import DummyDataModule, DummyDataModuleConfig

    return DummyDataModule(DummyDataModuleConfig(
        batch_size=8, max_length=16, num_samples=48, vocab_size=64,
        validation_split=16, **kwargs,
    ))


def test_run_evaluation_packed_nll(devices):
    from llm_training_tpu.infer import run_evaluation
    from llm_training_tpu.lms import CLM, CLMConfig, ModelProvider
    from llm_training_tpu.parallel import MeshConfig
    from llm_training_tpu.parallel.mesh import build_mesh
    from llm_training_tpu.trainer.state import TrainState

    objective = CLM(CLMConfig(model=ModelProvider(
        model_class="llm_training_tpu.models.Llama", model_kwargs=TINY,
    )))
    variables = _init(objective.model)
    state = TrainState.create(variables, (), jax.random.key(0))
    mesh = build_mesh(MeshConfig(), devices)
    result = run_evaluation(objective, state, _dummy_data(), mesh)
    assert np.isfinite(result["eval/nll_per_token"])
    np.testing.assert_allclose(
        result["eval/perplexity"], np.exp(result["eval/nll_per_token"]), rtol=1e-6
    )
    # 2 val batches of 8x16 tokens, every position a target except the last
    # of each (unpacked) row
    assert result["eval/batches"] == 2.0
    assert result["eval/tokens"] == 2 * 8 * (16 - 1)
    with pytest.raises(ValueError, match="limit_batches"):
        run_evaluation(objective, state, _dummy_data(), mesh, split="train")


# ------------------------------------------------------------ CLI


@pytest.mark.slow
def test_cli_generate_and_evaluate_from_checkpoint(devices, tmp_path):
    """End-to-end acceptance path: fit -> checkpoint -> `generate` /
    `evaluate` -> decode gauges visible in `report`."""
    import yaml

    from llm_training_tpu.cli.main import main

    config = {
        "seed_everything": 7,
        "trainer": {
            "max_steps": 2,
            "log_every_n_steps": 1,
            "checkpoint_every_n_steps": 2,
            "checkpoint": {"dirpath": str(tmp_path / "ckpt"), "async_save": False},
            "loggers": [{
                "class_path": "llm_training_tpu.callbacks.JsonlLogger",
                "init_args": {
                    "save_dir": str(tmp_path / "runs"),
                    "project": "t", "name": "r",
                },
            }],
        },
        "model": {
            "class_path": "llm_training_tpu.lms.CLM",
            "init_args": {
                "model": {
                    "model_class": "llm_training_tpu.models.Llama",
                    "model_kwargs": TINY,
                },
                "optim": {"learning_rate": 1e-3},
            },
        },
        "data": {
            "class_path": "llm_training_tpu.data.DummyDataModule",
            "init_args": {
                "batch_size": 8, "max_length": 16, "num_samples": 32,
                "vocab_size": 64, "validation_split": 8,
            },
        },
    }
    config_path = tmp_path / "config.yaml"
    config_path.write_text(yaml.safe_dump(config))
    assert main(["fit", "--config", str(config_path)]) == 0
    assert main([
        "generate", "--config", str(config_path),
        "--prompt-tokens", "3,17,42", "--max-new-tokens", "4",
    ]) == 0
    assert main([
        "evaluate", "--config", str(config_path), "--limit-batches", "1",
    ]) == 0
    from llm_training_tpu.telemetry.report import render_report

    report = render_report(tmp_path / "runs" / "t" / "r")
    assert "== Inference ==" in report
    assert "decode_tokens_per_sec" in report
    assert "perplexity" in report
