"""gpt-oss: sink attention, clamped-swiglu MoE, HF parity + round trip."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_training_tpu.models.gpt_oss import GptOss, GptOssConfig
from llm_training_tpu.models.gpt_oss.hf_conversion import (
    config_from_hf,
    config_to_hf,
    params_from_hf,
    params_to_hf,
)

TINY = dict(
    vocab_size=128,
    hidden_size=64,
    intermediate_size=48,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    head_dim=16,
    max_position_embeddings=64,
    sliding_window=8,
    num_local_experts=4,
    num_experts_per_tok=2,
    compute_dtype="float32",
)


def _hf_tiny(**extra):
    torch = pytest.importorskip("torch")
    from transformers import GptOssConfig as HFConfig
    from transformers import GptOssForCausalLM

    kwargs = dict(
        vocab_size=128, hidden_size=64, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64, sliding_window=8,
        num_local_experts=4, num_experts_per_tok=2,
        attn_implementation="eager",
    )
    kwargs.update(extra)
    hf_config = HFConfig(**kwargs)
    torch.manual_seed(0)
    return GptOssForCausalLM(hf_config).eval(), hf_config


def test_logits_parity_with_hf():
    """Sink softmax + alternating sliding window + interleaved fused
    gate_up experts with clamped activation, against HF eager."""
    torch = pytest.importorskip("torch")
    hf_model, hf_config = _hf_tiny()
    sd = hf_model.state_dict()
    assert "model.layers.0.self_attn.sinks" in sd
    assert sd["model.layers.0.mlp.experts.gate_up_proj"].shape == (4, 64, 96)
    assert hf_config.layer_types == ["sliding_attention", "full_attention"]
    # non-trivial sinks so the denominator term actually matters
    with torch.no_grad():
        for i in range(2):
            sd[f"model.layers.{i}.self_attn.sinks"].copy_(
                torch.linspace(-1.0, 2.0, 4)
            )

    cfg = config_from_hf(hf_config, compute_dtype="float32", moe_impl="dense")
    assert cfg.layer_sliding_window(0) == 8 and cfg.layer_sliding_window(1) is None
    params = params_from_hf(sd, cfg)
    model = GptOss(cfg)

    # 24 > sliding_window so the sliding layer actually truncates
    ids = np.random.default_rng(50).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=3e-4, atol=3e-4)


def test_hf_round_trip():
    hf_model, hf_config = _hf_tiny()
    cfg = config_from_hf(hf_config)
    params = params_from_hf(hf_model.state_dict(), cfg)
    back = params_to_hf(params, cfg)
    sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    assert set(back) == set(sd)
    for key in sd:
        np.testing.assert_array_equal(back[key], sd[key], err_msg=key)


def test_config_round_trip():
    cfg = GptOssConfig(**TINY)
    hf = config_to_hf(cfg)
    assert hf["model_type"] == "gpt_oss"
    cfg2 = config_from_hf(hf, compute_dtype="float32")
    # the export materializes the implicit even-index alternation
    assert cfg2.layer_types == ["sliding_attention", "full_attention"]
    a, b = cfg.model_dump(), cfg2.model_dump()
    a.pop("layer_types"), b.pop("layer_types")
    assert a == b
    assert [cfg2.layer_sliding_window(i) for i in range(2)] == [
        cfg.layer_sliding_window(i) for i in range(2)
    ]


@pytest.mark.slow
def test_ragged_and_dense_impls_agree():
    cfg_d = GptOssConfig(**TINY, moe_impl="dense")
    cfg_r = GptOssConfig(**TINY, moe_impl="ragged")
    model_d, model_r = GptOss(cfg_d), GptOss(cfg_r)
    ids = jnp.asarray(np.random.default_rng(51).integers(0, 128, (2, 16)))
    params = model_d.init(jax.random.key(10), ids)
    out_d = model_d.apply(params, ids).logits
    out_r = model_r.apply(params, ids).logits
    np.testing.assert_allclose(np.asarray(out_d), np.asarray(out_r), rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_e2e_fit_decreases_loss():
    from llm_training_tpu.data import DummyDataModule, DummyDataModuleConfig
    from llm_training_tpu.lms import CLM, CLMConfig, ModelProvider
    from llm_training_tpu.optim import OptimConfig
    from llm_training_tpu.parallel import MeshConfig
    from llm_training_tpu.trainer import Trainer, TrainerConfig

    objective = CLM(CLMConfig(
        model=ModelProvider(
            model_class="llm_training_tpu.models.GptOss",
            model_kwargs=dict(TINY, enable_gradient_checkpointing=True,
                              router_aux_loss_coef=0.01),
        ),
        optim=OptimConfig(learning_rate=3e-3, warmup_steps=2),
    ))
    data = DummyDataModule(DummyDataModuleConfig(
        batch_size=8, max_length=32, num_samples=64, vocab_size=128,
    ))
    losses = []

    class Track:
        def on_step_end(self, trainer, step, metrics):
            losses.append(float(metrics["loss"]))

    Trainer(
        TrainerConfig(max_steps=20, log_every_n_steps=1, mesh=MeshConfig()),
        callbacks=[Track()],
    ).fit(objective, data)
    assert np.mean(losses[-3:]) < np.mean(losses[:3])


@pytest.mark.slow
def test_export_reloads_in_transformers(tmp_path):
    torch = pytest.importorskip("torch")
    from transformers import AutoModelForCausalLM

    from llm_training_tpu.models.hf_io import save_hf_checkpoint

    cfg = GptOssConfig(**TINY)
    model = GptOss(cfg)
    ids = jnp.asarray(np.random.default_rng(52).integers(0, 128, (2, 16)))
    params = model.init(jax.random.key(11), ids)
    out_dir = save_hf_checkpoint(params, cfg, tmp_path / "export", dtype="float32")

    hf_model = AutoModelForCausalLM.from_pretrained(
        out_dir, attn_implementation="eager"
    ).eval()
    assert type(hf_model).__name__ == "GptOssForCausalLM"
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(np.asarray(ids))).logits.numpy()
    ours = model.apply(params, ids).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=3e-4, atol=3e-4)


@pytest.mark.slow
def test_sharded_fit_matches_single_device(devices):
    """Sink attention + fused biased experts must compose with a real
    fsdp x tensor mesh: sharded losses equal the single-device run."""
    from conftest import fit_losses
    from llm_training_tpu.parallel import MeshConfig

    kwargs = dict(TINY, moe_impl="dense", router_aux_loss_coef=0.01)
    single = fit_losses("llm_training_tpu.models.GptOss", kwargs)
    sharded = fit_losses(
        "llm_training_tpu.models.GptOss", kwargs,
        mesh=MeshConfig(fsdp_size=4, tensor_parallel_size=2),
    )
    np.testing.assert_allclose(single, sharded, rtol=2e-4)
