"""Router tier unit tests: cross-replica journal folding (colliding ids
namespace independently; torn tails skipped), exactly-once terminals across
the replica-death window, hedging winner/loser suppression, leg adoption,
health-driven eviction, least-loaded assignment, and SLO-burn elasticity.

All jax-free: the Router core is exercised directly with stub replica
handles — no serve children, no subprocesses.
"""

from __future__ import annotations

import json

import pytest

from llm_training_tpu.serve.journal import RequestJournal, replay_journal
from llm_training_tpu.serve.router import (
    Router,
    fold_replica_journals,
    namespaced_id,
    split_namespaced_id,
)


class _StubHandle:
    """Bare-minimum stand-in for ReplicaHandle (rid/port are all Router reads)."""

    def __init__(self, rid: str, port: int):
        self.rid = rid
        self.port = port


class _Clock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


def _router(clock=None, **kw) -> Router:
    return Router(clock=clock or _Clock(), **kw)


def _add_replicas(router: Router, *specs) -> None:
    for rid, port in specs:
        router.register_replica(_StubHandle(rid, port))


def _snapshot(*entries) -> dict:
    """Build an aggregator-shaped snapshot: entries are (port, healthy,
    stale, metrics)."""
    replicas = {}
    for i, (port, healthy, stale, metrics) in enumerate(entries):
        replicas[f"replica-{i}"] = {
            "port": port,
            "healthy": healthy,
            "stale": stale,
            "metrics": metrics,
        }
    return {"replicas": replicas}


def _intake(router: Router, rid_id: str = "req-0", n: int = 8):
    req = router.intake({"id": rid_id, "prompt": [1, 2], "max_new_tokens": n})
    assert req is not None
    return req


# --------------------------------------------------------------- namespacing


def test_namespaced_id_roundtrip():
    nsid = namespaced_id("r0", "req-0")
    assert nsid == "r0::req-0"
    assert split_namespaced_id(nsid) == ("r0", "req-0")
    # client ids containing "::" split at the FIRST separator (replica ids
    # never contain "::", so the remainder is the verbatim client id)
    assert split_namespaced_id("r1::a::b") == ("r1", "a::b")


def test_fold_replica_journals_namespaces_colliding_ids(tmp_path):
    """The ISSUE case: `req-0` from replica A and replica B must fold
    independently — distinct namespaced ids, distinct watermarks."""
    for rid, toks in (("rA", [10, 11, 12]), ("rB", [20])):
        j = RequestJournal(tmp_path / f"{rid}.jsonl")
        j.delivered("req-0", [1, 2], 8)

        class R:
            id = "req-0"
            generated = toks
            emitted = len(toks)

        j.progress(R)
        j.close()

    folded = fold_replica_journals(
        {"rA": tmp_path / "rA.jsonl", "rB": tmp_path / "rB.jsonl"}
    )
    by_id = {e["id"]: e for e in folded}
    assert set(by_id) == {"rA::req-0", "rB::req-0"}
    assert by_id["rA::req-0"]["client_id"] == "req-0"
    assert by_id["rA::req-0"]["source_replica"] == "rA"
    assert by_id["rA::req-0"]["generated"] == [10, 11, 12]
    assert by_id["rB::req-0"]["generated"] == [20]


def test_fold_replica_journals_skips_torn_tail(tmp_path):
    """A SIGKILL mid-append leaves a torn last line; the fold keeps every
    complete record before it."""
    path = tmp_path / "torn.jsonl"
    j = RequestJournal(path)
    j.delivered("req-0", [1], 8)

    class R:
        id = "req-0"
        generated = [5, 6]
        emitted = 2

    j.progress(R)
    j.close()
    with open(path, "a") as f:
        f.write('{"event": "progress", "id": "req-0", "genera')  # torn
    folded = fold_replica_journals({"rX": path})
    assert len(folded) == 1
    assert folded[0]["id"] == "rX::req-0"
    assert folded[0]["generated"] == [5, 6]


def test_fold_replica_journals_missing_journal_is_empty(tmp_path):
    assert fold_replica_journals({"rZ": tmp_path / "absent.jsonl"}) == []


# ----------------------------------------------------- stream fold / terminals


def test_token_and_done_flow_exactly_once():
    router = _router()
    _add_replicas(router, ("r0", 9001))
    req = _intake(router)
    assert router.assign(req)[0] == "r0"

    ev = router.record_token("r0", {"id": "r0::req-0", "token": 7, "generation": 3})
    assert [e["token"] for e in ev] == [7]
    assert ev[0]["id"] == "req-0"  # de-namespaced for the client
    assert ev[0]["generation"] == 3  # weights generation passes through

    done = router.record_done(
        "r0", {"id": "r0::req-0", "type": "done", "stop_reason": "eos", "generation": 3}
    )
    assert len(done) == 1
    assert done[0]["id"] == "req-0"
    assert done[0]["tokens"] == [7]
    assert done[0]["n_tokens"] == 1
    assert done[0]["replica"] == "r0"
    assert router.stats()["requests_completed"] == 1
    assert router.inflight() == 0


def test_duplicate_terminal_in_death_window_suppressed():
    """Replica emits done, then dies before the router sees EOF; the
    journal fold (or a raced second done) must not produce a second
    terminal."""
    router = _router()
    _add_replicas(router, ("r0", 9001), ("r1", 9002))
    req = _intake(router)
    router.assign(req)
    first = router.record_done(
        "r0", {"id": "r0::req-0", "type": "done", "stop_reason": "eos"}
    )
    assert len(first) == 1
    # raced duplicate done for the same client id → suppressed
    second = router.record_done(
        "r0", {"id": "r0::req-0", "type": "done", "stop_reason": "eos"}
    )
    assert second == []
    assert router.stats()["duplicate_terminals_suppressed"] == 1
    # the death-window fold: the dead replica's journal still lists req-0
    # as unfinished (done chunk emitted but never journaled) — fail_replica
    # must not resurrect an already-terminal request
    folded = [
        {
            "id": "r0::req-0",
            "client_id": "req-0",
            "source_replica": "r0",
            "prompt": [1, 2],
            "generated": [7, 8],
            "emitted": 2,
            "max_new_tokens": 8,
            "priority": 0,
        }
    ]
    result = router.fail_replica("r0", folded)
    assert result["events"] == []
    assert result["orphans"] == []
    # and a replayed client record for the finished id dedupes at intake
    assert router.intake({"id": "req-0", "prompt": [1, 2]}) is None


def test_synthesize_done_is_terminal_and_unique():
    router = _router()
    _add_replicas(router, ("r0", 9001))
    req = _intake(router)
    router.assign(req)
    ev = router.synthesize_done(req, "max_tokens")
    assert len(ev) == 1 and ev[0]["stop_reason"] == "max_tokens"
    assert router.synthesize_done(req, "max_tokens") == []
    assert router.record_done("r0", {"id": "r0::req-0", "stop_reason": "eos"}) == []


# ------------------------------------------------------------------- failover


def test_fail_replica_folds_journal_extension_and_orphans():
    """Dead replica got further than the client saw: the journal watermark
    prefix-extends `generated`, recovered tokens are emitted once, and the
    request is orphaned for resubmission."""
    router = _router()
    _add_replicas(router, ("r0", 9001), ("r1", 9002))
    req = _intake(router)
    router.assign(req)
    router.record_token("r0", {"id": "r0::req-0", "token": 5})
    folded = [
        {
            "id": "r0::req-0",
            "client_id": "req-0",
            "source_replica": "r0",
            "prompt": [1, 2],
            "generated": [5, 6, 7],
            "emitted": 1,
            "max_new_tokens": 8,
            "priority": 0,
        }
    ]
    result = router.fail_replica("r0", folded)
    assert [e["token"] for e in result["events"]] == [6, 7]
    assert [o.id for o in result["orphans"]] == ["req-0"]
    assert req.generated == [5, 6, 7]
    assert req.emitted == 3
    stats = router.stats()
    assert stats["recovered_tokens"] == 2
    assert stats["failovers"] == 1
    # the dead replica is out of rotation: reassignment lands on r1
    req.legs.pop("r0", None)
    assert router.assign(req, exclude=("r0",))[0] == "r1"


def test_fail_replica_divergent_journal_not_folded():
    """A journal watermark that does NOT prefix-extend what the client has
    seen is discarded (greedy decode means agreement; divergence means a
    torn/competing record) — never re-stream different tokens."""
    router = _router()
    _add_replicas(router, ("r0", 9001))
    req = _intake(router)
    router.assign(req)
    router.record_token("r0", {"id": "r0::req-0", "token": 5})
    folded = [
        {
            "id": "r0::req-0",
            "client_id": "req-0",
            "generated": [9, 9, 9],
            "emitted": 3,
        }
    ]
    result = router.fail_replica("r0", folded)
    assert result["events"] == []
    assert req.generated == [5]
    assert [o.id for o in result["orphans"]] == ["req-0"]


def test_fail_replica_adopts_surviving_hedge_leg():
    """Winner dies while a hedge leg holds a superset of the stream: the
    survivor is adopted and only the unseen suffix is emitted."""
    clock = _Clock()
    router = _router(clock=clock, hedge_ttft_ms=10.0)
    _add_replicas(router, ("r0", 9001), ("r1", 9002))
    router.update_fleet(
        _snapshot(
            (9001, True, False, {"llmt_serve_ttft_p99_ms": 500.0}),
            (9002, True, False, {"llmt_serve_queue_depth": 0.0}),
        )
    )
    req = _intake(router)
    router.assign(req)
    clock.t = 1.0  # 1000ms elapsed > 10ms hedge budget
    hedged = router.maybe_hedge(clock.t)
    assert [(r.id, rid) for r, rid in hedged] == [("req-0", "r1")]
    # r0 wins (first token), emits 2; r1 trails with 3 cached (suppressed)
    router.record_token("r0", {"id": "r0::req-0", "token": 1})
    router.record_token("r0", {"id": "r0::req-0", "token": 2})
    for tok in (1, 2, 3):
        assert router.record_token("r1", {"id": "r1::req-0", "token": tok}) == []
    assert req.winner == "r0"
    result = router.fail_replica("r0", [])
    assert [e["token"] for e in result["events"]] == [3]
    assert result["orphans"] == []
    assert req.winner == "r1"
    assert router.stats()["leg_adoptions"] == 1
    # survivor finishes the stream normally
    done = router.record_done("r1", {"id": "r1::req-0", "stop_reason": "eos"})
    assert len(done) == 1 and done[0]["tokens"] == [1, 2, 3]


def test_fail_replica_adopted_leg_with_done_finishes_immediately():
    clock = _Clock()
    router = _router(clock=clock, hedge_ttft_ms=10.0)
    _add_replicas(router, ("r0", 9001), ("r1", 9002))
    router.update_fleet(
        _snapshot(
            (9001, True, False, {"llmt_serve_ttft_p99_ms": 500.0}),
            (9002, True, False, {"llmt_serve_queue_depth": 0.0}),
        )
    )
    req = _intake(router)
    router.assign(req)
    clock.t = 1.0
    router.maybe_hedge(clock.t)
    router.record_token("r0", {"id": "r0::req-0", "token": 1})
    # hedge leg races ahead and even finishes — all suppressed while r0 wins
    router.record_token("r1", {"id": "r1::req-0", "token": 1})
    router.record_token("r1", {"id": "r1::req-0", "token": 2})
    assert (
        router.record_done("r1", {"id": "r1::req-0", "stop_reason": "eos"}) == []
    )
    result = router.fail_replica("r0", [])
    tokens = [e for e in result["events"] if e.get("type") == "token"]
    dones = [e for e in result["events"] if e.get("type") == "done"]
    assert [e["token"] for e in tokens] == [2]
    assert len(dones) == 1 and dones[0]["tokens"] == [1, 2]
    assert router.inflight() == 0
    assert router.stats()["requests_completed"] == 1


# -------------------------------------------------------------------- hedging


def test_hedge_loser_terminal_suppressed_winner_unique():
    """First token wins; the loser's entire stream — including its done —
    is suppressed. Never two terminals."""
    clock = _Clock()
    router = _router(clock=clock, hedge_ttft_ms=10.0)
    _add_replicas(router, ("r0", 9001), ("r1", 9002))
    router.update_fleet(
        _snapshot(
            (9001, True, False, {"llmt_serve_ttft_p99_ms": 500.0}),
            (9002, True, False, {"llmt_serve_queue_depth": 0.0}),
        )
    )
    req = _intake(router)
    router.assign(req)
    clock.t = 1.0
    assert len(router.maybe_hedge(clock.t)) == 1
    # no re-hedge while two legs are open
    assert router.maybe_hedge(clock.t) == []
    # hedge replica answers first → it becomes winner
    ev = router.record_token("r1", {"id": "r1::req-0", "token": 4})
    assert [e["token"] for e in ev] == [4]
    assert router.record_token("r0", {"id": "r0::req-0", "token": 4}) == []
    assert router.record_done("r0", {"id": "r0::req-0", "stop_reason": "eos"}) == []
    done = router.record_done("r1", {"id": "r1::req-0", "stop_reason": "eos"})
    assert len(done) == 1
    stats = router.stats()
    assert stats["hedges"] == 1
    assert stats["hedge_wins"] == 1
    assert stats["requests_completed"] == 1
    assert stats["duplicate_terminals_suppressed"] == 0


def test_hedge_requires_idle_candidate():
    clock = _Clock()
    router = _router(clock=clock, hedge_ttft_ms=10.0)
    _add_replicas(router, ("r0", 9001), ("r1", 9002))
    router.update_fleet(
        _snapshot(
            (9001, True, False, {"llmt_serve_ttft_p99_ms": 500.0}),
            (9002, True, False, {"llmt_serve_queue_depth": 3.0}),
        )
    )
    req = _intake(router)
    router.assign(req)
    clock.t = 1.0
    assert router.maybe_hedge(clock.t) == []  # r1 busy → no hedge


# ------------------------------------------------- health / eviction / routing


def test_update_fleet_evicts_red_and_stale_then_restores():
    router = _router()
    _add_replicas(router, ("r0", 9001), ("r1", 9002))
    evicted = router.update_fleet(
        _snapshot((9001, False, False, {}), (9002, True, True, {}))
    )
    assert sorted(evicted) == ["r0", "r1"]
    req = _intake(router)
    assert router.assign(req) is None  # nothing in rotation
    # recovery un-evicts without double-counting
    assert router.update_fleet(
        _snapshot((9001, True, False, {}), (9002, True, False, {}))
    ) == []
    assert router.assign(req) is not None
    assert router.stats()["evictions"] == 2


def test_assign_least_loaded_uses_scrape_and_intra_scrape_delta():
    router = _router()
    _add_replicas(router, ("r0", 9001), ("r1", 9002))
    router.update_fleet(
        _snapshot(
            (9001, True, False, {"llmt_serve_queue_depth": 4.0, "llmt_serve_running": 1.0}),
            (9002, True, False, {"llmt_serve_queue_depth": 0.0, "llmt_serve_running": 1.0}),
        )
    )
    picks = []
    for i in range(5):
        req = _intake(router, f"req-{i}")
        picks.append(router.assign(req)[0])
    # r1 soaks the first 4 (scraped load 1 vs 5), then the intra-scrape
    # delta tips the 5th to r0
    assert picks == ["r1", "r1", "r1", "r1", "r0"]


# --------------------------------------------------------------- router journal


def test_router_journal_roundtrip_resume(tmp_path):
    """Router dies mid-stream; its own journal folds back into a resumable
    entry whose watermark resumes without re-streaming."""
    path = tmp_path / "router-journal.jsonl"
    journal = RequestJournal(path)
    router = _router()
    router.journal = journal
    _add_replicas(router, ("r0", 9001))
    req = _intake(router)
    router.assign(req)
    router.record_token("r0", {"id": "r0::req-0", "token": 5})
    router.record_token("r0", {"id": "r0::req-0", "token": 6})
    journal.close()  # simulate router death (no done journaled)

    entries = replay_journal(path)
    assert len(entries) == 1
    assert entries[0]["generated"] == [5, 6]
    assert entries[0]["emitted"] == 2

    incarnation2 = _router()
    resumed = incarnation2.resume(entries[0])
    assert resumed.emitted == 2
    assert resumed.generated == [5, 6]
    assert resumed.replays == 1
    assert incarnation2.stats()["resumed"] == 1


def test_router_journal_done_drops_entry(tmp_path):
    path = tmp_path / "router-journal.jsonl"
    journal = RequestJournal(path)
    router = _router()
    router.journal = journal
    _add_replicas(router, ("r0", 9001))
    req = _intake(router)
    router.assign(req)
    router.record_token("r0", {"id": "r0::req-0", "token": 5})
    router.record_done("r0", {"id": "r0::req-0", "stop_reason": "eos"})
    journal.close()
    assert replay_journal(path) == []
    # assignment notes ride the stream without affecting the fold
    events = [json.loads(l)["event"] for l in path.read_text().splitlines()]
    assert "assigned" in events


# ----------------------------------------------------------------- elasticity


def test_scale_decision_out_on_burn_in_on_idle():
    clock = _Clock(100.0)
    router = _router(
        clock=clock,
        min_replicas=1,
        max_replicas=3,
        scale_cooldown_s=5.0,
        idle_retire_s=10.0,
    )
    _add_replicas(router, ("r0", 9001))
    # sustained burn → scale out (once per cooldown)
    assert router.scale_decision(100.0, breaches=1) == ("out", None)
    _add_replicas(router, ("r1", 9002))
    assert router.scale_decision(101.0, breaches=2) is None  # cooldown
    assert router.scale_decision(106.0, breaches=2) == ("out", None)
    _add_replicas(router, ("r2", 9003))
    assert router.target() == 3
    # traffic at t=112 re-arms the idle clock
    req = _intake(router)
    clock.t = 112.0
    router.assign(req)
    router.record_done("r0", {"id": "r0::req-0", "stop_reason": "eos"})
    # steady breach count (not growing), not yet idle long enough → hold
    assert router.scale_decision(115.0, breaches=2) is None
    # idle → retire the youngest ordinal, down to min_replicas
    decision = router.scale_decision(130.0, breaches=2)
    assert decision == ("in", "r2")
    router.retire_replica("r2")
    assert router.scale_decision(140.0, breaches=2) == ("in", "r1")
    router.retire_replica("r1")
    assert router.scale_decision(150.0, breaches=2) is None  # at floor
    stats = router.stats()
    assert stats["scale_out_total"] == 2
    assert stats["scale_in_total"] == 2


def test_scale_in_blocked_by_inflight_traffic():
    clock = _Clock(0.0)
    router = _router(clock=clock, min_replicas=1, max_replicas=2,
                     scale_cooldown_s=0.0, idle_retire_s=5.0)
    _add_replicas(router, ("r0", 9001), ("r1", 9002))
    req = _intake(router)
    clock.t = 1.0
    router.assign(req)  # traffic at t=1, in flight
    assert router.scale_decision(20.0, breaches=0) is None  # inflight != 0
    router.record_done("r0", {"id": "r0::req-0", "stop_reason": "eos"})
    assert router.scale_decision(20.0, breaches=0) == ("in", "r1")


# -------------------------------------------------------------- observability


def test_live_stats_shape_and_prefix():
    router = _router()
    _add_replicas(router, ("r0", 9001))
    req = _intake(router)
    router.assign(req)
    live = router.live_stats()
    assert live["router/replicas"] == 1.0
    assert live["router/inflight"] == 1.0
    assert live["router/requests_total"] == 1.0
    assert all(k.startswith("router/") for k in live)
    flat = router.stats()
    assert flat["requests_total"] == 1
    assert not any(k.startswith("router/") for k in flat)


def test_intake_dedupes_inflight_ids():
    # dedupe keys off registered requests: intake alone doesn't register
    # (the runtime assigns or parks immediately after), so assign first
    router = _router()
    _add_replicas(router, ("r0", 9001))
    req = _intake(router)
    router.assign(req)
    assert router.intake({"id": "req-0", "prompt": [1]}) is None
    assert router.stats()["duplicate_requests"] == 1


# ------------------------------------------------------------------ chaos env


def test_chaos_router_hooks_parse_and_fire_once(monkeypatch):
    from llm_training_tpu.resilience.chaos import ChaosConfig, config_from_env

    monkeypatch.setenv("LLMT_CHAOS_ROUTER_KILL_REPLICA", "3")
    monkeypatch.setenv("LLMT_CHAOS_ROUTER_BLACKHOLE", "2")
    cfg = config_from_env()
    assert cfg.router_kill_replica_at == 3
    assert cfg.router_blackhole_at == 2
    assert cfg.any_active()

    from llm_training_tpu.resilience.chaos import Chaos

    chaos = Chaos(cfg)
    assert not chaos.maybe_router_kill_replica(2)
    assert chaos.maybe_router_kill_replica(3)
    assert not chaos.maybe_router_kill_replica(4)  # fire-once
    assert not chaos.maybe_router_blackhole(1)
    assert chaos.maybe_router_blackhole(2)
    assert not chaos.maybe_router_blackhole(2)  # fire-once

    inert = Chaos(ChaosConfig())
    assert not inert.maybe_router_kill_replica(10**6)
    assert not inert.maybe_router_blackhole(1)
