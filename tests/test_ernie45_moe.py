"""Ernie 4.5 MoE: aux-free softmax routing + interleaved rope, HF parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_training_tpu.models.ernie45_moe import Ernie45Moe, Ernie45MoeConfig
from llm_training_tpu.models.ernie45_moe.hf_conversion import (
    config_from_hf,
    config_to_hf,
    params_from_hf,
    params_to_hf,
)

TINY = dict(
    vocab_size=128,
    hidden_size=64,
    intermediate_size=112,
    moe_intermediate_size=32,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    head_dim=16,
    max_position_embeddings=64,
    moe_num_experts=8,
    moe_k=2,
    moe_num_shared_experts=1,
    moe_layer_start_index=1,
    use_bias=True,
    tie_word_embeddings=True,
    compute_dtype="float32",
)


def _hf_tiny(**extra):
    torch = pytest.importorskip("torch")
    from transformers import Ernie4_5_MoeConfig as HFConfig
    from transformers import Ernie4_5_MoeForCausalLM

    kwargs = dict(TINY)
    kwargs.pop("compute_dtype")
    kwargs.update(attn_implementation="eager", **extra)
    hf_config = HFConfig(**kwargs)
    torch.manual_seed(0)
    return Ernie4_5_MoeForCausalLM(hf_config).eval(), hf_config


def test_logits_parity_with_hf():
    """Softmax router with a LIVE aux-free selection bias (biasing selection
    only, not the combine weights), gate-free shared expert, dense prefix,
    interleaved rope, use_bias over q/k/v/o."""
    torch = pytest.importorskip("torch")
    hf_model, hf_config = _hf_tiny()
    sd = hf_model.state_dict()
    assert "model.layers.1.mlp.moe_statics.e_score_correction_bias" in sd
    assert "model.layers.0.mlp.gate_proj.weight" in sd  # dense prefix
    assert "model.layers.0.self_attn.o_proj.bias" in sd  # use_bias covers o
    assert "model.layers.1.mlp.shared_experts.gate_proj.weight" in sd
    with torch.no_grad():
        sd["model.layers.1.mlp.moe_statics.e_score_correction_bias"].copy_(
            torch.linspace(-0.2, 0.2, 8).reshape(1, -1)
        )

    cfg = config_from_hf(hf_config, compute_dtype="float32", moe_impl="dense")
    assert not cfg.layer_is_moe(0) and cfg.layer_is_moe(1)
    params = params_from_hf(sd, cfg)
    model = Ernie45Moe(cfg)

    ids = np.random.default_rng(96).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=4e-4, atol=4e-4)


def test_hf_round_trip():
    hf_model, hf_config = _hf_tiny()
    cfg = config_from_hf(hf_config)
    params = params_from_hf(hf_model.state_dict(), cfg)
    back = params_to_hf(params, cfg)
    sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    assert set(back) == set(sd)
    for key in sd:
        np.testing.assert_array_equal(back[key], sd[key], err_msg=key)


def test_config_round_trip():
    cfg = Ernie45MoeConfig(**TINY)
    hf = config_to_hf(cfg)
    assert hf["model_type"] == "ernie4_5_moe"
    cfg2 = config_from_hf(hf, compute_dtype="float32")
    assert cfg2.model_dump() == cfg.model_dump()


@pytest.mark.slow
def test_e2e_fit_decreases_loss():
    from conftest import fit_losses

    losses = fit_losses(
        "llm_training_tpu.models.Ernie45Moe",
        dict(TINY, enable_gradient_checkpointing=True, moe_impl="dense"),
        max_steps=20, lr=3e-3,
    )
    assert np.mean(losses[-3:]) < np.mean(losses[:3])


def test_clm_fused_loss_applies_tied_head_bias():
    """The fused-CE path must add the standalone lm_head bias that rides on
    TIED embeddings (the sibling-bias heuristic cannot see it)."""
    from llm_training_tpu.lms import CLM, CLMConfig

    cfg = Ernie45MoeConfig(**TINY)
    model = Ernie45Moe(cfg)
    ids = jnp.asarray(np.random.default_rng(97).integers(1, 128, (2, 16)))
    params = model.init(jax.random.key(14), ids)
    # salt the zero-init head bias so it is LIVE
    import flax.linen as fnn
    leaf = params["params"]["lm_head_bias"]
    noise = jnp.asarray(np.random.default_rng(98).normal(0, 0.5, 128), jnp.float32)
    params["params"]["lm_head_bias"] = (
        leaf.replace_boxed(noise) if isinstance(leaf, fnn.Partitioned) else noise
    )

    objective = CLM(CLMConfig(), model=model)
    loss, _ = objective.loss_and_metrics(params, {"input_ids": ids}, train=False)

    logits = model.apply(params, ids).logits
    shifted = np.full(ids.shape, -100)
    shifted[:, :-1] = np.asarray(ids)[:, 1:]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    rows = [
        -logp[b, t, shifted[b, t]]
        for b in range(ids.shape[0]) for t in range(ids.shape[1] - 1)
    ]
    np.testing.assert_allclose(float(loss), np.mean(rows), rtol=1e-5)
