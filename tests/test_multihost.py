"""Multi-host wiring tests — no hardware required.

`initialize_distributed` (parallel/mesh.py) parses SLURM/coordinator/TPU-pod
env and decides fatal-vs-continue; `scripts/train_tpu_pod.sh` composes the
per-launcher command line. Both are exercised here via env matrices and the
script's --dry-run flag (reference analogue: the NCCL rendezvous in
`fsdp2_strategy.py:411-428` + `scripts/train.sh`).
"""

import os
import stat
import subprocess
from pathlib import Path

import pytest

from llm_training_tpu.parallel import mesh as mesh_mod
from llm_training_tpu.parallel.mesh import (
    _multi_host_intended,
    initialize_distributed,
)

REPO = Path(__file__).resolve().parent.parent
POD_SCRIPT = REPO / "scripts" / "train_tpu_pod.sh"

_DIST_ENV = (
    "JAX_COORDINATOR_ADDRESS",
    "SLURM_NTASKS",
    "SLURM_PROCID",
    "SLURM_JOB_ID",
    "SLURM_JOB_NODELIST",
    "TPU_WORKER_HOSTNAMES",
)


@pytest.fixture
def clean_env(monkeypatch):
    for key in _DIST_ENV:
        monkeypatch.delenv(key, raising=False)
    monkeypatch.setattr(mesh_mod, "_distributed_initialized", False)
    return monkeypatch


class _InitRecorder:
    def __init__(self, fail=False):
        self.calls = []
        self.fail = fail

    def __call__(self, **kwargs):
        self.calls.append(kwargs)
        if self.fail:
            raise RuntimeError("backend already created")


# ------------------------------------------------------------ intent matrix


def test_single_process_not_multi_host(clean_env):
    assert not _multi_host_intended(None)


@pytest.mark.parametrize(
    "env,value",
    [
        ("JAX_COORDINATOR_ADDRESS", "10.0.0.1:1234"),
        ("SLURM_NTASKS", "16"),
        ("TPU_WORKER_HOSTNAMES", "host-0,host-1"),
    ],
)
def test_multi_host_intent_from_env(clean_env, env, value):
    clean_env.setenv(env, value)
    assert _multi_host_intended(None)


def test_multi_host_intent_from_arg(clean_env):
    assert _multi_host_intended("10.0.0.1:1234")


def test_single_worker_pod_not_multi_host(clean_env):
    clean_env.setenv("TPU_WORKER_HOSTNAMES", "host-0")  # one host, no comma
    assert not _multi_host_intended(None)


def test_slurm_single_task_not_multi_host(clean_env):
    clean_env.setenv("SLURM_NTASKS", "1")
    assert not _multi_host_intended(None)


# ------------------------------------------------- initialize_distributed


def test_slurm_env_composes_coordinates(clean_env):
    clean_env.setenv("JAX_COORDINATOR_ADDRESS", "head-node:12345")
    clean_env.setenv("SLURM_NTASKS", "16")
    clean_env.setenv("SLURM_PROCID", "3")
    rec = _InitRecorder()
    clean_env.setattr(mesh_mod.jax.distributed, "initialize", rec)
    initialize_distributed()
    assert rec.calls == [
        dict(coordinator_address="head-node:12345", num_processes=16, process_id=3)
    ]


def test_explicit_args_override_env(clean_env):
    clean_env.setenv("JAX_COORDINATOR_ADDRESS", "stale:1")
    clean_env.setenv("SLURM_NTASKS", "2")
    rec = _InitRecorder()
    clean_env.setattr(mesh_mod.jax.distributed, "initialize", rec)
    initialize_distributed(
        coordinator_address="fresh:9", num_processes=4, process_id=1
    )
    assert rec.calls == [
        dict(coordinator_address="fresh:9", num_processes=4, process_id=1)
    ]


def test_self_discovery_when_no_env(clean_env):
    rec = _InitRecorder()
    clean_env.setattr(mesh_mod.jax.distributed, "initialize", rec)
    initialize_distributed()
    assert rec.calls == [{}]  # TPU-pod metadata self-discovery path


def test_failure_fatal_when_multi_host_intended(clean_env):
    clean_env.setenv("JAX_COORDINATOR_ADDRESS", "head-node:12345")
    clean_env.setenv("SLURM_NTASKS", "16")
    clean_env.setattr(mesh_mod.jax.distributed, "initialize", _InitRecorder(fail=True))
    with pytest.raises(RuntimeError, match="multi-host run detected"):
        initialize_distributed()


def test_failure_tolerated_single_process(clean_env):
    clean_env.setattr(mesh_mod.jax.distributed, "initialize", _InitRecorder(fail=True))
    initialize_distributed()  # logs and continues


def test_idempotent(clean_env):
    rec = _InitRecorder()
    clean_env.setattr(mesh_mod.jax.distributed, "initialize", rec)
    initialize_distributed()
    initialize_distributed()
    assert len(rec.calls) == 1


# ------------------------------------------------------- pod launcher script


def _run_script(args, env_extra=None, path_prepend=None):
    env = {k: v for k, v in os.environ.items() if k not in _DIST_ENV}
    env.update(env_extra or {})
    if path_prepend:
        env["PATH"] = f"{path_prepend}:{env.get('PATH', '')}"
    return subprocess.run(
        ["bash", str(POD_SCRIPT), "--dry-run", *args],
        capture_output=True, text=True, env=env, cwd=str(REPO), timeout=30,
    )


def test_pod_script_single_host(tmp_path):
    proc = _run_script(["fit", "--config", "cfg.yaml"])
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.strip() == "python -m llm_training_tpu fit --config cfg.yaml"


def test_pod_script_gcloud_quotes_args():
    proc = _run_script(
        ["--tpu-name", "my-pod", "--zone", "us-east5-a",
         "fit", "--config", "a config.yaml"]  # space must survive quoting
    )
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout.strip()
    assert out.startswith("gcloud compute tpus tpu-vm ssh my-pod --zone us-east5-a")
    assert "--worker=all" in out
    # the remote command is %q-quoted and the dry-run printer %q-quotes it
    # again, so the embedded space appears double-escaped: a\\\ config.yaml
    assert "a\\\\\\ config.yaml" in out


def test_pod_script_slurm_composes_srun(tmp_path):
    # fake scontrol so the head-node lookup works without SLURM installed
    scontrol = tmp_path / "scontrol"
    scontrol.write_text("#!/bin/sh\necho head-node\necho other-node\n")
    scontrol.chmod(scontrol.stat().st_mode | stat.S_IEXEC)
    proc = _run_script(
        ["fit", "--config", "cfg.yaml"],
        env_extra={"SLURM_JOB_ID": "99", "SLURM_JOB_NODELIST": "nodes[0-1]"},
        path_prepend=str(tmp_path),
    )
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.strip() == (
        "srun --ntasks-per-node=1 python -m llm_training_tpu fit --config cfg.yaml"
    )


@pytest.mark.parametrize(
    "preset,expected",
    [
        ("keep:1", "keep:1"),  # existing coordinator must not be overwritten
        (None, "head-node:12345"),  # otherwise derived from the nodelist head
    ],
)
def test_pod_script_slurm_coordinator(tmp_path, preset, expected):
    scontrol = tmp_path / "scontrol"
    scontrol.write_text("#!/bin/sh\necho head-node\n")
    scontrol.chmod(scontrol.stat().st_mode | stat.S_IEXEC)
    env_extra = {"SLURM_JOB_ID": "1", "SLURM_JOB_NODELIST": "n"}
    if preset:
        env_extra["JAX_COORDINATOR_ADDRESS"] = preset
    proc = _run_script(
        ["fit"], env_extra=env_extra, path_prepend=str(tmp_path)
    )
    assert proc.returncode == 0, proc.stderr
    assert "srun --ntasks-per-node=1" in proc.stdout
    # the dry-run prints the env the launched command would see
    assert f"JAX_COORDINATOR_ADDRESS={expected}" in proc.stderr
