"""Data pipeline: packing invariants, chat-template masks, collators."""

import numpy as np
import pytest

from data_fixtures import chat_dataset, preference_dataset, text_dataset, tiny_tokenizer
from llm_training_tpu.data.chat_templates import available_chat_templates, get_chat_template
from llm_training_tpu.data.instruction_tuning import (
    InstructionTuningDataModule,
    InstructionTuningDataModuleConfig,
)
from llm_training_tpu.data.pre_training import (
    PackingMethod,
    PreTrainingDataModule,
    PreTrainingDataModuleConfig,
)
from llm_training_tpu.data.pre_training.datamodule import best_fit_bin_packing
from llm_training_tpu.data.preference_tuning import (
    PreferenceTuningDataModule,
    PreferenceTuningDataModuleConfig,
)


def _pt_module(**kwargs):
    kwargs = {"max_length": 32, **kwargs}
    module = PreTrainingDataModule(
        PreTrainingDataModuleConfig(
            tokenizer=tiny_tokenizer(),
            batch_size=2,
            enable_cache=False,
            **kwargs,
        )
    )
    module.load_data = lambda: text_dataset()
    return module


@pytest.mark.parametrize("method", [PackingMethod.NAIVE_PACKING, PackingMethod.BEST_FIT_BIN_PACKING])
def test_pre_training_packing_invariants(method):
    module = _pt_module(packing_method=method)
    module.setup()
    tokenizer = tiny_tokenizer()
    all_tokens = 0
    for row in module.train_dataset:
        ids = row["input_ids"]
        segs = row["segment_ids"]
        assert len(ids) <= 32
        assert len(ids) == len(segs) == row["length"]
        # segment ids are 1..N contiguous non-decreasing... for naive packing
        # they may start mid-document but are renumbered to start at 1
        assert segs[0] == 1
        assert all(b - a in (0, 1) for a, b in zip(segs, segs[1:]))
        all_tokens += len(ids)
        if method == PackingMethod.BEST_FIT_BIN_PACKING:
            # documents never span rows: every segment begins with BOS
            starts = [0] + [i for i in range(1, len(segs)) if segs[i] != segs[i - 1]]
            for s in starts:
                assert ids[s] == tokenizer.bos_token_id
    # token conservation: every tokenized token lands in exactly one row
    expected = 0
    for row in text_dataset()["train"]:
        if row["text"]:
            expected += len(tokenizer(row["text"])["input_ids"]) + 2  # +BOS+EOS
    assert all_tokens == expected


def test_pre_training_sources_not_mixed():
    module = _pt_module(packing_method=PackingMethod.BEST_FIT_BIN_PACKING)
    module.setup()
    # each packed row carries a single source
    assert set(module.train_dataset["source"]) == {"wiki", "code"}


def test_pre_training_sample_rate():
    base = _pt_module()
    base.setup()
    wiki_rows = sum(1 for s in base.train_dataset["source"] if s == "wiki")
    code_rows = sum(1 for s in base.train_dataset["source"] if s == "code")

    module = _pt_module(sample_rate={"wiki": 2.5, "code": 1.0})
    module.setup()
    wiki_sampled = sum(1 for s in module.train_dataset["source"] if s == "wiki")
    code_sampled = sum(1 for s in module.train_dataset["source"] if s == "code")
    assert code_sampled == code_rows
    assert wiki_sampled == 2 * wiki_rows + int(wiki_rows * 0.5)


def test_pre_training_stride():
    module = _pt_module(max_length=16, stride=8, packing_method=PackingMethod.NO_PACKING)
    module.setup()
    assert all(row["length"] <= 16 for row in module.train_dataset)


def test_pre_training_collator():
    module = _pt_module()
    module.setup()
    batch = module.collate([module.train_dataset[0], module.train_dataset[1]])
    assert batch["input_ids"].shape == batch["labels"].shape == batch["segment_ids"].shape
    tokenizer = tiny_tokenizer()
    # BOS and padding are masked in labels
    assert (batch["labels"][batch["input_ids"] == tokenizer.bos_token_id] == -100).all()
    assert (batch["labels"][batch["segment_ids"] == 0] == -100).all()


def test_tokens_table():
    module = _pt_module()
    module.setup()
    table = module.tokens_table()
    assert "wiki" in table and "code" in table and "*" in table


# ---------------------------------------------------------------- bin packing


def test_best_fit_bin_packing_properties():
    lengths = [10, 9, 8, 7, 2, 2, 1]
    groups = best_fit_bin_packing(10, lengths)
    # all items placed exactly once
    placed = sorted(i for g in groups for i in g)
    assert placed == list(range(len(lengths)))
    for g in groups:
        assert sum(lengths[i] for i in g) <= 10
    # best-fit on sorted-desc input: [10], [9,1], [8,2], [7,2]
    assert len(groups) == 4


# ---------------------------------------------------------------- instruction


def _it_module(**kwargs):
    module = InstructionTuningDataModule(
        InstructionTuningDataModuleConfig(
            tokenizer=tiny_tokenizer(),
            chat_template="chatml",
            batch_size=2,
            enable_cache=False,
            **kwargs,
        )
    )
    module.load_data = lambda: chat_dataset()
    return module


def test_instruction_tuning_assistant_masks():
    module = _it_module()
    module.setup()
    tokenizer = tiny_tokenizer()
    for row in module.train_dataset:
        labels = np.asarray(row["labels"])
        ids = np.asarray(row["input_ids"])
        assert (labels != -100).any() and (labels == -100).any()
        # labeled positions reproduce the assistant text + <|im_end|>
        text = tokenizer.decode(ids[labels != -100])
        assert "<|im_end|>" in text
        assert "<|im_start|>" not in text  # prompt tokens never labeled


def test_instruction_tuning_group_by_length_packing():
    module = _it_module(max_length=64, packing_method="group_by_length")
    module.setup()
    for row in module.train_dataset:
        assert row["length"] <= 64
        segs = np.asarray(row["segment_ids"])
        assert segs[0] == 1
    # packing reduced the row count below the example count
    assert len(module.train_dataset) < len(chat_dataset()["train"])


def test_instruction_tuning_collator_positions_restart():
    module = _it_module(max_length=64, packing_method="group_by_length")
    module.setup()
    batch = module.collate([module.train_dataset[0]])
    segs = batch["segment_ids"][0]
    positions = batch["position_ids"][0]
    for seg in np.unique(segs[segs > 0]):
        assert positions[segs == seg][0] == 0


def test_instruction_tuning_overlong_drop_vs_truncate():
    drop = _it_module(max_length=24, overlong_handling_method="drop")
    drop.setup()
    truncate = _it_module(max_length=24, overlong_handling_method="truncate")
    truncate.setup()
    assert all(r["length"] <= 24 for r in drop.train_dataset)
    assert all(r["length"] <= 24 for r in truncate.train_dataset)
    assert len(truncate.train_dataset) >= len(drop.train_dataset)


def test_default_system_prompt_injection():
    injected = _it_module(
        add_default_system_prompt_rate=1.0,
        default_system_prompt="be helpful and kind to every user always",
    )
    injected.setup()
    plain = _it_module()
    plain.setup()
    # rate=1.0 -> every example gains the system-prompt tokens
    for with_sys, without in zip(injected.train_dataset, plain.train_dataset):
        assert with_sys["length"] > without["length"]

    # rate=0.0 -> nothing injected
    none = _it_module(add_default_system_prompt_rate=0.0,
                      default_system_prompt="be helpful and kind to every user always")
    none.setup()
    for with_sys, without in zip(none.train_dataset, plain.train_dataset):
        assert with_sys["length"] == without["length"]


# ---------------------------------------------------------------- preference


def test_preference_tuning_pairs():
    module = PreferenceTuningDataModule(
        PreferenceTuningDataModuleConfig(
            tokenizer=tiny_tokenizer(),
            chat_template="chatml",
            batch_size=2,
            max_length=64,
            enable_cache=False,
        )
    )
    module.load_data = lambda: preference_dataset()
    module.setup()
    row = module.train_dataset[0]
    assert row["chosen_length"] == len(row["chosen_input_ids"])
    batch = module.collate([module.train_dataset[0], module.train_dataset[1]])
    assert batch["chosen_input_ids"].shape == batch["rejected_input_ids"].shape
    assert (batch["chosen_labels"] != -100).any()


# ---------------------------------------------------------------- templates


def test_all_templates_render_with_masks():
    tokenizer = tiny_tokenizer()
    messages = [
        {"role": "user", "content": "hello world"},
        {"role": "assistant", "content": "how are you"},
    ]
    assert len(available_chat_templates()) == 9
    for name in available_chat_templates():
        if name == "gemma":
            continue  # needs no system; fine here, but keep loop uniform
        out = tokenizer.apply_chat_template(
            messages,
            chat_template=get_chat_template(name),
            return_dict=True,
            return_assistant_tokens_mask=True,
        )
        mask = np.asarray(out["assistant_masks"])
        assert mask.sum() > 0, name
        text = tokenizer.decode(np.asarray(out["input_ids"])[mask == 1])
        assert "how are you" in text, name


def test_gemma_template_rejects_system():
    tokenizer = tiny_tokenizer()
    with pytest.raises(Exception):
        tokenizer.apply_chat_template(
            [{"role": "system", "content": "x"}, {"role": "user", "content": "y"}],
            chat_template=get_chat_template("gemma"),
        )


def test_unknown_template_raises():
    with pytest.raises(ValueError, match="unknown chat template"):
        get_chat_template("nope")
