"""70B-scale readiness on the virtual mesh (VERDICT r4 missing #3).

The reference reaches 70B through DeepSpeed ZeRO-3
(`lightning/strategy/deepspeed/deepspeed_strategy.py:16`); here the same
scale story is GSPMD fsdp x tensor sharding. Real 70B hardware is not
available in CI, so the proof is split:

- AOT-compile one FULL train step at the exact Llama-3-70B geometry
  (h8192 / i28672 / 80 scanned layers / 64q+8kv / vocab 128256 / seq 8192)
  on the 8-way CPU mesh and check `memory_analysis()` against a v5p-128
  HBM budget (per-chip bytes: sharded state scales with mesh size, per-chip
  activations stay constant at fixed per-chip batch).
- Stream HF weights at true 70B PER-TENSOR shapes (depth cut to 2 layers so
  CI fits in host RAM) through `models/hf_io.load_pretrained_params` into
  sharded fp32-master buffers, asserting the storage-dtype placement +
  on-device widening path and that every leaf lands sharded.

Numbers recorded in BASELINE.md ("70B readiness").
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from llm_training_tpu.models import Llama, LlamaConfig
from llm_training_tpu.parallel.mesh import MeshConfig, build_mesh

V5P_HBM_BYTES = 95e9  # per chip
V5P_CHIPS = 128

LLAMA_3_70B = dict(
    vocab_size=128256,
    hidden_size=8192,
    intermediate_size=28672,
    num_attention_heads=64,
    num_key_value_heads=8,
    head_dim=128,
    rms_norm_eps=1e-5,
    rope_theta=500000.0,
    max_position_embeddings=8192,
)


@pytest.fixture()
def mesh_4x2(devices):
    return build_mesh(MeshConfig(fsdp_size=4, tensor_parallel_size=2))


def _compile_70b_step(mesh_config, batch: int, seq: int,
                      extra_model_kwargs=None):
    """AOT-compile (never execute) one jitted 70B train step; returns the
    per-device CompiledMemoryStats (probed: XLA CPU reports argument/temp
    sizes per device)."""
    import flax.linen as nn

    from llm_training_tpu.lms import CLM, CLMConfig, ModelProvider
    from llm_training_tpu.optim import OptimConfig
    from llm_training_tpu.optim.builder import build_optimizer
    from llm_training_tpu.trainer.trainer import (
        LOGICAL_AXIS_RULES,
        Trainer,
        TrainerConfig,
        _batch_shardings,
    )

    objective = CLM(
        CLMConfig(
            model=ModelProvider(
                model_class="llm_training_tpu.models.Llama",
                model_kwargs=dict(
                    **LLAMA_3_70B,
                    num_hidden_layers=80,
                    scan_layers=True,
                    enable_gradient_checkpointing=True,
                    recompute_granularity="selective",
                    **(extra_model_kwargs or {}),
                ),
            ),
            optim=OptimConfig(learning_rate=1e-4, warmup_steps=10),
            ce_chunk_size=2048,
        )
    )
    trainer = Trainer(TrainerConfig(mesh=mesh_config))
    mesh = build_mesh(mesh_config)
    trainer.mesh = mesh
    tx, _ = build_optimizer(objective.config.optim, num_total_steps=100)
    keys = ("input_ids", "labels", "segment_ids", "position_ids")
    sample_batch = {k: np.zeros((batch, seq), np.int32) for k in keys}
    abstract_batch = {
        k: jax.ShapeDtypeStruct((batch, seq), jnp.int32) for k in keys
    }

    with mesh, nn.logical_axis_rules(LOGICAL_AXIS_RULES):
        abstract_boxed = trainer._abstract_state(objective, sample_batch, tx)
        trainer.state_shardings = trainer._state_shardings(abstract_boxed)
        abstract_state = nn.meta.unbox(abstract_boxed)
        batch_shardings = _batch_shardings(sample_batch, mesh)

        n_params = sum(
            int(np.prod(l.shape)) for l in jax.tree.leaves(abstract_state.params)
        )
        assert 69e9 < n_params < 72e9, f"not 70B geometry: {n_params/1e9:.1f}B"

        step = jax.jit(
            trainer._build_step(objective, tx),
            in_shardings=(trainer.state_shardings, batch_shardings),
            out_shardings=(trainer.state_shardings, None),
            donate_argnums=0,
        )
        compiled = step.lower(abstract_state, abstract_batch).compile()
    ma = compiled.memory_analysis()
    assert ma is not None
    return ma, abstract_state


@pytest.mark.slow
def test_70b_train_step_aot_fits_v5p128(devices):
    """Compile the full 70B step at per-device batch 1 AND 2 on the 8-way
    mesh, split per-device temp into a param-proportional part (grads +
    optimizer temporaries — shards with the mesh, x8/128 on v5p-128) and a
    per-sequence activation part (constant at fixed per-chip batch), then
    assert the v5p-128 per-chip estimate fits HBM."""
    seq = 8192
    cfg = MeshConfig(fsdp_size=4, tensor_parallel_size=2)
    ma1, _ = _compile_70b_step(cfg, batch=4, seq=seq)   # 1 seq / device
    ma2, _ = _compile_70b_step(cfg, batch=8, seq=seq)   # 2 seq / device

    t1, t2 = ma1.temp_size_in_bytes, ma2.temp_size_in_bytes
    act_per_seq = max(0, t2 - t1)        # per-device, per extra sequence
    param_temp = max(0, t1 - act_per_seq)  # per-device at 8-way
    # state (params + mu + nu fp32) lives in args, fully sharded; this
    # config keeps everything in device memory (no optimizer offload)
    assert ma1.host_argument_size_in_bytes == 0
    sharded = ma1.argument_size_in_bytes + max(
        0, ma1.output_size_in_bytes - ma1.alias_size_in_bytes
    )
    n_dev = 8
    per_chip_128 = (
        (sharded + param_temp) * n_dev / V5P_CHIPS + act_per_seq  # 1 seq/chip
    )
    budget = 0.9 * V5P_HBM_BYTES  # 10% headroom for fragmentation/runtime
    assert per_chip_128 < budget, (
        f"estimated v5p-128 per-chip bytes {per_chip_128/1e9:.1f}G exceeds "
        f"{budget/1e9:.1f}G (args {ma1.argument_size_in_bytes/1e9:.1f}G, "
        f"temp {t1/1e9:.1f}G = param {param_temp/1e9:.1f}G + "
        f"act/seq {act_per_seq/1e9:.1f}G on the 8-way mesh)"
    )
    print(
        f"70B step@8way/dev: args {ma1.argument_size_in_bytes/1e9:.1f}G, "
        f"temp {t1/1e9:.1f}G (param-prop {param_temp/1e9:.1f}G + "
        f"act/seq {act_per_seq/1e9:.1f}G); "
        f"est v5p-128 per-chip {per_chip_128/1e9:.1f}G of {V5P_HBM_BYTES/1e9:.0f}G"
    )


@pytest.mark.slow
def test_70b_pipeline_step_compiles(devices):
    """The 70B geometry also compiles as a GPipe pipeline (pipe 2 x fsdp 2
    x tensor 2): 80 scanned layers become 2 vmapped stages of 40, the tick
    loop traces, GSPMD accepts the stage-sharded buffers, and the stage
    stacks report the [2, 40, ...] layout. Compile-only, like the fsdp
    readiness proof — PP hardware runs need a pod."""
    ma, abstract_state = _compile_70b_step(
        MeshConfig(pipeline_parallel_size=2, fsdp_size=2, tensor_parallel_size=2),
        batch=8, seq=8192,
        extra_model_kwargs=dict(pipeline_stages=2, pipeline_microbatches=4),
    )
    # the stage stacks really carry the [S=2, L/S=40, ...] layout
    stacks = abstract_state.params["params"]["pipeline"]["ticks"]["layers"]
    assert all(
        leaf.shape[:2] == (2, 40) for leaf in jax.tree.leaves(stacks)
    ), {tuple(l.shape) for l in jax.tree.leaves(stacks)}
    # memory_analysis presence is the compile proof; GPipe holds M
    # microbatch activations so no single-chip budget assert here — the
    # numbers go to BASELINE.md for the pod-geometry discussion
    print(
        f"70B PP step@pipe2xfsdp2xtp2/dev: args {ma.argument_size_in_bytes/1e9:.1f}G, "
        f"temp {ma.temp_size_in_bytes/1e9:.1f}G"
    )


class _MetaHFStateDict(dict):
    """HF-style state dict with true 70B per-tensor shapes, zero-backed."""

    def __init__(self, config: LlamaConfig):
        import torch

        super().__init__()
        h, i = config.hidden_size, config.intermediate_size
        kv = config.num_key_value_heads * config.resolved_head_dim
        q = config.num_attention_heads * config.resolved_head_dim
        self["model.embed_tokens.weight"] = torch.zeros(config.vocab_size, h, dtype=torch.bfloat16)
        self["model.norm.weight"] = torch.zeros(h, dtype=torch.bfloat16)
        self["lm_head.weight"] = torch.zeros(config.vocab_size, h, dtype=torch.bfloat16)
        for layer in range(config.num_hidden_layers):
            p = f"model.layers.{layer}"
            self[f"{p}.self_attn.q_proj.weight"] = torch.zeros(q, h, dtype=torch.bfloat16)
            self[f"{p}.self_attn.k_proj.weight"] = torch.zeros(kv, h, dtype=torch.bfloat16)
            self[f"{p}.self_attn.v_proj.weight"] = torch.zeros(kv, h, dtype=torch.bfloat16)
            self[f"{p}.self_attn.o_proj.weight"] = torch.zeros(h, q, dtype=torch.bfloat16)
            self[f"{p}.mlp.gate_proj.weight"] = torch.zeros(i, h, dtype=torch.bfloat16)
            self[f"{p}.mlp.up_proj.weight"] = torch.zeros(i, h, dtype=torch.bfloat16)
            self[f"{p}.mlp.down_proj.weight"] = torch.zeros(h, i, dtype=torch.bfloat16)
            self[f"{p}.input_layernorm.weight"] = torch.zeros(h, dtype=torch.bfloat16)
            self[f"{p}.post_attention_layernorm.weight"] = torch.zeros(h, dtype=torch.bfloat16)


@pytest.mark.slow
def test_70b_shapes_stream_into_sharded_masters(mesh_4x2):
    """bf16 checkpoint tensors at true Llama-3-70B per-tensor shapes (depth
    cut to 2 so CI fits in RAM) stream leaf-at-a-time into fsdp x tensor
    sharded fp32 master buffers; the widening happens ON DEVICE (hf_io
    places storage dtype first), and every placed leaf is actually sharded
    (no replicated 70B-row tensors)."""
    import flax.linen as nn

    from llm_training_tpu.trainer.trainer import LOGICAL_AXIS_RULES
    from llm_training_tpu.parallel.sharding import logical_to_spec
    from jax.sharding import NamedSharding

    config = LlamaConfig(
        **LLAMA_3_70B, num_hidden_layers=2, tie_word_embeddings=False
    )
    model = Llama(config)

    with mesh_4x2, nn.logical_axis_rules(LOGICAL_AXIS_RULES):
        abstract = jax.eval_shape(
            lambda: model.init(
                jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
            )
        )

        def leaf_sharding(leaf):
            spec = (
                logical_to_spec(leaf.names, LOGICAL_AXIS_RULES)
                if isinstance(leaf, nn.Partitioned)
                else jax.sharding.PartitionSpec()
            )
            return NamedSharding(mesh_4x2, spec)

        shardings = jax.tree.map(
            leaf_sharding, abstract, is_leaf=lambda x: isinstance(x, nn.Partitioned)
        )

        from llm_training_tpu.models.hf_io import load_pretrained_params

        loaded = load_pretrained_params(
            config, _MetaHFStateDict(config), shardings=shardings,
            dtypes=jnp.float32,
        )

    leaves = jax.tree.leaves(loaded)
    assert all(l.dtype == jnp.float32 for l in leaves)
    big = [l for l in leaves if l.size * 4 > 1e9]
    assert big, "expected >1GB master leaves at 70B shapes"
    for leaf in big:
        n_shards = len({s.index for s in leaf.addressable_shards})
        assert n_shards > 1, f"large leaf not sharded: {leaf.shape}"
    # true 70B tensor shapes made it through the conversion (layers arrive
    # scanned/stacked — the default layout, and the one whose stacked host
    # tensor is the peak-memory hazard the storage-dtype placement bounds)
    shapes = {tuple(l.shape) for l in leaves}
    assert (128256, 8192) in shapes  # embed / lm_head
    assert (2, 8192, 28672) in shapes  # stacked mlp gate/up
    assert (2, 28672, 8192) in shapes  # stacked mlp down
