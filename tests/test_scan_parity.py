"""Loop-vs-scan parity for the dense-prefix + scanned-MoE-suffix families.

VERDICT r3 #3: deepseek / glm4_moe / ernie45_moe now scan their uniform MoE
suffix (compile time ~flat in depth). The same HF weights loaded through
both layouts must produce identical logits, and the scan->HF export must
byte-match the loop->HF export (same state dict, different flax trees).
"""

import importlib

import jax.numpy as jnp
import numpy as np
import pytest


def _deepseek():
    from tests.test_deepseek import _hf_tiny

    hf_model, hf_config = _hf_tiny(
        "DeepseekV3", n_group=4, topk_group=2, num_hidden_layers=3
    )
    return hf_model, hf_config, "deepseek"


def _glm4_moe():
    from tests.test_glm4_moe import _hf_tiny

    return (*_hf_tiny(num_hidden_layers=3), "glm4_moe")


def _ernie45_moe():
    from tests.test_ernie45_moe import _hf_tiny

    return (*_hf_tiny(num_hidden_layers=3), "ernie45_moe")


def _gpt_oss():
    from tests.test_gpt_oss import _hf_tiny

    # 4 layers = 2 cycles of the (sliding, full) pair — the scan needs a
    # proper repetition (detect_period returns 0 at 2 layers)
    return (*_hf_tiny(num_hidden_layers=4), "gpt_oss")


def _qwen3_next():
    from tests.test_qwen3_next import _hf_tiny

    # 8 layers = 2 cycles of the 3×linear+full period
    return (*_hf_tiny(num_hidden_layers=8), "qwen3_next")


def _minimax():
    from tests.test_minimax import _hf_tiny

    return (*_hf_tiny(), "minimax")  # 4 layers alternating = period 2


def _bamba():
    from tests.test_bamba import _hf_tiny

    # (mamba, attention) × 2 — slope-free periodic hybrid
    return (*_hf_tiny(num_hidden_layers=4, attn_layer_indices=[1, 3]), "bamba")


@pytest.mark.parametrize(
    "build",
    [_deepseek, _glm4_moe, _ernie45_moe, _gpt_oss, _qwen3_next, _minimax, _bamba],
)
def test_loop_vs_scan_parity(build):
    torch = pytest.importorskip("torch")
    hf_model, hf_config, family = build()
    mod = importlib.import_module(f"llm_training_tpu.models.{family}")
    conv = importlib.import_module(
        f"llm_training_tpu.models.{family}.hf_conversion"
    )
    model_cls = next(
        getattr(mod, n) for n in dir(mod)
        if n.lower().replace("_", "") == family.replace("_", "")
    )

    sd = hf_model.state_dict()
    outs, cfgs, trees = [], [], []
    for scan in (True, False):
        overrides = {"compute_dtype": "float32", "scan_layers": scan}
        if "moe_impl" in type(conv.config_from_hf(hf_config)).model_fields:
            overrides["moe_impl"] = "dense"
        cfg = conv.config_from_hf(hf_config, **overrides)
        active = getattr(cfg, "num_scanned_layers", 0) or getattr(cfg, "scan_period", 0)
        assert bool(active) == scan
        params = conv.params_from_hf(sd, cfg)
        ids = np.random.default_rng(7).integers(0, cfg.vocab_size, (2, 16))
        outs.append(np.asarray(model_cls(cfg).apply(params, jnp.asarray(ids)).logits))
        cfgs.append(cfg)
        trees.append(params)

    np.testing.assert_allclose(outs[0], outs[1], rtol=2e-5, atol=2e-5)

    # exports from both layouts must agree key-for-key, value-for-value
    hf_scan = conv.params_to_hf(trees[0], cfgs[0])
    hf_loop = conv.params_to_hf(trees[1], cfgs[1])
    assert set(hf_scan) == set(hf_loop)
    for key in hf_scan:
        np.testing.assert_array_equal(hf_scan[key], hf_loop[key], err_msg=key)


@pytest.mark.slow
def test_scan_compile_time_flat_in_depth():
    """The point of the scanned suffix: tracing+lowering a deepseek-v3-shaped
    stack must not grow linearly with depth (61 layers would otherwise
    compile 58 copies of the MoE body)."""
    import time

    import jax

    from llm_training_tpu.models.deepseek import Deepseek, DeepseekConfig
    from tests.test_deepseek import TINY

    def lower_seconds(n_layers):
        cfg = DeepseekConfig(**{**TINY, "num_hidden_layers": n_layers},
                             n_group=4, topk_group=2)
        model = Deepseek(cfg)
        ids = jnp.zeros((1, 16), jnp.int32)
        params = jax.eval_shape(model.init, jax.random.PRNGKey(0), ids)
        t0 = time.perf_counter()
        jax.jit(model.apply).lower(params, ids)
        return time.perf_counter() - t0

    lower_seconds(3)  # warm import/caches
    t_short, t_deep = lower_seconds(4), lower_seconds(22)
    # 18 extra scanned layers must not add ~6x trace work; allow generous
    # slack for wall-clock noise
    assert t_deep < 3 * t_short, (t_short, t_deep)
