"""HF checkpoint IO: streamed safetensors loading, export round-trip through
`transformers`, convert_to_hf script, and pre-trained init in the trainer."""

import json
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_training_tpu.models import Llama, LlamaConfig
from llm_training_tpu.models.hf_io import (
    LazyStateDict,
    load_hf_config,
    load_pretrained_params,
    model_class_for_hf,
    save_hf_checkpoint,
)
from llm_training_tpu.models.llama.hf_conversion import config_from_hf, params_from_hf

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "scripts"))

TINY_HF = dict(
    vocab_size=128,
    hidden_size=64,
    intermediate_size=112,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    max_position_embeddings=64,
)


@pytest.fixture(scope="module")
def hf_llama_dir(tmp_path_factory):
    """A tiny HF Llama saved with save_pretrained (single safetensors)."""
    import torch
    from transformers import LlamaConfig as HFLlamaConfig
    from transformers import LlamaForCausalLM

    torch.manual_seed(0)
    hf_model = LlamaForCausalLM(HFLlamaConfig(**TINY_HF, attention_bias=False))
    out = tmp_path_factory.mktemp("hf_llama")
    hf_model.save_pretrained(out, safe_serialization=True)
    return out


def test_lazy_state_dict_reads_all_keys(hf_llama_dir):
    lazy = LazyStateDict(hf_llama_dir)
    assert "model.embed_tokens.weight" in lazy
    tensor = lazy["model.layers.0.self_attn.q_proj.weight"]
    assert tuple(tensor.shape) == (64, 64)
    assert len(lazy) > 10


def test_load_pretrained_matches_eager(hf_llama_dir):
    from transformers import LlamaForCausalLM

    cfg = config_from_hf(load_hf_config(hf_llama_dir), compute_dtype="float32")
    streamed = load_pretrained_params(cfg, hf_llama_dir)
    eager = params_from_hf(
        LlamaForCausalLM.from_pretrained(hf_llama_dir).state_dict(), cfg
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        streamed, eager,
    )


def test_load_pretrained_with_shardings(hf_llama_dir, devices):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    cfg = config_from_hf(load_hf_config(hf_llama_dir), compute_dtype="float32")
    mesh = Mesh(np.array(devices).reshape(8), ("fsdp",))
    params = load_pretrained_params(cfg, hf_llama_dir)
    shardings = jax.tree.map(
        lambda _: NamedSharding(mesh, PartitionSpec()), params
    )
    placed = load_pretrained_params(cfg, hf_llama_dir, shardings, jnp.float32)
    leaf = placed["params"]["embed_tokens"]["embedding"]
    assert isinstance(leaf, jax.Array) and leaf.dtype == jnp.float32


@pytest.mark.slow
def test_export_roundtrip_through_transformers(tmp_path):
    """our params -> save_hf_checkpoint -> transformers forward == ours."""
    import torch
    from transformers import LlamaForCausalLM

    cfg = LlamaConfig(
        **{k: v for k, v in TINY_HF.items()}, compute_dtype="float32",
        param_dtype="float32",
    )
    model = Llama(cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 128, (2, 16), np.int32))
    params = model.init(jax.random.key(0), ids)
    ours = model.apply(params, ids).logits

    out = save_hf_checkpoint(params, cfg, tmp_path / "export", dtype="float32")
    hf_model = LlamaForCausalLM.from_pretrained(out, torch_dtype=torch.float32)
    with torch.no_grad():
        theirs = hf_model(torch.from_numpy(np.asarray(ids)).long()).logits.numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-4, rtol=2e-3)


@pytest.mark.slow
def test_sharded_export(tmp_path):
    """Multiple safetensors shards + index.json when over the shard budget."""
    cfg = LlamaConfig(**TINY_HF, compute_dtype="float32", param_dtype="float32")
    model = Llama(cfg)
    params = model.init(jax.random.key(0), jnp.ones((1, 4), jnp.int32))
    out = save_hf_checkpoint(
        params, cfg, tmp_path / "sharded", dtype="float32", max_shard_bytes=200_000
    )
    index = json.loads((out / "model.safetensors.index.json").read_text())
    assert len(set(index["weight_map"].values())) > 1
    # and it still loads
    streamed = load_pretrained_params(cfg, out)
    assert "embed_tokens" in streamed["params"]


def test_model_class_for_hf():
    assert model_class_for_hf({"model_type": "llama"}).endswith("Llama")
    assert model_class_for_hf({"model_type": "mistral"}).endswith("Llama")
    assert model_class_for_hf({"model_type": "phi3"}).endswith("Phi3")


def test_unknown_model_type_llama_fallback():
    """Unknown model_types fail loudly by default; the opt-in routes them
    to the Llama family (renamed llama-layout forks)."""
    with pytest.raises(ValueError, match="assume_llama_layout"):
        model_class_for_hf({"model_type": "somebodys_llama_fork"})
    assert model_class_for_hf(
        {"model_type": "somebodys_llama_fork"}, assume_llama_layout=True
    ).endswith("Llama")
    with pytest.raises(ValueError):
        model_class_for_hf({"model_type": "mamba"})


def _tiny_fit(tmp_path, pre_trained=None, max_steps=1, lr=1e-3):
    from llm_training_tpu.data import DummyDataModule, DummyDataModuleConfig
    from llm_training_tpu.lms import CLM, CLMConfig, ModelProvider
    from llm_training_tpu.optim import OptimConfig
    from llm_training_tpu.parallel import MeshConfig
    from llm_training_tpu.trainer import Trainer, TrainerConfig
    from llm_training_tpu.trainer.checkpoint import CheckpointConfig, Checkpointer

    model_kwargs = dict(TINY_HF, compute_dtype="float32", param_dtype="float32")
    model_node = {
        "class_path": "llm_training_tpu.lms.CLM",
        "init_args": {
            "model": {
                "model_class": "llm_training_tpu.models.Llama",
                "model_kwargs": model_kwargs,
            },
            "optim": {"learning_rate": lr, "warmup_steps": 0},
            **({"pre_trained_weights": str(pre_trained)} if pre_trained else {}),
        },
    }
    objective = CLM(
        CLMConfig(
            model=ModelProvider(
                model_class="llm_training_tpu.models.Llama", model_kwargs=model_kwargs
            ),
            optim=OptimConfig(learning_rate=lr, warmup_steps=0),
            pre_trained_weights=str(pre_trained) if pre_trained else None,
        )
    )
    datamodule = DummyDataModule(
        DummyDataModuleConfig(
            batch_size=8, max_length=16, num_samples=32, vocab_size=128
        )
    )
    checkpointer = Checkpointer(
        CheckpointConfig(dirpath=str(tmp_path / "ckpt"), async_save=False),
        run_config={"model": model_node, "data": {}},
    )
    trainer = Trainer(
        TrainerConfig(max_steps=max_steps, log_every_n_steps=1, mesh=MeshConfig()),
        checkpointer=checkpointer,
    )
    state = trainer.fit(objective, datamodule)
    return trainer, objective, state, tmp_path / "ckpt"


@pytest.mark.slow
def test_convert_to_hf_script(tmp_path):
    """fit -> checkpoint -> convert -> transformers can load the export."""
    import torch
    from transformers import LlamaForCausalLM

    from convert_to_hf import convert_checkpoint

    _, objective, state, ckpt_dir = _tiny_fit(tmp_path)
    out = convert_checkpoint(ckpt_dir, tmp_path / "hf_out", dtype="float32")
    hf_model = LlamaForCausalLM.from_pretrained(out, torch_dtype=torch.float32)

    ids = np.random.default_rng(1).integers(0, 128, (2, 12), np.int64)
    with torch.no_grad():
        theirs = hf_model(torch.from_numpy(ids)).logits.numpy()
    ours = objective.model.apply(
        jax.device_get(state.params), jnp.asarray(ids, jnp.int32)
    ).logits
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-4, rtol=2e-3)


def test_dpo_pretrained_loads_policy_and_ref(hf_llama_dir):
    from llm_training_tpu.lms import DPO, DPOConfig, ModelProvider

    model_kwargs = dict(TINY_HF, compute_dtype="float32", param_dtype="float32")
    objective = DPO(
        DPOConfig(
            model=ModelProvider(
                model_class="llm_training_tpu.models.Llama", model_kwargs=model_kwargs
            ),
            pre_trained_weights=str(hf_llama_dir),
        )
    )
    import flax.linen as nn
    from jax.sharding import SingleDeviceSharding

    abstract = nn.meta.unbox(
        jax.eval_shape(
            lambda: objective.init_params(
                jax.random.key(0), {"chosen_input_ids": jnp.ones((1, 4), jnp.int32)}
            )
        )
    )
    shardings = jax.tree.map(
        lambda _: SingleDeviceSharding(jax.devices()[0]), abstract
    )
    dtypes = jax.tree.map(lambda _: jnp.float32, abstract)
    params = objective.pretrained_params(shardings, dtypes)
    a = params["policy"]["params"]["embed_tokens"]["embedding"]
    b = params["ref"]["params"]["embed_tokens"]["embedding"]
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_trainer_pretrained_init(tmp_path, hf_llama_dir):
    """pre_trained_weights + lr=0: params after one step == the HF weights."""
    _, objective, state, _ = _tiny_fit(tmp_path, pre_trained=hf_llama_dir, lr=0.0)
    cfg = config_from_hf(load_hf_config(hf_llama_dir), compute_dtype="float32")
    expected = load_pretrained_params(cfg, hf_llama_dir)
    got = jax.device_get(state.params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6
        ),
        got, expected,
    )


# ---------------------------------------------------------------- HFCausalLM


def test_hf_causal_lm_routes_to_family(hf_llama_dir):
    """HFCausalLM(config) returns the routed flax family model with merged
    hparams and the checkpoint wired as pre-trained weights (the reference's
    wrap-any-AutoModelForCausalLM escape hatch, hf_causal_lm.py:22)."""
    from llm_training_tpu.models import HFCausalLM, HFCausalLMConfig, Llama

    model = HFCausalLM(HFCausalLMConfig(hf_path=str(hf_llama_dir)))
    assert isinstance(model, Llama)
    assert model.config.hidden_size == TINY_HF["hidden_size"]
    assert model.config.num_key_value_heads == TINY_HF["num_key_value_heads"]
    assert model.config.pre_trained_weights == str(hf_llama_dir)


def test_hf_causal_lm_overrides_and_validation(hf_llama_dir):
    from llm_training_tpu.models import HFCausalLM, HFCausalLMConfig

    model = HFCausalLM(
        HFCausalLMConfig(
            hf_path=str(hf_llama_dir),
            enable_gradient_checkpointing=True,
            attention_impl="xla",
        )
    )
    assert model.config.enable_gradient_checkpointing is True
    with pytest.raises(Exception):  # family pydantic config rejects typos
        HFCausalLM(
            HFCausalLMConfig(hf_path=str(hf_llama_dir), hiden_size=12)
        )


def test_hf_causal_lm_unknown_arch_fails_loudly(tmp_path):
    from llm_training_tpu.models import HFCausalLM, HFCausalLMConfig

    (tmp_path / "config.json").write_text(json.dumps({"model_type": "mamba"}))
    with pytest.raises(ValueError, match="unsupported HF model_type"):
        HFCausalLM(HFCausalLMConfig(hf_path=str(tmp_path)))


def test_hf_causal_lm_through_model_provider(hf_llama_dir):
    """The YAML path: ModelProvider with model_class=HFCausalLM."""
    from llm_training_tpu.lms.base import ModelProvider
    from llm_training_tpu.models import Llama

    provider = ModelProvider(
        model_class="HFCausalLM",
        model_kwargs=dict(hf_path=str(hf_llama_dir), scan_layers=False),
    )
    model = provider.get_model()
    assert isinstance(model, Llama)
    assert model.config.scan_layers is False


def test_hf_causal_lm_pipeline_load_logits_parity(hf_llama_dir, devices):
    """The llama-3-8b_pp_pt.yaml path end-to-end at tiny scale: HFCausalLM
    routes the checkpoint into a PIPELINED Llama (pipeline_stages forwarded
    through the router), load_pretrained_params adapts the scan-layout
    conversion into the [S, L/S, ...] stage stacks, and the loaded model's
    logits match the scan-routed model loaded from the same directory."""
    from llm_training_tpu.models import HFCausalLM, HFCausalLMConfig
    from llm_training_tpu.models.hf_io import load_pretrained_params

    m_scan = HFCausalLM(
        HFCausalLMConfig(hf_path=str(hf_llama_dir), compute_dtype="float32")
    )
    m_pp = HFCausalLM(
        HFCausalLMConfig(
            hf_path=str(hf_llama_dir),
            compute_dtype="float32",
            pipeline_stages=2,
            pipeline_microbatches=2,
        )
    )
    assert m_pp.config.pipeline_stages == 2

    p_scan = load_pretrained_params(m_scan.config, str(hf_llama_dir))
    p_pp = load_pretrained_params(m_pp.config, str(hf_llama_dir))
    stack_leaf = jax.tree.leaves(p_pp["params"]["pipeline"])[0]
    assert stack_leaf.shape[:2] == (2, 1)  # [S, L/S, ...]

    ids = jnp.asarray(
        np.random.default_rng(0).integers(1, TINY_HF["vocab_size"], (4, 16)),
        jnp.int32,
    )
    seg = jnp.ones((4, 16), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(16), (4, 16)).astype(jnp.int32)
    out_scan = m_scan.apply(p_scan, ids, seg, pos)
    out_pp = m_pp.apply(p_pp, ids, seg, pos)
    np.testing.assert_allclose(
        np.asarray(out_pp.logits), np.asarray(out_scan.logits), atol=2e-5
    )
