"""Test harness: single-process 8-device CPU mesh.

The reference has no test suite (SURVEY.md §4); this framework's tests run
every parallelism mode (DP/FSDP/TP/SP/CP) on a virtual 8-device CPU mesh via
XLA's host-platform device-count override, so distributed behavior is
CI-testable without hardware.

Note: this image's sitecustomize imports jax and registers a TPU backend at
interpreter start, so env vars alone are too late — we must override via
jax.config before the backend client is instantiated.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_PLATFORM_NAME"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs


def fit_losses(model_class: str, model_kwargs: dict, mesh=None,
               max_steps: int = 6, lr: float = 1e-3) -> list[float]:
    """Run a tiny CLM fit and return the per-step losses (shared harness for
    the per-family sharded-mesh tests)."""
    from llm_training_tpu.data import DummyDataModule, DummyDataModuleConfig
    from llm_training_tpu.lms import CLM, CLMConfig, ModelProvider
    from llm_training_tpu.optim import OptimConfig
    from llm_training_tpu.parallel import MeshConfig
    from llm_training_tpu.trainer import Trainer, TrainerConfig

    objective = CLM(CLMConfig(
        model=ModelProvider(model_class=model_class, model_kwargs=model_kwargs),
        optim=OptimConfig(learning_rate=lr, warmup_steps=2),
    ))
    data = DummyDataModule(DummyDataModuleConfig(
        batch_size=8, max_length=32, num_samples=64,
        vocab_size=model_kwargs.get("vocab_size", 128),
    ))
    losses: list[float] = []

    class Track:
        def on_step_end(self, trainer, step, metrics):
            losses.append(float(metrics["loss"]))

    Trainer(
        TrainerConfig(max_steps=max_steps, log_every_n_steps=1,
                      mesh=mesh or MeshConfig()),
        callbacks=[Track()],
    ).fit(objective, data)
    return losses
