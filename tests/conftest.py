"""Test harness: single-process 8-device CPU mesh.

The reference has no test suite (SURVEY.md §4); this framework's tests run
every parallelism mode (DP/FSDP/TP/SP/CP) on a virtual 8-device CPU mesh via
XLA's host-platform device-count override, so distributed behavior is
CI-testable without hardware.

Note: this image's sitecustomize imports jax and registers a TPU backend at
interpreter start, so env vars alone are too late — we must override via
jax.config before the backend client is instantiated.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_PLATFORM_NAME"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs
