"""MiniMax: hybrid lightning attention + mixtral MoE, HF parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_training_tpu.models.minimax import MiniMax, MiniMaxConfig
from llm_training_tpu.models.minimax.hf_conversion import (
    config_from_hf,
    config_to_hf,
    params_from_hf,
    params_to_hf,
)

TINY = dict(
    vocab_size=128,
    hidden_size=64,
    intermediate_size=48,
    moe_intermediate_size=48,
    num_hidden_layers=4,
    num_attention_heads=4,
    num_key_value_heads=2,
    head_dim=16,
    max_position_embeddings=128,
    block_size=16,
    layer_types=["linear_attention", "full_attention",
                 "linear_attention", "full_attention"],
    num_experts=4,
    num_experts_per_tok=2,
    linear_attn_alpha_factor=1.0,
    linear_attn_beta_factor=1.0,
    compute_dtype="float32",
)


def _hf_tiny(**extra):
    torch = pytest.importorskip("torch")
    from transformers import MiniMaxConfig as HFConfig
    from transformers import MiniMaxForCausalLM

    kwargs = dict(
        vocab_size=128, hidden_size=64, intermediate_size=48,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=128, block_size=16,
        layer_types=["linear_attention", "full_attention",
                     "linear_attention", "full_attention"],
        num_local_experts=4, num_experts_per_tok=2,
        attn_implementation="eager",
    )
    kwargs.update(extra)
    hf_config = HFConfig(**kwargs)
    torch.manual_seed(0)
    return MiniMaxForCausalLM(hf_config).eval(), hf_config


@pytest.mark.parametrize("seq", [12, 40])
def test_logits_parity_with_hf(seq):
    """Hybrid stack vs HF eager: seq 12 fits one lightning block (16); seq
    40 spans three, exercising the cross-block KV state and decay."""
    torch = pytest.importorskip("torch")
    hf_model, hf_config = _hf_tiny()
    sd = hf_model.state_dict()
    assert "model.layers.0.self_attn.qkv_proj.weight" in sd
    assert "model.layers.1.self_attn.q_proj.weight" in sd
    assert "model.layers.0.block_sparse_moe.experts.0.w1.weight" in sd

    cfg = config_from_hf(hf_config, compute_dtype="float32", moe_impl="dense")
    assert cfg.layer_is_linear(0) and not cfg.layer_is_linear(1)
    assert cfg.moe_style == "mixtral"
    params = params_from_hf(sd, cfg)
    model = MiniMax(cfg)

    ids = np.random.default_rng(80).integers(0, 128, (2, seq))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=4e-4, atol=4e-4)


def test_residual_factors_are_live():
    """Non-unit alpha/beta residual combiners must change the graph and
    still match HF."""
    torch = pytest.importorskip("torch")
    hf_model, hf_config = _hf_tiny(
        linear_attn_alpha_factor=0.7, linear_attn_beta_factor=1.3,
        full_attn_alpha_factor=0.9, full_attn_beta_factor=1.1,
        mlp_alpha_factor=0.8, mlp_beta_factor=1.2,
    )
    cfg = config_from_hf(hf_config, compute_dtype="float32", moe_impl="dense")
    assert cfg.linear_attn_alpha_factor == 0.7 and cfg.mlp_beta_factor == 1.2
    params = params_from_hf(hf_model.state_dict(), cfg)
    model = MiniMax(cfg)
    ids = np.random.default_rng(81).integers(0, 128, (2, 20))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=4e-4, atol=4e-4)


def test_hf_round_trip():
    hf_model, hf_config = _hf_tiny()
    cfg = config_from_hf(hf_config)
    params = params_from_hf(hf_model.state_dict(), cfg)
    back = params_to_hf(params, cfg)
    sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    assert set(back) == set(sd)
    for key in sd:
        if any(b in key for b in ("decay", "slope_rate")):
            # deterministic buffers recomputed at export: numpy and torch
            # exp() differ in the last ulp
            np.testing.assert_allclose(back[key], sd[key], rtol=1e-6, err_msg=key)
        else:
            np.testing.assert_array_equal(back[key], sd[key], err_msg=key)


def test_config_round_trip():
    cfg = MiniMaxConfig(**TINY)
    hf = config_to_hf(cfg)
    assert hf["model_type"] == "minimax"
    cfg2 = config_from_hf(hf, compute_dtype="float32")
    assert cfg2.model_dump() == cfg.model_dump()


@pytest.mark.slow
def test_e2e_fit_decreases_loss():
    from conftest import fit_losses

    losses = fit_losses(
        "llm_training_tpu.models.MiniMax",
        dict(TINY, enable_gradient_checkpointing=True, moe_impl="dense"),
        max_steps=20, lr=3e-3,
    )
    assert np.mean(losses[-3:]) < np.mean(losses[:3])
