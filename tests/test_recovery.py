"""Self-healing training: DataSkipList determinism, rollback-and-skip
recovery, LR cooldown, budget escalation, the crash-restart supervisor, and
the recovery exit-code/report surfaces (docs/resilience.md#recovery)."""

import json
import sys
from pathlib import Path

import jax
import numpy as np
import pytest
import yaml

from llm_training_tpu.callbacks import NanGuard, NanGuardConfig
from llm_training_tpu.data import DummyDataModule, DummyDataModuleConfig
from llm_training_tpu.resilience import (
    LOSS_SPIKE_EXIT_CODE,
    NON_FINITE_EXIT_CODE,
    RECOVERY_EXHAUSTED_EXIT_CODE,
    RESUMABLE_EXIT_CODE,
    ChaosConfig,
    DataSkipList,
    RecoveryConfig,
    RecoveryExhaustedError,
    RecoveryManager,
    ResilienceConfig,
    Supervisor,
    SupervisorConfig,
    cooldown_schedule,
    config_from_env,
    install_chaos,
    uninstall_chaos,
)
from llm_training_tpu.telemetry import TelemetryRegistry


@pytest.fixture(autouse=True)
def _no_leaked_chaos():
    yield
    uninstall_chaos()


def _dummy(num_samples=64, batch_size=8):
    dm = DummyDataModule(
        DummyDataModuleConfig(
            batch_size=batch_size, max_length=16, num_samples=num_samples,
            vocab_size=64,
        )
    )
    dm.setup()
    return dm


def _take(stream, n):
    return [next(stream)["input_ids"] for _ in range(n)]


# ---------------------------------------------------------------- skip list


def test_skip_list_windows_and_ordinals():
    skips = DataSkipList(windows=[(3, 2), (10, 1)], reserve=4)
    assert skips.is_skipped(3) and skips.is_skipped(4) and skips.is_skipped(10)
    assert not skips.is_skipped(2) and not skips.is_skipped(5)
    assert skips.skipped_steps == 3
    # ordinal = skipped steps in [epoch_start, step)
    assert skips.replacement_ordinal(3, 0) == 0
    assert skips.replacement_ordinal(4, 0) == 1
    assert skips.replacement_ordinal(10, 0) == 2
    assert skips.replacement_ordinal(10, 8) == 0  # epoch-local


def test_skip_list_metadata_roundtrip():
    skips = DataSkipList(windows=[(3, 2)], reserve=5)
    restored = DataSkipList.from_metadata(skips.to_metadata())
    assert restored.windows == [(3, 2)]
    assert restored.reserve == 5
    assert DataSkipList.from_metadata(None) is None
    assert DataSkipList.from_metadata({}) is None


def test_stream_without_skip_list_is_unchanged():
    """The recovery-off data order must be byte-identical to the historical
    stream (the acceptance bar: recovery unset == HEAD)."""
    a = _take(_dummy().train_batches(start_step=0), 10)
    b = _take(_dummy().train_batches(start_step=0, skip_list=None), 10)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_skipped_step_serves_reserved_batch_and_rest_unchanged():
    # 64 samples / batch 8 = 8 batches; reserve 2 -> 6 served per epoch
    skips = DataSkipList(windows=[(2, 1)], reserve=2)
    plain = _take(_dummy().train_batches(start_step=0), 8)
    skipped = _take(_dummy().train_batches(start_step=0, skip_list=skips), 6)
    for step in (0, 1, 3, 4, 5):
        np.testing.assert_array_equal(skipped[step], plain[step])
    # step 2 serves the FIRST reserved batch (batch index 6 of the epoch)
    np.testing.assert_array_equal(skipped[2], plain[6])  # pool[0] = batch 6
    assert not np.array_equal(skipped[2], plain[2])


def test_skip_replacements_stable_across_resume():
    """Resume mid-window must serve the same replacements as a from-scratch
    stream with the same skip list — the checkpoint-metadata contract."""
    skips = DataSkipList(windows=[(2, 2), (9, 1)], reserve=4)
    full = _take(_dummy().train_batches(start_step=0, skip_list=skips), 12)
    for start in (2, 3, 5, 9):
        resumed = _take(
            _dummy().train_batches(start_step=start, skip_list=skips), 12 - start
        )
        for offset, batch in enumerate(resumed):
            np.testing.assert_array_equal(
                batch, full[start + offset],
                err_msg=f"start={start} step={start + offset}",
            )


def test_no_duplicate_or_lost_batches_per_epoch():
    """Within an epoch: every sample served exactly once, replacements come
    from the reserved tail (disjoint from the served set), and the epoch
    still has served-count batches."""
    dm = _dummy(num_samples=64, batch_size=8)  # 8 batches/epoch
    skips = DataSkipList(windows=[(1, 1), (4, 2)], reserve=3)
    served = 8 - 3  # 5 per epoch
    epoch = _take(dm.train_batches(start_step=0, skip_list=skips), served)
    rows = np.concatenate([b for b in epoch], axis=0)
    flat = [tuple(r) for r in rows]
    assert len(flat) == len(set(flat)), "duplicate samples within an epoch"
    # second epoch starts right after `served` steps and is internally
    # deduplicated too (windows are epoch-local via the ordinal)
    epoch2 = _take(dm.train_batches(start_step=served, skip_list=skips), served)
    rows2 = np.concatenate([b for b in epoch2], axis=0)
    flat2 = [tuple(r) for r in rows2]
    assert len(flat2) == len(set(flat2))


def test_reserve_consuming_whole_epoch_raises():
    dm = _dummy(num_samples=16, batch_size=8)  # 2 batches/epoch
    skips = DataSkipList(windows=[(0, 1)], reserve=2)
    with pytest.raises(ValueError, match="reserve"):
        next(dm.train_batches(start_step=0, skip_list=skips))


def test_skip_pool_wraps_when_exhausted():
    dm = _dummy(num_samples=32, batch_size=8)  # 4 batches/epoch
    skips = DataSkipList(windows=[(0, 3)], reserve=1)  # 3 skips, 1 reserved
    batches = _take(dm.train_batches(start_step=0, skip_list=skips), 3)
    # every skipped step wraps onto the single reserved batch
    np.testing.assert_array_equal(batches[0], batches[1])
    np.testing.assert_array_equal(batches[1], batches[2])


# ---------------------------------------------------------------- cooldown


def test_cooldown_schedule_decays_and_expires():
    base = lambda count: 2.0
    cooled = cooldown_schedule(base, [(10, 5, 0.1)])
    assert float(cooled(9)) == pytest.approx(2.0)
    for count in range(10, 15):
        assert float(cooled(count)) == pytest.approx(0.2)
    assert float(cooled(15)) == pytest.approx(2.0)


def test_cooldown_schedule_stacks_windows():
    cooled = cooldown_schedule(lambda c: 1.0, [(0, 4, 0.5), (2, 4, 0.5)])
    assert float(cooled(1)) == pytest.approx(0.5)
    assert float(cooled(3)) == pytest.approx(0.25)  # overlap multiplies
    assert float(cooled(5)) == pytest.approx(0.5)
    assert float(cooled(7)) == pytest.approx(1.0)


def test_cooldown_schedule_traces_under_jit():
    cooled = cooldown_schedule(lambda c: 1.0, [(2, 2, 0.25)])
    values = jax.jit(jax.vmap(cooled))(np.arange(6))
    np.testing.assert_allclose(
        np.asarray(values), [1.0, 1.0, 0.25, 0.25, 1.0, 1.0]
    )


# ---------------------------------------------------------------- manager


def _manager(registry=None, metadata=None, **overrides):
    kwargs = dict(max_rollbacks=2, skip_window_steps=2, escalate_after=3)
    kwargs.update(overrides)
    return RecoveryManager(
        RecoveryConfig(**kwargs), registry=registry, metadata=metadata
    )


def test_manager_budget_exhaustion_escalates():
    registry = TelemetryRegistry()
    manager = _manager(registry=registry)
    manager.on_failure(RuntimeError("boom"), failed_step=4)
    manager.on_failure(RuntimeError("boom"), failed_step=9)
    with pytest.raises(RecoveryExhaustedError, match="budget exhausted"):
        manager.on_failure(RuntimeError("boom"), failed_step=14)
    snapshot = registry.snapshot()
    assert snapshot["resilience/rollbacks"] == 2
    assert snapshot["resilience/recovery_escalations"] == 1


def test_manager_same_step_failures_escalate_early():
    manager = _manager(max_rollbacks=10, escalate_after=2)
    manager.on_failure(RuntimeError("a"), failed_step=5)
    manager.on_failure(RuntimeError("b"), failed_step=5)
    with pytest.raises(RecoveryExhaustedError, match="escalating"):
        manager.on_failure(RuntimeError("c"), failed_step=5)


def test_manager_skip_window_clamped_to_restore_point():
    manager = _manager(skip_window_steps=4)
    # failure at micro end 6, restored to micro 4: only [4, 6) is skippable
    start, length = manager.register_skip(6, floor_micro=4)
    assert (start, length) == (4, 2)
    assert manager.skip_list.windows == [(4, 2)]


def test_manager_metadata_roundtrip_replays_skips_and_cooldowns():
    registry = TelemetryRegistry()
    manager = _manager(registry=registry, lr_cooldown_steps=3)
    manager.on_failure(RuntimeError("x"), failed_step=3)
    manager.register_skip(3, floor_micro=0)
    assert manager.register_cooldown(2)
    meta = manager.metadata()
    resumed = _manager(metadata=meta, lr_cooldown_steps=3)
    assert resumed.skip_list.windows == manager.skip_list.windows
    assert resumed.skip_list.reserve == manager.skip_list.reserve
    assert resumed.cooldowns == manager.cooldowns
    assert resumed.schedule_transform() is not None


def test_manager_reserve_ignores_preset_windows():
    """The default reserve must depend only on the stable budget knobs —
    NOT on preset windows — or a healed run and its clean comparison run
    (same knobs, different windows) would serve different epochs."""
    a = _manager(max_rollbacks=3, skip_window_steps=2)
    b = _manager(max_rollbacks=3, skip_window_steps=2, skip_windows=((5, 1),))
    assert a.skip_list.reserve == b.skip_list.reserve == 6


def test_recovery_config_in_trainer_config():
    from llm_training_tpu.trainer import TrainerConfig

    config = TrainerConfig(
        resilience={"recovery": {"max_rollbacks": 5, "skip_window_steps": 2,
                                 "lr_cooldown_steps": 10}}
    )
    assert config.resilience.recovery.max_rollbacks == 5
    assert TrainerConfig().resilience.recovery is None  # default: off
    with pytest.raises(Exception):
        TrainerConfig(resilience={"recovery": {"max_rollbakcs": 1}})


# ---------------------------------------------------------------- chaos


def test_chaos_nan_injection_fires_once_at_first_log_step():
    chaos = install_chaos(ChaosConfig(nan_step=3))
    metrics = {"loss": 2.0, "grad_norm": 1.0}
    assert chaos.maybe_poison_metrics(2, metrics) == []
    assert np.isfinite(metrics["loss"])
    # trigger step was not a log step: fires at the FIRST log step past it
    assert chaos.maybe_poison_metrics(4, metrics) == ["nan"]
    assert np.isnan(metrics["loss"]) and np.isnan(metrics["grad_norm"])
    assert chaos.maybe_poison_metrics(5, {"loss": 1.0}) == []  # once


def test_chaos_spike_injection_scales_metrics():
    chaos = install_chaos(ChaosConfig(spike_step=2, spike_scale=100.0))
    metrics = {"loss": 2.0, "grad_norm": 0.5}
    assert chaos.maybe_poison_metrics(2, metrics) == ["spike"]
    assert metrics["loss"] == pytest.approx(200.0)
    assert metrics["grad_norm"] == pytest.approx(50.0)


def test_chaos_sigkill_requires_fresh_start():
    """The supervise-gate contract: the SIGKILL trigger must be inert in a
    resumed run, or the supervisor's relaunch would crash-loop on it."""
    chaos = install_chaos(ChaosConfig(sigkill_step=3))
    # resumed run (fresh_start=False) crossing the trigger: must survive
    chaos.maybe_sigkill(3, fresh_start=False)  # would SIGKILL the test if broken
    # wrong step in a fresh run: also inert
    chaos.maybe_sigkill(2, fresh_start=True)


def test_chaos_env_overlay_covers_new_triggers(monkeypatch):
    monkeypatch.setenv("LLMT_CHAOS_NAN_STEP", "7")
    monkeypatch.setenv("LLMT_CHAOS_SIGKILL_STEP", "9")
    monkeypatch.setenv("LLMT_CHAOS_SPIKE_STEP", "4")
    monkeypatch.setenv("LLMT_CHAOS_SPIKE_SCALE", "12.5")
    config = config_from_env(ChaosConfig())
    assert config.nan_step == 7
    assert config.sigkill_step == 9
    assert config.spike_step == 4
    assert config.spike_scale == 12.5
    assert config.any_active()


# ---------------------------------------------------------------- nan guard state


def test_nan_guard_state_roundtrip():
    guard = NanGuard(NanGuardConfig(spike_zscore=6.0, spike_warmup_steps=3))
    for value in (2.0, 2.1, 1.9, 2.0, 2.05):
        for detector in guard._detectors.values():
            detector.update(value)
    guard.non_finite_steps = 2
    guard.spike_steps = 1
    state = guard.state_dict()
    assert json.dumps(state)  # JSON-serializable (checkpoint metadata rider)

    fresh = NanGuard(NanGuardConfig(spike_zscore=6.0, spike_warmup_steps=3))
    fresh.load_state_dict(state)
    assert fresh.non_finite_steps == 2
    assert fresh.spike_steps == 1
    for name, detector in guard._detectors.items():
        restored = fresh._detectors[name]
        assert restored.count == detector.count
        assert restored.mean == pytest.approx(detector.mean)
        assert restored.var == pytest.approx(detector.var)
    # the restored detector is armed (past warmup) — no blind window
    assert fresh._detectors["loss"].score(100.0) is not None


def test_nan_guard_state_ignored_when_spike_detection_off():
    armed = NanGuard(NanGuardConfig(spike_zscore=6.0))
    state = armed.state_dict()
    plain = NanGuard(NanGuardConfig())  # no detectors configured
    plain.load_state_dict(state)  # must not invent detectors
    assert plain._detectors == {}


def test_nan_guard_on_rollback_clears_streaks_keeps_totals():
    guard = NanGuard(NanGuardConfig(patience=5))
    guard.non_finite_steps = 3
    guard._streak = 3
    guard._spike_streak = 2
    guard.on_rollback(trainer=None, step=4)
    assert guard._streak == 0 and guard._spike_streak == 0
    assert guard.non_finite_steps == 3  # lifetime total survives


# ---------------------------------------------------------------- supervisor


def _fake_child(script: list[int]):
    """Returns a run_child(argv) that pops scripted exit codes."""
    remaining = list(script)

    def run(argv):
        return remaining.pop(0)

    return run


def test_supervisor_restarts_on_resumable_and_hard_deaths(tmp_path):
    log = tmp_path / "supervisor.jsonl"
    sup = Supervisor(
        ["child"],
        SupervisorConfig(max_restarts=5, backoff_base_s=0.0, log_path=str(log)),
        run_child=_fake_child([RESUMABLE_EXIT_CODE, -9, -6, 0]),
        sleep=lambda s: None,
    )
    assert sup.run() == 0
    assert sup.restarts == 3
    events = [json.loads(line) for line in log.read_text().splitlines()]
    kinds = [e["event"] for e in events]
    assert kinds.count("launch") == 4
    assert kinds.count("restart") == 3
    assert kinds[-1] == "complete"
    sigkill_exit = next(e for e in events if e["event"] == "exit" and e["rc"] == -9)
    assert sigkill_exit["signal"] == "SIGKILL"


def test_supervisor_gives_up_on_non_resumable_exit():
    sup = Supervisor(
        ["child"],
        SupervisorConfig(max_restarts=5, backoff_base_s=0.0),
        run_child=_fake_child([RECOVERY_EXHAUSTED_EXIT_CODE]),
        sleep=lambda s: None,
    )
    assert sup.run() == RECOVERY_EXHAUSTED_EXIT_CODE
    assert sup.restarts == 0
    assert sup.events[-1]["event"] == "giveup"


def test_supervisor_restart_budget_propagates_last_code():
    sup = Supervisor(
        ["child"],
        SupervisorConfig(max_restarts=2, backoff_base_s=0.0),
        run_child=_fake_child([-9, -9, -9]),
        sleep=lambda s: None,
    )
    # a raw -9 would be truncated mod 256 by the OS; signal deaths
    # propagate as the shell convention 128+signum
    assert sup.run() == 128 + 9
    assert sup.restarts == 2


def test_supervisor_backoff_is_exponential_and_resets_when_healthy():
    sleeps = []
    clock = {"t": 0.0}
    script = iter([(1.0, RESUMABLE_EXIT_CODE), (1.0, RESUMABLE_EXIT_CODE),
                   (1000.0, RESUMABLE_EXIT_CODE), (1.0, 0)])

    def run(argv):
        runtime, rc = next(script)
        clock["t"] += runtime
        return rc

    sup = Supervisor(
        ["child"],
        SupervisorConfig(
            max_restarts=10, backoff_base_s=1.0, backoff_max_s=60.0,
            healthy_runtime_s=600.0,
        ),
        run_child=run,
        sleep=sleeps.append,
        clock=lambda: clock["t"],
    )
    assert sup.run() == 0
    # 1.0, 2.0 (two crash-loops), then the healthy child reset -> 1.0
    assert sleeps == [1.0, 2.0, 1.0]


def test_supervisor_uses_relaunch_argv_after_first_launch():
    seen = []

    def run(argv):
        seen.append(list(argv))
        return RESUMABLE_EXIT_CODE if len(seen) == 1 else 0

    sup = Supervisor(
        ["fit", "--ckpt-path", "3"],
        SupervisorConfig(backoff_base_s=0.0),
        run_child=run,
        sleep=lambda s: None,
        relaunch_argv=["fit"],
    )
    assert sup.run() == 0
    assert seen == [["fit", "--ckpt-path", "3"], ["fit"]]


def test_supervisor_runs_real_child_processes(tmp_path):
    """End to end with actual subprocesses: the child exits 75 until a
    marker file exists (it creates it on the first run), then 0."""
    marker = tmp_path / "resumed"
    child = (
        "import pathlib, sys; m = pathlib.Path(sys.argv[1]); "
        "sys.exit(0) if m.exists() else (m.touch(), sys.exit(75))"
    )
    sup = Supervisor(
        [sys.executable, "-c", child, str(marker)],
        SupervisorConfig(max_restarts=3, backoff_base_s=0.0,
                         log_path=str(tmp_path / "supervisor.jsonl")),
    )
    assert sup.run() == 0
    assert sup.restarts == 1


# ---------------------------------------------------------------- CLI codes


def _tiny_cli_config(tmp_path) -> Path:
    config = {
        "trainer": {"max_steps": 2},
        "model": {
            "class_path": "llm_training_tpu.lms.CLM",
            "init_args": {
                "model": {
                    "model_class": "llm_training_tpu.models.Llama",
                    "model_kwargs": {
                        "vocab_size": 64, "hidden_size": 16,
                        "intermediate_size": 32, "num_hidden_layers": 1,
                        "num_attention_heads": 2, "num_key_value_heads": 2,
                        "max_position_embeddings": 32,
                    },
                },
                "optim": {"learning_rate": 1e-3},
            },
        },
        "data": {
            "class_path": "llm_training_tpu.data.DummyDataModule",
            "init_args": {"batch_size": 8, "max_length": 16, "num_samples": 16,
                          "vocab_size": 64},
        },
    }
    path = tmp_path / "config.yaml"
    path.write_text(yaml.safe_dump(config))
    return path


@pytest.mark.parametrize(
    "error,expected",
    [
        (RecoveryExhaustedError("budget gone", step=4), RECOVERY_EXHAUSTED_EXIT_CODE),
        ("LossSpikeError", LOSS_SPIKE_EXIT_CODE),
        ("NonFiniteLossError", NON_FINITE_EXIT_CODE),
    ],
)
def test_cli_maps_recovery_errors_to_documented_codes(
    tmp_path, monkeypatch, error, expected
):
    from llm_training_tpu.callbacks.nan_guard import (
        LossSpikeError,
        NonFiniteLossError,
    )
    from llm_training_tpu.cli.main import main
    from llm_training_tpu.trainer import Trainer

    if error == "LossSpikeError":
        error = LossSpikeError("spiked")
    elif error == "NonFiniteLossError":
        error = NonFiniteLossError("diverged")

    def fake_fit(self, objective, datamodule, resume_step=None, state=None):
        raise error

    monkeypatch.setattr(Trainer, "fit", fake_fit)
    assert main(["fit", "--config", str(_tiny_cli_config(tmp_path))]) == expected
    # the contract is documented and distinct
    assert len({RESUMABLE_EXIT_CODE, RECOVERY_EXHAUSTED_EXIT_CODE,
                LOSS_SPIKE_EXIT_CODE, NON_FINITE_EXIT_CODE}) == 4


# ---------------------------------------------------------------- report


def test_report_renders_recovery_section(tmp_path):
    from llm_training_tpu.telemetry.report import render_report

    (tmp_path / "metrics.jsonl").write_text(
        json.dumps({"step": 1, "loss": 2.0, "steps_per_sec": 1.0}) + "\n"
    )
    (tmp_path / "telemetry.jsonl").write_text(
        json.dumps({
            "step": 1, "goodput/total_s": 10.0, "goodput/step_compute_s": 8.0,
            "resilience/rollbacks": 1.0, "resilience/skip_windows": 1.0,
            "resilience/skipped_steps": 2.0, "resilience/lr_cooldowns": 1.0,
        }) + "\n"
    )
    report = render_report(tmp_path)
    assert "== Recovery ==" in report
    assert "in-process rollbacks (rewind + resume): 1" in report
    assert "micro-steps served from the reserve pool: 2" in report


def test_report_omits_recovery_section_for_clean_runs(tmp_path):
    from llm_training_tpu.telemetry.report import render_report

    (tmp_path / "metrics.jsonl").write_text(
        json.dumps({"step": 1, "loss": 2.0}) + "\n"
    )
    (tmp_path / "telemetry.jsonl").write_text(
        json.dumps({"step": 1, "goodput/total_s": 10.0,
                    "resilience/rollbacks": 0.0}) + "\n"
    )
    assert "== Recovery ==" not in render_report(tmp_path)


# ---------------------------------------------------------------- fit-level


TINY_MODEL = dict(
    model_class="llm_training_tpu.models.Llama",
    model_kwargs=dict(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=64, attention_impl="xla",
        param_dtype="float32", compute_dtype="float32",
    ),
)


def _objective():
    from llm_training_tpu.lms import CLM, CLMConfig, ModelProvider
    from llm_training_tpu.optim import OptimConfig

    return CLM(
        CLMConfig(
            model=ModelProvider(**TINY_MODEL),
            optim=OptimConfig(learning_rate=1e-3, warmup_steps=2,
                              lr_scheduler="constant"),
        )
    )


def _data():
    return DummyDataModule(
        DummyDataModuleConfig(batch_size=8, max_length=32, num_samples=64,
                              vocab_size=128)
    )


class _Rec:
    def __init__(self):
        self.losses = {}

    def on_step_end(self, trainer, step, metrics):
        self.losses[step] = float(metrics["loss"])


def _trainer(tmp_path, name, callbacks, **overrides):
    from llm_training_tpu.trainer import Trainer, TrainerConfig
    from llm_training_tpu.trainer.checkpoint import CheckpointConfig, Checkpointer

    kwargs = dict(max_steps=6, log_every_n_steps=1, checkpoint_every_n_steps=2)
    kwargs.update(overrides)
    return Trainer(
        TrainerConfig(**kwargs),
        callbacks=callbacks,
        checkpointer=Checkpointer(
            CheckpointConfig(dirpath=str(tmp_path / name), async_save=False)
        ),
    )


@pytest.mark.slow
def test_chaos_nan_self_heals_and_matches_clean_skip_run(devices, tmp_path):
    """The acceptance path: chaos NaN at step 4 -> NanGuard raises ->
    rollback to the step-2 checkpoint IN-PROCESS -> skip micro-step 3 ->
    run completes with rollbacks == 1 and losses identical to a clean run
    configured to skip the same window."""
    rec_heal = _Rec()
    healed = _trainer(
        tmp_path, "heal",
        [rec_heal, NanGuard(NanGuardConfig(patience=0, action="raise"))],
        resilience=ResilienceConfig(
            chaos=ChaosConfig(nan_step=4),
            recovery=RecoveryConfig(max_rollbacks=3, skip_window_steps=1),
        ),
    )
    state = healed.fit(_objective(), _data())
    assert int(jax.device_get(state.step)) == 6  # SAME process, no relaunch
    snapshot = healed.telemetry.snapshot()
    assert snapshot["resilience/rollbacks"] == 1
    assert snapshot["resilience/skip_windows"] == 1
    assert snapshot["resilience/skipped_steps"] == 1

    rec_clean = _Rec()
    clean = _trainer(
        tmp_path, "clean", [rec_clean],
        resilience=ResilienceConfig(
            recovery=RecoveryConfig(
                max_rollbacks=3, skip_window_steps=1, skip_windows=((3, 1),)
            ),
        ),
    )
    clean.fit(_objective(), _data())
    # post-rollback steps replay against the skip list: every loss the two
    # runs share must match exactly
    for step in (5, 6):
        np.testing.assert_allclose(
            rec_heal.losses[step], rec_clean.losses[step], rtol=1e-6,
            err_msg=f"step {step}",
        )
    assert healed.counters == clean.counters


@pytest.mark.slow
def test_rollback_restores_loss_exact_state(devices, tmp_path):
    """The replayed step right after a rollback must reproduce the loss a
    clean run saw at that step (the restore is value-exact and the data
    stream repositions correctly)."""
    rec_plain = _Rec()
    plain = _trainer(
        tmp_path, "plain", [rec_plain],
        resilience=ResilienceConfig(
            recovery=RecoveryConfig(max_rollbacks=2, skip_window_steps=1,
                                    skip_windows=((3, 1),))
        ),
    )
    plain.fit(_objective(), _data())

    rec_heal = _Rec()
    healed = _trainer(
        tmp_path, "healed",
        [rec_heal, NanGuard(NanGuardConfig(patience=0, action="raise"))],
        resilience=ResilienceConfig(
            chaos=ChaosConfig(nan_step=4),
            recovery=RecoveryConfig(max_rollbacks=2, skip_window_steps=1),
        ),
    )
    healed.fit(_objective(), _data())
    # step 3 replays the same (unskipped) batch the clean run served at
    # step 3 from the restored step-2 state: loss must match exactly
    np.testing.assert_allclose(rec_heal.losses[3], rec_plain.losses[3], rtol=1e-6)


@pytest.mark.slow
def test_recovery_budget_exhaustion_escalates_in_fit(devices, tmp_path):
    """A failure that data-skipping cannot cure (poisoned objective) burns
    the budget and escalates with RecoveryExhaustedError."""
    import jax.numpy as jnp

    from llm_training_tpu.lms import CLM, CLMConfig, ModelProvider
    from llm_training_tpu.lms.clm import _get_path
    from llm_training_tpu.optim import OptimConfig
    from llm_training_tpu.trainer import Trainer, TrainerConfig

    class PoisonedCLM(CLM):
        def loss_and_metrics(self, params, batch, rng=None, train=True,
                             with_health=False):
            loss, metrics = super().loss_and_metrics(
                params, batch, rng=rng, train=train, with_health=with_health
            )
            p = params["params"] if "params" in params else params
            embed = _get_path(p, self.model.get_input_embeddings_path())
            loss = loss + jnp.float32(0.0) * (
                jnp.float32(jnp.inf) * embed.astype(jnp.float32).sum()
            )
            metrics["loss"] = loss
            return loss, metrics

    objective = PoisonedCLM(
        CLMConfig(model=ModelProvider(**TINY_MODEL),
                  optim=OptimConfig(learning_rate=1e-3, lr_scheduler="constant"))
    )
    trainer = Trainer(
        TrainerConfig(
            max_steps=4, log_every_n_steps=1,
            resilience=ResilienceConfig(
                recovery=RecoveryConfig(max_rollbacks=2, escalate_after=1)
            ),
        ),
        callbacks=[NanGuard(NanGuardConfig(patience=0, action="raise"))],
    )
    with pytest.raises(RecoveryExhaustedError):
        trainer.fit(objective, _data())
    snapshot = trainer.telemetry.snapshot()
    assert snapshot["resilience/recovery_escalations"] == 1
    assert snapshot["resilience/rollbacks"] >= 1


@pytest.mark.slow
def test_lr_cooldown_applies_after_rollback_and_expires(devices, tmp_path):
    rec = _Rec()

    class LrRec:
        def __init__(self):
            self.lrs = {}

        def on_step_end(self, trainer, step, metrics):
            self.lrs[step] = float(metrics["lr"])

    lrs = LrRec()
    trainer = _trainer(
        tmp_path, "cooldown",
        [rec, lrs, NanGuard(NanGuardConfig(patience=0, action="raise"))],
        max_steps=8,
        resilience=ResilienceConfig(
            chaos=ChaosConfig(nan_step=4),
            recovery=RecoveryConfig(
                max_rollbacks=2, skip_window_steps=1,
                lr_cooldown_factor=0.1, lr_cooldown_steps=2,
            ),
        ),
    )
    trainer.fit(_objective(), _data())
    assert trainer.telemetry.snapshot()["resilience/lr_cooldowns"] == 1
    base = lrs.lrs[8]
    # cooldown armed at restored opt step 2: the replayed step 3 logs the
    # cooled LR; by step 5 the window [2, 4) has expired on its own
    assert lrs.lrs[3] == pytest.approx(0.1 * base)
    assert lrs.lrs[5] == pytest.approx(base)


@pytest.mark.slow
def test_nan_guard_ema_state_survives_resume(devices, tmp_path):
    """After a preemption-style stop and relaunch, the spike detector must
    be armed immediately (its EMA state rides checkpoint metadata) instead
    of re-warming blind."""
    from llm_training_tpu.resilience import PreemptionInterrupt

    guard_a = NanGuard(NanGuardConfig(spike_zscore=6.0, spike_warmup_steps=3))
    t1 = _trainer(
        tmp_path, "resume", [guard_a],
        resilience=ResilienceConfig(chaos=ChaosConfig(sigterm_step=4)),
        checkpoint_every_n_steps=2, max_steps=8,
    )
    with pytest.raises(PreemptionInterrupt):
        t1.fit(_objective(), _data())
    warm_count = guard_a._detectors["loss"].count
    assert warm_count >= 3  # armed before the preemption

    guard_b = NanGuard(NanGuardConfig(spike_zscore=6.0, spike_warmup_steps=3))
    t2 = _trainer(
        tmp_path, "resume", [guard_b],
        checkpoint_every_n_steps=2, max_steps=8,
    )
    t2.fit(_objective(), _data())
    # the relaunch started from the persisted tracker, not from zero
    assert guard_b._detectors["loss"].count > warm_count
