"""DeepSeek V2/V3: MLA attention, grouped MoE routing, HF parity + round trip."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_training_tpu.models.deepseek import Deepseek, DeepseekConfig
from llm_training_tpu.models.deepseek.hf_conversion import (
    config_from_hf,
    config_to_hf,
    params_from_hf,
    params_to_hf,
)

TINY = dict(
    vocab_size=128,
    hidden_size=64,
    intermediate_size=112,
    moe_intermediate_size=48,
    num_hidden_layers=2,
    num_attention_heads=4,
    max_position_embeddings=64,
    q_lora_rank=24,
    kv_lora_rank=32,
    qk_rope_head_dim=16,
    qk_nope_head_dim=32,
    v_head_dim=32,
    n_routed_experts=8,
    n_shared_experts=2,
    num_experts_per_tok=2,
    first_k_dense_replace=1,
    compute_dtype="float32",
)


def _hf_tiny(cls_name, **extra):
    torch = pytest.importorskip("torch")
    import transformers

    config_cls = getattr(transformers, cls_name + "Config")
    model_cls = getattr(transformers, cls_name + "ForCausalLM")
    kwargs = dict(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        moe_intermediate_size=48, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=4, max_position_embeddings=64,
        q_lora_rank=24, kv_lora_rank=32, qk_rope_head_dim=16,
        qk_nope_head_dim=32, v_head_dim=32, n_routed_experts=8,
        n_shared_experts=2, num_experts_per_tok=2, first_k_dense_replace=1,
        attn_implementation="eager",
    )
    kwargs.update(extra)
    hf_config = config_cls(**kwargs)
    torch.manual_seed(0)
    return model_cls(hf_config).eval(), hf_config


def _parity(hf_model, hf_config, seed):
    torch = pytest.importorskip("torch")
    cfg = config_from_hf(hf_config, compute_dtype="float32", moe_impl="dense")
    params = params_from_hf(hf_model.state_dict(), cfg)
    model = Deepseek(cfg)
    ids = np.random.default_rng(seed).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=3e-4, atol=3e-4)
    return cfg, params, model


def test_logits_parity_with_hf_deepseek_v3():
    """V3: MLA + sigmoid router with e_score_correction_bias and top-2-sum
    group selection; layer 0 dense (first_k_dense_replace=1), layer 1 MoE
    with 2 shared experts."""
    torch = pytest.importorskip("torch")
    hf_model, hf_config = _hf_tiny(
        "DeepseekV3", n_group=4, topk_group=2, routed_scaling_factor=2.5,
        norm_topk_prob=True, rope_interleave=True,
    )
    sd = hf_model.state_dict()
    assert "model.layers.1.mlp.gate.e_score_correction_bias" in sd
    assert "model.layers.0.mlp.gate_proj.weight" in sd  # dense prefix
    assert "model.layers.1.mlp.experts.7.down_proj.weight" in sd
    # make the noaux bias actually change the selection
    with torch.no_grad():
        sd["model.layers.1.mlp.gate.e_score_correction_bias"].copy_(
            torch.linspace(-0.2, 0.2, 8)
        )
    cfg, _, _ = _parity(hf_model, hf_config, seed=30)
    assert cfg.version == 3 and cfg.rope_interleave
    assert cfg.routed_scaling_factor == 2.5 and cfg.n_group == 4


def test_kimi_k2_routes_as_deepseek_v3():
    """Kimi-K2 ships the DeepSeek-V3 graph/key layout verbatim under
    `model_type: kimi_k2`: the router must select the Deepseek family and
    the conversion must run in v3 mode, with logits parity against the HF
    DeepseekV3 reference the checkpoint structure matches."""
    torch = pytest.importorskip("torch")
    from llm_training_tpu.models.hf_io import model_class_for_hf

    hf_model, hf_config = _hf_tiny("DeepseekV3", n_group=4, topk_group=2)
    hf_dict = hf_config.to_dict()
    hf_dict["model_type"] = "kimi_k2"
    assert model_class_for_hf(hf_dict) == "llm_training_tpu.models.Deepseek"
    cfg = config_from_hf(hf_dict, compute_dtype="float32", moe_impl="dense")
    assert cfg.version == 3
    params = params_from_hf(hf_model.state_dict(), cfg)
    ids = np.random.default_rng(31).integers(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = Deepseek(cfg).apply(params, jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=3e-4, atol=3e-4)


def test_logits_parity_with_hf_deepseek_v2_greedy():
    """V2-Lite-style: softmax scores, plain greedy top-k."""
    hf_model, hf_config = _hf_tiny(
        "DeepseekV2", topk_method="greedy", routed_scaling_factor=1.0,
    )
    cfg, _, _ = _parity(hf_model, hf_config, seed=31)
    assert cfg.version == 2 and cfg.topk_method == "greedy"


def test_logits_parity_with_hf_deepseek_v2_group_limited():
    """V2/V2-Chat-style: group-limited greedy (per-group max selection)."""
    hf_model, hf_config = _hf_tiny(
        "DeepseekV2", topk_method="group_limited_greedy", n_group=4,
        topk_group=2, routed_scaling_factor=16.0,
    )
    cfg, _, _ = _parity(hf_model, hf_config, seed=32)
    assert cfg.topk_method == "group_limited_greedy"


def test_full_rank_q_when_lora_disabled():
    """q_lora_rank=None uses the single full-rank q projection (V2-Lite)."""
    hf_model, hf_config = _hf_tiny("DeepseekV2", q_lora_rank=None)
    sd = hf_model.state_dict()
    assert "model.layers.0.self_attn.q_proj.weight" in sd
    assert "model.layers.0.self_attn.q_a_proj.weight" not in sd
    cfg, _, _ = _parity(hf_model, hf_config, seed=33)
    assert cfg.q_lora_rank is None


def test_hf_round_trip():
    """params -> HF -> params is exact, including stacked expert weights and
    the v3 router bias."""
    hf_model, hf_config = _hf_tiny("DeepseekV3", n_group=4, topk_group=2)
    cfg = config_from_hf(hf_config)
    params = params_from_hf(hf_model.state_dict(), cfg)
    back = params_to_hf(params, cfg)
    sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    assert set(back) == set(sd)
    for key in sd:
        np.testing.assert_array_equal(back[key], sd[key], err_msg=key)


def test_config_round_trip():
    cfg = DeepseekConfig(**TINY, n_group=4, topk_group=2)
    hf = config_to_hf(cfg)
    assert hf["model_type"] == "deepseek_v3"
    cfg2 = config_from_hf(hf, compute_dtype="float32")
    assert cfg2.model_dump() == cfg.model_dump()


@pytest.mark.slow
def test_ragged_and_dense_impls_agree():
    cfg_d = DeepseekConfig(**TINY, n_group=4, topk_group=2, moe_impl="dense")
    cfg_r = DeepseekConfig(**TINY, n_group=4, topk_group=2, moe_impl="ragged")
    model_d, model_r = Deepseek(cfg_d), Deepseek(cfg_r)
    ids = jnp.asarray(np.random.default_rng(34).integers(0, 128, (2, 16)))
    params = model_d.init(jax.random.key(7), ids)
    out_d = model_d.apply(params, ids).logits
    out_r = model_r.apply(params, ids).logits
    np.testing.assert_allclose(np.asarray(out_d), np.asarray(out_r), rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_e2e_fit_decreases_loss():
    """Tiny DeepSeek V3 trains end to end (MLA + MoE under jit/grad/remat)."""
    from llm_training_tpu.data import DummyDataModule, DummyDataModuleConfig
    from llm_training_tpu.lms import CLM, CLMConfig, ModelProvider
    from llm_training_tpu.optim import OptimConfig
    from llm_training_tpu.parallel import MeshConfig
    from llm_training_tpu.trainer import Trainer, TrainerConfig

    objective = CLM(CLMConfig(
        model=ModelProvider(
            model_class="llm_training_tpu.models.Deepseek",
            model_kwargs=dict(
                TINY, n_group=4, topk_group=2,
                enable_gradient_checkpointing=True,
            ),
        ),
        optim=OptimConfig(learning_rate=3e-3, warmup_steps=2),
    ))
    data = DummyDataModule(DummyDataModuleConfig(
        batch_size=8, max_length=32, num_samples=64, vocab_size=128,
    ))
    losses = []

    class Track:
        def on_step_end(self, trainer, step, metrics):
            losses.append(float(metrics["loss"]))

    Trainer(
        TrainerConfig(max_steps=20, log_every_n_steps=1, mesh=MeshConfig()),
        callbacks=[Track()],
    ).fit(objective, data)
    assert np.mean(losses[-3:]) < np.mean(losses[:3])


@pytest.mark.slow
def test_export_reloads_in_transformers(tmp_path):
    torch = pytest.importorskip("torch")
    from transformers import AutoModelForCausalLM

    from llm_training_tpu.models.hf_io import save_hf_checkpoint

    cfg = DeepseekConfig(**TINY, n_group=4, topk_group=2)
    model = Deepseek(cfg)
    ids = jnp.asarray(np.random.default_rng(35).integers(0, 128, (2, 16)))
    params = model.init(jax.random.key(8), ids)
    out_dir = save_hf_checkpoint(params, cfg, tmp_path / "export", dtype="float32")

    hf_model = AutoModelForCausalLM.from_pretrained(
        out_dir, attn_implementation="eager"
    ).eval()
    assert type(hf_model).__name__ == "DeepseekV3ForCausalLM"
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(np.asarray(ids))).logits.numpy()
    ours = model.apply(params, ids).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=3e-4, atol=3e-4)


def test_v2_greedy_ignores_groups():
    """HF V2 only group-masks under topk_method='group_limited_greedy'; a
    greedy config that happens to carry n_group/topk_group must route over
    ALL experts (parity would break if the mask applied)."""
    hf_model, hf_config = _hf_tiny(
        "DeepseekV2", topk_method="greedy", n_group=4, topk_group=1,
    )
    cfg, _, _ = _parity(hf_model, hf_config, seed=36)
    assert cfg.topk_method == "greedy" and cfg.n_group == 4


@pytest.mark.slow
def test_sharded_fit_matches_single_device(devices):
    """The MLA + MoE logical axes must compose with a real fsdp x tensor
    mesh: losses on the sharded mesh equal the single-device run."""
    from conftest import fit_losses
    from llm_training_tpu.parallel import MeshConfig

    kwargs = dict(TINY, n_group=4, topk_group=2, num_attention_heads=4, moe_impl="dense")
    single = fit_losses("llm_training_tpu.models.Deepseek", kwargs)
    sharded = fit_losses(
        "llm_training_tpu.models.Deepseek", kwargs,
        mesh=MeshConfig(fsdp_size=4, tensor_parallel_size=2),
    )
    np.testing.assert_allclose(single, sharded, rtol=2e-4)


@pytest.mark.slow
def test_hf_causal_lm_loads_deepseek_checkpoint(tmp_path):
    """End-to-end: HF checkpoint dir -> HFCausalLM router -> Deepseek module
    -> streamed weights -> logits parity (the reference's `HFCausalLM`
    wrapping, `hf_causal_lm.py:22`, for the newest family class)."""
    torch = pytest.importorskip("torch")
    from llm_training_tpu.models import HFCausalLM, HFCausalLMConfig
    from llm_training_tpu.models.hf_io import load_pretrained_params

    hf_model, _ = _hf_tiny("DeepseekV3", n_group=4, topk_group=2)
    hf_model.save_pretrained(tmp_path / "dsv3", safe_serialization=True)

    model = HFCausalLM(HFCausalLMConfig(
        hf_path=str(tmp_path / "dsv3"), compute_dtype="float32",
        moe_impl="dense",
    ))
    assert isinstance(model, Deepseek)
    params = load_pretrained_params(model.config, tmp_path / "dsv3")

    ids = np.random.default_rng(37).integers(0, 128, (2, 16))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply(jax.tree.map(jnp.asarray, params), jnp.asarray(ids)).logits
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=3e-4, atol=3e-4)
