"""Expert parallelism: shard_map EP dispatch vs the exact dense path, and
the expert-axis sharding rules.

VERDICT r3 #2: the `expert` mesh axis (parallel/mesh.py) shards the stacked
expert parameters' leading dim and switches `dropless_moe_apply` to the
all-gather + local-ragged + reduce-scatter EP path (models/moe.py). The
reference has no MoE training path at all, so the correctness bar is
internal: EP output == dense-every-expert output on the same weights.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_training_tpu.models import Llama, LlamaConfig
from llm_training_tpu.parallel.mesh import EXPERT_AXIS, MeshConfig, build_mesh
from llm_training_tpu.parallel.sharding import (
    DEFAULT_LOGICAL_AXIS_RULES,
    logical_to_spec,
)
from tests.test_moe import TINY_MOE


@pytest.fixture()
def ep_mesh(devices):
    return build_mesh(
        MeshConfig(fsdp_size=2, expert_parallel_size=2, tensor_parallel_size=2)
    )


def test_expert_rule_maps_to_expert_axis():
    spec = logical_to_spec(("expert", "embed", "mlp"), DEFAULT_LOGICAL_AXIS_RULES)
    assert spec == jax.sharding.PartitionSpec("expert", "fsdp", "tensor")
    # batch gains the expert axis as extra data parallelism
    batch_spec = logical_to_spec(("batch", "act_seq"), DEFAULT_LOGICAL_AXIS_RULES)
    assert "expert" in batch_spec[0]


def test_ep_dispatch_matches_dense(ep_mesh):
    """Same weights through the EP shard_map path (expert axis 2) and the
    exact every-expert dense path must agree: at ep=2 the default capacity
    factor 2.0 sizes each rank's buffer to ALL T·K rows, so drops are
    impossible and the comparison is exact."""
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 128, (4, 16)))
    cfg_r = LlamaConfig(**TINY_MOE, moe_impl="ragged")
    cfg_d = LlamaConfig(**TINY_MOE, moe_impl="dense")
    model_r, model_d = Llama(cfg_r), Llama(cfg_d)
    params = model_d.init(jax.random.key(0), ids)

    out_d = model_d.apply(params, ids)  # no mesh: plain dense reference
    with ep_mesh:
        out_ep = jax.jit(lambda p, x: model_r.apply(p, x).logits)(params, ids)
    np.testing.assert_allclose(
        np.asarray(out_ep), np.asarray(out_d.logits), rtol=2e-5, atol=2e-5
    )


def test_ep_grads_match_dense(ep_mesh):
    """The EP dispatch is fully differentiable (gather/scatter transpose);
    gradients must match the dense path's."""
    ids = jnp.asarray(np.random.default_rng(1).integers(0, 128, (2, 16)))
    cfg_r = LlamaConfig(**TINY_MOE, moe_impl="ragged")
    cfg_d = LlamaConfig(**TINY_MOE, moe_impl="dense")
    model_r, model_d = Llama(cfg_r), Llama(cfg_d)
    params = model_d.init(jax.random.key(1), ids)

    def loss(model):
        def f(p):
            return jnp.mean(model.apply(p, ids).logits.astype(jnp.float32) ** 2)
        return f

    g_d = jax.grad(loss(model_d))(params)
    with ep_mesh:
        g_ep = jax.jit(jax.grad(loss(model_r)))(params)
    flat_d, flat_ep = jax.tree.leaves(g_d), jax.tree.leaves(g_ep)
    for a, b in zip(flat_d, flat_ep):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5
        )


def test_ep_dropped_rows_match_capacity_math(ep_mesh):
    """Adversarial routing (every token to ONE expert) must report exactly
    the rows the static capacity buffer cannot hold — the silent-drop hazard
    VERDICT r4 flagged, now surfaced as a counter."""
    from llm_training_tpu.models.moe import dropless_moe_apply

    T, H, E, K = 32, 8, 4, 2
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((T, H)), jnp.float32)
    topk_idx = jnp.zeros((T, K), jnp.int32)  # all T*K rows -> expert 0
    topk_w = jnp.full((T, K), 0.5, jnp.float32)
    w = jnp.asarray(rng.standard_normal((E, H, H)) * 0.1, jnp.float32)

    def dense_fn(xc):
        return jnp.einsum("th,ehg->teg", xc, w)

    def ragged_fn(xs, gs, order, wl):
        return jax.lax.ragged_dot(xs, wl[0], gs)

    def run(factor):
        out, dropped = dropless_moe_apply(
            x, topk_idx, topk_w, E, "ragged", dense_fn, ragged_fn,
            weights=(w,), ep_capacity_factor=factor,
        )
        return out, dropped

    with ep_mesh:
        out, dropped = jax.jit(run, static_argnums=0)(0.5)
        # ep=2: capacity = ceil(T*K/ep * 0.5) = 16 rows/rank; all 64 rows
        # route to rank 0's expert -> 64 - 16 = 48 dropped, psum'd
        assert int(jax.device_get(dropped)) == 48
        assert bool(jnp.all(jnp.isfinite(out)))

        # the default factor 2.0 at ep=2 sizes the buffer to ALL T*K rows:
        # even fully-imbalanced routing cannot drop
        _, dropped_full = jax.jit(run, static_argnums=0)(2.0)
        assert int(jax.device_get(dropped_full)) == 0


def test_ep_dropped_rows_metric_flows_to_output(ep_mesh):
    """The counter reaches CausalLMOutput (and thus CLM's train metrics)."""
    ids = jnp.asarray(np.random.default_rng(3).integers(0, 128, (4, 16)))
    cfg = LlamaConfig(**TINY_MOE, moe_impl="ragged")
    model = Llama(cfg)
    params = model.init(jax.random.key(0), ids)
    with ep_mesh:
        out = jax.jit(lambda p, x: model.apply(p, x))(params, ids)
    assert out.ep_dropped_rows is not None
    # default capacity factor 2.0 at ep=2 -> drops impossible
    assert float(jax.device_get(out.ep_dropped_rows)) == 0.0


def test_ep_dropped_rows_flow_deepseek_scan_route(ep_mesh):
    """The counter also flows through the dense-prefix + scanned-suffix
    plumbing (DeepSeek — the EP flagship; GLM-4.5/Ernie/HunYuan share the
    pattern)."""
    from llm_training_tpu.models import Deepseek, DeepseekConfig
    from tests.test_deepseek import TINY

    ids = jnp.asarray(np.random.default_rng(4).integers(0, 128, (2, 16)))
    model = Deepseek(DeepseekConfig(**TINY, n_group=4, topk_group=2, moe_impl="ragged"))
    params = model.init(jax.random.key(0), ids)
    with ep_mesh:
        out = jax.jit(lambda p, x: model.apply(p, x))(params, ids)
    assert out.ep_dropped_rows is not None
    assert float(jax.device_get(out.ep_dropped_rows)) == 0.0  # factor 2 @ ep=2


def test_ep_requires_divisible_experts(ep_mesh):
    cfg = LlamaConfig(**{**TINY_MOE, "num_experts": 3, "num_experts_per_tok": 2},
                      moe_impl="ragged")
    model = Llama(cfg)
    ids = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.key(0), ids)
    with ep_mesh:
        with pytest.raises(ValueError, match="divide"):
            jax.jit(lambda p, x: model.apply(p, x).logits)(params, ids)
