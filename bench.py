"""Benchmark: CLM train-step throughput + MFU on the available chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
Target (BASELINE.md): ≥55% MFU on Llama-3-8B class workloads; on the single
bench chip we measure a scaled-down Llama with the same arithmetic shape and
report MFU fraction with vs_baseline = mfu / 0.55.
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

# peak bf16 FLOP/s per chip by TPU generation (public specs)
_PEAK_FLOPS = {
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6e": 918e12,
    "cpu": 1e12,  # nominal, so CPU runs still print a line
}


def _detect_peak() -> float:
    import os

    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "")
    if gen in _PEAK_FLOPS:
        return _PEAK_FLOPS[gen]
    # device_kind strings: 'TPU v5 lite' == v5e, 'TPU v6 lite' == v6e,
    # 'TPU v5p'/'TPU v5' == v5p, 'TPU v4' == v4
    kind = jax.devices()[0].device_kind.lower()
    if "v5 lite" in kind or "v5e" in kind:
        return _PEAK_FLOPS["v5e"]
    if "v6 lite" in kind or "v6e" in kind:
        return _PEAK_FLOPS["v6e"]
    if "v5" in kind:
        return _PEAK_FLOPS["v5p"]
    if "v4" in kind:
        return _PEAK_FLOPS["v4"]
    return _PEAK_FLOPS["cpu"]


def _watchdog(seconds: float, stage: str):
    """A wedged axon tunnel blocks jax calls FOREVER (r5: after a
    pathological remote compile, backend init AND in-flight device fetches
    hung indefinitely). Emit a diagnosable JSON line and exit instead of
    hanging the driver. Re-armed per stage: a short fuse for backend init,
    a long one covering the compile+run (remote compiles are legitimately
    ~30-90s each)."""
    import threading

    def fire():
        print(json.dumps({
            "metric": "llama_clm_train_mfu",
            "value": None,
            "unit": "mfu_fraction",
            "vs_baseline": None,
            "error": f"jax {stage} unresponsive after {seconds:.0f}s "
                     "(axon tunnel wedged?) — bench did not finish",
        }), flush=True)
        os._exit(3)

    timer = threading.Timer(seconds, fire)
    timer.daemon = True
    timer.start()
    return timer


def main() -> None:
    from llm_training_tpu.data import DummyDataModule, DummyDataModuleConfig
    from llm_training_tpu.lms import CLM, CLMConfig, ModelProvider
    from llm_training_tpu.optim import OptimConfig
    from llm_training_tpu.parallel import MeshConfig
    from llm_training_tpu.trainer import Trainer, TrainerConfig

    watchdog = _watchdog(
        float(os.environ.get("BENCH_BACKEND_TIMEOUT", 300)), "backend init"
    )
    on_tpu = jax.default_backend() == "tpu"
    watchdog.cancel()
    # the r5 wedge incidents struck DURING remote compiles, not just init —
    # keep a long fuse armed over the whole compile+run
    watchdog = _watchdog(
        float(os.environ.get("BENCH_RUN_TIMEOUT", 2400)), "compile/run"
    )
    bench_model = os.environ.get("BENCH_MODEL", "8b-layer")
    if bench_model == "8b-layer":
        # north-star layer proxy (the DEFAULT bench): the EXACT Llama-3-8B
        # per-layer shape (h4096, inter 14336, 32q+8kv heads, head_dim 128)
        # at seq 8192 — few layers so params + fp32 Adam masters fit 16G HBM.
        # This measures the matmul/attention mix the 8B runs, per layer;
        # depth only amortizes the (already-small) embed/CE ends. r4 sweep:
        # SELECTIVE remat (save flash_out+lse — attention never recomputes)
        # at batch 3 wins: 0.716-0.721 > B3/full 0.69-0.70 > B4/selective
        # 0.681 > B2/selective 0.674 ≈ B2/full 0.654-0.673 > B4/full 0.632
        # > L3/B1 0.509 (B6/selective and L3/B2 OOM; batch response is
        # non-monotone — XLA scheduling). The h4096 shapes beat the 697M
        # proxy (0.567): bigger MXU tiles win, and selective remat breaks
        # the ~0.75 full-remat convention ceiling.
        model_kwargs = dict(
            vocab_size=32000,
            hidden_size=4096,
            intermediate_size=14336,
            num_hidden_layers=2,
            num_attention_heads=32,
            num_key_value_heads=8,
            head_dim=128,
            max_position_embeddings=8192,
            enable_gradient_checkpointing=True,
            recompute_granularity="selective",
        )
        default_seq, default_batch = 8192, 3
    elif bench_model == "697m":
        # ~700M-param Llama (largest that fits 16G HBM with fp32 Adam masters):
        # hidden 2048 pushes arithmetic intensity toward the 8B north star —
        # attention + elementwise cost shrinks relative to matmul FLOPs as hidden
        # grows, worth +0.018 MFU over the 317M/hidden-1024 proxy (r3 sweep:
        # 697M@B16 0.5665 > 697M@B20 0.5638 > 317M@B64 0.549; B24+ and an
        # 824M/hidden-2560 variant OOM). head_dim 128 is the MXU-native
        # contraction (22% faster than head_dim 64 at equal params, r1).
        model_kwargs = dict(
            vocab_size=32000,
            hidden_size=2048,
            intermediate_size=5632,
            num_hidden_layers=12,
            num_attention_heads=16,
            num_key_value_heads=8,
            head_dim=128,
            max_position_embeddings=2048,
            # full remat is mandatory on a 16G-HBM chip: no-remat needs 22G even
            # at batch 8; selective (save flash_out+lse) compiles to 15.9-18.5G
            # at batch 56-64 (r3 — XLA fragmentation varies non-monotonically
            # with batch) vs the 15.75G budget. MFU ceiling under the
            # no-recompute-credit convention is ~0.75 with full remat
            enable_gradient_checkpointing=True,
            recompute_granularity="full",
        )
        default_seq, default_batch = 2048, 16
    elif bench_model == "moe":
        # MoE proxy at the 697M-class shape (VERDICT r3 #2): 8 experts,
        # top-2, expert width sized so TOTAL expert params/layer match the
        # 697M dense MLP (8·3·h·704 == 3·h·5632) — measures the dropless
        # sort/ragged_dot/scatter dispatch against the same memory budget.
        model_kwargs = dict(
            vocab_size=32000,
            hidden_size=2048,
            intermediate_size=5632,
            num_hidden_layers=12,
            num_attention_heads=16,
            num_key_value_heads=8,
            head_dim=128,
            max_position_embeddings=2048,
            num_experts=8,
            num_experts_per_tok=2,
            moe_intermediate_size=704,
            enable_gradient_checkpointing=True,
            recompute_granularity="full",
        )
        default_seq, default_batch = 2048, 16
    else:
        raise SystemExit(
            f"unknown BENCH_MODEL {bench_model!r}; use 8b-layer, 697m or moe"
        )
    # sweep overrides (experiments only; defaults above are the recorded bench)
    remat = os.environ.get("BENCH_REMAT")
    if remat == "none":
        model_kwargs.update(enable_gradient_checkpointing=False)
    elif remat in ("full", "selective"):
        model_kwargs.update(enable_gradient_checkpointing=True,
                            recompute_granularity=remat)
    for env, key in (("BENCH_HIDDEN", "hidden_size"), ("BENCH_INTER", "intermediate_size"),
                     ("BENCH_LAYERS", "num_hidden_layers"), ("BENCH_HEADS", "num_attention_heads"),
                     ("BENCH_KV", "num_key_value_heads")):
        if os.environ.get(env):
            model_kwargs[key] = int(os.environ[env])
    if os.environ.get("BENCH_SCAN"):
        model_kwargs["scan_layers"] = os.environ["BENCH_SCAN"] == "1"
    if os.environ.get("BENCH_MOE_IMPL"):  # ragged | bucketed | dense
        model_kwargs["moe_impl"] = os.environ["BENCH_MOE_IMPL"]
    if os.environ.get("BENCH_MOE_CAP"):  # bucketed per-expert capacity factor
        model_kwargs["moe_capacity_factor"] = float(os.environ["BENCH_MOE_CAP"])
    if not on_tpu:  # CPU smoke: tiny
        model_kwargs.update(hidden_size=128, intermediate_size=256, num_hidden_layers=2,
                            num_attention_heads=4, num_key_value_heads=2, head_dim=None,
                            vocab_size=2048)

    seq = int(os.environ.get("BENCH_SEQ", default_seq if on_tpu else 2048))
    batch = int(os.environ.get("BENCH_BATCH", default_batch)) if on_tpu else 4
    model_kwargs["max_position_embeddings"] = max(
        model_kwargs["max_position_embeddings"], seq
    )
    steps = 10 if on_tpu else 3
    warmup = 2 if on_tpu else 1

    objective = CLM(
        CLMConfig(
            model=ModelProvider(
                model_class="llm_training_tpu.models.Llama", model_kwargs=model_kwargs
            ),
            optim=OptimConfig(learning_rate=1e-4, warmup_steps=2),
            ce_chunk_size=int(os.environ.get("BENCH_CE_CHUNK", 2048)),
        )
    )
    n_dev = len(jax.devices())
    datamodule = DummyDataModule(
        DummyDataModuleConfig(
            batch_size=batch * max(1, n_dev), max_length=seq,
            num_samples=batch * max(1, n_dev) * 2, vocab_size=model_kwargs["vocab_size"],
        )
    )

    # Pipelined timing: sync ONCE after warmup and ONCE at the end. Real
    # training does not fetch metrics every step (log cadence is sparse), so
    # the honest throughput number lets host dispatch overlap device compute;
    # per-step device_get syncs would bill one tunnel round trip per step.
    # Default timing syncs once per step (block on the step's metrics, one
    # batched transfer) and reports the median step latency. Measured r3 on
    # the tunneled v5e: per-step sync runs AT DEVICE SPEED (2.789s/step ==
    # the jax.profiler device time), while free-running dispatch
    # (BENCH_TIMING=pipelined) is ~20% slower — unsynced host run-ahead
    # floods the remote-execute tunnel. Sync mode is also the conservative
    # measure: it bills one host round trip per step.
    sync_mode = os.environ.get("BENCH_TIMING", "sync") == "sync"

    def timed_fit(health_every=None):
        """One measured fit; `health_every` turns the model-health layer on
        (the A/B for `health_overhead_pct`)."""
        window = {}
        sync_times = []

        class Timer:
            # the fence fetches a real scalar: on the tunnel-attached chip
            # jax.block_until_ready can return before remote execution
            # finishes (measured r3), so only a data round trip proves the
            # step completed
            def on_train_step(self, trainer, step):
                if sync_mode:
                    jax.device_get(trainer.last_metrics["loss"])
                    sync_times.append(time.perf_counter())
                elif step == warmup:
                    jax.device_get(trainer.last_metrics["loss"])
                    window["t0"] = time.perf_counter()

            def on_step_end(self, trainer, step, metrics):
                # fires on log steps only; by config that is the final step,
                # and metrics arrive here already device_get (i.e. synced)
                if step == steps:
                    window["t1"] = time.perf_counter()

        callbacks = [Timer()]
        if os.environ.get("BENCH_PROFILE") and health_every is None:
            # capture a jax.profiler trace window (headline run only)
            from llm_training_tpu.callbacks import ProfilerCallback, ProfilerCallbackConfig

            callbacks.append(ProfilerCallback(ProfilerCallbackConfig(
                trace_dir=os.environ["BENCH_PROFILE"], start_step=4, num_steps=2,
            )))
        trainer = Trainer(
            TrainerConfig(
                max_steps=steps, log_every_n_steps=steps, mesh=MeshConfig(),
                # BENCH_OFFLOAD=1 parks fp32 mu/nu in pinned host memory (XLA
                # host offloading) — frees 8 bytes/param of HBM for bigger
                # models at a per-step transfer cost (recorded in BASELINE.md)
                offload_optimizer_state=bool(os.environ.get("BENCH_OFFLOAD")),
                # BENCH_OFFLOAD_DTYPE=int8|bfloat16 compresses the offloaded
                # state storage (quantized_state.py) to cut the host round trip
                offload_state_dtype=os.environ.get("BENCH_OFFLOAD_DTYPE", "float32"),
                health={"every_n_steps": health_every},
            ),
            callbacks=callbacks,
        )
        trainer.fit(objective, datamodule)

        if sync_mode:
            # intervals between consecutive post-warmup syncs; the slice
            # starts at warmup-1 so the first post-warmup interval is kept
            sec = float(np.median(np.diff(sync_times[warmup - 1:])))
        else:
            sec = (window["t1"] - window["t0"]) / (steps - warmup)
        return trainer, sec

    trainer, sec_per_step = timed_fit()
    # perf cost of the health instrumentation (per-layer norms + the host
    # fetch each health step): same fit with every_n_steps=1 vs disabled.
    # BENCH_HEALTH=0 skips the second fit (halves bench wall time)
    health_overhead_pct = None
    if os.environ.get("BENCH_HEALTH", "1") != "0":
        _, sec_health = timed_fit(health_every=1)
        health_overhead_pct = 100.0 * (sec_health - sec_per_step) / sec_per_step

    # decode-path gauge (docs/inference.md): a TINY-model generate run —
    # the headline bench model's fp32 state is torn down by the fits above,
    # and the gauge exists to track the decode program's dispatch/step
    # overhead trend, not model-scale decode throughput. BENCH_DECODE=0
    # skips it.
    prefill_time_s = decode_tokens_per_sec = None
    if os.environ.get("BENCH_DECODE", "1") != "0":
        from llm_training_tpu.infer import GenerateConfig, InferenceEngine
        from llm_training_tpu.models import Llama, LlamaConfig

        tiny = Llama(LlamaConfig(
            vocab_size=2048, hidden_size=128, intermediate_size=256,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=512,
            compute_dtype="float32" if not on_tpu else "bfloat16",
        ))
        variables = tiny.init(jax.random.key(0), np.zeros((1, 4), np.int32))
        engine = InferenceEngine(tiny, variables)
        prompts = [[int(t) for t in np.arange(1, 17) + 7 * row]
                   for row in range(4)]
        # warm-up generate absorbs the prefill/decode compiles so the
        # recorded prefill_time_s is a run number, not a compile number;
        # max_length pinned so both runs share one cache shape (and so one
        # compiled program)
        engine.generate(prompts, GenerateConfig(max_new_tokens=4, max_length=48))
        decode_stats = engine.generate(
            prompts, GenerateConfig(max_new_tokens=32, max_length=48)
        )["stats"]
        prefill_time_s = round(decode_stats["decode/prefill_time_s"], 4)
        decode_tokens_per_sec = round(decode_stats["decode/tokens_per_sec"], 1)
    tokens_per_step = batch * max(1, n_dev) * seq
    tokens_per_sec = tokens_per_step / sec_per_step
    tokens_per_sec_chip = tokens_per_sec / max(1, n_dev)

    cfg = objective.model.config
    attn_params = (
        cfg.hidden_size * (cfg.num_attention_heads + 2 * cfg.num_key_value_heads)
        * cfg.resolved_head_dim
        + cfg.num_attention_heads * cfg.resolved_head_dim * cfg.hidden_size
        + 2 * cfg.hidden_size
    )
    if cfg.num_experts:
        expert_mlp = 3 * cfg.hidden_size * cfg.moe_intermediate_size
        router = cfg.hidden_size * cfg.num_experts
        n_params = (
            cfg.vocab_size * cfg.hidden_size * 2
            + cfg.num_hidden_layers
            * (attn_params + router + cfg.num_experts * expert_mlp)
        )
        # MoE MFU credits ACTIVATED params only (top-k experts per token) —
        # the standard sparse-model convention; total params still reported
        n_active = (
            cfg.vocab_size * cfg.hidden_size * 2
            + cfg.num_hidden_layers
            * (attn_params + router + cfg.num_experts_per_tok * expert_mlp)
        )
    else:
        n_params = n_active = (
            cfg.vocab_size * cfg.hidden_size * 2
            + cfg.num_hidden_layers
            * (attn_params + 3 * cfg.hidden_size * cfg.intermediate_size)
        )
    # standard MFU convention (PaLM appendix B): model FLOPs only — 6N per
    # token fwd+bwd plus the attention quadratic 12·L·h·S; rematerialization
    # is NOT credited (it is overhead, not useful work)
    flops_per_token = 6 * n_active + 12 * cfg.num_hidden_layers * cfg.hidden_size * seq
    mfu = tokens_per_sec_chip * flops_per_token / _detect_peak()

    watchdog.cancel()
    # goodput/telemetry extras so BENCH_* rounds can attribute regressions
    # to compile/data/step shifts, not just the MFU headline
    goodput = trainer.ledger.summary()
    snapshot = trainer.telemetry.snapshot()
    print(json.dumps({
        "metric": "llama_clm_train_mfu",
        "value": round(mfu, 4),
        "unit": "mfu_fraction",
        "vs_baseline": round(mfu / 0.55, 4),
        "tokens_per_sec_per_chip": round(tokens_per_sec_chip, 1),
        "sec_per_step": round(sec_per_step, 4),
        "n_params": n_params,
        "model": bench_model,
        "n_devices": n_dev,
        "backend": jax.default_backend(),
        "goodput_pct": round(goodput["goodput/goodput_pct"], 2),
        "compile_time_s": round(snapshot.get("compile_time_s", 0.0), 2),
        # step-time cost of health.every_n_steps=1 vs disabled (None when
        # BENCH_HEALTH=0 skipped the A/B fit)
        "health_overhead_pct": (
            round(health_overhead_pct, 2) if health_overhead_pct is not None else None
        ),
        # tiny-model generate gauges (None when BENCH_DECODE=0 skipped it):
        # decode-program overhead trend, not model-scale throughput
        "prefill_time_s": prefill_time_s,
        "decode_tokens_per_sec": decode_tokens_per_sec,
        # global per OPTIMIZER step (the gauge is per-device per train_step
        # invocation), same units as the estimator's perf/xla_flops_per_step
        "xla_flops_per_step": (
            snapshot["xla/flops_per_step"]
            * trainer.config.accumulate_grad_batches * max(1, n_dev)
            if "xla/flops_per_step" in snapshot else None
        ),
    }))


if __name__ == "__main__":
    main()
