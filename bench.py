"""Benchmark: CLM train-step throughput + MFU on the available chip(s).

Wedge-proof multi-stage harness (ISSUE 6 / ROADMAP item 1). BENCH_r04 died
inside the flash backward and r05 wedged at backend init, leaving zero perf
signal for two rounds — so each stage now runs in a SUPERVISED CHILD
process (the PR 3/PR 5 `Supervisor` + `HangWatchdog` machinery):

  backend_init  prove the jax backend answers at all (the r05 wedge)
  train         the headline MFU fit
  health        A/B fit with the model-health layer on (health_overhead_pct)
  trace         A/B fit with host tracing fully on (trace_overhead_pct)
  exporter      A/B fit with the /metrics exporter scraped at Prometheus
                cadence (exporter_overhead_pct)
  decode        tiny-model generate (decode-program overhead trend)
  serve         tiny-model continuous batching (serve tokens/s/chip + TTFT)

`--check-regression` runs no bench at all: it parses the committed
BENCH_r*.json history (telemetry/perf_ledger.py), prints the round-over-
round trend table, and exits nonzero when the newest same-backend round
regressed MFU / decode tokens-per-sec / serve TTFT beyond
BENCH_REGRESSION_TOLERANCE_PCT.

The PARENT never imports jax — a wedged backend can only hang a child,
which the per-stage timeout kills (and the fit stages arm the in-process
`HangWatchdog` with action=abort as defense in depth). Each finished stage
emits a partial JSON line `{"stage": ..., "partial": true, ...}` as it
lands, so a crash later in the run cannot erase earlier results; the final
line is the summary record (`"stage": "summary", "partial": false`) with
the per-stage status map — an MFU number (or an honest per-stage error)
lands on the board every round.

Prints the summary as the LAST JSON line: {"metric", "value", "unit",
"vs_baseline", "stage", "partial", "stages", ...extras}. Target
(BASELINE.md): >=55% MFU on Llama-3-8B class workloads; on the single
bench chip we measure a scaled-down Llama with the same arithmetic shape
and report MFU fraction with vs_baseline = mfu / 0.55.

`--dry` exercises the full stage/subprocess/partial-JSON plumbing on CPU
with the tiny proxy (wired into scripts/precommit.sh). Chaos hooks for
tests: BENCH_CHAOS_WEDGE=<stage> wedges that stage (killed at its
timeout), BENCH_CHAOS_CRASH=<stage> crashes it; either degrades that one
stage to an error record while the rest of the bench completes. Env
reference: docs/performance.md.

Exit codes: 0 = every attempted stage ok; 1 = the train stage (headline
metric) failed; 2 = train ok but an auxiliary stage failed.
"""

import argparse
import json
import os
import subprocess
import sys
import time

STAGES = (
    "backend_init", "train", "health", "trace", "exporter", "decode", "serve"
)

# peak bf16 FLOP/s per chip by TPU generation (public specs)
_PEAK_FLOPS = {
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6e": 918e12,
    "cpu": 1e12,  # nominal, so CPU runs still print a line
}


def _detect_peak() -> float:
    import jax

    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "")
    if gen in _PEAK_FLOPS:
        return _PEAK_FLOPS[gen]
    # device_kind strings: 'TPU v5 lite' == v5e, 'TPU v6 lite' == v6e,
    # 'TPU v5p'/'TPU v5' == v5p, 'TPU v4' == v4
    kind = jax.devices()[0].device_kind.lower()
    if "v5 lite" in kind or "v5e" in kind:
        return _PEAK_FLOPS["v5e"]
    if "v6 lite" in kind or "v6e" in kind:
        return _PEAK_FLOPS["v6e"]
    if "v5" in kind:
        return _PEAK_FLOPS["v5p"]
    if "v4" in kind:
        return _PEAK_FLOPS["v4"]
    return _PEAK_FLOPS["cpu"]


def _chaos(stage: str) -> None:
    """Env-triggered fault hooks so the degrade-not-die plumbing is testable
    (and tested — precommit wedges a stage on every commit)."""
    if os.environ.get("BENCH_CHAOS_WEDGE") == stage:
        print(f"bench chaos: wedging stage {stage}", file=sys.stderr, flush=True)
        while True:
            time.sleep(60)
    if os.environ.get("BENCH_CHAOS_CRASH") == stage:
        raise SystemExit(f"bench chaos: crashing stage {stage}")


# --------------------------------------------------------------- model setup


def _model_setup():
    """(model_kwargs, seq, batch, steps, warmup, on_tpu) for the fit stages —
    the BENCH_* knob surface is shared so train and health measure the same
    program."""
    import jax

    on_tpu = jax.default_backend() == "tpu"
    bench_model = os.environ.get("BENCH_MODEL", "8b-layer")
    if bench_model == "8b-layer":
        # north-star layer proxy (the DEFAULT bench): the EXACT Llama-3-8B
        # per-layer shape (h4096, inter 14336, 32q+8kv heads, head_dim 128)
        # at seq 8192 — few layers so params + fp32 Adam masters fit 16G HBM.
        # This measures the matmul/attention mix the 8B runs, per layer;
        # depth only amortizes the (already-small) embed/CE ends. r4 sweep:
        # SELECTIVE remat (save flash_out+lse — attention never recomputes)
        # at batch 3 wins: 0.716-0.721 > B3/full 0.69-0.70 > B4/selective
        # 0.681 > B2/selective 0.674 ≈ B2/full 0.654-0.673 > B4/full 0.632
        # > L3/B1 0.509 (B6/selective and L3/B2 OOM; batch response is
        # non-monotone — XLA scheduling). The h4096 shapes beat the 697M
        # proxy (0.567): bigger MXU tiles win, and selective remat breaks
        # the ~0.75 full-remat convention ceiling.
        model_kwargs = dict(
            vocab_size=32000,
            hidden_size=4096,
            intermediate_size=14336,
            num_hidden_layers=2,
            num_attention_heads=32,
            num_key_value_heads=8,
            head_dim=128,
            max_position_embeddings=8192,
            enable_gradient_checkpointing=True,
            recompute_granularity="selective",
        )
        default_seq, default_batch = 8192, 3
    elif bench_model == "697m":
        # ~700M-param Llama (largest that fits 16G HBM with fp32 Adam masters):
        # hidden 2048 pushes arithmetic intensity toward the 8B north star —
        # attention + elementwise cost shrinks relative to matmul FLOPs as hidden
        # grows, worth +0.018 MFU over the 317M/hidden-1024 proxy (r3 sweep:
        # 697M@B16 0.5665 > 697M@B20 0.5638 > 317M@B64 0.549; B24+ and an
        # 824M/hidden-2560 variant OOM). head_dim 128 is the MXU-native
        # contraction (22% faster than head_dim 64 at equal params, r1).
        model_kwargs = dict(
            vocab_size=32000,
            hidden_size=2048,
            intermediate_size=5632,
            num_hidden_layers=12,
            num_attention_heads=16,
            num_key_value_heads=8,
            head_dim=128,
            max_position_embeddings=2048,
            # full remat is mandatory on a 16G-HBM chip: no-remat needs 22G even
            # at batch 8; selective (save flash_out+lse) compiles to 15.9-18.5G
            # at batch 56-64 (r3 — XLA fragmentation varies non-monotonically
            # with batch) vs the 15.75G budget. MFU ceiling under the
            # no-recompute-credit convention is ~0.75 with full remat
            enable_gradient_checkpointing=True,
            recompute_granularity="full",
        )
        default_seq, default_batch = 2048, 16
    elif bench_model == "moe":
        # MoE proxy at the 697M-class shape (VERDICT r3 #2): 8 experts,
        # top-2, expert width sized so TOTAL expert params/layer match the
        # 697M dense MLP (8·3·h·704 == 3·h·5632) — measures the dropless
        # sort/ragged_dot/scatter dispatch against the same memory budget.
        model_kwargs = dict(
            vocab_size=32000,
            hidden_size=2048,
            intermediate_size=5632,
            num_hidden_layers=12,
            num_attention_heads=16,
            num_key_value_heads=8,
            head_dim=128,
            max_position_embeddings=2048,
            num_experts=8,
            num_experts_per_tok=2,
            moe_intermediate_size=704,
            enable_gradient_checkpointing=True,
            recompute_granularity="full",
        )
        default_seq, default_batch = 2048, 16
    else:
        raise SystemExit(
            f"unknown BENCH_MODEL {bench_model!r}; use 8b-layer, 697m or moe"
        )
    # sweep overrides (experiments only; defaults above are the recorded bench)
    remat = os.environ.get("BENCH_REMAT")
    if remat == "none":
        model_kwargs.update(enable_gradient_checkpointing=False)
    elif remat in ("full", "selective"):
        model_kwargs.update(enable_gradient_checkpointing=True,
                            recompute_granularity=remat)
    for env, key in (("BENCH_HIDDEN", "hidden_size"), ("BENCH_INTER", "intermediate_size"),
                     ("BENCH_LAYERS", "num_hidden_layers"), ("BENCH_HEADS", "num_attention_heads"),
                     ("BENCH_KV", "num_key_value_heads")):
        if os.environ.get(env):
            model_kwargs[key] = int(os.environ[env])
    if os.environ.get("BENCH_SCAN"):
        model_kwargs["scan_layers"] = os.environ["BENCH_SCAN"] == "1"
    if os.environ.get("BENCH_MOE_IMPL"):  # ragged | bucketed | dense
        model_kwargs["moe_impl"] = os.environ["BENCH_MOE_IMPL"]
    if os.environ.get("BENCH_MOE_CAP"):  # bucketed per-expert capacity factor
        model_kwargs["moe_capacity_factor"] = float(os.environ["BENCH_MOE_CAP"])
    if not on_tpu:  # CPU smoke: tiny
        model_kwargs.update(hidden_size=128, intermediate_size=256, num_hidden_layers=2,
                            num_attention_heads=4, num_key_value_heads=2, head_dim=None,
                            vocab_size=2048)

    seq = int(os.environ.get("BENCH_SEQ", default_seq if on_tpu else 2048))
    batch = int(os.environ.get("BENCH_BATCH", default_batch)) if on_tpu else 4
    model_kwargs["max_position_embeddings"] = max(
        model_kwargs["max_position_embeddings"], seq
    )
    # BENCH_STEPS/BENCH_WARMUP: more measured intervals tighten the A/B
    # overhead stages' medians (the CPU default keeps precommit fast)
    steps = int(os.environ.get("BENCH_STEPS", 10 if on_tpu else 3))
    warmup = int(os.environ.get("BENCH_WARMUP", 2 if on_tpu else 1))
    return model_kwargs, seq, batch, steps, warmup, on_tpu


def _timed_fit(model_kwargs, seq, batch, steps, warmup, on_tpu, health_every=None):
    """One measured fit; `health_every` turns the model-health layer on
    (the A/B for `health_overhead_pct`). Returns (trainer, objective,
    sec_per_step)."""
    import jax
    import numpy as np

    from llm_training_tpu.data import DummyDataModule, DummyDataModuleConfig
    from llm_training_tpu.lms import CLM, CLMConfig, ModelProvider
    from llm_training_tpu.optim import OptimConfig
    from llm_training_tpu.parallel import MeshConfig
    from llm_training_tpu.trainer import Trainer, TrainerConfig

    objective = CLM(
        CLMConfig(
            model=ModelProvider(
                model_class="llm_training_tpu.models.Llama", model_kwargs=model_kwargs
            ),
            optim=OptimConfig(learning_rate=1e-4, warmup_steps=2),
            ce_chunk_size=int(os.environ.get("BENCH_CE_CHUNK", 2048)),
        )
    )
    n_dev = len(jax.devices())
    datamodule = DummyDataModule(
        DummyDataModuleConfig(
            batch_size=batch * max(1, n_dev), max_length=seq,
            num_samples=batch * max(1, n_dev) * 2, vocab_size=model_kwargs["vocab_size"],
        )
    )

    # Pipelined timing: sync ONCE after warmup and ONCE at the end. Real
    # training does not fetch metrics every step (log cadence is sparse), so
    # the honest throughput number lets host dispatch overlap device compute;
    # per-step device_get syncs would bill one tunnel round trip per step.
    # Default timing syncs once per step (block on the step's metrics, one
    # batched transfer) and reports the median step latency. Measured r3 on
    # the tunneled v5e: per-step sync runs AT DEVICE SPEED (2.789s/step ==
    # the jax.profiler device time), while free-running dispatch
    # (BENCH_TIMING=pipelined) is ~20% slower — unsynced host run-ahead
    # floods the remote-execute tunnel. Sync mode is also the conservative
    # measure: it bills one host round trip per step.
    sync_mode = os.environ.get("BENCH_TIMING", "sync") == "sync"
    window = {}
    sync_times = []

    class Timer:
        # the fence fetches a real scalar: on the tunnel-attached chip
        # jax.block_until_ready can return before remote execution
        # finishes (measured r3), so only a data round trip proves the
        # step completed
        def on_train_step(self, trainer, step):
            if sync_mode:
                jax.device_get(trainer.last_metrics["loss"])
                sync_times.append(time.perf_counter())
            elif step == warmup:
                jax.device_get(trainer.last_metrics["loss"])
                window["t0"] = time.perf_counter()

        def on_step_end(self, trainer, step, metrics):
            # fires on log steps only; by config that is the final step,
            # and metrics arrive here already device_get (i.e. synced)
            if step == steps:
                window["t1"] = time.perf_counter()

    callbacks = [Timer()]
    if os.environ.get("BENCH_PROFILE") and health_every is None:
        # capture a jax.profiler trace window (headline run only)
        from llm_training_tpu.callbacks import ProfilerCallback, ProfilerCallbackConfig

        callbacks.append(ProfilerCallback(ProfilerCallbackConfig(
            trace_dir=os.environ["BENCH_PROFILE"], start_step=4, num_steps=2,
        )))
    # in-fit wedge defense (PR 3 machinery): a stalled step/collective dumps
    # stacks and SIGABRTs the CHILD, which the parent records as a stage
    # error — the parent's timeout is the backstop, this is the fast path.
    # Off on CPU unless explicitly set (interpret-mode steps are slow).
    watchdog_s = float(os.environ.get("BENCH_WATCHDOG", 600 if on_tpu else 0))
    trainer = Trainer(
        TrainerConfig(
            max_steps=steps, log_every_n_steps=steps, mesh=MeshConfig(),
            # BENCH_OFFLOAD=1 parks fp32 mu/nu in pinned host memory (XLA
            # host offloading) — frees 8 bytes/param of HBM for bigger
            # models at a per-step transfer cost (recorded in BASELINE.md)
            offload_optimizer_state=bool(os.environ.get("BENCH_OFFLOAD")),
            # BENCH_OFFLOAD_DTYPE=int8|bfloat16 compresses the offloaded
            # state storage (quantized_state.py) to cut the host round trip
            offload_state_dtype=os.environ.get("BENCH_OFFLOAD_DTYPE", "float32"),
            health={"every_n_steps": health_every},
            resilience={
                "watchdog_timeout_s": watchdog_s or None,
                "watchdog_action": "abort",
            },
        ),
        callbacks=callbacks,
    )
    trainer.fit(objective, datamodule)

    if sync_mode:
        # intervals between consecutive post-warmup syncs; the slice
        # starts at warmup-1 so the first post-warmup interval is kept
        sec = float(np.median(np.diff(sync_times[warmup - 1:])))
    else:
        sec = (window["t1"] - window["t0"]) / (steps - warmup)
    return trainer, objective, sec


def _count_params(cfg, seq):
    """(n_params, flops_per_token) under the standard MFU convention (PaLM
    appendix B): model FLOPs only — 6N per token fwd+bwd plus the attention
    quadratic 12·L·h·S; rematerialization is NOT credited (overhead, not
    useful work). MoE credits ACTIVATED params only."""
    attn_params = (
        cfg.hidden_size * (cfg.num_attention_heads + 2 * cfg.num_key_value_heads)
        * cfg.resolved_head_dim
        + cfg.num_attention_heads * cfg.resolved_head_dim * cfg.hidden_size
        + 2 * cfg.hidden_size
    )
    if cfg.num_experts:
        expert_mlp = 3 * cfg.hidden_size * cfg.moe_intermediate_size
        router = cfg.hidden_size * cfg.num_experts
        n_params = (
            cfg.vocab_size * cfg.hidden_size * 2
            + cfg.num_hidden_layers
            * (attn_params + router + cfg.num_experts * expert_mlp)
        )
        # MoE MFU credits ACTIVATED params only (top-k experts per token) —
        # the standard sparse-model convention; total params still reported
        n_active = (
            cfg.vocab_size * cfg.hidden_size * 2
            + cfg.num_hidden_layers
            * (attn_params + router + cfg.num_experts_per_tok * expert_mlp)
        )
    else:
        n_params = n_active = (
            cfg.vocab_size * cfg.hidden_size * 2
            + cfg.num_hidden_layers
            * (attn_params + 3 * cfg.hidden_size * cfg.intermediate_size)
        )
    flops_per_token = 6 * n_active + 12 * cfg.num_hidden_layers * cfg.hidden_size * seq
    return n_params, flops_per_token


# ------------------------------------------------------------------- stages


def stage_backend_init() -> dict:
    """Prove the backend answers: import jax, enumerate devices, run one
    trivial device computation (the r05 wedge froze exactly here)."""
    import jax
    import jax.numpy as jnp

    devices = jax.devices()
    # a real device round trip, not just enumeration — a wedged tunnel can
    # list devices and then hang the first execute
    value = float(jax.device_get(jnp.ones(()) + 1.0))
    assert value == 2.0
    return {
        "backend": jax.default_backend(),
        "n_devices": len(devices),
        "device_kind": devices[0].device_kind,
    }


def stage_train() -> dict:
    """The headline MFU fit."""
    import jax

    model_kwargs, seq, batch, steps, warmup, on_tpu = _model_setup()
    trainer, objective, sec_per_step = _timed_fit(
        model_kwargs, seq, batch, steps, warmup, on_tpu
    )
    n_dev = len(jax.devices())
    tokens_per_step = batch * max(1, n_dev) * seq
    tokens_per_sec = tokens_per_step / sec_per_step
    tokens_per_sec_chip = tokens_per_sec / max(1, n_dev)

    n_params, flops_per_token = _count_params(objective.model.config, seq)
    mfu = tokens_per_sec_chip * flops_per_token / _detect_peak()

    # goodput/telemetry extras so BENCH_* rounds can attribute regressions
    # to compile/data/step shifts, not just the MFU headline
    goodput = trainer.ledger.summary()
    snapshot = trainer.telemetry.snapshot()
    # which flash tiles the compiled step actually ran with (tuning layer
    # gauges; absent on the CPU/XLA path)
    blocks = {
        kind: [snapshot[f"flash/{kind}/block_q"], snapshot[f"flash/{kind}/block_k"]]
        for kind in ("fwd", "bwd")
        if f"flash/{kind}/block_q" in snapshot
    }
    block_sources = {
        key.rsplit("/", 1)[-1]: int(value)
        for key, value in snapshot.items()
        if key.startswith("flash/tuning_table_hit/")
    }
    return {
        "value": round(mfu, 4),
        "vs_baseline": round(mfu / 0.55, 4),
        "tokens_per_sec_per_chip": round(tokens_per_sec_chip, 1),
        "sec_per_step": round(sec_per_step, 4),
        "n_params": n_params,
        "model": os.environ.get("BENCH_MODEL", "8b-layer"),
        "n_devices": n_dev,
        "backend": jax.default_backend(),
        "goodput_pct": round(goodput["goodput/goodput_pct"], 2),
        "compile_time_s": round(snapshot.get("compile_time_s", 0.0), 2),
        "blocks": blocks,
        "block_sources": block_sources,
        # global per OPTIMIZER step (the gauge is per-device per train_step
        # invocation), same units as the estimator's perf/xla_flops_per_step
        "xla_flops_per_step": (
            snapshot["xla/flops_per_step"]
            * trainer.config.accumulate_grad_batches * max(1, n_dev)
            if "xla/flops_per_step" in snapshot else None
        ),
        # static collective-payload share of the compiled step's bytes
        # (attr/ gauges from the HLO walk, docs/observability.md#device-plane)
        # — tracked round over round so a sharding regression that trades
        # FLOPs for traffic shows up even when MFU barely moves
        "comm_fraction": (
            round(snapshot["attr/comm_fraction"], 4)
            if "attr/comm_fraction" in snapshot else None
        ),
    }


def stage_health() -> dict:
    """Same fit with health.every_n_steps=1; the parent divides against the
    train stage's sec_per_step for health_overhead_pct (back-to-back child
    processes on the same chip — the cross-process noise is the same
    run-to-run noise the in-process A/B had)."""
    model_kwargs, seq, batch, steps, warmup, on_tpu = _model_setup()
    _, _, sec_health = _timed_fit(
        model_kwargs, seq, batch, steps, warmup, on_tpu, health_every=1
    )
    return {"sec_per_step_health": round(sec_health, 4)}


def stage_trace() -> dict:
    """Same fit as the train stage with host tracing AT ITS DEFAULT
    deployment — ring recording every step + an attached trace.jsonl sink
    receiving the coarse lifecycle events (per-step span WRITES stay off,
    exactly as a production run defaults). The parent divides against the
    train stage's sec_per_step for trace_overhead_pct, the gauge that
    proves the event layer stays under its <2% budget at default sampling
    (docs/observability.md#tracing). LLMT_TRACE_TRAIN=1 on this stage
    additionally prices the per-step sink writes."""
    import shutil
    import tempfile

    from llm_training_tpu.telemetry.trace import get_tracer

    tracer = get_tracer()
    sink_dir = tempfile.mkdtemp(prefix="bench-trace-")
    tracer.attach_sink(os.path.join(sink_dir, "trace.jsonl"))
    model_kwargs, seq, batch, steps, warmup, on_tpu = _model_setup()
    try:
        _, _, sec_trace = _timed_fit(
            model_kwargs, seq, batch, steps, warmup, on_tpu
        )
    finally:
        counts = tracer.counts()
        tracer.detach_sink()
        shutil.rmtree(sink_dir, ignore_errors=True)
    return {
        "sec_per_step_trace": round(sec_trace, 4),
        "trace_events_written": counts["written"],
    }


def stage_exporter() -> dict:
    """Same fit as the train stage with the live-telemetry exporter ON and
    a Prometheus-cadence scraper polling /metrics throughout — the A/B
    for `exporter_overhead_pct` (docs/observability.md#live-telemetry).
    The scraper runs in-process (a daemon thread hitting localhost), so
    the measured overhead includes both the serving thread and the
    registry snapshots each scrape takes."""
    import threading
    import urllib.request

    from llm_training_tpu.telemetry.exporter import find_free_port

    # ephemeral port chosen here (bind-then-release) rather than port 0:
    # the trainer reads LLMT_METRICS_PORT and the scraper must know where
    # to point before the fit starts
    port = find_free_port()
    os.environ["LLMT_METRICS_PORT"] = str(port)

    stop = threading.Event()
    scrapes = {"ok": 0, "failed": 0, "last": ""}

    def scrape_loop():
        url = f"http://127.0.0.1:{port}/metrics"
        while not stop.wait(0.5):
            try:
                with urllib.request.urlopen(url, timeout=2.0) as resp:
                    scrapes["last"] = resp.read().decode("utf-8", "replace")
                scrapes["ok"] += 1
            except OSError:
                scrapes["failed"] += 1  # exporter not up yet / fit finished

    scraper = threading.Thread(target=scrape_loop, daemon=True)
    scraper.start()
    model_kwargs, seq, batch, steps, warmup, on_tpu = _model_setup()
    try:
        _, _, sec_exporter = _timed_fit(
            model_kwargs, seq, batch, steps, warmup, on_tpu
        )
    finally:
        stop.set()
        scraper.join(timeout=5.0)
        os.environ.pop("LLMT_METRICS_PORT", None)
    return {
        "sec_per_step_exporter": round(sec_exporter, 4),
        "exporter_scrapes": scrapes["ok"],
        "exporter_scrape_series": scrapes["last"].count("# TYPE"),
    }


def stage_decode() -> dict:
    """Decode-path gauge (docs/inference.md): a TINY-model generate run —
    the gauge tracks the decode program's dispatch/step overhead trend, not
    model-scale decode throughput."""
    import jax
    import numpy as np

    from llm_training_tpu.infer import GenerateConfig, InferenceEngine
    from llm_training_tpu.models import Llama, LlamaConfig

    on_tpu = jax.default_backend() == "tpu"
    tiny = Llama(LlamaConfig(
        vocab_size=2048, hidden_size=128, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=512,
        compute_dtype="float32" if not on_tpu else "bfloat16",
    ))
    variables = tiny.init(jax.random.key(0), np.zeros((1, 4), np.int32))
    engine = InferenceEngine(tiny, variables)
    prompts = [[int(t) for t in np.arange(1, 17) + 7 * row] for row in range(4)]
    # warm-up generate absorbs the prefill/decode compiles so the recorded
    # prefill_time_s is a run number, not a compile number; max_length
    # pinned so both runs share one cache shape (one compiled program)
    engine.generate(prompts, GenerateConfig(max_new_tokens=4, max_length=48))
    decode_stats = engine.generate(
        prompts, GenerateConfig(max_new_tokens=32, max_length=48)
    )["stats"]
    return {
        "prefill_time_s": round(decode_stats["decode/prefill_time_s"], 4),
        "decode_tokens_per_sec": round(decode_stats["decode/tokens_per_sec"], 1),
    }


def stage_serve() -> dict:
    """Serving-path gauge (docs/serving.md): a TINY-model continuous-
    batching run through the `ServingEngine` — paged pool, chunked prefill,
    per-slot ragged decode. Like the decode stage this tracks the serve
    program's dispatch/step overhead trend, not model-scale throughput.
    A warm-up run absorbs the prefill/decode compiles, so the measured
    run's TTFT percentiles are scheduling numbers, not compile numbers."""
    import jax
    import numpy as np

    from llm_training_tpu.models import Llama, LlamaConfig
    from llm_training_tpu.serve import ServeConfig, ServingEngine

    on_tpu = jax.default_backend() == "tpu"
    tiny = Llama(LlamaConfig(
        vocab_size=2048, hidden_size=128, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=512,
        compute_dtype="float32" if not on_tpu else "bfloat16",
    ))
    variables = tiny.init(jax.random.key(0), np.zeros((1, 4), np.int32))
    engine = ServingEngine(tiny, variables, ServeConfig(
        max_batch=4, max_model_len=96, prefill_chunk=16, eos_token_id=None,
    ))

    def traffic(tag, n_tokens):
        return [
            {"id": f"{tag}{row}", "prompt": [int(t) for t in np.arange(1, 9 + 4 * row)],
             "max_new_tokens": n_tokens}
            for row in range(4)
        ]

    engine.run(traffic("warm", 4))
    t0 = time.perf_counter()
    events = engine.run(traffic("r", 32))
    wall = time.perf_counter() - t0
    done = [e for e in events if e["type"] == "done"]
    assert len(done) == 4, f"serve bench dropped requests: {done}"
    tokens = sum(e["n_tokens"] for e in done)
    ttft = [e["ttft_ms"] for e in done if "ttft_ms" in e]
    tps_chip = tokens / wall / max(1, len(jax.devices()))
    return {
        "serve_tokens_per_sec_per_chip": round(tps_chip, 1),
        "serve_ttft_p50_ms": round(float(np.percentile(ttft, 50)), 3),
        "serve_ttft_p99_ms": round(float(np.percentile(ttft, 99)), 3),
    }


_STAGE_FNS = {
    "backend_init": stage_backend_init,
    "train": stage_train,
    "health": stage_health,
    "trace": stage_trace,
    "exporter": stage_exporter,
    "decode": stage_decode,
    "serve": stage_serve,
}


def run_stage(stage: str) -> int:
    """Child-process entry: run one stage, print its partial record last."""
    _chaos(stage)
    payload = _STAGE_FNS[stage]()
    print(json.dumps({"stage": stage, "partial": True, "status": "ok", **payload}),
          flush=True)
    return 0


# ------------------------------------------------------------------- parent


def _stage_timeout(stage: str) -> float:
    def env(name, default):
        return float(os.environ.get(name, default))

    run_timeout = env("BENCH_RUN_TIMEOUT", 2400)
    return {
        # the r5 wedge incidents struck backend init AND remote compiles —
        # short fuse for init, long one covering compile+run
        "backend_init": env("BENCH_BACKEND_TIMEOUT", 300),
        "train": run_timeout,
        "health": env("BENCH_HEALTH_TIMEOUT", run_timeout),
        "trace": env("BENCH_TRACE_TIMEOUT", run_timeout),
        "exporter": env("BENCH_EXPORTER_TIMEOUT", run_timeout),
        "decode": env("BENCH_DECODE_TIMEOUT", 600),
        "serve": env("BENCH_SERVE_TIMEOUT", 600),
    }[stage]


def _stage_enabled(stage: str) -> bool:
    if stage == "health":
        return os.environ.get("BENCH_HEALTH", "1") != "0"
    if stage == "trace":
        return os.environ.get("BENCH_TRACE", "1") != "0"
    if stage == "exporter":
        return os.environ.get("BENCH_EXPORTER", "1") != "0"
    if stage == "decode":
        return os.environ.get("BENCH_DECODE", "1") != "0"
    if stage == "serve":
        return os.environ.get("BENCH_SERVE", "1") != "0"
    return True


def run_supervised_stage(stage: str, dry: bool) -> dict:
    """Run one stage as a supervised child; returns its partial record
    (status ok with the stage payload, or status error with diagnostics).
    Reuses the PR 5 `Supervisor` for launch/exit/restart bookkeeping (its
    jsonl event log + signal decoding); the injected `run_child` adds the
    per-stage timeout kill the Supervisor's plain `subprocess.call` lacks."""
    from llm_training_tpu.resilience.supervisor import Supervisor, SupervisorConfig

    argv = [sys.executable, os.path.abspath(__file__), "--stage", stage]
    if dry:
        argv.append("--dry")
    timeout = _stage_timeout(stage)
    cell = {"out": "", "err": "", "timed_out": False}

    def run_child(child_argv):
        cell["timed_out"] = False
        proc = subprocess.Popen(
            child_argv, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=child_env(dry),
        )
        try:
            out, err = proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, err = proc.communicate()
            cell["timed_out"] = True
        cell["out"], cell["err"] = out or "", err or ""
        return proc.returncode

    # backend-init wedges are sometimes transient tunnel hiccups: one free
    # relaunch (a timeout kill is a signal death, which Supervisor restarts);
    # fit/decode stages never auto-rerun — a crashed fit would only recrash.
    retries = int(os.environ.get("BENCH_STAGE_RETRIES", 1 if stage == "backend_init" else 0))
    supervisor = Supervisor(
        argv,
        SupervisorConfig(
            max_restarts=retries,
            restart_codes=(),
            restart_on_signals=retries > 0,
            backoff_base_s=1.0,
            healthy_runtime_s=timeout,
            log_path=os.environ.get("BENCH_SUPERVISOR_LOG"),
        ),
        run_child=run_child,
    )
    t0 = time.monotonic()
    rc = supervisor.run()
    runtime_s = round(time.monotonic() - t0, 2)

    payload = None
    for line in reversed(cell["out"].splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            candidate = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(candidate, dict) and candidate.get("stage") == stage:
            payload = candidate
            break

    if rc == 0 and payload is not None:
        payload["runtime_s"] = runtime_s
        return payload
    if cell["timed_out"]:
        error = (f"stage wedged: no completion within {timeout:.0f}s "
                 "(child killed)")
    elif rc == 0:
        error = "stage exited 0 without emitting its record"
    else:
        error = f"stage failed (exit {rc})"
    tail = ("\n".join((cell["err"] + "\n" + cell["out"]).splitlines()[-6:]))[-500:]
    return {
        "stage": stage, "partial": True, "status": "error",
        "error": error, "rc": rc, "runtime_s": runtime_s, "tail": tail,
    }


def child_env(dry: bool) -> dict:
    env = dict(os.environ)
    if dry:
        env["JAX_PLATFORMS"] = "cpu"
    return env


def summarize(results: dict) -> dict:
    """Assemble the final summary record (the driver parses the LAST JSON
    line; `stages` carries per-stage status so a partially-failed round is
    still attributable)."""
    def ok(stage):
        return results.get(stage, {}).get("status") == "ok"

    train = results.get("train", {})
    summary = {
        "metric": "llama_clm_train_mfu",
        "value": train.get("value") if ok("train") else None,
        "unit": "mfu_fraction",
        "vs_baseline": train.get("vs_baseline") if ok("train") else None,
        "stage": "summary",
        "partial": False,
    }
    if ok("train"):
        for key in ("tokens_per_sec_per_chip", "sec_per_step", "n_params", "model",
                    "n_devices", "backend", "goodput_pct", "compile_time_s",
                    "xla_flops_per_step", "comm_fraction", "blocks",
                    "block_sources"):
            if key in train:
                summary[key] = train[key]
    elif "train" in results:
        summary["error"] = train.get("error", "train stage failed")
    elif results.get("backend_init", {}).get("status") == "error":
        summary["error"] = results["backend_init"].get("error", "backend init failed")

    # step-time cost of health.every_n_steps=1 vs disabled (None when
    # skipped or either fit failed)
    health = results.get("health", {})
    if ok("train") and ok("health") and train.get("sec_per_step"):
        overhead = (health["sec_per_step_health"] - train["sec_per_step"]) \
            / train["sec_per_step"]
        summary["health_overhead_pct"] = round(100.0 * overhead, 2)
    else:
        summary["health_overhead_pct"] = None
    # step-time cost of the event layer at its DEFAULT deployment (ring
    # recording + coarse sink events; per-step writes only if the stage ran
    # with LLMT_TRACE_TRAIN=1) vs untraced; the <2% acceptance gauge
    trace = results.get("trace", {})
    if ok("train") and ok("trace") and train.get("sec_per_step"):
        overhead = (trace["sec_per_step_trace"] - train["sec_per_step"]) \
            / train["sec_per_step"]
        summary["trace_overhead_pct"] = round(100.0 * overhead, 2)
    else:
        summary["trace_overhead_pct"] = None
    # step-time cost of the live-telemetry exporter under a steady scrape
    # (docs/observability.md#live-telemetry) vs unexported
    exporter = results.get("exporter", {})
    if ok("train") and ok("exporter") and train.get("sec_per_step"):
        overhead = (exporter["sec_per_step_exporter"] - train["sec_per_step"]) \
            / train["sec_per_step"]
        summary["exporter_overhead_pct"] = round(100.0 * overhead, 2)
        summary["exporter_scrapes"] = exporter.get("exporter_scrapes")
    else:
        summary["exporter_overhead_pct"] = None
    decode = results.get("decode", {})
    summary["prefill_time_s"] = decode.get("prefill_time_s")
    summary["decode_tokens_per_sec"] = decode.get("decode_tokens_per_sec")
    serve = results.get("serve", {})
    for key in ("serve_tokens_per_sec_per_chip", "serve_ttft_p50_ms",
                "serve_ttft_p99_ms"):
        summary[key] = serve.get(key)

    summary["stages"] = {
        stage: {
            key: record[key]
            for key in ("status", "error", "rc", "runtime_s")
            if key in record
        }
        for stage, record in results.items()
    }
    return summary


def orchestrate(dry: bool) -> int:
    results: dict[str, dict] = {}
    backend_dead = False
    for stage in STAGES:
        if not _stage_enabled(stage):
            results[stage] = {"stage": stage, "partial": True, "status": "skipped"}
            continue
        if backend_dead and stage != "backend_init":
            results[stage] = {
                "stage": stage, "partial": True, "status": "skipped",
                "error": "backend init failed — stage not attempted",
            }
            print(json.dumps(results[stage]), flush=True)
            continue
        record = run_supervised_stage(stage, dry)
        results[stage] = record
        print(json.dumps(record), flush=True)
        if stage == "backend_init" and record.get("status") != "ok":
            # don't burn the full run timeout re-wedging on a dead backend;
            # the summary still lands with every stage accounted for
            backend_dead = True

    summary = summarize(results)
    print(json.dumps(summary), flush=True)
    out_path = os.environ.get("BENCH_OUT")
    if out_path:
        with open(out_path, "w") as f:
            json.dump(summary, f, indent=2)
            f.write("\n")

    attempted = [s for s, r in results.items() if r.get("status") != "skipped"]
    if results.get("train", {}).get("status") != "ok":
        return 1
    if any(results[s].get("status") != "ok" for s in attempted):
        return 2
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(description="wedge-proof multi-stage bench")
    parser.add_argument("--stage", choices=STAGES,
                        help="internal: run ONE stage in this process")
    parser.add_argument("--dry", action="store_true",
                        help="CPU dry run of the full stage/subprocess/"
                             "partial-JSON plumbing with the tiny proxy")
    parser.add_argument("--check-regression", action="store_true",
                        help="no bench run: parse the committed BENCH_r*.json "
                             "history, print the trend table, and exit "
                             "nonzero when the newest same-backend round "
                             "regressed MFU / decode tokens-per-sec / serve "
                             "TTFT beyond BENCH_REGRESSION_TOLERANCE_PCT "
                             "(docs/performance.md#perf-ledger)")
    parser.add_argument("--bench-dir", default=".",
                        help="directory holding BENCH_r*.json rounds "
                             "(--check-regression only; default: cwd)")
    parser.add_argument("--tolerance-pct", type=float, default=None,
                        help="regression tolerance override "
                             "(default: BENCH_REGRESSION_TOLERANCE_PCT or 40)")
    args = parser.parse_args()
    if args.check_regression:
        # jax-free by contract, like the whole bench parent: the regression
        # gate must run on any machine the repo is checked out on
        from llm_training_tpu.telemetry.perf_ledger import ledger_main

        return ledger_main(args.bench_dir, tolerance_pct=args.tolerance_pct)
    if args.dry and not args.stage:
        os.environ["JAX_PLATFORMS"] = "cpu"
    if args.stage:
        if args.dry:
            # child_env's JAX_PLATFORMS=cpu covers plain machines, but the
            # axon sitecustomize re-pins that env var at interpreter start —
            # demote through the config API (which wins over env and skips
            # the axon plugin's backend init) before the stage touches jax,
            # so precommit's dry legs stay off the chip on bench machines
            import jax

            jax.config.update("jax_platforms", "cpu")
        return run_stage(args.stage)
    return orchestrate(args.dry)


if __name__ == "__main__":
    sys.exit(main())
