#!/usr/bin/env python
"""Precommit router-smoke gate (docs/serving.md#router).

Proves the fleet resilience tier end to end on CPU, on every commit:

1. **failover leg** — the loadgen's `--router` mode drives two real serve
   replicas behind the `route` tier; `LLMT_CHAOS_ROUTER_KILL_REPLICA`
   SIGKILLs the replica that produced the Nth forwarded token mid-stream.
   The client census must stay exactly-once (every request exactly one
   terminal, zero duplicates, zero losses), the router must report >= 1
   `router/replays` and `router/failovers`, and the fleet aggregator's
   verdict at the all-terminal moment must be GREEN again — the
   replacement replica armed and the dead replica's card was reaped.
   The router's run dir must then render a `report` `== Router ==`
   section with an `exactly-once: green` verdict line.
2. **blackhole/hedge leg** — `LLMT_CHAOS_ROUTER_BLACKHOLE` swallows one
   request->replica submission (the leg stays open but the replica never
   hears of it); with a hedge budget set the router must re-enqueue on a
   second replica and deliver EXACTLY one terminal per request
   (`router/blackholed` == 1, >= 1 hedge win, duplicate terminals only
   ever suppressed, never emitted).

This parent is jax-free (the router and its serve children own any
backend) by the same contract as the fleet smoke.

Usage: python scripts/router_smoke.py <scratch_dir> [seed_run_dir]

`seed_run_dir` is an existing run dir whose `checkpoints/` seeds the
router's run root (precommit passes its CPU-fit smoke dir so no extra
fit is paid); standalone invocations omit it and a tiny fit runs first.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

_CONFIG = "config/examples/smoke/cpu-smoke.yaml"
# run dirs resolve as <run_root>/<project>/<name> (cli.main's jax-free
# mirror of the logger layout); cpu-smoke pins smoke/cpu-smoke
_RUN_SUFFIX = Path("smoke") / "cpu-smoke"
_SERVE_FLAGS = [
    "--max-batch", "2", "--max-model-len", "64",
    "--prefill-chunk", "4", "--eos-token-id", "-1",
]


def _seed_checkpoints(scratch: Path, seed_run_dir: str | None, env) -> Path:
    if seed_run_dir:
        seed = Path(seed_run_dir)
        if (seed / "checkpoints").is_dir():
            return seed
        print(f"router smoke: {seed}/checkpoints absent — fitting fresh",
              file=sys.stderr)
    seed_root = scratch / "seed"
    fit = subprocess.run(
        [
            sys.executable, "-m", "llm_training_tpu", "fit",
            "--config", _CONFIG, f"run_root={seed_root}",
        ],
        env=env, capture_output=True, text=True, timeout=600,
    )
    if fit.returncode != 0:
        print(fit.stdout[-2000:], file=sys.stderr)
        print(fit.stderr[-2000:], file=sys.stderr)
        raise SystemExit("router smoke: seed fit failed")
    return seed_root / _RUN_SUFFIX


def _loadgen(scratch: Path, leg: str, env: dict, requests: int,
             max_new_tokens: int, extra: list[str]) -> dict:
    """One `serve_loadgen --router` run under a fresh run root; returns the
    summary dict (the loadgen already enforces the exactly-once census,
    quiescent-exporter cross-check, and fleet-rollup==client-census)."""
    out = scratch / f"{leg}.json"
    run = subprocess.run(
        [
            sys.executable, "scripts/serve_loadgen.py",
            "--config", _CONFIG,
            "--requests", str(requests),
            "--max-new-tokens", str(max_new_tokens),
            "--router", "--router-replicas", "2",
            "--fleet-dir", str(scratch / f"{leg}-fleet"),
            "--out", str(out),
            *extra,
            # `--` so argparse keeps the serve flags (with their values)
            # intact in serve_args instead of stealing one as a positional
            "--", *_SERVE_FLAGS, f"run_root={scratch / leg}",
        ],
        env=env, capture_output=True, text=True, timeout=600,
    )
    if run.returncode != 0:
        print(run.stdout[-3000:], file=sys.stderr)
        print(run.stderr[-3000:], file=sys.stderr)
        raise SystemExit(f"router smoke: {leg} loadgen failed")
    summary = json.loads(out.read_text())
    assert not summary["errors"], (leg, summary["errors"])
    assert summary["completed"] == requests, (leg, summary)
    return summary


def main() -> int:
    if len(sys.argv) not in (2, 3):
        print(__doc__, file=sys.stderr)
        return 2
    scratch = Path(sys.argv[1])
    # a previous (crashed) invocation's cards/journals must not pollute
    shutil.rmtree(scratch, ignore_errors=True)
    scratch.mkdir(parents=True, exist_ok=True)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("LLMT_CHAOS_ROUTER_KILL_REPLICA", None)
    env.pop("LLMT_CHAOS_ROUTER_BLACKHOLE", None)

    seed = _seed_checkpoints(
        scratch, sys.argv[2] if len(sys.argv) == 3 else None, env
    )
    for leg in ("kill", "blackhole"):
        dst = scratch / leg / _RUN_SUFFIX
        dst.mkdir(parents=True, exist_ok=True)
        shutil.copytree(seed / "checkpoints", dst / "checkpoints")

    # --- 1. failover: SIGKILL the producing replica at forwarded token 5
    print("router smoke: failover leg (chaos kill mid-stream)...", flush=True)
    summary = _loadgen(
        scratch, "kill",
        {**env, "LLMT_CHAOS_ROUTER_KILL_REPLICA": "5"},
        requests=4, max_new_tokens=16, extra=[],
    )
    stats = summary["engine"]
    assert stats["failovers"] >= 1.0, stats
    assert stats["replays"] >= 1.0, (
        f"no in-flight request replayed across the kill: {stats}"
    )
    assert stats["requests_completed"] == 4.0, stats
    fleet = summary["fleet"]
    assert fleet["verdict"] == "green", (
        f"fleet not green after replacement replica armed: {fleet['verdict']}"
        f" red={fleet['red']} stale={fleet['stale_cards']}"
    )
    assert fleet["rollup"]["llmt_fleet_router_requests_completed"] == 4.0, fleet
    print(
        "router smoke: failover OK —"
        f" {int(stats['failovers'])} failover,"
        f" {int(stats['replays'])} replay(s),"
        f" {int(stats['recovered_tokens'])} journal-recovered token(s),"
        " fleet green", flush=True,
    )

    # --- report renders the router section with a green exactly-once line
    run_dir = scratch / "kill" / _RUN_SUFFIX
    report = subprocess.run(
        [sys.executable, "-m", "llm_training_tpu", "report", str(run_dir)],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert report.returncode == 0, report.stderr
    assert "== Router ==" in report.stdout, report.stdout
    assert "exactly-once: green (4/4 terminals)" in report.stdout, report.stdout

    # --- 2. hedging: blackhole one submission, the hedge must deliver
    print("router smoke: blackhole leg (hedged retry)...", flush=True)
    summary = _loadgen(
        scratch, "blackhole",
        {**env, "LLMT_CHAOS_ROUTER_BLACKHOLE": "1"},
        requests=2, max_new_tokens=8,
        extra=["--hedge-ttft-ms", "1500"],
    )
    stats = summary["engine"]
    assert stats["blackholed"] == 1.0, stats
    assert stats["hedges"] >= 1.0, f"blackholed request never hedged: {stats}"
    assert stats["hedge_wins"] >= 1.0, stats
    assert stats["requests_completed"] == 2.0, stats
    print(
        "router smoke: hedge OK —"
        f" {int(stats['hedges'])} hedge(s),"
        f" {int(stats['hedge_wins'])} win(s),"
        f" {int(stats['duplicate_terminals_suppressed'])} duplicate"
        " terminal(s) suppressed", flush=True,
    )

    print("router smoke: OK — failover exactly-once, hedged blackhole")
    return 0


if __name__ == "__main__":
    sys.exit(main())
