#!/usr/bin/env python
"""Precommit fleet-smoke gate (docs/observability.md#fleet).

Proves the fleet observability plane end to end on CPU, on every commit:

1. **2-replica census** — the loadgen's `--replicas 2` mode drives two
   real serve children (own run roots, own exporter ports, discovery
   cards in a shared `LLMT_FLEET_DIR`) and asserts the fleet census at
   the all-terminal moment: aggregator rollup == summed per-replica
   client censuses, terminals exactly-once fleet-wide, verdict green.
   After the clean stop, the discovery dir must hold ZERO cards.
2. **cross-replica trace merge** — `trace --merge` over both replica run
   dirs must emit ONE Chrome-trace JSON where both replicas' request
   tracks render side by side (wall-anchor aligned; every request id
   appears exactly once, under its own replica's pid namespace).
3. **replica kill** — two cheap stub exporters (no backend) under a
   fresh discovery dir: the aggregator sweeps green, one stub is
   SIGKILLed, and the fleet verdict must flip red within ONE scrape
   interval with `/fleetz` naming the dead replica's stale card; the
   federation `/metrics` must parse as labeled Prometheus text
   throughout. A `fleet --once --out` snapshot then surfaces as report
   --format json's `fleet` block (schema_version stays 1), and
   `fleet --once` against an empty dir exits 2 naming the searched path.

This parent is jax-free (children own any backend) by the same contract
as the exporter smoke.

Usage: python scripts/fleet_smoke.py <scratch_dir> [seed_run_dir]

`seed_run_dir` is an existing run dir whose `checkpoints/` seeds every
replica's run root (precommit passes its CPU-fit smoke dir so no extra
fit is paid); standalone invocations omit it and a tiny fit runs first.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import subprocess
import sys
import time
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from llm_training_tpu.telemetry.exporter import (  # noqa: E402
    parse_prometheus_text,
)
from llm_training_tpu.telemetry.fleet import FleetAggregator  # noqa: E402

# a serve-shaped exporter with no backend: the kill leg needs replicas
# cheap enough to SIGKILL without paying two more jax boots
_STUB = """
import sys, time
from llm_training_tpu.telemetry.exporter import MetricsExporter
from llm_training_tpu.telemetry.registry import TelemetryRegistry
reg = TelemetryRegistry()
reg.gauge("serve/queue_depth").set(0.0)
reg.gauge("serve/running").set(0.0)
reg.gauge("serve/requests_completed").set(float(sys.argv[1]))
exporter = MetricsExporter(0, registry=reg, role="serve")
assert exporter.start()
print("READY", exporter.port, flush=True)
time.sleep(600)
"""


def _seed_checkpoints(scratch: Path, seed_run_dir: str | None, env) -> Path:
    """The serve children restore a checkpoint from their own run roots:
    reuse the caller's fit-smoke run dir when given, else pay one tiny
    CPU fit here."""
    if seed_run_dir:
        seed = Path(seed_run_dir)
        if (seed / "checkpoints").is_dir():
            return seed
        print(f"fleet smoke: {seed}/checkpoints absent — fitting fresh",
              file=sys.stderr)
    seed_root = scratch / "seed"
    fit = subprocess.run(
        [
            sys.executable, "-m", "llm_training_tpu", "fit",
            "--config", "config/examples/smoke/cpu-smoke.yaml",
            f"run_root={seed_root}",
        ],
        env=env, capture_output=True, text=True, timeout=600,
    )
    if fit.returncode != 0:
        print(fit.stdout[-2000:], file=sys.stderr)
        print(fit.stderr[-2000:], file=sys.stderr)
        raise SystemExit("fleet smoke: seed fit failed")
    return seed_root / "smoke" / "cpu-smoke"


def main() -> int:
    if len(sys.argv) not in (2, 3):
        print(__doc__, file=sys.stderr)
        return 2
    scratch = Path(sys.argv[1])
    scratch.mkdir(parents=True, exist_ok=True)
    fleet_dir = scratch / "fleet"
    # a previous (crashed) invocation's cards must not pollute this census
    shutil.rmtree(fleet_dir, ignore_errors=True)
    shutil.rmtree(scratch / "fleet-kill", ignore_errors=True)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}

    # --- 0. every replica run root starts from the same tiny checkpoint
    seed = _seed_checkpoints(
        scratch, sys.argv[2] if len(sys.argv) == 3 else None, env
    )
    for index in range(2):
        dst = scratch / f"replica-{index}" / "smoke" / "cpu-smoke"
        if not (dst / "checkpoints").is_dir():
            dst.mkdir(parents=True, exist_ok=True)
            shutil.copytree(seed / "checkpoints", dst / "checkpoints")

    # --- 1. two real serve replicas, fleet census at the terminal moment
    print("fleet smoke: 2-replica loadgen census...", flush=True)
    loadgen = subprocess.run(
        [
            sys.executable, "scripts/serve_loadgen.py",
            "--config", "config/examples/smoke/cpu-smoke.yaml",
            "--requests", "4", "--max-new-tokens", "16",
            "--replicas", "2",
            "--replica-run-root", str(scratch),
            "--fleet-dir", str(fleet_dir),
            "--out", str(scratch / "fleet_loadgen.json"),
            # `--` so argparse keeps the serve flags (with their values)
            # intact in serve_args instead of stealing "2" as a positional
            "--", "--max-batch", "2", "--max-model-len", "64",
            "--prefill-chunk", "4", "--eos-token-id", "-1",
        ],
        env=env, capture_output=True, text=True, timeout=600,
    )
    if loadgen.returncode != 0:
        print(loadgen.stdout[-2000:], file=sys.stderr)
        print(loadgen.stderr[-2000:], file=sys.stderr)
        print("fleet smoke: multi-replica loadgen failed", file=sys.stderr)
        return 1
    summary = json.loads((scratch / "fleet_loadgen.json").read_text())
    assert not summary["errors"], summary["errors"]
    assert summary["replicas"] == 2 and summary["completed"] == 4, summary
    fleet = summary["fleet"]
    assert fleet and fleet["verdict"] == "green", fleet
    assert fleet["rollup"]["llmt_fleet_serve_requests_completed"] == 4.0, fleet
    assert fleet["rollup"]["llmt_fleet_replicas"] == 2.0, fleet
    leftovers = list(fleet_dir.glob("replica-*.json"))
    assert not leftovers, (
        f"clean stop left discovery cards behind: {leftovers}"
    )
    print("fleet smoke: census OK —", fleet["rollup"], flush=True)

    # --- 2. cross-replica trace merge: one Perfetto file, both tracks
    run_dirs = [
        scratch / f"replica-{i}" / "smoke" / "cpu-smoke" for i in range(2)
    ]
    merged_path = scratch / "trace_merged.json"
    merge = subprocess.run(
        [
            sys.executable, "-m", "llm_training_tpu", "trace",
            "--merge", *map(str, run_dirs), "--out", str(merged_path),
        ],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert merge.returncode == 0, merge.stderr
    document = json.loads(merged_path.read_text())
    events = document["traceEvents"]
    assert isinstance(events, list) and events, "empty merged trace"
    # both replicas' serve tracks: distinct pid namespaces, labeled
    # process names, and every request id exactly once fleet-wide
    serve_pids = {
        e["pid"] for e in events
        if e.get("name") == "process_name" and "/serve" in e["args"]["name"]
    }
    assert len(serve_pids) == 2, f"want 2 serve process tracks: {serve_pids}"
    request_tracks: dict[str, set[int]] = {}
    for event in events:
        rid = (event.get("args") or {}).get("request_id")
        if rid is not None:
            request_tracks.setdefault(str(rid), set()).add(event["pid"])
    assert set(request_tracks) == {f"req-{n}" for n in range(4)}, (
        f"merged trace lost requests: {sorted(request_tracks)}"
    )
    for rid, pids in request_tracks.items():
        assert len(pids) == 1, f"{rid} rendered under {pids} — pid bleed"
    spans = [e for e in events if e.get("ph") == "X"]
    assert spans and all(e["ts"] >= 0 for e in spans), "bad merged rebase"
    print(
        f"fleet smoke: merge OK — {len(events)} events, "
        f"{len(request_tracks)} request tracks over {len(serve_pids)} "
        "replicas", flush=True,
    )

    # --- 3. kill leg: green fleet -> SIGKILL one stub -> red within one
    # scrape interval, /fleetz names the stale card
    print("fleet smoke: replica-kill verdict flip...", flush=True)
    kill_dir = scratch / "fleet-kill"
    stub_env = {**os.environ, "LLMT_FLEET_DIR": str(kill_dir)}
    stubs = [
        subprocess.Popen(
            [sys.executable, "-c", _STUB, str(7 * (i + 1))],
            env=stub_env, stdout=subprocess.PIPE, text=True,
        )
        for i in range(2)
    ]
    try:
        for stub in stubs:
            ready = stub.stdout.readline()
            assert ready.startswith("READY"), f"stub never armed: {ready!r}"
        interval_s = 1.0
        aggregator = FleetAggregator(fleet_dir=kill_dir, interval_s=interval_s)
        aggregator.start(port=0)
        try:
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                snapshot = aggregator.snapshot()
                if snapshot["verdict"] == "green" and len(
                    snapshot["replicas"]
                ) == 2:
                    break
                time.sleep(0.05)
            assert snapshot["verdict"] == "green", snapshot
            # federation surface is parse-valid LABELED Prometheus
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{aggregator.port}/metrics", timeout=3.0
            ).read().decode()
            federated = parse_prometheus_text(body, labels=True)
            labeled = [k for k in federated if "{replica=" in k]
            assert labeled, sorted(federated)[:10]
            assert federated["llmt_fleet_serve_requests_completed"] == 21.0, (
                {k: v for k, v in federated.items() if "fleet" in k}
            )
            victim_pid = stubs[1].pid
            os.kill(victim_pid, signal.SIGKILL)
            stubs[1].wait()
            killed_at = time.monotonic()
            while time.monotonic() < killed_at + interval_s + 2.0:
                snapshot = aggregator.snapshot()
                if snapshot["verdict"] == "red":
                    break
                time.sleep(0.05)
            flip_s = time.monotonic() - killed_at
            assert snapshot["verdict"] == "red", (
                f"verdict never flipped red after SIGKILL: {snapshot}"
            )
            assert flip_s <= interval_s + 2.0, (
                f"flip took {flip_s:.1f}s (> one {interval_s}s interval "
                "+ sweep slack)"
            )
            dead = [
                rid for rid in snapshot["stale_cards"]
                if rid.endswith(str(victim_pid))
            ]
            assert dead, (victim_pid, snapshot["stale_cards"])
            fleetz = urllib.request.urlopen(
                f"http://127.0.0.1:{aggregator.port}/fleetz", timeout=3.0
            ).read().decode()
            assert "RED" in fleetz and dead[0] in fleetz, fleetz
            print(
                f"fleet smoke: kill OK — verdict red {flip_s:.2f}s after "
                f"SIGKILL, /fleetz names {dead[0]}", flush=True,
            )

            # --- fleet --once snapshot -> report --format json fleet block
            # (the SEED run dir: report wants a fit-shaped metrics.jsonl,
            # which the serve replicas' run dirs deliberately lack)
            fleet_out = seed / "fleet.json"
            once = subprocess.run(
                [
                    sys.executable, "-m", "llm_training_tpu", "fleet",
                    "--dir", str(kill_dir), "--once", "--json",
                    "--out", str(fleet_out),
                ],
                env=env, capture_output=True, text=True, timeout=60,
            )
            assert once.returncode == 0, once.stderr
            assert json.loads(once.stdout)["verdict"] == "red"
        finally:
            aggregator.stop()
    finally:
        for stub in stubs:
            if stub.poll() is None:
                stub.kill()
                stub.wait()
    report = subprocess.run(
        [
            sys.executable, "-m", "llm_training_tpu", "report",
            str(seed), "--format", "json",
        ],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert report.returncode == 0, report.stderr
    doc = json.loads(report.stdout)
    assert doc["schema_version"] == 1, doc.get("schema_version")
    assert doc["fleet"] and doc["fleet"]["verdict"] == "red", doc.get("fleet")
    assert doc["fleet"]["stale_cards"], doc["fleet"]

    # --- exit-2 contract: an empty discovery dir names the searched path
    empty = scratch / "fleet-empty"
    empty.mkdir(exist_ok=True)
    nobody = subprocess.run(
        [
            sys.executable, "-m", "llm_training_tpu", "fleet",
            "--dir", str(empty), "--once",
        ],
        env=env, capture_output=True, text=True, timeout=60,
    )
    assert nobody.returncode == 2, (nobody.returncode, nobody.stderr)
    assert str(empty) in nobody.stderr, nobody.stderr

    print(
        "fleet smoke: OK — census, merge, kill-flip, report fleet block, "
        "exit-2 paths"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
