"""Forced-NaN micro-fit: the NaN-provenance + auto-recovery commit gates.

Leg 1 (provenance, ISSUE 2): a tiny MoE fit whose loss is poisoned through
the EMBEDDING TABLE (`0 * (inf * embed.sum())` — forward NaN, and the chain
rule puts NaN into exactly the embedding gradients while every other
layer's stay finite), with the health layer on every step and a
`NanGuard(action="raise")`. Asserts the whole provenance path end to end:

1. the fit dies with `NonFiniteLossError`,
2. the error message names the offending layer path (`embed_tokens`), and
3. an `anomaly-<step>.json` dump lands in the run dir with that layer in
   `offending_layers`.

Leg 2 (auto-recovery, ISSUE 5): a healthy fit with a chaos-injected NaN at
a deterministic step and `trainer.resilience.recovery` enabled must
self-heal IN-PROCESS — rollback to the last committed checkpoint, skip the
poisoned data window, run to completion without exiting — with
`resilience/rollbacks == 1` in telemetry and a `== Recovery ==` section in
the rendered report.

Usage: `python scripts/force_nan_smoke.py <scratch-dir>` (exit 0 = pass).
`scripts/precommit.sh` runs it on CPU after the report smoke.
"""

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp

from llm_training_tpu.callbacks import JsonlLogger, JsonlLoggerConfig, NanGuard, NanGuardConfig, NonFiniteLossError
from llm_training_tpu.data import DummyDataModule, DummyDataModuleConfig
from llm_training_tpu.lms import CLM, CLMConfig, ModelProvider
from llm_training_tpu.lms.clm import _get_path
from llm_training_tpu.parallel import MeshConfig
from llm_training_tpu.trainer import Trainer, TrainerConfig


class PoisonedCLM(CLM):
    """CLM whose loss carries `0 * (inf * sum(embed_table))`: NaN forward,
    and NaN gradients ONLY for the embedding table — the provenance walk
    must name it and nothing else."""

    def loss_and_metrics(self, params, batch, rng=None, train=True, with_health=False):
        loss, metrics = super().loss_and_metrics(
            params, batch, rng=rng, train=train, with_health=with_health
        )
        p = params["params"] if "params" in params else params
        embed = _get_path(p, self.model.get_input_embeddings_path())
        poison = jnp.float32(0.0) * (
            jnp.float32(jnp.inf) * embed.astype(jnp.float32).sum()
        )
        loss = loss + poison
        metrics["loss"] = loss
        return loss, metrics


def main(scratch: str) -> int:
    objective = PoisonedCLM(
        CLMConfig(
            model=ModelProvider(
                model_class="Llama",
                model_kwargs=dict(
                    vocab_size=128, hidden_size=32, intermediate_size=64,
                    num_hidden_layers=1, num_attention_heads=2,
                    num_key_value_heads=2, max_position_embeddings=64,
                    attention_impl="xla", param_dtype="float32",
                    compute_dtype="float32", num_experts=4,
                    num_experts_per_tok=2, moe_intermediate_size=32,
                ),
            )
        )
    )
    datamodule = DummyDataModule(
        DummyDataModuleConfig(batch_size=8, max_length=32, num_samples=64, vocab_size=128)
    )
    logger = JsonlLogger(JsonlLoggerConfig(save_dir=scratch, name="nan-smoke"))
    trainer = Trainer(
        TrainerConfig(
            max_steps=3, log_every_n_steps=1, mesh=MeshConfig(),
            health={"every_n_steps": 1},
        ),
        callbacks=[logger, NanGuard(NanGuardConfig(patience=0, action="raise"))],
    )
    try:
        trainer.fit(objective, datamodule)
    except NonFiniteLossError as e:
        message = str(e)
        if "embed_tokens" not in message:
            print(f"FAIL: NonFiniteLossError does not name embed_tokens: {message}")
            return 1
        dumps = sorted(Path(logger.run_dir).glob("anomaly-*.json"))
        if not dumps:
            print(f"FAIL: no anomaly-*.json dump under {logger.run_dir}")
            return 1
        payload = json.loads(dumps[0].read_text())
        if not any("embed_tokens" in layer for layer in payload["offending_layers"]):
            print(f"FAIL: dump offending_layers lacks embed_tokens: {payload['offending_layers']}")
            return 1
        print(f"OK: {message.splitlines()[0]}")
        print(f"OK: dump {dumps[0]} offending_layers={payload['offending_layers']}")
        return recovery_leg(scratch)
    print("FAIL: fit completed without NonFiniteLossError")
    return 1


def recovery_leg(scratch: str) -> int:
    """Auto-recovery gate: a chaos-injected NaN at step 4 must self-heal
    in the SAME process (rollback to the step-2 checkpoint + skip the
    poisoned window), and the run dir's report must render `== Recovery ==`."""
    from llm_training_tpu.resilience import ChaosConfig, RecoveryConfig, ResilienceConfig
    from llm_training_tpu.telemetry.report import render_report
    from llm_training_tpu.trainer.checkpoint import CheckpointConfig, Checkpointer

    objective = CLM(
        CLMConfig(
            model=ModelProvider(
                model_class="Llama",
                model_kwargs=dict(
                    vocab_size=128, hidden_size=32, intermediate_size=64,
                    num_hidden_layers=1, num_attention_heads=2,
                    num_key_value_heads=2, max_position_embeddings=64,
                    attention_impl="xla", param_dtype="float32",
                    compute_dtype="float32",
                ),
            )
        )
    )
    datamodule = DummyDataModule(
        DummyDataModuleConfig(batch_size=8, max_length=32, num_samples=64, vocab_size=128)
    )
    logger = JsonlLogger(JsonlLoggerConfig(save_dir=scratch, name="recovery-smoke"))
    trainer = Trainer(
        TrainerConfig(
            max_steps=6, log_every_n_steps=1, checkpoint_every_n_steps=2,
            mesh=MeshConfig(),
            resilience={
                "chaos": {"nan_step": 4},
                "recovery": {"max_rollbacks": 2, "skip_window_steps": 1},
            },
        ),
        callbacks=[logger, NanGuard(NanGuardConfig(patience=0, action="raise"))],
        checkpointer=Checkpointer(
            CheckpointConfig(dirpath=f"{scratch}/recovery-ckpt", async_save=False)
        ),
    )
    try:
        state = trainer.fit(objective, datamodule)
    except Exception as e:
        print(f"FAIL: recovery fit did not self-heal: {type(e).__name__}: {e}")
        return 1
    if int(jax.device_get(state.step)) != 6:
        print(f"FAIL: recovery fit stopped at step {int(jax.device_get(state.step))}")
        return 1
    snapshot = trainer.telemetry.snapshot()
    if snapshot.get("resilience/rollbacks") != 1:
        print(f"FAIL: expected resilience/rollbacks == 1, got {snapshot}")
        return 1
    report = render_report(Path(logger.run_dir))
    if "== Recovery ==" not in report:
        print(f"FAIL: report lacks '== Recovery ==' section:\n{report}")
        return 1
    print("OK: chaos NaN at step 4 self-healed in-process "
          f"(rollbacks={int(snapshot['resilience/rollbacks'])}, "
          f"skipped_steps={int(snapshot.get('resilience/skipped_steps', 0))})")
    print("OK: report renders == Recovery ==")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else "runs/nan-smoke"))
