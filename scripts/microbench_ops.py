"""Microbenchmark the XLA-fusion stand-in ops (SURVEY §2.9 / VERDICT r2 #3).

The reference ships Triton kernels for rms_norm / rope / swiglu / fused CE
(`ops/liger_kernel/*.py`); this repo leaves the first three to XLA fusion and
hand-chunks the CE. This script measures whether that bet holds on the real
chip: each op runs CHAINED inside one jit (output feeds the next iteration,
so neither XLA nor the async dispatch queue can elide or overlap iterations)
and is reported as ns/token and achieved HBM GB/s against the chip's ~819
GB/s peak (all four ops are bandwidth-bound — roofline says a fused
implementation can only win by moving fewer bytes).

Usage: python scripts/microbench_ops.py  (prints a markdown table)
"""

from __future__ import annotations

import functools
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

from llm_training_tpu.ops import apply_rope, rms_norm
from llm_training_tpu.ops.cross_entropy import fused_linear_cross_entropy
from llm_training_tpu.ops.swiglu import silu_mul

ITERS = 200
# The working set must exceed the chip's ~128M VMEM or the chained scan keeps
# the carry resident in VMEM and reports impossible bandwidth (26 TB/s at
# 16384 tokens, measured r3) — 131072 tokens x hidden 1024 is 268M bf16, so
# every iteration genuinely streams HBM like a model layer does.
TOKENS = 131072  # 64 x 2048
HIDDEN = 1024
INTER = 4096
VOCAB = 32000
HEADS, HEAD_DIM = 8, 128
_RNG = np.random.default_rng(0)


def _fetch(out) -> None:
    """Force completion by pulling a few result elements to the host.

    On the tunnel-attached chip `jax.block_until_ready` returns before remote
    execution finishes (measured r3: block 0.3 ms, actual compute 16 s —
    revealed only by fetching data), so timing must round-trip real bytes.
    The one tunnel RTT this costs is amortized over ITERS chained iterations.
    """
    jax.device_get(jax.tree.leaves(out)[0].ravel()[:8])


def _timed(fn, *args) -> float:
    """Median seconds per chained iteration.

    Every rep passes a distinct salt that perturbs the carry before the
    chain, so no rep can be served from any repeat-execution fast path.
    """
    _fetch(fn(jnp.float32(0.0), *args))
    times = []
    for rep in range(1, 4):
        t0 = time.perf_counter()
        _fetch(fn(jnp.float32(rep), *args))
        times.append((time.perf_counter() - t0) / ITERS)
    return float(np.median(times))


def _chain(op):
    """iterate x -> op(x) ITERS times inside one jit via lax.scan."""

    @jax.jit
    def run(salt, x, *rest):
        x = jax.tree.map(
            lambda a: a + jnp.asarray(salt, a.dtype)
            if jnp.issubdtype(a.dtype, jnp.floating) else a,
            x,
        )

        def body(carry, _):
            return op(carry, *rest), None

        y, _ = jax.lax.scan(body, x, None, length=ITERS)
        return y

    return run


def bench_rms_norm():
    x = jnp.asarray(_RNG.standard_normal((TOKENS, HIDDEN)), jnp.bfloat16)
    w = jnp.ones((HIDDEN,), jnp.bfloat16)
    t = _timed(_chain(lambda x, w: rms_norm(x, w, 1e-5)), x, w)
    moved = TOKENS * HIDDEN * 2 * 2  # read + write bf16
    return "rms_norm", t, moved


def bench_rope():
    q = jnp.asarray(_RNG.standard_normal((1, TOKENS, HEADS, HEAD_DIM)), jnp.bfloat16)
    k = jnp.asarray(_RNG.standard_normal((1, TOKENS, HEADS // 2, HEAD_DIM)), jnp.bfloat16)
    inv = 1.0 / (10000.0 ** (np.arange(0, HEAD_DIM, 2) / HEAD_DIM))
    freqs = np.outer(np.arange(TOKENS), inv)
    # rotate_half layout: full-width [seq, head_dim] tables, halves duplicated
    cos = jnp.asarray(np.cos(np.concatenate([freqs, freqs], -1)), jnp.float32)
    sin = jnp.asarray(np.sin(np.concatenate([freqs, freqs], -1)), jnp.float32)

    def op(qk, cos, sin):
        q, k = qk
        q2, k2 = apply_rope(q, k, cos, sin)
        return (q2, k2)

    t = _timed(_chain(op), (q, k), cos, sin)
    moved = (q.size + k.size) * 2 * 2 + (cos.size + sin.size) * 4
    return "rope", t, moved


def bench_swiglu():
    gate = jnp.asarray(_RNG.standard_normal((TOKENS, INTER)), jnp.bfloat16)
    up = jnp.asarray(_RNG.standard_normal((TOKENS, INTER)), jnp.bfloat16)

    def op(gate, up):
        out = silu_mul(gate, up)
        # chain through gate so the scan carries a same-shaped tensor
        return out

    t = _timed(_chain(op), gate, up)
    moved = TOKENS * INTER * 2 * 3  # 2 reads + 1 write
    return "silu_mul", t, moved


def bench_fused_ce():
    hidden = jnp.asarray(_RNG.standard_normal((TOKENS, HIDDEN)) * 0.01, jnp.bfloat16)
    w = jnp.asarray(_RNG.standard_normal((HIDDEN, VOCAB)) * 0.01, jnp.bfloat16)
    labels = jnp.asarray(_RNG.integers(0, VOCAB, TOKENS), jnp.int32)

    def op(hidden, w, labels):
        loss, _ = fused_linear_cross_entropy(
            hidden, w, labels, chunk_size=2048
        )
        # chain: fold the scalar back in so iterations serialize
        return hidden + loss.astype(hidden.dtype) * 0

    t = _timed(_chain(op), hidden, w, labels)
    # dominated by the lm_head matmul: report FLOP efficiency instead
    flops = 2 * TOKENS * HIDDEN * VOCAB
    return "fused_linear_ce(fwd)", t, None, flops


def main():
    peak_bw = 819e9  # v5e HBM
    peak_flops = 197e12
    print(f"| op | time/iter | ns/token | GB/s (of ~819) | MXU eff |")
    print(f"|---|---|---|---|---|")
    for fn in (bench_rms_norm, bench_rope, bench_swiglu, bench_fused_ce):
        res = fn()
        name, t, moved = res[0], res[1], res[2]
        flops = res[3] if len(res) > 3 else None
        ns_tok = t / TOKENS * 1e9
        bw = f"{moved / t / 1e9:.0f}" if moved else "-"
        eff = f"{flops / t / peak_flops:.2f}" if flops else "-"
        print(f"| {name} | {t*1e6:.1f} us | {ns_tok:.2f} | {bw} | {eff} |")


if __name__ == "__main__":
    main()
