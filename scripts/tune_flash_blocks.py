"""Offline block-size sweep for the Pallas flash-attention kernels.

Times candidate `(block_q, block_k)` tiles per
`(seq_len, head_dim, dtype, causal, sliding_window)` key — FORWARD and
BACKWARD independently (the bwd kernels carry different scratch footprints
and a 4-D dkv grid, so their best tiles are generally not the forward's) —
and persists the winners into the tuning table that
`llm_training_tpu/ops/pallas/tuning.py` consults at trace time.

Sweep order per key: the forward candidates first; then the backward
candidates with the forward pinned to its winner, so the fwd+bwd timing
delta isolates the backward tiles.

Deterministic by construction: fixed input seed, sorted candidate
enumeration, sorted JSON output, no timestamps — re-running on identical
hardware produces an identical table modulo the measured times. On CPU the
kernels run in interpreter mode; entries are tagged `cpu-interpret` and are
plumbing placeholders (real block choice only matters compiled on TPU) —
re-run on the bench chip to fill in measured entries.

Usage:
  python scripts/tune_flash_blocks.py                    # backend-sized sweep
  python scripts/tune_flash_blocks.py --seqs 8192,32768 --blocks 1024x1024,2048x1024
  python scripts/tune_flash_blocks.py --seed-defaults    # also write the
      v5e-measured 1024x1024 @ seq-2048/8192 entries (BASELINE/r3-r4 data)

Timing follows scripts/microbench_flash.py's tunnel rules: chained
iterations inside one jit, per-rep salt, completion proven by fetching
bytes.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

from llm_training_tpu.ops.pallas.flash_attention import flash_attention
from llm_training_tpu.ops.pallas import tuning

_RNG = np.random.default_rng(0)


def _fetch(out) -> None:
    jax.device_get(jax.tree.leaves(out)[0].ravel()[:8])


def _timed(fn, *args, iters: int, reps: int) -> float:
    """Median per-iteration seconds; first call absorbs the compile."""
    _fetch(fn(jnp.zeros((), jnp.float32), *args))
    times = []
    for rep in range(1, reps + 1):
        t0 = time.perf_counter()
        _fetch(fn(jnp.float32(rep * 1e-3), *args))
        times.append((time.perf_counter() - t0) / iters)
    return float(np.median(times))


def _make_inputs(seq: int, heads_q: int, heads_kv: int, head_dim: int, dtype):
    q = jnp.asarray(_RNG.standard_normal((1, seq, heads_q, head_dim)) * 0.1, dtype)
    k = jnp.asarray(_RNG.standard_normal((1, seq, heads_kv, head_dim)) * 0.1, dtype)
    v = jnp.asarray(_RNG.standard_normal((1, seq, heads_kv, head_dim)) * 0.1, dtype)
    return q, k, v


def _run_case(
    q, k, v, *, causal, sliding_window, fwd_blocks, bwd_blocks, bwd, iters, interpret
):
    """Build the timed jit: `iters` chained fwd (or fwd+grad) invocations."""
    kwargs = dict(
        causal=causal, sliding_window=sliding_window, interpret=interpret,
        block_q=fwd_blocks[0], block_k=fwd_blocks[1],
    )
    if bwd_blocks is not None:
        kwargs.update(bwd_block_q=bwd_blocks[0], bwd_block_k=bwd_blocks[1])

    if not bwd:
        @jax.jit
        def run(salt, q, k, v):
            def body(carry, _):
                o = flash_attention(q + carry.astype(q.dtype), k, v, **kwargs)
                return o[0, 0, 0, 0].astype(jnp.float32), None

            y, _ = jax.lax.scan(body, salt, None, length=iters)
            return y
    else:
        def loss_fn(q, k, v):
            o = flash_attention(q, k, v, **kwargs)
            return jnp.sum(o.astype(jnp.float32) ** 2)

        grad_fn = jax.grad(loss_fn, argnums=(0, 1, 2))

        @jax.jit
        def run(salt, q, k, v):
            def body(carry, _):
                # all three grads feed the carry or DCE drops the dkv call
                dq, dk, dv = grad_fn(q + carry.astype(q.dtype), k, v)
                live = dq[0, 0, 0, 0] + dk[0, 0, 0, 0] + dv[0, 0, 0, 0]
                return live.astype(jnp.float32), None

            y, _ = jax.lax.scan(body, salt, None, length=iters)
            return y

    return run


def _candidates(blocks: list[tuple[int, int]], seq: int) -> list[tuple[int, int]]:
    """Sorted candidates whose tiles divide the (block-padded) sequence —
    the wrapper pads seq up to a block multiple, so any tile <= padded seq
    works; skip tiles larger than the sequence (they'd all collapse to the
    same clamped shape and re-measure it)."""
    out = sorted(
        {(bq, bk) for bq, bk in blocks if bq <= max(seq, 128) and bk <= max(seq, 128)}
    )
    return out or [(min(seq, 128), min(seq, 128))]


def sweep(args) -> dict:
    on_tpu = jax.default_backend() == "tpu"
    interpret = not on_tpu
    backend = jax.default_backend() + ("-interpret" if interpret else "")
    iters = args.iters or (8 if on_tpu else 2)
    reps = 3 if on_tpu else 2

    entries: dict[str, dict] = {}
    for seq in args.seqs:
        for head_dim in args.head_dims:
            heads_q, heads_kv = args.heads
            for dtype_name in args.dtypes:
                dtype = jnp.dtype(dtype_name)
                for causal, window in args.configs:
                    q, k, v = _make_inputs(seq, heads_q, heads_kv, head_dim, dtype)
                    cands = _candidates(args.blocks, seq)

                    def time_blocks(fwd_blocks, bwd_blocks, bwd):
                        run = _run_case(
                            q, k, v, causal=causal, sliding_window=window,
                            fwd_blocks=fwd_blocks, bwd_blocks=bwd_blocks,
                            bwd=bwd, iters=iters, interpret=interpret,
                        )
                        return _timed(run, q, k, v, iters=iters, reps=reps)

                    # ---- forward sweep
                    fwd_times = {c: time_blocks(c, None, bwd=False) for c in cands}
                    best_fwd = min(sorted(fwd_times), key=fwd_times.get)
                    key = tuning.table_key("fwd", seq, head_dim, dtype, causal, window)
                    entries[key] = {
                        "block_q": best_fwd[0], "block_k": best_fwd[1],
                        "time_us": round(fwd_times[best_fwd] * 1e6, 2),
                        "backend": backend,
                    }
                    print(f"{key}: {best_fwd} "
                          f"({entries[key]['time_us']}us/iter)", flush=True)

                    # ---- backward sweep, forward pinned to its winner
                    bwd_times = {c: time_blocks(best_fwd, c, bwd=True) for c in cands}
                    best_bwd = min(sorted(bwd_times), key=bwd_times.get)
                    key = tuning.table_key("bwd", seq, head_dim, dtype, causal, window)
                    entries[key] = {
                        "block_q": best_bwd[0], "block_k": best_bwd[1],
                        "time_us": round(bwd_times[best_bwd] * 1e6, 2),
                        "backend": backend,
                    }
                    print(f"{key}: {best_bwd} "
                          f"({entries[key]['time_us']}us/iter)", flush=True)
    return entries


# v5e measurements already recorded in-repo (BASELINE.md / the r3-r4 sweep
# notes that used to live on the import-time constant): 1024x1024 best at
# seq 2048 and still the 8k bench choice. Written only with --seed-defaults
# so a CPU placeholder run cannot masquerade as chip data.
_V5E_SEEDS = {
    tuning.table_key(kind, seq, 128, jnp.bfloat16, True, None): {
        "block_q": 1024, "block_k": 1024, "time_us": None, "backend": "v5e",
    }
    for kind in ("fwd", "bwd")
    for seq in (2048, 8192)
}


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    on_tpu = jax.default_backend() == "tpu"
    parser.add_argument("--out", default=str(tuning.DEFAULT_TABLE_PATH))
    parser.add_argument("--seqs", default=None,
                        help="comma ints (default: 2048,8192 on TPU; 256,512 on CPU)")
    parser.add_argument("--head-dims", default=None, help="comma ints")
    parser.add_argument("--heads", default=None, help="HQxHKV (default 32x8 TPU, 4x2 CPU)")
    parser.add_argument("--dtypes", default=None, help="comma dtype names")
    parser.add_argument("--blocks", default=None,
                        help="comma QxK candidates, e.g. 512x512,1024x1024")
    parser.add_argument("--windows", default="",
                        help="comma sliding windows to sweep in addition to "
                             "plain causal (each adds a causal+window config)")
    parser.add_argument("--causal-only", action="store_true",
                        help="skip the non-causal config (swept by default: "
                             "ring attention's off-diagonal chunk pairs — the "
                             "bulk of ring compute at high ring degree — look "
                             "up causal0 entries at the chunk length)")
    parser.add_argument("--iters", type=int, default=None)
    parser.add_argument("--no-merge", action="store_true",
                        help="replace the table instead of merging entries in")
    parser.add_argument("--seed-defaults", action="store_true",
                        help="also write the recorded v5e 1024x1024 entries")
    args = parser.parse_args()

    args.seqs = [int(s) for s in (
        args.seqs or ("2048,8192" if on_tpu else "256,512")).split(",")]
    args.head_dims = [int(s) for s in (args.head_dims or ("128" if on_tpu else "64")).split(",")]
    hq, hkv = (args.heads or ("32x8" if on_tpu else "4x2")).split("x")
    args.heads = (int(hq), int(hkv))
    args.dtypes = (args.dtypes or ("bfloat16" if on_tpu else "float32")).split(",")
    default_blocks = "512x512,1024x1024,1024x2048,2048x1024" if on_tpu else "128x128,256x256,128x256"
    args.blocks = [
        tuple(int(x) for x in pair.split("x"))
        for pair in (args.blocks or default_blocks).split(",")
    ]
    args.configs = [(True, None)]
    if not args.causal_only:
        args.configs.append((False, None))
    args.configs += [(True, int(w)) for w in args.windows.split(",") if w]

    entries = sweep(args)
    if args.seed_defaults:
        for key, value in _V5E_SEEDS.items():
            entries.setdefault(key, value)

    out = Path(args.out)
    table = {"version": 1, "generated_by": "scripts/tune_flash_blocks.py", "entries": {}}
    if out.exists() and not args.no_merge:
        try:
            prior = json.loads(out.read_text())
            table["entries"].update(prior.get("entries", {}))
        except (OSError, json.JSONDecodeError):
            print(f"warning: could not merge unreadable table at {out}", file=sys.stderr)
    table["entries"].update(entries)
    table["entries"] = dict(sorted(table["entries"].items()))
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(table, indent=2) + "\n")
    print(f"wrote {len(entries)} swept entries ({len(table['entries'])} total) -> {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
