#!/usr/bin/env python
"""Synthetic-traffic load driver for the `serve` CLI (docs/serving.md).

Spawns `llm-training-tpu serve` (or, with `--supervised`, `supervise
--child serve` — the drain/replay harness) as a child process and drives
the real JSONL stdin/stdout protocol. Two arrival modes:

- **overlap** (default): the first request goes in immediately; every
  later request is held until the first streamed token chunk proves decode
  is in flight, then submitted with a small gap — so continuous batching
  (admission mid-decode) is what the run exercises, not a closed batch;
- **burst**: every request is written up front, as fast as the pipe takes
  them — the overload shape that drives the intake bound / projected-TTFT
  shedding (`--max-batch`/`--max-queue` small → `overloaded` terminals).

`--deadline-ms N --deadline-every K` stamps every K-th request with a
latency budget (mixed traffic: some requests carry deadlines, some don't),
and `--malformed N` interleaves N junk lines the server must answer with
`{"type": "error"}` chunks while everything well-formed still terminates.
`--metrics-port N` additionally scrapes the child's live-telemetry
exporter (/metrics + /healthz, docs/observability.md#live-telemetry)
throughout the run: every scrape must parse as Prometheus text, and at
the moment every request has its terminal the final scrape's
`serve/requests_completed` and queue-depth gauges must MATCH this
driver's client-side census — exporter/engine drift is a failure.

The terminal contract this driver enforces (exit nonzero on violation) is
the serving tier's resilience acceptance: every submitted request must end
in EXACTLY ONE `done` chunk — stop_reason ∈ eos / max_tokens / deadline /
overloaded / rejected / capacity — across the whole run, including a
supervised drain/replay boundary (the relaunched child inherits this
driver's pipes, so duplicate or missing terminals are visible here).
Additional failures: a done with no token chunks for a FULL completion
(eos/max_tokens), a pool-block leak in the last stats record, fewer error
chunks than injected malformed lines, and (overlap mode only) arrivals
that never overlapped (`serve/peak_running` < 2).

Client-side latency is measured per request from its submit time: TTFT to
the first token chunk, TPOT across subsequent chunks. The summary merges
the engine's own `serve/*` stats record (throughput, shed/deadline/replay
counters, pool pressure) with the client percentiles and a per-stop_reason
terminal census, prints one JSON object, and exits nonzero on any failure.

`--router` drives the `route` fleet tier instead of a bare serve child
(docs/serving.md#router): the same exactly-once-terminal audit applies
across a chaos-injected mid-stream replica SIGKILL
(LLMT_CHAOS_ROUTER_KILL_REPLICA), and with `--fleet-dir` the all-terminal
moment additionally sweeps the fleet and asserts the rollup's
`router_requests_completed` still equals the client census after the
failover replay.

Usage:
    python scripts/serve_loadgen.py --config <yaml> [overrides...] \
        [--requests 4] [--max-new-tokens 8] [--arrival {overlap,burst}] \
        [--deadline-ms 0 --deadline-every 2] [--malformed 0] \
        [--supervised | --router] [--out summary.json] \
        [-- <extra serve args>]
"""

from __future__ import annotations

import argparse
import json
import os
import random
import subprocess
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# the ONE strict scrape parser, shared with the precommit exporter smoke
# and the unit tests so format drift fails identically everywhere; the
# telemetry package surface is jax-free at import time by contract, so
# this parent stays backend-free
from llm_training_tpu.telemetry.exporter import parse_prometheus_text  # noqa: E402

# the terminal states the protocol may end a request in — anything else
# (or anything twice, or nothing at all) is a dropped/duplicated stream
TERMINAL_REASONS = (
    "eos", "max_tokens", "deadline", "overloaded", "rejected", "capacity"
)


class ExporterScraper:
    """Polls the serve child's /metrics + /healthz during the run
    (docs/observability.md#live-telemetry). Connection failures are
    expected (child starting up / relaunching) and only counted; a scrape
    that ANSWERS but fails to parse is a recorded error. `scrape_final()`
    is called synchronously the moment every request has its terminal —
    at that instant the engine is quiescent (nothing queued or running),
    so the gauge cross-check against the client census is exact."""

    def __init__(self, port: int, interval_s: float = 0.2):
        import urllib.request as _request

        self._request = _request
        self.base = f"http://127.0.0.1:{port}"
        self.interval_s = interval_s
        self._lock = threading.Lock()
        self.ok = 0  # guarded by: _lock
        self.failed = 0  # guarded by: _lock
        self.parse_errors: list[str] = []  # guarded by: _lock
        self.unhealthy_observed = False  # guarded by: _lock
        self.max_queue_depth = 0.0  # guarded by: _lock
        self.final: dict[str, float] | None = None  # guarded by: _lock
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> "ExporterScraper":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)

    def _get(self, path: str):
        return self._request.urlopen(self.base + path, timeout=2.0)

    def scrape_once(self) -> dict[str, float] | None:
        """One scrape (network I/O outside the lock; called from both the
        poll thread and the main thread's final-census moment)."""
        try:
            with self._get("/metrics") as resp:
                body = resp.read().decode("utf-8", "replace")
        except OSError:
            with self._lock:
                self.failed += 1  # child starting/relaunching: expected
            return None
        try:
            metrics = parse_prometheus_text(body)
        except ValueError as e:
            with self._lock:
                self.parse_errors.append(str(e))
            return None
        with self._lock:
            self.ok += 1
            self.max_queue_depth = max(
                self.max_queue_depth, metrics.get("llmt_serve_queue_depth", 0.0)
            )
        return metrics

    def _check_health(self) -> None:
        try:
            with self._get("/healthz"):
                pass  # 200
        except OSError as e:
            if getattr(e, "code", None) == 503:
                with self._lock:
                    self.unhealthy_observed = True
            # anything else: child down/starting — not a health verdict

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.scrape_once()
            self._check_health()

    def scrape_final(self) -> None:
        # bounded retry: the child races from its last terminal through
        # stats/telemetry-write to exporter.stop(), and losing that race
        # must not turn a healthy run into a spurious census failure — the
        # engine is quiescent, so a slightly later scrape reads the same
        # gauges
        metrics = None
        for _ in range(10):
            metrics = self.scrape_once()
            if metrics is not None:
                break
            time.sleep(0.1)
        with self._lock:
            self.final = metrics

    def summary(self) -> dict:
        with self._lock:
            return {
                "scrapes_ok": self.ok,
                "scrapes_failed": self.failed,
                "parse_errors": list(self.parse_errors),
                "unhealthy_observed": self.unhealthy_observed,
                "max_queue_depth": self.max_queue_depth,
                "final": dict(self.final) if self.final else None,
            }


def percentile(values: list[float], q: float) -> float:
    """Nearest-rank percentile; avoids a numpy import in this jax-free
    parent (the child owns the devices)."""
    ordered = sorted(values)
    rank = max(0, min(len(ordered) - 1, round(q / 100.0 * (len(ordered) - 1))))
    return ordered[rank]


def build_requests(args) -> list[dict]:
    rng = random.Random(args.seed)
    requests = []
    for n in range(args.requests):
        length = rng.randint(args.min_prompt, args.max_prompt)
        request = {
            "id": f"req-{n}",
            "prompt": [rng.randint(3, args.vocab - 1) for _ in range(length)],
            "max_new_tokens": args.max_new_tokens,
        }
        if args.deadline_ms and args.deadline_every and n % args.deadline_every == 0:
            request["deadline_ms"] = args.deadline_ms
        requests.append(request)
    return requests


def check_misplaced_flags(
    serve_args: list[str], passthrough: list[str], argv: list[str] | None = None
) -> None:
    """The PR 16 argparse watch-out, made loud: with an otherwise-empty
    `serve_args` positional, `parse_known_args` assigns the token FOLLOWING
    the first unknown flag to the positional — the flag's value silently
    vanishes into serve_args while the flag itself lands in passthrough
    (`--max-batch 2` becomes serve_args=['2'] + passthrough=['--max-batch']).
    Any positional token that appears AFTER the first unknown flag on the
    command line is that swallow; error loudly and demand `--`. Flags after
    genuine positionals (the precommit idiom: `run_root=/x --max-batch 2`)
    keep order and stay legal."""
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    if "--" in argv:
        return  # explicit separator: everything after it is intentional
    unknown_positions = [argv.index(tok) for tok in set(passthrough) if tok in argv]
    if not unknown_positions:
        return
    first_unknown = min(unknown_positions)
    for token in serve_args:
        try:
            index = argv.index(token)
        except ValueError:
            continue
        if index > first_unknown:
            raise SystemExit(
                f"error: positional {token!r} follows the unknown flag "
                f"{argv[first_unknown]!r} — argparse would silently swallow "
                "the flag's value into serve_args. Put child flags after "
                "an explicit `--` separator (e.g. `-- "
                f"{argv[first_unknown]} {token}`)."
            )


def build_child_argv(args) -> list[str]:
    """The plain `serve` command, the `route` fleet tier (`--router`), or
    the supervised wrapper that relaunches serve on exit 75 / signal deaths
    (drain + journal replay, docs/serving.md#resilience)."""
    if args.router:
        argv = [
            sys.executable, "-m", "llm_training_tpu", "route",
            "--config", args.config,
            "--replicas", str(args.router_replicas),
        ]
        if args.router_max_replicas:
            argv += ["--max-replicas", str(args.router_max_replicas)]
        if args.hedge_ttft_ms:
            argv += ["--hedge-ttft-ms", str(args.hedge_ttft_ms)]
        if args.serve_args:
            argv += ["--", *args.serve_args]
        return argv
    if not args.supervised:
        return [
            sys.executable, "-m", "llm_training_tpu", "serve",
            "--config", args.config, *args.serve_args,
        ]
    import shlex

    return [
        sys.executable, "-m", "llm_training_tpu", "supervise",
        "--child", "serve", "--config", args.config,
        "--max-restarts", str(args.max_restarts),
        "--backoff-base-s", "0.2", "--backoff-max-s", "1.0",
        "--child-args", shlex.join(args.serve_args),
    ]


class ReplicaDriver:
    """One serve child of the multi-replica loadgen (`--replicas N`):
    owns the child process, a feeder thread (this replica's share of the
    traffic), and a reader thread folding protocol chunks into the
    per-replica census. The feeder deliberately does NOT close stdin —
    the fleet census must sweep live exporters AFTER every terminal, so
    the children idle until `finish()` releases them."""

    def __init__(self, index: int, args, requests: list[dict], env: dict,
                 run_root: str):
        self.index = index
        self.args = args
        self.requests = requests
        self.child = subprocess.Popen(
            [
                sys.executable, "-m", "llm_training_tpu", "serve",
                "--config", args.config, *args.serve_args,
                f"run_root={run_root}",
            ],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
            bufsize=1, env=env,
        )
        self._lock = threading.Lock()
        self.done: dict[str, dict] = {}  # guarded by: _lock
        self.done_counts: dict[str, int] = {}  # guarded by: _lock
        self.chunks: dict[str, int] = {}  # guarded by: _lock
        self.stats: dict[str, float] = {}  # guarded by: _lock
        self.error_chunks = 0  # guarded by: _lock
        self.all_terminal = threading.Event()
        self.first_token_seen = threading.Event()
        self._feeder = threading.Thread(target=self._feed, daemon=True)
        self._reader = threading.Thread(target=self._read, daemon=True)

    def start(self) -> "ReplicaDriver":
        self._reader.start()
        self._feeder.start()
        return self

    def _send(self, request: dict) -> None:
        self.child.stdin.write(json.dumps(request) + "\n")
        self.child.stdin.flush()

    def _feed(self) -> None:
        try:
            self._send(self.requests[0])
            if self.args.arrival == "overlap":
                self.first_token_seen.wait()
            for n, request in enumerate(self.requests[1:]):
                if n and self.args.arrival == "overlap":
                    time.sleep(self.args.arrival_gap_s)
                self._send(request)
        except (BrokenPipeError, OSError):
            pass  # child died; the terminal audit reports the hole

    def _read(self) -> None:
        expected = {r["id"] for r in self.requests}
        for line in self.child.stdout:
            line = line.strip()
            if not line:
                continue
            try:
                event = json.loads(line)
            except json.JSONDecodeError:
                continue  # interleaved logging, not a protocol chunk
            kind = event.get("type")
            if kind == "token":
                rid = event["id"]
                with self._lock:
                    self.chunks[rid] = self.chunks.get(rid, 0) + 1
                self.first_token_seen.set()
            elif kind == "done":
                rid = event["id"]
                with self._lock:
                    self.done[rid] = event
                    self.done_counts[rid] = self.done_counts.get(rid, 0) + 1
                    terminal = expected <= set(self.done)
                self.first_token_seen.set()
                if terminal:
                    self.all_terminal.set()
            elif kind == "stats":
                with self._lock:
                    self.stats = event["stats"]
            elif kind == "error":
                with self._lock:
                    self.error_chunks += 1
                self.first_token_seen.set()
        # stdout EOF: the child is gone. Unblock the census waiter NOW —
        # the rc audit and the exactly-once terminal audit report the
        # holes; hanging out the idle timeout helps nobody.
        self.first_token_seen.set()
        self.all_terminal.set()

    def finish(self) -> int:
        """Release the idling child (close stdin), collect its exit."""
        self.first_token_seen.set()  # unwedge the feeder on a dead child
        try:
            self.child.stdin.close()
        except OSError:
            pass
        try:
            rc = self.child.wait(timeout=60.0)
        except subprocess.TimeoutExpired:
            self.child.kill()
            rc = self.child.wait()
        self._reader.join(timeout=10.0)
        self._feeder.join(timeout=10.0)
        return rc

    def census(self) -> dict:
        with self._lock:
            reasons: dict[str, int] = {}
            for event in self.done.values():
                reason = str(event.get("stop_reason"))
                reasons[reason] = reasons.get(reason, 0) + 1
            return {
                "replica": self.index,
                "requests": len(self.requests),
                "completed": reasons.get("eos", 0) + reasons.get("max_tokens", 0),
                "terminal_reasons": reasons,
                "streamed_chunks": sum(self.chunks.values()),
                "error_chunks": self.error_chunks,
                "done_counts": dict(self.done_counts),
                "engine": dict(self.stats),
            }


def run_multi(args) -> int:
    """`--replicas N`: split the traffic round-robin across N serve
    children (each with its own run_root, metrics port, and fleet card)
    and assert the FLEET census at the all-terminal moment: the
    aggregator's rollup must equal the sum of the per-replica client
    censuses, terminals exactly-once fleet-wide, verdict green."""
    from llm_training_tpu.telemetry.exporter import find_free_port
    from llm_training_tpu.telemetry.fleet import FleetAggregator

    if args.supervised or args.malformed:
        print(
            "--replicas composes with neither --supervised nor "
            "--malformed (drive those single-replica)", file=sys.stderr,
        )
        return 2
    if not args.replica_run_root:
        print("--replicas needs --replica-run-root", file=sys.stderr)
        return 2
    requests = build_requests(args)
    if len(requests) < args.replicas:
        print(
            f"--requests {len(requests)} < --replicas {args.replicas}",
            file=sys.stderr,
        )
        return 2
    fleet_dir = args.fleet_dir or os.environ.get("LLMT_FLEET_DIR")
    drivers: list[ReplicaDriver] = []
    ports: list[int] = []
    for index in range(args.replicas):
        port = find_free_port()
        env = {**os.environ, "LLMT_METRICS_PORT": str(port)}
        if fleet_dir:
            env["LLMT_FLEET_DIR"] = str(fleet_dir)
        drivers.append(ReplicaDriver(
            index, args, requests[index::args.replicas], env,
            str(Path(args.replica_run_root) / f"replica-{index}"),
        ))
        ports.append(port)
    for driver in drivers:
        driver.start()

    failures: list[str] = []
    deadline = time.monotonic() + args.idle_timeout_s
    for driver in drivers:
        remaining = max(0.0, deadline - time.monotonic())
        if not driver.all_terminal.wait(remaining):
            failures.append(
                f"replica-{driver.index}: not every request terminal "
                f"within {args.idle_timeout_s}s"
            )

    # --- THE fleet census moment: every engine quiescent (all terminals
    # in), every exporter still armed (stdin held open) — one sweep must
    # see the whole fleet green and its rollup equal the client truth
    fleet_snapshot = None
    if not failures:
        aggregator = FleetAggregator(
            fleet_dir=fleet_dir,
            targets="" if fleet_dir else ",".join(
                f"127.0.0.1:{port}" for port in ports
            ),
        )
        fleet_snapshot = aggregator.sweep()
        if len(fleet_snapshot["replicas"]) != args.replicas:
            failures.append(
                f"fleet census: {len(fleet_snapshot['replicas'])} "
                f"replica(s) visible, want {args.replicas} "
                f"(dir={fleet_dir!r})"
            )
        if fleet_snapshot["verdict"] != "green":
            failures.append(
                f"fleet verdict {fleet_snapshot['verdict']!r} at the "
                f"census moment (red={fleet_snapshot['red']}, "
                f"stale={fleet_snapshot['stale_cards']})"
            )
        rollup = fleet_snapshot["rollup"]
        client_completed = sum(d.census()["completed"] for d in drivers)
        scraped = rollup.get("llmt_fleet_serve_requests_completed")
        if scraped != float(client_completed):
            failures.append(
                f"fleet census drift: rollup requests_completed "
                f"{scraped} != summed client censuses {client_completed}"
            )
        for gauge in (
            "llmt_fleet_serve_queue_depth", "llmt_fleet_serve_running"
        ):
            if rollup.get(gauge, 0.0) != 0.0:
                failures.append(
                    f"fleet not quiescent at census: {gauge} = "
                    f"{rollup[gauge]}"
                )

    rcs = [driver.finish() for driver in drivers]
    for index, rc in enumerate(rcs):
        if rc != 0:
            failures.append(f"replica-{index}: serve exited {rc}")

    # --- exactly-once terminals FLEET-WIDE: each request was routed to
    # one replica and must have exactly one done chunk anywhere
    fleet_done: dict[str, int] = {}
    per_replica = [driver.census() for driver in drivers]
    for census in per_replica:
        for rid, count in census.pop("done_counts").items():
            fleet_done[rid] = fleet_done.get(rid, 0) + count
    for request in requests:
        count = fleet_done.get(request["id"], 0)
        if count != 1:
            failures.append(
                f"{request['id']}: {count} terminal(s) fleet-wide — "
                "want exactly one"
            )

    summary = {
        "replicas": args.replicas,
        "requests": len(requests),
        "completed": sum(c["completed"] for c in per_replica),
        "per_replica": per_replica,
        "fleet": {
            "verdict": fleet_snapshot["verdict"],
            "red": fleet_snapshot["red"],
            "stale_cards": fleet_snapshot["stale_cards"],
            "rollup": {
                key: value
                for key, value in fleet_snapshot["rollup"].items()
                if key.startswith(("llmt_fleet_serve_", "llmt_fleet_replicas"))
            },
        } if fleet_snapshot else None,
        "errors": failures,
    }
    print(json.dumps(summary))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f)
    return 1 if failures else 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--config", required=True)
    parser.add_argument("--requests", type=int, default=4)
    parser.add_argument("--max-new-tokens", type=int, default=8)
    parser.add_argument("--min-prompt", type=int, default=2)
    parser.add_argument("--max-prompt", type=int, default=6)
    parser.add_argument("--vocab", type=int, default=64, help="synthetic token id bound")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--arrival", default="overlap", choices=("overlap", "burst"),
        help="overlap = follow-ups wait for the first token (continuous-"
        "batching proof); burst = everything up front (overload/shedding)",
    )
    parser.add_argument(
        "--arrival-gap-s", type=float, default=0.05,
        help="gap between follow-up arrivals (overlap mode)",
    )
    parser.add_argument(
        "--deadline-ms", type=float, default=0.0,
        help="latency budget stamped on every --deadline-every-th request "
        "(0 = no deadlines)",
    )
    parser.add_argument(
        "--deadline-every", type=int, default=2,
        help="which requests carry --deadline-ms (every K-th, from the "
        "first) — mixed deadline traffic by default",
    )
    parser.add_argument(
        "--malformed", type=int, default=0,
        help="junk lines interleaved into the stream; the server owes an "
        "error chunk for each and every real request still a terminal",
    )
    parser.add_argument(
        "--supervised", action="store_true",
        help="drive `supervise --child serve` instead of bare `serve`: "
        "SIGTERM/SIGABRT deaths relaunch and replay the request journal "
        "(pair with LLMT_CHAOS_SERVE_* faults)",
    )
    parser.add_argument(
        "--max-restarts", type=int, default=3,
        help="supervise restart budget (--supervised only)",
    )
    parser.add_argument(
        "--idle-timeout-s", type=float, default=600.0,
        help="kill the child when no stdout line lands for this long",
    )
    parser.add_argument(
        "--metrics-port", type=int, default=0,
        help="scrape the serve child's /metrics + /healthz exporter "
        "(docs/observability.md#live-telemetry) on this port during the "
        "run and cross-check serve/requests_completed + queue-depth "
        "gauges against the client-side census (exporter/engine drift is "
        "a failure). The child must run with LLMT_METRICS_PORT set to the "
        "same port; 0 = no scraping",
    )
    parser.add_argument(
        "--replicas", type=int, default=1,
        help="multi-replica mode (docs/observability.md#fleet): split the "
        "traffic round-robin across N serve children and assert the FLEET "
        "census (aggregator rollup == summed per-replica client censuses, "
        "terminals exactly-once fleet-wide)",
    )
    parser.add_argument(
        "--replica-run-root", default=None,
        help="base directory for per-replica run roots "
        "(<base>/replica-<i>; required with --replicas > 1)",
    )
    parser.add_argument(
        "--fleet-dir", default=None,
        help="discovery directory for the fleet census (sets "
        "LLMT_FLEET_DIR for the children; default: inherit the env; "
        "unset = census by static --targets over the child ports)",
    )
    parser.add_argument(
        "--router", action="store_true",
        help="drive the `route` fleet tier instead of a bare serve child "
        "(docs/serving.md#router): same protocol audit, but the child is "
        "the router over --router-replicas serve replicas — pair with "
        "LLMT_CHAOS_ROUTER_* faults to prove exactly-once terminals "
        "across a mid-stream replica kill",
    )
    parser.add_argument(
        "--router-replicas", type=int, default=2,
        help="serve replicas behind the router (--router only)",
    )
    parser.add_argument(
        "--router-max-replicas", type=int, default=None,
        help="router elasticity ceiling (--router only; default: "
        "--router-replicas)",
    )
    parser.add_argument(
        "--hedge-ttft-ms", type=float, default=0.0,
        help="router hedge budget (--router only; 0 = hedging off)",
    )
    parser.add_argument("--out", default=None, help="also write the summary JSON here")
    parser.add_argument(
        "serve_args", nargs="*",
        help="config overrides and extra `serve` flags (e.g. run_root=... "
        "--max-batch 2)",
    )
    # unknown flags (e.g. --max-batch) pass through to the serve child —
    # but a flag whose value argparse swallowed into the positional slot
    # must error loudly, not vanish (see check_misplaced_flags)
    args, passthrough = parser.parse_known_args()
    check_misplaced_flags(args.serve_args, passthrough)
    args.serve_args += passthrough

    if args.router and (args.supervised or args.malformed or args.replicas > 1):
        print(
            "--router composes with none of --supervised / --malformed / "
            "--replicas (the router owns its own fleet)", file=sys.stderr,
        )
        return 2
    if args.replicas > 1:
        return run_multi(args)

    requests = build_requests(args)
    env_updates: dict[str, str] = {}
    if args.metrics_port:
        # the child reads LLMT_METRICS_PORT itself; setting it here keeps
        # one flag driving both sides (and supervise's env passthrough
        # carries it across relaunches)
        env_updates["LLMT_METRICS_PORT"] = str(args.metrics_port)
    if args.router and args.fleet_dir:
        env_updates["LLMT_FLEET_DIR"] = str(args.fleet_dir)
    child_env = {**os.environ, **env_updates} if env_updates else None
    child = subprocess.Popen(
        build_child_argv(args),
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True, bufsize=1,
        env=child_env,
    )
    scraper = (
        ExporterScraper(args.metrics_port).start() if args.metrics_port else None
    )

    submit_s: dict[str, float] = {}
    first_token_s: dict[str, float] = {}
    last_token_s: dict[str, float] = {}
    chunks: dict[str, int] = {}
    done: dict[str, dict] = {}
    done_counts: dict[str, int] = {}
    stats: dict[str, float] = {}
    error_chunks: list[str] = []
    failures: list[str] = []
    first_token_seen = threading.Event()

    def send_line(line: str) -> None:
        child.stdin.write(line + "\n")
        child.stdin.flush()

    def send(request: dict) -> None:
        submit_s[request["id"]] = time.perf_counter()
        send_line(json.dumps(request))

    def feed() -> None:
        malformed_left = args.malformed
        try:
            send(requests[0])
            if args.arrival == "overlap":
                # hold the rest until decode is demonstrably in flight, so
                # every later arrival exercises mid-stream admission; the
                # first follow-up goes immediately (a warm decode step is
                # ~ms — any fixed gap risks outliving the first generation)
                first_token_seen.wait()
            for n, request in enumerate(requests[1:]):
                if malformed_left > 0:
                    send_line('{"garbage: true')  # interleaved junk
                    malformed_left -= 1
                if n and args.arrival == "overlap":
                    time.sleep(args.arrival_gap_s)
                send(request)
            while malformed_left > 0:
                send_line('{"garbage: true')
                malformed_left -= 1
        except BrokenPipeError:
            pass  # child died; the reader loop reports it
        finally:
            if not args.router:
                # router mode holds stdin open: the fleet census must sweep
                # the router's live exporters AFTER every terminal (the
                # reader's all-done moment closes it)
                try:
                    child.stdin.close()
                except OSError:
                    pass

    feeder = threading.Thread(target=feed, daemon=True)
    feeder.start()

    router_fleet: dict | None = None
    census_taken = False

    def router_all_done_census() -> None:
        """The --router census moment: every request just went terminal,
        the router and its replicas are quiescent but still alive (stdin
        held open) — sweep the fleet NOW, then release the router."""
        nonlocal router_fleet
        if scraper is not None:
            scraper.scrape_final()
        if args.fleet_dir:
            from llm_training_tpu.telemetry.fleet import FleetAggregator

            router_fleet = FleetAggregator(fleet_dir=args.fleet_dir).sweep()
        try:
            child.stdin.close()
        except OSError:
            pass

    timer = threading.Timer(args.idle_timeout_s, child.kill)
    timer.start()
    try:
        for line in child.stdout:
            timer.cancel()
            timer = threading.Timer(args.idle_timeout_s, child.kill)
            timer.start()
            line = line.strip()
            if not line:
                continue
            try:
                event = json.loads(line)
            except json.JSONDecodeError:
                continue  # interleaved logging, not a protocol chunk
            now = time.perf_counter()
            kind = event.get("type")
            if kind == "token":
                rid = event["id"]
                chunks[rid] = chunks.get(rid, 0) + 1
                first_token_s.setdefault(rid, now)
                last_token_s[rid] = now
                first_token_seen.set()
            elif kind == "done":
                rid = event["id"]
                done[rid] = event
                done_counts[rid] = done_counts.get(rid, 0) + 1
                # a token-less termination (rejected / capacity / deadline /
                # overloaded) must also unblock the feeder, or a first
                # request that never streams wedges the run until the idle
                # timeout
                first_token_seen.set()
                if not census_taken and all(r["id"] in done for r in requests):
                    # every request just went terminal: the engine is
                    # quiescent NOW (nothing queued or running), so this
                    # synchronous scrape is the exact-census moment
                    census_taken = True
                    if args.router:
                        router_all_done_census()
                    elif scraper is not None:
                        scraper.scrape_final()
            elif kind == "stats":
                stats = event["stats"]  # last record wins across relaunches
            elif kind == "error":
                error_chunks.append(event.get("error", "unknown"))
                first_token_seen.set()
    finally:
        timer.cancel()
        first_token_seen.set()  # unblock the feeder if the child died early
    rc = child.wait()

    # --- the terminal contract: exactly one honest terminal per request
    for request in requests:
        rid = request["id"]
        count = done_counts.get(rid, 0)
        if count == 0:
            failures.append(f"{rid}: no done chunk (rc {rc})")
            continue
        if count > 1:
            failures.append(
                f"{rid}: {count} done chunks — a terminal must arrive "
                "exactly once (duplicate across a drain/replay boundary?)"
            )
        reason = done[rid].get("stop_reason")
        if reason not in TERMINAL_REASONS:
            failures.append(f"{rid}: unknown stop_reason {reason!r}")
        elif reason in ("eos", "max_tokens") and not chunks.get(rid):
            failures.append(f"{rid}: done without any streamed token chunks")
    if args.router:
        # the final stats record is router/*-shaped: the pool-leak check
        # belongs to the replicas (the router audits its own census)
        if not stats:
            failures.append("no stats record from the router")
        else:
            total = stats.get("requests_total", -1)
            terminals = stats.get("requests_completed", 0) + stats.get(
                "requests_failed", 0
            )
            if terminals != total:
                failures.append(
                    f"router census not exactly-once: {terminals} terminals "
                    f"for {total} routed requests"
                )
    else:
        leaked = stats.get("decode/cache_blocks_in_use")
        if leaked is None:
            failures.append("no stats record from the child")
        elif leaked:
            failures.append(f"pool leak: {int(leaked)} blocks still in use at exit")
    # the serve process also answers chaos-injected junk
    # (LLMT_CHAOS_SERVE_MALFORMED_FLOOD) with error chunks on this stream
    expected_errors = args.malformed + int(
        os.environ.get("LLMT_CHAOS_SERVE_MALFORMED_FLOOD", "0") or 0
    )
    if len(error_chunks) < expected_errors:
        failures.append(
            f"only {len(error_chunks)} error chunk(s) for "
            f"{expected_errors} malformed line(s)"
        )
    peak = (
        stats.get("peak_inflight", 0) if args.router
        else stats.get("serve/peak_running", 0)
    )
    if args.arrival == "overlap" and len(requests) > 1 and peak < 2:
        failures.append(
            f"arrivals never overlapped (peak_running {peak}) — raise "
            "--max-new-tokens or check --max-batch > 1"
        )

    # --- exporter cross-check (--metrics-port): the live gauges must agree
    # with this driver's own census — scraped-vs-client drift means the
    # exporter (or the engine state it renders) is lying to the fleet
    scrape_summary = None
    if scraper is not None:
        scraper.stop()
        scrape_summary = scraper.summary()
        if scrape_summary["parse_errors"]:
            failures.append(
                "scrape parse errors (exporter format drift?): "
                f"{scrape_summary['parse_errors'][:3]}"
            )
        if scrape_summary["scrapes_ok"] == 0:
            failures.append(
                "--metrics-port set but /metrics was never scrapeable"
            )
        final = scrape_summary["final"]
        if final is None:
            failures.append(
                "no parse-valid scrape at the all-terminal moment"
            )
        elif args.router:
            for gauge in ("llmt_router_queue_depth", "llmt_router_inflight"):
                if final.get(gauge, 0.0) != 0.0:
                    failures.append(
                        f"router not quiescent at the final scrape: "
                        f"{gauge} = {final[gauge]}"
                    )
            client_completed = sum(
                1 for event in done.values()
                if event.get("stop_reason") in ("eos", "max_tokens")
            )
            scraped = final.get("llmt_router_requests_completed")
            if scraped != float(client_completed):
                failures.append(
                    f"exporter/router drift: scraped requests_completed "
                    f"{scraped} != client census {client_completed}"
                )
        else:
            for gauge in ("llmt_serve_queue_depth", "llmt_serve_running"):
                if final.get(gauge, 0.0) != 0.0:
                    failures.append(
                        f"engine not quiescent at the final scrape: "
                        f"{gauge} = {final[gauge]}"
                    )
            if not args.supervised:
                # a supervised run's relaunched engine only counts its own
                # segment's completions; the strict census equality is an
                # unsupervised-run contract
                client_completed = sum(
                    1 for event in done.values()
                    if event.get("stop_reason") in ("eos", "max_tokens")
                )
                scraped = final.get("llmt_serve_requests_completed")
                if scraped != float(client_completed):
                    failures.append(
                        f"exporter/engine drift: scraped "
                        f"requests_completed {scraped} != client census "
                        f"{client_completed}"
                    )

    # --- --router + --fleet-dir: the fleet rollup at the all-terminal
    # sweep must still match the client census even after a mid-stream
    # replica kill and failover replay (satellite of the failover proof)
    if args.router and args.fleet_dir:
        if router_fleet is None:
            failures.append(
                "--fleet-dir set but the all-terminal fleet sweep never ran "
                "(did every request get a terminal?)"
            )
        else:
            if router_fleet["verdict"] != "green":
                failures.append(
                    f"fleet verdict {router_fleet['verdict']!r} at the "
                    f"census moment (red={router_fleet['red']}, "
                    f"stale={router_fleet['stale_cards']})"
                )
            client_completed = sum(
                1 for event in done.values()
                if event.get("stop_reason") in ("eos", "max_tokens")
            )
            rolled = router_fleet["rollup"].get(
                "llmt_fleet_router_requests_completed"
            )
            if rolled != float(client_completed):
                failures.append(
                    f"fleet census drift after failover: rollup "
                    f"router_requests_completed {rolled} != client census "
                    f"{client_completed}"
                )

    ttft = [
        1000.0 * (first_token_s[r] - submit_s[r]) for r in first_token_s
    ]
    tpot = [
        1000.0 * (last_token_s[r] - first_token_s[r]) / (chunks[r] - 1)
        for r in first_token_s if chunks.get(r, 0) > 1
    ]
    reasons: dict[str, int] = {}
    for event in done.values():
        reason = str(event.get("stop_reason"))
        reasons[reason] = reasons.get(reason, 0) + 1
    summary = {
        "requests": len(requests),
        "completed": reasons.get("eos", 0) + reasons.get("max_tokens", 0),
        "terminal_reasons": reasons,
        "streamed_chunks": sum(chunks.values()),
        "error_chunks": len(error_chunks),
        "errors": failures,
        "engine": stats,
    }
    if scrape_summary is not None:
        summary["scrape"] = scrape_summary
    if router_fleet is not None:
        summary["fleet"] = {
            "verdict": router_fleet["verdict"],
            "red": router_fleet["red"],
            "stale_cards": router_fleet["stale_cards"],
            "rollup": {
                key: value
                for key, value in router_fleet["rollup"].items()
                if key.startswith(("llmt_fleet_router_", "llmt_fleet_serve_",
                                   "llmt_fleet_replicas"))
            },
        }
    if ttft:
        summary["client_ttft_p50_ms"] = round(percentile(ttft, 50), 3)
        summary["client_ttft_p99_ms"] = round(percentile(ttft, 99), 3)
    if tpot:
        summary["client_tpot_p50_ms"] = round(percentile(tpot, 50), 3)
        summary["client_tpot_p99_ms"] = round(percentile(tpot, 99), 3)
    if "serve/tokens_per_sec_per_chip" in stats:
        summary["tokens_per_sec_per_chip"] = stats["serve/tokens_per_sec_per_chip"]
    print(json.dumps(summary))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
