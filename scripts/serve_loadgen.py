#!/usr/bin/env python
"""Synthetic-traffic load driver for the `serve` CLI (docs/serving.md).

Spawns `llm-training-tpu serve` as a child process and drives the real
JSONL stdin/stdout protocol with OVERLAPPING arrivals: the first request
goes in immediately; every later request is held until the first streamed
token chunk proves decode is in flight, then submitted with a small gap —
so continuous batching (admission mid-decode) is what the run exercises,
not a closed batch.

Client-side latency is measured per request from its submit time: TTFT to
the first token chunk, TPOT across subsequent chunks. The summary merges
the engine's own `serve/*` stats record (throughput, pool pressure) with
the client percentiles, prints one JSON object, and exits nonzero when

- any request fails to terminate (no `done` chunk),
- a `done` arrives with no preceding token chunks for that id,
- the engine leaks pool blocks (`decode/cache_blocks_in_use` != 0), or
- arrivals never overlapped (`serve/peak_running` < 2).

The child merges its gauges into the run dir's telemetry.jsonl as usual,
so a following `report` renders `== Serving ==` — the precommit
serve-smoke gate asserts exactly that chain.

Usage:
    python scripts/serve_loadgen.py --config <yaml> [overrides...] \
        [--requests 4] [--max-new-tokens 8] [--arrival-gap-s 0.05] \
        [--out summary.json] [-- <extra serve args>]
"""

from __future__ import annotations

import argparse
import json
import random
import subprocess
import sys
import threading
import time


def percentile(values: list[float], q: float) -> float:
    """Nearest-rank percentile; avoids a numpy import in this jax-free
    parent (the child owns the devices)."""
    ordered = sorted(values)
    rank = max(0, min(len(ordered) - 1, round(q / 100.0 * (len(ordered) - 1))))
    return ordered[rank]


def build_requests(args) -> list[dict]:
    rng = random.Random(args.seed)
    requests = []
    for n in range(args.requests):
        length = rng.randint(args.min_prompt, args.max_prompt)
        requests.append({
            "id": f"req-{n}",
            "prompt": [rng.randint(3, args.vocab - 1) for _ in range(length)],
            "max_new_tokens": args.max_new_tokens,
        })
    return requests


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--config", required=True)
    parser.add_argument("--requests", type=int, default=4)
    parser.add_argument("--max-new-tokens", type=int, default=8)
    parser.add_argument("--min-prompt", type=int, default=2)
    parser.add_argument("--max-prompt", type=int, default=6)
    parser.add_argument("--vocab", type=int, default=64, help="synthetic token id bound")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--arrival-gap-s", type=float, default=0.05,
        help="gap between follow-up arrivals (all after the first token)",
    )
    parser.add_argument(
        "--idle-timeout-s", type=float, default=600.0,
        help="kill the child when no stdout line lands for this long",
    )
    parser.add_argument("--out", default=None, help="also write the summary JSON here")
    parser.add_argument(
        "serve_args", nargs="*",
        help="config overrides and extra `serve` flags (e.g. run_root=... "
        "--max-batch 2)",
    )
    # unknown flags (e.g. --max-batch) pass through to the serve child
    args, passthrough = parser.parse_known_args()
    args.serve_args += passthrough

    requests = build_requests(args)
    argv = [
        sys.executable, "-m", "llm_training_tpu", "serve",
        "--config", args.config, *args.serve_args,
    ]
    child = subprocess.Popen(
        argv, stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True, bufsize=1
    )

    submit_s: dict[str, float] = {}
    first_token_s: dict[str, float] = {}
    last_token_s: dict[str, float] = {}
    chunks: dict[str, int] = {}
    done: dict[str, dict] = {}
    stats: dict[str, float] = {}
    errors: list[str] = []
    first_token_seen = threading.Event()

    def send(request: dict) -> None:
        submit_s[request["id"]] = time.perf_counter()
        child.stdin.write(json.dumps(request) + "\n")
        child.stdin.flush()

    def feed() -> None:
        try:
            send(requests[0])
            # hold the rest until decode is demonstrably in flight, so
            # every later arrival exercises mid-stream admission; the first
            # follow-up goes immediately (a warm decode step is ~ms — any
            # fixed gap risks outliving the whole first generation)
            first_token_seen.wait()
            for n, request in enumerate(requests[1:]):
                if n:
                    time.sleep(args.arrival_gap_s)
                send(request)
        except BrokenPipeError:
            pass  # child died; the reader loop reports it
        finally:
            try:
                child.stdin.close()
            except OSError:
                pass

    feeder = threading.Thread(target=feed, daemon=True)
    feeder.start()

    timer = threading.Timer(args.idle_timeout_s, child.kill)
    timer.start()
    try:
        for line in child.stdout:
            timer.cancel()
            timer = threading.Timer(args.idle_timeout_s, child.kill)
            timer.start()
            line = line.strip()
            if not line:
                continue
            try:
                event = json.loads(line)
            except json.JSONDecodeError:
                continue  # interleaved logging, not a protocol chunk
            now = time.perf_counter()
            kind = event.get("type")
            if kind == "token":
                rid = event["id"]
                chunks[rid] = chunks.get(rid, 0) + 1
                first_token_s.setdefault(rid, now)
                last_token_s[rid] = now
                first_token_seen.set()
            elif kind == "done":
                done[event["id"]] = event
                # a token-less termination (rejected / capacity) must also
                # unblock the feeder, or a first request that never streams
                # wedges the whole run until the idle timeout
                first_token_seen.set()
            elif kind == "stats":
                stats = event["stats"]
            elif kind == "error":
                errors.append(event.get("error", "unknown"))
                first_token_seen.set()
    finally:
        timer.cancel()
        first_token_seen.set()  # unblock the feeder if the child died early
    rc = child.wait()

    for request in requests:
        rid = request["id"]
        if rid not in done:
            errors.append(f"{rid}: no done chunk (rc {rc})")
        elif done[rid].get("stop_reason") in ("eos", "max_tokens") and not chunks.get(rid):
            errors.append(f"{rid}: done without any streamed token chunks")
    leaked = stats.get("decode/cache_blocks_in_use")
    if leaked is None:
        errors.append("no stats record from the child")
    elif leaked:
        errors.append(f"pool leak: {int(leaked)} blocks still in use at exit")
    peak = stats.get("serve/peak_running", 0)
    if len(requests) > 1 and peak < 2:
        errors.append(
            f"arrivals never overlapped (peak_running {peak}) — raise "
            "--max-new-tokens or check --max-batch > 1"
        )

    ttft = [
        1000.0 * (first_token_s[r] - submit_s[r]) for r in first_token_s
    ]
    tpot = [
        1000.0 * (last_token_s[r] - first_token_s[r]) / (chunks[r] - 1)
        for r in first_token_s if chunks.get(r, 0) > 1
    ]
    summary = {
        "requests": len(requests),
        "completed": sum(
            1 for d in done.values() if d.get("stop_reason") in ("eos", "max_tokens")
        ),
        "streamed_chunks": sum(chunks.values()),
        "errors": errors,
        "engine": stats,
    }
    if ttft:
        summary["client_ttft_p50_ms"] = round(percentile(ttft, 50), 3)
        summary["client_ttft_p99_ms"] = round(percentile(ttft, 99), 3)
    if tpot:
        summary["client_tpot_p50_ms"] = round(percentile(tpot, 50), 3)
        summary["client_tpot_p99_ms"] = round(percentile(tpot, 99), 3)
    if "serve/tokens_per_sec_per_chip" in stats:
        summary["tokens_per_sec_per_chip"] = stats["serve/tokens_per_sec_per_chip"]
    print(json.dumps(summary))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f)
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
