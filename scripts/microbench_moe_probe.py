"""Follow-up probes for the MoE grouped-matmul gap (r5).

Measures, at the bench proxy shape (rows 65536, h 2048):
  1. gmm tiling sweep — is megablox's default (128,128,128) the problem?
  2. ragged_dot at MXU-aligned width 768 vs the proxy's 704 — how much of
     the gap is lane misalignment?
  3. a BUCKETED formulation: balanced groups -> fixed per-expert capacity
     buckets -> ONE dense batched matmul [E, C, h] @ [E, h, w] with
     gather/scatter at the edges. Semantics = capacity-factor MoE (drops on
     overflow — surfaced by the ep_dropped_rows metric), FLOPs = C/avg
     padding overhead, but the matmul is fully dense on the MXU.

Same timing discipline as microbench_moe.py.
"""

from __future__ import annotations

import functools
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

ITERS = 8
_PEAK = 197e12
_RNG = np.random.default_rng(0)
ROWS, HIDDEN = 65536, 2048


def _fetch(out) -> None:
    jax.device_get(jax.tree.leaves(out)[0].ravel()[:8])


def _timed(fn, *args) -> float:
    _fetch(fn(jnp.bfloat16(0.0), *args))
    times = []
    for rep in range(1, 4):
        t0 = time.perf_counter()
        _fetch(fn(jnp.bfloat16(rep * 1e-3), *args))
        times.append((time.perf_counter() - t0) / ITERS)
    return float(np.median(times))


def _inputs(n_experts, width):
    x = jnp.asarray(_RNG.standard_normal((ROWS, HIDDEN)) * 0.1, jnp.bfloat16)
    wg = jnp.asarray(_RNG.standard_normal((n_experts, HIDDEN, width)) * 0.02, jnp.bfloat16)
    wu = jnp.asarray(_RNG.standard_normal((n_experts, HIDDEN, width)) * 0.02, jnp.bfloat16)
    wd = jnp.asarray(_RNG.standard_normal((n_experts, width, HIDDEN)) * 0.02, jnp.bfloat16)
    gs = jnp.full((n_experts,), ROWS // n_experts, jnp.int32)
    return x, wg, wu, wd, gs


def bench_mlp(mlp, n_experts, width, bwd):
    x, wg, wu, wd, gs = _inputs(n_experts, width)
    if not bwd:
        @jax.jit
        def run(salt, x, wg, wu, wd, gs):
            def body(carry, _):
                y = mlp(x + carry, wg, wu, wd, gs)
                return y.ravel()[0].astype(jnp.bfloat16), None
            y, _ = jax.lax.scan(body, salt, None, length=ITERS)
            return y
    else:
        grad = jax.grad(
            lambda *a: jnp.sum(mlp(*a).astype(jnp.float32) ** 2), argnums=(0, 1, 2, 3)
        )

        @jax.jit
        def run(salt, x, wg, wu, wd, gs):
            def body(carry, _):
                gx, *_ = grad(x + carry, wg, wu, wd, gs)
                return gx.ravel()[0].astype(jnp.bfloat16), None
            y, _ = jax.lax.scan(body, salt, None, length=ITERS)
            return y

    t = _timed(run, x, wg, wu, wd, gs)
    n_mm = 3 if not bwd else 9
    flops = n_mm * 2 * ROWS * HIDDEN * width
    return t, flops / t / _PEAK


def ragged_mlp(x, wg, wu, wd, gs):
    dot = jax.lax.ragged_dot
    gate = dot(x, wg, gs)
    up = dot(x, wu, gs)
    return dot(jax.nn.silu(gate) * up, wd, gs)


def gmm_mlp_tiled(tiling):
    from jax.experimental.pallas.ops.tpu.megablox.ops import gmm

    dot = functools.partial(gmm, preferred_element_type=jnp.bfloat16, tiling=tiling)

    def mlp(x, wg, wu, wd, gs):
        gate = dot(x, wg, gs)
        up = dot(x, wu, gs)
        return dot(jax.nn.silu(gate) * up, wd, gs)

    return mlp


def bucketed_mlp(x, wg, wu, wd, gs):
    """Fixed-capacity buckets + dense bmm. Rows are already expert-sorted
    (as in dropless_moe_apply); bucket e takes rows [e*C, (e+1)*C) of a
    capacity-padded layout built by one gather."""
    E = wg.shape[0]
    cap = ROWS // E  # balanced probe: capacity factor 1.0, no padding waste
    start = jnp.cumsum(gs) - gs
    # index of row r within bucket e -> source row start[e] + offset
    offs = jnp.arange(cap)
    src = (start[:, None] + offs[None, :]).reshape(-1)  # [E*cap]
    valid = (offs[None, :] < gs[:, None]).reshape(-1)
    xb = jnp.where(valid[:, None], x[jnp.clip(src, 0, ROWS - 1)], 0)
    xb = xb.reshape(E, cap, HIDDEN)
    gate = jnp.einsum("ech,ehw->ecw", xb, wg, preferred_element_type=jnp.bfloat16)
    up = jnp.einsum("ech,ehw->ecw", xb, wu, preferred_element_type=jnp.bfloat16)
    yb = jnp.einsum("ecw,ewh->ech", jax.nn.silu(gate) * up, wd,
                    preferred_element_type=jnp.bfloat16)
    # scatter back to sorted-row order
    y = jnp.zeros((ROWS, HIDDEN), yb.dtype)
    return y.at[jnp.clip(src, 0, ROWS - 1)].add(
        yb.reshape(-1, HIDDEN) * valid[:, None]
    )


def main():
    print("| case | impl | pass | ms/iter | MXU eff |")
    print("|---|---|---|---|---|")
    cases = [(8, 704), (8, 768), (64, 256)]
    for E, W in cases:
        for p in ("fwd", "bwd"):
            t, eff = bench_mlp(ragged_mlp, E, W, p == "bwd")
            print(f"| {E}x{W} | ragged | {p} | {t*1e3:.2f} | {eff:.3f} |", flush=True)
    for tiling in ((512, 512, 704), (1024, 2048, 704), (2048, 512, 352)):
        try:
            t, eff = bench_mlp(gmm_mlp_tiled(tiling), 8, 704, False)
            print(f"| 8x704 tiling={tiling} | gmm | fwd | {t*1e3:.2f} | {eff:.3f} |", flush=True)
        except Exception as e:
            print(f"| 8x704 tiling={tiling} | gmm | fwd | FAIL | {str(e)[:60]} |", flush=True)
    for E, W in cases:
        for p in ("fwd", "bwd"):
            try:
                t, eff = bench_mlp(bucketed_mlp, E, W, p == "bwd")
                print(f"| {E}x{W} | bucketed | {p} | {t*1e3:.2f} | {eff:.3f} |", flush=True)
            except Exception as e:
                print(f"| {E}x{W} | bucketed | {p} | FAIL | {str(e)[:60]} |", flush=True)


if __name__ == "__main__":
    main()
