"""Follow-up probes for the MoE grouped-matmul gap (r5).

Reuses the harness from `scripts/microbench_moe.py` (timing discipline,
input builder, ragged/gmm MLPs). Adds, at the bench proxy shape
(rows 65536, h 2048):
  1. ragged_dot at MXU-aligned width 768 vs the proxy's 704 — how much of
     the gap is lane misalignment? (measured r5: 0.19 -> 0.21 fwd, minor)
  2. gmm tiling sweep — rejected: non-128-multiple expert widths violate
     the megablox kernel's lowering constraints.
  3. a BUCKETED formulation: balanced groups -> fixed per-expert capacity
     buckets -> ONE dense batched matmul [E, C, h] @ [E, h, w] with
     gather/scatter at the edges. Semantics = capacity-factor MoE (drops
     on overflow — surfaced by the ep_dropped_rows metric); the matmul is
     fully dense on the MXU.
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import jax
import jax.numpy as jnp

from scripts.microbench_moe import HIDDEN, ROWS, bench_one


def bucketed_mlp(x, wg, wu, wd, gs):
    """Fixed-capacity buckets + dense bmm. Rows are already expert-sorted
    (as in dropless_moe_apply); bucket e takes rows [start_e, start_e + C)
    of the sorted layout via one gather."""
    E = wg.shape[0]
    cap = ROWS // E  # balanced probe: capacity factor 1.0
    start = jnp.cumsum(gs) - gs
    offs = jnp.arange(cap)
    src = (start[:, None] + offs[None, :]).reshape(-1)  # [E*cap]
    valid = (offs[None, :] < gs[:, None]).reshape(-1)
    xb = (x[jnp.clip(src, 0, ROWS - 1)] * valid[:, None].astype(x.dtype))
    xb = xb.reshape(E, cap, HIDDEN)
    gate = jnp.einsum("ech,ehw->ecw", xb, wg, preferred_element_type=jnp.bfloat16)
    up = jnp.einsum("ech,ehw->ecw", xb, wu, preferred_element_type=jnp.bfloat16)
    yb = jnp.einsum("ecw,ewh->ech", jax.nn.silu(gate) * up, wd,
                    preferred_element_type=jnp.bfloat16)
    y = jnp.zeros((ROWS, HIDDEN), yb.dtype)
    return y.at[jnp.clip(src, 0, ROWS - 1)].add(
        yb.reshape(-1, HIDDEN) * valid[:, None].astype(yb.dtype)
    )


def main():
    print("| case | impl | pass | ms/iter | MXU eff |")
    print("|---|---|---|---|---|")
    for E, W in ((8, 704), (8, 768), (64, 256)):
        for p in ("fwd", "bwd"):
            t, eff = bench_one(E, W, "ragged", p == "bwd")
            print(f"| {E}x{W} | ragged | {p} | {t*1e3:.2f} | {eff:.3f} |", flush=True)
    for E, W in ((8, 704), (64, 256)):
        for p in ("fwd", "bwd"):
            try:
                t, eff = bench_one(E, W, "bucketed", p == "bwd", mlp=bucketed_mlp)
                print(f"| {E}x{W} | bucketed | {p} | {t*1e3:.2f} | {eff:.3f} |", flush=True)
            except Exception as e:
                print(f"| {E}x{W} | bucketed | {p} | FAIL | {str(e)[:60]} |", flush=True)


if __name__ == "__main__":
    main()
