"""Microbenchmark the MoE grouped-matmul primitive on the chip.

VERDICT r4 #4: the MoE bench proxy reaches 0.330 activated-MFU vs 0.567
dense, with ~2x of the gap attributed to the `jax.lax.ragged_dot` lowering
at E=8/width-704. This measures the three-projection expert MLP
(gate/up -> silu*mul -> down) as a unit — fwd and fwd+bwd — for:

- `ragged`: jax.lax.ragged_dot (the XLA lowering the r4 bench used)
- `gmm`: the Pallas megablox grouped-matmul kernel bundled with jax
  (jax.experimental.pallas.ops.tpu.megablox.ops.gmm, custom VJP included)

across expert counts E=8 (bench proxy) and E=64/E=256-class widths
(DeepSeek-style fine-grained experts), with balanced groups (the bench's
routing is near-balanced). MXU eff credits 3 * 2*rows*h*w FLOPs (fwd;
x3 for fwd+bwd) against the nominal v5e peak.

Timing per the tunnel rules: chained iterations in one jit, per-rep salt,
completion proven by fetching bytes (block_until_ready lies on this chip).

Usage:
  python scripts/microbench_moe.py
  CASES=8x704,64x176 IMPLS=ragged,gmm PASSES=fwd,bwd python scripts/microbench_moe.py
"""

from __future__ import annotations

import functools
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

ITERS = 8
_PEAK = 197e12  # v5e nominal bf16
_RNG = np.random.default_rng(0)

HIDDEN = int(os.environ.get("MOE_HIDDEN", 2048))
# bench proxy: 2048 seq * 16 batch * top-2. ROWS is overridable so new
# graph shapes (e.g. the bucketed gather/scatter probe) can be validated
# small first — a 65k-row first-contact graph once wedged the tunnel
# permanently (see .claude/skills/verify/SKILL.md).
ROWS = int(os.environ.get("MOE_ROWS", 65536))


def _fetch(out) -> None:
    jax.device_get(jax.tree.leaves(out)[0].ravel()[:8])


def _timed(fn, *args) -> float:
    _fetch(fn(jnp.bfloat16(0.0), *args))  # compile
    times = []
    for rep in range(1, 4):
        t0 = time.perf_counter()
        _fetch(fn(jnp.bfloat16(rep * 1e-3), *args))
        times.append((time.perf_counter() - t0) / ITERS)
    return float(np.median(times))


def _expert_mlp(impl: str):
    if impl == "gmm":
        from jax.experimental.pallas.ops.tpu.megablox.ops import gmm

        dot = functools.partial(gmm, preferred_element_type=jnp.bfloat16)
    else:
        dot = jax.lax.ragged_dot

    def mlp(x, wg, wu, wd, gs):
        gate = dot(x, wg, gs)
        up = dot(x, wu, gs)
        return dot(jax.nn.silu(gate) * up, wd, gs)

    return mlp


def bench_one(n_experts: int, width: int, impl: str, bwd: bool, mlp=None):
    x = jnp.asarray(_RNG.standard_normal((ROWS, HIDDEN)) * 0.1, jnp.bfloat16)
    wg = jnp.asarray(_RNG.standard_normal((n_experts, HIDDEN, width)) * 0.02, jnp.bfloat16)
    wu = jnp.asarray(_RNG.standard_normal((n_experts, HIDDEN, width)) * 0.02, jnp.bfloat16)
    wd = jnp.asarray(_RNG.standard_normal((n_experts, width, HIDDEN)) * 0.02, jnp.bfloat16)
    gs = jnp.full((n_experts,), ROWS // n_experts, jnp.int32)  # balanced
    if mlp is None:
        mlp = _expert_mlp(impl)

    if not bwd:
        @jax.jit
        def run(salt, x, wg, wu, wd, gs):
            def body(carry, _):
                y = mlp(x + carry, wg, wu, wd, gs)
                return y[0, 0].astype(jnp.bfloat16), None

            y, _ = jax.lax.scan(body, salt, None, length=ITERS)
            return y
    else:
        def loss(x, wg, wu, wd, gs):
            return jnp.sum(mlp(x, wg, wu, wd, gs).astype(jnp.float32) ** 2)

        grad = jax.grad(loss, argnums=(0, 1, 2, 3))

        @jax.jit
        def run(salt, x, wg, wu, wd, gs):
            def body(carry, _):
                # every gradient output must feed the carry, or jax's DCE
                # removes the dw matmuls from the timed graph entirely
                gx, gg, gu, gd = grad(x + carry, wg, wu, wd, gs)
                live = gx[0, 0] + gg[0, 0, 0] + gu[0, 0, 0] + gd[0, 0, 0]
                return live.astype(jnp.bfloat16), None

            y, _ = jax.lax.scan(body, salt, None, length=ITERS)
            return y

    t = _timed(run, x, wg, wu, wd, gs)
    n_mm = 3 if not bwd else 9  # bwd: dx + dw per projection (2x) + fwd recompute
    flops = n_mm * 2 * ROWS * HIDDEN * width
    return t, flops / t / _PEAK


def main():
    # (E, width): 8x704 = bench proxy (total expert params == 697M dense
    # MLP); E-sweeps hold TOTAL params constant so MFU is comparable;
    # 64x2048-class = DeepSeek-V3-like wide-E fine-grained shape at h2048
    cases = [
        tuple(int(v) for v in c.split("x"))
        for c in os.environ.get(
            "CASES", "8x704,16x352,64x88,8x2048,64x256,256x64"
        ).split(",")
    ]
    impls = os.environ.get("IMPLS", "ragged,gmm").split(",")
    passes = os.environ.get("PASSES", "fwd,bwd").split(",")
    print(f"| E | width | impl | pass | ms/iter | MXU eff | rows {ROWS} h {HIDDEN} |")
    print("|---|---|---|---|---|---|---|")
    for n_experts, width in cases:
        for impl in impls:
            for p in passes:
                try:
                    t, eff = bench_one(n_experts, width, impl, p == "bwd")
                    print(
                        f"| {n_experts} | {width} | {impl} | {p} "
                        f"| {t*1e3:.2f} | {eff:.3f} |",
                        flush=True,
                    )
                except Exception as e:  # shape/lowering limits: record, move on
                    print(
                        f"| {n_experts} | {width} | {impl} | {p} | FAIL "
                        f"| {type(e).__name__}: {str(e)[:60]} |",
                        flush=True,
                    )


if __name__ == "__main__":
    main()
